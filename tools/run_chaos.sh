#!/usr/bin/env bash
# Chaos lanes: fault-injection / kill-and-recover / elastic-membership
# tests (default lane, pytest -m chaos) and the data-integrity lane
# (pytest -m integrity: bitflip detection + retransmit, drop-with-retry
# dedup, non-finite quarantine — tests/test_integrity.py), both with TWO
# layers of wedge protection:
#
#   1. a hard per-test timeout (tools/chaos_timeout_plugin.py, SIGALRM):
#      a wedged rendezvous or hung worker process fails ITS test fast
#      with a traceback instead of parking pytest forever;
#   2. an outer `timeout -k` on the whole lane as the backstop for
#      anything the in-process alarm cannot interrupt.
#
# Usage:  tools/run_chaos.sh [lane] [extra pytest args...]
#         lane: chaos (default) | integrity | obs | coordinator | serve
#               | serve_dist | straggler | compressed | trace
#               | transport | doctor | gossip | fleet | durability
#               | sharded | lint | all
#         sharded: the sharded-weight-update elastic slice (ISSUE 20,
#              core/sharded_update.py, docs/performance.md "Sharded
#              weight update") — kill one rank mid-step while every
#              worker trains through declare_update/push_pull_update:
#              the survivors' shrink tears each engine down
#              (possibly mid-dispatch), the suspend stash re-pads
#              master + momentum onto the rebuilt mesh (RESHARDED
#              evidence, owner reassignment), the slot's `applied`
#              counter arbitrates the torn step (committed → skip,
#              dropped-as-stale → redispatch; never lost, never
#              double-applied), and the final master is bit-for-bit
#              the eager-optax replay of the mean-gradient sequence
#              (tests/test_elastic.py
#              test_shrink_resharding_sharded_update)
#         durability: the durable-state-plane slice (ISSUE 19,
#              server/wal.py, docs/fault_tolerance.md "Durable state &
#              cold start") — the full-world kill acceptance (SIGKILL
#              the ENTIRE world mid-step, cold-restart from the local
#              WAL + snapshot cuts, finals bit-exact vs a fault-free
#              run), the torn-tail / bitflipped-segment / fsync-dropped
#              variants (each truncates to the last durable point,
#              detected and counted, zero silent corruption), the
#              disk_full journal-before-merge pin (failed append leaves
#              memory untouched and the dedup floor unburned), and the
#              serve-host restart-in-place arc-restore pins
#              (tests/test_durability.py)
#         fleet: the fleet-reconciler slice (ISSUE 18,
#              launcher/reconciler.py, docs/serving.md "The
#              self-operating fleet") — the 8-host storm acceptance
#              (pull storm scales the tier up with REAL spawned
#              serve_host processes, kill-storm healed back to target,
#              a deliberately crash-looping host
#              (kill:site=serve_host_start) banned without
#              destabilizing the ring, scale-down drains with zero
#              failed reads), the graceful-drain protocol pins
#              (DRAINING mark → gen bump → final unregister handshake
#              → HOST-DRAINED), crash-loop backoff/ban unit pins, and
#              the drain-deadline escalation (tests/test_fleet.py)
#         gossip: the partition-tolerance slice (ISSUE 17,
#              fault/gossip.py, docs/fault_tolerance.md) — the
#              multi-process split-brain proof (partition:ranks=A|B
#              cuts the world, the majority side shrinks and keeps
#              training, the minority parks with
#              membership.partition_minority, NO second epoch is ever
#              agreed, heal → rejoin → bit-identical finals), gray
#              suspect/refutation (a slow-but-live rank un-suspects
#              itself via incarnation bump), the 64-rank in-process
#              convergence pins, and the bps_doctor partition
#              postmortem (tests/test_partition.py,
#              tests/test_gossip.py)
#         serve_dist: the distributed-serving-tier chaos slice
#              (server/serving_tier.py, docs/serving.md) — ≥3 real
#              serving-host processes behind the TCP transport serve a
#              concurrent pull storm while one host is chaos-killed
#              (kill:site=serve_host) and another is partitioned
#              mid-storm (serve_ctl chaos_arm): zero failed reads,
#              ring heals through the bus directory, staleness stays
#              bounded; plus slow_socket on a host, admission-control
#              shed pins, and the reshard-while-pulls-in-flight tests
#              (tests/test_serving_tier.py)
#         transport: socket-fault chaos on the TCP data plane
#              (comm/transport.py, docs/transport.md) — 4-process
#              bitflip-over-real-sockets convergence, conn_reset
#              absorbed by reconnect + seq-token dedup (zero double
#              sums), a partitioned rank escalating to
#              shrink-and-continue, the 32-endpoint supervisor soak,
#              and the in-process socket-fault pins
#              (tests/test_transport.py, tests/test_transport_chaos.py)
#         lint: the project-invariant analyzer (tools/bpslint,
#              docs/dev_invariants.md) over the tree — env-knob /
#              metric-name / chaos-site / lock-discipline drift, exit
#              nonzero on any finding; plus its fixture tests and the
#              lock-order witness unit tests (tests/test_bpslint.py,
#              tests/test_lock_witness.py)
#         trace: the causal-tracing slice (ISSUE 12) — a real 3-process
#              run with BYTEPS_TRACE_SAMPLE armed writes per-rank trace
#              files that tools/bps_trace.py merges into ONE aligned
#              timeline with --validate clean (every flow `s` paired
#              with its `f`, clock-aligned timestamps, cross-process
#              barrier arcs), plus the step-attribution pins
#              (tests/test_trace_merge.py, tests/test_observability.py
#              attribution tests)
#         compressed: chaos on the QUANTIZED wire path — a 3-process
#              compressed run under bitflip:site=server_push converges
#              bit-identical (every corrupt quantized frame NACKed and
#              retransmitted before the decode runs), a compressed push
#              crossing an elastic world change drops-not-sums, and the
#              declare-time validation/zero-compile pins
#              (tests/test_compressed_aot.py, tests/test_integrity.py
#              compressed tests)
#         serve: the serving-plane chaos slice — replica kill under
#              concurrent training pushes (zero failed reads, primary
#              degradation) and serve_pull reply corruption
#              (NACK/retransmit to exact values)
#              (tests/test_serving.py)
#         straggler: the gray-failure slice — one rank under a
#              sustained `slow` fault is demoted to probation
#              (throughput recovers to the checked bound), readmitted
#              once the fault window clears, and hedged pulls bound the
#              serving tail under one slow endpoint
#              (tests/test_straggler.py, tests/test_serving.py hedge
#              tests, tests/test_sync_deadline.py stall guards)
#         doctor: the history/health slice (ISSUE 16) — a 3-process run
#              on a fast sampling cadence under a sustained straggler
#              fault (slow:rank=1:site=sync) with a slow_socket rule
#              armed: the matching health rules fire on the victim
#              within a few sampling windows, its /healthz flips to 503
#              (and back to 200 after the fault budget exhausts and K
#              clean windows pass), cluster_metrics() grows the history
#              view, and bps_doctor --postmortem over the run's flight
#              dumps + saved /timeseries windows names the culprit rank
#              and injection site (tests/test_doctor_chaos.py), plus
#              the in-process ring/health unit pins
#              (tests/test_timeseries_health.py)
#         obs: the observability-under-chaos slice — every rank of a
#              3-process chaos run serves /metrics//healthz, the
#              membership bus answers cluster_metrics, and a
#              chaos-killed worker leaves a flight-recorder dump whose
#              tail holds the events leading into the kill
#              (tests/test_observability.py)
#         coordinator: kill-the-coordinator lanes — bus failover with
#              replicated state (mid-step kill + rejoin through the
#              successor bus), double failure during the failover
#              (standby dies mid-rendezvous), heartbeat re-hosting, and
#              the BYTEPS_SYNC_DEADLINE_S wedge→reconcile path
#              (tests/test_coordinator_failover.py,
#              tests/test_sync_deadline.py); all chaos-marked, so the
#              `all` lane includes them too
# Env:    CHAOS_TEST_TIMEOUT  per-test seconds   (default 120)
#         CHAOS_LANE_TIMEOUT  whole-lane seconds (default 600)
set -o pipefail

cd "$(dirname "$0")/.."

PER_TEST="${CHAOS_TEST_TIMEOUT:-120}"
LANE="${CHAOS_LANE_TIMEOUT:-600}"

MARK="chaos"
KEXPR=""
case "${1:-}" in
    chaos)     MARK="chaos"; shift ;;
    integrity) MARK="integrity"; shift ;;
    obs)       MARK="chaos"; KEXPR="flight_recorder or obs_cluster"; shift ;;
    coordinator) MARK="chaos"
                 KEXPR="coordinator or sync_deadline or reconcile"
                 shift ;;
    serve)     MARK="chaos or integrity"
               KEXPR="serve and not serve_dist and not serving_tier"
               shift ;;
    serve_dist) MARK="chaos or integrity"
                KEXPR="serve_dist or serving_tier"
                shift ;;
    straggler) MARK="chaos"
               KEXPR="straggler or demote or hedge or stall"
               shift ;;
    compressed) MARK="chaos or integrity"; KEXPR="compress"; shift ;;
    transport) MARK="chaos or integrity"; KEXPR="transport"; shift ;;
    trace)     MARK="chaos"; KEXPR="trace or attrib"; shift ;;
    doctor)    MARK="chaos"; KEXPR="doctor or timeseries or health"; shift ;;
    gossip)    MARK="chaos"
               KEXPR="gossip or partition or quorum"
               shift ;;
    fleet)     MARK="chaos or integrity"
               KEXPR="fleet"
               shift ;;
    durability) MARK="chaos or integrity"
                KEXPR="durability or wal"
                shift ;;
    sharded)   MARK="chaos"
               KEXPR="sharded"
               shift ;;
    all)       MARK="chaos or integrity"; shift ;;
    lint)
        shift
        # static half: the analyzer itself (no JAX, fails on findings),
        # then the rule-fixture and witness unit tests
        python -m tools.bpslint || exit $?
        exec timeout -k 15 "$LANE" \
            env JAX_PLATFORMS=cpu python -m pytest \
            tests/test_bpslint.py tests/test_lock_witness.py -q \
            -p tools.chaos_timeout_plugin --chaos-timeout "$PER_TEST" \
            -p no:cacheprovider -p no:xdist -p no:randomly \
            "$@"
        ;;
esac

# Fail fast on an invalid ambient BYTEPS_FAULT_SPEC: the workers that
# honor it would raise at init, but many lane tests *clear* the env var
# before spawning — an operator's typo'd spec would then inject nothing
# anywhere and the lane would count as passed while the intended chaos
# never ran.  Validate up front and refuse loudly instead.
if [ -n "${BYTEPS_FAULT_SPEC:-}" ]; then
    if ! err=$(env JAX_PLATFORMS=cpu python -c \
        "import os; from byteps_tpu.fault.injector import parse_spec; \
parse_spec(os.environ['BYTEPS_FAULT_SPEC'])" 2>&1); then
        echo "run_chaos.sh: refusing to run — the BYTEPS_FAULT_SPEC" \
             "exported in this environment failed validation, so the" \
             "lane would pass vacuously without the intended chaos:" >&2
        echo "$err" | tail -3 >&2
        exit 2
    fi
fi

# Every chaos lane runs with the lock-order witness armed
# (byteps_tpu/common/lock_witness.py): the high-traffic locks record
# their acquisition order and RAISE on a cycle, so each fault-injection
# run doubles as a deadlock hunt across every thread the lane spawns
# (worker subprocesses inherit the env and are witnessed too).
exec timeout -k 15 "$LANE" \
    env JAX_PLATFORMS=cpu BYTEPS_LOCK_WITNESS=1 \
    python -m pytest tests/ -q -m "$MARK" \
    ${KEXPR:+-k "$KEXPR"} \
    -p tools.chaos_timeout_plugin --chaos-timeout "$PER_TEST" \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    "$@"
