#!/usr/bin/env bash
# Chaos lanes: fault-injection / kill-and-recover / elastic-membership
# tests (default lane, pytest -m chaos) and the data-integrity lane
# (pytest -m integrity: bitflip detection + retransmit, drop-with-retry
# dedup, non-finite quarantine — tests/test_integrity.py), both with TWO
# layers of wedge protection:
#
#   1. a hard per-test timeout (tools/chaos_timeout_plugin.py, SIGALRM):
#      a wedged rendezvous or hung worker process fails ITS test fast
#      with a traceback instead of parking pytest forever;
#   2. an outer `timeout -k` on the whole lane as the backstop for
#      anything the in-process alarm cannot interrupt.
#
# Usage:  tools/run_chaos.sh [lane] [extra pytest args...]
#         lane: chaos (default) | integrity | all
# Env:    CHAOS_TEST_TIMEOUT  per-test seconds   (default 120)
#         CHAOS_LANE_TIMEOUT  whole-lane seconds (default 600)
set -o pipefail

cd "$(dirname "$0")/.."

PER_TEST="${CHAOS_TEST_TIMEOUT:-120}"
LANE="${CHAOS_LANE_TIMEOUT:-600}"

MARK="chaos"
case "${1:-}" in
    chaos)     MARK="chaos"; shift ;;
    integrity) MARK="integrity"; shift ;;
    all)       MARK="chaos or integrity"; shift ;;
esac

exec timeout -k 15 "$LANE" \
    env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "$MARK" \
    -p tools.chaos_timeout_plugin --chaos-timeout "$PER_TEST" \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    "$@"
