"""Peak-memory evidence for the two FSDP designs (round-2 VERDICT item 6).

`parallel/zero.py`'s flat-vector FSDP all-gathers the ENTIRE parameter
vector per step — full-bandwidth collectives, but the transient
full-params peak forfeits FSDP's memory property for large models.  The
streamed fix is per-block gather, and in this framework that path is
`parallel/fsdp_tp.py`: GSPMD sharding annotations make XLA gather each
layer's weights where they are used (and, under remat, re-gather in the
backward instead of keeping them live).

This tool compiles both train steps for the same multi-layer model on
the 8-device CPU mesh and reads the compiled programs' XLA memory
analysis — the per-device transient footprint is the datum the designs
differ on.  Printed as JSON; cited in docs/performance.md.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    from tools._bench_util import setup_cpu8_mesh
    setup_cpu8_mesh()
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from byteps_tpu.models.llama import Llama, LlamaConfig
    from byteps_tpu.parallel.long_context import synthetic_lm_batch

    # Enough layers that per-layer streaming has something to stream;
    # f32 + remat (remat is what lets gathered weights die after use).
    cfg = LlamaConfig(vocab_size=256, hidden_size=512, num_layers=8,
                      num_heads=4, num_kv_heads=4, intermediate_size=2048,
                      max_position=128, dtype=jnp.float32, remat=True)
    model = Llama(cfg)
    rng = jax.random.PRNGKey(0)
    batch = synthetic_lm_batch(rng, cfg, batch=8, seq_len=64)
    params = model.init(rng, batch["input_ids"][:1])
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    tx = optax.sgd(0.1)

    def loss_fn(p, b):
        from byteps_tpu.models.llama import lm_loss
        return lm_loss(model.apply(p, b["input_ids"]), b["labels"])

    out = {"n_params": n_params, "param_bytes_f32": n_params * 4}

    # ---- flat-vector FSDP (zero.py): whole-vector gather per step ----
    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.parallel import shard_batch

    comm = CommContext(mesh=_build_mesh(jax.devices()[:8], 1), n_dcn=1,
                       n_ici=8)
    b_dp = shard_batch(comm, batch)
    out["flat_fsdp"] = _measure_flat(comm, loss_fn, tx, params, b_dp)

    # ---- GSPMD streamed FSDP (fsdp_tp, n_tp=1: pure fsdp) ----
    from byteps_tpu.parallel.fsdp_tp import (
        init_llama_opt_state, init_llama_params_sharded, make_fsdp_tp_mesh,
        shard_llama_batch)
    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=1)
    p_sh = init_llama_params_sharded(mesh, cfg, rng, batch["input_ids"][:1])
    o_sh = init_llama_opt_state(tx, p_sh)

    def gspmd_step(p, o, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    b_sh = shard_llama_batch(mesh, batch)
    lowered = jax.jit(gspmd_step).lower(p_sh, o_sh, b_sh)
    ma = lowered.compile().memory_analysis()
    out["gspmd_fsdp"] = {
        "temp_bytes": int(ma.temp_size_in_bytes),
        "arg_bytes": int(ma.argument_size_in_bytes),
    }
    out["temp_ratio_flat_over_gspmd"] = round(
        out["flat_fsdp"]["temp_bytes"]
        / max(1, out["gspmd_fsdp"]["temp_bytes"]), 2)
    print(json.dumps(out))
    return 0


def _measure_flat(comm, loss_fn, tx, params, b_dp):
    """Lower THE step zero.py builds (via its `.lower` hook — not a
    re-implementation that could drift) and read the compiled memory
    stats."""
    from byteps_tpu.parallel.zero import (init_zero_state,
                                          make_fsdp_train_step)

    zstate = init_zero_state(comm, tx, params)
    fstep = make_fsdp_train_step(comm, loss_fn, tx, params_template=params,
                                 donate=False)
    ma = fstep.lower(zstate, b_dp).compile().memory_analysis()
    return {"temp_bytes": int(ma.temp_size_in_bytes),
            "arg_bytes": int(ma.argument_size_in_bytes)}


if __name__ == "__main__":
    sys.exit(main())
