"""End-to-end overlap benchmark: does cross-barrier + priority + credit
actually buy step time? (round-3 VERDICT task 2)

The reference claims 0–15% end-to-end from priority scheduling with
cross-iteration barriers removed (reference docs/best-practice.md:7, the
ByteScheduler design).  Every prior round measured micro-latency proxies;
this harness trains a real torch model through the engine and times full
steps in three modes:

- **nocomm**: forward/backward/step with NO gradient communication — the
  pure-compute floor; ``t_sync - t_nocomm`` estimates the step's
  communication share.
- **sync**: ``DistributedDataParallel`` — gradients engine-push_pulled
  during backward, barrier at backward end, then ``optimizer.step()``.
  The plain "reduce, then step" path every framework adapter defaults to.
- **xb** (cross-barrier): ``CrossBarrier`` with priority + a credit
  window — ``step()`` returns immediately; each layer's update lands
  just-in-time at the next forward's pre-hook, so late-layer communication
  overlaps the next forward (torch/parallel.py:89-183).

Reported: median step ms (+IQR) per mode, the end-to-end gain
``sync/xb``, and ``overlap_fraction`` = (t_sync - t_xb)/(t_sync -
t_nocomm) — the fraction of the communication share that overlap hides.
That number is the measured replacement for round 3's analytic 82–100%
no-overlap/full-overlap bracket.

Prints one JSON object; bench.py embeds it as the "overlap" section.
Wall-clock caveat: compute (torch) and transport (XLA CPU) share host
cores here, so a 1-core host under-reports the gain a TPU host (compute
on-chip, dispatch on host) would see; the conditions block records the
environment.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools._bench_util import (conditions_block,  # noqa: E402
                               quantile_stats, setup_cpu8_mesh)


def _model(width=512, depth=8, seed=0):
    import torch
    torch.manual_seed(seed)
    layers = []
    for _ in range(depth):
        layers += [torch.nn.Linear(width, width), torch.nn.ReLU()]
    layers.append(torch.nn.Linear(width, 1))
    return torch.nn.Sequential(*layers)


# --compression lanes: codec kwargs handed to the torch wrappers so the
# overlap figures exist for compressed streams too (ISSUE 11 satellite —
# a fused quantized pipeline that destroyed overlap would be invisible
# to the GB/s micro-benches)
COMPRESSION_KWARGS = {
    "none": None,
    "onebit": {"compressor": "onebit", "ef": "vanilla"},
    "randomk": {"compressor": "randomk", "k": "0.25", "ef": "vanilla"},
    "topk": {"compressor": "topk", "k": "0.25", "ef": "vanilla"},
}


def one_mode_pass(mode: str, steps=6, warmup=2, width=512, depth=8,
                  batch=64, compression=None):
    """A fresh model trained ``steps`` measured steps in one mode.

    A fresh model per pass keeps wrapper hooks from accumulating across
    modes; the engine (already initialized by main) is shared — declared
    names are per-mode, and re-declaring the same name with the same shape
    next round is idempotent."""
    import torch

    from byteps_tpu.torch.parallel import CrossBarrier, \
        DistributedDataParallel

    torch.manual_seed(1)
    x = torch.randn(batch, width)
    y = torch.randn(batch, 1)
    model = _model(width, depth)
    opt = torch.optim.SGD(model.parameters(), lr=1e-2)
    loss_fn = torch.nn.MSELoss()

    if mode == "nocomm":
        wrapped, stepper, sync = model, opt.step, lambda: None
    elif mode == "sync":
        wrapped = DistributedDataParallel(model, compression=compression)
        stepper, sync = opt.step, lambda: None
    else:  # xb
        xb = CrossBarrier(model, opt, compression=compression)
        wrapped, stepper, sync = model, xb.step, xb.synchronize

    times, losses = [], []
    for it in range(warmup + steps):
        t0 = time.perf_counter()
        opt.zero_grad(set_to_none=False)
        out = wrapped(x)
        loss = loss_fn(out, y)
        loss.backward()
        stepper()
        if it >= warmup:
            times.append(time.perf_counter() - t0)
        losses.append(float(loss.detach()))
    sync()                           # drain pending xb updates
    return times, losses


def _measure(width=512, rounds=4, compression=None):
    """Interleave modes at round granularity: slow load drift on a shared
    host then hits every mode equally instead of whichever mode ran last
    (the round-3 artifact's failure mode).

    The headline ``overlap_fraction`` is the MEDIAN OF PER-ROUND PAIRED
    fractions — each round's xb measured against its own temporally
    adjacent sync/nocomm passes — not the fraction of pooled medians.
    Pooling completes only half the interleaving logic: on a host whose
    step time is bimodal (this one swings ~130↔180 ms), the pooled
    per-mode medians land on either cluster edge essentially at random
    and the derived fraction flips sign run to run, while adjacent
    passes inside one round see the same regime and their difference is
    stable.  The pooled figure is kept as ``overlap_fraction_pooled``
    for continuity with rounds ≤ 5."""
    modes = ("nocomm", "sync", "xb")
    all_times = {m: [] for m in modes}
    all_losses = {m: None for m in modes}
    round_meds = []
    for _ in range(rounds):
        meds = {}
        for m in modes:
            ts, ls = one_mode_pass(m, width=width, compression=compression)
            all_times[m] += ts
            all_losses[m] = ls
            meds[m] = sorted(ts)[len(ts) // 2]
        round_meds.append(meds)

    res = {}
    for m in modes:
        med, iqr = quantile_stats(all_times[m])
        res[m] = {"step_ms": med, "iqr_ms": iqr,
                  "loss_first": round(all_losses[m][0], 5),
                  "loss_last": round(all_losses[m][-1], 5)}
    t_no, t_sync, t_xb = (res[m]["step_ms"] for m in modes)
    comm_share = max(t_sync - t_no, 0.0)
    paired = [(r["sync"] - r["xb"]) / (r["sync"] - r["nocomm"])
              for r in round_meds if r["sync"] - r["nocomm"] > 1e-6]
    paired.sort()
    import statistics
    frac = round(statistics.median(paired), 3) if paired else None
    return {
        "modes": res,
        "gain_sync_over_xb": round(t_sync / max(t_xb, 1e-9), 3),
        "comm_share_ms": round(comm_share, 1),
        "overlap_fraction": frac,
        "overlap_fraction_rounds": [round(f, 3) for f in paired],
        "overlap_fraction_pooled": (
            round((t_sync - t_xb) / comm_share, 3)
            if comm_share > 1e-6 else None),
        # structural ceiling: overlap can hide at most min(compute, comm)
        # of the comm share — when comm >> compute (CPU-mesh transport is
        # slow), even perfect overlap moves the needle by only this much
        "overlap_ceiling": (round(min(t_no, comm_share) / comm_share, 3)
                            if comm_share > 1e-6 else None),
    }


def _pin_disjoint():
    """Split the available cores: torch compute (the main thread, with
    torch intra-op parallelism off) on one half, every OTHER thread — the
    engine dispatcher/syncer and XLA's device thread pools — on the other
    half (round-4 VERDICT task 4 path B: on a multi-core host, give
    transport somewhere to overlap ONTO).  Must run after the engine and
    the XLA client have spawned their threads (threads created later
    inherit the creator's affinity).  Returns (info, None) on success or
    (None, reason) when the host can't support it."""
    spec = os.environ.get("BYTEPS_BENCH_PIN", "")
    if spec.lower() in ("off", "none"):
        return None, "pinning disabled by BYTEPS_BENCH_PIN"
    try:
        avail = sorted(os.sched_getaffinity(0))
    except AttributeError:
        return None, "sched_setaffinity unavailable on this platform"
    if spec:
        # honor pin_cores()'s core-spec semantics: a user confining the
        # bench to "0,1" must not have every thread silently re-spread
        # across the full host
        try:
            want = set()
            for part in spec.split(","):
                lo, _, hi = part.partition("-")
                want |= set(range(int(lo), int(hi or lo) + 1))
            avail = sorted(want & set(avail))
        except ValueError:
            return None, f"malformed BYTEPS_BENCH_PIN spec {spec!r}"
    if len(avail) < 2:
        return None, (f"host has {len(avail)} available core(s); disjoint "
                      "compute/transport pinning needs >= 2")
    import threading
    half = max(1, len(avail) // 2)
    compute, transport = avail[:half], avail[half:]
    main_tid = threading.get_native_id()
    try:
        os.sched_setaffinity(main_tid, compute)
    except OSError as e:
        return None, f"sched_setaffinity failed: {e}"
    # only after the pin is committed: confine torch compute to the main
    # thread (a global side effect a failed pin must not leave behind)
    import torch
    torch.set_num_threads(1)
    pinned_others = 0
    for tid_s in os.listdir("/proc/self/task"):
        tid = int(tid_s)
        if tid == main_tid:
            continue
        try:
            os.sched_setaffinity(tid, transport)
            pinned_others += 1
        except OSError:
            pass                  # thread exited between listdir and pin
    return {"compute_cores": compute, "transport_cores": transport,
            "other_threads_pinned": pinned_others}, None


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--compression", default="none",
                    choices=sorted(COMPRESSION_KWARGS),
                    help="gradient codec for the sync/xb modes: "
                         "overlap_fraction is then measured on the "
                         "fused quantized stream (nocomm is codec-free "
                         "by construction)")
    args = ap.parse_args(argv)
    compression = COMPRESSION_KWARGS[args.compression]
    setup_cpu8_mesh()
    from byteps_tpu.common.config import Config
    from byteps_tpu.core import api

    width = 512
    # ~2 layers' worth of gradient bytes in flight: the credit window that
    # makes priority meaningful (docs/performance.md, mechanism section).
    # telemetry stays ON (unlike bench_smoke): the engine-side StepStats
    # (sync stall / overlap fraction per step) are part of this bench's
    # OUTPUT; the accounting is a few dict ops per push, identical across
    # the three modes, so the mode comparison is unaffected.
    cfg = Config(telemetry_on=True, trace_on=False,
                 enable_priority=True,
                 scheduling_credit=2 * width * width * 4)
    api.init(cfg)
    try:
        out = _measure(width=width, compression=compression)
        out["compression"] = args.compression
        # Pinned re-measure (round-4 VERDICT task 4 path B): by now the
        # engine + XLA threads all exist, so the disjoint split reaches
        # them.  On a 1-core host the skip reason IS the datum: it
        # documents why this environment cannot show positive overlap.
        info, reason = _pin_disjoint()
        if info is None:
            out["pinned_disjoint"] = {"skipped": reason}
        else:
            pinned = _measure(width=width, compression=compression)
            pinned["pinning"] = info
            out["pinned_disjoint"] = pinned
        # Engine-side evidence beside the end-to-end figures (ISSUE 6):
        # the engine's own per-step view (bytes pushed, sync stall ms,
        # overlap fraction = un-stalled share of step wall) and the
        # diagnostics a regression needs to explain itself.
        from tools._bench_util import metrics_diag
        eng = api._require()
        out["engine_step_stats"] = eng.step_stats.summary()
        out["metrics"] = dict(metrics_diag(),
                              planner=eng.planner.snapshot())
    finally:
        api.shutdown()
    out["conditions"] = conditions_block(
        note=("unpinned figures: torch compute and XLA transport share "
              "host cores; pinned_disjoint (when the host allows) gives "
              "transport its own cores — the regime a TPU host's "
              "on-chip compute / host-side dispatch split resembles"))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
