"""8B-scale (fsdp, tp) feasibility by AOT compilation — no execution.

Round-3 VERDICT task 6: the flagship (fsdp, tp) Llama config is parity-
tested at toy scale, but nothing showed the BASELINE.json configs[4]
target — Llama-3-8B — fits per-device HBM at a plausible mesh.  Execution
at 8B needs hardware; *placement* does not: ``jax.jit(...).lower(...)``
over ``ShapeDtypeStruct``s compiles the full sharded train step without
materializing a single parameter, and XLA's ``memory_analysis()`` reports
per-device argument (persistent: params + opt state), output, alias
(donation overlap) and temp (transient: activations, gradients,
collective buffers) bytes.

The tool compiles the step at Llama-3-8B geometry with a layer-count
sweep (1/2/4/8), fits the per-layer slope, reports the measured 8-layer
point and the projected full-depth (32-layer) footprint per device, and
compares against v5e HBM (16 GB).  Set ``BYTEPS_AOT_FULL=1`` to also
compile the full 32-layer program directly (minutes of XLA time).

Prints one JSON object; bench.py embeds it as the "aot_memory_8b"
section.  Reference scale claim being answered:
/root/reference/README.md:35-41 (BERT-large at 256 GPUs); the rebuild's
flagship is 8B-class with composite sharding instead.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools._bench_util import setup_cpu8_mesh  # noqa: E402

V5E_HBM_BYTES = 16 * 1024**3
FULL_LAYERS = 32
GiB = float(1024**3)


def compile_step(n_layers: int, n_tp: int = 4, batch: int = 8,
                 seq: int = 2048, remat: bool = True):
    """AOT-compile the (fsdp, tp) train step at 8B geometry with
    ``n_layers`` layers; return the XLA memory stats (per device).

    Exact attention only: the deployable config would use the Mosaic
    flash kernel, but this tool runs on the CPU backend where the pallas
    *interpreter* stands in and allocates scratch a real Mosaic kernel
    never materializes — measured, it INFLATED the transient slope
    (2.27 -> 2.86 GiB/layer).  So the sweep compiles exact attention and
    the output labels its transient column an upper bound."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import jax.tree_util as jtu
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from byteps_tpu.models.llama import Llama, llama3_8b, lm_loss
    from byteps_tpu.parallel.fsdp_tp import (
        llama_opt_shardings, llama_shardings, make_fsdp_tp_mesh)

    cfg = dataclasses.replace(llama3_8b(), num_layers=n_layers,
                              remat=remat)
    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=n_tp)
    model = Llama(cfg)
    tx = optax.adamw(3e-4)

    ids = jnp.zeros((1, 8), jnp.int32)
    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0), ids)
    shardings = llama_shardings(mesh, shapes)
    p_structs = jtu.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes, shardings)
    opt_sh = llama_opt_shardings(tx, mesh, p_structs, shardings)
    o_structs = jtu.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        jax.eval_shape(tx.init, p_structs), opt_sh)
    bsh = NamedSharding(mesh, P("fsdp", None))
    batch_structs = {
        "input_ids": jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                          sharding=bsh),
        "labels": jax.ShapeDtypeStruct((batch, seq), jnp.int32,
                                       sharding=bsh),
    }

    def step(params, opt_state, b):
        def loss_fn(p):
            return lm_loss(model.apply(p, b["input_ids"]), b["labels"])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    compiled = jax.jit(step, donate_argnums=(0, 1)).lower(
        p_structs, o_structs, batch_structs).compile()
    ma = compiled.memory_analysis()
    return {
        "n_layers": n_layers,
        "argument_gib": round(ma.argument_size_in_bytes / GiB, 3),
        "temp_gib": round(ma.temp_size_in_bytes / GiB, 3),
        "output_gib": round(ma.output_size_in_bytes / GiB, 3),
        "alias_gib": round(ma.alias_size_in_bytes / GiB, 3),
    }


def main() -> int:
    setup_cpu8_mesh()
    sweep = []
    for n in (1, 2, 4, 8):
        sweep.append(compile_step(n))
    # linear fit of persistent + transient vs layer count from the two
    # largest points (embedding/unembedding are the fixed intercept)
    a, b = sweep[-2], sweep[-1]
    d_layers = b["n_layers"] - a["n_layers"]
    arg_slope = (b["argument_gib"] - a["argument_gib"]) / d_layers
    tmp_slope = (b["temp_gib"] - a["temp_gib"]) / d_layers
    proj_arg = b["argument_gib"] + arg_slope * (FULL_LAYERS - b["n_layers"])
    proj_tmp = b["temp_gib"] + tmp_slope * (FULL_LAYERS - b["n_layers"])
    out = {
        "mesh": "fsdp=2 x tp=4 (8 devices)",
        "geometry": "Llama-3-8B (4096h/32q/8kv/14336ffn), batch 8 x 2048, "
                    "f32 params + adamw moments, remat blocks",
        "sweep_per_device": sweep,
        "per_layer_gib": {"argument": round(arg_slope, 3),
                          "temp": round(tmp_slope, 3)},
        "projected_32_layers_per_device_gib": {
            "argument": round(proj_arg, 2),
            "temp": round(proj_tmp, 2),
        },
        "v5e_hbm_gib": 16,
        # argument bytes are exact and backend-independent: the sharded
        # params + adamw state the mesh must persistently hold per device
        "persistent_fits_v5e_8dev": bool(proj_arg * GiB < V5E_HBM_BYTES),
        "persistent_at_16dev_gib_est": round(proj_arg / 2, 2),
        "temp_caveat": (
            "temp bytes come from the CPU backend's buffer assignment, "
            "which demonstrably does not reuse remat'd block buffers "
            "(remat on/off moves the slope only 2.5->2.27 GiB/layer) and "
            "cannot run the Mosaic flash kernel; on TPU the transient "
            "term is bounded by one block's flash working set, not this "
            "projection.  Treat argument bytes as the feasibility datum "
            "and temp as an upper bound under exact attention."),
        "note": ("per-device bytes from XLA memory_analysis of the AOT-"
                 "compiled donated train step (no execution); scaling the "
                 "mesh divides every sharded term by the device count, so "
                 "what is tight at 8 devices is comfortable at v5e-16 "
                 "(docs/run-on-gke.md deployment shape)"),
    }
    if os.environ.get("BYTEPS_AOT_FULL") == "1":
        out["measured_32_layers_per_device_gib"] = compile_step(FULL_LAYERS)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
