"""Per-test hard timeout for the chaos lane (tools/run_chaos.sh).

The chaos tests spawn real processes and park on rendezvous barriers;
a wedged rendezvous must fail ONE test fast, not eat the whole tier-1
time budget.  pytest-timeout is not in the image, so this is the
minimal POSIX equivalent: SIGALRM around each test phase, raising a
``ChaosTimeout`` in the main thread — which interrupts blocking socket
reads and ``subprocess`` waits exactly where a wedge would park.

Usage (what run_chaos.sh does):

    pytest -p tools.chaos_timeout_plugin --chaos-timeout 120 -m chaos

Main-thread only by design: worker threads are daemonic in this
codebase and die with the test process; the failure modes worth
bounding (multiprocess communicate(), bus rendezvous) all block the
main thread.
"""

from __future__ import annotations

import signal

import pytest


class ChaosTimeout(Exception):
    pass


def pytest_addoption(parser):
    parser.addoption(
        "--chaos-timeout", type=float, default=120.0, metavar="SECONDS",
        help="hard per-test timeout for the chaos lane (SIGALRM; "
             "0 disables)")


def _limit(seconds: float):
    def _on_alarm(signum, frame):
        raise ChaosTimeout(
            f"chaos test exceeded its {seconds:.0f}s hard timeout "
            "(wedged rendezvous / hung worker process?)")
    signal.signal(signal.SIGALRM, _on_alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)


def _clear():
    signal.setitimer(signal.ITIMER_REAL, 0)
    signal.signal(signal.SIGALRM, signal.SIG_DFL)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    seconds = item.config.getoption("--chaos-timeout")
    if seconds and seconds > 0:
        _limit(seconds)
        try:
            yield
        finally:
            _clear()
    else:
        yield
