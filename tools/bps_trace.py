"""bps_trace: merge per-rank trace files into ONE aligned cluster timeline.

Every process of a traced run (``BYTEPS_TRACE_ON`` window or
``BYTEPS_TRACE_SAMPLE`` stream) flushes
``bps_trace_rank{R}_{pid}.json`` into ``BYTEPS_TRACE_DIR``.  Each file's
event timestamps are that process's MONOTONIC clock — meaningless across
processes — but the file carries a ``monoAnchor`` (one simultaneous
``(wall, monotonic)`` pair) and a ``clockSync`` offset (this process's
wall clock minus the membership coordinator's, estimated NTP-style over
the bus ``ping`` verb).  This tool rebases every event onto the
coordinator's wall clock:

    aligned = (ts_mono - anchor.mono) + anchor.wall - clockSync.offset_s

and emits one chrome://tracing / Perfetto JSON whose flow events
(``ph: s/t/f``, bound by ``id``) now connect spans ACROSS ranks — a
push's enqueue → dispatch → wire → merge arc, and each rank's step
flowing into the coordinator's ``bus.step_barrier`` span.

Usage:
    python tools/bps_trace.py [--dir DIR] [--out merged.json] [--validate]

    --dir       directory of per-rank trace files
                (default: $BYTEPS_TRACE_DIR, else the per-user tmp
                trace dir the engine writes to — byteps_tpu.common
                .config.trace_dir_from_env, the one source of truth)
    --out       merged output path (default: <dir>/bps_trace_merged.json)
    --validate  check the merged timeline and exit nonzero on:
                  * any flow ``s`` without a matching ``f`` (same id)
                  * a flow whose aligned timestamps run backwards
                    (f before s beyond the clock-sync error budget)
                  * non-finite/negative aligned timestamps
                Orphan ``f`` flows (a member's reply lost after the
                coordinator closed the arc) are warned, not failed.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import Dict, List, Optional, Tuple

# aligned-causality slack: two clock-sync estimates each carry half-RTT
# error; the validator only fails an arc that runs backwards by more
# than the files' combined declared error (floored at 1 ms)
MIN_SLACK_S = 0.001


def load_trace_files(dir_: str) -> List[dict]:
    """Every per-rank trace doc in ``dir_`` (merged outputs and spill
    side files excluded).  Files are keyed rank+pid, so one RUN yields
    one file per rank; a directory shared across runs merges them all —
    point --dir at a per-run directory (the workers' BYTEPS_TRACE_DIR)
    for a single-run timeline."""
    docs = []
    for path in sorted(glob.glob(os.path.join(dir_, "bps_trace_rank*.json"))):
        if path.endswith("_merged.json") or ".spill." in path:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bps_trace: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        if "traceEvents" not in doc:
            continue
        doc["_path"] = path
        docs.append(doc)
    return docs


def _file_shift(doc: dict) -> Tuple[float, float]:
    """(shift_s, err_s): add ``shift_s`` to a file's monotonic seconds to
    land on the coordinator's wall clock.  Files without an anchor (old
    emitters) fall back to raw monotonic — flagged by err = inf."""
    anchor = doc.get("monoAnchor") or {}
    if "wall" not in anchor or "mono" not in anchor:
        return 0.0, math.inf
    shift = float(anchor["wall"]) - float(anchor["mono"])
    sync = doc.get("clockSync") or {}
    off = sync.get("offset_s")
    err = sync.get("err_s")
    if off is not None:
        shift -= float(off)
        return shift, float(err or 0.0)
    # no bus estimate (single process, or clock sync off): wall clocks
    # are assumed NTP-close; the validator allows generous slack
    return shift, 0.05


def merge(docs: List[dict]) -> dict:
    """One aligned chrome-trace doc from N per-rank docs.

    - every event's ``ts`` is rebased to coordinator wall time (then to
      a zero origin at the earliest event, so the viewer opens at t=0);
    - each file keeps its own ``pid`` namespace (tids are per-pid in the
      chrome model) but gets a ``process_name`` metadata row naming the
      rank, so the merged view reads "rank 0 / rank 1 / ...";
    - flow events pass through untouched — their ``id`` is
      cluster-unique by construction (rank and pid are folded into the
      high bits), which is exactly what makes the cross-rank arcs bind.
    """
    out_events: List[dict] = []
    meta_files = []
    t_min = math.inf
    for doc in docs:
        shift, err = _file_shift(doc)
        rank = doc.get("rank", "?")
        pid = doc.get("pid") or 0
        for ev in doc["traceEvents"]:
            if ev.get("ph") == "M":
                out_events.append(ev)
                continue
            ev = dict(ev)
            ev["ts"] = ev.get("ts", 0.0) + shift * 1e6
            t_min = min(t_min, ev["ts"])
            out_events.append(ev)
        out_events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"rank {rank} (pid {pid})"}})
        meta_files.append({"path": doc.get("_path"), "rank": rank,
                           "pid": pid, "shift_s": round(shift, 6),
                           "clock_err_s": (None if math.isinf(err)
                                           else err),
                           "events": len(doc["traceEvents"]),
                           "dropped": doc.get("droppedEvents", 0)})
    if math.isinf(t_min):
        t_min = 0.0
    for ev in out_events:
        if ev.get("ph") != "M":
            ev["ts"] = ev["ts"] - t_min
    out_events.sort(key=lambda e: (e.get("ph") == "M", e.get("ts", 0.0)))
    return {"traceEvents": out_events, "displayTimeUnit": "ms",
            "mergedFrom": meta_files,
            "originWall": t_min / 1e6}


def validate(merged: dict) -> List[str]:
    """Problems in a merged timeline (empty list = clean).  The two
    contracts the trace lane gates on: every flow ``s`` has its ``f``,
    and aligned timestamps respect causality within the declared
    clock-sync error."""
    errors: List[str] = []
    files = merged.get("mergedFrom") or [{}]
    # a file with no anchor declared an UNKNOWN (infinite) clock error
    # (merge stores it as None): its events sit on raw monotonic time,
    # so cross-file causality is meaningless — skip the backwards check
    # entirely instead of failing every arc against a 0-slack bound
    unalignable = any("clock_err_s" in f and f["clock_err_s"] is None
                      for f in files)
    if unalignable:
        print("bps_trace: warning: file(s) without a clock anchor — "
              "flow-direction validation skipped", file=sys.stderr)
    slack_s = max(MIN_SLACK_S,
                  2 * max((f.get("clock_err_s") or 0.0) for f in files))
    starts: Dict[int, dict] = {}
    finishes: Dict[int, dict] = {}
    n_flows = 0
    for ev in merged["traceEvents"]:
        ph = ev.get("ph")
        if ph == "M":
            continue
        ts = ev.get("ts")
        if ts is None or not math.isfinite(ts) or ts < -1e-6:
            errors.append(f"non-monotonic/invalid aligned ts {ts!r} on "
                          f"{ev.get('name')!r} (pid {ev.get('pid')})")
            continue
        if ph in ("s", "t", "f"):
            n_flows += 1
            fid = ev.get("id")
            if fid is None:
                errors.append(f"flow event without id: {ev}")
                continue
            if ph == "s":
                if fid in starts:
                    errors.append(f"duplicate flow s for id {fid}")
                starts[fid] = ev
            elif ph == "f":
                if fid in finishes:
                    errors.append(f"duplicate flow f for id {fid}")
                finishes[fid] = ev
    for fid, ev in starts.items():
        fin = finishes.get(fid)
        if fin is None:
            errors.append(
                f"flow s id={fid} ({ev.get('name')}, pid {ev.get('pid')},"
                f" tid {ev.get('tid')}) has no matching f")
        elif not unalignable and fin["ts"] + slack_s * 1e6 < ev["ts"]:
            errors.append(
                f"flow id={fid} runs backwards after alignment: "
                f"s at {ev['ts']:.1f}us, f at {fin['ts']:.1f}us "
                f"(slack {slack_s * 1e3:.1f}ms)")
    for fid in set(finishes) - set(starts):
        # the coordinator closed an arc whose member never learned the
        # round completed (lost reply) — noisy, not wrong
        print(f"bps_trace: warning: flow f id={fid} has no s",
              file=sys.stderr)
    if n_flows == 0:
        print("bps_trace: warning: no flow events in the merged trace",
              file=sys.stderr)
    return errors


def summarize(merged: dict) -> dict:
    evs = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    flows = [e for e in evs if e.get("ph") in ("s", "t", "f")]
    pids_per_flow: Dict[int, set] = {}
    for e in flows:
        pids_per_flow.setdefault(e.get("id"), set()).add(e.get("pid"))
    cross = sum(1 for pids in pids_per_flow.values() if len(pids) > 1)
    return {"files": len(merged.get("mergedFrom", [])),
            "events": len(evs),
            "flow_events": len(flows),
            "flow_arcs": len(pids_per_flow),
            "cross_process_arcs": cross,
            "span_ms": round((max((e.get("ts", 0) for e in evs),
                                  default=0)) / 1e3, 3)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--dir", default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--validate", action="store_true")
    args = ap.parse_args(argv)

    if args.dir is None:
        # same derivation the engine flushes to — the tool must look
        # where the tracer wrote, not at a second hardcoded default
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from byteps_tpu.common.config import trace_dir_from_env
        args.dir = trace_dir_from_env()

    docs = load_trace_files(args.dir)
    if not docs:
        print(f"bps_trace: no bps_trace_rank*.json under {args.dir}",
              file=sys.stderr)
        return 2
    merged = merge(docs)
    out = args.out or os.path.join(args.dir, "bps_trace_merged.json")
    with open(out, "w") as f:
        json.dump(merged, f)
    summary = summarize(merged)
    summary["out"] = out
    if args.validate:
        errors = validate(merged)
        summary["validation_errors"] = len(errors)
        print(json.dumps(summary))
        for e in errors[:50]:
            print(f"bps_trace: INVALID: {e}", file=sys.stderr)
        return 1 if errors else 0
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
