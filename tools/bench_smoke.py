"""Bench-smoke gate: the 8 MB engine micro-bench as a CI lane.

Measures the engine and fused push_pull paths at 8 MB on the virtual
8-device CPU mesh and FAILS (exit 1) when the engine-vs-fused ratio
regresses more than ``BENCH_SMOKE_TOLERANCE`` (default 30%) below the
checked-in floor (``tools/bench_smoke_floor.json``).

Why the RATIO gates and not raw GB/s: absolute throughput on a shared
CI host measures the host (round-to-round fused figures here span
0.23–0.47 GB/s on identical code).  The fused path is measured in the
same run, on the same load, so engine/fused cancels host speed and
isolates what this lane exists to catch — a regression in the engine
machinery (ISSUE 5's headline was exactly this ratio collapsing to
0.30x).  Raw engine GB/s is still printed and recorded for the trend.

Usage:  python tools/bench_smoke.py [--update-floor]
        --update-floor: re-measure and rewrite the floor file (use after
        an intentional perf change; review the diff like any artifact).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools._bench_util import quantile_stats_raw, setup_cpu8_mesh  # noqa: E402

FLOOR_PATH = os.path.join(REPO, "tools", "bench_smoke_floor.json")
MB = 1024 * 1024


def _measure(nbytes=8 * MB, reps=9):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from byteps_tpu.comm.collectives import push_pull_array
    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common.config import Config
    from byteps_tpu.core.engine import PushPullEngine

    devices = jax.devices()
    n = len(devices)
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)

    def med(xs):
        m, _, _ = quantile_stats_raw(xs)
        return m

    # fused ceiling: the exact collective the engine dispatches
    x_dev = jax.device_put(jnp.zeros((n, nbytes // 4), jnp.float32),
                           comm.stacked_sharding(extra_dims=1))
    push_pull_array(comm, x_dev, op="sum").block_until_ready()

    # engine path, host-staged (the product's own metric), warmed to the
    # planner's locked steady state exactly as bench.py measures it
    cfg = Config(telemetry_on=False, trace_on=False)
    eng = PushPullEngine(comm, cfg)
    try:
        x = np.random.RandomState(0).randn(nbytes // 4).astype(np.float32)
        eng.declare_tensor("smoke.pp", x.shape, np.float32)
        for _ in range(24):
            eng.push_pull_local(x, "smoke.pp")
            if eng.planner.locked(nbytes):
                break
        # INTERLEAVED timed reps: fused and engine adjacent within each
        # rep, ratio taken PER REP, median across reps.  The two paths
        # measured a minute apart see different host regimes (this host's
        # step speed is bimodal, ~2x swing) and their ratio then measures
        # the host, not the engine — adjacent pairs see the same regime,
        # so the per-rep ratio isolates what this gate exists to catch.
        fused_t, eng_t, ratios = [], [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            push_pull_array(comm, x_dev, op="sum").block_until_ready()
            tf = time.perf_counter() - t0
            t0 = time.perf_counter()
            eng.push_pull_local(x, "smoke.pp")
            te = time.perf_counter() - t0
            fused_t.append(tf)
            eng_t.append(te)
            ratios.append(tf / te)   # engine/fused throughput ratio
        snap = eng.planner.snapshot()
    finally:
        eng.shutdown(wait=False)
    # Diagnostics snapshot riding the bench record (ISSUE 6 satellite):
    # a ratio regression arrives with its own evidence instead of
    # needing a rerun under a profiler.
    from tools._bench_util import metrics_diag
    diag = metrics_diag()
    return {"fused_8MB_gbps": round(nbytes / med(fused_t) / 1e9, 3),
            "engine_8MB_gbps": round(nbytes / med(eng_t) / 1e9, 3),
            "engine_vs_fused_ratio": round(med(ratios), 3),
            "ratio_per_rep": [round(r, 3) for r in sorted(ratios)],
            "autotune": snap,
            "metrics": diag}


def _measure_serve():
    """Serving lane (ISSUE 9): pulls/sec + p99 pull latency under
    concurrent training pushes, recorded beside the push figures so the
    read dimension lands in the benched trajectory.  Not gated — the
    delta-accounting ``ok`` flag is the correctness proxy, and absolute
    pulls/sec on a shared host measures the host."""
    from tools import serve_bench
    out = serve_bench.measure(seconds=1.0, clients=2, keys=4,
                              numel=32768, replicas=3, staleness=0.0)
    out["delta"] = serve_bench.delta_check()
    return {k: out[k] for k in ("pulls_per_s", "p50_ms", "p99_ms",
                                "pushes_per_s", "failed_reads", "delta")}


def main() -> int:
    setup_cpu8_mesh()
    tol = float(os.environ.get("BENCH_SMOKE_TOLERANCE", "0.30"))
    out = _measure()
    out["serve"] = _measure_serve()
    if "--update-floor" in sys.argv:
        floor = {"engine_vs_fused_ratio": out["engine_vs_fused_ratio"],
                 "engine_8MB_gbps": out["engine_8MB_gbps"],
                 "note": "measured floor; the lane fails below "
                         "ratio * (1 - tolerance)"}
        with open(FLOOR_PATH, "w") as f:
            json.dump(floor, f, indent=1)
            f.write("\n")
        out["floor_updated"] = floor
        print(json.dumps(out))
        return 0
    with open(FLOOR_PATH) as f:
        floor = json.load(f)
    # Either/or gate, because the two floors fail in OPPOSITE host
    # regimes: when the shared host runs slow, the fused denominator
    # collapses and the ratio is honest while raw GB/s measures the
    # host; when it runs fast, fused scales with memory speed but the
    # engine is capped by fixed per-push host latency, so the ratio
    # structurally drops (measured ~1.0 slow vs ~0.35 fast on identical
    # code) while raw GB/s is honest.  An engine-machinery regression
    # tanks BOTH; a legitimate run in either regime passes one.
    gate_r = floor["engine_vs_fused_ratio"] * (1.0 - tol)
    gate_a = floor["engine_8MB_gbps"] * (1.0 - tol)
    out["floor"] = {k: floor[k] for k in ("engine_vs_fused_ratio",
                                          "engine_8MB_gbps")}
    out["gate_ratio"] = round(gate_r, 3)
    out["gate_gbps"] = round(gate_a, 3)
    out["ok"] = (out["engine_vs_fused_ratio"] >= gate_r
                 or out["engine_8MB_gbps"] >= gate_a)
    print(json.dumps(out))
    if not out["ok"]:
        print(f"bench-smoke FAIL: engine_vs_fused_ratio "
              f"{out['engine_vs_fused_ratio']} < gate {gate_r:.3f} AND "
              f"engine_8MB_gbps {out['engine_8MB_gbps']} < gate "
              f"{gate_a:.3f} (floor {out['floor']}, tolerance {tol:.0%})",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
