"""Bench-smoke gate: the 8 MB engine micro-bench as a CI lane.

Measures the engine and fused push_pull paths at 8 MB on the virtual
8-device CPU mesh and FAILS (exit 1) when the engine-vs-fused ratio
regresses more than ``BENCH_SMOKE_TOLERANCE`` (default 30%) below the
checked-in floor (``tools/bench_smoke_floor.json``).

Why the RATIO gates and not raw GB/s: absolute throughput on a shared
CI host measures the host (round-to-round fused figures here span
0.23–0.47 GB/s on identical code).  The fused path is measured in the
same run, on the same load, so engine/fused cancels host speed and
isolates what this lane exists to catch — a regression in the engine
machinery (ISSUE 5's headline was exactly this ratio collapsing to
0.30x).  Raw engine GB/s is still printed and recorded for the trend.

Usage:  python tools/bench_smoke.py [--update-floor]
        --update-floor: re-measure and rewrite the floor file (use after
        an intentional perf change; review the diff like any artifact).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools._bench_util import quantile_stats_raw, setup_cpu8_mesh  # noqa: E402

FLOOR_PATH = os.path.join(REPO, "tools", "bench_smoke_floor.json")
MB = 1024 * 1024


def _measure(nbytes=8 * MB, reps=9):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from byteps_tpu.comm.collectives import push_pull_array
    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common.config import Config
    from byteps_tpu.core.engine import PushPullEngine

    devices = jax.devices()
    n = len(devices)
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)

    def med(xs):
        m, _, _ = quantile_stats_raw(xs)
        return m

    # fused ceiling: the exact collective the engine dispatches
    x_dev = jax.device_put(jnp.zeros((n, nbytes // 4), jnp.float32),
                           comm.stacked_sharding(extra_dims=1))
    push_pull_array(comm, x_dev, op="sum").block_until_ready()

    # engine path, host-staged (the product's own metric), warmed to the
    # planner's locked steady state exactly as bench.py measures it
    cfg = Config(telemetry_on=False, trace_on=False)
    eng = PushPullEngine(comm, cfg)
    try:
        x = np.random.RandomState(0).randn(nbytes // 4).astype(np.float32)
        eng.declare_tensor("smoke.pp", x.shape, np.float32)
        for _ in range(24):
            eng.push_pull_local(x, "smoke.pp")
            if eng.planner.locked(nbytes):
                break
        # INTERLEAVED timed reps: fused and engine adjacent within each
        # rep, ratio taken PER REP, median across reps.  The two paths
        # measured a minute apart see different host regimes (this host's
        # step speed is bimodal, ~2x swing) and their ratio then measures
        # the host, not the engine — adjacent pairs see the same regime,
        # so the per-rep ratio isolates what this gate exists to catch.
        fused_t, eng_t, ratios = [], [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            push_pull_array(comm, x_dev, op="sum").block_until_ready()
            tf = time.perf_counter() - t0
            t0 = time.perf_counter()
            eng.push_pull_local(x, "smoke.pp")
            te = time.perf_counter() - t0
            fused_t.append(tf)
            eng_t.append(te)
            ratios.append(tf / te)   # engine/fused throughput ratio
        snap = eng.planner.snapshot()
    finally:
        eng.shutdown(wait=False)
    # Diagnostics snapshot riding the bench record (ISSUE 6 satellite):
    # a ratio regression arrives with its own evidence instead of
    # needing a rerun under a profiler.
    from tools._bench_util import metrics_diag
    diag = metrics_diag()
    return {"fused_8MB_gbps": round(nbytes / med(fused_t) / 1e9, 3),
            "engine_8MB_gbps": round(nbytes / med(eng_t) / 1e9, 3),
            "engine_vs_fused_ratio": round(med(ratios), 3),
            "ratio_per_rep": [round(r, 3) for r in sorted(ratios)],
            "autotune": snap,
            "metrics": diag}


def _measure_compressed(nbytes=2 * MB, reps=5):
    """Compressed lanes (ISSUE 11): onebit and randomk through the
    engine's fused quantized path at a >= 1 MiB tensor.  Reported per
    lane: wire-byte ratio vs uncompressed (analytic payload bytes — the
    quantized reduce-leg contract), engine GB/s, per-rep throughput
    ratio vs the uncompressed engine path (interleaved, same host
    regime — the bench_smoke pairing trick), the codec-golden quality
    figure, and a zero-compile flag (no new cache programs during the
    timed reps: the AOT contract on the bench path).

    Gating (floor file): onebit wire ratio must stay under
    ``compressed_wire_ratio_max``, every lane's golden error under
    ``compressed_quality_ceiling`` (deterministic — no tolerance), and
    the throughput ratio over ``compressed_throughput_floor`` with the
    lane tolerance.  On a CPU mesh compression is compute-bound and
    SLOWER than uncompressed (the wire it saves is emulated); the
    throughput floor guards the machinery from regressing further, the
    wire ratio is what the feature ships."""
    import jax
    import numpy as np

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common.config import Config
    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.compression import registry as creg
    from byteps_tpu.core.engine import PushPullEngine

    devices = jax.devices()
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1,
                       n_ici=len(devices))
    cfg = Config(telemetry_on=False, trace_on=False,
                 min_compress_bytes=4096)
    eng = PushPullEngine(comm, cfg)
    lanes = {}
    try:
        n = nbytes // 4
        x = np.random.RandomState(3).randn(n).astype(np.float32)
        stacked = np.ascontiguousarray(
            np.broadcast_to(x[None], (comm.num_ranks, n)))

        def push(name, **kw):
            h = eng.push_pull_async(stacked, name, op="sum",
                                    out_shape=(n,), **kw)
            out = h.wait()
            import jax as _jax
            _jax.block_until_ready(out)

        eng.declare_tensor("cmp.base", (n,), np.float32, op="sum",
                           local=False)
        push("cmp.base")
        for codec, kwargs in (
                ("onebit", {"compressor": "onebit", "ef": "vanilla"}),
                ("randomk", {"compressor": "randomk", "k": "0.25",
                             "ef": "vanilla"})):
            name = f"cmp.{codec}"
            eng.declare_tensor(name, (n,), np.float32, op="sum",
                               compression=kwargs)
            push(name, compression=kwargs)      # warm (states, staging)
            ctx = eng.registry.get(name)
            payload = sum(s.worker.payload_nbytes()
                          for s in (ctx.compressor or ()))
            m0 = counters.get("engine.compile_cache_miss")
            base_t, lane_t, ratios = [], [], []
            for _ in range(reps):
                t0 = time.perf_counter()
                push("cmp.base")
                tb = time.perf_counter() - t0
                t0 = time.perf_counter()
                push(name, compression=kwargs)
                tc = time.perf_counter() - t0
                base_t.append(tb)
                lane_t.append(tc)
                ratios.append(tb / tc)   # compressed/uncompressed tput
            def med(xs):
                m, _, _ = quantile_stats_raw(xs)
                return m
            lanes[codec] = {
                "wire_ratio": round(payload / nbytes, 4),
                "gbps": round(nbytes / med(lane_t) / 1e9, 3),
                "uncompressed_gbps": round(nbytes / med(base_t) / 1e9, 3),
                "throughput_ratio": round(med(ratios), 3),
                "golden_error": round(creg.golden_error(kwargs), 4),
                "zero_compile": counters.get("engine.compile_cache_miss")
                == m0,
            }
    finally:
        eng.shutdown(wait=False)
    return lanes


def _compressed_ok(lanes: dict, floor: dict, tol: float) -> bool:
    """The compressed gate (pure; pinned by a unit test like the
    straggler gate): onebit's wire ratio and every lane's golden error
    are deterministic contracts — no tolerance; the throughput ratio is
    a host measurement and takes the lane tolerance."""
    ratio_max = floor.get("compressed_wire_ratio_max", 0.35)
    quality_max = floor.get("compressed_quality_ceiling", 0.55)
    tput_floor = floor.get("compressed_throughput_floor", 0.0)
    ok = True
    for codec, lane in lanes.items():
        lane_ok = lane["golden_error"] <= quality_max
        if codec == "onebit":
            lane_ok = lane_ok and lane["wire_ratio"] <= ratio_max
        lane_ok = lane_ok and (lane["throughput_ratio"]
                               >= tput_floor * (1.0 - tol))
        lane["ok"] = lane_ok
        ok = ok and lane_ok
    return ok


def _measure_sharded_update(reps=7):
    """Sharded weight update lane (ISSUE 20): the MLP model's leaves
    through the engine twice — unsharded (push_pull + caller-side eager
    optax, the DistributedOptimizer data path) and sharded
    (``declare_update`` / ``push_pull_update``: owner-resident optimizer
    + parameter-shard pull leg) — on the 8-device mesh.

    Reported: steady-state wire bytes/step per arm (from the per-leg
    ``wire_bytes{leg=push|pull}`` counters, ISSUE satellite a), their
    ratio (the feature's headline: push N + pull N/R vs push N + pull N
    = 0.5625 at R=8 for buffer-eligible leaves), the interleaved
    step-time ratio (per-rep pairing cancels host regime, exactly the
    engine-vs-fused trick), and an ``exact`` flag: the two arms'
    parameters after the timed steps must be bitwise identical — the
    replay proof riding the bench.

    Gating (floor file): the wire ratio is a deterministic contract —
    ``sharded_wire_ratio_max``, no tolerance — and ``exact`` must hold;
    the step-time ratio is a host measurement and takes the lane
    tolerance against ``sharded_step_ratio_floor``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common.config import Config
    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.core.engine import PushPullEngine
    from byteps_tpu.models.mlp import MLP

    devices = jax.devices()
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1,
                       n_ici=len(devices))
    model = MLP(features=(256, 128, 10))
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 64), jnp.float32))
    leaves, treedef = jax.tree_util.tree_flatten(params)
    names = [f"su.{i}" for i in range(len(leaves))]
    p_np = [np.asarray(l, np.float32) for l in leaves]
    rng = np.random.RandomState(7)
    grads = [rng.randn(*l.shape).astype(np.float32) for l in leaves]
    tx = optax.adam(1e-2)
    # telemetry ON in BOTH arms: the wire figures come from the per-leg
    # counters, and the step-time ratio stays fair because both sides
    # pay the same accounting
    cfg_kw = dict(telemetry_on=True, trace_on=False,
                  partition_bytes=16384)

    eng_u = PushPullEngine(comm, Config(**cfg_kw))
    eng_s = PushPullEngine(comm, Config(sharded_update=True, **cfg_kw))
    try:
        p_u = [jnp.asarray(a) for a in p_np]
        state = tx.init(jax.tree_util.tree_unflatten(treedef, p_u))
        p_s = [jnp.asarray(a) for a in p_np]
        for name, a in zip(names, p_np):
            eng_u.declare_tensor(name, a.shape, np.float32, op="average",
                                 local=True)
            eng_s.declare_update(name, a.shape, np.float32, tx=tx,
                                 init_value=a)

        def step_u(p, state):
            red = [eng_u.push_pull_local(g, n, op="average")
                   for n, g in zip(names, grads)]
            upd, state = tx.update(
                jax.tree_util.tree_unflatten(treedef,
                                             [jnp.asarray(r)
                                              for r in red]),
                state, jax.tree_util.tree_unflatten(treedef, p))
            out = [optax.apply_updates(a, u)
                   for a, u in zip(p, jax.tree_util.tree_leaves(upd))]
            jax.block_until_ready(out)
            return out, state

        def step_s(p):
            upd = [eng_s.push_pull_update(g, n)
                   for n, g in zip(names, grads)]
            out = [optax.apply_updates(a, jnp.asarray(u))
                   for a, u in zip(p, upd)]
            jax.block_until_ready(out)
            return out

        p_u, state = step_u(p_u, state)          # warm both arms
        p_s = step_s(p_s)
        # steady-state wire bytes/step from the per-leg counters
        pu0, pl0 = (counters.get("wire_bytes", leg="push"),
                    counters.get("wire_bytes", leg="pull"))
        p_u, state = step_u(p_u, state)
        pu1, pl1 = (counters.get("wire_bytes", leg="push"),
                    counters.get("wire_bytes", leg="pull"))
        p_s = step_s(p_s)
        pu2, pl2 = (counters.get("wire_bytes", leg="push"),
                    counters.get("wire_bytes", leg="pull"))
        wire_u = (pu1 - pu0) + (pl1 - pl0)
        wire_s = (pu2 - pu1) + (pl2 - pl1)
        u_t, s_t, ratios = [], [], []
        for _ in range(reps):
            t0 = time.perf_counter()
            p_u, state = step_u(p_u, state)
            tu = time.perf_counter() - t0
            t0 = time.perf_counter()
            p_s = step_s(p_s)
            ts = time.perf_counter() - t0
            u_t.append(tu)
            s_t.append(ts)
            ratios.append(tu / ts)   # sharded/unsharded step throughput
        exact = all(np.array_equal(np.asarray(a), np.asarray(b))
                    for a, b in zip(p_u, p_s))
    finally:
        eng_u.shutdown(wait=False)
        eng_s.shutdown(wait=False)

    def med(xs):
        m, _, _ = quantile_stats_raw(xs)
        return m
    return {"wire_bytes_per_step_unsharded": wire_u,
            "wire_bytes_per_step_sharded": wire_s,
            "wire_ratio": round(wire_s / wire_u, 4),
            "step_ms_unsharded": round(med(u_t) * 1e3, 3),
            "step_ms_sharded": round(med(s_t) * 1e3, 3),
            "step_time_ratio": round(med(ratios), 3),
            "ratio_per_rep": [round(r, 3) for r in sorted(ratios)],
            "exact": exact}


def _sharded_update_ok(su: dict, floor: dict, tol: float) -> bool:
    """The sharded_update gate (pure; pinned by a unit test): the wire
    ratio and the replay exactness are deterministic contracts — no
    tolerance; the step-time ratio is a host measurement and takes the
    lane tolerance."""
    ratio_max = floor.get("sharded_wire_ratio_max", 0.62)
    step_floor = floor.get("sharded_step_ratio_floor", 0.0)
    gate = step_floor * (1.0 - tol)
    su["gate_step_ratio"] = round(gate, 3)
    return (su["exact"]
            and su["wire_ratio"] <= ratio_max
            and su["step_time_ratio"] >= gate)


def _measure_trace(nbytes=4 * MB, reps=9, sample_n=4):
    """Sampled-tracing overhead lane (ISSUE 12 acceptance: the ratio
    gate still passes with ``BYTEPS_TRACE_SAMPLE`` armed — sampled
    tracing is cheap enough to leave on in production).

    Interleaved per-rep pairs on ONE engine: each rep times a push with
    the process tracer's sampled stream OFF then ON (``sample_n`` far
    denser than a production 1/64, so the gate bounds a worst case).
    The ratio (off wall / on wall) cancels host regime exactly like the
    engine-vs-fused pairing; gated against
    ``trace_sample_overhead_floor`` with the lane tolerance."""
    import tempfile

    import jax
    import numpy as np

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common import tracing as _tracing
    from byteps_tpu.common.config import Config
    from byteps_tpu.core.engine import PushPullEngine

    devices = jax.devices()
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1,
                       n_ici=len(devices))
    tmp = tempfile.mkdtemp(prefix="bps_trace_bench_")
    tr = _tracing.set_tracer(_tracing.Tracer(
        enabled=False, sample_n=0, out_dir=tmp, capacity=1 << 16))
    cfg = Config(telemetry_on=True, trace_on=False)
    eng = PushPullEngine(comm, cfg)
    try:
        x = np.random.RandomState(1).randn(nbytes // 4).astype(np.float32)
        eng.declare_tensor("trace.pp", x.shape, np.float32)
        for _ in range(24):
            eng.push_pull_local(x, "trace.pp")
            if eng.planner.locked(nbytes):
                break
        tr.sample_n = sample_n       # warm the sampled path's branches
        eng.push_pull_local(x, "trace.pp")
        ratios = []
        for _ in range(reps):
            tr.sample_n = 0
            t0 = time.perf_counter()
            eng.push_pull_local(x, "trace.pp")
            t_off = time.perf_counter() - t0
            tr.sample_n = sample_n
            t0 = time.perf_counter()
            eng.push_pull_local(x, "trace.pp")
            t_on = time.perf_counter() - t0
            ratios.append(t_off / t_on)   # sampled/unsampled throughput
        def med(xs):
            m, _, _ = quantile_stats_raw(xs)
            return m
        return {"sample_n": sample_n,
                "overhead_ratio": round(med(ratios), 3),
                "ratio_per_rep": [round(r, 3) for r in sorted(ratios)],
                "events_buffered": tr.debug_state()["events_buffered"],
                "events_dropped": tr.dropped}
    finally:
        eng.shutdown(wait=False)
        _tracing.set_tracer(None)


def _trace_ok(trc: dict, floor: dict, tol: float) -> bool:
    """Sampled tracing must not cost more than the floor allows AND the
    sampled stream must actually have recorded something (a 1.0 ratio
    with zero events would mean the lane silently stopped tracing)."""
    gate = floor.get("trace_sample_overhead_floor", 0.7) * (1.0 - tol)
    trc["gate_ratio"] = round(gate, 3)
    return (trc["overhead_ratio"] >= gate
            and trc["events_buffered"] > 0)


def _measure_ts_sampler(nbytes=4 * MB, reps=9):
    """Time-series sampler overhead lane (ISSUE 16 acceptance: the
    history plane's registry sampler is cheap enough to leave armed in
    production — it runs inside every trained process).

    Interleaved per-rep pairs on ONE engine: each rep times a push with
    the sampler idle, then a push followed by a forced ``sample_once()``
    (one full registry snapshot + delta-encode + ring append per PUSH —
    hundreds of times denser than the production 2 s cadence, so the
    gate bounds a gross worst case).  The ratio (off wall / on wall)
    cancels host regime exactly like the engine-vs-fused pairing; gated
    against ``ts_sampler_overhead_floor`` with the lane tolerance."""
    import jax
    import numpy as np

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common.config import Config
    from byteps_tpu.common.timeseries import TimeSeriesStore
    from byteps_tpu.core.engine import PushPullEngine

    devices = jax.devices()
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1,
                       n_ici=len(devices))
    store = TimeSeriesStore(interval_s=2.0, window=64)
    cfg = Config(telemetry_on=True, trace_on=False)
    eng = PushPullEngine(comm, cfg)
    try:
        x = np.random.RandomState(2).randn(nbytes // 4).astype(np.float32)
        eng.declare_tensor("ts.pp", x.shape, np.float32)
        for _ in range(24):
            eng.push_pull_local(x, "ts.pp")
            if eng.planner.locked(nbytes):
                break
        store.sample_once()          # warm the sampler's branches
        ratios = []
        for _ in range(reps):
            t0 = time.perf_counter()
            eng.push_pull_local(x, "ts.pp")
            t_off = time.perf_counter() - t0
            t0 = time.perf_counter()
            eng.push_pull_local(x, "ts.pp")
            store.sample_once()
            t_on = time.perf_counter() - t0
            ratios.append(t_off / t_on)   # sampled/unsampled throughput

        def med(xs):
            m, _, _ = quantile_stats_raw(xs)
            return m
        return {"samples": len(store.points()),
                "overhead_ratio": round(med(ratios), 3),
                "ratio_per_rep": [round(r, 3) for r in sorted(ratios)]}
    finally:
        eng.shutdown(wait=False)


def _ts_ok(ts: dict, floor: dict, tol: float) -> bool:
    """The sampler must not cost more than the floor allows AND must
    actually have filled the ring (a 1.0 ratio with an empty ring would
    mean the lane silently stopped sampling)."""
    gate = floor.get("ts_sampler_overhead_floor", 0.95) * (1.0 - tol)
    ts["gate_ratio"] = round(gate, 3)
    return ts["overhead_ratio"] >= gate and ts["samples"] > 0


def _measure_transport(nbytes=256 * 1024, reps=30):
    """Transport lane (comm/transport.py, docs/transport.md): the
    loopback-vs-TCP throughput ratio for seq-tokened KV deltas
    (interleaved per-rep pairs — the bench_smoke host-regime pairing
    trick), and the p99 push latency to a LIVE shard while a second
    shard's peer is partitioned (a dead endpoint in the shard set must
    cost the live path nothing: its supervisor retries in the
    background, it never blocks another connection's sends).

    Gated (floor file): ``transport_tcp_ratio_floor`` bounds how much
    the real wire may cost versus the in-process fast path for this
    payload size, and ``transport_partitioned_p99_ms`` is an absolute
    ceiling on the live-shard p99 under one partitioned peer — the
    isolation contract, checkable on any host because the partition is
    injected, not environmental."""
    import math
    import socket as _socket
    import threading as _threading

    import numpy as np

    from byteps_tpu.common import integrity as _bint
    from byteps_tpu.comm import transport as btp
    from byteps_tpu.server.kv_store import KVStore

    n = nbytes // 4
    kv_lb, kv_tcp = KVStore(), KVStore()
    for kv in (kv_lb, kv_tcp):
        kv.init_key("bench", np.zeros(n, np.float32))
    srv = btp.TransportServer(rank=0, kv=kv_tcp)
    lb = btp.LoopbackEndpoint(kv=kv_lb)
    ep = btp.TcpEndpoint(srv.addr, peer=0)
    delta = np.random.RandomState(0).randn(n).astype(np.float32)
    lb.push_delta("bench", delta, seq=1)
    ep.push_delta("bench", delta, seq=1)          # warm (conn, buffers)
    lb_t, tcp_t, ratios = [], [], []
    for i in range(reps):
        t0 = time.perf_counter()
        lb.push_delta("bench", delta, seq=i + 2)
        tl = time.perf_counter() - t0
        t0 = time.perf_counter()
        ep.push_delta("bench", delta, seq=i + 2)
        tt = time.perf_counter() - t0
        lb_t.append(tl)
        tcp_t.append(tt)
        ratios.append(tl / tt)    # tcp/loopback throughput ratio

    def med(xs):
        m, _, _ = quantile_stats_raw(xs)
        return m

    # one partitioned peer in a 2-shard world: a dead endpoint whose
    # supervisor dials a black hole forever, beside the live one
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    dead = btp.TcpEndpoint(("127.0.0.1", dead_port), peer=1,
                           send_deadline_s=0.2, keepalive_s=0.0)
    client = btp.ShardedClient([ep, dead])
    live_key = next(k for k in (f"k{j}" for j in range(64))
                    if client.assigner.write_target(k) == 0)
    dead_key = next(k for k in (f"k{j}" for j in range(64))
                    if client.assigner.write_target(k) == 1)
    kv_tcp.init_key(live_key, np.zeros(256, np.float32))
    stop = _threading.Event()

    def hammer():
        seq = 1
        while not stop.is_set():
            try:
                client.push_delta(dead_key, np.zeros(256, np.float32),
                                  seq=seq)
            except (_bint.AckLost, btp.TransportError):
                pass
            seq += 1

    t = _threading.Thread(target=hammer, daemon=True)
    t.start()
    lats = []
    small = np.ones(256, np.float32)
    for i in range(150):
        t0 = time.perf_counter()
        client.push_delta(live_key, small, seq=i + 1)
        lats.append(time.perf_counter() - t0)
    stop.set()
    t.join(timeout=5)
    lats.sort()
    p99 = lats[min(len(lats) - 1, math.ceil(0.99 * len(lats)) - 1)]
    dead.close(drain=False)
    ep.close()
    srv.close()
    from byteps_tpu.common.telemetry import counters as _counters
    return {"nbytes": nbytes,
            "loopback_gbps": round(nbytes / med(lb_t) / 1e9, 3),
            "tcp_gbps": round(nbytes / med(tcp_t) / 1e9, 3),
            "tcp_vs_loopback_ratio": round(med(ratios), 3),
            "partitioned_peer_p99_ms": round(p99 * 1e3, 3),
            "deadline_trips": _counters.get("transport.send_deadline_trips"),
            "reconnect_attempts": dead.connection.dial_attempts}


def _transport_ok(trp: dict, floor: dict, tol: float) -> bool:
    """The transport gate (pure; pinned by a unit test): the TCP/loopback
    ratio is a host measurement and takes the lane tolerance; the
    partitioned-peer p99 is an absolute isolation ceiling (the fault is
    injected, so the bound holds on any host)."""
    gate_ratio = floor.get("transport_tcp_ratio_floor", 0.0) * (1.0 - tol)
    gate_p99 = floor.get("transport_partitioned_p99_ms", 50.0)
    trp["gate_ratio"] = round(gate_ratio, 4)
    trp["gate_p99_ms"] = gate_p99
    return (trp["tcp_vs_loopback_ratio"] >= gate_ratio
            and trp["partitioned_peer_p99_ms"] <= gate_p99)


def _measure_serve():
    """Serving lane (ISSUE 9): pulls/sec + p99 pull latency under
    concurrent training pushes, recorded beside the push figures so the
    read dimension lands in the benched trajectory.  Not gated — the
    delta-accounting ``ok`` flag is the correctness proxy, and absolute
    pulls/sec on a shared host measures the host."""
    from tools import serve_bench
    out = serve_bench.measure(seconds=1.0, clients=2, keys=4,
                              numel=32768, replicas=3, staleness=0.0)
    out["delta"] = serve_bench.delta_check()
    return {k: out[k] for k in ("pulls_per_s", "p50_ms", "p99_ms",
                                "pushes_per_s", "failed_reads", "delta")}


def _measure_straggler(slow_s=0.03, reps=150):
    """Straggler section (ISSUE 10): p99 pull latency with ONE slowed
    serving replica, hedged vs unhedged, against the no-fault p99.

    Gated (unlike the serve section): hedging exists to bound the tail,
    and the bound is checkable on any host because the slow endpoint's
    delay is injected, not environmental — unhedged p99 tracks the
    injected delay, hedged p99 must stay within the floor file's factor
    of the no-fault p99 (with a small absolute allowance for thread
    scheduling noise on a loaded CI host)."""
    import numpy as np

    from byteps_tpu.server.kv_store import KVStore
    from byteps_tpu.server.serve_client import PullClient
    from byteps_tpu.server.serving import ServingPlane

    store = KVStore()
    for k in ("st.a", "st.b"):
        store.init_key(k, np.zeros(4096, np.float32))
        store.push_delta(k, np.ones(4096, np.float32))
    plane = ServingPlane(store, replicas=3, retention=8, hot_keys=8)
    plane.cut()
    PullClient(plane, max_staleness_s=0.0).pull()   # hotness histogram
    plane.cut()                                     # mirror the hot keys

    def p99_ms(hedge, n=reps):
        import math
        client = PullClient(plane, max_staleness_s=0.0, hedge=hedge)
        lats = []
        for _ in range(n):
            t0 = time.perf_counter()
            client.pull()
            lats.append(time.perf_counter() - t0)
        lats.sort()
        # ceil-based p99 index: with n=150 this is element 148 of 149 —
        # a single scheduler/GC outlier cannot fail the gate (n=60 with
        # a naive index was literally gating on the sample MAX)
        idx = min(n - 1, math.ceil(0.99 * n) - 1)
        return round(lats[idx] * 1e3, 3)

    # no-fault baseline measured on the HEDGED path: the comparison must
    # not credit hedging for also skipping its own thread overhead (and
    # this run warms the adaptive delay ring with healthy latencies)
    nofault = p99_ms(hedge=True)
    plane.replicas[0].delay_s = slow_s
    unhedged = p99_ms(hedge=False)
    hedged = p99_ms(hedge=True)
    plane.close()
    from byteps_tpu.common.telemetry import counters
    return {"p99_nofault_ms": nofault,
            "p99_unhedged_ms": unhedged,
            "p99_hedged_ms": hedged,
            "slow_endpoint_ms": slow_s * 1e3,
            "hedged_pulls": counters.get("serve.hedged_pulls"),
            "hedge_wins": counters.get("serve.hedge_wins")}


def _straggler_ok(st, floor) -> bool:
    gate = max(floor.get("straggler_hedge_p99_factor", 2.0)
               * st["p99_nofault_ms"],
               floor.get("straggler_hedge_p99_abs_ms", 10.0))
    st["gate_ms"] = round(gate, 3)
    return st["p99_hedged_ms"] <= gate


def _measure_serve_dist():
    """Distributed serving tier (ISSUE 15): pulls/s + p99 against 3
    REAL serving-host processes behind the TCP transport — snapshot
    deltas shipped per the consistent-hash ring, membership-bus
    directory, admission control armed.  The headline read-scale figure
    of the benched trajectory."""
    from tools import serve_bench
    out = serve_bench.measure_distributed(
        hosts=3, seconds=1.5, clients=3, keys=6, numel=16384,
        replicas=2, staleness=0.05)
    keep = ("hosts", "pulls_per_s", "p50_ms", "p99_ms", "pushes_per_s",
            "failed_reads", "per_host", "ships", "ship_failures",
            "failovers", "shed")
    return {k: out[k] for k in keep}


def _serve_dist_ok(sd: dict, floor: dict, tol: float) -> bool:
    """The serve_dist gate (pure; pinned by a unit test): zero failed
    reads is ABSOLUTE (the tier's whole promise), every spawned host
    must actually have answered pulls (a silently dead host that never
    failed a read would otherwise pass), and aggregate pulls/s must
    clear the floor with the lane tolerance."""
    gate = floor.get("serve_dist_pulls_per_s_floor", 0.0) * (1.0 - tol)
    sd["gate_pulls_per_s"] = round(gate, 1)
    every_host_served = all(v.get("pulls", 0) > 0
                            for v in sd.get("per_host", {}).values())
    return (sd["failed_reads"] == 0
            and every_host_served
            and sd["pulls_per_s"] >= gate)


def _measure_durability(numel=16 * 1024, reps=9, batch=24,
                        replay_pushes=400):
    """Durability lane (ISSUE 19, server/wal.py): what the journal
    costs on the hot push path, and how fast a cold start replays it.

    Push cost: interleaved per-rep batches of ``push_delta`` against a
    plain in-memory KVStore and a WAL-attached one (same key shape,
    same deltas, adjacent in time — the bench_smoke host-regime pairing
    trick), ratio = plain wall / durable wall per rep, median across
    reps.  The journal runs with ``fsync=off`` so the ratio isolates
    the journaling machinery (pickle + CRC seal + buffered write),
    not this host's disk — the fsync policy cost is an operator
    choice documented in docs/fault_tolerance.md, not a regression
    this gate could meaningfully bound on a shared CI host.

    Replay: a fresh journal of ``replay_pushes`` records is cold-read
    back through ``wal.recover`` into an empty store; MB/s over the
    journal bytes actually replayed.  Gated (floor file):
    ``durability_push_ratio_floor`` and
    ``durability_replay_mbps_floor``."""
    import shutil
    import tempfile

    import numpy as np

    from byteps_tpu.common.config import Config
    from byteps_tpu.server import wal
    from byteps_tpu.server.kv_store import KVStore

    tmp = tempfile.mkdtemp(prefix="bps_bench_durable_")
    cfg = Config(telemetry_on=False, trace_on=False,
                 durable_dir=tmp, wal_fsync="off")
    try:
        plain = KVStore()
        durable = KVStore()
        dur = wal.attach(durable, os.path.join(tmp, "push"), cfg)
        zeros = np.zeros(numel, np.float32)
        plain.init_key("b", zeros)
        durable.init_key("b", zeros)
        delta = np.random.RandomState(3).randn(numel).astype(np.float32)

        def burst(store, start):
            for seq in range(start, start + batch):
                store.push_delta("b", delta, worker_id=0, seq=seq)

        burst(plain, 1)          # warm both paths past first-touch
        burst(durable, 1)
        ratios = []
        for rep in range(reps):
            base = (rep + 1) * batch + 1
            t0 = time.perf_counter()
            burst(plain, base)
            t_plain = time.perf_counter() - t0
            t0 = time.perf_counter()
            burst(durable, base)
            t_dur = time.perf_counter() - t0
            ratios.append(t_plain / t_dur)
        dur.close()

        # cold-start replay: a fresh journal, then recover into an
        # empty store and clock the whole snapshot+replay path
        replay_dir = os.path.join(tmp, "replay")
        src = KVStore()
        src_dur = wal.attach(src, replay_dir, cfg)
        src.init_key("b", zeros)
        for seq in range(1, replay_pushes + 1):
            src.push_delta("b", delta, worker_id=0, seq=seq)
        src_dur.close()
        t0 = time.perf_counter()
        _, stats = wal.recover(replay_dir, cfg=cfg)
        replay_s = time.perf_counter() - t0

        def med(xs):
            m, _, _ = quantile_stats_raw(xs)
            return m
        return {"push_ratio": round(med(ratios), 3),
                "ratio_per_rep": [round(r, 3) for r in sorted(ratios)],
                "replay_records": stats["records"],
                "replay_mb": round(stats["bytes"] / MB, 2),
                "replay_mbps": round(stats["bytes"] / MB / replay_s, 1),
                "truncated_tails": stats["truncated_tails"],
                "corrupt_records": stats["corrupt_records"]}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _durability_ok(du: dict, floor: dict, tol: float) -> bool:
    """The durability gate (pure; pinned by a unit test): the journal
    must not tax the push path below the floor ratio, a cold start
    must replay above the MB/s floor, the replay must actually have
    read the records back (a 0-record replay would gate nothing), and
    a CLEAN journal must replay with zero damage detected — a torn
    tail or corrupt record on a fault-free bench run means the write
    path itself is producing garbage."""
    gate_r = floor.get("durability_push_ratio_floor", 0.0) * (1.0 - tol)
    gate_m = floor.get("durability_replay_mbps_floor", 0.0) * (1.0 - tol)
    du["gate_push_ratio"] = round(gate_r, 3)
    du["gate_replay_mbps"] = round(gate_m, 1)
    return (du["push_ratio"] >= gate_r
            and du["replay_mbps"] >= gate_m
            and du["replay_records"] > 0
            and du["truncated_tails"] == 0
            and du["corrupt_records"] == 0)


def _measure_fleet():
    """Fleet churn (ISSUE 18): pulls/s + p99 measured WHILE the fleet
    reconciler spawns real serve-host processes up to the peak target
    and gracefully drains back to base — the autoscaler-driven
    membership churn the self-operating fleet promises to serve
    through."""
    from tools import serve_bench
    out = serve_bench.measure_fleet(
        seconds=4.0, clients=3, keys=6, numel=16384, replicas=2,
        staleness=0.05, base_hosts=2, peak_hosts=4)
    keep = ("base_hosts", "peak_hosts", "pulls_per_s", "p50_ms",
            "p99_ms", "pushes_per_s", "failed_reads", "spawned",
            "drain_started", "drained", "drain_escalated", "banned",
            "final_hosts", "still_draining")
    return {k: out[k] for k in keep}


def _fleet_ok(fl: dict, floor: dict, tol: float) -> bool:
    """The fleet gate (pure; pinned by a unit test): zero failed reads
    through the churn is ABSOLUTE, the churn must actually have
    happened (spawns up to the peak AND at least one graceful drain
    back — a bench that never grew the fleet would gate nothing), the
    drains must have completed clean (none escalated to kill, none
    still draining), and pulls/s under churn must clear the floor with
    the lane tolerance."""
    gate = floor.get("fleet_pulls_per_s_floor", 0.0) * (1.0 - tol)
    fl["gate_pulls_per_s"] = round(gate, 1)
    churned = (fl.get("spawned", 0) >= fl.get("peak_hosts", 0)
               and fl.get("drained", 0) >= 1)
    drains_clean = (fl.get("drain_escalated", 0) == 0
                    and not fl.get("still_draining"))
    return (fl["failed_reads"] == 0
            and churned
            and drains_clean
            and fl["pulls_per_s"] >= gate)


def main() -> int:
    setup_cpu8_mesh()
    tol = float(os.environ.get("BENCH_SMOKE_TOLERANCE", "0.30"))
    out = _measure()
    out["serve"] = _measure_serve()
    out["straggler"] = _measure_straggler()
    out["compressed"] = _measure_compressed()
    out["sharded_update"] = _measure_sharded_update()
    out["trace"] = _measure_trace()
    out["ts_sampler"] = _measure_ts_sampler()
    out["transport"] = _measure_transport()
    out["serve_dist"] = _measure_serve_dist()
    out["fleet"] = _measure_fleet()
    out["durability"] = _measure_durability()
    if "--update-floor" in sys.argv:
        # compressed throughput floor: half the measured worst lane —
        # room for host noise, still catches a machinery collapse
        worst_tput = min(lane["throughput_ratio"]
                         for lane in out["compressed"].values())
        floor = {"engine_vs_fused_ratio": out["engine_vs_fused_ratio"],
                 "engine_8MB_gbps": out["engine_8MB_gbps"],
                 "straggler_hedge_p99_factor": 2.0,
                 "straggler_hedge_p99_abs_ms": 5.0,
                 "compressed_wire_ratio_max": 0.35,
                 "compressed_quality_ceiling": 0.55,
                 "compressed_throughput_floor": round(worst_tput / 2, 3),
                 # sharded update: the wire ratio is the feature's
                 # deterministic contract (push N + pull N/R = 0.5625x
                 # at R=8 for buffer-eligible leaves; small leaves ride
                 # the parts fallback at 1.0x, so the model-level bound
                 # sits just above the hot-path figure); the step-time
                 # floor is half the measured ratio (host-noise room,
                 # still catches an update-machinery collapse)
                 "sharded_wire_ratio_max": 0.62,
                 "sharded_step_ratio_floor": round(
                     out["sharded_update"]["step_time_ratio"] / 2, 3),
                 "trace_sample_overhead_floor": 0.7,
                 # ts sampler: one registry snapshot per push costs
                 # near-nothing next to a 4 MB collective — 0.95 is the
                 # always-on contract, not a host measurement
                 "ts_sampler_overhead_floor": 0.95,
                 # transport: half the measured TCP/loopback ratio
                 # (host-noise room, still catches a wire-machinery
                 # collapse); the p99 ceiling is an absolute isolation
                 # contract, not a measurement
                 "transport_tcp_ratio_floor": round(
                     out["transport"]["tcp_vs_loopback_ratio"] / 2, 3),
                 "transport_partitioned_p99_ms": 50.0,
                 # serve_dist: a tenth of the measured distributed
                 # pulls/s — generous host-noise room (the figure spans
                 # three processes and the scheduler), still catches a
                 # tier-machinery collapse
                 "serve_dist_pulls_per_s_floor": round(
                     out["serve_dist"]["pulls_per_s"] / 10, 1),
                 # fleet: same tenth-of-measured rule — the churn
                 # figure spans reconciler passes, process spawns, and
                 # graceful drains, so it is the noisiest lane of all
                 "fleet_pulls_per_s_floor": round(
                     out["fleet"]["pulls_per_s"] / 10, 1),
                 # durability: half the measured push ratio (the
                 # interleaved pairing cancels host regime, but pickle
                 # + CRC cost still jitters with CPU contention) and a
                 # tenth of the measured replay MB/s (cold reads hit
                 # the page cache unpredictably on a shared host)
                 "durability_push_ratio_floor": round(
                     out["durability"]["push_ratio"] / 2, 3),
                 "durability_replay_mbps_floor": round(
                     out["durability"]["replay_mbps"] / 10, 1),
                 "note": "measured floor; the lane fails below "
                         "ratio * (1 - tolerance)"}
        with open(FLOOR_PATH, "w") as f:
            json.dump(floor, f, indent=1)
            f.write("\n")
        out["floor_updated"] = floor
        print(json.dumps(out))
        return 0
    with open(FLOOR_PATH) as f:
        floor = json.load(f)
    # Either/or gate, because the two floors fail in OPPOSITE host
    # regimes: when the shared host runs slow, the fused denominator
    # collapses and the ratio is honest while raw GB/s measures the
    # host; when it runs fast, fused scales with memory speed but the
    # engine is capped by fixed per-push host latency, so the ratio
    # structurally drops (measured ~1.0 slow vs ~0.35 fast on identical
    # code) while raw GB/s is honest.  An engine-machinery regression
    # tanks BOTH; a legitimate run in either regime passes one.
    gate_r = floor["engine_vs_fused_ratio"] * (1.0 - tol)
    gate_a = floor["engine_8MB_gbps"] * (1.0 - tol)
    out["floor"] = {k: floor[k] for k in ("engine_vs_fused_ratio",
                                          "engine_8MB_gbps")}
    out["gate_ratio"] = round(gate_r, 3)
    out["gate_gbps"] = round(gate_a, 3)
    engine_ok = (out["engine_vs_fused_ratio"] >= gate_r
                 or out["engine_8MB_gbps"] >= gate_a)
    straggler_ok = _straggler_ok(out["straggler"], floor)
    out["straggler"]["ok"] = straggler_ok
    compressed_ok = _compressed_ok(out["compressed"], floor, tol)
    sharded_ok = _sharded_update_ok(out["sharded_update"], floor, tol)
    out["sharded_update"]["ok"] = sharded_ok
    trace_ok = _trace_ok(out["trace"], floor, tol)
    out["trace"]["ok"] = trace_ok
    ts_ok = _ts_ok(out["ts_sampler"], floor, tol)
    out["ts_sampler"]["ok"] = ts_ok
    transport_ok = _transport_ok(out["transport"], floor, tol)
    out["transport"]["ok"] = transport_ok
    serve_dist_ok = _serve_dist_ok(out["serve_dist"], floor, tol)
    out["serve_dist"]["ok"] = serve_dist_ok
    fleet_ok = _fleet_ok(out["fleet"], floor, tol)
    out["fleet"]["ok"] = fleet_ok
    durability_ok = _durability_ok(out["durability"], floor, tol)
    out["durability"]["ok"] = durability_ok
    out["ok"] = (engine_ok and straggler_ok and compressed_ok
                 and sharded_ok and trace_ok
                 and ts_ok and transport_ok and serve_dist_ok
                 and fleet_ok and durability_ok)
    print(json.dumps(out))
    if not engine_ok:
        print(f"bench-smoke FAIL: engine_vs_fused_ratio "
              f"{out['engine_vs_fused_ratio']} < gate {gate_r:.3f} AND "
              f"engine_8MB_gbps {out['engine_8MB_gbps']} < gate "
              f"{gate_a:.3f} (floor {out['floor']}, tolerance {tol:.0%})",
              file=sys.stderr)
    if not straggler_ok:
        st = out["straggler"]
        print(f"bench-smoke FAIL: hedged-pull p99 {st['p99_hedged_ms']}ms "
              f"under one slowed replica exceeds the gate "
              f"{st['gate_ms']}ms (no-fault p99 {st['p99_nofault_ms']}ms, "
              f"unhedged {st['p99_unhedged_ms']}ms) — the hedge path is "
              f"no longer bounding the tail", file=sys.stderr)
    if not compressed_ok:
        bad = {k: v for k, v in out["compressed"].items()
               if not v.get("ok")}
        print(f"bench-smoke FAIL: compressed lane(s) {sorted(bad)} "
              f"violate the floor (wire ratio max "
              f"{floor.get('compressed_wire_ratio_max')}, quality "
              f"ceiling {floor.get('compressed_quality_ceiling')}, "
              f"throughput floor "
              f"{floor.get('compressed_throughput_floor')}): {bad}",
              file=sys.stderr)
    if not sharded_ok:
        su = out["sharded_update"]
        print(f"bench-smoke FAIL: sharded_update lane violates the "
              f"floor — exact {su['exact']} (the sharded trajectory "
              f"must be bitwise the unsharded one), wire_ratio "
              f"{su['wire_ratio']} > max "
              f"{floor.get('sharded_wire_ratio_max')}, or "
              f"step_time_ratio {su['step_time_ratio']} < gate "
              f"{su['gate_step_ratio']} — the sharded-update machinery "
              f"regressed", file=sys.stderr)
    if not trace_ok:
        trc = out["trace"]
        print(f"bench-smoke FAIL: sampled tracing "
              f"(BYTEPS_TRACE_SAMPLE=1/{trc['sample_n']}) costs too "
              f"much: throughput ratio {trc['overhead_ratio']} < gate "
              f"{trc['gate_ratio']} (or the sampled stream recorded "
              f"nothing: {trc['events_buffered']} events) — always-on "
              f"sampling is no longer cheap enough to leave armed",
              file=sys.stderr)
    if not ts_ok:
        tss = out["ts_sampler"]
        print(f"bench-smoke FAIL: the time-series sampler costs too "
              f"much: throughput ratio {tss['overhead_ratio']} < gate "
              f"{tss['gate_ratio']} (or the ring recorded nothing: "
              f"{tss['samples']} samples) — the always-on history "
              f"plane is no longer cheap enough to leave armed",
              file=sys.stderr)
    if not serve_dist_ok:
        sd = out["serve_dist"]
        print(f"bench-smoke FAIL: serve_dist lane violates the floor — "
              f"failed_reads {sd['failed_reads']} (must be 0), per-host "
              f"pulls {sd['per_host']} (every host must serve), or "
              f"pulls_per_s {sd['pulls_per_s']} < gate "
              f"{sd['gate_pulls_per_s']} — the distributed tier "
              f"machinery regressed", file=sys.stderr)
    if not fleet_ok:
        fl = out["fleet"]
        print(f"bench-smoke FAIL: fleet lane violates the floor — "
              f"failed_reads {fl['failed_reads']} (must be 0 through "
              f"the churn), spawned {fl['spawned']} / drained "
              f"{fl['drained']} (the churn must actually happen), "
              f"drain_escalated {fl['drain_escalated']} / "
              f"still_draining {fl['still_draining']} (drains must "
              f"land clean), or pulls_per_s {fl['pulls_per_s']} < gate "
              f"{fl['gate_pulls_per_s']} — the self-operating fleet "
              f"machinery regressed", file=sys.stderr)
    if not durability_ok:
        du = out["durability"]
        print(f"bench-smoke FAIL: durability lane violates the floor — "
              f"push_ratio {du['push_ratio']} < gate "
              f"{du['gate_push_ratio']} (the journal is taxing the hot "
              f"push path), replay_mbps {du['replay_mbps']} < gate "
              f"{du['gate_replay_mbps']} over {du['replay_records']} "
              f"record(s) (cold start got slow or replayed nothing), "
              f"or a CLEAN journal replayed with damage "
              f"(truncated_tails {du['truncated_tails']}, "
              f"corrupt_records {du['corrupt_records']} — the write "
              f"path is producing garbage)", file=sys.stderr)
    if not transport_ok:
        trp = out["transport"]
        print(f"bench-smoke FAIL: transport lane violates the floor — "
              f"tcp_vs_loopback_ratio {trp['tcp_vs_loopback_ratio']} < "
              f"gate {trp['gate_ratio']} OR partitioned-peer p99 "
              f"{trp['partitioned_peer_p99_ms']}ms > ceiling "
              f"{trp['gate_p99_ms']}ms (a dead shard peer must never "
              f"tax the live path)", file=sys.stderr)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
