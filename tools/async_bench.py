"""Async-PS vs sync convergence datum (round-4 VERDICT task 7).

The async weight-delta mode (§2.6.7; reference server.cc:310-314 sum-on-
arrival, torch/__init__.py:186-214 worker cycle) exists and is
unit-tested, but no artifact showed async training *converging* against
the sync baseline.  This tool trains the same MNIST-style MLP on the
same synthetic data both ways and reports the final-loss gap:

- **sync**: one barriered step per iteration — every worker's gradient is
  averaged before anyone applies it (the fused-DP semantics).
- **async**: N workers share a KVStore; each runs its own local
  SGD step, pushes its weight DELTA (no barrier), and pulls the current
  global weights — workers interleave at thread-scheduler granularity,
  so the measured gap includes real staleness, not a simulation of it.

Prints ONE JSON line.  Run standalone (``python tools/async_bench.py``)
or embedded by bench.py as the ``async_vs_sync`` section of the full
record.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools._bench_util import conditions_block, pin_cores  # noqa: E402

STEPS = 80
WORKERS = 2
LR = 0.05


def main() -> int:
    pinned = pin_cores()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from byteps_tpu.jax.async_opt import AsyncDistributedOptimizer
    from byteps_tpu.models.mlp import mnist_mlp, softmax_cross_entropy
    from byteps_tpu.server import KVStore

    rng = np.random.RandomState(42)
    x_all = jnp.asarray(rng.randn(64 * WORKERS, 16).astype(np.float32))
    y_all = jnp.asarray(rng.randint(0, 10, 64 * WORKERS))
    shards = [(x_all[i::WORKERS], y_all[i::WORKERS]) for i in range(WORKERS)]

    model = mnist_mlp()
    params0 = model.init(jax.random.PRNGKey(0), x_all[:1])

    def loss_fn(p, xb, yb):
        return softmax_cross_entropy(model.apply(p, xb), yb)

    grad = jax.jit(jax.grad(loss_fn))
    loss_init = float(loss_fn(params0, x_all, y_all))

    # ---- sync baseline: barriered gradient average every step ----
    tx = optax.sgd(LR)
    state = tx.init(params0)
    params = params0
    t0 = time.perf_counter()
    for _ in range(STEPS):
        gs = [grad(params, xb, yb) for xb, yb in shards]
        g = jax.tree.map(lambda *a: sum(a) / WORKERS, *gs)
        upd, state = tx.update(g, state, params)
        params = optax.apply_updates(params, upd)
    wall_sync = time.perf_counter() - t0
    loss_sync = float(loss_fn(params, x_all, y_all))

    # ---- async: shared store, one thread per worker, no barrier ----
    store = KVStore()
    opts = [AsyncDistributedOptimizer(optax.sgd(LR), store=store)
            for _ in range(WORKERS)]
    states = [o.init(params0) for o in opts]
    # init() re-registers the same keys; the store keeps one copy — every
    # worker starts from params0 and the versions advance from there.

    errors = []

    def worker(i):
        # a crashed worker must surface in the JSON, not produce a
        # plausible-looking "async diverged" datum (the store would hold
        # partially-trained weights with nothing saying why)
        try:
            p, s = params0, states[i]
            xb, yb = shards[i]
            for _ in range(STEPS):
                g = grad(p, xb, yb)
                p, s = opts[i].update_and_sync(g, s, p)
        except Exception as e:  # noqa: BLE001
            errors.append(f"worker {i}: {type(e).__name__}: {e}"[:200])

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(WORKERS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_async = time.perf_counter() - t0

    # final global weights live in the store
    names = opts[0]._leaf_names(params0)
    leaves = [jnp.asarray(store.pull(n)) for n in names]
    final = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params0), leaves)
    loss_async = float(loss_fn(final, x_all, y_all))
    versions = [store.version(k) for k in store.keys()]
    progress = loss_init - loss_sync

    out = {
        "workers": WORKERS,
        "steps_per_worker": STEPS,
        "lr": LR,
        "loss_init": round(loss_init, 4),
        "loss_sync": round(loss_sync, 4),
        "loss_async": round(loss_async, 4),
        "final_loss_gap": round(loss_async - loss_sync, 4),
        # gap as a fraction of the sync run's improvement; undefined (null)
        # if sync made none — a 1e9-scale clamp artifact is worse than a
        # missing field
        "gap_rel_to_progress": (round((loss_async - loss_sync) / progress, 4)
                                if progress > 1e-6 else None),
        "async_converged": bool(loss_async < loss_init * 0.5),
        # every key must have seen every worker's every delta; unequal
        # versions mean lost pushes (or a crashed worker) and are reported
        # as a range, not averaged away
        "delta_pushes_per_key": (versions[0]
                                 if len(set(versions)) == 1 else
                                 {"min": min(versions),
                                  "max": max(versions)}),
        "wall_sync_s": round(wall_sync, 2),
        "wall_async_s": round(wall_async, 2),
        "conditions": conditions_block(
            pinned, note="async staleness is real thread interleaving; "
                         "gap varies run to run on a loaded host"),
    }
    if errors:
        out["error"] = "; ".join(errors)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
