"""Shared helpers for the bench tools (mechanism_bench, overlap_bench):
one copy of the CPU-mesh setup, quantile stats, and core pinning, so the
tools can't silently drift apart in how they measure."""

from __future__ import annotations

import os


def cpu8_flags(existing=None) -> str:
    """XLA_FLAGS value forcing the virtual 8-device CPU mesh, stripping
    any stale device-count flag first.  The ONE copy of this
    strip-and-append (bench.py and every tool import it), so embedded and
    standalone runs can't drift in what mesh they measure.  jax-free:
    safe to import from processes that must not init a backend."""
    import re
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", "")
                   if existing is None else existing)
    return (flags + " --xla_force_host_platform_device_count=8").strip()


def setup_cpu8_mesh():
    """Force the virtual 8-device CPU mesh in THIS process.

    A bare ``python tools/<bench>.py`` must measure the same multi-rank
    configuration bench.py embeds, not a silent 1-device mesh.  Must run
    before the first JAX backend use; jax.config.update is the reliable
    platform switch (the image's sitecustomize consumes JAX_PLATFORMS at
    interpreter start)."""
    os.environ["XLA_FLAGS"] = cpu8_flags()
    import jax
    jax.config.update("jax_platforms", "cpu")


def quantile_stats_raw(samples):
    """(median_s, q25_s, q75_s) unrounded, in seconds, linearly
    interpolated.  Derived rates (GB/s) must divide by THESE, not the
    display-rounded ms from quantile_stats: a sub-50 ns median rounds to
    0.0 ms at 4 digits and a rate computed from it divides by zero."""
    xs = sorted(samples)
    n = len(xs)

    def q(p):
        i = p * (n - 1)
        lo, hi = int(i), min(int(i) + 1, n - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (i - lo)

    return q(0.5), q(0.25), q(0.75)


def quantile_stats(samples, digits=1):
    """(median, [q25, q75]) in ms from samples in seconds, rounded for
    display.  The IQR is the honesty term: a shared host can't promise
    tight medians, so every artifact carries its spread."""
    med, q25, q75 = quantile_stats_raw(samples)
    return (round(med * 1e3, digits),
            [round(q25 * 1e3, digits), round(q75 * 1e3, digits)])


def pin_cores():
    """Pin this process to a stable core subset when that actually changes
    anything; return the pinned set (or None) for the conditions block.

    Pinning cannot evict other processes, but it stops scheduler migration
    from adding its own variance.  Only a *strict subset* of the available
    cores is ever reported: pinning to everything is a no-op and recording
    it would claim a stabilization that didn't happen.  Opt out with
    BYTEPS_BENCH_PIN=off; choose cores with e.g. BYTEPS_BENCH_PIN=0-3 or
    BYTEPS_BENCH_PIN=0,2,5 (a bare "1" pins core 1 — every non-empty
    value that isn't "off"/"none" is a core spec).
    """
    spec = os.environ.get("BYTEPS_BENCH_PIN", "")
    if spec.lower() in ("off", "none"):
        return None
    try:
        avail = sorted(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        return None
    if spec:
        try:
            want = set()
            for part in spec.split(","):
                lo, _, hi = part.partition("-")
                want |= set(range(int(lo), int(hi or lo) + 1))
            want &= set(avail)
        except ValueError:
            return None  # malformed spec: run unpinned rather than die
        if want == set(avail):
            # explicit spec covering every available core: setting the
            # affinity is a no-op; honoring the strict-subset invariant
            # beats honoring the spec literally
            return None
    elif len(avail) >= 4:
        # leave core 0 (interrupt-heavy) out when there's room
        want = set(avail[1:])
    else:
        # 1-3 cores: any default pin is the full set, i.e. a no-op —
        # don't report a stabilization that didn't happen
        return None
    if not want:
        return None
    try:
        os.sched_setaffinity(0, want)
    except OSError:
        return None
    return sorted(want)


def conditions_block(pinned=None, note: str = "") -> dict:
    """The measurement-environment stamp every bench JSON carries."""
    return {
        "pinned_cores": pinned,
        "host_cores": os.cpu_count(),
        "loadavg_1m": (round(os.getloadavg()[0], 2)
                       if hasattr(os, "getloadavg") else None),
        "note": note,
    }


def metrics_diag() -> dict:
    """Diagnostics counters embedded in bench artifacts (bench_smoke,
    overlap_bench): a regression record arrives with its own evidence —
    did the compile cache stop hitting, did AOT warm fail, did the wire
    start retransmitting.  ONE copy, so the benches cannot drift in
    which counters they snapshot."""
    from byteps_tpu.common.telemetry import counters
    return {
        "compile_cache_hit": counters.get("engine.compile_cache_hit"),
        "compile_cache_miss": counters.get("engine.compile_cache_miss"),
        "aot_compiled": counters.get("engine.aot_compiled"),
        "aot_compile_failed": counters.get("engine.aot_compile_failed"),
        "retransmits": counters.get("integrity.retransmit"),
        "crc_rejects": counters.get("integrity.crc_reject"),
    }
