"""TPU watcher: probe the tunneled chip, run bench.py on green, record.

Round-2 VERDICT item 1: the chip drops intermittently, so the bench must be
run early and often — not once at round end.  This watcher loops:

  1. probe the backend in a subprocess (90 s timeout, probe()'s default),
  2. on green, run the full ``bench.py`` and parse its JSON line,
  3. if the line is a TPU line, write it to ``BENCH_TPU_LATEST.json`` and
     append a dated entry to ``BENCH_TPU_MEASURED.json``'s history,
  4. sleep and repeat — dense probing until the first complete green
     bench, then hourly probes with a re-bench at most every 6 h (drift
     history without hogging the chip the driver's round-end capture
     needs).

Run in the background for the whole round:  python tools/tpu_watch.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MEASURED = os.path.join(REPO, "BENCH_TPU_MEASURED.json")
LATEST = os.path.join(REPO, "BENCH_TPU_LATEST.json")
WATCHLOG = os.path.join(REPO, "TPU_WATCH_LOG.json")

PROBE = ("import jax, json; ds = jax.devices();"
         "print('PROBE', ds[0].platform, len(ds), ds[0].device_kind)")


def _atomic_dump(doc, path):
    """Write-temp-then-rename so a mid-write kill can't truncate the
    history file (the watch runs unattended for hours)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def _load_json(path, default):
    """Load a state file, falling back to ``default`` on anything that
    isn't a JSON dict (missing, truncated, hand-edited, null) — a bad
    state file must never kill the unattended watch loop."""
    if os.path.exists(path):
        try:
            with open(path) as f:
                doc = json.load(f)
            if isinstance(doc, dict):
                return doc
        except (json.JSONDecodeError, OSError):
            pass
    return default


def probe(timeout=90.0):
    try:
        p = subprocess.run([sys.executable, "-c", PROBE],
                           capture_output=True, text=True,
                           timeout=timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        return None
    for line in p.stdout.splitlines():
        if line.startswith("PROBE "):
            parts = line.split(maxsplit=3)
            return {"platform": parts[1], "n": int(parts[2]),
                    "kind": parts[3] if len(parts) > 3 else "?"}
    return None


# Sized from the sum of bench.py's own internal worst-case budgets
# (probe 240 + inner 3000 + re-probe 90 + degraded retry 2400 + scaling
# 3600 + overlap 1800 + 2x900 mech/aot merges + 600 async + 600 dcn
# ≈ 14,130 s) plus slack — an outer timeout below the child's own budget
# would fire exactly on the runs that took longest and had the most to
# salvage (round-4 advisor finding).
_BENCH_TIMEOUT = 15300


def _parse_bench_stdout(text):
    """The record from a bench run's stdout: the 'BENCH_FULL '-prefixed
    full-record line when present (since round 5 bench.py's final
    plain-JSON line is a compact driver summary whose section figures the
    watch history needs are stripped), else the last JSON line, else —
    for a run killed before any final line — a partial reassembled from
    the BENCH_SECTION stream the outer echoes (bench._echo_inner_stream)."""
    lines = (text or "").strip().splitlines()
    for pick in (lambda ln: (ln[len("BENCH_FULL "):]
                             if ln.startswith("BENCH_FULL ") else None),
                 lambda ln: ln if ln.startswith("{") else None):
        for line in reversed(lines):
            candidate = pick(line)
            if candidate is not None:
                try:
                    return json.loads(candidate)
                except json.JSONDecodeError:
                    return None
    sys.path.insert(0, REPO)
    import bench
    sections, hung = bench._sections_from_stdout(text)
    if not sections:
        return None
    doc = bench._assemble(sections, "outer bench killed by watch timeout",
                          write_baseline=False)
    doc["partial"] = True
    if hung:
        doc["hung_section"] = hung
    return doc


def run_bench():
    try:
        p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           capture_output=True, text=True,
                           timeout=_BENCH_TIMEOUT, cwd=REPO)
        out = p.stdout
    except subprocess.TimeoutExpired as e:
        # Salvage what the child streamed before the outer timeout:
        # bench.py echoes the inner's BENCH_SECTION stream to its own
        # stdout as soon as the inner finishes (its own budget is 3000 s,
        # far inside this timeout), so the green window's sections are in
        # the captured partial stdout even when a later merge tool hung —
        # discarding them is exactly the loss this watch exists to
        # prevent.
        out = e.stdout if isinstance(e.stdout, str) else (
            (e.stdout or b"").decode("utf-8", "replace"))
    return _parse_bench_stdout(out)


def record(line: dict):
    stamp = time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())
    _atomic_dump({"recorded": stamp, "line": line}, LATEST)
    doc = _load_json(MEASURED, {"note": "", "line": {}, "history": []})
    # A degraded line (salvaged partial, or value-0 from a raised train
    # step) never displaces a complete insurance line; it still lands in
    # LATEST and in the history below.  The note's timestamp describes
    # doc["line"], so it only moves when the line does.
    def _degraded(ln):
        return bool(ln.get("partial")) or not ln.get("value")
    if not _degraded(line) or not doc.get("line") or _degraded(doc["line"]):
        doc["line"] = line
        doc["note"] = ("Most recent green TPU run (%s). Recorded because "
                       "the tunneled chip drops intermittently; bench.py "
                       "reproduces this line whenever the chip is "
                       "reachable." % stamp)
    doc.setdefault("history", []).append({
        "recorded": stamp,
        "value": line.get("value"),
        "mfu": line.get("mfu"),
        "onebit_pack_gbps": (line.get("onebit_pallas") or {}).get("pack_gbps"),
        "flash_fwd_speedup": (line.get("flash_attention") or {}).get(
            "fwd_speedup"),
        "engine_device_gbps": next(
            (v for k, v in (line.get("push_pull_gbps") or {}).items()
             if k.startswith("engine_device")
             and not k.endswith("_iqr")), None),
        # round-4 additions: the reworked-engine-on-hardware question and
        # the bf16 composite (VERDICT r3 missing #2 / task 7).
        # engine_host picks the LARGEST plain engine_<N>MB so all three
        # figures (host / device / fused) compare the same workload size.
        "engine_host_gbps": max(
            ((int(k[len("engine_"):-2]), v)
             for k, v in (line.get("push_pull_gbps") or {}).items()
             if k.startswith("engine_") and k.endswith("MB")
             and k[len("engine_"):-2].isdigit()),
            default=(None, None))[1],
        # round-5: drain-mode dispatch amortization — the hardware answer
        # to "is per-chunk dispatch the engine's remaining rent?"
        "engine_grouped_gbps": max(
            ((int(k[len("engine_grouped_"):-2]), v)
             for k, v in (line.get("push_pull_gbps") or {}).items()
             if k.startswith("engine_grouped_") and k.endswith("MB")
             and k[len("engine_grouped_"):-2].isdigit()),
            default=(None, None))[1],
        "fused_gbps": next(
            (v for k, v in (line.get("push_pull_gbps") or {}).items()
             if k.startswith("fused") and not k.endswith("_iqr")), None),
        "bf16_fsdp_tp_decreased": (line.get("bf16_fsdp_tp") or {}).get(
            "decreased"),
        "tpu_overlap_fraction": (line.get("tpu_overlap") or {}).get(
            "overlap_fraction"),
        **({"partial": True, "hung_section": line.get("hung_section")}
           if line.get("partial") else {}),
    })
    _atomic_dump(doc, MEASURED)


def log_probe(result):
    """Append a probe record so the watch itself is auditable evidence.

    Round-3 VERDICT Weak #6: if no green window opens, the probe log (all
    red, with timestamps and total watch duration) documents that the watch
    was running and found nothing — absence of data becomes data.
    """
    doc = _load_json(WATCHLOG, {"started": None, "probes": []})
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    if not doc.get("started"):
        doc["started"] = stamp
    doc["last"] = stamp
    green = (isinstance(result, dict)
             and result.get("platform") != "cpu")
    probes = doc.setdefault("probes", [])
    probes.append({"t": stamp, "result": result})
    # Running counters + a capped tail: a multi-day watch stays O(1) per
    # probe while the totals still prove how long it ran and what it saw.
    doc["n_probes"] = doc.get("n_probes", 0) + 1
    doc["n_green"] = doc.get("n_green", 0) + (1 if green else 0)
    if len(probes) > 500:
        del probes[:len(probes) - 500]
        doc["probes_truncated_to_last"] = 500
    _atomic_dump(doc, WATCHLOG)


def main():
    greens = 0
    last_bench = 0.0
    while True:
        info = probe()
        log_probe(info if info else "red")
        now = time.strftime("%H:%M:%S")
        if info and info["platform"] not in ("cpu",):
            if greens > 0 and time.time() - last_bench < 6 * 3600:
                # A complete green bench is recent: keep the probe log
                # fresh without holding the chip — a watch-held chip at
                # round end would starve the driver's own capture (the
                # one that lands in BENCH_r{N}).  Re-bench on a 6 h
                # cadence so the MEASURED history still shows drift over
                # a multi-day watch.
                print(f"[{now}] probe green (bench recorded "
                      f"{(time.time() - last_bench) / 3600:.1f}h ago)",
                      flush=True)
                time.sleep(3600)
                continue
            print(f"[{now}] probe green: {info}; running bench", flush=True)
            last_bench = time.time()
            line = run_bench()
            if line and str(line.get("device", "")).lower().startswith(
                    ("tpu", "v5", "v6", "v4")):
                record(line)
                if line.get("partial") or not line.get("value"):
                    # Salvaged/degraded sections are worth recording, but
                    # only a complete run with a real headline number
                    # relaxes the probing cadence.
                    print(f"[{now}] degraded TPU bench recorded "
                          f"(partial={line.get('partial')}, "
                          f"hung={line.get('hung_section')})", flush=True)
                else:
                    greens += 1
                    print(f"[{now}] green TPU bench #{greens}: "
                          f"value={line.get('value')} mfu={line.get('mfu')}",
                          flush=True)
            else:
                print(f"[{now}] bench ran but no TPU line: "
                      f"{str(line)[:200]}", flush=True)
        else:
            print(f"[{now}] probe: chip unreachable", flush=True)
        # Dense probing until the first complete green run (a red probe
        # already burns its 90 s timeout, so 120 s sleep ≈ 3.5 min cadence
        # — short green windows are the whole reason this watch exists),
        # then hourly freshness.
        time.sleep(120 if greens == 0 else 3600)


if __name__ == "__main__":
    main()
