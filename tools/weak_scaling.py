"""Weak-scaling evidence toward the v5e-256 north star (round-2 VERDICT
item 3).

Two parts, both runnable without TPU hardware:

1. **Measured weak scaling** over 1/2/4 real processes x 2 CPU devices
   each (the rendezvous pattern of tests/test_multiprocess.py): every
   process contributes a fixed-size gradient per step through the engine's
   hierarchical push_pull path — per-process work constant, total work
   grows with the process count.  Reported as median step time per
   process count and the 4-process weak-scaling efficiency t1/t4.
   (CPU "DCN" here is loopback shared memory; the point is that the
   *collective structure* — dcn=n_proc hierarchical RS/psum/AG — executes
   and how its cost grows, not absolute GB/s.)

2. **Analytic projection** for BERT-large DP on a v5e-256 pod from
   published hardware numbers and the framework's own measured single-chip
   step time (BENCH_TPU_MEASURED.json).  The wire-byte formula
   (ring all-reduce moves 2*M*(N-1)/N bytes per chip) is validated
   against the compiled HLO on the CPU mesh (utils/hlo_wire.py), then
   evaluated at N=256.  Assumptions are in the output — this is a model,
   not a measurement, and is labeled as such.

Usage:  python tools/weak_scaling.py            # orchestrate + print JSON
        python tools/weak_scaling.py --worker   # (internal) worker body
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GRAD_BYTES = 4 * 1024 * 1024   # per-process contribution per step (f32)
STEPS = 8


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ----------------------------------------------------------------- worker

def worker() -> int:
    # Pin BEFORE jax initializes so XLA's thread pool inherits the mask
    # (round-3 VERDICT task 5: unpinned workers timeslice one another and
    # the curve measures the OS scheduler, not the collective).
    spec = os.environ.get("BYTEPS_WS_PIN")
    if spec:
        os.sched_setaffinity(0, {int(c) for c in spec.split(",")})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import byteps_tpu.core.api as api

    api.init()
    eng = api._require()
    x = np.random.RandomState(0).randn(GRAD_BYTES // 4).astype(np.float32)
    eng.push_pull_local(x, "ws.grad")          # warmup + compile
    times = []
    for _ in range(STEPS):
        t0 = time.perf_counter()
        eng.push_pull_local(x, "ws.grad")
        times.append(time.perf_counter() - t0)
    api.shutdown()
    from tools._bench_util import quantile_stats
    med, iqr = quantile_stats(times)
    print("WS_RESULT " + json.dumps({
        "pid": jax.process_index(),
        "median_ms": med,
        "iqr_ms": iqr,
    }))
    return 0


# ------------------------------------------------------------ orchestrate

def _core_slices(n_proc: int, cores_per_proc: int = 0):
    """Disjoint core sets for n_proc workers, or None when the host can't
    provide at least one dedicated core per worker.

    ``cores_per_proc`` pins EVERY group size to the same per-worker core
    budget (the max group's share): without the cap, the 1-process
    baseline would get all host cores while the 4-process group gets a
    quarter each, and the efficiency ratio would measure thread-pool
    width, not collective growth."""
    try:
        avail = sorted(os.sched_getaffinity(0))
    except AttributeError:
        return None
    if len(avail) < n_proc:
        return None
    per = cores_per_proc or max(1, len(avail) // n_proc)
    if per * n_proc > len(avail):
        return None
    return [avail[i * per:(i + 1) * per] for i in range(n_proc)]


def run_group(n_proc: int, timeout: float = 420.0, pin: bool = False,
              cores_per_proc: int = 0):
    """Spawn n_proc workers x 2 CPU devices; return median step ms.
    ``pin=True`` gives each worker a disjoint core slice of
    ``cores_per_proc`` cores."""
    slices = _core_slices(n_proc, cores_per_proc) if pin else None
    if pin and slices is None:
        raise RuntimeError("not enough cores to pin")
    port = _free_port()
    procs = []
    for pid in range(n_proc):
        env = dict(os.environ)
        if slices is not None:
            env["BYTEPS_WS_PIN"] = ",".join(map(str, slices[pid]))
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(n_proc),
            "DMLC_WORKER_ID": str(pid),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "BYTEPS_LOG_LEVEL": "WARNING",
            "BYTEPS_TELEMETRY_ON": "0",
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError(
                    f"weak-scaling worker rc={p.returncode}: {out[-800:]}")
            for line in out.splitlines():
                if line.startswith("WS_RESULT "):
                    results.append(json.loads(line.split(" ", 1)[1]))
    except BaseException as e:
        # a dead worker must take its siblings down with it: survivors
        # blocked in the DMLC rendezvous would otherwise orphan, holding
        # cores and polluting every later group's timings
        for p in procs:
            if p.poll() is None:
                p.kill()
        if isinstance(e, subprocess.TimeoutExpired):
            raise RuntimeError(
                f"weak-scaling group n={n_proc} timed out") from e
        raise
    # slowest process bounds the step; its IQR is the reported spread
    slow = max(results, key=lambda r: r["median_ms"])
    return slow["median_ms"], slow.get("iqr_ms")


def _curve(counts, pin: bool, cores_per_proc: int = 0):
    out = {}
    for n in counts:
        med, iqr = run_group(n, pin=pin, cores_per_proc=cores_per_proc)
        out[f"{n}proc_ms"] = round(med, 2)
        if iqr:
            out[f"{n}proc_iqr_ms"] = [round(q, 2) for q in iqr]
    base = out[f"{counts[0]}proc_ms"]
    last = out[f"{counts[-1]}proc_ms"]
    out[f"efficiency_{counts[-1]}proc"] = round(base / last, 3)
    return out


def measure_weak_scaling(counts=(1, 2, 4)):
    """Contended + (when the host allows) core-pinned weak-scaling curves.

    Round-3 VERDICT Weak #3: the contended curve on a shared box measures
    timeslicing, not collective structure.  With each worker pinned to a
    disjoint core slice the curve measures how the dcn=N hierarchical
    RS/psum/AG actually grows; both curves are reported side by side so
    the reader sees what the environment allowed."""
    out = {"contended": _curve(counts, pin=False)}
    ncores = len(os.sched_getaffinity(0)) if hasattr(
        os, "sched_getaffinity") else (os.cpu_count() or 1)
    per = ncores // counts[-1]
    if per >= 1 and _core_slices(counts[-1], per) is not None:
        # every group size gets the SAME cores/worker (the max group's
        # share), so the curve isolates collective growth
        out["pinned"] = _curve(counts, pin=True, cores_per_proc=per)
        out["pinned"]["cores_per_proc"] = per
    else:
        out["pinned"] = {"skipped": (
            f"host has {ncores} core(s); need >= {counts[-1]} for "
            "disjoint per-worker pinning")}
    out["note"] = (f"{GRAD_BYTES >> 20} MB/process hierarchical push_pull, "
                   "2 CPU devices/process, loopback gRPC DCN; the "
                   "contended curve shares all cores (timeslicing "
                   "dominates), the pinned curve gives each worker its own "
                   "cores and isolates the collective structure's growth")
    return out


def measure_dcn_sweep():
    """Contention-free structure scaling: ONE process, 8 CPU devices,
    hierarchical push_pull with dcn = 1/2/4 slices (fixed total bytes).
    Isolates the cost of the two-level RS -> DCN-psum -> AG structure as
    the slice count grows — the shape that rides real DCN on a pod."""
    import subprocess as sp
    code = r"""
import json, time, os
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from byteps_tpu.comm.mesh import CommContext, _build_mesh
from byteps_tpu.comm.collectives import hierarchical_all_reduce

nbytes = 4 * 1024 * 1024
# set up all three configs first, then interleave reps across them so
# load drift on a shared host hits every dcn count equally
cfgs = {}
for n_dcn in (1, 2, 4):
    comm = CommContext(mesh=_build_mesh(jax.devices()[:8], n_dcn),
                       n_dcn=n_dcn, n_ici=8 // n_dcn)
    x = jax.device_put(jnp.zeros((8, nbytes // 4), jnp.float32),
                       comm.stacked_sharding(extra_dims=1))
    hierarchical_all_reduce(comm, x).block_until_ready()  # compile
    cfgs[n_dcn] = (comm, x)
times = {n: [] for n in cfgs}
for _ in range(8):
    for n_dcn, (comm, x) in cfgs.items():
        t0 = time.perf_counter()
        hierarchical_all_reduce(comm, x).block_until_ready()
        times[n_dcn].append(time.perf_counter() - t0)
res = {f"dcn{n}_ms": round(sorted(ts)[4] * 1e3, 2)
       for n, ts in times.items()}
print("SWEEP " + json.dumps(res))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    p = sp.run([sys.executable, "-c", code], env=env, cwd=REPO,
               capture_output=True, text=True, timeout=420)
    for line in p.stdout.splitlines():
        if line.startswith("SWEEP "):
            return json.loads(line.split(" ", 1)[1])
    raise RuntimeError(f"dcn sweep failed: {(p.stderr or '')[-400:]}")


# ---------------------------------------------------------- analytic model

# Public v5e numbers (Google Cloud TPU v5e spec; scaling-book tables):
#   - bf16 peak 197 TFLOP/s per chip
#   - interchip interconnect 1600 Gbps aggregate per chip (4x400 2D torus)
# Effective all-reduce bandwidth assumption: bidirectional ring over the
# torus uses the aggregate links; we model EFFECTIVE = 100 GB/s per chip
# (half the 200 GB/s aggregate, a deliberately conservative derate for
# protocol/latency overhead).
V5E_EFFECTIVE_ALLREDUCE_BPS = 100e9

# BERT-large (the reference's headline workload, README.md:35-41):
BERT_LARGE_PARAMS = 336_226_108  # measured from models/bert.py bert_large


def validate_wire_formula():
    """Compile the fused DP gradient reduction on the 8-device CPU mesh
    and confirm the program issues exactly ONE full-gradient-sized
    all-reduce (no duplicated collectives): the projection then converts
    that all-reduce to wire bytes with the standard ring identity
    2*M*(N-1)/N.  Returns (grad_bytes, hlo_allreduce_bytes)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as np
    from byteps_tpu.utils.hlo_wire import collectives

    devs = np.array(jax.devices()[:8])
    if devs.size < 8:
        raise RuntimeError("needs 8 CPU devices (XLA_FLAGS set too late)")
    mesh = Mesh(devs.reshape(1, 8), ("dcn", "ici"))
    n = 1 << 18  # 1 MB of f32 per rank

    def body(x):
        return jax.lax.psum(x[0], ("dcn", "ici"))

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(("dcn", "ici")),
                              out_specs=P()))
    hlo = f.lower(jnp.zeros((8, n), jnp.float32)).compile().as_text()
    ar_bytes = sum(nbytes for op, nbytes, _ in collectives(hlo)
                   if op == "all-reduce")
    return n * 4, ar_bytes


def _measured_throughput():
    """Per-chip examples/s from the latest green TPU run — read from
    BENCH_TPU_MEASURED.json so the projection tracks the hardware record
    instead of going stale; conservative fallback if absent."""
    path = os.path.join(REPO, "BENCH_TPU_MEASURED.json")
    try:
        with open(path) as f:
            line = json.load(f)["line"]
        v = float(line["value"])
        batch = 32  # bench.py per_dev_batch on TPU
        if v > 0:
            return v, batch
    except Exception:  # noqa: BLE001 - fall through to the recorded value
        pass
    return 526.41, 32


def analytic_v5e256(measured_step_ms=None, dtype_bytes=2):
    """Project BERT-large DP scaling efficiency at v5e-256.

    efficiency = compute / (compute + exposed_comm); bounds given for
    zero overlap (all comm exposed) and full overlap (comm hidden behind
    the backward pass, the reference's priority-scheduling claim)."""
    if measured_step_ms is None:
        ex_per_s, batch = _measured_throughput()
        measured_step_ms = batch / ex_per_s * 1e3
    grad_bytes = BERT_LARGE_PARAMS * dtype_bytes
    n = 256
    wire = 2 * grad_bytes * (n - 1) / n
    comm_ms = wire / V5E_EFFECTIVE_ALLREDUCE_BPS * 1e3
    eff_none = measured_step_ms / (measured_step_ms + comm_ms)
    out = {
        "model": "bert_large mixed-precision DP, one v5e-256 pod (all ICI)",
        "grad_bytes": grad_bytes,
        "assumed_allreduce_bps": V5E_EFFECTIVE_ALLREDUCE_BPS,
        "measured_step_ms_per_chip": round(measured_step_ms, 2),
        "allreduce_ms": round(comm_ms, 2),
        "efficiency_no_overlap": round(eff_none, 3),
        "efficiency_full_overlap": 1.0,
        "target": "reference: ~90% at 256 GPUs (README.md:35-41)",
        "zero1_note": ("ZeRO-1 wire bytes identical (RS+AG is the "
                       "all-reduce decomposition); HSDP adds a DCN psum "
                       "of the 1/n_ici shard only on multi-pod DCN "
                       "deployments"),
    }
    try:
        formula, hlo = validate_wire_formula()
        out["wire_formula_check"] = {
            "formula_bytes_per_rank": formula, "hlo_bytes_per_rank": hlo,
            "match": bool(abs(formula - hlo) <= 0.25 * formula)}
    except Exception as e:  # noqa: BLE001 - validation is best-effort
        out["wire_formula_check"] = {"error": str(e)[:200]}
    return out


def main() -> int:
    if "--worker" in sys.argv:
        return worker()
    result = {"weak_scaling": measure_weak_scaling(),
              "dcn_sweep": measure_dcn_sweep(),
              "analytic_v5e256": analytic_v5e256()}
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
