"""bps_top: live terminal view of a running byteps_tpu cluster.

Polls the membership bus's ``metrics`` verb (one round-trip returns
every live rank's latest snapshot — ``core/api.py:cluster_metrics()``)
and renders a per-rank table: push_pull GB/s, scheduler queue depth,
sync-stall %, retransmits, and the membership epoch — the "what is the
cluster doing RIGHT NOW" companion to the flight recorder's "what was
it doing when it died".  Works against anything from a 3-process chaos
run to a single local engine (no bus → a local-only view).

Usage:
    python tools/bps_top.py [--bus HOST:PORT] [--interval SEC]
                            [--once] [--json]

    --bus       membership bus address (default: DMLC_PS_ROOT_URI +
                BYTEPS_MEMBERSHIP_PORT, the ElasticMembership default)
    --interval  refresh period, seconds (default 2)
    --once      print one frame and exit (scripting / tests)
    --json      print raw cluster_metrics() JSON instead of the table
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_COLUMNS = ("RANK", "ROLE", "GB/s", "QDEPTH", "INFLIGHT", "STALL%",
            "ATTRIB", "RETX", "PULLS", "SHED%", "ARC", "CONN", "WAL",
            "CODEC", "TREND", "SLOW", "STATE", "EPOCH", "STEP", "AGE")


def _wal_cell(gauges: dict) -> str:
    """Durable-plane replay lag (server/wal.py): the on-disk journal
    bytes a cold start of this rank would replay, from the
    ``wal.lag_bytes`` gauge each checkpoint cycle refreshes.  '-' =
    durability off on this rank; a value climbing across refreshes
    means cuts have stopped landing (full disk, wedged cut thread) and
    the cold-start story is silently getting worse."""
    lag = gauges.get("wal.lag_bytes")
    if lag is None:
        return "-"
    if lag >= 1 << 20:
        return "%.1fM" % (lag / (1 << 20))
    if lag >= 1 << 10:
        return "%.1fK" % (lag / (1 << 10))
    return str(int(lag))


def _conn_cell(gauges: dict) -> str:
    """The rank's transport-connection health as ``ready/total`` from
    the ``transport.connections*`` gauges (comm/transport.py).  '-' =
    the rank runs no TCP transport (loopback-only world); a ready count
    below the total is the operator's cue that a peer is partitioned or
    mid-reconnect."""
    total = gauges.get("transport.connections")
    if not total:
        return "-"
    ready = int(gauges.get("transport.connections_ready") or 0)
    return f"{ready}/{int(total)}"


def _attrib_cell(step: dict) -> str:
    """The last step's DOMINANT attribution component as 'comp:NN%'
    (share of step wall time) — the one-glance answer to "what is this
    rank's step time going to".  '-' = no attribution yet (engine idle,
    telemetry off, or a pre-attribution snapshot); 'other' only shows
    when nothing measured dominates."""
    at = step.get("attrib") or {}
    wall = step.get("wall_ms") or 0.0
    if not at or not wall:
        return "-"
    comps = {k: v for k, v in at.items() if k != "other" and v > 0}
    if not comps:
        comps = {k: v for k, v in at.items() if v > 0}
    if not comps:
        return "-"
    k = max(comps, key=comps.get)
    return f"{k}:{min(999, round(100.0 * comps[k] / wall))}%"


def _codec_cell(gauges: dict) -> str:
    """The rank's active compression codecs, from the labeled
    ``compression.codec_locked{bucket=..,codec=..}`` (planner-ladder
    locks) and ``compression.active{tensor=..,codec=..}`` (explicitly
    configured tensors) gauges.  '-' = nothing compressed on this rank;
    multiple distinct codecs join with ','."""
    import re
    codecs = set()
    for series, value in gauges.items():
        if not value:
            continue       # a zeroed series is a RETIRED codec
        if series.startswith(("compression.codec_locked{",
                              "compression.active{")):
            m = re.search(r'codec="([^"]*)"', series)
            if m:
                codecs.add(m.group(1))
    return ",".join(sorted(codecs)) if codecs else "-"


def _shed_cell(counters: dict) -> str:
    """Shed share of this endpoint's pull traffic (``serve.shed`` /
    total answered), the admission-control health figure: 0% = nothing
    degraded, climbing = the host is trading freshness for survival
    under a storm (docs/serving.md)."""
    shed = counters.get("serve.shed", 0)
    pulls = counters.get("serve.pulls", 0) + shed
    if not pulls:
        return "-"
    return f"{100.0 * shed / pulls:.0f}%"


def _trend_cell(hist: dict) -> str:
    """The rank's throughput trend as a sparkline over its piggybacked
    time-series window (``common/timeseries.py`` summary ``spark``
    tail): mbps preferred, overlap fraction as the fallback on a rank
    that moves no wire bytes.  '-' = no history posted yet."""
    series = ((hist or {}).get("summary") or {}).get("series") or {}
    st = series.get("mbps") or series.get("overlap") or {}
    vals = st.get("spark") or []
    if not vals:
        return "-"
    try:                                  # importable both as a script
        from bps_doctor import sparkline  # (tools/ on path) and as the
    except ImportError:                   # tools.bps_top module
        from tools.bps_doctor import sparkline
    return sparkline(vals)


def _alert_rules(entry: dict) -> list:
    """Firing health-rule ids from a rank's snapshot gauges (the
    ``health.alerts_active{rule=}`` family; value 1 = firing)."""
    import re
    gauges = (entry.get("metrics") or {}).get("gauges") or {}
    out = []
    for series, v in gauges.items():
        m = re.match(r'^health\.alerts_active\{rule="([^"]+)"\}$', series)
        if m and v:
            out.append(m.group(1))
    return sorted(out)


def _rank_row(rank: int, entry: dict, slow=None, probation=(),
              role: str = "trainer", arc: float = None,
              label: str = None, hist: dict = None,
              gstate: str = None) -> tuple:
    """One table row from a rank's cached snapshot (missing fields render
    as '-': a rank mid-transition posts partial snapshots).  ``slow`` is
    the bus's per-rank step-barrier phi score, ``probation`` the demoted
    set — together they make a demotion watchable live: the score climbs,
    STATE flips to PROBATION, and the rank leaves the world until it
    recovers and rejoins (docs/gray_failures.md).  ``role`` / ``arc``
    render the serving tier's rows (ROLE=serve, ring-arc share)."""
    m = entry.get("metrics") or {}
    gauges = m.get("gauges") or {}
    counters = m.get("counters") or {}
    step = m.get("step") or {}

    def fmt(v, spec="{}"):
        return "-" if v is None else spec.format(v)

    mbps = m.get("speed_mbps")   # MiB/s (SpeedMonitor's 2**20 unit)
    stall = None
    if step.get("wall_ms"):
        stall = 100.0 * min(1.0, (step.get("sync_stall_ms") or 0.0)
                            / step["wall_ms"])
    return (
        label if label is not None else str(rank),
        role,
        # decimal GB/s, the same unit the bench tools' *_gbps report —
        # an operator comparing a row against the bench floor must not
        # eat a silent 7.4% MiB/GiB discrepancy
        fmt(None if mbps is None else mbps * 2**20 / 1e9, "{:.3f}"),
        fmt(m.get("sched_pending",
                  gauges.get("engine.sched_pending"))),
        fmt(m.get("bytes_in_flight")),
        fmt(stall, "{:.0f}"),
        # causal attribution (ISSUE 12): where the last step's wall time
        # went, from the step.attrib_* breakdown riding the snapshot
        _attrib_cell(step),
        fmt(counters.get("integrity.retransmit", 0)),
        # serving plane (server/serving.py): cumulative pulls served by
        # this rank — 0 everywhere means the rank runs no read plane
        fmt(counters.get("serve.pulls", 0)),
        # serving tier (server/serving_tier.py): shed share of answered
        # pulls, and this host's consistent-hash ring arc
        _shed_cell(counters),
        fmt(None if arc is None else 100.0 * arc, "{:.0f}%"),
        # transport (comm/transport.py): ready/total peer connections
        _conn_cell(gauges),
        # durable state plane (server/wal.py): cold-start replay lag
        _wal_cell(gauges),
        # compression (ISSUE 11): which codec(s) this rank's pushes ride
        _codec_cell(gauges),
        # history (ISSUE 16): throughput sparkline over the rank's
        # piggybacked time-series window
        _trend_cell(hist),
        # gray-failure columns: the coordinator's phi suspicion of this
        # rank's step-barrier lag, and whether it is demoted right now
        fmt(slow, "{:.1f}"),
        # STATE: probation wins; else the gossip membership verdict
        # (alive/suspect/dead/parked, fault/gossip.py) when the SWIM
        # plane is on; plain "ok" otherwise
        ("PROBATION" if rank in probation
         else (gstate if gstate and gstate != "alive" else "ok")),
        fmt(m.get("epoch")),
        fmt(step.get("step")),
        fmt(entry.get("age_s"), "{:.1f}s"),
    )


def render(cluster: dict) -> str:
    """The table for one cluster_metrics() reply (pure; unit-tested)."""
    slow = cluster.get("slow") or {}
    probation = set(cluster.get("probation") or ())
    history = cluster.get("history") or {}
    # gossip membership states (ISSUE 17): {rank: {"inc","state","hb"}}
    # from the local SWIM table — suspect/dead/parked rows stay visible
    # even when their metrics payloads have gone stale
    gstates = {int(r): (e or {}).get("state")
               for r, e in (cluster.get("states") or {}).items()}
    rows = [_COLUMNS]
    ranks = cluster.get("ranks", {})
    coordinator = cluster.get("coordinator")
    # demoted ranks leave the world (and the metrics cache) but stay
    # VISIBLE: a probation row with '-' metrics is the operator's cue
    # that the rank is parked, not vanished
    for rank in sorted(set(ranks) | probation | set(gstates)):
        rows.append(_rank_row(
            rank, ranks.get(rank, {}), slow=slow.get(rank),
            probation=probation,
            role="coordinator" if rank == coordinator else "trainer",
            hist=history.get(rank), gstate=gstates.get(rank)))
    # serving-tier rows (server/serving_tier.py): every host in the
    # bus's serving directory is a first-class row — id prefixed 's',
    # ROLE=serve, ring-arc share from the same ring math every client
    # routes by, shed rate from the host's published counters
    serve_hosts = cluster.get("serve_hosts") or {}
    serve_ranks = cluster.get("serve_ranks") or {}
    if serve_hosts:
        try:
            from byteps_tpu.server.serve_ring import ServeRing
            shares = ServeRing(serve_hosts).arc_share()
        except Exception:  # noqa: BLE001 — render must not die on a
            # directory/ring mismatch mid-transition
            shares = {}
        draining = {int(h) for h in cluster.get("serve_draining") or ()}
        for hid in sorted(serve_hosts):
            rows.append(_rank_row(
                hid, serve_ranks.get(hid, {}), role="serve",
                arc=shares.get(hid), label=f"s{hid}",
                # DRAINING rides the gossip-state slot: same STATE cell,
                # same "anything but alive wins over ok" rule
                gstate="DRAINING" if hid in draining else None))
    widths = [max(len(r[i]) for r in rows) for i in range(len(_COLUMNS))]
    head = "byteps_tpu cluster — epoch %s, world %s" % (
        cluster.get("epoch"), cluster.get("world"))
    if cluster.get("coordinator") is not None:
        # who hosts the control plane, and who takes over if it dies
        head += " — coordinator=%s standby=%s" % (
            cluster.get("coordinator"), cluster.get("standby"))
    if serve_hosts:
        head += " — serve tier: %d host(s), gen %s" % (
            len(serve_hosts), cluster.get("serve_gen"))
        # the fleet banner (ISSUE 18): target vs actual is THE
        # reconciler-health signal — actual counts only non-draining
        # hosts, so a lagging drain shows as actual > target
        draining = {int(h) for h in cluster.get("serve_draining") or ()}
        if cluster.get("serve_target") is not None or draining:
            target = cluster.get("serve_target")
            head += " — fleet: target=%s actual=%d" % (
                "-" if target is None else target,
                len(set(serve_hosts) - draining))
            if draining:
                head += " draining=%s" % sorted(draining)
    if probation:
        head += " — probation=%s" % sorted(probation)
    if cluster.get("gossip"):
        head += " — gossip view (no bus round-trip)"
    if cluster.get("failover_in_progress"):
        head += (" (COORDINATOR FAILOVER IN PROGRESS — bus not "
                 "answering, local-only view)")
    elif cluster.get("local_only"):
        head += " (local-only view: no membership bus)"
    lines = [head]
    # health banner (ISSUE 16): every firing SLO rule, named per rank,
    # from the health.alerts_active{rule=} gauges riding the snapshots —
    # the same source a --once --json consumer reads, so the banner and
    # the JSON never disagree
    firing = {rank: _alert_rules(entry)
              for rank, entry in sorted(ranks.items())}
    firing = {r: rules for r, rules in firing.items() if rules}
    if firing:
        lines.append("ALERTS: " + "; ".join(
            "rank %s: %s" % (r, ",".join(rules))
            for r, rules in firing.items()))
    lines.append("  ".join(c.rjust(w) for c, w in zip(rows[0], widths)))
    for row in rows[1:]:
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
    missing = sorted(set(cluster.get("world", []))
                     - set(cluster.get("ranks", {})))
    if missing:
        lines.append(f"(no snapshot yet from rank(s) {missing} — they "
                     "report on their next step_sync)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--bus", default=None, help="membership bus host:port")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from byteps_tpu.core.api import cluster_metrics

    while True:
        try:
            cluster = cluster_metrics(bus=args.bus)
        except Exception as e:  # noqa: BLE001 — a dead bus mid-watch
            print(f"bps_top: cluster_metrics failed: {e}", file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        if args.json:
            print(json.dumps(cluster, default=str))
        else:
            if not args.once:
                # clear + home, like top (plain ANSI, no curses dep)
                sys.stdout.write("\x1b[2J\x1b[H")
            print(render(cluster), flush=True)
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
