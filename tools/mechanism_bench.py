"""Prove (or retire) the signature BytePS mechanisms on TPU (round-2
VERDICT item 4).

The round-2 measurements showed priority, partitioning and credit are
throughput-neutral-to-negative on bulk GB/s — but bulk GB/s is not what
they are for.  In the reference they exist to cut the LATENCY of the
gradients the next forward pass needs first (priority scheduling +
cross-barrier, reference docs/best-practice.md:7; partitioning bounds
head-of-line blocking, operations.cc:140-180).  This harness measures
exactly that:

- **priority**: the backward pass produces gradients last-layer-first;
  the next forward needs first-layer gradients first.  Enqueue K tensors
  in reverse declaration order and time how long the FIRST-declared
  (highest-priority) tensor takes to complete, priority on vs off.
- **partitioning**: enqueue one big low-priority tensor, then a small
  urgent one; partitioning lets the small tensor preempt at chunk
  granularity instead of waiting out the whole transfer.

Prints one JSON object; bench.py embeds it as the "mechanisms" section.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools._bench_util import (conditions_block, pin_cores,  # noqa: E402
                               quantile_stats, setup_cpu8_mesh)


def _setup():
    setup_cpu8_mesh()
    import jax
    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    devices = jax.devices()
    n = len(devices)
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)
    return comm, n


def priority_latency(comm, n, k_tensors=6, mbytes=4, reps=15):
    """Median time-to-ready of the first-declared tensor when all K are
    enqueued in reverse order (backward-pass production order).

    The credit window is load-bearing here: JAX async dispatch returns
    immediately, so with an unlimited window every chunk is dispatched the
    moment it is enqueued and the priority queue never holds anything to
    reorder.  A bytes-in-flight budget (the reference's
    BYTEPS_SCHEDULING_CREDIT) makes dispatch wait for completions — the
    queue builds depth, and priority picks what goes next.  This is the
    composition the mechanisms were designed as: credit creates the
    decision point, priority decides, partitioning sets the granularity.
    """
    import numpy as np
    from byteps_tpu.common.config import Config
    from byteps_tpu.core.engine import PushPullEngine

    credit = 2 * mbytes * (1 << 20)   # ~2 tensors in flight
    xs = [np.random.RandomState(i).randn(
        mbytes * (1 << 20) // 4).astype(np.float32)
        for i in range(k_tensors)]
    # Both engines up front, reps INTERLEAVED across them: slow load
    # drift on a shared host then hits priority and fifo equally instead
    # of whichever ran last (the round-3 artifact's failure mode).
    engines = {}
    lats = {}
    out = {}
    try:
        for tag, prio in (("priority", True), ("fifo", False)):
            cfg = Config(telemetry_on=False, trace_on=False,
                         enable_priority=prio, scheduling_credit=credit)
            engines[tag] = (PushPullEngine(comm, cfg), prio)
            lats[tag] = []
        for tag, (eng, _) in engines.items():
            # declare in forward order so declared_key (priority) is set
            for i in range(k_tensors):
                eng.push_pull_local(xs[i], f"layer{i}")  # init + warmup
        for _ in range(reps):
            for tag, (eng, prio) in engines.items():
                handles = {}
                # enqueue in REVERSE (backward produces last layer first).
                # The fifo baseline pins priority to arrival order — what
                # a plain allreduce queue (Horovod/NCCL production order)
                # executes; with enable_priority the engine's default
                # -declared_key ordering takes over.  (Config alone can't
                # express arrival order: the scheduler tie-breaks equal
                # priorities by key, which IS declaration order.)
                for pos, i in enumerate(reversed(range(k_tensors))):
                    handles[i] = eng.push_pull_local_async(
                        xs[i], f"layer{i}",
                        **({} if prio else {"priority": -pos}))
                t0 = time.perf_counter()
                handles[0].wait()           # the next forward's first need
                lats[tag].append(time.perf_counter() - t0)
                for h in handles.values():
                    h.wait()
        for tag in engines:
            med, iqr = quantile_stats(lats[tag])
            out[f"layer0_ready_ms_{tag}"] = med
            out[f"layer0_ready_{tag}_iqr_ms"] = iqr
    finally:
        for eng, _ in engines.values():
            eng.shutdown(wait=False)
    out["speedup"] = round(out["layer0_ready_ms_fifo"]
                           / max(out["layer0_ready_ms_priority"], 1e-9), 2)
    # pessimistic/optimistic bracket from the quartiles: the claimable
    # range under load, not just the point estimate
    out["speedup_range"] = [
        round(out["layer0_ready_fifo_iqr_ms"][0]
              / max(out["layer0_ready_priority_iqr_ms"][1], 1e-9), 2),
        round(out["layer0_ready_fifo_iqr_ms"][1]
              / max(out["layer0_ready_priority_iqr_ms"][0], 1e-9), 2)]
    return out


def partition_latency(comm, n, big_mb=64, small_kb=256, reps=15):
    """Median time-to-ready of a small urgent tensor enqueued right after
    a big low-priority one, with and without partitioning."""
    import numpy as np
    from byteps_tpu.common.config import Config
    from byteps_tpu.core.engine import PushPullEngine

    big = np.random.RandomState(0).randn(
        big_mb * (1 << 20) // 4).astype(np.float32)
    small = np.random.RandomState(1).randn(
        small_kb * 1024 // 4).astype(np.float32)
    engines = {}
    lats = {}
    out = {}
    try:
        for tag, pbytes in (("partitioned", 4096 * 1000),
                            ("whole", 2**31 - 512)):
            cfg = Config(telemetry_on=False, trace_on=False,
                         partition_bytes=pbytes,
                         scheduling_credit=8 * (1 << 20))
            engines[tag] = PushPullEngine(comm, cfg)
            lats[tag] = []
        for eng in engines.values():
            eng.push_pull_local(small, "urgent", priority=10)
            eng.push_pull_local(big, "bulk", priority=-10)
        # reps interleaved across configs so drift cancels (see
        # priority_latency)
        for _ in range(reps):
            for tag, eng in engines.items():
                hb = eng.push_pull_local_async(big, "bulk", priority=-10)
                hs = eng.push_pull_local_async(small, "urgent", priority=10)
                t0 = time.perf_counter()
                hs.wait()
                lats[tag].append(time.perf_counter() - t0)
                hb.wait()
        for tag in engines:
            med, iqr = quantile_stats(lats[tag])
            out[f"urgent_ready_ms_{tag}"] = med
            out[f"urgent_ready_{tag}_iqr_ms"] = iqr
    finally:
        for eng in engines.values():
            eng.shutdown(wait=False)
    out["speedup"] = round(out["urgent_ready_ms_whole"]
                           / max(out["urgent_ready_ms_partitioned"], 1e-9),
                           2)
    out["speedup_range"] = [
        round(out["urgent_ready_whole_iqr_ms"][0]
              / max(out["urgent_ready_partitioned_iqr_ms"][1], 1e-9), 2),
        round(out["urgent_ready_whole_iqr_ms"][1]
              / max(out["urgent_ready_partitioned_iqr_ms"][0], 1e-9), 2)]
    return out


def main() -> int:
    pinned = pin_cores()
    comm, n = _setup()
    result = {"priority": priority_latency(comm, n),
              "partitioning": partition_latency(comm, n),
              "conditions": conditions_block(
                  pinned,
                  note=("wall-clock latencies on a shared host; the "
                        "deterministic dispatch-order claims are pinned "
                        "load-independently by "
                        "tests/test_mechanism_order.py"))}
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
