"""bps_doctor: incident interrogation + postmortem for a byteps_tpu cluster.

Two modes, one report shape (markdown by default, ``--json`` for
scripting):

**Live** (default): one ``cluster_metrics()`` round-trip over the
membership bus answers "is anything wrong RIGHT NOW, and who": the
firing health rules per rank (from each snapshot's
``health.alerts_active{rule=}`` gauges), the coordinator's slowness phi
scores and probation list, cross-rank attribution skew (the SAME pure
function the SLO engine runs — ``common/health.py:
attrib_skew_findings`` — so the doctor and the pager name the same
culprit), each rank's dominant attribution component, and trend
sparklines drawn from the piggybacked time-series window summaries
(``common/timeseries.py``).  The verdict names ONE culprit rank with
its evidence.

**Postmortem** (``--postmortem DIR``): correlates what a dead or sick
run left behind in one directory — flight-recorder dumps
(``bps_flight_*.json``: the ``alert`` events the health engine recorded
and the ``fault.*`` events the injector recorded), saved ``/timeseries``
windows (``bps_timeseries_*.json``), and a merged trace
(``bps_trace_merged.json``, from ``tools/bps_trace.py``) — into one
report that names WHAT degraded first (the earliest firing alert),
WHICH rank, and at WHICH injection/code site.

Usage:
    python tools/bps_doctor.py [--bus HOST:PORT] [--json]
    python tools/bps_doctor.py --postmortem DIR [--json] [--out PATH]

    --bus         membership bus address (default: DMLC_PS_ROOT_URI +
                  BYTEPS_MEMBERSHIP_PORT, the ElasticMembership default)
    --postmortem  directory of flight dumps / timeseries dumps / merged
                  trace to correlate instead of asking a live bus
    --skew-ratio  cross-rank attribution skew threshold (default 4.0,
                  the BYTEPS_HEALTH_SKEW_RATIO default)
    --json        machine-readable report on stdout
    --out         also write the JSON report to this path
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_SPARK_CHARS = "▁▂▃▄▅▆▇█"

_ALERT_GAUGE_RE = re.compile(r'^health\.alerts_active\{rule="([^"]+)"\}$')


def sparkline(values: List[float]) -> str:
    """A tiny unicode graph of ``values`` (empty input -> '-')."""
    vals = [float(v) for v in values]
    if not vals:
        return "-"
    lo, hi = min(vals), max(vals)
    if hi - lo < 1e-12:
        return _SPARK_CHARS[0] * len(vals)
    return "".join(
        _SPARK_CHARS[min(len(_SPARK_CHARS) - 1,
                         int((v - lo) / (hi - lo) * (len(_SPARK_CHARS) - 1)))]
        for v in vals)


def firing_rules(cluster: dict) -> Dict[int, List[str]]:
    """``{rank: [rule ids]}`` of alerts firing per the snapshots'
    ``health.alerts_active{rule=}`` gauges (value 1 = firing)."""
    out: Dict[int, List[str]] = {}
    for rank, entry in (cluster.get("ranks") or {}).items():
        gauges = (entry.get("metrics") or {}).get("gauges") or {}
        rules = sorted(m.group(1) for series, v in gauges.items()
                       if v and (m := _ALERT_GAUGE_RE.match(series)))
        if rules:
            out[int(rank)] = rules
    return out


def dominant_attrib(summary: dict) -> Optional[dict]:
    """The attribution component whose window-mean dominates a rank's
    history summary — "where is this rank's step time going"."""
    series = (summary or {}).get("series") or {}
    best = None
    for key, st in series.items():
        if not key.startswith("attrib_"):
            continue
        mean = float(st.get("mean", 0.0))
        if mean > 0 and (best is None or mean > best["mean_ms"]):
            best = {"component": key[len("attrib_"):],
                    "mean_ms": round(mean, 3)}
    return best


def _history_summaries(cluster: dict) -> Dict[int, dict]:
    return {int(r): (h or {}).get("summary") or {}
            for r, h in (cluster.get("history") or {}).items()}


def diagnose_live(cluster: dict, skew_ratio: float = 4.0) -> dict:
    """The live report document (pure over a cluster_metrics() reply;
    unit-tested without a bus)."""
    from byteps_tpu.common.health import attrib_skew_findings
    alerts = firing_rules(cluster)
    slow = {int(r): float(v) for r, v in (cluster.get("slow") or {}).items()}
    probation = [int(r) for r in cluster.get("probation") or ()]
    history = _history_summaries(cluster)
    skews = attrib_skew_findings(history, skew_ratio)
    trends: Dict[int, dict] = {}
    attrib: Dict[int, dict] = {}
    for rank, summ in history.items():
        series = summ.get("series") or {}
        trends[rank] = {
            key: {"last": st.get("last"), "mean": st.get("mean"),
                  "min": st.get("min"), "max": st.get("max"),
                  "spark": sparkline(st.get("spark") or [])}
            for key, st in sorted(series.items())
            if key in ("overlap", "mbps", "slow_score", "step_wall_ms",
                       "retransmit", "shed", "ef_norm")}
        dom = dominant_attrib(summ)
        if dom:
            attrib[rank] = dom

    # the verdict: one culprit rank, by weight of evidence
    evidence: Dict[int, List[str]] = {}
    for rank, rules in alerts.items():
        evidence.setdefault(rank, []).extend(
            f"alert {rid} firing" for rid in rules)
    for rank in probation:
        evidence.setdefault(rank, []).append("on probation")
    if slow:
        worst = max(slow, key=lambda r: slow[r])
        if slow[worst] > 0:
            evidence.setdefault(worst, []).append(
                f"worst slowness phi {slow[worst]:.1f}")
    for f in skews:
        evidence.setdefault(int(f["rank"]), []).append(
            "attrib skew: %s %.1fms vs median %.1fms"
            % (f["component"], f["mean_ms"], f["median_ms"]))
    culprit = None
    if evidence:
        rank = max(evidence, key=lambda r: len(evidence[r]))
        culprit = {"rank": rank, "evidence": evidence[rank]}
    return {"mode": "live",
            "epoch": cluster.get("epoch"),
            "world": cluster.get("world"),
            "coordinator": cluster.get("coordinator"),
            "healthy": not alerts,
            "alerts": alerts,
            "slow": slow,
            "probation": probation,
            "attrib_skew": skews,
            "dominant_attrib": attrib,
            "trends": trends,
            "culprit": culprit}


# -- postmortem ------------------------------------------------------------


def load_flight_dumps(dir_: str) -> List[dict]:
    docs = []
    for path in sorted(glob.glob(os.path.join(dir_, "bps_flight_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bps_doctor: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        doc["_path"] = path
        docs.append(doc)
    return docs


def _partition_incident(faults: List[dict],
                        parks: List[dict]) -> Optional[dict]:
    """Fold the partition-flavored flight events (``fault.partition`` /
    ``fault.partition_healed`` from the injector, ``membership.
    partition_minority`` / ``membership.quorum_refused`` from the
    quorum gate) into one incident: the two sides, which ranks parked
    as the minority, and how long the split lasted.  None when the
    incident directory shows no partition at all."""
    cuts = [f for f in faults if f.get("kind") == "partition"]
    heals = [f for f in faults if f.get("kind") == "partition_healed"]
    if not cuts and not parks:
        return None
    side_a: List[int] = []
    side_b: List[int] = []
    for f in cuts:
        d = f.get("detail") or {}
        if d.get("side_a"):
            side_a = sorted({int(r) for r in d["side_a"]} | set(side_a))
        if d.get("side_b"):
            side_b = sorted({int(r) for r in d["side_b"]} | set(side_b))
    parked = sorted({int(p["rank"]) for p in parks
                     if p.get("kind") == "partition_minority"
                     and p.get("rank") is not None})
    out: Dict = {"side_a": side_a, "side_b": side_b,
                 "parked_ranks": parked,
                 "cut_t": cuts[0].get("t") if cuts else None,
                 "healed": bool(heals)}
    if heals:
        h = heals[0]
        out["heal_t"] = h.get("t")
        after = (h.get("detail") or {}).get("after_ms")
        if after is not None:
            out["split_ms"] = float(after)
        elif out["cut_t"] is not None and h.get("t") is not None:
            out["split_ms"] = round(
                (float(h["t"]) - float(out["cut_t"])) * 1000.0, 1)
    return out


def diagnose_postmortem(dir_: str) -> dict:
    """Correlate one incident directory into the postmortem document
    (pure over files on disk; unit-tested from synthetic dumps)."""
    dumps = load_flight_dumps(dir_)
    alerts: List[dict] = []
    faults: List[dict] = []
    parks: List[dict] = []
    reconcile: List[dict] = []
    durability: List[dict] = []
    for doc in dumps:
        rank = doc.get("rank")
        for ev in doc.get("events") or ():
            kind = ev.get("kind", "")
            if kind.startswith("reconcile."):
                # fleet-reconciler incidents (ISSUE 18): spawns,
                # crash-loop restarts, bans, drains and their
                # escalations — the supervisor's side of the story
                reconcile.append({"t": ev.get("t"), "rank": rank,
                                  "kind": kind[len("reconcile."):],
                                  "host": ev.get("host"),
                                  "detail": {k: v for k, v in ev.items()
                                             if k not in ("t", "mono",
                                                          "kind", "host")}})
            elif kind == "alert":
                alerts.append({"t": ev.get("t"), "rank": rank,
                               "rule": ev.get("rule"),
                               "state": ev.get("state"),
                               "detail": {k: v for k, v in ev.items()
                                          if k not in ("t", "mono", "kind",
                                                       "rule", "state")}})
            elif kind.startswith("fault."):
                faults.append({"t": ev.get("t"), "rank": rank,
                               "kind": kind[len("fault."):],
                               "site": ev.get("site"),
                               "detail": {k: v for k, v in ev.items()
                                          if k not in ("t", "mono",
                                                       "kind", "site")}})
            elif kind.startswith("wal."):
                # durable-state-plane incidents (ISSUE 19): cold-start
                # replays, torn tails truncated, corrupt segments or
                # snapshots discarded, serving arcs restored from disk
                durability.append({"t": ev.get("t"), "rank": rank,
                                   "kind": kind[len("wal."):],
                                   "detail": {k: v for k, v in ev.items()
                                              if k not in ("t", "mono",
                                                           "kind")}})
            elif kind in ("membership.partition_minority",
                          "membership.quorum_refused"):
                parks.append({"t": ev.get("t"), "rank": rank,
                              "kind": kind.split(".", 1)[1],
                              "detail": {k: v for k, v in ev.items()
                                         if k not in ("t", "mono",
                                                      "kind")}})
    alerts.sort(key=lambda a: a.get("t") or 0.0)
    faults.sort(key=lambda f: f.get("t") or 0.0)
    parks.sort(key=lambda p: p.get("t") or 0.0)
    reconcile.sort(key=lambda r: r.get("t") or 0.0)
    durability.sort(key=lambda d: d.get("t") or 0.0)
    partition = _partition_incident(faults, parks)
    firing = [a for a in alerts if a.get("state") == "firing"]
    first = firing[0] if firing else None

    # the culprit: the rank the evidence converges on — injected faults
    # outrank alerts (the alert is the symptom, the fault the cause)
    evidence: Dict[int, List[str]] = {}
    site = None
    for f in faults:
        if f.get("rank") is None:
            continue
        r = int(f["rank"])
        evidence.setdefault(r, []).append(
            "fault %s at site %s" % (f["kind"], f.get("site")))
        if site is None and f.get("site"):
            site = f["site"]
    fault_ranks = set(evidence)
    for a in firing:
        if a.get("rank") is None:
            continue
        evidence.setdefault(int(a["rank"]), []).append(
            "alert %s fired" % a.get("rule"))
    culprit = None
    if evidence:
        # prefer a rank with an injected/recorded fault; break ties by
        # evidence weight
        rank = max(evidence,
                   key=lambda r: (r in fault_ranks, len(evidence[r])))
        culprit = {"rank": rank, "site": site,
                   "evidence": evidence[rank]}

    # saved /timeseries windows, one per rank that captured one
    ts: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(dir_,
                                              "bps_timeseries_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        pts = doc.get("points") or []
        overlaps = [p["overlap"] for p in pts if "overlap" in p]
        ts[os.path.basename(path)] = {
            "len": len(pts),
            "span_s": (round(pts[-1]["t"] - pts[0]["t"], 3)
                       if len(pts) > 1 else 0.0),
            "overlap_min": round(min(overlaps), 4) if overlaps else None,
            "overlap_last": round(overlaps[-1], 4) if overlaps else None,
            "overlap_spark": sparkline(overlaps[-16:])}

    # merged trace (tools/bps_trace.py output), if the incident dir has
    # one: enough stats to say whether the timeline covers the window
    trace = None
    merged_path = os.path.join(dir_, "bps_trace_merged.json")
    if os.path.exists(merged_path):
        try:
            with open(merged_path) as f:
                merged = json.load(f)
            evs = [e for e in merged.get("traceEvents") or ()
                   if e.get("ph") != "M"]
            trace = {"path": merged_path, "events": len(evs),
                     "files": len(merged.get("mergedFrom") or ()),
                     "span_ms": round(max((e.get("ts", 0) for e in evs),
                                          default=0) / 1e3, 3)}
        except (OSError, ValueError):
            pass
    return {"mode": "postmortem",
            "dir": dir_,
            "dumps": [{"path": d["_path"], "rank": d.get("rank"),
                       "reason": d.get("reason"),
                       "events": len(d.get("events") or ())}
                      for d in dumps],
            "first_degradation": first,
            "alerts": alerts,
            "faults": faults,
            "partition": partition,
            "parks": parks,
            "reconciler": reconcile,
            "durability": durability,
            "timeseries": ts,
            "trace": trace,
            "culprit": culprit}


# -- rendering -------------------------------------------------------------


def render_markdown(report: dict) -> str:
    lines: List[str] = []
    if report["mode"] == "live":
        lines.append("# bps_doctor — live (epoch %s, world %s)"
                     % (report.get("epoch"), report.get("world")))
        if report.get("healthy"):
            lines.append("\n**Cluster healthy** — no health rule firing.")
        else:
            lines.append("\n**DEGRADED** — firing rules:")
            for rank, rules in sorted(report["alerts"].items()):
                lines.append("- rank %s: %s" % (rank, ", ".join(rules)))
        if report.get("culprit"):
            c = report["culprit"]
            lines.append("\n**Culprit: rank %s**" % c["rank"])
            for e in c["evidence"]:
                lines.append("  - %s" % e)
        if report.get("attrib_skew"):
            lines.append("\n## Cross-rank attribution skew")
            for f in report["attrib_skew"]:
                lines.append("- rank %(rank)s: %(component)s "
                             "%(mean_ms)sms vs median %(median_ms)sms" % f)
        if report.get("dominant_attrib"):
            lines.append("\n## Dominant attribution component")
            for rank, d in sorted(report["dominant_attrib"].items()):
                lines.append("- rank %s: %s (%.1fms mean)"
                             % (rank, d["component"], d["mean_ms"]))
        if report.get("trends"):
            lines.append("\n## Trends (window summaries)")
            for rank, series in sorted(report["trends"].items()):
                lines.append("- rank %s:" % rank)
                for key, st in series.items():
                    lines.append("    %-12s %s last=%s mean=%s"
                                 % (key, st["spark"], st["last"],
                                    st["mean"]))
    else:
        lines.append("# bps_doctor — postmortem of %s" % report["dir"])
        lines.append("\n%d flight dump(s), %d alert event(s), "
                     "%d fault event(s)"
                     % (len(report["dumps"]), len(report["alerts"]),
                        len(report["faults"])))
        first = report.get("first_degradation")
        if first:
            lines.append("\n**Degraded first: rule `%s` on rank %s** "
                         "(t=%s)" % (first.get("rule"), first.get("rank"),
                                     first.get("t")))
        if report.get("culprit"):
            c = report["culprit"]
            lines.append("\n**Culprit: rank %s%s**"
                         % (c["rank"],
                            (", site %s" % c["site"]) if c.get("site")
                            else ""))
            for e in c["evidence"]:
                lines.append("  - %s" % e)
        if report.get("partition"):
            p = report["partition"]
            lines.append("\n## Network partition")
            lines.append("- sides: %s | %s"
                         % (p.get("side_a"), p.get("side_b")))
            if p.get("parked_ranks"):
                lines.append("- minority parked: rank(s) %s (quorum "
                             "gate refused the epoch)"
                             % p["parked_ranks"])
            if p.get("healed"):
                lines.append("- healed after %sms"
                             % p.get("split_ms", "?"))
            else:
                lines.append("- NEVER healed within the recorded window")
        if report["alerts"]:
            lines.append("\n## Alert timeline")
            for a in report["alerts"]:
                lines.append("- t=%s rank %s: %s %s %s"
                             % (a.get("t"), a.get("rank"), a.get("rule"),
                                a.get("state"), a.get("detail") or ""))
        if report.get("reconciler"):
            lines.append("\n## Reconciler incidents")
            bans = [r for r in report["reconciler"]
                    if r["kind"] == "banned"]
            escalated = [r for r in report["reconciler"]
                         if r["kind"] == "drain_escalated"]
            if bans:
                lines.append("- BANNED (crash loop): host(s) %s"
                             % sorted({r.get("host") for r in bans}))
            if escalated:
                lines.append("- drain deadline ESCALATED to kill: "
                             "host(s) %s"
                             % sorted({r.get("host") for r in escalated}))
            for r in report["reconciler"]:
                lines.append("- t=%s host %s: %s %s"
                             % (r.get("t"), r.get("host"), r.get("kind"),
                                r.get("detail") or ""))
        if report.get("durability"):
            lines.append("\n## Durability / cold start")
            restores = [d for d in report["durability"]
                        if d["kind"] in ("recovered", "arc_restored")]
            losses = [d for d in report["durability"]
                      if d["kind"] in ("truncated_tail", "corrupt_record",
                                       "snapshot_corrupt", "arc_corrupt")]
            if restores:
                lines.append("- restored from local disk: rank(s) %s"
                             % sorted({d.get("rank") for d in restores}))
            if losses:
                lines.append("- journal damage detected and truncated to "
                             "the last durable point: %d event(s)"
                             % len(losses))
            for d in report["durability"]:
                lines.append("- t=%s rank %s: %s %s"
                             % (d.get("t"), d.get("rank"), d.get("kind"),
                                d.get("detail") or ""))
        if report["faults"]:
            lines.append("\n## Injected/recorded faults")
            for f in report["faults"]:
                lines.append("- t=%s rank %s: %s at site %s"
                             % (f.get("t"), f.get("rank"), f.get("kind"),
                                f.get("site")))
        if report.get("timeseries"):
            lines.append("\n## Saved time-series windows")
            for name, t in sorted(report["timeseries"].items()):
                lines.append("- %s: %d point(s) over %ss, overlap %s "
                             "(min %s, last %s)"
                             % (name, t["len"], t["span_s"],
                                t["overlap_spark"], t["overlap_min"],
                                t["overlap_last"]))
        if report.get("trace"):
            t = report["trace"]
            lines.append("\n## Merged trace")
            lines.append("- %s: %d event(s) from %d file(s), span %sms"
                         % (t["path"], t["events"], t["files"],
                            t["span_ms"]))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--bus", default=None, help="membership bus host:port")
    ap.add_argument("--postmortem", default=None, metavar="DIR")
    ap.add_argument("--skew-ratio", type=float, default=4.0)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    if args.postmortem:
        report = diagnose_postmortem(args.postmortem)
    else:
        from byteps_tpu.core.api import cluster_metrics
        try:
            cluster = cluster_metrics(bus=args.bus)
        except Exception as e:  # noqa: BLE001 — a dead bus IS the finding
            print(f"bps_doctor: cluster_metrics failed: {e}",
                  file=sys.stderr)
            return 2
        report = diagnose_live(cluster, skew_ratio=args.skew_ratio)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, default=str)
    if args.json:
        print(json.dumps(report, default=str))
    else:
        print(render_markdown(report))
    # exit status mirrors /healthz: nonzero while something is wrong, so
    # the chaos lane (and operators' scripts) can gate on the verdict
    if report["mode"] == "live":
        return 0 if report.get("healthy") else 1
    return 0 if report.get("culprit") else 1


if __name__ == "__main__":
    sys.exit(main())
