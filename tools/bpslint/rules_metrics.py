"""Rule ``metric-name``: established-metric-name drift, bidirectionally.

Contract (docs/dev_invariants.md):

1. every literal name passed to the MetricsRegistry facades —
   ``counters.inc(...)``, ``gauges.set(...)``,
   ``histograms.observe(...)``, or the registry's own
   ``inc``/``set``/``observe`` — must have a row in the
   ``docs/observability.md`` "Established metric names" table; and
2. every name in that table must appear as a string literal somewhere in
   the package, so a renamed or deleted metric cannot leave a
   live-looking doc row behind.

Dynamically built names (f-strings, name maps) are skipped on the code
side — which is exactly why direction 2 exists: the full name must
still appear *somewhere* as a literal (e.g. a module-level name table),
keeping dynamic emitters greppable and the doc row checkable.

Doc-table grammar: names are backtick spans in the first column; a
label suffix ``{k=,v=}`` is stripped; a name without a dot inherits the
dotted prefix of the previous name in the same row
(```integrity.nonfinite_rejected` / `nonfinite_skipped``` documents
``integrity.nonfinite_skipped``).
"""

from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

from .core import Finding, LintTree, call_target, first_str_arg

_FACADES = {"counters": {"inc"},
            "gauges": {"set"},
            "histograms": {"observe"},
            "registry": {"inc", "set", "observe"}}

_NAME_SPAN = re.compile(r"`([^`]+)`")
_METRIC_SHAPE = re.compile(r"^[a-z0-9_.]+$")


def doc_names(lines: List[str]) -> Dict[str, int]:
    """``{metric name: line}`` from the table whose header row starts
    with ``| Name |``."""
    out: Dict[str, int] = {}
    in_table = False
    for i, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if cells and cells[0] == "Name":
            in_table = True
            continue
        if not in_table or not cells:
            continue
        if set(cells[0]) <= set("-: "):
            continue
        prefix = ""
        for span in _NAME_SPAN.findall(cells[0]):
            name = re.sub(r"\{[^}]*\}", "", span).strip()
            if not _METRIC_SHAPE.match(name):
                continue
            if "." in name:
                prefix = name.rsplit(".", 1)[0] + "."
            elif prefix:
                name = prefix + name
            out.setdefault(name, i)
    return out


def check(tree: LintTree) -> List[Finding]:
    cfg = tree.cfg
    lines = tree.doc_text(cfg.metrics_doc)
    if lines is None:
        return [Finding("metric-name", cfg.metrics_doc, 1,
                        "metrics doc missing — the metric-name rule has "
                        "no documentation source")]
    documented = doc_names(lines)
    if not documented:
        return [Finding("metric-name", cfg.metrics_doc, 1,
                        "no `| Name | Kind | Meaning |` table found — "
                        "the metric-name rule has nothing to check "
                        "against")]

    findings: List[Finding] = []
    pkg = cfg.package.rstrip("/") + "/"
    pkg_files = [f for f in tree.py_files if f.rel.startswith(pkg)]

    all_literals: Set[str] = set()
    emitted: List[Tuple[str, str, int]] = []   # (name, rel, line)
    for pf in pkg_files:
        for s, _ in pf.string_constants():
            all_literals.add(s)
        if not pf.requested:
            continue
        for call in pf.calls():
            recv, meth = call_target(call)
            if recv not in _FACADES or meth not in _FACADES[recv]:
                continue
            lit = first_str_arg(call)
            if lit is None:
                continue   # dynamic name: covered by direction 2
            emitted.append((lit[0], pf.rel, lit[1]))

    seen: Set[Tuple[str, str]] = set()
    for name, rel, line in emitted:
        if name in documented:
            continue
        key = (name, rel)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            "metric-name", rel, line,
            f"metric {name!r} is emitted here but has no row in the "
            f"{cfg.metrics_doc} established-names table — document it "
            f"(dashboards and bps_top are built from that table)"))

    if tree.requested_path(cfg.metrics_doc):
        for name, line in sorted(documented.items()):
            if name not in all_literals:
                findings.append(Finding(
                    "metric-name", cfg.metrics_doc, line,
                    f"documented metric {name!r} appears nowhere in "
                    f"{cfg.package} as a string literal — dead doc row "
                    f"(delete it, or emit the metric; dynamically built "
                    f"names should come from a literal name table)"))
    return findings
