"""bpslint engine: file walking, pragma handling, AST helpers, runner.

The analyzer is pure stdlib-``ast`` — it never imports the package it
checks, so a tree with an import-time bug still lints (and the lint can
run in CI before any heavyweight dependency exists).

Pragma contract (docs/dev_invariants.md): a finding is suppressed by

    # bpslint: ignore[rule-name] reason=why this exception is sound

on the finding's line or the line directly above it.  The ``reason=`` is
*required*: an ignore that cannot say why it is safe is itself reported
(rule ``pragma``), as is an ignore naming a rule that does not exist.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .config import RULE_NAMES, BpslintConfig

_PRAGMA_RE = re.compile(
    r"#\s*bpslint:\s*ignore\[([^\]]*)\]\s*(?:reason=(.*))?$")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str       # root-relative, slash-separated
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class Pragma:
    line: int
    rules: Set[str]
    reason: str


class PyFile:
    """One parsed source file: text, AST, pragmas, literal index."""

    def __init__(self, root: Path, path: Path):
        self.path = path
        # False when the file was scanned only to seed the global
        # consumption/emission/fired sets (a path-subset CLI run):
        # rules report findings only on requested files
        self.requested = True
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8", errors="replace")
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text)
        except SyntaxError as e:
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
        self.pragmas: Dict[int, Pragma] = {}
        self.bad_pragmas: List[Tuple[int, str]] = []
        for i, comment in self._comments():
            m = _PRAGMA_RE.search(comment)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            unknown = sorted(r for r in rules if r not in RULE_NAMES)
            if unknown:
                self.bad_pragmas.append(
                    (i, f"ignore pragma names unknown rule(s) "
                        f"{', '.join(unknown)}; valid rules: "
                        f"{', '.join(RULE_NAMES)}"))
                continue
            if not rules:
                self.bad_pragmas.append(
                    (i, "ignore pragma lists no rules — use "
                        "ignore[rule-name]"))
                continue
            if not reason:
                self.bad_pragmas.append(
                    (i, "ignore pragma carries no reason= — every "
                        "suppression must say why the exception is sound"))
                continue
            self.pragmas[i] = Pragma(i, rules, reason)

    def _comments(self) -> List[Tuple[int, str]]:
        """(line, text) of every real COMMENT token — pragma syntax
        quoted inside a docstring or string literal is documentation,
        not a suppression."""
        try:
            return [(tok.start[0], tok.string) for tok in
                    tokenize.generate_tokens(io.StringIO(self.text).readline)
                    if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, SyntaxError, IndentationError):
            # unparseable file: fall back to the lexical scan (the file
            # already carries a parse finding)
            return [(i, ln) for i, ln in enumerate(self.lines, 1)
                    if "#" in ln]

    def suppressed(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            p = self.pragmas.get(ln)
            if p and rule in p.rules:
                return True
        return False

    # -- AST helpers -------------------------------------------------------

    def string_constants(self) -> Iterable[Tuple[str, int]]:
        """Every string Constant in the file with its line, docstrings
        excluded (a knob named in prose must not count as consumption)."""
        if self.tree is None:
            return
        doc_ids = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                body = getattr(node, "body", [])
                if (body and isinstance(body[0], ast.Expr)
                        and isinstance(body[0].value, ast.Constant)
                        and isinstance(body[0].value.value, str)):
                    doc_ids.add(id(body[0].value))
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and id(node) not in doc_ids):
                yield node.value, node.lineno

    def calls(self) -> Iterable[ast.Call]:
        if self.tree is None:
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                yield node


def call_target(call: ast.Call) -> Tuple[Optional[str], str]:
    """(receiver terminal name | None, callee name) of a call:
    ``counters.inc(...)`` -> ("counters", "inc"); ``fire(...)`` ->
    (None, "fire"); ``a.b.c(...)`` -> ("b", "c")."""
    f = call.func
    if isinstance(f, ast.Name):
        return None, f.id
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name):
            return v.id, f.attr
        if isinstance(v, ast.Attribute):
            return v.attr, f.attr
        return "", f.attr
    return None, ""


def first_str_arg(call: ast.Call) -> Optional[Tuple[str, int]]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value, call.args[0].lineno
    return None


class LintTree:
    """The scanned tree: source files by role, with caching."""

    def __init__(self, root: Path, cfg: BpslintConfig,
                 paths: Optional[Sequence[str]] = None):
        self.root = root
        self.cfg = cfg
        self.paths = list(paths) if paths else list(cfg.paths)
        self._files: Dict[str, PyFile] = {}
        self.py_files: List[PyFile] = []
        seen: Set[str] = set()

        def _scan(p: str, requested: bool, must_exist: bool) -> None:
            base = (root / p).resolve()
            if not base.exists():
                if must_exist:
                    raise FileNotFoundError(
                        f"scan path {p!r} does not exist under {root}")
                return
            if base.is_file() and base.suffix != ".py":
                if must_exist:
                    raise FileNotFoundError(
                        f"scan path {p!r} is not a Python source — the "
                        f"analyzer lints .py files (doc files are "
                        f"checked as the doc side of the bidirectional "
                        f"rules, from the configured paths)")
                return
            cands = [base] if base.is_file() else sorted(
                base.rglob("*.py"))
            for f in cands:
                if f.suffix != ".py" or "__pycache__" in f.parts:
                    continue
                rel = f.relative_to(root).as_posix()
                if rel in seen:
                    continue
                seen.add(rel)
                pf = PyFile(root, f)
                pf.requested = requested
                self._files[rel] = pf
                self.py_files.append(pf)

        # requested paths first (their files carry findings) ...
        for p in self.paths:
            _scan(p, requested=True, must_exist=True)
        # ... then the configured paths, so the bidirectional rules'
        # consumption/emission/fired sets see the WHOLE project even on
        # a path-subset run — otherwise `bpslint some/file.py` would
        # report every doc row as dead and every site as unwoven
        for p in cfg.paths:
            _scan(p, requested=False, must_exist=False)

    def requested_path(self, rel: str) -> bool:
        """True when ``rel`` falls under one of this run's requested
        scan paths — reverse-direction findings (dead doc rows, unwoven
        sites) are reported only on requested targets, so a path-subset
        run stays restricted to the files it was asked about."""
        for p in self.paths:
            q = p.rstrip("/")
            if rel == q or rel.startswith(q + "/"):
                return True
        return False

    def scan_scope(self) -> str:
        """Human-readable scope the consumption/emission/fired sets were
        seeded from: the requested paths plus the configured paths."""
        return ", ".join(dict.fromkeys(
            list(self.paths) + list(self.cfg.paths)))

    def file(self, rel: str) -> Optional[PyFile]:
        """A role file (config module, injector) — loaded on demand even
        when outside the scan paths."""
        if rel in self._files:
            return self._files[rel]
        p = self.root / rel
        if not p.is_file():
            return None
        pf = PyFile(self.root, p)
        self._files[rel] = pf
        return pf

    def package_files(self) -> List[PyFile]:
        pkg = self.cfg.package.rstrip("/") + "/"
        return [f for f in self.py_files
                if f.rel.startswith(pkg) or f.rel == self.cfg.package]

    def doc_text(self, rel: str) -> Optional[List[str]]:
        p = self.root / rel
        if not p.is_file():
            return None
        return p.read_text(encoding="utf-8", errors="replace").splitlines()


def run(root: Path, cfg: Optional[BpslintConfig] = None,
        paths: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run every enabled rule over the tree; returns unsuppressed
    findings sorted by (path, line)."""
    from . import (rules_chaos, rules_env, rules_health, rules_locks,
                   rules_metrics)
    if cfg is None:
        from .config import load_config
        cfg = load_config(root)
    tree = LintTree(root, cfg, paths)

    findings: List[Finding] = []
    # parse errors and pragma hygiene are not disableable — they gate
    # the analyzer's own ability to mean anything
    for pf in tree.py_files:
        if not pf.requested:
            continue
        if pf.parse_error:
            findings.append(Finding("parse", pf.rel, 1, pf.parse_error))
        for line, msg in pf.bad_pragmas:
            findings.append(Finding("pragma", pf.rel, line, msg))

    checkers = {
        "env-knob": rules_env.check,
        "metric-name": rules_metrics.check,
        "chaos-site": rules_chaos.check,
        "lock-discipline": rules_locks.check,
        "health-rule": rules_health.check,
    }
    for rule in cfg.enabled_rules():
        findings.extend(checkers[rule](tree))

    out: List[Finding] = []
    for f in findings:
        pf = tree._files.get(f.path)
        if pf is not None and f.rule in RULE_NAMES \
                and pf.suppressed(f.rule, f.line):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out
