"""Rule ``health-rule``: SLO health-rule drift, bidirectionally.

Contract (common/health.py module docstring):

1. every rule id in the health module's ``RULE_IDS`` literal tuple must
   have a row in the ``docs/observability.md`` health-rule table (the
   one whose header row starts with ``| Rule |``) — an operator paged
   by ``health.alerts_active{rule=}`` must be able to look the rule up;
   and
2. every rule id in that table must appear in ``RULE_IDS`` — a renamed
   or deleted rule cannot leave a live-looking doc row behind.

The rule is inert when the configured health module does not exist
(``health-module`` in ``[tool.bpslint]``): a project without an SLO
engine has no table to drift from.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from .core import Finding, LintTree

_ID_SPAN = re.compile(r"`([^`]+)`")
_ID_SHAPE = re.compile(r"^[a-z0-9_]+$")


def doc_rules(lines: List[str]) -> Dict[str, int]:
    """``{rule id: line}`` from the table whose header row starts with
    ``| Rule |`` (same grammar as the metric-name table: ids are
    backtick spans in the first column)."""
    out: Dict[str, int] = {}
    in_table = False
    for i, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if cells and cells[0] == "Rule":
            in_table = True
            continue
        if not in_table or not cells:
            continue
        if set(cells[0]) <= set("-: "):
            continue
        for span in _ID_SPAN.findall(cells[0]):
            if _ID_SHAPE.match(span):
                out.setdefault(span, i)
    return out


def declared_rules(pf) -> Optional[List[Tuple[str, int]]]:
    """``(rule id, line)`` entries of the health module's module-level
    ``RULE_IDS`` literal tuple/list; None when no such assignment
    exists (itself a finding — the table has no code anchor)."""
    if pf.tree is None:
        return None
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "RULE_IDS"
                   for t in node.targets):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            return None
        out = []
        for elt in node.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append((elt.value, elt.lineno))
        return out
    return None


def check(tree: LintTree) -> List[Finding]:
    cfg = tree.cfg
    pf = tree.file(cfg.health_module)
    if pf is None:
        return []   # no SLO engine in this tree — nothing to drift
    declared = declared_rules(pf)
    if declared is None:
        return [Finding(
            "health-rule", pf.rel, 1,
            "health module declares no literal RULE_IDS tuple — the "
            "health-rule table cannot be checked against it")]

    lines = tree.doc_text(cfg.metrics_doc)
    if lines is None:
        return [Finding("health-rule", cfg.metrics_doc, 1,
                        "metrics doc missing — the health-rule rule has "
                        "no documentation source")]
    documented = doc_rules(lines)
    if declared and not documented:
        return [Finding(
            "health-rule", cfg.metrics_doc, 1,
            "no `| Rule | ... |` health-rule table found — every "
            "RULE_IDS entry needs a documented row (operators look "
            "firing rules up here)")]

    findings: List[Finding] = []
    declared_ids = {rid for rid, _ in declared}
    for rid, line in declared:
        if rid not in documented:
            findings.append(Finding(
                "health-rule", pf.rel, line,
                f"health rule {rid!r} is declared in RULE_IDS but has "
                f"no row in the {cfg.metrics_doc} health-rule table — "
                f"document what fires it and what clears it"))
    if tree.requested_path(cfg.metrics_doc):
        for rid, line in sorted(documented.items()):
            if rid not in declared_ids:
                findings.append(Finding(
                    "health-rule", cfg.metrics_doc, line,
                    f"documented health rule {rid!r} is not declared in "
                    f"{cfg.health_module} RULE_IDS — dead doc row "
                    f"(delete it, or declare the rule)"))
    return findings
