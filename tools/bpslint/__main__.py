"""CLI: ``python -m tools.bpslint [paths...]``.

Paths default to the ``[tool.bpslint] paths`` entry in pyproject.toml
(which defaults to ``byteps_tpu docs tools``).  Exit status: 0 = clean,
1 = findings, 2 = configuration/usage error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .config import RULE_NAMES, BpslintConfigError, load_config
from .core import run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bpslint",
        description="Project-invariant analyzer: env-knob / metric-name /"
                    " chaos-site / lock-discipline / health-rule drift, "
                    "bidirectional.")
    ap.add_argument("paths", nargs="*",
                    help="directories/files to scan (default: "
                         "[tool.bpslint] paths from pyproject.toml)")
    ap.add_argument("--root", default=".",
                    help="repository root holding pyproject.toml "
                         "(default: cwd)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule names and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULE_NAMES:
            print(r)
        return 0

    root = Path(args.root).resolve()
    try:
        cfg = load_config(root)
        findings = run(root, cfg, args.paths or None)
    except BpslintConfigError as e:
        print(f"bpslint: configuration error: {e}", file=sys.stderr)
        return 2
    except FileNotFoundError as e:
        print(f"bpslint: {e}", file=sys.stderr)
        return 2

    for f in findings:
        print(f.render())
    if findings:
        print(f"bpslint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
