"""Rule ``lock-discipline``: no blocking call or user callback under a
held lock, lexically.

Contract (docs/dev_invariants.md): inside the body of a
``with <something lock-shaped>:`` statement, a call to a known-blocking
function (``time.sleep``, ``jax.block_until_ready`` / the engine's
injectable ``_block`` hook, the membership bus's socket ``_request``) or
to a user-supplied callback (a bare ``fn(...)`` / ``cb(...)`` /
``callback(...)`` / ``hook(...)``) is flagged.  Both failure modes are
from this repo's own review history: subscriber hooks fired inside
``KVStore._lock`` (PR 8) and a SIGTERM handler deadlocking on a held
non-reentrant flight-recorder lock (PR 6).

Lexical scope: nested ``def``/``lambda``/``class`` bodies are *not*
"under the lock" (they run later); nested ``with`` bodies are.  The
check is deliberately shallow — it cannot see a blocking call two
frames down — which is what the runtime lock-order witness
(``byteps_tpu/common/lock_witness.py``) complements at chaos time.

A context expression is lock-shaped when its terminal identifier ends in
``lock``/``mutex``/``mu`` (``self._lock``, ``_graph_mu``, …).
Condition variables (``self._cv``) are deliberately NOT matched:
``Condition.wait`` releases its lock, so waiting under one is the
correct pattern, not a bug.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set

from .core import Finding, LintTree, call_target

_LOCKISH = re.compile(r"(?:^|_)(?:lock|mutex|mu)$", re.IGNORECASE)


def _terminal_name(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Call):
        # `with named_lock(...)`-style: look at the callee name
        _, callee = call_target(expr)
        return callee or None
    return None


def _lockish(expr: ast.expr) -> bool:
    name = _terminal_name(expr)
    return bool(name and _LOCKISH.search(name))


def _body_calls(stmts: Iterable[ast.stmt]) -> Iterable[ast.Call]:
    """Calls lexically executed within these statements: descends
    everything except deferred bodies (function/class/lambda)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _blocking_match(call: ast.Call, blocking: List[str],
                    callbacks: List[str]) -> Optional[str]:
    recv, callee = call_target(call)
    for spec in blocking:
        if "." in spec:
            srecv, sname = spec.rsplit(".", 1)
            if recv == srecv and callee == sname:
                return spec
        elif callee == spec:
            return (f"{recv}.{callee}" if recv else callee)
    if recv is None and callee in callbacks:
        return f"user callback {callee}"
    return None


def check(tree: LintTree) -> List[Finding]:
    cfg = tree.cfg
    findings: List[Finding] = []
    pkg = cfg.package.rstrip("/") + "/"
    for pf in tree.py_files:
        if not pf.requested or not pf.rel.startswith(pkg) \
                or pf.tree is None:
            continue
        # nested lock-shaped `with` blocks both see the same call via
        # _body_calls — report it once, attributed to the outermost
        # (first-visited) lock, which is held for the whole region
        reported: Set[int] = set()
        for node in ast.walk(pf.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = [it.context_expr for it in node.items
                    if _lockish(it.context_expr)]
            if not held:
                continue
            lock_desc = ", ".join(
                ast.unparse(h) if hasattr(ast, "unparse") else "lock"
                for h in held)
            for call in _body_calls(node.body):
                hit = _blocking_match(call, cfg.blocking_calls,
                                      cfg.callback_names)
                if hit is None or id(call) in reported:
                    continue
                reported.add(id(call))
                findings.append(Finding(
                    "lock-discipline", pf.rel, call.lineno,
                    f"{hit}(...) called inside `with {lock_desc}:` "
                    f"(held since line {node.lineno}) — a blocking call "
                    f"or user callback under a held lock stalls every "
                    f"contender and can re-enter the component; move it "
                    f"outside the lock, or pragma this line with the "
                    f"reason it cannot block/re-enter"))
    return findings
