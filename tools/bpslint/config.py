"""``[tool.bpslint]`` configuration: parsed from pyproject.toml, validated
eagerly with actionable errors (the same contract as the fault injector's
spec parser — a typo'd key must fail the run loudly, not silently lint
nothing).

Python 3.10 has no ``tomllib``, so a minimal TOML-subset reader backs it
up: only the ``[tool.bpslint*]`` tables are read, supporting string /
bool / int scalars and (possibly multi-line) string arrays — exactly the
shapes this config uses.  Anything else inside those tables is a
configuration error, reported with the offending line.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path
from typing import Dict, List, Optional

# Rule names are the analyzer's public contract: pragma rule lists and
# the enable/disable config are validated against this set.
RULE_NAMES = ("env-knob", "metric-name", "chaos-site", "lock-discipline",
              "health-rule")

# bare "sleep" matches any receiver (time.sleep included); a dotted
# entry would narrow a spec to one receiver, so none is needed here
_DEFAULT_BLOCKING = ["sleep", "block_until_ready", "_request", "_block"]
_DEFAULT_CALLBACKS = ["fn", "cb", "callback", "hook"]


class BpslintConfigError(ValueError):
    """A [tool.bpslint] entry the analyzer cannot honor."""


@dataclasses.dataclass
class BpslintConfig:
    """Resolved analyzer configuration (defaults match this repo)."""

    paths: List[str] = dataclasses.field(
        default_factory=lambda: ["byteps_tpu", "docs", "tools"])
    disable: List[str] = dataclasses.field(default_factory=list)
    # the code tree whose BYTEPS_*/metric literals are ENFORCED (other
    # scanned paths only count as consumers)
    package: str = "byteps_tpu"
    config_module: str = "byteps_tpu/common/config.py"
    env_doc: str = "docs/env.md"
    metrics_doc: str = "docs/observability.md"
    injector_module: str = "byteps_tpu/fault/injector.py"
    health_module: str = "byteps_tpu/common/health.py"
    blocking_calls: List[str] = dataclasses.field(
        default_factory=lambda: list(_DEFAULT_BLOCKING))
    callback_names: List[str] = dataclasses.field(
        default_factory=lambda: list(_DEFAULT_CALLBACKS))

    def enabled_rules(self) -> List[str]:
        return [r for r in RULE_NAMES if r not in self.disable]


def _fail(msg: str) -> BpslintConfigError:
    return BpslintConfigError(f"[tool.bpslint] {msg}")


_TOP_KEYS = {
    "paths": ("paths", list),
    "disable": ("disable", list),
    "package": ("package", str),
    "config-module": ("config_module", str),
    "env-doc": ("env_doc", str),
    "metrics-doc": ("metrics_doc", str),
    "injector-module": ("injector_module", str),
    "health-module": ("health_module", str),
}
_LOCK_KEYS = {
    "blocking-calls": ("blocking_calls", list),
    "callback-names": ("callback_names", list),
}


def parse_tables(text: str) -> Dict[str, Dict[str, object]]:
    """Extract the ``[tool.bpslint*]`` tables from a pyproject document.

    Prefers stdlib ``tomllib`` when available; otherwise reads the
    subset described in the module docstring.  Returns
    ``{table_suffix: {key: value}}`` where the suffix of
    ``[tool.bpslint]`` itself is ``""`` and of
    ``[tool.bpslint.lock-discipline]`` is ``"lock-discipline"``.
    """
    try:
        import tomllib  # Python >= 3.11
    except ModuleNotFoundError:
        return _parse_tables_mini(text)
    try:
        doc = tomllib.loads(text)
    except tomllib.TOMLDecodeError as e:
        # a config error, not a lint finding: exit 2, matching the
        # mini parser's behavior on Python 3.10
        raise _fail(f"pyproject.toml is not valid TOML: {e}") from None
    node = doc.get("tool", {}).get("bpslint")
    if node is None:
        return {}
    out: Dict[str, Dict[str, object]] = {"": {}}
    for k, v in node.items():
        if isinstance(v, dict):
            out[k] = dict(v)
        else:
            out[""][k] = v
    return out


def _parse_tables_mini(text: str) -> Dict[str, Dict[str, object]]:
    out: Dict[str, Dict[str, object]] = {}
    current: Optional[str] = None  # table suffix, None = not ours
    pending_key: Optional[str] = None
    pending_buf = ""
    pending_line = 0

    def _finish(value_text: str, lineno: int):
        nonlocal pending_key
        assert current is not None and pending_key is not None
        out.setdefault(current, {})[pending_key] = _parse_value(
            value_text, pending_key, lineno)
        pending_key = None

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if pending_key is not None:
            pending_buf += " " + line
            if _array_closed(pending_buf):
                _finish(pending_buf, lineno)
            continue
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^\[(.+?)\]$", line)
        if m:
            name = m.group(1).strip()
            if name == "tool.bpslint":
                current = ""
            elif name.startswith("tool.bpslint."):
                current = name[len("tool.bpslint."):]
            else:
                current = None
            if current is not None:
                out.setdefault(current, {})
            continue
        if current is None:
            continue
        m = re.match(r"^([A-Za-z0-9_\-]+)\s*=\s*(.*)$", line)
        if not m:
            raise _fail(f"cannot parse line {lineno}: {raw!r} (expected "
                        f"`key = value`)")
        key, value_text = m.group(1), m.group(2).strip()
        if value_text.startswith("[") and not _array_closed(value_text):
            pending_key, pending_buf, pending_line = key, value_text, lineno
            continue
        out[current][key] = _parse_value(value_text, key, lineno)
    if pending_key is not None:
        raise _fail(f"unterminated array for key {pending_key!r} "
                    f"(started at line {pending_line})")
    return out


def _array_closed(s: str) -> bool:
    # good enough for string arrays: balanced bracket outside quotes
    depth = 0
    in_str: Optional[str] = None
    for c in s:
        if in_str:
            if c == in_str:
                in_str = None
        elif c in "\"'":
            in_str = c
        elif c == "[":
            depth += 1
        elif c == "]":
            depth -= 1
    return depth == 0 and in_str is None


def _parse_value(s: str, key: str, lineno: int):
    s = s.strip()
    # strip a trailing comment (outside quotes)
    out, in_str = [], None
    for c in s:
        if in_str:
            out.append(c)
            if c == in_str:
                in_str = None
        elif c in "\"'":
            in_str = c
            out.append(c)
        elif c == "#":
            break
        else:
            out.append(c)
    s = "".join(out).strip()
    if s in ("true", "false"):
        return s == "true"
    if re.fullmatch(r"-?\d+", s):
        return int(s)
    if len(s) >= 2 and s[0] in "\"'" and s[-1] == s[0]:
        return s[1:-1]
    if s.startswith("[") and s.endswith("]"):
        inner = s[1:-1].strip()
        if not inner:
            return []
        items = []
        for part in _split_commas(inner):
            part = part.strip()
            if not part:
                continue
            if len(part) >= 2 and part[0] in "\"'" and part[-1] == part[0]:
                items.append(part[1:-1])
            else:
                raise _fail(f"key {key!r} (line {lineno}): array elements "
                            f"must be quoted strings, got {part!r}")
        return items
    raise _fail(f"key {key!r} (line {lineno}): unsupported value {s!r} "
                f"(strings, booleans, integers and string arrays only)")


def _split_commas(s: str) -> List[str]:
    parts, buf, in_str = [], "", None
    for c in s:
        if in_str:
            buf += c
            if c == in_str:
                in_str = None
        elif c in "\"'":
            in_str = c
            buf += c
        elif c == ",":
            parts.append(buf)
            buf = ""
        else:
            buf += c
    if buf.strip():
        parts.append(buf)
    return parts


def load_config(root: Path) -> BpslintConfig:
    """Read and validate ``[tool.bpslint]`` from ``root/pyproject.toml``.
    A missing file or missing section yields the defaults."""
    pj = root / "pyproject.toml"
    if not pj.is_file():
        return BpslintConfig()
    tables = parse_tables(pj.read_text())
    if not tables:
        return BpslintConfig()
    cfg = BpslintConfig()
    known_tables = {"", "lock-discipline"}
    for suffix in tables:
        if suffix not in known_tables:
            raise _fail(
                f"unknown table [tool.bpslint.{suffix}]; known sub-tables: "
                f"lock-discipline")
    for key, value in tables.get("", {}).items():
        if key not in _TOP_KEYS:
            raise _fail(f"unknown key {key!r}; valid keys: "
                        f"{', '.join(sorted(_TOP_KEYS))}")
        attr, typ = _TOP_KEYS[key]
        _check_type(key, value, typ)
        setattr(cfg, attr, value)
    for key, value in tables.get("lock-discipline", {}).items():
        if key not in _LOCK_KEYS:
            raise _fail(f"[lock-discipline] unknown key {key!r}; valid "
                        f"keys: {', '.join(sorted(_LOCK_KEYS))}")
        attr, typ = _LOCK_KEYS[key]
        _check_type(key, value, typ)
        setattr(cfg, attr, value)
    bad = [r for r in cfg.disable if r not in RULE_NAMES]
    if bad:
        raise _fail(f"disable names unknown rule(s) {bad}; valid rules: "
                    f"{', '.join(RULE_NAMES)}")
    if not cfg.paths:
        raise _fail("paths must name at least one directory to scan")
    for p in cfg.paths:
        if not isinstance(p, str) or not p:
            raise _fail(f"paths entries must be non-empty strings, "
                        f"got {p!r}")
    return cfg


def _check_type(key: str, value: object, typ: type) -> None:
    if typ is list:
        if not isinstance(value, list) or any(
                not isinstance(x, str) for x in value):
            raise _fail(f"key {key!r} must be an array of strings, "
                        f"got {value!r}")
    elif not isinstance(value, typ):
        raise _fail(f"key {key!r} must be a {typ.__name__}, got {value!r}")
