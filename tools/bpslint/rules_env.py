"""Rule ``env-knob``: BYTEPS_* environment-knob drift, bidirectionally.

Contract (docs/dev_invariants.md):

1. every ``BYTEPS_*`` string literal in the package must be **validated**
   — the same name appears in ``common/config.py`` (the typed Config is
   the single parse/validate point for knobs); and
2. the same name must have a **row in docs/env.md** (any table whose
   header column is ``Variable``; the reference-disposition table is
   historical record, not live documentation, and is excluded); and
3. every documented knob must be **consumed** — the name appears as a
   literal somewhere in the scanned code — so a deleted knob cannot
   leave a live-looking doc row behind (the
   ``BYTEPS_SERVE_CUT_INTERVAL`` failure mode: defined, documented,
   consumed by nothing).

Only full-string literals count (``"BYTEPS_FOO"``), never substrings of
messages or docstrings — an error string *naming* a knob is not a read.
"""

from __future__ import annotations

import re
from typing import Dict, List, Set, Tuple

from .core import Finding, LintTree

_KNOB = re.compile(r"^BYTEPS_[A-Z0-9_]+$")
_KNOB_IN_ROW = re.compile(r"BYTEPS_[A-Z0-9_]+")


def doc_rows(lines: List[str]) -> Dict[str, int]:
    """``{knob: first line}`` from every markdown table whose header
    row's first column is exactly ``Variable``.  Knob names are taken
    from the WHOLE row (a knob explained in another row's meaning cell
    — e.g. a renamed fallback — is documented there)."""
    out: Dict[str, int] = {}
    in_table = False
    for i, line in enumerate(lines, 1):
        stripped = line.strip()
        if stripped.startswith("|"):
            cells = [c.strip() for c in stripped.strip("|").split("|")]
            first = cells[0].strip("`* ") if cells else ""
            if first == "Variable":
                in_table = True
                continue
            if in_table:
                if set(first) <= set("-: "):
                    continue  # the |---|---| separator
                for m in _KNOB_IN_ROW.finditer(stripped):
                    out.setdefault(m.group(0), i)
        else:
            in_table = False
    return out


def check(tree: LintTree) -> List[Finding]:
    cfg = tree.cfg
    findings: List[Finding] = []

    config_pf = tree.file(cfg.config_module)
    if config_pf is None or config_pf.tree is None:
        return [Finding("env-knob", cfg.config_module, 1,
                        "config module missing or unparseable — the "
                        "env-knob rule has no validation source")]
    config_names: Set[str] = {
        s for s, _ in config_pf.string_constants() if _KNOB.match(s)}

    lines = tree.doc_text(cfg.env_doc)
    if lines is None:
        return [Finding("env-knob", cfg.env_doc, 1,
                        "env doc missing — the env-knob rule has no "
                        "documentation source")]
    documented = doc_rows(lines)

    # all consumers (package + tools + any other scanned py), for the
    # dead-doc-row direction
    consumed: Set[str] = set()
    # package literals, for the validated+documented direction
    pkg_literals: List[Tuple[str, str, int]] = []   # (knob, rel, line)
    pkg = cfg.package.rstrip("/") + "/"
    for pf in tree.py_files:
        for s, line in pf.string_constants():
            if not _KNOB.match(s):
                continue
            consumed.add(s)
            if pf.requested and pf.rel.startswith(pkg):
                pkg_literals.append((s, pf.rel, line))
    # the config module itself may sit outside the scan paths
    for s, _ in config_pf.string_constants():
        if _KNOB.match(s):
            consumed.add(s)

    seen: Set[Tuple[str, str, str]] = set()
    for knob, rel, line in pkg_literals:
        is_config = rel == cfg.config_module
        if not is_config and knob not in config_names:
            key = (knob, rel, "validate")
            if key not in seen:
                seen.add(key)
                findings.append(Finding(
                    "env-knob", rel, line,
                    f"env knob {knob} is read here but never validated "
                    f"in {cfg.config_module} — add a Config field (or "
                    f"an ignore pragma saying why this read cannot go "
                    f"through Config)"))
        if knob not in documented:
            key = (knob, rel, "doc")
            if key not in seen:
                seen.add(key)
                findings.append(Finding(
                    "env-knob", rel, line,
                    f"env knob {knob} has no row in {cfg.env_doc} — "
                    f"document it (operators discover knobs there)"))

    if tree.requested_path(cfg.env_doc):
        for knob, line in sorted(documented.items()):
            if knob not in consumed:
                findings.append(Finding(
                    "env-knob", cfg.env_doc, line,
                    f"documented knob {knob} is consumed nowhere in "
                    f"{tree.scan_scope()} — dead doc row (delete it, or "
                    f"wire the knob back up)"))
    return findings
