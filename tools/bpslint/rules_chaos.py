"""Rule ``chaos-site``: fault-injection site drift, bidirectionally.

Contract (docs/dev_invariants.md):

1. every literal site passed to the injector delegates — ``fire(...)``,
   ``should_drop(...)``, ``corrupt(...)``, ``corrupt_bytes(...)`` —
   must be a member of the injector's ``VALID_SITES`` tuple (a typo'd
   site would validate specs against a site that never fires); and
2. every ``VALID_SITES`` entry must be woven somewhere — passed as a
   literal to one of those delegates outside the injector itself — so a
   site that was unwired during a refactor fails the lint instead of
   silently accepting specs that inject nothing.

Entries that are deliberately not woven code sites (e.g. the
``coordinator`` kill-only predicate) carry an inline ignore pragma on
their own line of the tuple, with the reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, LintTree, call_target, first_str_arg

_DELEGATES = {"fire", "should_drop", "corrupt", "corrupt_bytes"}


def valid_sites(injector_pf) -> Optional[Dict[str, int]]:
    """``{site: line}`` from the injector's ``VALID_SITES = (...)``
    assignment — per-element linenos, so an unwoven site is reported
    (and pragma-suppressible) on its own line."""
    if injector_pf is None or injector_pf.tree is None:
        return None
    for node in ast.walk(injector_pf.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "VALID_SITES"
                   for t in node.targets):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            return None
        out: Dict[str, int] = {}
        for elt in node.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out[elt.value] = elt.lineno
        return out
    return None


def check(tree: LintTree) -> List[Finding]:
    cfg = tree.cfg
    injector_pf = tree.file(cfg.injector_module)
    sites = valid_sites(injector_pf)
    if sites is None:
        return [Finding("chaos-site", cfg.injector_module, 1,
                        "cannot locate a literal VALID_SITES tuple — the "
                        "chaos-site rule has no source of truth")]

    findings: List[Finding] = []
    fired: Set[str] = set()
    pkg = cfg.package.rstrip("/") + "/"
    seen: Set[Tuple[str, str]] = set()
    for pf in tree.py_files:
        if not pf.rel.startswith(pkg) or pf.rel == cfg.injector_module:
            continue
        for call in pf.calls():
            _, meth = call_target(call)
            if meth not in _DELEGATES:
                continue
            lit = first_str_arg(call)
            if lit is None:
                continue   # dynamic site (e.g. wire_transmit's) — the
                # values flowing in are themselves literals elsewhere
            site, line = lit
            fired.add(site)
            if not pf.requested:
                continue
            if site not in sites and (site, pf.rel) not in seen:
                seen.add((site, pf.rel))
                findings.append(Finding(
                    "chaos-site", pf.rel, line,
                    f"chaos site {site!r} is not in the injector's "
                    f"VALID_SITES — specs naming it are rejected at "
                    f"init, so this hook can never fire (add the site, "
                    f"or fix the typo; valid: "
                    f"{', '.join(sorted(sites))})"))

    if not tree.requested_path(cfg.injector_module):
        return findings
    for site, line in sorted(sites.items()):
        if site not in fired:
            findings.append(Finding(
                "chaos-site", cfg.injector_module, line,
                f"VALID_SITES entry {site!r} is never woven — no "
                f"fire/should_drop/corrupt call passes it, so a spec "
                f"targeting it injects nothing (wire it up, remove it, "
                f"or pragma its tuple line with the reason it is not a "
                f"woven site)"))
    return findings
