"""bpslint: project-invariant static analysis for this repository.

Four rule families keep the hand-maintained cross-file contracts
machine-checked (docs/dev_invariants.md):

- ``env-knob``      — every BYTEPS_* literal Config-validated AND
                      documented in docs/env.md; every doc row consumed
- ``metric-name``   — facade metric names <-> docs/observability.md table
- ``chaos-site``    — fire()/should_drop()/corrupt() literals <->
                      the injector's VALID_SITES, both directions
- ``lock-discipline`` — no blocking call / user callback lexically
                      inside a ``with <lock>:`` body

Run: ``python -m tools.bpslint byteps_tpu docs tools`` (exit 0 clean,
1 findings, 2 usage/config error).  Suppress a finding with
``# bpslint: ignore[rule] reason=...`` — the reason is mandatory.

The runtime complement is the lock-order witness
(``byteps_tpu/common/lock_witness.py``, ``BYTEPS_LOCK_WITNESS=1``).
"""

from .config import (BpslintConfig, BpslintConfigError, RULE_NAMES,
                     load_config)
from .core import Finding, LintTree, run

__all__ = ["BpslintConfig", "BpslintConfigError", "RULE_NAMES",
           "load_config", "Finding", "LintTree", "run"]
