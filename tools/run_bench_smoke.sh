#!/usr/bin/env bash
# bench-smoke lane: the 8 MB engine micro-bench on the virtual 8-device
# CPU mesh, gated against the checked-in floor
# (tools/bench_smoke_floor.json) — fails on a >30% regression of the
# engine-vs-fused ratio (see tools/bench_smoke.py for why the ratio and
# not raw GB/s is what gates on a shared host).
#
# Usage:  tools/run_bench_smoke.sh            # measure + gate
#         tools/run_bench_smoke.sh --update-floor   # rewrite the floor
# Env:    BENCH_SMOKE_TOLERANCE  allowed fractional regression (0.30)
#         BENCH_SMOKE_TIMEOUT    whole-lane seconds (default 420)
set -o pipefail

cd "$(dirname "$0")/.."

LANE="${BENCH_SMOKE_TIMEOUT:-420}"

exec timeout -k 15 "$LANE" \
    env JAX_PLATFORMS=cpu python tools/bench_smoke.py "$@"
