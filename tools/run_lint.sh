#!/usr/bin/env bash
# bpslint entry point: the project-invariant analyzer (tools/bpslint,
# docs/dev_invariants.md).  Exit 0 = clean, 1 = findings, 2 = config
# error.  Pure stdlib — no JAX import, safe as the first CI step.
#
# Usage: tools/run_lint.sh [paths...]     (default: [tool.bpslint] paths)
set -o pipefail
cd "$(dirname "$0")/.."
exec python -m tools.bpslint "$@"
