"""Serve bench: pulls/sec + p99 pull latency under concurrent pushes.

The read-dimension headline the bench trajectory ignored until ISSUE 9:
every prior figure measures push GB/s.  This tool stands up the
parameter-serving plane (server/serving.py) over a live KV store, keeps
a TRAINING pusher thread summing deltas and cutting snapshots the whole
time, and drives N concurrent pull clients — reporting:

- ``pulls_per_s``     — aggregate client pull throughput
- ``p50_ms`` / ``p99_ms`` — per-pull latency quantiles (client-observed,
  cache hits included when ``--staleness`` > 0: that IS the product's
  latency story)
- ``pushes_per_s``    — the write load sustained while serving
- ``delta``           — a controlled wire-byte accounting check proving
  a delta pull ships ONLY changed keys' encoded bytes (O(churn), not
  O(model))

Usage:  python tools/serve_bench.py [--seconds S] [--clients N]
            [--keys K] [--numel E] [--replicas R] [--staleness SEC]
            [--hosts N]

``--hosts N`` switches to DISTRIBUTED mode (server/serving_tier.py):
N real serving-host processes are spawned behind the TCP transport, a
membership bus carries the host directory, a ``ServingTier`` ships
snapshot deltas per the consistent-hash ring while the pusher keeps
training writes landing, and the pull clients route by the ring —
reporting aggregate pulls/s, p50/p99, AND per-host pulls + latency
quantiles (the figures the serve_dist bench-smoke section gates on).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def delta_check(numel: int = 4096, keys: int = 4) -> dict:
    """Deterministic byte accounting: a full hydration costs the whole
    model; a delta pull after ONE changed key costs exactly that key's
    encoded bytes.  Returns the measured figures plus ``ok``."""
    import numpy as np

    from byteps_tpu.server.kv_store import KVStore
    from byteps_tpu.server.serve_client import PullClient
    from byteps_tpu.server.serving import ServingPlane

    store = KVStore()
    names = [f"serve.delta.{i}" for i in range(keys)]
    for n in names:
        store.init_key(n, np.zeros(numel, np.float32))
        store.push_delta(n, np.ones(numel, np.float32))
    plane = ServingPlane(store, replicas=1, retention=8)
    plane.cut()
    client = PullClient(plane, max_staleness_s=0.0)
    client.pull()
    full_bytes = client.bytes_received
    store.push_delta(names[0], np.ones(numel, np.float32))
    plane.cut()
    client.pull()
    delta_bytes = client.bytes_received - full_bytes
    key_bytes = numel * 4
    return {"model_bytes": keys * key_bytes,
            "full_pull_bytes": full_bytes,
            "delta_pull_bytes": delta_bytes,
            "changed_key_bytes": key_bytes,
            "ok": (full_bytes == keys * key_bytes
                   and delta_bytes == key_bytes)}


def measure(*, seconds: float = 2.0, clients: int = 4, keys: int = 8,
            numel: int = 65536, replicas: int = 3,
            staleness: float = 0.0) -> dict:
    """The concurrent-read/write measurement.  One pusher thread keeps
    training pushes landing (one cut per full key sweep, the per-step
    publication pattern); ``clients`` threads pull as fast as they can
    under the given staleness bound."""
    import numpy as np

    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.server.kv_store import KVStore
    from byteps_tpu.server.serve_client import PullClient
    from byteps_tpu.server.serving import ServingPlane

    store = KVStore()
    names = [f"serve.bench.{i}" for i in range(keys)]
    rng = np.random.RandomState(0)
    for n in names:
        store.init_key(n, rng.randn(numel).astype(np.float32))
    plane = ServingPlane(store, replicas=replicas, retention=16)
    plane.cut()
    # warm the hot-key histogram so replicas participate from the start
    warm = PullClient(plane, max_staleness_s=0.0)
    warm.pull()
    plane.cut()

    stop = threading.Event()
    pushes = [0]

    def pusher():
        delta = np.ones(numel, np.float32) * 1e-3
        i = 0
        while not stop.is_set():
            store.push_delta(names[i % keys], delta)
            pushes[0] += 1
            i += 1
            if i % keys == 0:
                plane.cut()

    lat_lock = threading.Lock()
    latencies: list = []
    pull_counts = [0] * clients
    errors = [0]

    def puller(idx: int):
        client = PullClient(plane, max_staleness_s=staleness)
        mine = []
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                client.pull()
            except Exception:  # noqa: BLE001 — an erroring read is the
                # one thing the plane promises not to produce
                errors[0] += 1
                continue
            mine.append((time.perf_counter() - t0) * 1e3)
            pull_counts[idx] += 1
        with lat_lock:
            latencies.extend(mine)

    push_thread = threading.Thread(target=pusher, daemon=True)
    threads = [threading.Thread(target=puller, args=(i,), daemon=True)
               for i in range(clients)]
    c0 = counters.get("serve.cache_hits")
    t0 = time.perf_counter()
    push_thread.start()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    push_thread.join(timeout=10)
    wall = time.perf_counter() - t0

    total_pulls = sum(pull_counts)
    lat = np.asarray(latencies) if latencies else np.asarray([0.0])
    return {
        "seconds": round(wall, 3),
        "clients": clients,
        "keys": keys,
        "numel": numel,
        "replicas": replicas,
        "staleness_s": staleness,
        "pulls": total_pulls,
        "pulls_per_s": round(total_pulls / wall, 1),
        "p50_ms": round(float(np.percentile(lat, 50)), 3),
        "p99_ms": round(float(np.percentile(lat, 99)), 3),
        "pushes": pushes[0],
        "pushes_per_s": round(pushes[0] / wall, 1),
        "failed_reads": errors[0],
        "cache_hits": counters.get("serve.cache_hits") - c0,
        "replica_reads": counters.get("serve.replica_reads"),
        "primary_reads": counters.get("serve.primary_reads"),
        "snapshot_cuts": counters.get("serve.snapshot_cuts"),
    }


def _await_host_up(p, timeout_s: float = 90.0) -> str:
    """First stdout line with a deadline: a host wedged before HOST-UP
    (import deadlock, bad env) must FAIL the bench, not hang it."""
    out: list = []
    t = threading.Thread(target=lambda: out.append(p.stdout.readline()),
                         daemon=True, name="serve-host-up")
    t.start()
    t.join(timeout_s)
    if not out:
        raise RuntimeError(
            f"serve host (pid {p.pid}) printed nothing within "
            f"{timeout_s}s — wedged before HOST-UP")
    return out[0]


def kill_serve_hosts(procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except Exception:  # noqa: BLE001 — escalate, never leak
            p.kill()


def spawn_serve_hosts(n: int, bus_port: int, *, ttl_s: float = 5.0,
                      extra_env=None):
    """Spawn ``n`` real serving-host processes registered against the
    bus at ``bus_port``; returns the Popen list once every host printed
    HOST-UP (shared by the distributed bench and the chaos tests).  On
    any startup failure every already-spawned host is killed — no
    orphan processes left registered against a bus nobody will close."""
    import subprocess
    procs = []
    try:
        for i in range(n):
            env = dict(os.environ,
                       JAX_PLATFORMS="cpu",
                       BYTEPS_SERVE_TIER_BUS=f"127.0.0.1:{bus_port}",
                       BYTEPS_SERVE_HOST_ID=str(i),
                       BYTEPS_SERVE_TIER_TTL=str(ttl_s),
                       BYTEPS_LOG_LEVEL="ERROR",
                       PYTHONPATH=REPO + os.pathsep + os.environ.get(
                           "PYTHONPATH", ""))
            env.update(extra_env(i) if callable(extra_env)
                       else (extra_env or {}))
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "byteps_tpu.server.serve_host"],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for p in procs:
            line = _await_host_up(p)
            if "HOST-UP" not in line:
                raise RuntimeError(f"serve host failed to start: {line!r}")
            # keep draining after HOST-UP: a host that logs under chaos
            # (fault-injector warnings, transport errors) would
            # otherwise fill the 64 KiB pipe and BLOCK mid-log
            threading.Thread(target=lambda f=p.stdout: f.read(),
                             daemon=True, name="serve-host-drain").start()
    except BaseException:
        kill_serve_hosts(procs)
        raise
    return procs


def measure_distributed(*, hosts: int = 3, seconds: float = 3.0,
                        clients: int = 4, keys: int = 8,
                        numel: int = 16384, replicas: int = 2,
                        staleness: float = 0.0) -> dict:
    """The distributed measurement: real host processes, a live bus, a
    shipping publisher, ring-routed clients."""
    import socket as _socket

    import numpy as np

    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.fault.membership import MembershipView, _BusServer
    from byteps_tpu.server.kv_store import KVStore
    from byteps_tpu.server.serving_tier import ServingTier

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    bus_port = s.getsockname()[1]
    s.close()
    bus = _BusServer(("127.0.0.1", bus_port), MembershipView(0, (0,)),
                     5.0, 5.0)
    procs = []
    tier = None
    try:
        procs = spawn_serve_hosts(hosts, bus_port)
        store = KVStore()
        names = [f"serve.dist.{i}" for i in range(keys)]
        rng = np.random.RandomState(0)
        for n in names:
            store.init_key(n, rng.randn(numel).astype(np.float32))
        tier = ServingTier(store, bus=f"127.0.0.1:{bus_port}",
                           replicas=replicas, cut_interval_s=None,
                           ship_deadline_s=3.0)
        tier.cut()

        stop = threading.Event()
        pushes = [0]

        def pusher():
            delta = np.ones(numel, np.float32) * 1e-3
            i = 0
            while not stop.is_set():
                store.push_delta(names[i % keys], delta)
                pushes[0] += 1
                i += 1
                if i % keys == 0:
                    tier.cut()

        lat_lock = threading.Lock()
        latencies: list = []
        per_host: dict = {}
        pull_counts = [0] * clients
        errors = [0]

        def puller(idx: int):
            client = tier.client(max_staleness_s=staleness)
            router = client._plane
            mine = []
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    client.pull()
                except Exception:  # noqa: BLE001 — the tier's one promise
                    errors[0] += 1
                    continue
                mine.append((time.perf_counter() - t0) * 1e3)
                pull_counts[idx] += 1
            with lat_lock:
                latencies.extend(mine)
                for h, c in router.host_pulls.items():
                    per_host[h] = per_host.get(h, 0) + c

        push_thread = threading.Thread(target=pusher, daemon=True)
        threads = [threading.Thread(target=puller, args=(i,), daemon=True)
                   for i in range(clients)]
        t0 = time.perf_counter()
        push_thread.start()
        for t in threads:
            t.start()
        time.sleep(seconds)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        push_thread.join(timeout=15)
        wall = time.perf_counter() - t0

        import numpy as _np
        total = sum(pull_counts)
        lat = _np.asarray(latencies) if latencies else _np.asarray([0.0])
        # per-host latency quantiles from the slowness tracker's windows
        from byteps_tpu.utils import slowness as _slowness
        snap = _slowness.tracker().snapshot().get("serve_pull", {})
        host_stats = {
            int(h): {"pulls": per_host.get(h, 0),
                     "p50_ms": (snap.get(h) or {}).get("median_ms", 0.0)}
            for h in sorted(tier.ring.hosts() | set(per_host))}
        # shed happens IN the host processes: their cumulative figures
        # ride the directory heartbeats (reading this process's
        # serve.shed counter would always print 0)
        dir_meta = tier.directory.info()["meta"]
        shed_total = sum(int(m.get("sheds", 0))
                         for m in dir_meta.values())
        return {
            "mode": "distributed",
            "hosts": hosts,
            "seconds": round(wall, 3),
            "clients": clients,
            "keys": keys,
            "numel": numel,
            "replicas": replicas,
            "staleness_s": staleness,
            "pulls": total,
            "pulls_per_s": round(total / wall, 1),
            "p50_ms": round(float(_np.percentile(lat, 50)), 3),
            "p99_ms": round(float(_np.percentile(lat, 99)), 3),
            "pushes_per_s": round(pushes[0] / wall, 1),
            "failed_reads": errors[0],
            "per_host": host_stats,
            "ring_gen": tier.debug_state()["gen"],
            "ships": counters.get("serve.tier_ships"),
            "ship_failures": counters.get("serve.tier_ship_failures"),
            "failovers": counters.get("serve.tier_failover"),
            "shed": shed_total,
        }
    finally:
        if tier is not None:
            tier.close()
        kill_serve_hosts(procs)
        bus.close()


def measure_fleet(*, seconds: float = 4.0, clients: int = 3,
                  keys: int = 6, numel: int = 16384, replicas: int = 2,
                  staleness: float = 0.1, base_hosts: int = 2,
                  peak_hosts: int = 4) -> dict:
    """Pulls/s and p99 DURING fleet churn (ISSUE 18): the fleet
    reconciler spawns the hosts (none are pre-spawned here), the bench
    drives the autoscaler's actuation channel — ``serve_scale`` target
    bumps on the bus — up to ``peak_hosts`` mid-storm and back down to
    ``base_hosts``, so the measurement window contains real spawns AND
    real graceful drains while the pull storm runs.  The gate: zero
    failed reads through all of it, and throughput above the floor."""
    import socket as _socket

    import numpy as np

    from byteps_tpu.fault.membership import MembershipView, _BusServer
    from byteps_tpu.launcher.reconciler import FleetReconciler
    from byteps_tpu.server.kv_store import KVStore
    from byteps_tpu.server.serving_tier import ServingTier, TierDirectory

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    bus_port = s.getsockname()[1]
    s.close()
    bus = _BusServer(("127.0.0.1", bus_port), MembershipView(0, (0,)),
                     5.0, 5.0)
    tier = None
    rec = None
    try:
        directory = TierDirectory(bus=f"127.0.0.1:{bus_port}", ttl_s=3.0)
        rec = FleetReconciler(
            directory=directory, interval_s=0.2, drain_deadline_s=8.0,
            spawn_env={"JAX_PLATFORMS": "cpu",
                       "BYTEPS_LOG_LEVEL": "ERROR"})
        rec_stop = threading.Event()
        rec_thread = threading.Thread(target=rec.run, args=(rec_stop,),
                                      daemon=True, name="fleet-bench-rec")
        directory.set_target(base_hosts)
        rec_thread.start()
        deadline = time.monotonic() + 90.0
        while len(directory.hosts(force=True)[1]) < base_hosts:
            if time.monotonic() > deadline:
                raise RuntimeError("reconciler never converged to the "
                                   "base fleet")
            time.sleep(0.1)

        store = KVStore()
        names = [f"serve.fleet.{i}" for i in range(keys)]
        rng = np.random.RandomState(0)
        for n in names:
            store.init_key(n, rng.randn(numel).astype(np.float32))
        tier = ServingTier(store, bus=f"127.0.0.1:{bus_port}",
                           replicas=replicas, cut_interval_s=None,
                           ship_deadline_s=3.0)
        tier.cut()

        stop = threading.Event()
        pushes = [0]

        def pusher():
            delta = np.ones(numel, np.float32) * 1e-3
            i = 0
            while not stop.is_set():
                store.push_delta(names[i % keys], delta)
                pushes[0] += 1
                i += 1
                if i % keys == 0:
                    tier.cut()

        lat_lock = threading.Lock()
        latencies: list = []
        pull_counts = [0] * clients
        errors = [0]

        def puller(idx: int):
            client = tier.client(max_staleness_s=staleness)
            mine = []
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    client.pull()
                except Exception:  # noqa: BLE001 — zero failed reads
                    # through the churn is THE fleet promise
                    errors[0] += 1
                    continue
                mine.append((time.perf_counter() - t0) * 1e3)
                pull_counts[idx] += 1
            with lat_lock:
                latencies.extend(mine)

        churn_done = threading.Event()

        def _await_fleet(n: int, deadline_s: float) -> bool:
            end = time.monotonic() + deadline_s
            while time.monotonic() < end and not stop.is_set():
                if len(directory.hosts(force=True)[1]) == n \
                        and not rec.debug_state()["draining"]:
                    return True
                time.sleep(0.1)
            return False

        def churner():
            """The autoscaler's actuation channel, STATE-driven: bump
            the target to the peak and wait for the spawned hosts to
            actually register (a serve_host cold-starts in seconds —
            a fixed schedule would end the storm before the fleet ever
            grew), then drop back to base and wait for the drains to
            complete.  Both transitions land inside the measurement
            window because the window ends only after this does."""
            if stop.wait(max(seconds / 4.0, 0.5)):
                return
            directory.set_target(peak_hosts)
            _await_fleet(peak_hosts, 60.0)
            directory.set_target(base_hosts)
            _await_fleet(base_hosts, 60.0)
            churn_done.set()

        push_thread = threading.Thread(target=pusher, daemon=True)
        churn_thread = threading.Thread(target=churner, daemon=True)
        threads = [threading.Thread(target=puller, args=(i,), daemon=True)
                   for i in range(clients)]
        t0 = time.perf_counter()
        push_thread.start()
        churn_thread.start()
        for t in threads:
            t.start()
        # the storm runs until the churn completes (spawns registered,
        # drains landed), with `seconds` as the minimum and a hard cap
        # as the wedge guard
        churn_done.wait(timeout=150.0)
        remaining = seconds - (time.perf_counter() - t0)
        if remaining > 0:
            time.sleep(remaining)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        push_thread.join(timeout=15)
        churn_thread.join(timeout=15)
        wall = time.perf_counter() - t0

        import numpy as _np
        from byteps_tpu.common.telemetry import counters
        total = sum(pull_counts)
        lat = _np.asarray(latencies) if latencies else _np.asarray([0.0])
        state = rec.debug_state()
        return {
            "mode": "fleet",
            "seconds": round(wall, 3),
            "clients": clients,
            "base_hosts": base_hosts,
            "peak_hosts": peak_hosts,
            "pulls": total,
            "pulls_per_s": round(total / wall, 1),
            "p50_ms": round(float(_np.percentile(lat, 50)), 3),
            "p99_ms": round(float(_np.percentile(lat, 99)), 3),
            "pushes_per_s": round(pushes[0] / wall, 1),
            "failed_reads": errors[0],
            "spawned": counters.get("reconcile.spawned"),
            "drain_started": counters.get("reconcile.drain_started"),
            "drained": counters.get("reconcile.drained"),
            "drain_escalated": counters.get("reconcile.drain_escalated"),
            "banned": counters.get("reconcile.banned"),
            "final_hosts": len(directory.hosts(force=True)[1]),
            "still_draining": state["draining"],
        }
    finally:
        if tier is not None:
            tier.close()
        if rec is not None:
            rec.close(kill_hosts=True)
        bus.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float, default=3.0)
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--keys", type=int, default=8)
    p.add_argument("--numel", type=int, default=65536)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--staleness", type=float, default=0.0)
    p.add_argument("--hosts", type=int, default=0,
                   help="N > 0: distributed mode with N real "
                        "serving-host processes")
    p.add_argument("--fleet", action="store_true",
                   help="fleet mode: the reconciler spawns the hosts "
                        "and the bench churns the target mid-storm")
    args = p.parse_args(argv)
    if args.fleet:
        out = measure_fleet(
            seconds=args.seconds, clients=args.clients, keys=args.keys,
            numel=args.numel, replicas=args.replicas,
            staleness=args.staleness or 0.1)
        print(json.dumps(out))
        return 0 if out["failed_reads"] == 0 else 1
    if args.hosts > 0:
        out = measure_distributed(
            hosts=args.hosts, seconds=args.seconds, clients=args.clients,
            keys=args.keys, numel=args.numel,
            replicas=min(args.replicas, args.hosts),
            staleness=args.staleness)
        print(json.dumps(out))
        return 0 if out["failed_reads"] == 0 else 1
    out = measure(seconds=args.seconds, clients=args.clients,
                  keys=args.keys, numel=args.numel,
                  replicas=args.replicas, staleness=args.staleness)
    out["delta"] = delta_check()
    print(json.dumps(out))
    return 0 if (out["failed_reads"] == 0 and out["delta"]["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
