"""Serve bench: pulls/sec + p99 pull latency under concurrent pushes.

The read-dimension headline the bench trajectory ignored until ISSUE 9:
every prior figure measures push GB/s.  This tool stands up the
parameter-serving plane (server/serving.py) over a live KV store, keeps
a TRAINING pusher thread summing deltas and cutting snapshots the whole
time, and drives N concurrent pull clients — reporting:

- ``pulls_per_s``     — aggregate client pull throughput
- ``p50_ms`` / ``p99_ms`` — per-pull latency quantiles (client-observed,
  cache hits included when ``--staleness`` > 0: that IS the product's
  latency story)
- ``pushes_per_s``    — the write load sustained while serving
- ``delta``           — a controlled wire-byte accounting check proving
  a delta pull ships ONLY changed keys' encoded bytes (O(churn), not
  O(model))

Usage:  python tools/serve_bench.py [--seconds S] [--clients N]
            [--keys K] [--numel E] [--replicas R] [--staleness SEC]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def delta_check(numel: int = 4096, keys: int = 4) -> dict:
    """Deterministic byte accounting: a full hydration costs the whole
    model; a delta pull after ONE changed key costs exactly that key's
    encoded bytes.  Returns the measured figures plus ``ok``."""
    import numpy as np

    from byteps_tpu.server.kv_store import KVStore
    from byteps_tpu.server.serve_client import PullClient
    from byteps_tpu.server.serving import ServingPlane

    store = KVStore()
    names = [f"serve.delta.{i}" for i in range(keys)]
    for n in names:
        store.init_key(n, np.zeros(numel, np.float32))
        store.push_delta(n, np.ones(numel, np.float32))
    plane = ServingPlane(store, replicas=1, retention=8)
    plane.cut()
    client = PullClient(plane, max_staleness_s=0.0)
    client.pull()
    full_bytes = client.bytes_received
    store.push_delta(names[0], np.ones(numel, np.float32))
    plane.cut()
    client.pull()
    delta_bytes = client.bytes_received - full_bytes
    key_bytes = numel * 4
    return {"model_bytes": keys * key_bytes,
            "full_pull_bytes": full_bytes,
            "delta_pull_bytes": delta_bytes,
            "changed_key_bytes": key_bytes,
            "ok": (full_bytes == keys * key_bytes
                   and delta_bytes == key_bytes)}


def measure(*, seconds: float = 2.0, clients: int = 4, keys: int = 8,
            numel: int = 65536, replicas: int = 3,
            staleness: float = 0.0) -> dict:
    """The concurrent-read/write measurement.  One pusher thread keeps
    training pushes landing (one cut per full key sweep, the per-step
    publication pattern); ``clients`` threads pull as fast as they can
    under the given staleness bound."""
    import numpy as np

    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.server.kv_store import KVStore
    from byteps_tpu.server.serve_client import PullClient
    from byteps_tpu.server.serving import ServingPlane

    store = KVStore()
    names = [f"serve.bench.{i}" for i in range(keys)]
    rng = np.random.RandomState(0)
    for n in names:
        store.init_key(n, rng.randn(numel).astype(np.float32))
    plane = ServingPlane(store, replicas=replicas, retention=16)
    plane.cut()
    # warm the hot-key histogram so replicas participate from the start
    warm = PullClient(plane, max_staleness_s=0.0)
    warm.pull()
    plane.cut()

    stop = threading.Event()
    pushes = [0]

    def pusher():
        delta = np.ones(numel, np.float32) * 1e-3
        i = 0
        while not stop.is_set():
            store.push_delta(names[i % keys], delta)
            pushes[0] += 1
            i += 1
            if i % keys == 0:
                plane.cut()

    lat_lock = threading.Lock()
    latencies: list = []
    pull_counts = [0] * clients
    errors = [0]

    def puller(idx: int):
        client = PullClient(plane, max_staleness_s=staleness)
        mine = []
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                client.pull()
            except Exception:  # noqa: BLE001 — an erroring read is the
                # one thing the plane promises not to produce
                errors[0] += 1
                continue
            mine.append((time.perf_counter() - t0) * 1e3)
            pull_counts[idx] += 1
        with lat_lock:
            latencies.extend(mine)

    push_thread = threading.Thread(target=pusher, daemon=True)
    threads = [threading.Thread(target=puller, args=(i,), daemon=True)
               for i in range(clients)]
    c0 = counters.get("serve.cache_hits")
    t0 = time.perf_counter()
    push_thread.start()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    push_thread.join(timeout=10)
    wall = time.perf_counter() - t0

    total_pulls = sum(pull_counts)
    lat = np.asarray(latencies) if latencies else np.asarray([0.0])
    return {
        "seconds": round(wall, 3),
        "clients": clients,
        "keys": keys,
        "numel": numel,
        "replicas": replicas,
        "staleness_s": staleness,
        "pulls": total_pulls,
        "pulls_per_s": round(total_pulls / wall, 1),
        "p50_ms": round(float(np.percentile(lat, 50)), 3),
        "p99_ms": round(float(np.percentile(lat, 99)), 3),
        "pushes": pushes[0],
        "pushes_per_s": round(pushes[0] / wall, 1),
        "failed_reads": errors[0],
        "cache_hits": counters.get("serve.cache_hits") - c0,
        "replica_reads": counters.get("serve.replica_reads"),
        "primary_reads": counters.get("serve.primary_reads"),
        "snapshot_cuts": counters.get("serve.snapshot_cuts"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float, default=3.0)
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--keys", type=int, default=8)
    p.add_argument("--numel", type=int, default=65536)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--staleness", type=float, default=0.0)
    args = p.parse_args(argv)
    out = measure(seconds=args.seconds, clients=args.clients,
                  keys=args.keys, numel=args.numel,
                  replicas=args.replicas, staleness=args.staleness)
    out["delta"] = delta_check()
    print(json.dumps(out))
    return 0 if (out["failed_reads"] == 0 and out["delta"]["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
