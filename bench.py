"""Benchmark: BERT-large MLM training throughput through byteps_tpu.

The reference's headline benchmark is BERT-large pretraining throughput /
scaling efficiency (reference README.md:35-41; BASELINE.md).  This harness
runs the fused data-parallel train step (forward + backward + push_pull +
adamw) on whatever devices are visible and prints ONE JSON line (the last
stdout line) with the headline metric plus secondary metrics:

    {"metric": ..., "value": N, "unit": "examples/s", "vs_baseline": N,
     "mfu": ..., "push_pull_gbps": {...}, "onebit_pallas": {...}}

Robustness (round-1 lesson, VERDICT.md "What's weak" #1): the TPU backend
init can hang forever or raise transiently.  The outer process never touches
JAX directly — it probes the backend in a subprocess with a timeout, runs
the real bench in a subprocess, and on terminal failure falls back to a
CPU-smoke run so the driver always records a parseable line.

Chip-drop salvage (round-4 lesson): the tunneled chip can probe green and
then drop mid-run, hanging the inner process inside a device call that no
in-process timeout can interrupt.  The inner therefore streams each
completed section as a flushed ``BENCH_SECTION`` stdout line; on timeout
the outer salvages them into a ``"partial": true`` result naming the hung
section, so a half-green window still yields TPU evidence.  On TPU the
engine-path section runs FIRST (cheapest compiles, and the open question
since the round-3 engine rework), before the multi-minute BERT-large
compile.

Baseline bookkeeping: the first green TPU run writes its per-chip
examples/s into BASELINE_MEASURED.json; later runs report vs_baseline
against it so the BENCH_r{N}.json series shows drift.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
MEASURED_BASELINE_FILE = os.path.join(REPO, "BASELINE_MEASURED.json")

# Approximate peak bf16 matmul FLOP/s per chip, by device_kind substring.
# Public numbers: v5e 197T, v5p 459T, v6e (Trillium) 918T, v4 275T, v3 123T.
_PEAK_FLOPS = (
    ("v6e", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12), ("v5litepod", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _peak_flops(device_kind: str):
    dk = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in dk:
            return peak
    return None


# --------------------------------------------------------------------------
# Inner bench (runs in a subprocess whose backend is already decided)
# --------------------------------------------------------------------------

def _bench_train_step(devices):
    """Headline: fused DP train-step throughput on the flagship model."""
    import jax
    import numpy as np
    import optax

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.models.bert import (BertForMLM, bert_large, bert_tiny,
                                        mlm_loss, synthetic_batch)
    from byteps_tpu.parallel import make_dp_train_step, replicate, shard_batch

    on_tpu = devices[0].platform != "cpu"
    n = len(devices)
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)

    cfg = bert_large() if on_tpu else bert_tiny()
    seq_len = 128 if on_tpu else 32
    per_dev_batch = 32 if on_tpu else 2
    steps = 20 if on_tpu else 3

    model = BertForMLM(cfg)
    rng = jax.random.PRNGKey(0)
    global_batch = per_dev_batch * n
    batch = synthetic_batch(rng, cfg, batch=global_batch, seq_len=seq_len)
    params = model.init(rng, batch["input_ids"], batch["attention_mask"])
    n_params = int(sum(int(np.prod(x.shape))
                       for x in jax.tree.leaves(params)))

    def loss_fn(params, b):
        # gathered MLM head: vocab projection only on masked positions
        logits = model.apply(params, b["input_ids"], b["attention_mask"],
                             masked_positions=b["masked_positions"])
        return mlm_loss(logits, b["masked_labels"])

    tx = optax.adamw(1e-4)
    opt_state = tx.init(params)
    step = make_dp_train_step(comm, loss_fn, tx)
    params = replicate(comm, params)
    opt_state = replicate(comm, opt_state)
    batch = shard_batch(comm, batch)

    def run(k):
        nonlocal params, opt_state
        t0 = time.perf_counter()
        loss = None
        for _ in range(k):
            params, opt_state, loss = step(params, opt_state, batch)
        # Host transfers force completion; on the experimental axon
        # platform block_until_ready alone can return early.
        jax.block_until_ready((params, opt_state))
        lv = float(loss)
        return time.perf_counter() - t0, lv

    run(3)  # warmup/compile
    dt, lv = run(steps)
    dt2, lv = run(steps)
    dt = min(dt, dt2)
    assert np.isfinite(lv), "non-finite loss"

    examples_per_sec = steps * global_batch / dt
    per_chip = examples_per_sec / n

    # Training FLOPs/example ~= 6 * N * T (fwd 2NT + bwd 4NT); the standard
    # transformer approximation used by the scaling literature.  N includes
    # embeddings (a few % overcount on BERT-large).
    flops_per_example = 6.0 * n_params * seq_len
    peak = _peak_flops(devices[0].device_kind) if on_tpu else None
    mfu = (per_chip * flops_per_example / peak) if peak else None
    return {
        "on_tpu": on_tpu,
        "per_chip": per_chip,
        "tokens_per_sec_per_chip": per_chip * seq_len,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "n_params": n_params,
        "seq_len": seq_len,
        "per_dev_batch": per_dev_batch,
        "device_kind": devices[0].device_kind,
        "n_devices": n,
    }


def _bench_push_pull(devices, on_tpu, emit=None):
    """Secondary: engine-path push_pull bandwidth (the product's own
    metric — BASELINE.json 'grad push_pull GB/s').  ``emit``, when given,
    receives the accumulated dict after every measurement (the bench's
    mid-section salvage stream).

    GB/s = logical gradient bytes / wall time, one direction.  The engine
    path includes host staging + partitioning + priority scheduling +
    per-chunk dispatch; 'fused' is the device-resident jitted reduction for
    comparison (what make_dp_train_step uses in-graph).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common.config import Config
    from byteps_tpu.core.engine import PushPullEngine

    n = len(devices)
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)

    def to_gbps(nbytes, times):
        """(median GB/s, [q25, q75] GB/s, median seconds) from per-rep
        seconds.  Per-rep MEDIAN, not total/mean: the dispatcher's
        group-merge width is timing-dependent, so a width can first
        appear mid-timing and drag a fresh XLA compile (seconds on the
        tunneled chip) into one rep; the median rejects that outlier,
        and the IQR carries the spread (the repo convention — every
        artifact shows its honesty term).  The raw median seconds feed
        the ablation window-economy guard without round-trip through the
        3-decimal GB/s rounding.  Rates divide by the UNROUNDED median
        seconds: the display rounding collapses sub-50 ns medians to 0
        and a rate computed from it would divide by zero, aborting the
        section's remaining sizes."""
        from tools._bench_util import quantile_stats_raw
        med_s, q25_s, q75_s = quantile_stats_raw(times)
        return (round(nbytes / med_s / 1e9, 3),
                [round(nbytes / q75_s / 1e9, 3),      # slow quartile ->
                 round(nbytes / q25_s / 1e9, 3)],     # low GB/s bound
                med_s)

    # The most recent engine run's auto-tuner snapshot (chunk/credit
    # choices): recorded into the section JSON so every round shows WHAT
    # the planner picked alongside how fast the pick ran.
    tuner = {}

    def _warm_to_steady_state(eng, push, nbytes, cap=24):
        """Warm until the planner locks its bucket (bounded): the timed
        reps then measure the tuned steady state — chunk size chosen,
        credits installed, every program compiled — not the exploration
        phase's dispatch patterns."""
        for _ in range(cap):
            push()
            if eng.planner.locked(nbytes):
                break
        tuner["snapshot"] = eng.planner.snapshot()

    def engine_gbps(nbytes, reps=5, **cfg_kw):
        cfg = Config(telemetry_on=False, trace_on=False, **cfg_kw)
        eng = PushPullEngine(comm, cfg)
        try:
            x = np.random.RandomState(0).randn(nbytes // 4).astype(np.float32)
            # declare-time AOT warm: the steady-state program set
            # compiles here, not inside a timed rep
            eng.declare_tensor("bench.pp", x.shape, np.float32)
            _warm_to_steady_state(
                eng, lambda: eng.push_pull_local(x, "bench.pp"), nbytes)
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                eng.push_pull_local(x, "bench.pp")
                times.append(time.perf_counter() - t0)
        finally:
            eng.shutdown(wait=False)
        return to_gbps(nbytes, times)

    def engine_device_gbps(nbytes, reps=5, **cfg_kw):
        """Engine path fed a device-resident stacked array: measures the
        engine itself (scheduler, partitioner, per-chunk dispatch,
        collective) without the host->device staging cost — the fair
        comparison against the fused path (round-1 weakness #4: the host
        round-trip must not be mistaken for engine overhead)."""
        cfg = Config(telemetry_on=False, trace_on=False, **cfg_kw)
        eng = PushPullEngine(comm, cfg)
        try:
            # (n, nbytes/4): every rank contributes nbytes, matching
            # engine_gbps's per-rank workload so the GB/s are comparable
            x = jax.device_put(
                jnp.zeros((n, nbytes // 4), jnp.float32),
                comm.stacked_sharding(extra_dims=1))
            eng.declare_tensor("bench.dev", (nbytes // 4,), np.float32,
                               local=False)
            _warm_to_steady_state(
                eng, lambda: jax.block_until_ready(
                    eng.push_pull(x, "bench.dev")), nbytes)
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                out = eng.push_pull(x, "bench.dev")
                jax.block_until_ready(out)
                times.append(time.perf_counter() - t0)
        finally:
            eng.shutdown(wait=False)
        return to_gbps(nbytes, times)

    def fused_gbps(nbytes, reps=10):
        """The exact collective the engine dispatches (push_pull_array on
        the stacked sharding), without the engine around it — so
        engine_device vs fused isolates the scheduling layer's cost on an
        identical workload."""
        from byteps_tpu.comm.collectives import push_pull_array
        x = jax.device_put(jnp.zeros((n, nbytes // 4), jnp.float32),
                           comm.stacked_sharding(extra_dims=1))
        push_pull_array(comm, x, op="sum").block_until_ready()
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            push_pull_array(comm, x, op="sum").block_until_ready()
            times.append(time.perf_counter() - t0)
        return to_gbps(nbytes, times)

    def dispatch_amortization(nchunks=64):
        """Deterministic dispatch-count datum (VERDICT r4 task 3): the
        same multi-chunk push through both dispatcher modes with the
        dispatcher paused until the queue holds every chunk, so the
        merge width is the mode's property, not a race."""
        counts = {}
        chunk_elems = 65536 // 4
        x = np.zeros(nchunks * chunk_elems, np.float32)
        for label, gs in (("group4", 4), ("drain", -1)):
            cfg = Config(telemetry_on=False, trace_on=False,
                         group_size=gs, partition_bytes=65536)
            eng = PushPullEngine(comm, cfg)
            try:
                eng.pause_dispatch()
                h = eng.push_pull_local_async(x, "bench.amort")
                eng.resume_dispatch()
                # bounded: a chip dying exactly here must cost two
                # minutes, not the whole inner budget (the sections after
                # this one are the expensive ones the window exists for)
                h.wait(timeout=120.0)
                counts[f"dispatches_{label}"] = eng.stats["dispatches"]
                counts[f"chunks_{label}"] = eng.stats["chunks"]
            finally:
                eng.shutdown(wait=False)
        return counts

    mb = 1024 * 1024
    sizes = [mb, 16 * mb, 256 * mb] if on_tpu else [mb, 8 * mb]
    out = {}

    med_s = {}

    def add(key, fn):
        # Stream each measurement as it lands: on hardware this section's
        # duration is itself the unknown under test (the engine path has
        # never run post-rework there), so a mid-section chip drop must
        # not lose the sizes already measured.  A RAISING drop (vs a hang)
        # annotates the error, keeps what was measured, and skips the
        # rest — the chip is gone; later sizes would only waste window.
        if "error" in out:
            return
        try:
            out[key], out[key + "_iqr"], med_s[key] = fn()
        except Exception as e:  # noqa: BLE001 - keep partial measurements
            out["error"] = f"{key}: {type(e).__name__}: {e}"[:300]
        if emit is not None:
            emit(dict(out))

    # fused ceiling first: it is the denominator every engine figure is
    # judged against, and the cheapest program of the lot.
    big = sizes[-1]
    add(f"fused_{big // mb}MB", lambda: fused_gbps(big))
    add(f"engine_device_{big // mb}MB", lambda: engine_device_gbps(big))
    for nbytes in sizes:
        add(f"engine_{nbytes // mb}MB", lambda n=nbytes: engine_gbps(n))
    # Drain-mode dispatch amortization (round-4 VERDICT task 3): the whole
    # eligible window executes as the fewest XLA programs (one chunk-
    # scatter program per contiguous run) — the ready answer if hardware
    # says per-chunk dispatch dominates the engine's rent.  Runs before
    # the window-economy gate on purpose: when the plain engine is slow
    # is exactly when this figure matters.  The device-resident variant
    # is the clean isolate (vs engine_device: same input, fewer
    # dispatches; no host-staging noise in the comparison).
    add(f"engine_grouped_{big // mb}MB",
        lambda: engine_gbps(big, group_size=-1))
    add(f"engine_device_grouped_{big // mb}MB",
        lambda: engine_device_gbps(big, group_size=-1))
    # Headline ratios (ISSUE 5 acceptance: engine >= 0.7x fused, from
    # 0.30x): the engine-vs-fused gap IS the metric this bench exists to
    # track, so it rides the compact summary line, not just the full
    # record.  The auto-tuner's chosen knobs land next to it — a
    # regression round can tell "the planner chose badly" apart from
    # "the path got slower".
    fused = out.get(f"fused_{big // mb}MB")
    for num, label in ((f"engine_{big // mb}MB", "engine_vs_fused_ratio"),
                       (f"engine_device_{big // mb}MB",
                        "engine_device_vs_fused_ratio")):
        if isinstance(fused, (int, float)) and fused > 0 \
                and isinstance(out.get(num), (int, float)):
            out[label] = round(out[num] / fused, 3)
    if tuner.get("snapshot") is not None:
        out["autotune"] = tuner["snapshot"]
    if emit is not None:
        emit(dict(out))
    if "error" not in out:  # same chip-gone gate as add(): once a drop
        try:                # is seen, stop touching the device
            out["dispatch_amortization"] = dispatch_amortization()
        except Exception as e:  # noqa: BLE001 - must not kill the sweep
            out["dispatch_amortization"] = {"error": str(e)[:200]}
        if emit is not None:
            emit(dict(out))
    # The three ablations are secondary to the headline engine figure; if
    # the hardware engine path is slow enough that each would eat minutes
    # of a possibly-short green window, skip them with the projection
    # recorded (each ablation costs ~8 calls: 3 warmup + 5 reps).
    headline_key = f"engine_{big // mb}MB"
    headline = out.get(headline_key)
    # measured median seconds, not the 3-decimal GB/s inverted (which
    # collapses anything under 0.0005 GB/s to a meaningless infinity)
    per_call_s = med_s.get(headline_key)
    if per_call_s is not None and per_call_s * 8 > 240.0:
        out["ablations_skipped"] = (
            f"projected {per_call_s * 8:.0f}s per ablation at "
            f"{headline} GB/s; window economy")
    else:
        add(f"engine_{big // mb}MB_no_partition",
            lambda: engine_gbps(big, partition_bytes=2**31 - 512))
        add(f"engine_{big // mb}MB_no_priority",
            lambda: engine_gbps(big, enable_priority=False))
        add(f"engine_{big // mb}MB_credit16MB",
            lambda: engine_gbps(big, scheduling_credit=16 * mb))
    return out


def _bench_resnet(devices):
    """Secondary: ResNet-50 synthetic images/s (the reference's other
    headline benchmark, docs/performance.md:3-12), via the fused DP step
    with cross-replica BatchNorm."""
    import jax
    import numpy as np
    import optax

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.models import resnet as R
    from byteps_tpu.parallel import shard_batch

    n = len(devices)
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)
    model = R.resnet50(axis_name=comm.dp_axes)
    rng = jax.random.PRNGKey(0)
    per_dev = 32
    batch = R.synthetic_images(rng, per_dev * n, 224, 1000)
    step, state = R.make_vision_trainer(
        comm, model, optax.sgd(0.1, momentum=0.9), batch, rng)
    batch = shard_batch(comm, batch)
    steps = 10

    def run(k):
        nonlocal state
        t0 = time.perf_counter()
        loss = None
        for _ in range(k):
            state, loss = step(state, batch)
        jax.block_until_ready(state)
        return time.perf_counter() - t0, float(loss)

    run(2)
    dt, loss = run(steps)
    assert np.isfinite(loss)
    return {"images_per_sec_per_chip": round(steps * per_dev / dt, 1),
            "batch_per_chip": per_dev}


def _bench_dcn_compare():
    """Compressed vs plain DCN hop on a (dcn=2, ici=4) CPU mesh (round-1
    VERDICT item 5): wall time of hierarchical_push_pull with and without
    the onebit DCN compression, plus the per-rank wire bytes each compiled
    program moves over each axis (from the HLO — the wire contract a real
    2-slice pod would execute)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from byteps_tpu.ops.collective_ops import (hierarchical_push_pull,
                                               make_onebit_pair,
                                               make_powersgd_pair)
    from byteps_tpu.utils.hlo_wire import dcn_ici_bytes

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dcn", "ici"))
    # 4 MB of f32 per rank: the wire-bytes ratio (the point of this
    # section) comes from the HLO and is size-independent; small keeps the
    # CPU-mesh run inside the smoke-test budget on a loaded host.
    n = 1 << 20

    def build(pair):
        c, d = pair() if pair else (None, None)

        def body(x):
            # compress_min_bytes=0: this section's point IS the compressed
            # wire contract, and the small benchmark shard (1 MB/device)
            # sits under the default economic gate that would otherwise
            # silently fall back to the plain path (ratio 1.0 artifact).
            return hierarchical_push_pull(x[0], op="sum", compress=c,
                                          decompress=d, compress_min_bytes=0)
        f = jax.jit(jax.shard_map(body, mesh=mesh,
                                  in_specs=P(("dcn", "ici")),
                                  out_specs=P(), check_vma=False))
        x = jnp.zeros((8, n), jnp.float32)
        return f, x, f.lower(x).compile().as_text()

    out = {}
    for tag, pair in (("plain", None), ("onebit_dcn", make_onebit_pair),
                      ("powersgd_dcn", make_powersgd_pair)):
        f, x, hlo = build(pair)
        f(x).block_until_ready()
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            r = f(x)
        r.block_until_ready()
        dt = time.perf_counter() - t0
        dcn_b, ici_b = dcn_ici_bytes(hlo, n_ici=4)
        out[tag] = {"ms_per_call": round(dt / reps * 1e3, 2),
                    "dcn_bytes_per_rank": dcn_b,
                    "ici_bytes_per_rank": ici_b}
    p, c = out["plain"], out["onebit_dcn"]
    out["dcn_wire_ratio"] = round(
        p["dcn_bytes_per_rank"] / max(1, c["dcn_bytes_per_rank"]), 1)
    out["dcn_wire_ratio_powersgd"] = round(
        p["dcn_bytes_per_rank"]
        / max(1, out["powersgd_dcn"]["dcn_bytes_per_rank"]), 1)
    return out


def _bench_pallas(devices):
    """On real TPU: compile the onebit Pallas kernels non-interpreted,
    bit-compare against the portable numpy refs, and time them (round-1
    weakness #5: the kernels had never run on hardware)."""
    import jax.numpy as jnp
    import numpy as np

    from byteps_tpu.ops import pallas_kernels as pk
    from tests import compression_refs as refs

    try:
        numel = 32 * 128 * 1024  # 16 MiB of f32
        rng = np.random.RandomState(3)
        x = rng.randn(numel).astype(np.float32)
        L = pk.padded_lanes(numel)
        x2d = jnp.pad(jnp.asarray(x), (0, 32 * L - numel)).reshape(32, L)

        words, abs_sum = pk.onebit_pack(x2d)  # non-interpret: Mosaic
        words.block_until_ready()
        ref_words, ref_scale = refs.onebit_compress(x, scaling=True)
        bitexact = bool(np.array_equal(np.asarray(words), ref_words))

        out2d = pk.onebit_unpack(words, abs_sum / numel)
        out2d.block_until_ready()
        ref_dec = refs.onebit_decompress(ref_words, ref_scale, numel)
        got_dec = np.asarray(out2d).reshape(-1)[:numel]
        bitexact = bitexact and bool(
            np.allclose(got_dec, ref_dec, rtol=1e-6))

        def _time(fn, reps=20):
            t0 = time.perf_counter()
            r = None
            for _ in range(reps):
                r = fn()
            jnp.asarray(
                r[0] if isinstance(r, tuple) else r).block_until_ready()
            return time.perf_counter() - t0

        nbytes = numel * 4
        dt_pack = _time(lambda: pk.onebit_pack(x2d))
        dt_unpack = _time(lambda: pk.onebit_unpack(words, abs_sum / numel))
        return {
            "bitexact_vs_ref": bitexact,
            "pack_gbps": round(20 * nbytes / dt_pack / 1e9, 2),
            "unpack_gbps": round(20 * nbytes / dt_unpack / 1e9, 2),
        }
    except Exception as e:  # noqa: BLE001 - Mosaic may reject on axon
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def _bench_flash(devices, emit=None):
    """On real TPU: flash-attention Pallas kernels vs XLA exact attention
    at long context (the regime the kernels exist for), forward and
    forward+backward, timed as scan-chained calls so the tunneled chip's
    host round-trip amortizes away.  ``emit`` streams the accumulated
    dict after each timed chain (each carries a multi-minute compile, so
    a chip drop mid-section should keep the chains already measured)."""
    import jax
    import jax.numpy as jnp

    from byteps_tpu.ops.flash_attention import flash_attention
    from byteps_tpu.parallel import full_attention

    try:
        # TPU: the long-context regime.  CPU (smoke/test only; the bench
        # skips this section off-TPU): tiny shapes the interpreter can
        # finish, exercising the same chains and emission protocol.
        on_cpu = devices[0].platform == "cpu"
        b, t, h, d = (1, 512, 2, 64) if on_cpu else (4, 4096, 16, 128)
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (b, t, h, d), jnp.bfloat16)
        k = jax.random.normal(ks[1], (b, t, h, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, t, h, d), jnp.bfloat16)
        reps = 2 if on_cpu else 10

        def fwd_chain(attn):
            def f(q, k, v):
                def body(c, _):
                    return attn(c, k, v), None
                out, _ = jax.lax.scan(body, q, None, length=reps)
                return jnp.sum(out.astype(jnp.float32))
            return jax.jit(f)

        def bwd_chain(attn):
            # grad w.r.t. all of (q, k, v): differentiating q alone would
            # let XLA dead-code the exact path's dK/dV branches while the
            # flash custom_vjp always computes all three — unequal work.
            def f(q, k, v):
                def body(c, _):
                    gq, gk, gv = jax.grad(
                        lambda qq, kk, vv: jnp.sum(
                            attn(qq, kk, vv).astype(jnp.float32)),
                        argnums=(0, 1, 2))(c, k, v)
                    nxt = (gq + gk + gv).astype(c.dtype)
                    return nxt, None
                out, _ = jax.lax.scan(body, q, None, length=reps)
                return jnp.sum(out.astype(jnp.float32))
            return jax.jit(f)

        def timeit(f):
            float(f(q, k, v))  # warm + forces completion through the host
            t0 = time.perf_counter()
            float(f(q, k, v))
            return (time.perf_counter() - t0) / reps * 1e3

        flash = lambda q, k, v: flash_attention(q, k, v, causal=True)  # noqa: E731
        exact = lambda q, k, v: full_attention(q, k, v, causal=True)  # noqa: E731
        diff = float(jnp.max(jnp.abs(
            flash(q[:1, :512], k[:1, :512], v[:1, :512]).astype(jnp.float32)
            - exact(q[:1, :512], k[:1, :512],
                    v[:1, :512]).astype(jnp.float32))))
        out = {"shape": f"b{b} t{t} h{h} d{d} bf16 causal",
               "max_diff_vs_exact": round(diff, 4)}

        def add(key, f):
            # Same raising-drop contract as _bench_push_pull.add: keep the
            # chains already measured, annotate, skip the rest.
            if "error" in out:
                return
            try:
                out[key] = round(timeit(f), 2)
            except Exception as e:  # noqa: BLE001 - keep partial chains
                out["error"] = f"{key}: {type(e).__name__}: {e}"[:300]
            if emit is not None:
                emit(dict(out))

        add("fwd_ms", fwd_chain(flash))
        add("fwd_exact_ms", fwd_chain(exact))
        add("fwd_bwd_ms", bwd_chain(flash))
        add("fwd_bwd_exact_ms", bwd_chain(exact))
        if "fwd_ms" in out and "fwd_exact_ms" in out:
            out["fwd_speedup"] = round(
                out["fwd_exact_ms"] / out["fwd_ms"], 2)
        if "fwd_bwd_ms" in out and "fwd_bwd_exact_ms" in out:
            out["fwd_bwd_speedup"] = round(
                out["fwd_bwd_exact_ms"] / out["fwd_bwd_ms"], 2)
        return out
    except Exception as e:  # noqa: BLE001 - secondary metric only
        return {"error": f"{type(e).__name__}: {e}"[:300]}


def _bench_tpu_overlap(devices):
    """On real TPU: does engine traffic hide behind device-busy compute?

    The single-chip projection of the cross-barrier pipelining claim
    (reference docs/best-practice.md:7, '0-15%' end-to-end): the engine's
    host-side staging + chunk dispatch runs on engine threads, so an
    async push_pull issued before a train step should cost
    max(compute, comm) wall-clock, not compute + comm.  The 1-core build
    host cannot show this (tools/overlap_bench.py records the negative
    honestly); the chip can — device programs run while the host stages.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common.config import Config
    from byteps_tpu.core.engine import PushPullEngine

    n = len(devices)
    on_cpu = devices[0].platform == "cpu"
    # TPU: ~10 ms of MXU work vs a 16 MB gradient.  CPU (smoke/test only,
    # the bench calls this section on TPU): scaled way down so the 1-core
    # host finishes in seconds.
    dim, depth, grad_elems, reps = ((256, 4, 1 << 18, 3) if on_cpu
                                    else (4096, 16, 4 * (1 << 20), 10))
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)
    eng = PushPullEngine(comm, Config(telemetry_on=False, trace_on=False))
    try:
        w = jax.random.normal(jax.random.PRNGKey(0), (dim, dim),
                              jnp.bfloat16)

        @jax.jit
        def compute(x):
            def body(c, _):
                return jnp.tanh(c @ w), None
            out, _ = jax.lax.scan(body, x, None, length=depth)
            return out

        x = jax.random.normal(jax.random.PRNGKey(1), (dim, dim),
                              jnp.bfloat16)
        grad = np.random.RandomState(2).randn(grad_elems).astype(
            np.float32)  # host gradient, the adapter-realistic input

        def comm_only():
            eng.push_pull_local(grad, "ov.g")

        def serial():
            compute(x).block_until_ready()
            eng.push_pull_local(grad, "ov.g")

        def pipelined():
            h = eng.push_pull_local_async(grad, "ov.g")
            compute(x).block_until_ready()
            h.wait()
            eng.handles.release(h.id)

        def timeit(fn):
            # per-rep median + IQR (same rationale and convention as
            # _bench_push_pull.to_gbps): the engine modes can hit a
            # timing-dependent group-merge recompile mid-measurement; the
            # median rejects that rep and the bracket shows the spread.
            # digits=4: the CPU smoke path's sub-ms times must not
            # quantize to zero.
            from tools._bench_util import quantile_stats
            fn()  # warm (compile + engine program cache)
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            return quantile_stats(times, digits=4)

        out = {"grad_mb": grad_elems * 4 // (1 << 20)}

        def add_t(key, fn):
            out[key + "_ms"], out[key + "_ms_iqr"] = timeit(fn)

        add_t("compute", lambda: compute(x).block_until_ready())
        add_t("comm", comm_only)
        add_t("serial", serial)
        add_t("pipelined", pipelined)
        hideable = min(out["compute_ms"], out["comm_ms"])
        out["overlap_fraction"] = (
            round((out["serial_ms"] - out["pipelined_ms"]) / hideable, 3)
            if hideable > 0 else None)
        out["note"] = ("async engine push_pull issued before a ~%d ms "
                       "device compute; overlap_fraction = recovered / "
                       "min(compute, comm)" % round(out["compute_ms"]))
        return out
    except Exception as e:  # noqa: BLE001 - secondary metric only
        return {"error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        eng.shutdown(wait=False)


def _bf16_composite_body():
    """Train the bf16 (fsdp, tp) Llama composite a few steps on the
    CURRENT backend and return the loss trajectory (round-3 VERDICT
    task 7: bf16 composite loss from either backend).  Mesh sizing:
    tp=2 when possible, and fsdp clamped to a divisor of the batch (8)
    so odd device counts don't fail the batch sharding."""
    import jax
    import optax

    from byteps_tpu.models.llama import Llama, llama_tiny
    from byteps_tpu.parallel.fsdp_tp import (
        init_llama_opt_state, make_fsdp_tp_mesh, make_fsdp_tp_train_step,
        shard_llama_batch, shard_llama_params)
    from byteps_tpu.parallel.long_context import synthetic_lm_batch

    devs = jax.devices()
    n_tp = 2 if len(devs) >= 2 else 1
    fsdp = max(f for f in (1, 2, 4, 8) if f <= len(devs) // n_tp)
    mesh = make_fsdp_tp_mesh(devs[:fsdp * n_tp], n_tp=n_tp)
    cfg = llama_tiny()
    model = Llama(cfg)
    rng = jax.random.PRNGKey(0)
    batch = synthetic_lm_batch(rng, cfg, batch=8, seq_len=16)
    params = shard_llama_params(mesh,
                                model.init(rng, batch["input_ids"][:1]))
    tx = optax.adam(1e-2)
    opt = init_llama_opt_state(tx, params)
    step = make_fsdp_tp_train_step(mesh, cfg, tx)
    b = shard_llama_batch(mesh, batch)
    losses = []
    for _ in range(8):
        params, opt, loss = step(params, opt, b)
        losses.append(round(float(loss), 4))
    return {"dtype": "bfloat16", "mesh": f"fsdp={fsdp} x tp={n_tp}",
            "platform": devs[0].platform, "losses": losses,
            "decreased": losses[-1] < losses[0]}


def _bench_bf16_fsdp_tp(on_tpu: bool):
    """bf16 (fsdp, tp) composite section, backend-appropriate isolation.

    On TPU: in-process — libtpu is exclusive to this process, so a child
    could never open the chip; the GSPMD jit path has no known process-
    killing failure there (the CHECK crash is the CPU emitter's
    partial-manual shard_map path, tests/test_three_d.py canary).
    On CPU: subprocess-isolated against exactly that CHECK, on the
    virtual 8-device mesh."""
    if on_tpu:
        try:
            return _bf16_composite_body()
        except Exception as e:  # noqa: BLE001 - section must not kill bench
            return {"error": f"{type(e).__name__}: {e}"[:300]}
    import subprocess
    code = ("import os, json\n"
            "flags = os.environ.get('XLA_FLAGS', '')\n"
            "if 'host_platform_device_count' not in flags:\n"
            "    os.environ['XLA_FLAGS'] = (flags +"
            " ' --xla_force_host_platform_device_count=8').strip()\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import bench\n"
            "print('BF16_FSDP_TP ' +"
            " json.dumps(bench._bf16_composite_body()))\n")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=600,
                           cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return {"error": "bf16 composite subprocess timed out"}
    for line in p.stdout.splitlines():
        if line.startswith("BF16_FSDP_TP "):
            return json.loads(line.split(" ", 1)[1])
    return {"error": (f"rc={p.returncode}: "
                      + (p.stderr or p.stdout or "")[-300:]),
            "canary": "tests/test_three_d.py tracks the related XLA bug"}


def _bench_bf16_three_d(devices):
    """bf16 (dp, pp, tp) composite on the available devices (round-4
    VERDICT task 8).  On the CPU emitter the bf16 partial-manual psum
    CHECK-crashes the process (tests/test_three_d.py canary keeps the
    repro hot), so the 3D path pins f32 there; real Mosaic is expected to
    be unaffected — this section is the hardware evidence.  Axis sizes
    adapt to the device count: a pod runs real (dp, pp, tp); a single
    chip degenerates to (1, 1, 1), where the full 3D program (GPipe scan,
    auto-tp GSPMD annotations, the psum pattern) still compiles and
    trains in bf16 with trivial collectives — the note records which
    regime the losses came from."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from byteps_tpu.models.gpt import gpt_tiny
    from byteps_tpu.parallel import (init_3d_opt_state, make_3d_mesh,
                                     make_dp_pp_tp_train_step,
                                     shard_3d_batch, shard_3d_params,
                                     synthetic_lm_batch)
    from byteps_tpu.parallel.pipeline import init_pipeline_params

    n = len(devices)
    n_pp = 2 if n % 2 == 0 else 1       # gpt_tiny has 2 layers
    n_tp = 2 if n % (n_pp * 2) == 0 else 1
    dp = n // (n_pp * n_tp)
    cfg = dataclasses.replace(gpt_tiny(), dtype=jnp.bfloat16)
    mesh = make_3d_mesh(devices, n_pp=n_pp, n_tp=n_tp)
    rng = jax.random.PRNGKey(0)
    batch = synthetic_lm_batch(rng, cfg, batch=4 * dp, seq_len=16)
    params = shard_3d_params(
        mesh, init_pipeline_params(cfg, rng, batch["input_ids"][:1]))
    tx = optax.sgd(0.1)
    opt = init_3d_opt_state(tx, params)
    step = make_dp_pp_tp_train_step(mesh, cfg, tx, num_microbatches=2)
    b = shard_3d_batch(mesh, batch)
    losses = []
    for _ in range(8):
        params, opt, loss = step(params, opt, b)
        losses.append(round(float(loss), 4))
    # the note must describe the axes actually exercised: 2 chips give
    # (1, 2, 1) — a single non-trivial axis, not "multi-axis" evidence
    live_axes = [f"{name}={size}" for name, size in
                 (("dp", dp), ("pp", n_pp), ("tp", n_tp)) if size > 1]
    return {
        "dtype": "bfloat16",
        "mesh": f"dp={dp} x pp={n_pp} x tp={n_tp}",
        "platform": devices[0].platform,
        "losses": losses,
        "decreased": losses[-1] < losses[0],
        "note": ("collectives trivial at (1,1,1); the multi-axis wire "
                 "pattern stays covered in f32 by dryrun_multichip"
                 if not live_axes else
                 f"bf16 collectives over {', '.join(live_axes)}"),
    }


def _emit_section(key, value):
    """Stream a completed section to stdout immediately (flushed through
    the pipe) so the outer process can salvage it if the tunneled chip
    drops mid-run and the rest of the bench hangs (round-3 lesson: the
    chip went green at round start, hung 25 min into the first compile,
    and the whole monolithic run was lost)."""
    print("BENCH_SECTION " + json.dumps({"key": key, "value": value}),
          flush=True)


def _mark_start(key):
    """Announce a section before it runs, so a hang is attributable."""
    print("BENCH_SECTION_START " + key, flush=True)


def _emit_progress(key, value):
    """Stream a section's accumulated state mid-run.  Salvage keeps the
    last progress value unless the section completed (a full
    BENCH_SECTION line wins), and a section that died mid-stream still
    counts as the hung one."""
    print("BENCH_SECTION_PROGRESS " + json.dumps(
        {"key": key, "value": value}), flush=True)


def _load_measured_baseline():
    if os.path.exists(MEASURED_BASELINE_FILE):
        try:
            with open(MEASURED_BASELINE_FILE) as f:
                return json.load(f).get("per_chip_examples_per_sec")
        except Exception:  # noqa: BLE001
            return None
    return None


def _assemble(sections, note="", write_baseline=True):
    """Build the single result line from whatever sections completed.

    Used by the inner process for a full run and by the outer process to
    reconstruct a partial run from salvaged BENCH_SECTION lines; a TPU
    run whose headline train section never finished still reports every
    completed TPU section, with value 0.0 and the hang noted.
    ``write_baseline`` is False on the salvage path: an aborted window
    must not seed BASELINE_MEASURED before a complete retry can."""
    train = sections.get("train")
    train_err = None
    if isinstance(train, dict) and "per_chip" not in train:
        train_err = train.get("error", "train section incomplete")
        train = None
    dev = sections.get("device") or {}
    on_tpu = bool(dev.get("on_tpu", (train or {}).get("on_tpu")))

    baseline = _load_measured_baseline()
    if on_tpu and train and baseline is None and write_baseline:
        # First green TPU run: record the measured baseline for later rounds.
        with open(MEASURED_BASELINE_FILE, "w") as f:
            json.dump({
                "per_chip_examples_per_sec": round(train["per_chip"], 2),
                "device_kind": train["device_kind"],
                "recorded": time.strftime("%Y-%m-%d"),
                "config": {"model": "bert_large", "seq_len": train["seq_len"],
                           "per_dev_batch": train["per_dev_batch"]},
            }, f, indent=1)
        baseline = train["per_chip"]

    per_chip = train["per_chip"] if train else 0.0
    result = {
        "metric": ("bert_large_mlm_train_throughput_per_chip" if on_tpu
                   else "bert_tiny_cpu_smoke_throughput_per_chip"),
        "value": round(per_chip, 2),
        "unit": "examples/s",
        "vs_baseline": (round(per_chip / baseline, 3)
                        if (on_tpu and train and baseline) else 0.0),
        "mfu": train["mfu"] if train else None,
        "tokens_per_sec_per_chip": (
            round(train["tokens_per_sec_per_chip"], 1) if train else 0.0),
        "device": (train or dev).get("device_kind", "unknown"),
        "n_devices": (train or dev).get("n_devices", 0),
        "push_pull_gbps": sections.get("push_pull_gbps",
                                       {"skipped": "not reached"}),
        "onebit_pallas": sections.get("onebit_pallas",
                                      {"skipped": "not reached"}),
        "flash_attention": sections.get("flash_attention",
                                        {"skipped": "not reached"}),
        "bf16_fsdp_tp": sections.get("bf16_fsdp_tp",
                                     {"skipped": "not reached"}),
    }
    for opt in ("resnet50", "dcn_compare", "tpu_overlap", "bf16_three_d"):
        if sections.get(opt) is not None:
            result[opt] = sections[opt]
    notes = [n for n in (note, train_err and f"train: {train_err}") if n]
    if notes:
        result["error"] = "; ".join(notes)
    return result


def inner_main() -> int:
    """Full bench; assumes the backend choice was made by the environment."""
    import jax

    note = os.environ.get("_BPS_BENCH_NOTE", "")
    if os.environ.get("_BPS_BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")

    if os.environ.get("_BPS_BENCH_ONLY") == "dcn":
        # standalone mode: the (dcn=2, ici=4) comparison needs 8 devices,
        # so on a single-chip TPU run the outer process re-invokes this on
        # the virtual CPU mesh.
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps({"dcn_compare": _bench_dcn_compare()}))
        return 0

    devices = jax.devices()
    on_tpu = devices[0].platform != "cpu"

    sections = {}

    def section(key, fn, *args):
        _mark_start(key)
        try:
            val = fn(*args)
        except Exception as e:  # noqa: BLE001 - one section must not kill
            val = {"error": f"{type(e).__name__}: {e}"[:300]}  # the rest
        sections[key] = val
        _emit_section(key, val)
        return val

    def push_pull_section(key="push_pull_gbps"):
        section(key, lambda: _bench_push_pull(
            devices, on_tpu, emit=lambda v: _emit_progress(key, v)))

    section("device", lambda: {"device_kind": devices[0].device_kind,
                               "n_devices": len(devices), "on_tpu": on_tpu})
    if on_tpu:
        # Cheapest-compile, highest-evidence sections first: if the
        # tunneled chip drops mid-run, the engine-path numbers (the open
        # perf question since the r3 rework) are salvaged before the
        # multi-minute BERT-large compile is even attempted.
        push_pull_section()
        section("tpu_overlap", _bench_tpu_overlap, devices)
        section("onebit_pallas", _bench_pallas, devices)
        section("flash_attention", lambda: _bench_flash(
            devices, emit=lambda v: _emit_progress("flash_attention", v)))
        section("train", _bench_train_step, devices)
        section("resnet50", _bench_resnet, devices)
        section("bf16_fsdp_tp", _bench_bf16_fsdp_tp, on_tpu)
        # bf16 3D runs ONLY where the emitter survives it: real Mosaic
        # (any chip count) — on CPU the partial-manual psum would kill
        # the process at multi-device axis sizes (canary test_three_d.py)
        section("bf16_three_d", _bench_bf16_three_d, devices)
    else:
        for key in ("onebit_pallas", "flash_attention"):
            sections[key] = {"skipped": "cpu run"}
            _emit_section(key, sections[key])
        sections["bf16_three_d"] = {
            "skipped": "cpu run: bf16 partial-manual psum CHECK-crashes "
                       "the CPU emitter (tests/test_three_d.py canary); "
                       "the 3D composite runs f32 in dryrun_multichip"}
        _emit_section("bf16_three_d", sections["bf16_three_d"])
        section("train", _bench_train_step, devices)
        push_pull_section()
        section("bf16_fsdp_tp", _bench_bf16_fsdp_tp, on_tpu)
        if len(devices) >= 8:
            section("dcn_compare", _bench_dcn_compare)

    print(json.dumps(_assemble(sections, note)))
    return 0


# --------------------------------------------------------------------------
# Outer orchestration: probe -> run -> fallback.  Never imports jax.
# --------------------------------------------------------------------------

_PROBE_CODE = (
    "import jax, json;"
    "ds = jax.devices();"
    "print('PROBE ' + json.dumps({'platform': ds[0].platform,"
    " 'n': len(ds), 'kind': ds[0].device_kind}))"
)


def _probe(timeout: float):
    try:
        p = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                           capture_output=True, text=True, timeout=timeout,
                           cwd=REPO)
    except subprocess.TimeoutExpired:
        return None, "backend init timed out after %ds" % timeout
    for line in p.stdout.splitlines():
        if line.startswith("PROBE "):
            return json.loads(line[len("PROBE "):]), None
    tail = (p.stderr or p.stdout or "").strip().splitlines()
    return None, (tail[-1] if tail else f"probe rc={p.returncode}")


# Full TPU bench budget: the section list (engine sweep + overlap +
# pallas + flash chains + BERT-large + resnet + bf16 composite) sums to
# ~25-35 min at tunneled-chip speeds.  A hang wastes at most this long
# before salvage returns the streamed sections, so the cost of headroom
# is bounded; too-tight a budget cuts off the tail sections instead.
_INNER_TIMEOUT = 3000.0


def _sections_from_stdout(text):
    """Salvage completed BENCH_SECTION lines from a killed inner run.
    Returns (sections, hung_section): the section that had started but
    never completed is where the chip (or compile) hung."""
    done, progress, started = {}, {}, None
    for ln in (text or "").splitlines():
        if ln.startswith("BENCH_SECTION_START "):
            started = ln[len("BENCH_SECTION_START "):].strip()
            continue
        for prefix, store in (("BENCH_SECTION_PROGRESS ", progress),
                              ("BENCH_SECTION ", done)):
            if ln.startswith(prefix):
                try:
                    doc = json.loads(ln[len(prefix):])
                    store[doc["key"]] = doc["value"]
                except (json.JSONDecodeError, KeyError, TypeError):
                    pass
                break
    sections = {**progress, **done}  # a completed section wins
    hung = started if started not in done else None
    return sections, hung


def _echo_inner_stream(out):
    """Re-emit the inner's section stream on the OUTER's stdout (flushed).
    The outer otherwise prints nothing until its final BENCH_FULL +
    compact lines, which can be hours after the sections were measured
    (merge tools); an outer-level kill — e.g. tools/tpu_watch.py's bench
    timeout — would lose every section the inner already streamed.  With
    the echo, any consumer of the outer's partial stdout can reassemble
    them (_sections_from_stdout)."""
    for ln in (out or "").splitlines():
        if ln.startswith("BENCH_SECTION"):
            print(ln, flush=True)


def _run_inner(extra_env=None, timeout=_INNER_TIMEOUT):
    env = dict(os.environ)
    env.update(extra_env or {})
    try:
        p = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "--inner"], capture_output=True, text=True,
                           timeout=timeout, cwd=REPO, env=env)
        _echo_inner_stream(p.stdout)
    except subprocess.TimeoutExpired as e:
        # subprocess.run kills the child and attaches the output read so
        # far; any sections the inner streamed before the hang survive.
        out = e.stdout if isinstance(e.stdout, str) else (
            (e.stdout or b"").decode("utf-8", "replace"))
        _echo_inner_stream(out)
        sections, hung = _sections_from_stdout(out)
        if sections:
            note = ("inner bench timed out after %ds" % timeout
                    + (f"; hung in section '{hung}'" if hung else ""))
            result = _assemble(sections, note, write_baseline=False)
            result["partial"] = True
            if hung:
                result["hung_section"] = hung
            return json.dumps(result), None
        return None, "inner bench timed out"
    for line in reversed(p.stdout.strip().splitlines()):
        if line.startswith("{"):
            return line, None
    tail = (p.stderr or p.stdout or "").strip().splitlines()
    return None, (" | ".join(tail[-3:]) if tail else f"rc={p.returncode}")


def _cpu8_flags() -> str:
    from tools._bench_util import cpu8_flags  # jax-free helper
    return cpu8_flags()


def _run_tool(script: str, timeout: float, env=None):
    """Run a tools/ script in its own session, returning its last JSON
    stdout line (or an {"error": ...} dict).  The session matters: these
    tools spawn their own worker subprocesses (weak_scaling's DMLC
    groups), and killing only the orchestrator on timeout would orphan
    workers stuck in rendezvous — they would keep burning CPU under the
    later bench sections.  killpg reaps the whole tree."""
    import signal
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", script)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO, env=env, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        return {"error": f"{script} timed out after {timeout:.0f}s"}
    for out_line in reversed(out.strip().splitlines()):
        if out_line.startswith("{"):
            try:
                return json.loads(out_line)
            except json.JSONDecodeError:
                return {"error": f"{script}: unparseable JSON line"}
    return {"error": (err or out or "no output")[-300:]}


def _merge_tool_section(line: str, key: str, script: str,
                        timeout: float, env=None) -> str:
    """Embed a tools/ script's JSON output as ``result[key]``."""
    try:
        result = json.loads(line)
    except json.JSONDecodeError:
        return line
    if key in result:
        return line
    try:
        result[key] = _run_tool(script, timeout, env=env)
    except Exception as e:  # noqa: BLE001 - evidence sections must not
        result[key] = {"error": str(e)[:300]}  # kill the bench
    return json.dumps(result)


def _merge_scaling(line: str) -> str:
    """Scaling-evidence section (round-2 VERDICT item 3): measured weak
    scaling over real processes, the contention-free dcn-structure sweep,
    and the analytic v5e-256 projection (tools/weak_scaling.py).  The
    timeout covers the tool's own internal worst case — contended AND
    pinned curves (3 groups x 420s each) plus the 420s dcn sweep plus
    compile slack — so a slow box degrades to a clean error."""
    return _merge_tool_section(line, "scaling", "weak_scaling.py",
                               timeout=3600.0)


def _merge_mechanisms(line: str) -> str:
    """Mechanism-proof section (round-2 VERDICT item 4): priority and
    partitioning measured as LATENCY mechanisms under a credit window
    (tools/mechanism_bench.py)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _cpu8_flags()
    return _merge_tool_section(line, "mechanisms", "mechanism_bench.py",
                               timeout=900.0, env=env)


def _merge_overlap(line: str) -> str:
    """End-to-end overlap section (round-3 VERDICT task 2): full torch
    training steps through the engine in sync vs cross-barrier mode, with
    a no-communication floor — the measured answer to the reference's
    0-15% overlap claim (tools/overlap_bench.py)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _cpu8_flags()
    # 1800 s (2x the single-pass budget): on a multi-core host the tool
    # measures TWICE (unpinned + disjoint-pinned, round-5) — a budget
    # sized for one pass would time out mid-second-pass and lose BOTH
    return _merge_tool_section(line, "overlap", "overlap_bench.py",
                               timeout=1800.0, env=env)


def _couple_overlap_to_projection(line: str) -> str:
    """Narrow the analytic 82-100% bracket with the MEASURED overlap
    fraction (round-3 VERDICT task 2's second half): the v5e-256
    projection's exposed-comm term becomes (1 - measured_overlap) * comm
    instead of an assumed bound.  On a saturated host the measured
    fraction is ~0 and the estimate lands on the no-overlap end — that
    is the honest reading for that host, and the conditions block says
    which host it was."""
    try:
        result = json.loads(line)
    except json.JSONDecodeError:
        return line
    ov = result.get("overlap") or {}
    an = (result.get("scaling") or {}).get("analytic_v5e256") or {}
    # Prefer the disjoint-pinned measurement when the host could run it
    # (round-5): transport with its own cores is the closest host-side
    # analog of a TPU's on-chip compute / host dispatch split.
    pinned = ov.get("pinned_disjoint") or {}
    frac = pinned.get("overlap_fraction")
    if frac is None:  # pinned skipped OR measured but undefined (comm
        frac = ov.get("overlap_fraction")  # share ~0): fall back

    step = an.get("measured_step_ms_per_chip")
    comm = an.get("allreduce_ms")
    if frac is None or step is None or comm is None:
        return line
    f = min(max(frac, 0.0), 1.0)
    an["measured_overlap_fraction"] = round(f, 3)
    an["efficiency_at_measured_overlap"] = round(
        step / (step + (1.0 - f) * comm), 3)
    an["overlap_note"] = (
        "overlap fraction from the end-to-end cross-barrier bench on THIS "
        "host (overlap.conditions records cores/load); hosts with spare "
        "transport cores — and TPU pods, where compute runs on-chip — "
        "land nearer the full-overlap end")
    result["scaling"]["analytic_v5e256"] = an
    return json.dumps(result)


def _merge_async_vs_sync(line: str) -> str:
    """Async-PS convergence datum (round-4 VERDICT task 7): the same MLP
    trained sync (barriered grad average) vs async weight-delta workers
    sharing a KVStore, final-loss gap recorded (tools/async_bench.py).
    Matches the mode the reference ships as BYTEPS_ENABLE_ASYNC
    (server.cc:310-314, torch/__init__.py:186-214)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return _merge_tool_section(line, "async_vs_sync", "async_bench.py",
                               timeout=600.0, env=env)


def _merge_aot_memory(line: str) -> str:
    """8B feasibility section (round-3 VERDICT task 6): XLA memory
    analysis of the AOT-compiled (fsdp, tp) Llama-3-8B train step —
    per-device persistent/transient bytes vs v5e HBM, layer-count trend
    (tools/aot_memory.py)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _cpu8_flags()
    return _merge_tool_section(line, "aot_memory_8b", "aot_memory.py",
                               timeout=900.0, env=env)


def _merge_dcn_compare(line: str) -> str:
    """If the main bench ran single-chip (no dcn_compare), obtain it from a
    virtual 8-device CPU mesh subprocess and merge into the JSON line."""
    try:
        result = json.loads(line)
    except json.JSONDecodeError:
        return line
    if "dcn_compare" in result:
        return line
    env = {
        "_BPS_BENCH_ONLY": "dcn",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": _cpu8_flags(),
    }
    dcn_line, err = _run_inner(extra_env=env, timeout=600.0)
    if dcn_line is not None:
        try:
            result["dcn_compare"] = json.loads(dcn_line)["dcn_compare"]
        except (json.JSONDecodeError, KeyError):
            result["dcn_compare"] = {"error": "unparseable"}
    else:
        result["dcn_compare"] = {"error": str(err)[:200]}
    return json.dumps(result)


def _parse_line(line):
    try:
        return json.loads(line)
    except (json.JSONDecodeError, TypeError):
        return None


def _merge_watch_summary(line: str) -> str:
    """When the bench could not reach the chip, embed the round's watch
    evidence (round-3 VERDICT item 1: if the chip never comes back, the
    probe log goes in the bench JSON so absence is itself documented).
    The summary carries the counters; the full probe list stays in
    TPU_WATCH_LOG.json."""
    result = _parse_line(line)
    if result is None or "tpu_watch" in result:
        return line
    on_tpu_line = str(result.get("device", "")).lower().startswith(
        ("tpu", "v5", "v6", "v4"))
    if on_tpu_line and not _is_degraded(result):
        return line  # a green capture speaks for itself
    path = os.path.join(REPO, "TPU_WATCH_LOG.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):  # truncated/hand-edited log
            raise TypeError("watch log is not an object")
        result["tpu_watch"] = {
            "started": doc.get("started"),
            "last": doc.get("last"),
            "n_probes": doc.get("n_probes"),
            "n_green": doc.get("n_green"),
            "log": "TPU_WATCH_LOG.json",
        }
    except (OSError, json.JSONDecodeError, TypeError):
        result["tpu_watch"] = {"log": "absent: watch not running"}
    return json.dumps(result)


# The driver snapshots the last ~2000 stdout chars; staying well under
# leaves room for a stray warning line landing after ours.
_COMPACT_BUDGET = 1500


def _record_dir():
    """Where the full-record artifacts are written.  _BPS_BENCH_REPO
    overrides it so a test-suite bench run cannot clobber the committed
    BENCH_FULL record; only the WRITES move — tool paths and subprocess
    cwds stay on the real repo."""
    return os.environ.get("_BPS_BENCH_REPO") or REPO


def _round_number():
    """Best-effort current round index: one past the newest BENCH_r{N}.json
    (the driver writes those at each round end; they live in the real
    repo even when the artifact WRITES are redirected).  Never raises —
    a failed stamp must not cost the record itself."""
    import re
    try:
        ns = [int(m.group(1)) for f in os.listdir(REPO)
              for m in [re.match(r"BENCH_r(\d+)\.json$", f)] if m]
    except OSError:
        return None
    return (max(ns) + 1) if ns else None


_SCALAR_KEYS = ("metric", "value", "unit", "vs_baseline", "mfu",
                "tokens_per_sec_per_chip", "device", "n_devices")


def _section_status(v):
    """One-word health flag for the compact line's per-section map."""
    if not isinstance(v, dict):
        return "ok"
    if "error" in v:
        data = [k for k in v if k not in ("error", "skipped", "note")]
        return "error+data" if data else "error"
    if "skipped" in v:
        return "skip"
    return "ok"


def _compact_summary(doc):
    """The FINAL stdout line: ≤_COMPACT_BUDGET chars so the driver's tail
    capture always ends in one parseable JSON object.  Rounds 3 and 4
    lost their records (BENCH_r0{3,4}.json parsed: null) because the full
    ~10 kB line outgrew the 2000-char tail window — the compact line
    carries the scalars, per-section status flags and a few headline
    figures; everything else lives in the committed full record."""
    import re
    out = {k: doc[k] for k in _SCALAR_KEYS if k in doc}
    for k in ("partial", "hung_section"):
        if doc.get(k):
            out[k] = doc[k]
    skip = set(_SCALAR_KEYS) | {"partial", "hung_section", "error",
                                "tpu_watch", "recorded", "round"}
    out["sections"] = {k: _section_status(v) for k, v in doc.items()
                       if k not in skip}
    heads = {}
    pp = doc.get("push_pull_gbps")

    def _largest(prefix):
        best = None
        if isinstance(pp, dict):
            for k, v in pp.items():
                m = re.match(re.escape(prefix) + r"_(\d+)MB$", k)
                if m and isinstance(v, (int, float)):
                    if best is None or int(m.group(1)) > best[0]:
                        best = (int(m.group(1)), k, v)
        return best

    for prefix in ("fused", "engine_device", "engine_grouped", "engine"):
        b = _largest(prefix)
        if b:
            heads[b[1] + "_gbps"] = b[2]
    if isinstance(pp, dict):
        for rk in ("engine_vs_fused_ratio", "engine_device_vs_fused_ratio"):
            if isinstance(pp.get(rk), (int, float)):
                heads[rk] = pp[rk]
    for sec, label in (("tpu_overlap", "tpu_overlap_fraction"),
                       ("overlap", "host_overlap_fraction")):
        v = doc.get(sec)
        if isinstance(v, dict) and isinstance(
                v.get("overlap_fraction"), (int, float)):
            heads[label] = v["overlap_fraction"]
    if heads:
        out["headline"] = heads
    tw = doc.get("tpu_watch")
    if isinstance(tw, dict):
        out["tpu_watch"] = {k: tw[k] for k in ("n_probes", "n_green", "last")
                            if k in tw}
    if doc.get("round") is not None:
        out["round"] = doc["round"]
    out["full_record"] = "BENCH_FULL.json"
    if doc.get("error"):
        out["error"] = str(doc["error"])[:200]
    s = json.dumps(out, separators=(",", ":"))
    for drop in ("headline", "sections"):  # belt-and-braces; the normal
        if len(s) <= _COMPACT_BUDGET:      # line is a few hundred chars
            break
        out.pop(drop, None)
        s = json.dumps(out, separators=(",", ":"))
    if len(s) > _COMPACT_BUDGET and "error" in out:
        out["error"] = out["error"][:80]
        s = json.dumps(out, separators=(",", ":"))
    return s


def _record_class(doc):
    """Displacement rank for the numbers-of-record file: a complete TPU
    record (2) outranks a complete chipless/CPU record (1) outranks a
    degraded or terminal-failure record (0).  Same idea as
    tools/tpu_watch.record()'s guard: a red round's failure line must not
    clobber the last good record at the path docs cite."""
    if not isinstance(doc, dict) or _is_degraded(doc):
        return 0
    on_tpu = str(doc.get("device", "")).lower().startswith(
        ("tpu", "v5", "v6", "v4"))
    return 2 if on_tpu else 1


def _atomic_write(doc, path):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def _finalize(line: str) -> str:
    """Persist the full assembled record and return the compact final line
    (round-4 VERDICT task 1).  The full record is echoed to stdout as a
    'BENCH_FULL '-prefixed line for stream consumers (tools/tpu_watch.py)
    and written to two committed files: BENCH_FULL_LATEST.json (every
    run, any quality) and BENCH_FULL.json — the numbers of record
    docs/performance.md cites — which a lower-class record never
    displaces (_record_class).  The returned compact summary is printed
    LAST so the driver's 2000-char tail capture always parses."""
    doc = _parse_line(line)
    if doc is None:
        return line
    doc["recorded"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    rnd = _round_number()
    if rnd is not None:
        doc["round"] = rnd
    full = json.dumps(doc)
    try:
        rec_dir = _record_dir()
        record_path = os.path.join(rec_dir, "BENCH_FULL.json")
        _atomic_write(doc, os.path.join(rec_dir,
                                        "BENCH_FULL_LATEST.json"))
        try:
            with open(record_path) as f:
                existing = json.load(f)
        except (OSError, json.JSONDecodeError):
            existing = None
        if _record_class(doc) >= _record_class(existing):
            _atomic_write(doc, record_path)
    except OSError:
        pass  # unwritable tree: stdout still carries the full line
    print("BENCH_FULL " + full, flush=True)
    return _compact_summary(doc)


def _is_degraded(doc):
    """A line that must not be trusted as the round's record: salvaged
    partial, or a 'complete' line whose train section failed (section()
    converts a raised train step into an error dict, so the inner still
    prints a line with value 0.0 — that is a failure, not a result)."""
    return bool(doc) and (bool(doc.get("partial")) or not doc.get("value"))


def _prefer_line(a, b):
    """Pick the more informative of two bench lines: measured content
    first (a headline train number, then more green sections), and only
    then completeness — a value-0 'complete' line whose sections all
    errored must not beat a data-rich salvaged partial."""
    def score(line):
        doc = _parse_line(line)
        if not doc:
            return (-1, -1, -1)
        keys = ("push_pull_gbps", "tpu_overlap", "onebit_pallas",
                "flash_attention", "bf16_fsdp_tp", "resnet50")
        # Count measurement ENTRIES, not whole sections: an error-annotated
        # section that salvaged five sizes before the drop outweighs an
        # error-free one holding a single measurement.  IQR brackets and
        # the ablation-skip note describe measurements, they aren't ones.
        meta = {"skipped", "error", "note", "shape", "ablations_skipped"}
        done = sum(sum(1 for kk in doc[k]
                       if kk not in meta and not kk.endswith("_iqr"))
                   for k in keys if isinstance(doc.get(k), dict))
        return (1 if doc.get("value") else 0, done,
                0 if doc.get("partial") else 1)
    return a if score(a) >= score(b) else b


def main() -> int:
    if "--inner" in sys.argv:
        return inner_main()

    errors = []
    for attempt, probe_timeout in enumerate((240.0, 60.0)):
        info, err = _probe(probe_timeout)
        if info is not None:
            # A probe that lands on plain CPU (no TPU plugin, but no
            # plugin HANG either) must still run the virtual 8-device
            # mesh: a bare inner would get jax's default single CPU
            # device, every collective degenerates to a no-op, and the
            # "engine GB/s" would be incomparable with every prior
            # round's 8-rank record (this exact skew produced one
            # n_devices=1 line before being caught).
            extra = None
            if info.get("platform") == "cpu":
                extra = {"_BPS_BENCH_FORCE_CPU": "1",
                         "JAX_PLATFORMS": "cpu",
                         "XLA_FLAGS": _cpu8_flags()}
            line, err = _run_inner(extra_env=extra)
            if line is None:
                errors.append(f"bench on {info['platform']} failed: {err}")
                # one retry of the full bench for transient failures
                line, err = _run_inner(extra_env=extra)
            elif _is_degraded(_parse_line(line)):
                # The chip dropped mid-run (salvaged partial) or the train
                # step raised (value-0 line).  Retry the full bench only if
                # the chip probes green again, and keep whichever run
                # captured more.  The retry budget must cover the nominal
                # full TPU section list (~25-35 min, see _INNER_TIMEOUT's
                # comment) — a shorter budget could only ever produce
                # another partial, never the complete line it exists to
                # recover (round-4 advisor finding).
                info2, _ = _probe(90.0)
                if info2 is not None:
                    line2, _ = _run_inner(extra_env=extra, timeout=2400.0)
                    line = _prefer_line(line, line2)
            if line is not None:
                print(_finalize(_merge_watch_summary(
                    _couple_overlap_to_projection(_merge_aot_memory(
                        _merge_async_vs_sync(_merge_overlap(
                            _merge_mechanisms(_merge_scaling(
                                _merge_dcn_compare(line))))))))))
                return 0
            errors.append(f"bench retry failed: {err}")
            break
        errors.append(f"probe {attempt + 1}: {err}")
        time.sleep(10)

    # Terminal fallback: CPU smoke so the driver still records a number.
    note = "tpu unavailable: " + "; ".join(errors)[:400]
    env = {
        "_BPS_BENCH_FORCE_CPU": "1",
        "_BPS_BENCH_NOTE": note,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": _cpu8_flags(),
    }
    line, err = _run_inner(extra_env=env, timeout=900.0)
    if line is not None:
        print(_finalize(_merge_watch_summary(_couple_overlap_to_projection(
            _merge_aot_memory(_merge_async_vs_sync(_merge_overlap(
                _merge_mechanisms(_merge_scaling(line)))))))))
        return 0
    # Terminal failure is the line that needs the watch evidence MOST:
    # nothing else documents that the chip was being probed all round.
    print(_finalize(_merge_watch_summary(json.dumps({
        "metric": "bert_large_mlm_train_throughput_per_chip",
        "value": 0.0, "unit": "examples/s", "vs_baseline": 0.0,
        "error": note + f"; cpu fallback also failed: {err}",
    }))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
