"""Benchmark: BERT-large MLM training throughput through byteps_tpu.

The reference's headline benchmark is BERT-large pretraining throughput /
scaling efficiency (reference README.md:35-41; BASELINE.md).  This harness
runs the fused data-parallel train step (forward + backward + push_pull +
adamw) on whatever devices are visible — the one real chip under the
driver, or a virtual CPU mesh for smoke runs — and prints one JSON line:

    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is the ratio against PUBLISHED_BASELINE below (per-chip
examples/s); 1.0 marks the first recorded run of this rebuild.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import numpy as np

# First-run value recorded on TPU v5e-1 (this repo, round 1, batch 32
# seq 128 bf16, forced host materialization); later rounds compare against
# it so the driver's BENCH_r{N}.json series shows drift.
PUBLISHED_BASELINE_EXAMPLES_PER_SEC = 520.0


def main() -> int:
    import optax

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.models.bert import (BertForMLM, bert_large, bert_tiny,
                                        mlm_loss, synthetic_batch)
    from byteps_tpu.parallel import make_dp_train_step, replicate, shard_batch

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n = len(devices)
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)

    cfg = bert_large() if on_tpu else bert_tiny()
    seq_len = 128 if on_tpu else 32
    per_dev_batch = 32 if on_tpu else 2
    steps = 20 if on_tpu else 3

    model = BertForMLM(cfg)
    rng = jax.random.PRNGKey(0)
    global_batch = per_dev_batch * n
    batch = synthetic_batch(rng, cfg, batch=global_batch, seq_len=seq_len)
    params = model.init(rng, batch["input_ids"], batch["attention_mask"])

    def loss_fn(params, b):
        # gathered MLM head: vocab projection only on masked positions
        logits = model.apply(params, b["input_ids"], b["attention_mask"],
                             masked_positions=b["masked_positions"])
        return mlm_loss(logits, b["masked_labels"])

    tx = optax.adamw(1e-4)
    opt_state = tx.init(params)
    step = make_dp_train_step(comm, loss_fn, tx)
    params = replicate(comm, params)
    opt_state = replicate(comm, opt_state)
    batch = shard_batch(comm, batch)

    def run(k):
        nonlocal params, opt_state
        t0 = time.perf_counter()
        loss = None
        for _ in range(k):
            params, opt_state, loss = step(params, opt_state, batch)
        # Host transfers force completion; on the experimental axon
        # platform block_until_ready alone can return early.
        jax.block_until_ready((params, opt_state))
        lv = float(loss)
        return time.perf_counter() - t0, lv

    run(3)  # warmup/compile
    dt, lv = run(steps)
    dt2, lv = run(steps)
    dt = min(dt, dt2)

    examples_per_sec = steps * global_batch / dt
    per_chip = examples_per_sec / n
    assert np.isfinite(lv), "non-finite loss"
    result = {
        "metric": "bert_large_mlm_train_throughput_per_chip"
                  if on_tpu else "bert_tiny_cpu_smoke_throughput_per_chip",
        "value": round(per_chip, 2),
        "unit": "examples/s",
        "vs_baseline": round(per_chip / PUBLISHED_BASELINE_EXAMPLES_PER_SEC,
                             3) if on_tpu else 0.0,
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
