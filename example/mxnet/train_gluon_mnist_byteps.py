"""Gluon MNIST training via byteps_tpu.mxnet DistributedTrainer
(reference example/mxnet/train_gluon_mnist_byteps.py, synthetic data).
Requires mxnet (pip install mxnet); the adapter itself does not.

Run:  python example/mxnet/train_gluon_mnist_byteps.py [--epochs N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse

import numpy as np

import byteps_tpu.mxnet as bps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()

    import mxnet as mx
    from mxnet import autograd, gluon

    bps.init()
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())

    trainer = bps.DistributedTrainer(
        net.collect_params(), "sgd",
        {"learning_rate": 0.05 * bps.size()})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(bps.rank())
    x = mx.nd.array(rng.randn(args.batch, 784).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, args.batch))

    for i in range(args.steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(args.batch)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss.mean().asscalar()):.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
