"""Gluon MNIST with gradient compression via compression_params
(reference example/mxnet/train_gluon_mnist_byteps_gc.py, synthetic data).

Shows the reference's compression plumbing end to end: the trainer's
``compression_params`` dict (onebit + error feedback + Nesterov momentum,
the reference's recommended chain) flows through the per-parameter
``byteps_*`` attributes into the engine's compressor registry.
Requires mxnet (pip install mxnet); the adapter itself does not.

Run:  python example/mxnet/train_gluon_mnist_byteps_gc.py [--steps N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse

import numpy as np

import byteps_tpu.mxnet as bps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--compressor", default="onebit",
                    choices=["onebit", "topk", "randomk", "dithering",
                             "powersgd"])
    args = ap.parse_args()

    import mxnet as mx
    from mxnet import autograd, gluon

    bps.init()
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())

    # the reference's compression_params surface (mxnet/__init__.py
    # compression attrs -> kwargs): with momentum configured, the
    # optimizer's momentum moves into the compressor chain (worker-side
    # Nesterov before compression), reference __init__.py:235-316
    compression_params = {
        "compressor": args.compressor,
        "ef": "vanilla",
        "momentum": "nesterov",
        "k": 0.1,              # topk/randomk fraction (ignored by onebit)
        "scaling": True,
    }
    trainer = bps.DistributedTrainer(
        net.collect_params(), "sgd",
        {"learning_rate": 0.05 * bps.size(), "momentum": 0.9},
        compression_params=compression_params)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(bps.rank())
    x = mx.nd.array(rng.randn(args.batch, 784).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, args.batch))

    for i in range(args.steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(args.batch)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss.mean().asscalar()):.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
