"""Synthetic ResNet-50 / VGG-16 throughput benchmark (images/s).

The rebuild's counterpart of the reference's synthetic benchmarks
(reference example/pytorch/benchmark_byteps.py, docs/performance.md:3-23
table): trains on random NHWC images through the fused DP step with
cross-replica BatchNorm and reports images/s per chip.

    python example/jax/benchmark_resnet.py --model resnet50 --batch 32
    python example/jax/benchmark_resnet.py --model vgg16 --steps 10
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet50", "resnet18", "vgg16", "tiny"])
    ap.add_argument("--batch", type=int, default=32, help="per device")
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (smoke runs)")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import optax

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.models import resnet as R
    from byteps_tpu.parallel import shard_batch

    devices = jax.devices()
    n = len(devices)
    comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)

    if args.model == "tiny":
        model = R.resnet_tiny(axis_name=comm.dp_axes)
        args.size, classes = min(args.size, 32), 10
    elif args.model == "vgg16":
        model, classes = R.vgg16(), 1000
    elif args.model == "resnet18":
        model = R.resnet18(axis_name=comm.dp_axes)
        classes = 1000
    else:
        model = R.resnet50(axis_name=comm.dp_axes)
        classes = 1000

    rng = jax.random.PRNGKey(0)
    global_batch = args.batch * n
    batch = R.synthetic_images(rng, global_batch, args.size, classes)
    step, state = R.make_vision_trainer(
        comm, model, optax.sgd(0.1, momentum=0.9), batch, rng)
    batch = shard_batch(comm, batch)

    def run(k):
        nonlocal state
        t0 = time.perf_counter()
        loss = None
        for _ in range(k):
            state, loss = step(state, batch)
        jax.block_until_ready(state)
        return time.perf_counter() - t0, float(loss)

    run(2)  # compile + warm
    dt, loss = run(args.steps)
    assert np.isfinite(loss), "non-finite loss"
    ips = args.steps * global_batch / dt
    print(json.dumps({
        "model": args.model, "images_per_sec": round(ips, 2),
        "per_chip": round(ips / n, 2), "n_devices": n,
        "batch_per_device": args.batch, "image_size": args.size,
        "loss": round(loss, 4),
    }))
    return 0


if __name__ == "__main__":
    main()
