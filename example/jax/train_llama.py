"""Train a Llama-family model under (fsdp, tp) composite sharding.

The flagship modern-LLM configuration (BASELINE.json configs[4]:
"Llama-3-8B"): RoPE/RMSNorm/SwiGLU/GQA decoder with Megatron-style tensor
parallelism inside the fastest ICI dimension and ZeRO-3-by-annotation
parameter sharding (XLA streams each layer's gather) over the rest of the
mesh, batch sharded over the fsdp axis.

    # tiny config on whatever devices are visible (CPU mesh in tests):
    python example/jax/train_llama.py --steps 10

    # the real 8B geometry (needs a pod slice; bf16 + remat):
    python example/jax/train_llama.py --config 8b --tp 4 --batch 8 \
        --seq 4096 --bf16

Per-device persistent memory for the 8B config at (fsdp=16, tp=4):
params 16 GB / 64 + adam 32 GB / 64 = ~0.75 GB, leaving HBM to
activations — the configuration the reference's replicated-optimizer
design cannot express at any cluster size (SURVEY.md §2.6).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", choices=["tiny", "8b"], default="tiny")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--tp", type=int, default=0,
                    help="tp axis size (0 = largest of 4/2/1 dividing "
                         "the device count)")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--bf16", action="store_true",
                    help="bf16 activations for the tiny config (which "
                         "defaults to f32 here for CPU parity); the 8b "
                         "config is always bf16 + remat")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from byteps_tpu.models.llama import llama3_8b, llama_tiny
    import byteps_tpu.parallel as par

    devices = jax.devices()
    n = len(devices)
    n_tp = args.tp or max(d for d in (4, 2, 1) if n % d == 0)

    import dataclasses

    if args.config == "8b":
        # always bf16 + remat: seq-4096 x 32-layer activations without
        # remat OOM a pod regardless of flags
        cfg = dataclasses.replace(llama3_8b(), dtype=jnp.bfloat16,
                                  remat=True)
    else:
        cfg = dataclasses.replace(
            llama_tiny(),
            dtype=jnp.bfloat16 if args.bf16 else jnp.float32)

    mesh = par.make_fsdp_tp_mesh(devices, n_tp=n_tp)
    rng = jax.random.PRNGKey(0)
    batch = par.synthetic_lm_batch(rng, cfg, args.batch, args.seq)
    tx = optax.adamw(args.lr)

    t0 = time.perf_counter()
    # sharded init: weights are born on their (fsdp, tp) placement — the
    # 8B tree never exists unsharded on any single device
    params = par.init_llama_params_sharded(mesh, cfg, rng,
                                           batch["input_ids"][:1])
    opt_state = par.init_llama_opt_state(tx, params)
    step = par.make_fsdp_tp_train_step(mesh, cfg, tx)
    batch = par.shard_llama_batch(mesh, batch)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    losses = []
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0

    print(json.dumps({
        "mode": "fsdp_tp", "mesh": {"fsdp": n // n_tp, "tp": n_tp},
        "n_params": n_params, "steps": args.steps,
        "first_loss": round(losses[0], 4),
        "last_loss": round(losses[-1], 4),
        "wall_s": round(dt, 2),
    }))
    assert losses[-1] < losses[0], "loss did not decrease"
    return 0


if __name__ == "__main__":
    main()
