"""Train a small GPT (or MoE layer) under each composite parallelism axis.

The byteps_tpu counterpart of "which axis do I reach for": the same tiny
model runs under (dp,tp) GSPMD, (dp,pp) GPipe, a (dp,ep) switch-MoE
regression, ZeRO-1/FSDP sharded-optimizer DP, or the full 3D
(dp,pp,tp) composite — all on whatever devices are visible (8 virtual
CPU devices in tests; a real slice in production).

    python example/jax/train_parallel_axes.py --mode tp --steps 10
    python example/jax/train_parallel_axes.py --mode pp --microbatches 4
    python example/jax/train_parallel_axes.py --mode ep --experts 8
    python example/jax/train_parallel_axes.py --mode zero
    python example/jax/train_parallel_axes.py --mode fsdp
    python example/jax/train_parallel_axes.py --mode 3d --microbatches 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["tp", "pp", "ep", "zero", "fsdp",
                                       "3d"], default="tp")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--inner", type=int, default=0,
                    help="size of the tp/pp/ep axis (0 = largest of "
                         "4/2/1 that divides the device count)")
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from byteps_tpu.models.gpt import GPT, GPTConfig
    import byteps_tpu.parallel as par

    devices = jax.devices()
    n = len(devices)
    # default inner axis: largest size that divides both the device count
    # and the model's shardable dims (4 heads / 4 layers)
    inner = args.inner or max(d for d in (4, 2, 1) if n % d == 0)
    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4,
                    num_heads=4, intermediate_size=128, max_position=256,
                    dtype=jnp.float32)
    tx = optax.adam(1e-2)
    rng = jax.random.PRNGKey(0)

    t0 = time.perf_counter()
    if args.mode == "tp":
        mesh = par.make_tp_mesh(devices, n_tp=inner)
        b = par.synthetic_lm_batch(rng, cfg, args.batch, args.seq)
        p = par.shard_gpt_params(
            mesh, GPT(cfg).init(rng, b["input_ids"][:1]))
        o = par.init_tp_opt_state(tx, p)
        step = par.make_dp_tp_train_step(mesh, cfg, tx)
        b = par.shard_tp_batch(mesh, b)
    elif args.mode == "pp":
        mesh = par.make_pp_mesh(devices, n_pp=inner)
        b = par.synthetic_lm_batch(rng, cfg, args.batch, args.seq)
        p = par.shard_pipeline_params(
            mesh, par.init_pipeline_params(cfg, rng, b["input_ids"][:1]))
        o = jax.jit(tx.init)(p)
        step = par.make_dp_pp_train_step(
            mesh, cfg, tx, num_microbatches=args.microbatches)
        b = par.shard_pp_batch(mesh, b)
    elif args.mode in ("zero", "fsdp"):
        # sharded-optimizer DP: master vector + moments live 1/R across
        # the whole mesh; fsdp additionally stores params only sharded
        from byteps_tpu.comm.mesh import CommContext, _build_mesh
        comm = CommContext(mesh=_build_mesh(devices, 1), n_dcn=1, n_ici=n)
        b = par.synthetic_lm_batch(rng, cfg, args.batch, args.seq)
        model = GPT(cfg)
        params = model.init(rng, b["input_ids"][:1])

        def loss_fn(p, bb):
            from byteps_tpu.models.gpt import lm_loss
            return lm_loss(model.apply(p, bb["input_ids"]), bb["labels"])

        zstate = par.init_zero_state(comm, tx, params)
        b = par.shard_batch(comm, b)
        if args.mode == "zero":
            zstep = par.make_zero_train_step(comm, loss_fn, tx)
            zp = par.replicate(comm, params)

            def step(p, o, bb):
                nonlocal zp
                zp, z, loss = zstep(zp, o, bb)
                return p, z, loss
        else:
            fstep = par.make_fsdp_train_step(comm, loss_fn, tx,
                                             params_template=params)

            def step(p, o, bb):
                z, loss = fstep(o, bb)
                return p, z, loss
        p, o = None, zstate
        mesh = comm.mesh
    elif args.mode == "3d":
        # honor --inner as the tp size when it fits (pp fixed at 2 when
        # the device count allows); degrade to trivial axes on small or
        # odd device counts rather than crashing
        if args.inner and n % (2 * args.inner) == 0 \
                and cfg.num_heads % args.inner == 0:
            n_tp = args.inner
        else:
            n_tp = max((d for d in (2, 1) if n % (2 * d) == 0), default=1)
        n_pp = 2 if n % (2 * n_tp) == 0 else 1
        inner = n_tp  # reported layout matches what actually ran
        mesh = par.make_3d_mesh(devices, n_pp=n_pp, n_tp=n_tp)
        b = par.synthetic_lm_batch(rng, cfg, args.batch, args.seq)
        p = par.shard_3d_params(
            mesh, par.init_pipeline_params(cfg, rng, b["input_ids"][:1]))
        o = par.init_3d_opt_state(tx, p)
        step = par.make_dp_pp_tp_train_step(
            mesh, cfg, tx, num_microbatches=args.microbatches)
        b = par.shard_3d_batch(mesh, b)
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = par.make_ep_mesh(devices, n_ep=inner)
        hidden = cfg.hidden_size
        p = par.shard_moe_params(mesh, par.init_moe_params(
            rng, hidden, cfg.intermediate_size, args.experts))
        o = jax.jit(tx.init)(p)
        step = par.make_dp_ep_train_step(
            mesh, args.experts, 1.5, tx,
            lambda out, bb: jnp.mean((out - bb["y"]) ** 2))
        x = jax.random.normal(jax.random.PRNGKey(1),
                              (args.batch * n, hidden))
        b = jax.device_put({"x": x, "y": jnp.tanh(x[:, ::-1])},
                           NamedSharding(mesh, P(("dp", "ep"))))

    losses = []
    for _ in range(args.steps):
        p, o, loss = step(p, o, b)
        losses.append(float(loss))
    assert np.isfinite(losses[-1])
    layout = {"3d": lambda: f"pp{mesh.shape['pp']}xtp{mesh.shape['tp']}"}
    print(json.dumps({
        "mode": args.mode, "n_devices": n,
        "inner_axis": layout.get(args.mode, lambda: inner)(),
        "first_loss": round(losses[0], 4), "last_loss": round(losses[-1], 4),
        "wall_s": round(time.perf_counter() - t0, 2),
    }))
    return 0


if __name__ == "__main__":
    main()
