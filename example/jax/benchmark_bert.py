"""BERT-large MLM training throughput (the reference's headline benchmark,
README.md:35-41 / BASELINE.md) on the byteps_tpu fused DP path.

Run:  python example/jax/benchmark_bert.py [--steps N] [--batch B]
      [--seq L] [--compress-dcn]  (onebit on the inter-slice hop)
CPU smoke uses bert_tiny automatically.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse
import time

import jax
import numpy as np
import optax

import byteps_tpu as bps
from byteps_tpu.comm.mesh import get_comm
from byteps_tpu.models.bert import (BertForMLM, bert_large, bert_tiny,
                                    mlm_loss, synthetic_batch)
from byteps_tpu.parallel import make_dp_train_step, replicate, shard_batch


def main():
    on_tpu = jax.devices()[0].platform == "tpu"
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20 if on_tpu else 3)
    ap.add_argument("--batch", type=int, default=32 if on_tpu else 2)
    ap.add_argument("--seq", type=int, default=128 if on_tpu else 32)
    ap.add_argument("--compress-dcn", action="store_true")
    args = ap.parse_args()

    bps.init()
    comm = get_comm()
    n = comm.num_ranks
    cfg = bert_large() if on_tpu else bert_tiny()
    model = BertForMLM(cfg)
    rng = jax.random.PRNGKey(0)
    gb = args.batch * n
    batch = synthetic_batch(rng, cfg, batch=gb, seq_len=args.seq)
    params = model.init(rng, batch["input_ids"][:1],
                        batch["attention_mask"][:1])
    tx = optax.adamw(1e-4)

    def loss_fn(p, b):
        logits = model.apply(p, b["input_ids"], b["attention_mask"],
                             masked_positions=b["masked_positions"])
        return mlm_loss(logits, b["masked_labels"])

    compress = None
    if args.compress_dcn:
        from byteps_tpu.ops import make_onebit_pair
        compress = make_onebit_pair()

    step = make_dp_train_step(comm, loss_fn, tx, compress_dcn=compress)
    params = replicate(comm, params)
    opt_state = replicate(comm, tx.init(params))
    batch = shard_batch(comm, batch)

    params, opt_state, loss = step(params, opt_state, batch)  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    eps = args.steps * gb / dt
    print(f"loss {float(loss):.4f}  {eps:.1f} examples/s "
          f"({eps / n:.1f}/chip, {n} chips)")
    bps.shutdown()


if __name__ == "__main__":
    main()
