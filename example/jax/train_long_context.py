"""Long-context causal-LM training over a (dp, sp) mesh.

What the reference cannot do at all (no sequence dimension anywhere,
SURVEY.md §5): sequence length is sharded across devices, attention runs
as a ring (K/V blocks rotating over ICI) or Ulysses (all-to-all head
resharding), and gradients are push_pulled over both mesh axes — one
jitted step.

Run:  python example/jax/train_long_context.py --seq 8192 --sp 4
CPU smoke:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python example/jax/train_long_context.py \
    --steps 3 --seq 256 --sp 4 --tiny
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse
import time

import jax
import optax

from byteps_tpu.models.gpt import GPT, gpt_small, gpt_tiny
from byteps_tpu.parallel import (make_dp_sp_train_step, make_sp_mesh,
                                 shard_lm_batch, synthetic_lm_batch)
from byteps_tpu.parallel.long_context import replicate


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8, help="global batch")
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--sp", type=int, default=None,
                    help="sequence-parallel degree (default: all devices)")
    ap.add_argument("--attention",
                choices=("ring", "striped", "ring_flash", "ulysses",
                         "ulysses_flash"),
                    default="ring")
    ap.add_argument("--tiny", action="store_true")
    args = ap.parse_args()

    cfg = gpt_tiny() if args.tiny else gpt_small()
    mesh = make_sp_mesh(n_sp=args.sp)
    n_dp, n_sp = mesh.devices.shape
    print(f"mesh: dp={n_dp} x sp={n_sp}, seq {args.seq} "
          f"({args.seq // n_sp}/device), attention={args.attention}")

    rng = jax.random.PRNGKey(0)
    batch = synthetic_lm_batch(rng, cfg, batch=args.batch,
                               seq_len=args.seq)
    params = GPT(cfg).init(rng, batch["input_ids"][:1, : args.seq])
    tx = optax.adamw(3e-4)
    step = make_dp_sp_train_step(mesh, cfg, tx, attention=args.attention)

    p = replicate(mesh, params)
    o = replicate(mesh, tx.init(params))
    b = shard_lm_batch(mesh, batch, striped=args.attention == "striped")

    p, o, loss = step(p, o, b)  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for i in range(args.steps):
        p, o, loss = step(p, o, b)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    toks = args.steps * args.batch * args.seq
    print(f"{toks / dt:.0f} tokens/s")


if __name__ == "__main__":
    main()
