"""Train an MNIST-class MLP with the fused data-parallel path.

The JAX equivalent of the reference's example/pytorch/train_mnist_byteps.py:
the whole step (forward + backward + push_pull + sgd) is one XLA program
over the (dcn, ici) mesh.  Synthetic data (no dataset download).

Run:  python example/jax/train_mnist_mlp.py [--steps N] [--batch B]
CPU smoke:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
            JAX_PLATFORMS=cpu python example/jax/train_mnist_mlp.py --steps 3
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import byteps_tpu as bps
from byteps_tpu.comm.mesh import get_comm
from byteps_tpu.models.mlp import mnist_mlp, softmax_cross_entropy
from byteps_tpu.parallel import make_dp_train_step, replicate, shard_batch


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=32, help="per-device")
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    bps.init()
    comm = get_comm()
    n = comm.num_ranks
    print(f"devices={n} mesh=({comm.n_dcn} dcn x {comm.n_ici} ici)")

    model = mnist_mlp()
    rng = np.random.RandomState(0)
    gb = args.batch * n
    x = jnp.asarray(rng.randn(gb, 784).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, size=(gb,)))

    params = model.init(jax.random.PRNGKey(0), x[:1])
    tx = optax.sgd(args.lr, momentum=0.9)

    def loss_fn(p, batch):
        logits = model.apply(p, batch["x"])
        return softmax_cross_entropy(logits, batch["y"]).mean()

    step = make_dp_train_step(comm, loss_fn, tx)
    params = replicate(comm, params)
    opt_state = replicate(comm, tx.init(params))
    batch = shard_batch(comm, {"x": x, "y": y})

    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, batch)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    print(f"{args.steps / dt:.1f} steps/s, "
          f"{args.steps * gb / dt:.0f} examples/s")
    bps.shutdown()


if __name__ == "__main__":
    main()
