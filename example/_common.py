"""Shared example bootstrap.

Every example inserts the repo root on sys.path (so a fresh checkout runs
without installation) and then calls :func:`honor_jax_platforms` — some
host images pre-import jax at interpreter start, which consumes
JAX_PLATFORMS before the example's own imports run; re-applying the
requested platform via jax.config is then the only effective switch.
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    """Re-apply a JAX_PLATFORMS env request that a pre-imported jax may
    have missed.  Only acts when the request puts CPU first — that is the
    case a pre-import breaks (the image's own accelerator platform is
    already the default, and images that pre-import jax typically export
    their platform name in JAX_PLATFORMS, which must not override a test
    harness's deliberate CPU mesh).  The full value passes through
    verbatim, so "cpu,tpu" keeps its fallback semantics."""
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms.split(",")[0].strip().lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", platforms)
