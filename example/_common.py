"""Shared example bootstrap.

Every example inserts the repo root on sys.path (so a fresh checkout runs
without installation) and then calls :func:`honor_jax_platforms` — some
host images pre-import jax at interpreter start, which consumes
JAX_PLATFORMS before the example's own imports run; re-applying the
requested platform via jax.config is then the only effective switch.
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    """Re-apply a JAX_PLATFORMS env request that a pre-imported jax may
    have missed.  Passes the value through verbatim (e.g. "cpu,tpu" keeps
    its fallback semantics); no-op when the variable is unset."""
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms:
        import jax
        jax.config.update("jax_platforms", platforms)
