"""TF2 synthetic push_pull benchmark (reference
example/tensorflow/synthetic_benchmark_tf2.py).

Run:  python example/tensorflow/synthetic_benchmark_tf2.py [--num-iters N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse
import time

import numpy as np
import tensorflow as tf

import byteps_tpu.tensorflow as bps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--tensor-mb", type=float, default=4.0)
    ap.add_argument("--num-tensors", type=int, default=10)
    args = ap.parse_args()

    bps.init()
    n = int(args.tensor_mb * 1e6 / 4)
    ts = [tf.constant(np.random.randn(n).astype(np.float32))
          for _ in range(args.num_tensors)]

    for i, t in enumerate(ts):  # warm-up / declare
        bps.push_pull(t, name=f"bench.{i}")
    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        for i, t in enumerate(ts):
            bps.push_pull(t, name=f"bench.{i}")
    dt = time.perf_counter() - t0
    mb = args.num_iters * args.num_tensors * args.tensor_mb
    print(f"{mb / dt:.1f} MB/s pushed+pulled")
    bps.shutdown()


if __name__ == "__main__":
    main()
