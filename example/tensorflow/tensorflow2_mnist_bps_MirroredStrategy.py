"""BytePS-backed MirroredStrategy (reference
example/tensorflow/tensorflow2_mnist_bps_MirroredStrategy.py): replica
reduction routes through the engine's push_pull.

Run:  python example/tensorflow/tensorflow2_mnist_bps_MirroredStrategy.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse

import numpy as np
import tensorflow as tf

import byteps_tpu.tensorflow as bps
from byteps_tpu.tensorflow.distribute import MirroredStrategy


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()

    strategy = MirroredStrategy()  # engine cross-device ops installed
    with strategy.scope():
        model = tf.keras.Sequential([
            tf.keras.layers.Dense(128, activation="relu"),
            tf.keras.layers.Dense(10),
        ])
        opt = tf.keras.optimizers.SGD(0.05)

    rng = np.random.RandomState(0)
    x = tf.constant(rng.randn(args.batch, 784).astype(np.float32))
    y = tf.constant(rng.randint(0, 10, args.batch))

    @tf.function
    def step():
        def replica_fn():
            with tf.GradientTape() as tape:
                logits = model(x, training=True)
                loss = tf.reduce_mean(
                    tf.nn.sparse_softmax_cross_entropy_with_logits(
                        y, logits))
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            return loss
        return strategy.run(replica_fn)

    for i in range(args.steps):
        loss = strategy.reduce(tf.distribute.ReduceOp.MEAN, step(),
                               axis=None)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
