"""Measure the TF communication-boundary options (VERDICT r1 item 6).

Three ways to train a TF model through byteps_tpu, timed on the same
model/batch so the decision in docs/performance.md is recorded with data:

1. ``nocomm_jit``      — tf.function(jit_compile=True), no communication:
                         the compute lower bound.
2. ``boundary_jit``    — make_compiled_train_step: XLA-compiled
                         forward/backward and apply, engine push_pull at
                         the program boundary (the TPU-native pattern).
3. ``ingraph_pyfunc``  — DistributedGradientTape inside tf.function
                         (jit_compile NOT possible): the round-1 path,
                         matching the reference's in-graph placement
                         (reference tensorflow/ops.cc:167-231).

Run: python example/tensorflow/bench_compiled_boundary.py [--steps N]
Prints one JSON line with steps/s per configuration and the overhead of
each communication placement vs the no-comm bound.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()


def _model(tf):
    # a real (if small) model: 4-block MLP-mixer-ish tower, ~1.1M params
    inputs = tf.keras.Input((256,))
    h = inputs
    for _ in range(4):
        h = tf.keras.layers.Dense(512, activation="gelu")(h)
    outputs = tf.keras.layers.Dense(10)(h)
    return tf.keras.Model(inputs, outputs)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    import numpy as np
    import tensorflow as tf

    import byteps_tpu.tensorflow as bps_tf

    tf.random.set_seed(0)
    bps_tf.init()
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    rng = np.random.RandomState(0)
    x = tf.constant(rng.randn(64, 256).astype(np.float32))
    y = tf.constant(rng.randint(0, 10, 64).astype(np.int64))

    def time_steps(step, n):
        step(x, y)  # warmup/trace/compile
        t0 = time.perf_counter()
        for _ in range(n):
            loss = step(x, y)
        _ = float(loss)
        return n / (time.perf_counter() - t0)

    results = {}

    # 1. no-comm jit bound
    m1 = _model(tf)
    o1 = tf.keras.optimizers.SGD(0.01)

    @tf.function(jit_compile=True)
    def step_nocomm(xb, yb):
        with tf.GradientTape() as tape:
            loss = loss_fn(yb, m1(xb, training=True))
        o1.apply_gradients(zip(tape.gradient(loss, m1.trainable_variables),
                               m1.trainable_variables))
        return loss
    results["nocomm_jit"] = time_steps(step_nocomm, args.steps)

    # 2. compiled boundary
    m2 = _model(tf)
    o2 = tf.keras.optimizers.SGD(0.01)
    step_boundary = bps_tf.make_compiled_train_step(
        m2, lambda logits, yb: loss_fn(yb, logits), o2)

    def step2(xb, yb):
        return step_boundary(xb, yb)
    results["boundary_jit"] = time_steps(step2, args.steps)

    # 3. in-graph py_function (cannot jit_compile)
    m3 = _model(tf)
    o3 = tf.keras.optimizers.SGD(0.01)

    @tf.function
    def step_ingraph(xb, yb):
        with bps_tf.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = loss_fn(yb, m3(xb, training=True))
        o3.apply_gradients(zip(tape.gradient(loss, m3.trainable_variables),
                               m3.trainable_variables))
        return loss
    results["ingraph_pyfunc"] = time_steps(step_ingraph, args.steps)

    bps_tf.shutdown()
    bound = results["nocomm_jit"]
    out = {k: round(v, 2) for k, v in results.items()}
    out["boundary_overhead_pct"] = round(
        100 * (1 - results["boundary_jit"] / bound), 1)
    out["ingraph_overhead_pct"] = round(
        100 * (1 - results["ingraph_pyfunc"] / bound), 1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
