"""TF2 eager/function MNIST-class training (reference
example/tensorflow/tensorflow2_mnist.py, synthetic data).

Run:  python example/tensorflow/tensorflow2_mnist.py [--steps N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse

import numpy as np
import tensorflow as tf

import byteps_tpu.tensorflow as bps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()

    bps.init()
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    opt = tf.keras.optimizers.SGD(0.05)

    rng = np.random.RandomState(bps.rank())
    x = tf.constant(rng.randn(args.batch, 784).astype(np.float32))
    y = tf.constant(rng.randint(0, 10, args.batch))

    @tf.function
    def step():
        with tf.GradientTape() as tape:
            logits = model(x, training=True)
            loss = tf.reduce_mean(
                tf.nn.sparse_softmax_cross_entropy_with_logits(y, logits))
        tape = bps.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    model.build((None, 784))
    # consistent start across workers (eager: before the first traced step)
    bps.broadcast_variables(model.variables, root_rank=0)

    for i in range(args.steps):
        loss = step()
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
