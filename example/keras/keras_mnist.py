"""Keras MNIST-class training with byteps_tpu callbacks (reference
example/keras/keras_mnist.py, synthetic data).

Run:  python example/keras/keras_mnist.py [--epochs N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse

import numpy as np
import tensorflow as tf

import byteps_tpu.keras as bps_keras
import byteps_tpu.tensorflow as bps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()

    bps.init()
    rng = np.random.RandomState(bps.rank())
    x = rng.randn(512, 784).astype(np.float32)
    y = rng.randint(0, 10, 512)

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10, activation="softmax"),
    ])
    # scale lr by worker count (reference keras examples do the same)
    opt = tf.keras.optimizers.SGD(0.05 * bps.size())
    opt = bps_keras.DistributedOptimizer(opt)
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"],
                  run_eagerly=True)  # engine hop is a host callback

    callbacks = [
        bps_keras.callbacks.BroadcastGlobalVariablesCallback(0),
        bps_keras.callbacks.MetricAverageCallback(),
        bps_keras.callbacks.LearningRateWarmupCallback(
            warmup_epochs=1, verbose=0),
    ]
    model.fit(x, y, batch_size=args.batch, epochs=args.epochs,
              callbacks=callbacks,
              verbose=2 if bps.rank() == 0 else 0)
    bps.shutdown()


if __name__ == "__main__":
    main()
