"""Cross-barrier pipelining benchmark (reference
example/pytorch/benchmark_cross_barrier_byteps.py): remove the
end-of-iteration barrier so communication overlaps the *next* forward
pass; per-layer averaged gradients are applied just-in-time.

Run:  python example/pytorch/benchmark_cross_barrier_byteps.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse
import time

import torch
import torch.nn.functional as F

import byteps_tpu.torch as bps
from byteps_tpu.torch.parallel import CrossBarrier


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-iters", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    bps.init()
    model = torch.nn.Sequential(
        torch.nn.Linear(1024, 2048), torch.nn.ReLU(),
        torch.nn.Linear(2048, 2048), torch.nn.ReLU(),
        torch.nn.Linear(2048, 1000))
    opt = torch.optim.SGD(model.parameters(), lr=0.01)
    xb = CrossBarrier(model, opt)

    x = torch.randn(args.batch, 1024)
    y = torch.randint(0, 1000, (args.batch,))

    F.cross_entropy(model(x), y).backward()  # warm-up
    xb.step()
    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        opt.zero_grad()
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        xb.step()  # returns immediately; grads applied at next forward
    xb.synchronize()  # drain before timing stops
    dt = time.perf_counter() - t0
    print(f"{args.num_iters * args.batch / dt:.1f} examples/s "
          f"with cross-barrier overlap")
    bps.shutdown()


if __name__ == "__main__":
    main()
