"""DistributedDataParallel benchmark (reference
example/pytorch/benchmark_byteps_ddp.py): gradient sync via backward
hooks with bucketing + no_sync() accumulation.

Run:  python example/pytorch/benchmark_byteps_ddp.py [--num-iters N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse
import time

import torch
import torch.nn.functional as F

import byteps_tpu.torch as bps
from byteps_tpu.torch.parallel import DistributedDataParallel


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-iters", type=int, default=20)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--accumulate", type=int, default=1,
                    help="micro-steps under no_sync() per sync step")
    args = ap.parse_args()

    bps.init()
    model = torch.nn.Sequential(
        torch.nn.Linear(1024, 2048), torch.nn.ReLU(),
        torch.nn.Linear(2048, 2048), torch.nn.ReLU(),
        torch.nn.Linear(2048, 1000))
    ddp = DistributedDataParallel(model)
    opt = torch.optim.SGD(model.parameters(), lr=0.01)

    x = torch.randn(args.batch, 1024)
    y = torch.randint(0, 1000, (args.batch,))

    def micro(sync: bool):
        if sync:
            loss = F.cross_entropy(ddp(x), y)
            loss.backward()
        else:
            with ddp.no_sync():
                loss = F.cross_entropy(ddp(x), y)
                loss.backward()
        return loss

    micro(True)  # warm-up
    opt.zero_grad()
    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        for _ in range(args.accumulate - 1):
            micro(sync=False)
        micro(sync=True)
        opt.step()
        opt.zero_grad()
    dt = time.perf_counter() - t0
    ex = args.num_iters * args.accumulate * args.batch
    print(f"{ex / dt:.1f} examples/s ({args.num_iters} sync steps, "
          f"accumulate={args.accumulate})")
    bps.shutdown()


if __name__ == "__main__":
    main()
