"""MNIST-class training with the torch adapter (reference
example/pytorch/train_mnist_byteps.py, synthetic data).

Run:  python example/pytorch/train_mnist_byteps.py [--epochs N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse

import torch
import torch.nn.functional as F

import byteps_tpu.torch as bps


class Net(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(784, 128)
        self.fc2 = torch.nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(torch.relu(self.fc1(x)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    bps.init()
    torch.manual_seed(bps.rank())  # different data per worker
    model = Net()
    opt = torch.optim.SGD(model.parameters(), lr=args.lr, momentum=0.9)
    opt = bps.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    # consistent start across workers (reference broadcast_parameters)
    bps.broadcast_parameters(model.state_dict(), root_rank=0)

    x = torch.randn(args.batch, 784)
    y = torch.randint(0, 10, (args.batch,))
    for i in range(args.steps):
        opt.zero_grad()
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss.detach()):.4f}")
    bps.shutdown()


if __name__ == "__main__":
    main()
