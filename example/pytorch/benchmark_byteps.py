"""Synthetic gradient push_pull benchmark, torch frontend (reference
example/pytorch/benchmark_byteps.py shape: timed push_pull of
model-sized gradients, optional compression).

Run:  python example/pytorch/benchmark_byteps.py [--num-iters N]
      [--compressor onebit|topk|randomk|dithering]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import argparse
import time

import torch

import byteps_tpu.torch as bps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--num-tensors", type=int, default=20)
    ap.add_argument("--tensor-mb", type=float, default=4.0)
    ap.add_argument("--compressor", default=None)
    args = ap.parse_args()

    bps.init()
    n_elem = int(args.tensor_mb * 1e6 / 4)
    grads = [torch.randn(n_elem) for _ in range(args.num_tensors)]
    comp = {"compressor": args.compressor} if args.compressor else None
    if comp and args.compressor in ("topk", "randomk"):
        comp["k"] = str(max(1, n_elem // 100))

    # warm-up (compilation)
    hs = [bps.push_pull_async(g, name=f"bench.{i}", compression=comp)
          for i, g in enumerate(grads)]
    for h in hs:
        bps.synchronize(h)

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        hs = [bps.push_pull_async(g, name=f"bench.{i}", compression=comp)
              for i, g in enumerate(grads)]
        for h in hs:
            bps.synchronize(h)
    dt = time.perf_counter() - t0
    total_mb = args.num_iters * args.num_tensors * args.tensor_mb
    print(f"{total_mb / dt:.1f} MB/s pushed+pulled "
          f"({args.num_tensors} x {args.tensor_mb} MB x "
          f"{args.num_iters} iters in {dt:.2f}s)")
    print("engine telemetry:", bps.size() and
          __import__("byteps_tpu").get_pushpull_speed())
    bps.shutdown()


if __name__ == "__main__":
    main()
