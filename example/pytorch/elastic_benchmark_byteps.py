"""Elastic training demo (reference
example/pytorch/elastic_benchmark_byteps.py): suspend() mid-training,
then resume() — declared tensors keep their key order, so training
continues with identical scheduling.

Run:  python example/pytorch/elastic_benchmark_byteps.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from example._common import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

import torch
import torch.nn.functional as F

import byteps_tpu as bps_core
import byteps_tpu.torch as bps


def main():
    bps.init()
    model = torch.nn.Linear(256, 10)
    opt = bps.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())
    x = torch.randn(64, 256)
    y = torch.randint(0, 10, (64,))

    def train(steps):
        for _ in range(steps):
            opt.zero_grad()
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
        return float(loss.detach())

    print("phase 1 loss:", round(train(5), 4))
    bps_core.suspend()          # drain engine, drop mesh
    print("suspended (simulating topology change)...")
    bps_core.resume()           # re-init; keys re-declared in order
    print("resumed")
    print("phase 2 loss:", round(train(5), 4))
    bps.shutdown()


if __name__ == "__main__":
    main()
