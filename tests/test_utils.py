"""utils: checkpoint save/restore-and-broadcast (SURVEY.md §5 — the
reference's restore-consistency contract), timing helpers."""

import numpy as np
import pytest

import byteps_tpu as bps
from byteps_tpu.utils import (CheckpointManager, Timer,
                              restore_and_broadcast, save_checkpoint,
                              throughput)


@pytest.fixture
def session():
    bps.init()
    yield
    bps.shutdown()


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {"params": {"w": rng.randn(4, 3).astype(np.float32),
                       "b": rng.randn(3).astype(np.float32)},
            "step": np.int32(7)}


def test_save_restore_broadcast_roundtrip(session, tmp_path):
    state = _state()
    assert save_checkpoint(str(tmp_path / "ck"), state)
    tmpl = {"params": {"w": np.zeros((4, 3), np.float32),
                       "b": np.zeros(3, np.float32)},
            "step": np.int32(0)}
    out = restore_and_broadcast(str(tmp_path / "ck"), tmpl)
    np.testing.assert_allclose(out["params"]["w"], state["params"]["w"])
    np.testing.assert_allclose(out["params"]["b"], state["params"]["b"])
    assert int(out["step"]) == 7
    assert out["params"]["w"].dtype == np.float32


def test_checkpoint_manager_retention_and_latest(session, tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpts"), max_to_keep=2)
    try:
        for step in (1, 2, 3):
            st = _state(seed=step)
            assert mgr.save(step, st)
        assert mgr.latest_step() == 3
        step, out = mgr.restore_latest(_state(seed=0))
        assert step == 3
        np.testing.assert_allclose(out["params"]["w"],
                                   _state(seed=3)["params"]["w"])
        # retention: only 2 kept
        import os
        kept = [d for d in os.listdir(tmp_path / "ckpts") if d.isdigit()]
        assert sorted(int(d) for d in kept) == [2, 3]
    finally:
        mgr.close()


def test_restore_latest_empty_returns_template(session, tmp_path):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    try:
        tmpl = _state()
        step, out = mgr.restore_latest(tmpl)
        assert step is None and out is tmpl
    finally:
        mgr.close()


def test_throughput_counts_items():
    calls = []
    rate = throughput(lambda: calls.append(1), steps=5, items_per_step=10)
    assert len(calls) == 6  # 1 warmup + 5 timed
    assert rate > 0


def test_timer_context():
    with Timer() as t:
        pass
    assert t.elapsed >= 0


def test_async_save_checkpoint_roundtrip(session, tmp_path):
    from byteps_tpu.utils import PendingSave
    state = _state(seed=11)
    pending = save_checkpoint(str(tmp_path / "ack"), state,
                              asynchronous=True)
    assert isinstance(pending, PendingSave)
    assert pending.wait()  # durable now
    tmpl = {"params": {"w": np.zeros((4, 3), np.float32),
                       "b": np.zeros(3, np.float32)},
            "step": np.int32(0)}
    out = restore_and_broadcast(str(tmp_path / "ack"), tmpl)
    np.testing.assert_allclose(out["params"]["w"], state["params"]["w"])


def test_async_checkpoint_manager(session, tmp_path):
    """async_save=True: save() returns without blocking on IO; in-flight
    writes join at restore_latest/wait; overwritten host state after
    save() does not corrupt the snapshot."""
    mgr = CheckpointManager(str(tmp_path / "ackpts"), max_to_keep=2,
                            async_save=True)
    try:
        st = _state(seed=4)
        assert mgr.save(1, st)
        st["params"]["w"][:] = -1.0  # mutate AFTER save returned
        assert mgr.save(2, _state(seed=5))
        mgr.wait_until_finished()
        step, out = mgr.restore_latest(_state(seed=0))
        assert step == 2
        np.testing.assert_allclose(out["params"]["w"],
                                   _state(seed=5)["params"]["w"])
        # the step-1 snapshot must hold the PRE-mutation values: orbax
        # copies before its background write, so save(); mutate; is safe
        import orbax.checkpoint as ocp
        from byteps_tpu.utils.checkpoint import _abstract_tree
        old = mgr._mgr.restore(
            1, args=ocp.args.StandardRestore(_abstract_tree(_state(0))))
        np.testing.assert_allclose(old["params"]["w"],
                                   _state(seed=4)["params"]["w"])
    finally:
        mgr.close()
