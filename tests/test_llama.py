"""Llama family tests: RoPE/RMSNorm/SwiGLU/GQA correctness and the
(fsdp, tp) composite step pinned against single-device math.

The established parity pattern (test_tensor_parallel.py): the sharding
must change the placement, never the numbers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.models.llama import (Llama, LlamaConfig, apply_rope,
                                     llama3_8b, llama_tiny, lm_loss,
                                     rope_frequencies)
from byteps_tpu.parallel.fsdp_tp import (
    FSDP_AXIS, TP_AXIS, fsdp_tp_spec_for, init_llama_opt_state,
    make_fsdp_tp_mesh, make_fsdp_tp_train_step, shard_llama_batch,
    shard_llama_params)
from byteps_tpu.parallel.long_context import synthetic_lm_batch
from .conftest import legacy_skip


def _cfg():
    # f32 end to end: the parity tests need bit-comparable math (one
    # shared definition — models.llama.llama_tiny_f32)
    from byteps_tpu.models.llama import llama_tiny_f32
    return llama_tiny_f32()


# ------------------------------------------------------------------ rotary

def test_rope_matches_naive():
    """apply_rope == the rotate-half formula (HF Llama checkpoint
    convention: pair (x[i], x[i+d/2]), not interleaved)."""
    d, t = 8, 16
    x = np.random.RandomState(0).randn(1, t, 2, d).astype(np.float32)
    pos = jnp.arange(t)[None]
    cos, sin = rope_frequencies(d, pos, theta=10000.0)
    got = np.asarray(apply_rope(jnp.asarray(x), cos, sin))

    inv = 1.0 / (10000.0 ** (np.arange(0, d, 2) / d))
    ang = np.arange(t)[:, None] * inv[None]  # [t, d/2]
    want = np.empty_like(x)
    for h in range(2):
        x1, x2 = x[0, :, h, :d // 2], x[0, :, h, d // 2:]
        want[0, :, h, :d // 2] = x1 * np.cos(ang) - x2 * np.sin(ang)
        want[0, :, h, d // 2:] = x1 * np.sin(ang) + x2 * np.cos(ang)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_rope_relative_position_property():
    """q(m) . k(n) after RoPE depends only on m - n: shifting both
    positions by the same offset leaves every dot product unchanged."""
    d = 16
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 4, 1, d).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 4, 1, d).astype(np.float32))

    def dots(offset):
        pos = (jnp.arange(4) + offset)[None]
        cos, sin = rope_frequencies(d, pos, theta=10000.0)
        qr, kr = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
        return np.asarray(jnp.einsum("bthd,bshd->bhts", qr, kr))

    np.testing.assert_allclose(dots(0), dots(37), rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------------- model

def test_gqa_matches_mha_with_tiled_kv_weights():
    """The GQA repeat path is exact: a GQA model (kv_heads < heads) must
    produce bit-identical outputs to an MHA model (kv_heads == heads)
    whose K/V kernels are the GQA kernels tiled along the head axis —
    repeating heads after projection == projecting with repeated weights."""
    import dataclasses
    cfg_gqa = _cfg()                      # 4 q heads, 2 kv heads
    cfg_mha = dataclasses.replace(cfg_gqa, num_kv_heads=4)
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 128, (2, 16)))
    m_gqa, m_mha = Llama(cfg_gqa), Llama(cfg_mha)
    p_gqa = m_gqa.init(jax.random.PRNGKey(0), ids)
    groups = cfg_gqa.num_heads // cfg_gqa.num_kv_heads

    p_mha = jax.tree.map(lambda x: x, p_gqa)  # shallow copy of the dicts
    for layer in (f"h{i}" for i in range(cfg_gqa.num_layers)):
        attn = dict(p_mha["params"][layer]["attn"])
        for name in ("k", "v"):
            kern = attn[name]["kernel"]  # [hidden, kv_heads, head_dim]
            attn[name] = {"kernel": jnp.repeat(kern, groups, axis=1)}
        p_mha["params"][layer] = {**p_mha["params"][layer], "attn": attn}

    out_gqa = m_gqa.apply(p_gqa, ids)
    out_mha = m_mha.apply(p_mha, ids)
    # ulp-level drift only: the two head layouts contract in different
    # orders; a wrong-axis repeat would diverge by O(1)
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha),
                               rtol=1e-4, atol=1e-5)


def test_llama_trains_single_device():
    cfg = _cfg()
    model = Llama(cfg)
    batch = synthetic_lm_batch(jax.random.PRNGKey(3), cfg, batch=8,
                               seq_len=16)
    params = model.init(jax.random.PRNGKey(4), batch["input_ids"][:1])
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(p, o, b):
        loss, g = jax.value_and_grad(
            lambda q: lm_loss(model.apply(q, b["input_ids"]),
                              b["labels"]))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    losses = []
    for _ in range(10):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_llama3_8b_geometry():
    """The 8B config has the advertised parameter count (structure only —
    eval_shape, no allocation)."""
    cfg = llama3_8b()
    model = Llama(cfg)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32)))
    n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    assert 7.9e9 < n < 8.2e9, n


def test_config_validation():
    with pytest.raises(ValueError, match="divisible"):
        LlamaConfig(num_heads=5, num_kv_heads=2)


# ----------------------------------------------------------- (fsdp, tp)

def test_rules_cover_the_sharded_layers():
    P = jax.sharding.PartitionSpec
    assert fsdp_tp_spec_for("h0/attn/q/kernel") == P(FSDP_AXIS, TP_AXIS,
                                                     None)
    assert fsdp_tp_spec_for("h0/attn/out/kernel") == P(TP_AXIS, None,
                                                       FSDP_AXIS)
    assert fsdp_tp_spec_for("h1/mlp/gate/kernel") == P(FSDP_AXIS, TP_AXIS)
    assert fsdp_tp_spec_for("h1/mlp/down/kernel") == P(TP_AXIS, FSDP_AXIS)
    assert fsdp_tp_spec_for("h0/attn_norm/scale") == P()
    assert fsdp_tp_spec_for("wte/embedding") == P(TP_AXIS, FSDP_AXIS)


@legacy_skip  # sharded-init tracking needs modern shard_map
def test_sharded_init_never_materializes_unsharded():
    """init_llama_params_sharded births every weight on its (fsdp, tp)
    placement and matches the shard-after-init route bit for bit."""
    cfg = _cfg()
    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=4)
    ids = jnp.zeros((1, 8), jnp.int32)
    from byteps_tpu.parallel.fsdp_tp import init_llama_params_sharded
    p_a = init_llama_params_sharded(mesh, cfg, jax.random.PRNGKey(5), ids)
    p_b = shard_llama_params(
        mesh, Llama(cfg).init(jax.random.PRNGKey(5), ids))
    q = p_a["params"]["h0"]["attn"]["q"]["kernel"]
    assert q.addressable_shards[0].data.shape[0] * 2 == q.shape[0]
    # jit-compiled vs eager init differ at ulp level only
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), p_a, p_b)


def test_unmatched_large_leaf_gets_fsdp_fallback():
    """A large param whose path matches no rule is fsdp-sharded on its
    largest divisible axis, not silently replicated."""
    from byteps_tpu.parallel.fsdp_tp import llama_shardings
    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=4)
    P = jax.sharding.PartitionSpec
    fake = {"params": {"adapter": {"lora_A": jnp.zeros((512, 256)),
                                   "tiny": jnp.zeros((8,))}}}
    sh = llama_shardings(mesh, fake)
    assert sh["params"]["adapter"]["lora_A"].spec == P(FSDP_AXIS, None)
    assert sh["params"]["adapter"]["tiny"].spec == P()


def test_fsdp_tp_params_are_distributed():
    cfg = _cfg()
    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=4)  # fsdp=2 x tp=4
    model = Llama(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = shard_llama_params(
        mesh, model.init(jax.random.PRNGKey(5), ids))
    q = params["params"]["h0"]["attn"]["q"]["kernel"]
    shard = q.addressable_shards[0].data
    # hidden split over fsdp (2), heads over tp (4): 1/8 per device
    assert shard.shape[0] * 2 == q.shape[0]
    assert shard.shape[1] * 4 == q.shape[1]
    norm = params["params"]["h0"]["attn_norm"]["scale"]
    assert norm.addressable_shards[0].data.shape == norm.shape


def test_fsdp_tp_matches_single_device_math():
    cfg = _cfg()
    model = Llama(cfg)
    rng = jax.random.PRNGKey(6)
    batch = synthetic_lm_batch(rng, cfg, batch=4, seq_len=16)
    params0 = model.init(rng, batch["input_ids"][:1])
    tx = optax.sgd(0.1)

    @jax.jit
    def ref_step(p, o, b):
        loss, g = jax.value_and_grad(
            lambda q: lm_loss(model.apply(q, b["input_ids"]),
                              b["labels"]))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    p_ref, o_ref = params0, tx.init(params0)
    for _ in range(3):
        p_ref, o_ref, loss_ref = ref_step(p_ref, o_ref, batch)

    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=4)
    p_sh = shard_llama_params(mesh, params0)
    o_sh = init_llama_opt_state(tx, p_sh)
    step = make_fsdp_tp_train_step(mesh, cfg, tx)
    b_sh = shard_llama_batch(mesh, batch)
    for _ in range(3):
        p_sh, o_sh, loss_sh = step(p_sh, o_sh, b_sh)

    np.testing.assert_allclose(float(loss_sh), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(p_ref),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(p_sh),
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=str(ka))


def test_fsdp_tp_step_trains_and_keeps_placement():
    cfg = _cfg()
    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=2)  # fsdp=4 x tp=2
    model = Llama(cfg)
    rng = jax.random.PRNGKey(7)
    batch = synthetic_lm_batch(rng, cfg, batch=8, seq_len=16)
    params = shard_llama_params(mesh,
                                model.init(rng, batch["input_ids"][:1]))
    tx = optax.adam(1e-2)
    opt = init_llama_opt_state(tx, params)
    step = make_fsdp_tp_train_step(mesh, cfg, tx)
    batch = shard_llama_batch(mesh, batch)
    losses = []
    for _ in range(10):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
    gate = params["params"]["h0"]["mlp"]["gate"]["kernel"]
    shard = gate.addressable_shards[0].data
    assert shard.shape[0] * 4 == gate.shape[0]  # fsdp placement survives
    assert shard.shape[1] * 2 == gate.shape[1]  # tp placement survives
    # adam moments are sharded like their params (memory scaling claim)
    mu = opt[0].mu["params"]["h0"]["mlp"]["gate"]["kernel"]
    assert mu.addressable_shards[0].data.shape == shard.shape


def test_bf16_fsdp_tp_trains():
    """The flagship composite in its DEPLOYMENT dtype: llama_tiny keeps
    the bf16 default, and the GSPMD (fsdp, tp) step must train on the CPU
    mesh — unlike the 3D shard_map path, whose partial-manual bf16 psum
    still crashes XLA CPU (tests/test_three_d.py canary).  Round-3
    VERDICT Weak #4 closed: bf16 composite loss recorded from the CPU
    backend; bench.py records it per-backend as bf16_fsdp_tp."""
    from byteps_tpu.models.llama import llama_tiny

    cfg = llama_tiny()
    assert cfg.dtype == jnp.bfloat16
    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=2)
    model = Llama(cfg)
    rng = jax.random.PRNGKey(0)
    batch = synthetic_lm_batch(rng, cfg, batch=8, seq_len=16)
    params = shard_llama_params(mesh,
                                model.init(rng, batch["input_ids"][:1]))
    tx = optax.adam(1e-2)
    opt = init_llama_opt_state(tx, params)
    step = make_fsdp_tp_train_step(mesh, cfg, tx)
    b = shard_llama_batch(mesh, batch)
    losses = []
    for _ in range(8):
        params, opt, loss = step(params, opt, b)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0] * 0.5, losses


def test_opt_state_sharding_survives_shape_collision():
    """Two params with identical shape+dtype but different shardings must
    each get their own sharding on the adam moments — the structural
    (key-path suffix) match can't be fooled the way a (shape, dtype)
    lookup was (round-3 ADVICE: square weights when hidden ==
    intermediate)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from byteps_tpu.parallel.mesh_util import make_2d_mesh

    mesh = make_2d_mesh(jax.devices()[:8], 2, ("fsdp", "tp"))
    sh_a = NamedSharding(mesh, P("fsdp", "tp"))
    sh_b = NamedSharding(mesh, P("tp", "fsdp"))
    params = {
        "a": {"kernel": jax.device_put(jnp.ones((8, 8)), sh_a)},
        "b": {"kernel": jax.device_put(jnp.ones((8, 8)), sh_b)},
    }
    opt = init_llama_opt_state(optax.adam(1e-3), params)
    mu = opt[0].mu
    assert mu["a"]["kernel"].sharding.spec == P("fsdp", "tp")
    assert mu["b"]["kernel"].sharding.spec == P("tp", "fsdp")


def test_unsharded_params_rejected():
    cfg = _cfg()
    mesh = make_fsdp_tp_mesh(jax.devices()[:8], n_tp=4)
    model = Llama(cfg)
    batch = synthetic_lm_batch(jax.random.PRNGKey(8), cfg, 4, 16)
    params = model.init(jax.random.PRNGKey(9), batch["input_ids"][:1])
    tx = optax.sgd(0.1)
    step = make_fsdp_tp_train_step(mesh, cfg, tx)
    with pytest.raises(ValueError, match="not mesh-sharded"):
        step(params, tx.init(params), shard_llama_batch(mesh, batch))