"""Server sum-engine + key-sharding semantics (SURVEY.md §2.3:
server.cc COPY_FIRST/SUM_RECV/ALL_RECV flow, queue.h priority
scheduling, server.h sticky thread assignment, global.cc hashing)."""

import threading
import time

import numpy as np
import pytest

from byteps_tpu.server.engine import PriorityQueue, ServerEngine, _Msg
from byteps_tpu.server.sharding import (ServerAssigner, hash_djb2,
                                        hash_naive, hash_sdbm)


def _msg(key, **kw):
    return _Msg(key=key, **kw)


# --- merge flow -------------------------------------------------------------


def test_push_pull_barrier_flow():
    eng = ServerEngine(num_threads=2)
    try:
        w = 3
        for r in range(w):
            eng.push("k", np.full(4, float(r + 1)), worker_id=r,
                     num_workers=w)
        out = eng.pull("k", timeout=5)
        np.testing.assert_allclose(out, 6.0)   # 1+2+3
        assert eng.version("k") == 1
        # next round: COPY_FIRST replaces, not accumulates
        for r in range(w):
            eng.push("k", np.full(4, 1.0), worker_id=r, num_workers=w)
        np.testing.assert_allclose(eng.pull("k", timeout=5), 3.0)
        assert eng.version("k") == 2
    finally:
        eng.shutdown()


def test_pull_parks_until_all_workers_arrive():
    eng = ServerEngine(num_threads=1)
    try:
        eng.push("p", np.ones(2), worker_id=0, num_workers=2)
        got = {}

        def puller():
            got["v"] = eng.pull("p", timeout=5)

        t = threading.Thread(target=puller)
        t.start()
        time.sleep(0.15)
        assert "v" not in got          # parked: only 1/2 pushes in
        eng.push("p", np.ones(2), worker_id=1, num_workers=2)
        t.join(timeout=5)
        np.testing.assert_allclose(got["v"], 2.0)
    finally:
        eng.shutdown()


def test_many_keys_many_threads_consistent():
    eng = ServerEngine(num_threads=4)
    try:
        w, keys = 4, [f"t{i}" for i in range(16)]
        for k in keys:
            for r in range(w):
                eng.push(k, np.full(8, float(r)), worker_id=r, num_workers=w)
        for k in keys:
            np.testing.assert_allclose(eng.pull(k, timeout=5), 0 + 1 + 2 + 3)
    finally:
        eng.shutdown()


def test_sticky_least_loaded_assignment():
    eng = ServerEngine(num_threads=2)
    try:
        a = eng.thread_id("a", 100)
        b = eng.thread_id("b", 10)
        assert a != b                   # second key goes to the idle thread
        c = eng.thread_id("c", 10)
        assert c == b                   # b's thread still lighter (20 < 100)
        assert eng.thread_id("a", 999) == a  # sticky: cached, no rebalance
    finally:
        eng.shutdown()


def test_pull_parks_during_partially_merged_round():
    """A pull between COPY_FIRST and round completion must park — never
    return one worker's raw contribution as if it were a merge."""
    eng = ServerEngine(num_threads=1)
    try:
        for r in range(2):
            eng.push("k", np.ones(2), worker_id=r, num_workers=2)
        eng.pull("k", timeout=5)
        eng.push("k", np.full(2, 7.0), worker_id=0, num_workers=2)
        time.sleep(0.2)  # engine pops COPY_FIRST; round incomplete
        res = {}
        t = threading.Thread(
            target=lambda: res.update(v=eng.pull("k", timeout=5)))
        t.start()
        time.sleep(0.2)
        assert "v" not in res
        eng.push("k", np.full(2, 1.0), worker_id=1, num_workers=2)
        t.join(5)
        np.testing.assert_allclose(res["v"], 8.0)
    finally:
        eng.shutdown()


def test_bad_push_rejected_caller_side_engine_survives():
    eng = ServerEngine(num_threads=1)
    try:
        for r in range(2):
            eng.push("k", np.ones(2), worker_id=r, num_workers=2)
        eng.pull("k", timeout=5)
        with pytest.raises(ValueError):
            eng.push("k", np.ones(5), worker_id=0, num_workers=2)
        for r in range(2):
            eng.push("k", np.ones(2), worker_id=r, num_workers=2)
        np.testing.assert_allclose(eng.pull("k", timeout=5), 2.0)
    finally:
        eng.shutdown()


def test_dtype_mismatch_rejected_caller_side():
    eng = ServerEngine(num_threads=1)
    try:
        eng.push("d", np.ones(2, np.float32), worker_id=0, num_workers=2)
        with pytest.raises(ValueError):
            eng.push("d", np.ones(2, np.float64), worker_id=1,
                     num_workers=2)
    finally:
        eng.shutdown()


def test_engine_merge_failure_poisons_key_not_thread(monkeypatch):
    """If a merge genuinely fails on the engine thread, the key is
    poisoned (parked + future ops raise) but the thread and other keys
    survive."""
    import byteps_tpu.server.engine as eng_mod

    calls = {"n": 0}
    real = eng_mod.inplace_add

    def flaky(dst, src, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected merge failure")
        return real(dst, src, *a, **kw)

    monkeypatch.setattr(eng_mod, "inplace_add", flaky)
    eng = ServerEngine(num_threads=1)
    try:
        eng.push("bad", np.ones(2), worker_id=0, num_workers=2)
        eng.push("bad", np.ones(2), worker_id=1, num_workers=2)  # fails
        with pytest.raises(RuntimeError):
            eng.pull("bad", timeout=5)
        with pytest.raises(RuntimeError):
            eng.push("bad", np.ones(2), worker_id=0, num_workers=2)
        # a different key on the same (sole) thread still works
        for r in range(2):
            eng.push("good", np.ones(2), worker_id=r, num_workers=2)
        np.testing.assert_allclose(eng.pull("good", timeout=5), 2.0)
    finally:
        eng.shutdown()


def _poison(eng, monkeypatch, eng_mod, key="bad"):
    """Poison ``key`` via one injected merge failure (the engine-thread
    path the chaos bitflip also exercises)."""
    calls = {"n": 0}
    real = eng_mod.inplace_add

    def flaky(dst, src, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected merge failure")
        return real(dst, src, *a, **kw)

    monkeypatch.setattr(eng_mod, "inplace_add", flaky)
    eng.push(key, np.ones(2), worker_id=0, num_workers=2)
    eng.push(key, np.ones(2), worker_id=1, num_workers=2)  # fails
    with pytest.raises(RuntimeError):
        eng.pull(key, timeout=5)


@pytest.mark.chaos
def test_reset_key_restores_service_after_poison(monkeypatch):
    """Satellite: a recovery pass clears a poisoned key with reset_key()
    and push/pull works again — poisoning is no longer terminal."""
    import byteps_tpu.server.engine as eng_mod

    eng = ServerEngine(num_threads=1)
    try:
        _poison(eng, monkeypatch, eng_mod)
        with pytest.raises(RuntimeError):
            eng.push("bad", np.ones(2), worker_id=0, num_workers=2)

        eng.reset_key("bad")
        # the key serves full rounds again — and with a fresh geometry,
        # since reset also clears the established shape/dtype
        for r in range(2):
            eng.push("bad", np.full(3, 2.0), worker_id=r, num_workers=2)
        np.testing.assert_allclose(eng.pull("bad", timeout=5), 4.0)
        assert eng.version("bad") >= 1
    finally:
        eng.shutdown()


@pytest.mark.chaos
def test_reset_key_fails_parked_pulls_and_drops_stale_pushes():
    """A pull parked on a round that reset_key sweeps away belongs to the
    dead era: it fails loudly (never silently re-parks into the fresh
    epoch), and the half-round's push cannot leak into the next round."""
    import threading

    eng = ServerEngine(num_threads=1)
    try:
        eng.push("k", np.full(2, 9.0), worker_id=0, num_workers=2)
        res = {}

        def parked():
            try:
                eng.pull("k", timeout=5)
            except RuntimeError as e:
                res["err"] = str(e)

        t = threading.Thread(target=parked)
        t.start()
        time.sleep(0.2)           # pull is parked: 1/2 pushes in
        eng.reset_key("k")
        t.join(5)
        assert "poisoned while this pull was parked" in res["err"]
        # fresh epoch: a full round merges cleanly, the pre-reset 9.0
        # contribution is gone
        for r in range(2):
            eng.push("k", np.ones(2), worker_id=r, num_workers=2)
        np.testing.assert_allclose(eng.pull("k", timeout=5), 2.0)
    finally:
        eng.shutdown()


@pytest.mark.chaos
def test_fault_injected_bitflip_poison_then_reset_recovers(monkeypatch):
    """End-to-end chaos loop on the UNPROTECTED server path
    (BYTEPS_INTEGRITY=0 — the pre-envelope baseline this pins): a
    bitflip-corrupted push merges into a wrong sum (detected by value),
    and reset_key gives the recovery pass a clean slate."""
    from byteps_tpu.common.config import reset_config
    from byteps_tpu.fault import injector as inj_mod

    monkeypatch.setenv("BYTEPS_INTEGRITY", "0")
    reset_config()
    inj_mod.arm("bitflip:site=server_push:p=1", seed=5, rank=0)
    eng = ServerEngine(num_threads=1)
    try:
        for r in range(2):
            eng.push("k", np.ones(4, np.float32), worker_id=r,
                     num_workers=2)
        corrupted = eng.pull("k", timeout=5)
        assert not np.allclose(corrupted, 2.0)  # the flip really landed
        inj_mod.disarm()
        eng.reset_key("k")
        for r in range(2):
            eng.push("k", np.ones(4, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_allclose(eng.pull("k", timeout=5), 2.0)
    finally:
        inj_mod.disarm()
        eng.shutdown()


@pytest.mark.chaos
@pytest.mark.integrity
def test_fault_injected_bitflip_detected_and_retransmitted():
    """The same chaos site with the integrity envelope armed (the
    default): every corrupted frame is NACKed (integrity.crc_reject),
    retransmitted from the caller's source copy, and the merged sum is
    exact — the silent-poisoning proof inverted into a resilience
    proof."""
    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.fault import injector as inj_mod

    counters.reset()
    inj_mod.arm("bitflip:site=server_push:p=0.5", seed=3, rank=0)
    eng = ServerEngine(num_threads=1)
    try:
        for r in range(4):
            eng.push("k", np.ones(64, np.float32), worker_id=r,
                     num_workers=4)
        np.testing.assert_array_equal(eng.pull("k", timeout=5), 4.0)
        assert counters.get("integrity.crc_reject") > 0
        assert counters.get("integrity.retransmit") > 0
    finally:
        inj_mod.disarm()
        eng.shutdown()


def test_pull_retry_survives_transient_timeout():
    """RetryPolicy on pull: the first wait times out (round incomplete),
    the straggler lands during the backoff, the retried pull succeeds."""
    import threading
    import time as _time
    from byteps_tpu.common.retry import RetryPolicy

    eng = ServerEngine(num_threads=1)
    try:
        eng.push("r", np.ones(2), worker_id=0, num_workers=2)

        def straggler():
            _time.sleep(0.4)
            eng.push("r", np.ones(2), worker_id=1, num_workers=2)

        t = threading.Thread(target=straggler)
        t.start()
        out = eng.pull("r", timeout=0.15,
                       retry=RetryPolicy(max_attempts=10, base_delay_s=0.05,
                                         max_delay_s=0.1))
        t.join(5)
        np.testing.assert_allclose(out, 2.0)
    finally:
        eng.shutdown()


def test_built_in_hash_deterministic_across_processes():
    """hash_built_in must not depend on Python's salted hash()."""
    import os
    import subprocess
    import sys
    import byteps_tpu
    repo_root = os.path.dirname(os.path.dirname(byteps_tpu.__file__))
    code = ("from byteps_tpu.server.sharding import hash_built_in;"
            "print(hash_built_in(123456))")
    outs = {subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           env={**os.environ, "PYTHONHASHSEED": seed,
                                "PYTHONPATH": repo_root},
                           check=True).stdout.strip()
            for seed in ("1", "2")}
    assert len(outs) == 1


# --- priority queue ---------------------------------------------------------


def test_priority_queue_fifo_without_schedule():
    q = PriorityQueue(enable_schedule=False)
    for i, k in enumerate(["x", "y", "x"]):
        q.push(_msg(k, worker_id=i))
    order = [q.wait_and_pop().worker_id for _ in range(3)]
    assert order == [0, 1, 2]


def test_priority_queue_schedule_prefers_fewest_outstanding():
    """queue.h ComparePriority: the key with fewer outstanding pushes pops
    first (it is closer to completing its merge)."""
    q = PriorityQueue(enable_schedule=True)
    q.push(_msg("busy", worker_id=0))
    q.push(_msg("busy", worker_id=1))
    q.push(_msg("fresh", worker_id=2))
    first = q.wait_and_pop()
    assert first.key in ("busy", "fresh")
    # 'fresh' (1 outstanding) must come out before busy's second message
    popped = [first.key] + [q.wait_and_pop().key for _ in range(2)]
    assert popped.index("fresh") <= 1
    q.clear_counter("busy")


# --- sharding ---------------------------------------------------------------


def test_hash_fns_match_reference_formulas():
    # djb2/sdbm over the decimal string of the key (global.cc:606-628)
    assert hash_djb2(0) == (5381 * 33 + ord("0")) & ((1 << 64) - 1)
    h = 0
    for c in b"12":
        h = (c + (h << 6) + (h << 16) - h) & ((1 << 64) - 1)
    assert hash_sdbm(12) == h
    assert hash_naive(1 << 16) == 9973  # (key>>16 + 0) * 9973 with key=65536


def test_assigner_stable_and_accounted():
    a = ServerAssigner(num_servers=4, fn="djb2")
    s1 = a.assign(42, nbytes=100)
    assert a.assign(42, nbytes=50) == s1      # sticky
    assert a.load_bytes[s1] == 150
    spread = {a.assign(k << 16) for k in range(64)}
    assert len(spread) >= 3                   # keys spread across servers
    assert "s0" in a.load_summary()


def test_assigner_mixed_mode_ranges():
    # 5 servers, 3 workers -> 2 non-colocated; ratio = 8/11, so both
    # groups get traffic across many keys
    a = ServerAssigner(num_servers=5, fn="djb2", mixed_mode=True,
                       num_workers=3)
    sids = [a.assign(k << 16) for k in range(200)]
    assert all(0 <= s < 5 for s in sids)
    assert any(s < 2 for s in sids) and any(s >= 2 for s in sids)
    with pytest.raises(ValueError):
        ServerAssigner(num_servers=2, fn="djb2", mixed_mode=True,
                       num_workers=2)   # no non-colocated servers


def test_assigner_mixed_reshard_rollback_keeps_previous_shape_routable():
    """ISSUE 9 satellite: a shape-violating mixed-mode reshard must
    raise AND leave the assigner fully routable under the shape it had
    before — service survives the failed transition."""
    a = ServerAssigner(num_servers=5, fn="djb2", mixed_mode=True,
                       num_workers=3)
    before = {k << 16: a.assign(k << 16) for k in range(50)}
    with pytest.raises(ValueError):
        a.reshard(2, num_workers=2)     # 0 non-colocated: invalid split
    assert a.num_servers == 5           # shape rolled back...
    sids = {k: a.assign(k) for k in before}
    assert sids == before               # ...and routing is unchanged
    assert all(0 <= s < 5 for s in sids.values())
    a.assign(99 << 16, nbytes=64)       # fresh keys still route
    with pytest.raises(ValueError):
        a.reshard(3)                    # mixed mode needs num_workers
    assert a.assign(99 << 16) == a.assign(99 << 16)


def test_assigner_load_summary_percentages():
    """ISSUE 9 satellite: load_summary() percentages are derived from
    the accumulated byte loads and sum to ~100%."""
    a = ServerAssigner(num_servers=2, fn="djb2")
    # route two keys to known servers, then charge known byte loads
    k0, k1 = 0, 1
    while a.assign(k1) == a.assign(k0):
        k1 += 1
    a.assign(k0, nbytes=300)
    a.assign(k1, nbytes=100)
    text = a.load_summary()
    assert "75.0%" in text and "25.0%" in text
    assert "300" in text and "100" in text
    # empty accounting renders 0% everywhere instead of dividing by zero
    fresh = ServerAssigner(num_servers=2, fn="djb2")
    assert fresh.load_summary() == "s0: 0 (0.0%), s1: 0 (0.0%)"


def test_debug_sample_tensor_logs():
    """BYTEPS_DEBUG_SAMPLE_TENSOR emits stage samples for matching names.
    (The byteps logger has its own handler and does not propagate, so a
    capture handler is attached directly rather than using caplog.)"""
    import dataclasses
    import logging
    import jax.numpy as jnp
    import byteps_tpu as bps
    from byteps_tpu.common.config import get_config, set_config
    from byteps_tpu.common.logging import get_logger
    old = get_config()
    set_config(dataclasses.replace(old, debug_sample_tensor="dbg/"))
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    get_logger().addHandler(handler)
    try:
        bps.init()
        x = jnp.ones((bps.size(), 32), jnp.float32)
        bps.push_pull(x, "dbg/w")
        bps.push_pull(x, "quiet/w")
        msgs = [r.getMessage() for r in records]
        assert any("sample dbg/w" in m for m in msgs), msgs
        assert not any("sample quiet" in m for m in msgs)
    finally:
        get_logger().removeHandler(handler)
        bps.shutdown()
        set_config(old)


# ---------------------------------------------------------- compressed mode

def test_compressed_push_pull_onebit_matches_pipeline_ref():
    """Reference server.cc:87-113: decompress every worker's push, sum,
    re-compress the merged result.  Pinned against the numpy pipeline:
    out = C_s(sum_i D_w(wire_i)); workers send entropy/wire-framed
    payloads, the pull returns wire bytes."""
    import jax.numpy as jnp
    from byteps_tpu.compression import create as create_compressor
    from tests import compression_refs as refs

    n, workers = 512, 3
    eng = ServerEngine(num_threads=2)
    try:
        kw = {"compressor": "onebit", "scaling": "true"}
        eng.register_compression("cg", kw, n)
        rng = np.random.RandomState(21)
        grads = [rng.randn(n).astype(np.float32) for _ in range(workers)]
        wcomp = create_compressor(kw, n)
        for w, g in enumerate(grads):
            payload, _ = wcomp.compress(jnp.asarray(g), wcomp.init_state())
            eng.push_compressed("cg", wcomp.wire_encode(payload), w, workers)
        wire = eng.pull_compressed("cg", timeout=30)
        scomp = create_compressor(kw, n, for_server=True)
        out = np.asarray(scomp.decompress(scomp.wire_decode(wire)))
        # numpy ref of the full worker->server cycle
        summed = np.zeros(n, np.float32)
        for g in grads:
            w_words, w_scale = refs.onebit_compress(g, True)
            summed += refs.onebit_decompress(w_words, w_scale, n)
        s_words, s_scale = refs.onebit_compress(summed, True)
        ref = refs.onebit_decompress(s_words, s_scale, n)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    finally:
        eng.shutdown()


def test_compressed_dithering_elias_wire_roundtrip():
    """Dithering keys ship the Elias-delta wire format end to end through
    the server; the compressed wire must be far smaller than the dense
    payload on sparse posteriors."""
    import jax.numpy as jnp
    from byteps_tpu.compression import create as create_compressor

    n, workers = 4096, 2
    eng = ServerEngine(num_threads=1)
    try:
        kw = {"compressor": "dithering", "partition_num": "16", "seed": "7"}
        eng.register_compression("dg", kw, n)
        rng = np.random.RandomState(22)
        base = np.zeros(n, np.float32)
        hot = rng.choice(n, 50, replace=False)
        base[hot] = rng.randn(50).astype(np.float32)
        wcomp = create_compressor(kw, n)
        sizes = []
        for w in range(workers):
            payload, _ = wcomp.compress(jnp.asarray(base * (w + 1)),
                                        wcomp.init_state())
            wire = wcomp.wire_encode(payload)
            sizes.append(len(wire))
            eng.push_compressed("dg", wire, w, workers)
        out_wire = eng.pull_compressed("dg", timeout=30)
        scomp = create_compressor(kw, n, for_server=True)
        out = np.asarray(scomp.decompress(scomp.wire_decode(out_wire)))
        assert out.shape == (n,)
        assert np.isfinite(out).all()
        # nonzeros only where contributions were
        assert set(np.flatnonzero(out)) <= set(hot)
        # entropy-coded wire crushes the dense int8 payload (4100 B)
        assert max(sizes + [len(out_wire)]) < (n + 4) / 5
    finally:
        eng.shutdown()


def test_pull_compressed_shares_one_compression_per_round():
    """Two pullers of the same merge round get byte-identical wire (the
    codec state advances once per round, like the reference's cached pull
    responses, server.cc:34-75)."""
    import jax.numpy as jnp
    from byteps_tpu.compression import create as create_compressor

    n = 256
    eng = ServerEngine(num_threads=1)
    try:
        kw = {"compressor": "onebit"}
        eng.register_compression("sk", kw, n)
        wcomp = create_compressor(kw, n)
        g = np.random.RandomState(23).randn(n).astype(np.float32)
        payload, _ = wcomp.compress(jnp.asarray(g), wcomp.init_state())
        eng.push_compressed("sk", wcomp.wire_encode(payload), 0, 1)
        w1 = eng.pull_compressed("sk", timeout=30)
        w2 = eng.pull_compressed("sk", timeout=30)
        assert w1 == w2
    finally:
        eng.shutdown()
