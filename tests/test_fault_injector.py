"""Fault injector: spec grammar, eager validation, deterministic
schedules, site behavior, and the zero-overhead disabled fast path
(fault/injector.py — the chaos half of the fault-tolerance subsystem)."""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import injector as inj_mod
from byteps_tpu.fault.injector import (CORRUPT_SITES, FaultInjector,
                                       VALID_KINDS, VALID_SITES,
                                       _FIELDS, _KIND_FIELDS, parse_spec)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with chaos off (module-global state)."""
    inj_mod.disarm()
    yield
    inj_mod.disarm()


# --- grammar / validation ---------------------------------------------------


def test_parse_full_grammar():
    rules = parse_spec("kill:rank=1:step=40; delay:site=dcn:p=0.01:ms=200,"
                       "bitflip:site=server_push:p=0.001;"
                       "straggler:rank=2:ms=50;drop:site=heartbeat:p=0.2")
    kinds = [r.kind for r in rules]
    assert kinds == ["kill", "delay", "bitflip", "straggler", "drop"]
    assert rules[0].rank == 1 and rules[0].step == 40
    assert rules[1].site == "dcn" and rules[1].ms == 200.0
    assert rules[3].site == "dispatch"  # straggler default site
    assert rules[4].p == 0.2


@pytest.mark.parametrize("bad,needle", [
    ("explode:site=dcn", "valid kinds"),
    ("delay:site=mars:p=1", "valid sites"),
    ("delay:ms=5", "needs site"),
    ("drop:p=0.5", "needs site"),
    ("kill:rank=1", "needs step"),
    ("bitflip:site=dcn:p=1", "bitflip needs site"),
    ("straggler:rank=0", "ms=N > 0"),
    ("delay:site=dcn:p=2", "must be in (0, 1]"),
    ("delay:site=dcn:frequency=2", "unknown field"),
    ("kill:rank=1:step=40:p=0.1", "no effect on 'kill'"),
    ("delay:site=dcn:step=10:ms=5", "no effect on 'delay'"),
    ("drop:site=heartbeat:ms=5", "no effect on 'drop'"),
    ("kill:rank=x:step=3", "must be integers"),
    ("  ; , ", "no fault clauses"),
])
def test_validation_is_actionable(bad, needle):
    with pytest.raises(ValueError) as ei:
        parse_spec(bad)
    assert needle in str(ei.value)


def test_error_lists_every_valid_kind_and_site():
    with pytest.raises(ValueError) as ei:
        parse_spec("bogus")
    for k in VALID_KINDS:
        assert k in str(ei.value)
    with pytest.raises(ValueError) as ei:
        parse_spec("delay:site=bogus")
    for s in VALID_SITES:
        assert s in str(ei.value)


# --- table-driven kind × field validation (ISSUE 10 satellite) --------------
#
# The master field list is DERIVED from the per-kind tables, and this
# test sweeps EVERY kind × field combination: a field a kind reads must
# parse, anything else must be rejected with the actionable "no effect"
# message — per-kind drift (e.g. delay/drop silently losing rank=) is
# structurally pinned.

# a minimal valid clause per kind, to which one extra field is appended
_BASE_CLAUSE = {
    "kill": "kill:step=3",
    "delay": "delay:site=dcn",
    "straggler": "straggler:ms=5",
    "slow": "slow:ms=5",
    "drop": "drop:site=heartbeat",
    "bitflip": "bitflip:site=server_push",
    "partition": "partition",
    "conn_reset": "conn_reset",
    "partial_write": "partial_write",
    "slow_socket": "slow_socket:ms=5",
}
# a value valid for each field (site chosen per kind: kill only accepts
# the coordinator predicate, bitflip only corrupt-woven sites, socket
# kinds only the socket shim's transport site)
_SITE_FOR = {"kill": "coordinator", "bitflip": "server_push",
             "partition": "transport", "conn_reset": "transport",
             "partial_write": "transport", "slow_socket": "transport"}


def _field_value(kind, field):
    if field == "site":
        return _SITE_FOR.get(kind, "dcn")
    return {"rank": "1", "step": "3", "p": "0.5", "ms": "5",
            "code": "9", "n": "4", "ranks": "0|1.2"}[field]


def test_master_field_table_is_derived_from_kind_tables():
    assert set(_KIND_FIELDS) == set(VALID_KINDS)
    assert set(_FIELDS) == {f for fs in _KIND_FIELDS.values() for f in fs}


@pytest.mark.parametrize("kind", VALID_KINDS)
@pytest.mark.parametrize("field", _FIELDS)
def test_every_kind_field_combination(kind, field):
    clause = f"{_BASE_CLAUSE[kind]}:{field}={_field_value(kind, field)}"
    if field in _KIND_FIELDS[kind]:
        rules = parse_spec(clause)
        assert rules[0].kind == kind
        # an ACCEPTED field must land on the rule, not be dropped
        if field == "rank":
            assert rules[0].rank == 1
        elif field == "n":
            assert rules[0].n == 4
    else:
        with pytest.raises(ValueError, match="no effect on"):
            parse_spec(clause)


@pytest.mark.parametrize("kind,site", [
    ("delay", "dcn"), ("drop", "heartbeat"), ("straggler", "dispatch"),
    ("slow", "dispatch"),
])
def test_rank_filter_is_honored_by_every_sleep_and_drop_kind(kind, site,
                                                             monkeypatch):
    """rank= must FILTER, not merely parse: an injector whose process
    rank differs never fires the rule."""
    slept = []
    monkeypatch.setattr(inj_mod.time, "sleep", slept.append)
    clause = {"delay": "delay:rank=1:site=dcn:p=1:ms=5",
              "drop": "drop:rank=1:site=heartbeat:p=1",
              "straggler": "straggler:rank=1:ms=5",
              "slow": "slow:rank=1:ms=5"}[kind]
    other = FaultInjector(clause, rank=0)
    mine = FaultInjector(clause, rank=1)
    if kind == "drop":
        assert not other.should_drop(site)
        assert mine.should_drop(site)
    else:
        other.fire(site)
        assert slept == []
        mine.fire(site)
        assert slept == [0.005]


# --- the slow kind (gray failures) ------------------------------------------


def test_slow_validation():
    with pytest.raises(ValueError, match="ms=N > 0"):
        parse_spec("slow:rank=1")
    with pytest.raises(ValueError, match="visit budget"):
        parse_spec("slow:ms=5:n=0")
    with pytest.raises(ValueError, match="no effect on 'slow'"):
        parse_spec("slow:ms=5:p=0.5")
    r = parse_spec("slow:rank=2:ms=300:n=20")[0]
    assert (r.rank, r.ms, r.n, r.site) == (2, 300.0, 20, "dispatch")
    assert parse_spec("slow:site=sync:ms=10")[0].n is None


def test_slow_is_sustained_and_budget_clears(monkeypatch):
    inj_mod._reset_lifetime_for_tests()
    counters.reset()
    slept = []
    monkeypatch.setattr(inj_mod.time, "sleep", slept.append)
    inj = FaultInjector("slow:site=sync:ms=100:n=3", rank=0)
    for _ in range(6):
        inj.fire("sync")
    # sustained for exactly the n-visit window, then the fault CLEARS
    assert slept == [0.1, 0.1, 0.1]
    assert counters.get("fault.slow") == 3
    assert counters.get("fault.slow_cleared") == 1
    # unbounded form never clears
    slept.clear()
    inj2 = FaultInjector("slow:site=sync:ms=50", rank=0)
    for _ in range(5):
        inj2.fire("sync")
    assert slept == [0.05] * 5
    assert counters.get("fault.slow_cleared") == 1


def test_slow_budget_survives_rearm(monkeypatch):
    """An elastic suspend/resume re-arms the injector from config; a
    slow window that already cleared must STAY cleared — otherwise a
    demoted rank's rejoin would resurrect the very fault it recovered
    from and be re-demoted forever."""
    inj_mod._reset_lifetime_for_tests()
    slept = []
    monkeypatch.setattr(inj_mod.time, "sleep", slept.append)
    spec = "slow:site=sync:ms=100:n=2"
    inj = inj_mod.arm(spec, seed=3, rank=0)
    inj.fire("sync")
    inj.fire("sync")
    inj.fire("sync")
    assert slept == [0.1, 0.1]
    inj_mod.disarm()
    # the re-armed incarnation resumes the CONSUMED budget
    inj2 = inj_mod.arm(spec, seed=3, rank=0)
    inj2.fire("sync")
    assert slept == [0.1, 0.1]
    inj_mod.disarm()
    # partial consumption carries over too
    inj_mod._reset_lifetime_for_tests()
    inj3 = inj_mod.arm(spec, seed=3, rank=0)
    inj3.fire("sync")
    inj_mod.disarm()
    inj4 = inj_mod.arm(spec, seed=3, rank=0)
    inj4.fire("sync")
    inj4.fire("sync")
    assert slept == [0.1, 0.1, 0.1, 0.1]   # 1 + 1 more, then cleared
    inj_mod._reset_lifetime_for_tests()


# --- determinism ------------------------------------------------------------


SPEC = ("drop:site=heartbeat:p=0.5;delay:site=dcn:p=0.3:ms=0;"
        "bitflip:site=server_push:p=1")


def _schedule(inj: FaultInjector, n: int = 200):
    drops = [inj.should_drop("heartbeat") for _ in range(n)]
    base = np.zeros(16, np.float32)
    flips = [np.asarray(inj.corrupt("server_push", base)).tobytes()
             for _ in range(8)]
    return drops, flips


def test_same_spec_and_seed_identical_schedule():
    a = _schedule(FaultInjector(SPEC, seed=11, rank=0))
    b = _schedule(FaultInjector(SPEC, seed=11, rank=0))
    assert a == b


def test_different_seed_different_schedule():
    a = _schedule(FaultInjector(SPEC, seed=11, rank=0))
    b = _schedule(FaultInjector(SPEC, seed=12, rank=0))
    assert a != b


def test_schedule_identical_across_two_runs():
    """The acceptance pin: two fresh interpreter runs, same spec + seed,
    byte-identical schedule (string seeding is hash-salt-free)."""
    code = (
        "from byteps_tpu.fault.injector import FaultInjector\n"
        f"inj = FaultInjector({SPEC!r}, seed=7, rank=0)\n"
        "print([inj.should_drop('heartbeat') for _ in range(100)])\n"
    )
    outs = set()
    for seed in ("1", "2"):  # different PYTHONHASHSEED on purpose
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env={**os.environ, "PYTHONHASHSEED": seed, "PYTHONPATH": REPO},
            check=True)
        outs.add(r.stdout)
    assert len(outs) == 1
    assert "True" in outs.pop()  # p=0.5 over 100 draws: some must fire


# --- site behavior ----------------------------------------------------------


def test_kill_fires_at_exact_step(monkeypatch):
    exits = []
    monkeypatch.setattr(inj_mod, "_exit", exits.append)
    inj = FaultInjector("kill:rank=0:step=3:code=9", rank=0)
    for _ in range(2):
        inj.on_step()
    assert not exits
    inj.on_step()
    assert exits == [9]


def test_kill_other_rank_never_fires(monkeypatch):
    exits = []
    monkeypatch.setattr(inj_mod, "_exit", exits.append)
    inj = FaultInjector("kill:rank=1:step=2", rank=0)
    for _ in range(10):
        inj.on_step()
    assert not exits and inj.step_count == 10


def test_delay_and_straggler_sleep(monkeypatch):
    slept = []
    monkeypatch.setattr(inj_mod.time, "sleep", slept.append)
    inj = FaultInjector("delay:site=dcn:p=1:ms=200;straggler:rank=0:ms=50",
                        rank=0)
    inj.fire("dcn")
    assert slept == [0.2]
    inj.fire("dispatch")
    assert slept == [0.2, 0.05]
    # straggler targets rank 0 only: a rank-1 injector never stalls
    inj1 = FaultInjector("straggler:rank=0:ms=50", rank=1)
    inj1.fire("dispatch")
    assert slept == [0.2, 0.05]


def test_bitflip_flips_exactly_one_bit():
    inj = FaultInjector("bitflip:site=server_push:p=1", seed=5, rank=0)
    x = np.arange(32, dtype=np.float32)
    y = inj.corrupt("server_push", x)
    assert y.shape == x.shape and y.dtype == x.dtype
    np.testing.assert_array_equal(x, np.arange(32, dtype=np.float32))  # copy
    diff = np.bitwise_xor(x.view(np.uint8), y.view(np.uint8))
    assert int(np.unpackbits(diff).sum()) == 1
    # corruption is woven only where corrupt() is called
    assert set(CORRUPT_SITES) <= set(VALID_SITES)


def test_drop_rate_roughly_matches_p():
    inj = FaultInjector("drop:site=heartbeat:p=0.25", seed=3, rank=0)
    n = sum(inj.should_drop("heartbeat") for _ in range(1000))
    assert 150 < n < 350  # deterministic given the seed; sanity band


# --- disabled fast path -----------------------------------------------------


def test_module_fast_path_disabled_by_default():
    assert inj_mod.ENABLED is False
    assert inj_mod.active() is None
    # delegates are no-ops, not errors, even when called unguarded
    inj_mod.on_step()
    inj_mod.fire("dcn")
    assert inj_mod.should_drop("heartbeat") is False
    x = np.ones(4)
    assert inj_mod.corrupt("server_push", x) is x


def test_arm_disarm_cycle():
    inj_mod.arm("delay:site=dcn:p=1:ms=0", seed=1, rank=0)
    assert inj_mod.ENABLED and inj_mod.active() is not None
    inj_mod.disarm()
    assert not inj_mod.ENABLED and inj_mod.active() is None


# --- engine integration -----------------------------------------------------


def test_init_validates_spec_eagerly_and_leaves_nothing_half_up():
    import byteps_tpu.core.api as api
    from byteps_tpu.common.config import Config
    with pytest.raises(ValueError) as ei:
        api.init(Config(fault_spec="delay:site=nowhere:p=1"))
    assert "valid sites" in str(ei.value)
    assert not api.initialized()
    assert not inj_mod.ENABLED


def test_engine_run_under_delay_injection_and_counters():
    import byteps_tpu as bps
    import byteps_tpu.core.api as api
    from byteps_tpu.common.config import Config
    counters.reset()
    api.init(Config(fault_spec="delay:site=dcn:p=1:ms=1", fault_seed=7))
    try:
        assert inj_mod.ENABLED
        x = np.ones((bps.size(), 64), np.float32)
        out = bps.push_pull(x, "chaos.delay")
        np.testing.assert_allclose(np.asarray(out), 1.0)
        assert counters.get("fault.delay") >= 1
        assert inj_mod.active().step_count == 1
    finally:
        bps.shutdown()
    # shutdown disarms: the next clean init pays only the ENABLED check
    assert not inj_mod.ENABLED


def test_heartbeat_drop_site_detected_as_loss():
    """drop:site=heartbeat:p=1 starves the coordinator of beats: a
    non-root rank must conclude the coordinator is unreachable — the
    woven send-site is what makes the loss real."""
    import threading
    import time
    from byteps_tpu.utils.failure_detector import HeartbeatMonitor
    from .conftest import free_port

    counters.reset()
    inj_mod.arm("drop:site=heartbeat:p=1", rank=1)
    fired = []
    done = threading.Event()
    port = free_port()
    m0 = HeartbeatMonitor(0, 2, f"127.0.0.1:{port}", interval=0.05,
                          timeout=10.0, grace=10.0,
                          on_failure=lambda s: None)
    m1 = HeartbeatMonitor(1, 2, f"127.0.0.1:{port}", interval=0.05,
                          timeout=0.5, grace=0.5,
                          on_failure=lambda s: (fired.append(s), done.set()))
    m0.start()
    m1.start()
    try:
        assert done.wait(5.0), "dropped heartbeats were not detected"
        assert fired == [{0}]
        assert counters.get("fault.drop") > 0
    finally:
        inj_mod.disarm()
        m1.stop()
        m0.stop()
        time.sleep(0.05)
