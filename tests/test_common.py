"""Unit tests for the common layer: config, partitioner, registry, scheduler,
handles.  Test strategy follows SURVEY.md §4: every scheduling/bookkeeping
behavior of the reference core gets a direct equivalent check here."""

import threading

import numpy as np
import pytest

from byteps_tpu.common import (
    ChunkScheduler,
    ChunkTask,
    Config,
    HandleManager,
    Status,
    TensorRegistry,
    chunk_bounds,
    make_key,
    split_key,
)
from byteps_tpu.common.config import ALIGN_BYTES, set_config


# --- config ----------------------------------------------------------------

def test_config_from_env(monkeypatch):
    monkeypatch.setenv("BYTEPS_PARTITION_BYTES", "1000000")
    monkeypatch.setenv("BYTEPS_SCHEDULING_CREDIT", "8388608")
    monkeypatch.setenv("DMLC_NUM_WORKER", "4")
    monkeypatch.setenv("DMLC_WORKER_ID", "2")
    cfg = Config.from_env()
    # partition bound is rounded up to alignment
    assert cfg.partition_bytes % ALIGN_BYTES == 0
    assert cfg.partition_bytes >= 1000000
    assert cfg.scheduling_credit == 8388608
    assert cfg.num_hosts == 4 and cfg.host_id == 2


def test_config_validation():
    with pytest.raises(ValueError):
        Config(partition_bytes=0)
    with pytest.raises(ValueError):
        Config(num_hosts=0)
    with pytest.raises(ValueError):
        Config(failure_exit_code=0)     # must survive a process exit status
    with pytest.raises(ValueError):
        Config(failure_exit_code=256)
    with pytest.raises(ValueError):
        Config(restart_limit=-1)


def test_config_fault_tolerance_knobs_from_env(monkeypatch):
    """Satellite: BYTEPS_FAULT_SPEC / RESTART_LIMIT / FAILURE_EXIT_CODE /
    retry knobs ride Config.from_env like every other knob."""
    monkeypatch.setenv("BYTEPS_FAULT_SPEC", "delay:site=dcn:p=0.5:ms=10")
    monkeypatch.setenv("BYTEPS_FAULT_SEED", "99")
    monkeypatch.setenv("BYTEPS_RESTART_LIMIT", "4")
    monkeypatch.setenv("BYTEPS_FAILURE_EXIT_CODE", "42")
    monkeypatch.setenv("BYTEPS_RETRY_MAX_ATTEMPTS", "6")
    monkeypatch.setenv("BYTEPS_RETRY_BASE_DELAY", "0.25")
    monkeypatch.setenv("BYTEPS_RETRY_MAX_DELAY", "3.5")
    monkeypatch.setenv("BYTEPS_RETRY_DEADLINE", "45")
    cfg = Config.from_env()
    assert cfg.fault_spec == "delay:site=dcn:p=0.5:ms=10"
    assert cfg.fault_seed == 99
    assert cfg.restart_limit == 4
    assert cfg.failure_exit_code == 42
    assert cfg.retry_max_attempts == 6
    assert cfg.retry_base_delay_s == 0.25
    assert cfg.retry_max_delay_s == 3.5
    assert cfg.retry_deadline_s == 45.0


def test_config_fault_tolerance_defaults():
    cfg = Config()
    assert cfg.fault_spec == ""          # chaos off: zero-overhead path
    assert cfg.failure_exit_code == 17   # the historical detector exit
    assert cfg.restart_limit == 0        # supervision is opt-in


# --- keys ------------------------------------------------------------------

def test_key_encoding_roundtrip():
    # declared_key<<16 | part, as the reference carves the key space
    # (operations.cc:302-311)
    key = make_key(7, 42)
    assert split_key(key) == (7, 42)
    assert make_key(0, 0) == 0
    with pytest.raises(ValueError):
        make_key(1, 1 << 16)


# --- partitioner -----------------------------------------------------------

def test_small_tensor_single_chunk():
    assert chunk_bounds(1000, 4, 4096000) == [(0, 1000)]


def test_partition_covers_exactly():
    n = 3_000_000
    bounds = chunk_bounds(n, 4, 1 << 20)  # 1 MB chunks of f32
    assert bounds[0][0] == 0
    assert sum(ln for _, ln in bounds) == n
    for (o1, l1), (o2, _) in zip(bounds, bounds[1:]):
        assert o1 + l1 == o2
    # all chunks but last respect the byte bound
    for _, ln in bounds:
        assert ln * 4 <= 1 << 20


def test_partition_alignment():
    bounds = chunk_bounds(10_000_000, 4, 1 << 20)
    from byteps_tpu.common.partitioner import ALIGN_ELEMS
    for off, _ in bounds:
        assert off % ALIGN_ELEMS == 0


# --- registry --------------------------------------------------------------

def test_declare_order_gives_keys():
    reg = TensorRegistry()
    a = reg.declare("grad/a")
    b = reg.declare("grad/b")
    again = reg.declare("grad/a")
    assert a.declared_key == 0 and b.declared_key == 1
    assert again is a
    assert reg.names_in_declaration_order() == ["grad/a", "grad/b"]


def test_init_tensor_carves_keys():
    set_config(Config(partition_bytes=ALIGN_BYTES))  # tiny bound -> many chunks
    reg = TensorRegistry()
    ctx = reg.init_tensor("g", shape=(4096,), dtype=np.float32)
    assert ctx.initialized
    assert ctx.num_elems == 4096
    assert len(ctx.chunk_bounds) == len(ctx.key_list) >= 2
    assert all(split_key(k)[0] == ctx.declared_key for k in ctx.key_list)
    # idempotent
    ctx2 = reg.init_tensor("g", shape=(4096,), dtype=np.float32)
    assert ctx2 is ctx


# --- scheduler -------------------------------------------------------------

def _task(name, key, priority, nbytes=100):
    return ChunkTask(name=name, key=key, priority=priority, version=0,
                     offset_elems=0, num_elems=nbytes // 4, nbytes=nbytes,
                     total_parts=1)


def test_priority_order():
    # priority desc, then key asc — the reference comparator
    # (scheduled_queue.cc:82-102)
    s = ChunkScheduler()
    s.add_task(_task("low", key=make_key(2, 0), priority=-2))
    s.add_task(_task("hi", key=make_key(0, 1), priority=0))
    s.add_task(_task("hi", key=make_key(0, 0), priority=0))
    s.add_task(_task("mid", key=make_key(1, 0), priority=-1))
    order = [s.get_task().key for _ in range(4)]
    assert order == [make_key(0, 0), make_key(0, 1), make_key(1, 0),
                     make_key(2, 0)]


def test_credit_window_blocks_and_returns():
    s = ChunkScheduler(credit_bytes=250)
    s.add_task(_task("a", 0, 0, nbytes=100))
    s.add_task(_task("b", 1, 0, nbytes=100))
    s.add_task(_task("c", 2, 0, nbytes=100))
    assert s.get_task() is not None
    assert s.get_task() is not None
    # third would exceed 250 in-flight bytes
    assert s.get_task() is None
    s.report_finish(100)
    assert s.get_task() is not None


def test_oversized_task_still_runs():
    s = ChunkScheduler(credit_bytes=50)
    s.add_task(_task("huge", 0, 0, nbytes=1000))
    assert s.get_task() is not None  # window empty -> allowed through


# --- handles ---------------------------------------------------------------

def test_handle_wait_and_callback():
    hm = HandleManager()
    h = hm.allocate("g")
    assert not h.poll()
    fired = []
    h.add_done_callback(lambda hh: fired.append(hh.id))

    def complete():
        h.set_result(np.ones(3), Status.ok())

    t = threading.Thread(target=complete)
    t.start()
    out = h.wait(timeout=5)
    t.join()
    assert np.allclose(out, 1.0)
    assert fired == [h.id]
    assert h.poll()
    hm.release(h.id)
    assert hm.get(h.id) is None


def test_handle_error_propagates():
    hm = HandleManager()
    h = hm.allocate("g")
    h.set_result(None, Status.error("boom"))
    with pytest.raises(RuntimeError, match="boom"):
        h.wait(timeout=1)
