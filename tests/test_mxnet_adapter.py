"""MXNet adapter tests, mirroring the reference's tests/test_mxnet.py
shape (push_pull sums 1-3D tensors across dtypes against numpy;
broadcast parameter-order check) — without mxnet: the adapter is
duck-typed to the NDArray protocol, exercised here via a stub, exactly as
it would drive real ``mx.nd.NDArray``s."""

import numpy as np
import pytest

import byteps_tpu.mxnet as bps_mx
from byteps_tpu.mxnet.ops import compression_kwargs


class FakeNDArray:
    """Minimal mx.nd.NDArray stand-in: asnumpy / slice-assign / imul."""

    def __init__(self, arr):
        self._a = np.array(arr)

    def asnumpy(self):
        return self._a

    def __setitem__(self, key, value):
        self._a[key] = np.asarray(value)

    def __imul__(self, other):
        self._a *= other
        return self

    @property
    def shape(self):
        return self._a.shape

    @property
    def dtype(self):
        return self._a.dtype


@pytest.fixture
def session():
    bps_mx.init()
    yield
    bps_mx.shutdown()


@pytest.mark.parametrize("shape", [(17,), (5, 3), (2, 3, 4)])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_push_pull_inplace_sum(session, shape, dtype):
    rng = np.random.RandomState(0)
    x = rng.randn(*shape).astype(dtype)
    t = FakeNDArray(x.copy())
    bps_mx.byteps_push_pull(t, name=f"mx/{shape}/{np.dtype(dtype)}",
                            is_average=False)
    # single worker: sum == identity (reference single-worker
    # forced-distributed mode)
    np.testing.assert_allclose(t.asnumpy(), x, rtol=1e-6)


def test_push_pull_requires_name(session):
    with pytest.raises(ValueError):
        bps_mx.byteps_push_pull(FakeNDArray(np.ones(4, np.float32)))


def test_distributed_optimizer_runs_push_pull_then_update(session):
    calls = []

    class SGD:
        def update(self, index, weight, grad, state):
            calls.append(("update", list(index)))
            for w, g in zip(weight, grad):
                w[:] = w.asnumpy() - 0.1 * g.asnumpy()

        def set_learning_rate(self, lr):
            calls.append(("lr", lr))

    opt = bps_mx.DistributedOptimizer(SGD())
    w = [FakeNDArray(np.ones(4, np.float32))]
    g = [FakeNDArray(np.full(4, 2.0, np.float32))]
    opt.update([0], w, g, [None])
    assert calls == [("update", [0])]
    np.testing.assert_allclose(w[0].asnumpy(), np.ones(4) - 0.2, rtol=1e-6)
    opt.set_learning_rate(0.5)
    assert calls[-1] == ("lr", 0.5)


def test_broadcast_parameters_sorted_order(session):
    params = {"b": FakeNDArray(np.full(3, 2.0, np.float32)),
              "a": FakeNDArray(np.full(3, 1.0, np.float32))}
    start = bps_mx.parameter_index
    bps_mx.broadcast_parameters(params)
    assert bps_mx.parameter_index == start + 2
    # root rank 0, single worker: values unchanged
    np.testing.assert_allclose(params["a"].asnumpy(), 1.0)
    np.testing.assert_allclose(params["b"].asnumpy(), 2.0)
    with pytest.raises(ValueError):
        bps_mx.broadcast_parameters([FakeNDArray(np.ones(2))])


def test_compression_params_attr_plumbing(session):
    class P:
        grad_req = "write"

    params = {"w0": P()}
    opt_params = {"momentum": 0.9, "wd": 1e-4}
    intra = bps_mx._register_compression_attrs(
        params, opt_params,
        {"compressor": "onebit", "ef": "vanilla", "momentum": "nesterov",
         "scaling": True})
    p = params["w0"]
    assert p.byteps_compressor_type == "onebit"
    assert p.byteps_ef_type == "vanilla"
    assert p.byteps_momentum_type == "nesterov"
    assert p.byteps_compressor_onebit_scaling == "True"
    assert p.byteps_momentum_mu == 0.9
    # momentum/wd moved from the optimizer into the compressor chain
    assert "momentum" not in opt_params and "wd" not in opt_params
    from byteps_tpu.mxnet.compression import (NagAdapter,
                                              WeightDecayMomentumAdapter)
    assert isinstance(intra, NagAdapter)
    assert isinstance(intra.compressor, WeightDecayMomentumAdapter)

    # declared attrs reach the engine as compression kwargs
    bps_mx.byteps_declare_tensor(
        "gradient_attr", **{k: str(v) for k, v in p.__dict__.items()
                            if k.startswith("byteps_")})
    kw = compression_kwargs("gradient_attr")
    assert kw["compressor"] == "onebit" and kw["ef"] == "vanilla"
    assert kw["momentum"] == "nesterov"


def test_push_pull_with_onebit_kwargs_roundtrip(session):
    """Declared compressor kwargs actually engage the engine's compression
    pipeline (single worker: onebit of onebit == sign*scale identity on the
    merged value)."""
    rng = np.random.RandomState(3)
    x = rng.randn(512).astype(np.float32)
    t = FakeNDArray(x.copy())
    bps_mx.byteps_declare_tensor("gradient_ob",
                                 byteps_compressor_type="onebit")
    bps_mx.byteps_push_pull(t, name="gradient_ob", is_average=False)
    out = t.asnumpy()
    from tests import compression_refs as refs
    w, s = refs.onebit_compress(x)
    dec = refs.onebit_decompress(w, s, 512)
    w2, s2 = refs.onebit_compress(dec)
    np.testing.assert_allclose(out, refs.onebit_decompress(w2, s2, 512),
                               rtol=1e-5, atol=1e-6)


def test_async_mode_preserves_base_weights(session):
    """Async-PS: local update -> push delta -> pull merged; the pulled
    weight must equal base + sum(deltas), not the bare delta sum."""
    from byteps_tpu.common import Config
    from byteps_tpu.common.config import get_config, set_config
    import dataclasses
    old = get_config()
    set_config(dataclasses.replace(old, enable_async=True))
    try:
        class SGD:
            def update(self, index, weight, grad, state):
                for w, g in zip(weight, grad):
                    w[:] = w.asnumpy() - 0.1 * g.asnumpy()

        opt = bps_mx.DistributedOptimizer(SGD())
        w = [FakeNDArray(np.array([1.0, 2.0], np.float32))]
        g = [FakeNDArray(np.array([1.0, 1.0], np.float32))]
        opt.update([0], w, g, [None])
        np.testing.assert_allclose(w[0].asnumpy(), [0.9, 1.9], rtol=1e-6)
        opt.update([0], w, g, [None])
        np.testing.assert_allclose(w[0].asnumpy(), [0.8, 1.8], rtol=1e-6)
    finally:
        set_config(old)


def test_wdmom_applies_wd_to_small_tensors(session):
    """Weight decay must reach every tensor; only the extra momentum is
    gated on the threshold (reference mxnet/compression.py:104-148)."""
    from byteps_tpu.mxnet.compression import Compression
    wd, mu = 0.1, 0.9
    comp = Compression.wdmom(Compression.none, mu, wd, threshold=10**9)
    g = FakeNDArray(np.zeros(4, np.float32))
    x = FakeNDArray(np.ones(4, np.float32))
    out = comp.decompress(g, None, x=x)
    # below threshold: g + wd*x, no momentum term
    np.testing.assert_allclose(out.asnumpy(), 0.1 * np.ones(4), rtol=1e-6)

    comp2 = Compression.wdmom(Compression.none, mu, wd, threshold=0)
    g2 = FakeNDArray(np.zeros(4, np.float32))
    out2 = comp2.decompress(g2, None, x=x)
    # at/above threshold: g + mu*(0 + wd*x) + wd*x
    np.testing.assert_allclose(out2.asnumpy(),
                               (mu * wd + wd) * np.ones(4), rtol=1e-6)
    with pytest.raises(ValueError):
        comp2.decompress(g2, None)


def test_fp16_intra_compressor():
    from byteps_tpu.mxnet.compression import Compression
    t = FakeNDArray(np.random.randn(32).astype(np.float32))
    orig = t.asnumpy().copy()
    out, ctx = Compression.fp16.compress(t)
    np.testing.assert_allclose(out.asnumpy(), orig, rtol=1e-2, atol=1e-2)
    assert Compression.fp16.decompress(out, ctx) is out
