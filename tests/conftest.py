"""Test fixtures: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's test harness shape (tests/meta_test.py in the
reference spawns a scheduler+server and forces distributed mode on one
machine); here the analog is XLA host-platform device virtualization —
8 CPU "chips" stand in for a TPU slice so every collective path is exercised
without hardware (SURVEY.md §4).
"""

import os

# Must run before the first JAX backend initialization.  Note: the image's
# sitecustomize imports jax at interpreter start, so JAX_PLATFORMS in the
# environment is already consumed — jax.config.update is the reliable switch.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Once the compression engine is wired, tests force compression regardless
# of tensor size, as the reference harness does (meta_test.py:31-33).  Until
# then this only exercises the Config parsing path.
os.environ.setdefault("BYTEPS_MIN_COMPRESS_BYTES", "0")

# Flight-recorder dumps default to a per-user temp dir (config.py
# _default_flight_dir); still route them to one session-scoped temp dir
# so parallel test sessions never see each other's dumps (tests that
# assert on dumps set BYTEPS_FLIGHT_DIR explicitly anyway).
if "BYTEPS_FLIGHT_DIR" not in os.environ:
    import tempfile

    os.environ["BYTEPS_FLIGHT_DIR"] = tempfile.mkdtemp(
        prefix="bps_flight_test_")

# Same hygiene for trace flushes (Tracer defaults trace_dir to cwd): a
# test arming BYTEPS_TRACE_ON/TRACE_SAMPLE without an explicit dir must
# not shed bps_trace_rank*.json files into the repo root.
if "BYTEPS_TRACE_DIR" not in os.environ:
    import tempfile

    os.environ["BYTEPS_TRACE_DIR"] = tempfile.mkdtemp(
        prefix="bps_trace_test_")

# Durable state plane (server/wal.py): durability is strictly opt-in
# (durable_dir defaults to ""), so tests run WAL-free unless they arm it
# themselves.  But if the operator exported BYTEPS_DURABLE_DIR into the
# test session, re-point it at a temp dir — a test run must never replay
# or truncate a real deployment's journal.
if os.environ.get("BYTEPS_DURABLE_DIR"):
    import tempfile

    os.environ["BYTEPS_DURABLE_DIR"] = tempfile.mkdtemp(
        prefix="bps_durable_test_")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Importing the package runs common/jax_compat.install(): on runtimes
# without jax.shard_map it publishes the compat adapters and flips
# LEGACY_RUNTIME.  A few tests pin behavior that simply does not exist
# before shard_map left experimental (VMA-aware pipeline numerics,
# jax.shard_map inside bare subprocesses, XLA all-reduce combining);
# they skip there instead of failing-by-environment.
from byteps_tpu.common.jax_compat import LEGACY_RUNTIME  # noqa: E402

legacy_skip = pytest.mark.skipif(
    LEGACY_RUNTIME,
    reason="pins modern-JAX behavior (VMA shard_map numerics / "
           "jax.shard_map in bare subprocesses / XLA collective "
           "combining) absent from this legacy runtime; see "
           "byteps_tpu/common/jax_compat.py")


def pytest_configure(config):
    # registered here as well as in pyproject.toml so the marker exists
    # even under bare `pytest tests/` invocations with a stripped config
    # (tools/run_chaos.sh's integrity lane selects on it)
    config.addinivalue_line(
        "markers",
        "integrity: data-integrity envelope / dedup / quarantine tests "
        "(common/integrity.py wire paths)")


def free_port() -> int:
    """An OS-assigned free TCP port (shared by the multi-process and
    failure-detector tests)."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _fresh_config():
    """Each test gets a config rebuilt from the current environment."""
    from byteps_tpu.common.config import reset_config
    reset_config()
    yield
    reset_config()


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Reset the process-wide observability singletons BETWEEN tests:
    the metrics registry (counters/gauges/histograms), the flight
    recorder's ring, and any leaked obs HTTP endpoint.  Without this,
    ``counters`` leaks across test files and every assertion on an
    absolute count is order-dependent (ISSUE 6 satellite)."""
    yield
    from byteps_tpu.common import flight_recorder as _flight
    from byteps_tpu.common import metrics as _metrics
    from byteps_tpu.common import obs_server as _obs
    from byteps_tpu.common import tracing as _btracing
    from byteps_tpu.common.telemetry import attribution as _attribution
    from byteps_tpu.utils import slowness as _slowness
    _obs.stop_server()
    # transport servers registered via comm.transport.serve() hold accept
    # threads and sockets; close any a test left behind (imported lazily:
    # most tests never touch the transport)
    import sys as _sys
    _transport = _sys.modules.get("byteps_tpu.comm.transport")
    if _transport is not None:
        _transport._reset_for_tests()
    _tier = _sys.modules.get("byteps_tpu.server.serving_tier")
    if _tier is not None:
        _tier._reset_for_tests()
    # the process-lifetime durable trainer store (server/wal.py) holds an
    # open journal file handle; close it so the next test's temp dir
    # starts cold
    _wal = _sys.modules.get("byteps_tpu.server.wal")
    if _wal is not None:
        _wal._reset_for_tests()
    _metrics.registry.reset()
    _metrics._reset_components_for_tests()
    _flight._reset_for_tests()
    _slowness._reset_for_tests()
    _btracing._reset_for_tests()
    _attribution.reset()
