"""Flash-attention Pallas kernels vs exact attention (ops/flash_attention.py).

Forward and gradients are pinned against parallel/sequence.py
full_attention — the same oracle the ring/Ulysses sequence-parallel tests
use — in interpret mode (the identical kernel code runs compiled by
Mosaic on a real TPU backend; bench.py re-validates there).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.ops.flash_attention import flash_attention
from byteps_tpu.parallel import full_attention


def _rand(shape, dtype, seed):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32
                             ).astype(dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [128, 256])
def test_forward_matches_exact(causal, t):
    b, h, d = 2, 4, 64
    q = _rand((b, t, h, d), jnp.float32, 0)
    k = _rand((b, t, h, d), jnp.float32, 1)
    v = _rand((b, t, h, d), jnp.float32, 2)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_ragged_shapes():
    """T not a block multiple, D not a lane multiple: padding is masked."""
    b, t, h, d = 2, 100, 3, 48
    q = _rand((b, t, h, d), jnp.float32, 3)
    k = _rand((b, t, h, d), jnp.float32, 4)
    v = _rand((b, t, h, d), jnp.float32, 5)
    for causal in (False, True):
        got = flash_attention(q, k, v, causal=causal, interpret=True)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_decode_alignment():
    """Tq < Tk with causal: q rows cover the LAST Tq key positions."""
    b, h, d = 1, 2, 64
    q = _rand((b, 64, h, d), jnp.float32, 6)
    k = _rand((b, 256, h, d), jnp.float32, 7)
    v = _rand((b, 256, h, d), jnp.float32, 8)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_causal_rejects_tq_gt_tk():
    """causal=True with Tq > Tk is rejected: fully-masked early rows would
    produce garbage forward values and exploding backward p = exp(s - lse)
    (round-2 advisor finding)."""
    b, h, d = 1, 2, 64
    q = _rand((b, 256, h, d), jnp.float32, 6)
    k = _rand((b, 64, h, d), jnp.float32, 7)
    v = _rand((b, 64, h, d), jnp.float32, 8)
    with pytest.raises(ValueError, match="Tq <= Tk"):
        flash_attention(q, k, v, causal=True, interpret=True)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_exact(causal):
    b, t, h, d = 2, 128, 2, 64
    q = _rand((b, t, h, d), jnp.float32, 9)
    k = _rand((b, t, h, d), jnp.float32, 10)
    v = _rand((b, t, h, d), jnp.float32, 11)
    # nontrivial downstream cotangent
    w = _rand((b, t, h, d), jnp.float32, 12)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       interpret=True) * w)

    def loss_exact(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) * w)

    g_got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


def test_gradients_ragged():
    b, t, h, d = 1, 72, 2, 32
    q = _rand((b, t, h, d), jnp.float32, 13)
    k = _rand((b, t, h, d), jnp.float32, 14)
    v = _rand((b, t, h, d), jnp.float32, 15)

    def loss(f):
        return lambda q, k, v: jnp.sum(
            f(q, k, v) * (1.0 + jnp.arange(d, dtype=jnp.float32)))

    g_got = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=True, interpret=True)), argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(loss(lambda q, k, v: full_attention(
        q, k, v, causal=True)), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_got, g_want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


def test_bf16_forward():
    b, t, h, d = 2, 128, 2, 64
    q = _rand((b, t, h, d), jnp.bfloat16, 16)
    k = _rand((b, t, h, d), jnp.bfloat16, 17)
    v = _rand((b, t, h, d), jnp.bfloat16, 18)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_long_context_flash_mode():
    """attention='flash' trains the GPT long-context step on an sp=1 mesh
    and matches the exact-attention trajectory; sp>1 is rejected."""
    import optax
    from byteps_tpu.models.gpt import GPT, gpt_tiny
    from byteps_tpu.parallel import (make_dp_sp_train_step, make_sp_mesh,
                                     shard_lm_batch, synthetic_lm_batch)
    from byteps_tpu.parallel.long_context import replicate

    cfg = gpt_tiny()
    mesh = make_sp_mesh(jax.devices()[:8], n_sp=1)
    batch = synthetic_lm_batch(jax.random.PRNGKey(0), cfg, batch=8,
                               seq_len=32)
    params = GPT(cfg).init(jax.random.PRNGKey(1), batch["input_ids"][:1])
    tx = optax.sgd(0.1)

    losses = {}
    for kind in ("flash", "ring"):
        step = make_dp_sp_train_step(mesh, cfg, tx, attention=kind,
                                     donate=False)
        p = replicate(mesh, params)
        o = replicate(mesh, tx.init(params))
        ls = []
        for _ in range(3):
            p, o, loss = step(p, o, shard_lm_batch(mesh, batch))
            ls.append(float(loss))
        losses[kind] = ls
    # gpt_tiny computes in bf16: the two softmax decompositions agree to
    # bf16 resolution, not f32
    np.testing.assert_allclose(losses["flash"], losses["ring"],
                               rtol=5e-3, atol=5e-3)

    mesh2 = make_sp_mesh(jax.devices()[:8], n_sp=2)
    with pytest.raises(ValueError, match="needs sp=1"):
        make_dp_sp_train_step(mesh2, cfg, tx, attention="flash")
