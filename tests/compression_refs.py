"""Pure-numpy reference implementations of every compressor.

Mirrors the reference's test strategy (SURVEY.md §4): its tests replicate
the entire worker+server compressor pipeline in numpy — including the exact
PRNG — so randomized compressors are deterministic across implementations
(reference tests/utils.py:31-50, test_onebit.py:32-113).  These refs must
match byteps_tpu.compression bit-for-bit on the PRNG and to float tolerance
on the math."""

import numpy as np

from byteps_tpu.compression.prng import uniform_np


# --- onebit ----------------------------------------------------------------

def _onebit_lanes(numel):
    # mirror ops/pallas_kernels.py padded_lanes: words rounded to 128 lanes
    words = -(-numel // 32)
    return -(-words // 128) * 128


def onebit_compress(x, scaling=True):
    # sublane-major layout (compression/onebit.py): bit i of word j is the
    # sign of padded element i*L + j, L lane-aligned
    x = x.astype(np.float32)
    scale = (np.abs(x).sum() / len(x)).astype(np.float32) \
        if scaling else np.float32(1.0)
    L = _onebit_lanes(len(x))
    # pad x (not the bits): pad elements are 0 and 0>=0 packs as 1, same
    # as the kernel; decompress slices the padding off before use
    bits = (np.pad(x, (0, 32 * L - len(x))) >= 0).astype(np.uint32)
    packed = (bits.reshape(32, L)
              << np.arange(32, dtype=np.uint32)[:, None]) \
        .sum(axis=0).astype(np.uint32)
    return packed, np.float32(scale)


def onebit_decompress(packed, scale, numel):
    bits = ((packed[None, :] >> np.arange(32, dtype=np.uint32)[:, None]) & 1)
    bits = bits.reshape(-1)[:numel]
    return (bits.astype(np.float32) * 2.0 - 1.0) * scale


# --- topk ------------------------------------------------------------------

def topk_compress(x, k):
    x = x.astype(np.float32)
    # np.argsort is ascending & stable; jax.lax.top_k takes largest with
    # ties broken by lowest index — replicate via (-|x|, index) lexsort
    order = np.lexsort((np.arange(len(x)), -np.abs(x)))
    idx = order[:k].astype(np.int32)
    return idx, x[idx]


def sparse_decompress(idx, vals, numel):
    out = np.zeros(numel, np.float32)
    out[idx] = vals
    return out


# --- randomk ---------------------------------------------------------------

def randomk_compress(x, k, seed, counter):
    x = x.astype(np.float32)
    scores = uniform_np(seed, counter, len(x))
    order = np.lexsort((np.arange(len(x)), -scores))
    idx = order[:k].astype(np.int32)
    return idx, x[idx], counter + len(x)


# --- dithering -------------------------------------------------------------

def dithering_levels(scheme, s):
    if scheme == "linear":
        return (np.arange(s + 1) / s).astype(np.float32)
    return np.asarray([0.0] + [2.0 ** -(s - 1 - i) for i in range(s)],
                      dtype=np.float32)


def dithering_compress(x, s, partition, normalize, seed, counter):
    x = x.astype(np.float32)
    mag = np.abs(x)
    norm = mag.max() if normalize == "max" else np.sqrt((mag * mag).sum())
    safe = norm if norm > 0 else np.float32(1.0)
    u = np.clip(mag / safe, 0.0, 1.0)
    lv = dithering_levels(partition, s)
    i = np.clip(np.searchsorted(lv, u, side="right") - 1, 0, s - 1)
    lo, hi = lv[i], lv[i + 1]
    p = (u - lo) / (hi - lo)
    r = uniform_np(seed, counter, len(x))
    code = i + (r < p)
    signed = np.where(x < 0, -code, code).astype(np.int8)
    return signed, np.float32(norm), counter + len(x)


def dithering_decompress(codes, norm, s, partition):
    lv = dithering_levels(partition, s)
    mags = lv[np.abs(codes.astype(np.int32))] * norm
    return np.sign(codes).astype(np.float32) * mags


# --- decorators ------------------------------------------------------------

def ef_compress(x, error, compress_fn, decompress_fn):
    corrected = x.astype(np.float32) + error
    payload = compress_fn(corrected)
    decompressed = decompress_fn(payload)
    return payload, corrected - decompressed


def nesterov_compress(x, m, mu):
    x = x.astype(np.float32)
    m2 = mu * m + x
    return x + mu * m2, m2


def powersgd_matrix_shape(numel):
    """Mirror of compression.powersgd._matrix_shape."""
    m = int(np.sqrt(numel))
    if m >= 256:
        m -= m % 128
    m = max(1, m)
    n = -(-numel // m)
    return n, m


def powersgd_compress(x, rank, seed=0, iters=1, q=None):
    """Pure-numpy mirror of PowerSGDCompressor.compress: returns
    (P, Q') with the same warm-start semantics (pass the previous call's
    Q' as ``q``)."""
    x = np.asarray(x, np.float32)
    numel = x.size
    n, m = powersgd_matrix_shape(numel)
    r = max(1, min(int(rank), n, m))
    M = np.pad(x, (0, n * m - numel)).reshape(n, m)
    if q is None:
        q = np.random.RandomState(seed).standard_normal(
            (m, r)).astype(np.float32)
    for _ in range(max(1, iters)):
        p, _ = np.linalg.qr(M @ q)
        q = M.T @ p
    return p.astype(np.float32), q.astype(np.float32)


def powersgd_decompress(p, q, numel, dtype=np.float32):
    return (p @ q.T).reshape(-1)[:numel].astype(dtype)
