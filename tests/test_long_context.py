"""Long-context (dp, sp) composite training: GPT with ring/Ulysses
attention must match the single-logical-device full-attention model, and
the composite train step must train."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.models.gpt import GPT, gpt_tiny, lm_loss
from byteps_tpu.parallel import (make_dp_sp_train_step, make_sp_mesh,
                                 shard_lm_batch, synthetic_lm_batch)
from byteps_tpu.parallel.long_context import replicate



pytestmark = pytest.mark.slow  # multi-device attention integration: minutes of XLA compile on small CPU hosts (tier-1 budget)
@pytest.fixture(scope="module")


def setup():
    cfg = gpt_tiny()
    rng = jax.random.PRNGKey(0)
    batch = synthetic_lm_batch(rng, cfg, batch=4, seq_len=64)
    model = GPT(cfg)
    params = model.init(rng, batch["input_ids"][:1])
    return cfg, batch, model, params


@pytest.mark.parametrize("attention", ["ring", "ulysses", "striped"])
def test_dp_sp_step_loss_matches_single_device(setup, attention):
    """First-step loss on the (2, 4) mesh equals the unsharded model's
    loss on the same batch/params (same math, different layout).  For
    "striped" the batch rides the round-robin layout end-to-end
    (shard_lm_batch(striped=True) + striped positions inside the step) —
    the loss is a sum over tokens, so it is layout-invariant and the
    same oracle applies."""
    cfg, batch, model, params = setup
    logits = model.apply(params, batch["input_ids"])
    ref_loss = float(lm_loss(logits, batch["labels"]))

    mesh = make_sp_mesh(n_sp=4)
    tx = optax.sgd(0.1)
    step = make_dp_sp_train_step(mesh, cfg, tx, attention=attention,
                                 donate=False)
    p = replicate(mesh, params)
    o = replicate(mesh, tx.init(params))
    b = shard_lm_batch(mesh, batch, striped=attention == "striped")
    _, _, loss = step(p, o, b)
    np.testing.assert_allclose(float(loss), ref_loss, rtol=2e-3)


def test_dp_sp_training_reduces_loss(setup):
    cfg, batch, model, params = setup
    mesh = make_sp_mesh(n_sp=4)
    tx = optax.adam(1e-2)
    step = make_dp_sp_train_step(mesh, cfg, tx, attention="ring")
    # donate=True + virtual-CPU devices: device_put can alias the fixture's
    # buffers, so donation would delete them for later tests — copy first
    p = replicate(mesh, jax.tree.map(jnp.array, params))
    o = replicate(mesh, tx.init(params))
    b = shard_lm_batch(mesh, batch)
    losses = []
    for _ in range(8):
        p, o, loss = step(p, o, b)
        losses.append(float(loss))
    assert losses[-1] < 0.8 * losses[0]


def test_gpt_ring_forward_matches_full(setup):
    """Forward parity at the model level (not just the loss): ring
    attention inside the sharded model reproduces full attention."""
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from byteps_tpu.parallel.sequence import SP_AXIS, DP_AXIS, ring_attention

    cfg, batch, model, params = setup
    ref = model.apply(params, batch["input_ids"])

    mesh = make_sp_mesh(n_sp=4)
    sharded_model = GPT(cfg, attn_fn=partial(ring_attention,
                                             axis_name=SP_AXIS))

    def fwd(p, ids):
        t_local = ids.shape[1]
        pos = (jax.lax.axis_index(SP_AXIS) * t_local
               + jnp.arange(t_local))[None]
        return sharded_model.apply(p, ids, positions=pos)

    out = jax.jit(jax.shard_map(
        fwd, mesh=mesh,
        in_specs=(P(), P(DP_AXIS, SP_AXIS)),
        out_specs=P(DP_AXIS, SP_AXIS), check_vma=False,
    ))(params, batch["input_ids"])
    # bf16 compute: reassociated reductions differ by O(0.05) on O(5)
    # logits; require close values plus near-total top-1 agreement
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=0.1, atol=0.1)
    agree = (np.asarray(out).argmax(-1) == np.asarray(ref).argmax(-1))
    assert agree.mean() > 0.95


def test_dp_sp_training_matches_single_device_exactly():
    """Step-for-step parity of (dp, sp) training with plain full-attention
    training on identical params (f32 so reduction order is the only
    noise).  Pins the gradient scaling: the r2 fix moved the loss psum
    out of the gradient path (long_context.py loss_fn) — before it,
    gradients were inflated by the mesh size and this test fails."""
    from byteps_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, intermediate_size=64, max_position=128,
                    dtype=jnp.float32)
    rng = jax.random.PRNGKey(9)
    batch = synthetic_lm_batch(rng, cfg, batch=4, seq_len=32)
    model = GPT(cfg)
    params = model.init(rng, batch["input_ids"][:1])
    tx = optax.sgd(0.1)

    @jax.jit
    def ref_step(p, o, b):
        loss, g = jax.value_and_grad(
            lambda q: lm_loss(model.apply(q, b["input_ids"]),
                              b["labels"]))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    p_ref, o_ref = params, tx.init(params)
    for _ in range(3):
        p_ref, o_ref, loss_ref = ref_step(p_ref, o_ref, batch)

    mesh = make_sp_mesh(n_sp=4)
    step = make_dp_sp_train_step(mesh, cfg, tx, attention="ring",
                                 donate=False)
    p = replicate(mesh, jax.tree.map(jnp.array, params))
    o = replicate(mesh, tx.init(params))
    b = shard_lm_batch(mesh, batch)
    for _ in range(3):
        p, o, loss = step(p, o, b)

    np.testing.assert_allclose(float(loss), float(loss_ref),
                               rtol=1e-4, atol=1e-5)
    for (ka, a), (kb, bb) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(p_ref),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(jax.device_get(p)),
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=2e-3, atol=2e-4, err_msg=str(ka))


def test_llama_dp_sp_matches_single_device():
    """The Llama family rides the same (dp, sp) composite: RoPE consumes
    each shard's absolute positions before the ring rotates K/V, so the
    sharded ring-attention model must equal the unsharded one on
    identical params/batch (f32 for bit-comparable math)."""
    import dataclasses
    from byteps_tpu.models.llama import Llama, llama_tiny, lm_loss as llm_loss

    cfg = dataclasses.replace(llama_tiny(), dtype=jnp.float32)
    rng = jax.random.PRNGKey(3)
    batch = synthetic_lm_batch(rng, cfg, batch=4, seq_len=64)
    model = Llama(cfg)
    params = model.init(rng, batch["input_ids"][:1])
    logits = model.apply(params, batch["input_ids"])
    ref_loss = float(llm_loss(logits, batch["labels"]))

    mesh = make_sp_mesh(n_sp=4)
    tx = optax.sgd(0.1)
    step = make_dp_sp_train_step(mesh, cfg, tx, attention="ring",
                                 donate=False)
    p = replicate(mesh, params)
    o = replicate(mesh, tx.init(params))
    b = shard_lm_batch(mesh, batch)
    losses = []
    for _ in range(3):
        p, o, loss = step(p, o, b)
        losses.append(float(loss))
    np.testing.assert_allclose(losses[0], ref_loss, rtol=1e-5, atol=1e-6)
    assert losses[-1] < losses[0], losses
