"""The supervised TCP transport (comm/transport.py): framing, the
connection state machine, NACK/retransmit over a real socket, seq-token
idempotence across reconnects, socket-level chaos (partition /
conn_reset / partial_write / slow_socket), backpressure, keepalives,
sharded routing determinism, and the 32-endpoint supervisor soak."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common import integrity
from byteps_tpu.common.config import Config, reset_config
from byteps_tpu.common.telemetry import counters, gauges
from byteps_tpu.comm import transport as tp
from byteps_tpu.fault import injector as inj
from byteps_tpu.server.engine import ServerEngine
from byteps_tpu.server.kv_store import KVStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chaos_off():
    inj.disarm()
    yield
    inj.disarm()
    tp._reset_for_tests()


def _kv_server(**kw):
    kv = KVStore()
    kv.init_key("k", np.zeros(8, np.float32))
    srv = tp.TransportServer(rank=0, kv=kv, **kw)
    return kv, srv


# --- framing ----------------------------------------------------------------


def test_frame_roundtrip():
    raw = tp._pack_frame(tp.OP_PUSH, 7, {"hop": "kv"}, b"payload")
    import io

    class _FakeSock:
        def __init__(self, data):
            self._b = io.BytesIO(data)

        def recv(self, n):
            return self._b.read(n)

    op, rid, meta, payload = tp._read_frame(_FakeSock(raw))
    assert (op, rid, meta, payload) == (tp.OP_PUSH, 7, {"hop": "kv"},
                                        b"payload")


def test_frame_clamp_and_bad_magic(monkeypatch):
    import io

    class _FakeSock:
        def __init__(self, data):
            self._b = io.BytesIO(data)

        def recv(self, n):
            return self._b.read(n)

    # a corrupt length prefix must fail the connection, not park a
    # multi-petabyte recv
    from byteps_tpu.common.config import set_config
    set_config(Config(bus_max_frame=1024))
    big = tp._HEADER.pack(tp.MAGIC, tp.VERSION, tp.OP_PUSH, 1, 0, 1 << 40)
    with pytest.raises(tp.TransportError, match="BYTEPS_BUS_MAX_FRAME"):
        tp._read_frame(_FakeSock(big))
    reset_config()
    bad = b"NOPE" + bytes(tp._HEADER.size - 4)
    with pytest.raises(tp.TransportError, match="BPST"):
        tp._read_frame(_FakeSock(bad))


# --- config / addressing ----------------------------------------------------


@pytest.mark.parametrize("kw,needle", [
    (dict(transport_port_base=70000), "transport_port_base"),
    (dict(transport_connect_timeout_s=0), "transport_connect_timeout_s"),
    (dict(transport_send_deadline_s=0), "transport_send_deadline_s"),
    (dict(transport_keepalive_s=-1), "transport_keepalive_s"),
    (dict(transport_max_inflight=0), "transport_max_inflight"),
])
def test_config_validation(kw, needle):
    with pytest.raises(ValueError, match=needle):
        Config(**kw)


def test_transport_addr_resolution(monkeypatch):
    monkeypatch.setenv("BYTEPS_TRANSPORT_HOSTS",
                       "10.0.0.1:7000, 10.0.0.2, 10.0.0.3:7002")
    monkeypatch.setenv("BYTEPS_TRANSPORT_PORT_BASE", "9100")
    reset_config()
    assert tp.transport_addr(0) == ("10.0.0.1", 7000)
    assert tp.transport_addr(1) == ("10.0.0.2", 9101)  # base + rank
    assert tp.transport_addr(2) == ("10.0.0.3", 7002)
    assert tp.transport_addr(5) == ("127.0.0.1", 9105)  # past the map
    monkeypatch.delenv("BYTEPS_TRANSPORT_PORT_BASE")
    reset_config()
    with pytest.raises(ValueError, match="BYTEPS_TRANSPORT_PORT_BASE"):
        tp.transport_addr(1)  # map entry without a port, no base
    monkeypatch.delenv("BYTEPS_TRANSPORT_HOSTS")
    reset_config()
    with pytest.raises(ValueError, match="BYTEPS_TRANSPORT_HOSTS"):
        tp.transport_addr(0)


# --- the data-plane hops over the wire --------------------------------------


def test_server_push_and_pull_over_tcp():
    eng = ServerEngine(num_threads=1)
    srv = tp.TransportServer(rank=0, engine=eng)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        ep.push("g", np.full(16, 1.5, np.float32), 0, 2)
        ep.push("g", np.full(16, 2.0, np.float32), 1, 2)
        out, version = ep.pull_versioned("g", timeout=10)
        assert np.all(out == np.float32(3.5)) and version == 1
        assert counters.get("transport.connects") >= 1
    finally:
        ep.close()
        srv.close()
        eng.shutdown()


def test_compressed_push_over_tcp():
    eng = ServerEngine(num_threads=1)
    kwargs = {"compressor": "onebit", "ef": "vanilla"}
    eng.register_compression("c", kwargs, 64)
    from byteps_tpu.compression import create as create_compressor
    comp = create_compressor(kwargs, 64)
    state = comp.init_state()
    import jax.numpy as jnp
    payload, state = comp.compress(jnp.asarray(np.ones(64, np.float32)),
                                   state)
    wire = comp.wire_encode(payload)
    srv = tp.TransportServer(rank=0, engine=eng)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        ep.push_compressed("c", wire, 0, 1)
        out = ep.pull("c", timeout=10)
        assert out.shape == (64,) and np.isfinite(out).all()
    finally:
        ep.close()
        srv.close()
        eng.shutdown()


def test_loopback_endpoint_same_interface():
    eng = ServerEngine(num_threads=1)
    kv = KVStore()
    kv.init_key("k", np.zeros(4, np.float32))
    ep = tp.LoopbackEndpoint(engine=eng, kv=kv)
    ep.push("g", np.ones(4, np.float32), 0, 1)
    assert np.all(ep.pull("g", timeout=10) == 1.0)
    assert ep.push_delta("k", np.ones(4, np.float32), seq=1) == 1
    val, ver = ep.kv_pull("k")
    assert np.all(val == 1.0) and ver == 1
    eng.shutdown()


def test_kv_delta_seq_dedup_over_wire():
    kv, srv = _kv_server()
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        before = counters.get("integrity.dup_dropped")
        assert ep.push_delta("k", np.ones(8, np.float32), seq=5) == 1
        # the retry-with-same-token scenario, by hand
        assert ep.push_delta("k", np.ones(8, np.float32), seq=5) == 1
        assert counters.get("integrity.dup_dropped") == before + 1
        assert float(kv.pull("k")[0]) == 1.0  # never double-summed
    finally:
        ep.close()
        srv.close()


def test_server_push_wire_level_dedup():
    """A retransmitted server_push frame whose ORIGINAL landed (the
    reply was lost, not the request) must be dropped by the transport
    server's per-(key, worker) floor — a sync merge round can never
    count one worker twice."""
    eng = ServerEngine(num_threads=1)
    srv = tp.TransportServer(rank=0, engine=eng)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        frame = integrity.seal_array(np.full(4, 2.0, np.float32), key="g",
                                     seq=1, worker=0)
        meta = {"hop": "server_push", "num_workers": 2, "mepoch": None}
        rop, rmeta, _ = ep.connection.request(tp.OP_PUSH, dict(meta), frame)
        assert rop == tp.OP_ACK and not rmeta.get("dup")
        rop, rmeta, _ = ep.connection.request(tp.OP_PUSH, dict(meta), frame)
        assert rop == tp.OP_ACK and rmeta.get("dup")
        ep.push("g", np.full(4, 3.0, np.float32), 1, 2)
        assert np.all(ep.pull("g", timeout=10) == np.float32(5.0))
    finally:
        ep.close()
        srv.close()
        eng.shutdown()


def test_mepoch_gate_over_wire():
    eng = ServerEngine(num_threads=1)
    eng.set_membership_epoch(3)
    srv = tp.TransportServer(rank=0, engine=eng)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        before = counters.get("membership.stale_pushes_dropped")
        ep.push("g", np.ones(4, np.float32), 0, 1, mepoch=2)  # stale
        assert counters.get("membership.stale_pushes_dropped") == before + 1
        ep.push("g", np.full(4, 7.0, np.float32), 0, 1, mepoch=3)
        assert np.all(ep.pull("g", timeout=10) == 7.0)
    finally:
        ep.close()
        srv.close()
        eng.shutdown()


def test_rejoin_state_over_wire_and_corruption_refused():
    from byteps_tpu.utils.checkpoint import pack_state
    state = {"w": np.arange(16, dtype=np.float32), "step": 7}
    blob = pack_state(state)
    corrupt = bytearray(blob)
    corrupt[len(corrupt) // 2] ^= 0x10
    provider = {"blob": blob}
    srv = tp.TransportServer(rank=0,
                             state_provider=lambda: provider["blob"])
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        got = ep.pull_state()
        assert np.all(got["w"] == state["w"]) and got["step"] == 7
        provider["blob"] = bytes(corrupt)
        with pytest.raises(integrity.IntegrityError):
            ep.pull_state()   # a rejoiner must NEVER unpack corrupt state
    finally:
        ep.close()
        srv.close()


# --- NACK / retransmit over the real wire -----------------------------------


def test_nack_retransmit_from_source_copy(monkeypatch):
    """One corrupted transmission: the server NACKs, the sender
    retransmits the sealed SOURCE frame, the value lands exact."""
    eng = ServerEngine(num_threads=1)
    srv = tp.TransportServer(rank=0, engine=eng)
    # arm an inert spec so the chaos branches run, then corrupt exactly
    # one transmission by hand (deterministic single-NACK scenario)
    inj.arm("drop:site=heartbeat:p=0.001", rank=0)
    flips = {"n": 0}
    real = inj.corrupt_bytes

    def flip_once(site, data):
        if site == "server_push" and flips["n"] == 0:
            flips["n"] += 1
            b = bytearray(data)
            b[len(b) // 2] ^= 0x01
            return bytes(b)
        return real(site, data)

    monkeypatch.setattr(tp._fault, "corrupt_bytes", flip_once)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        r0 = counters.get("integrity.crc_reject")
        t0 = counters.get("integrity.retransmit")
        ep.push("g", np.full(64, 3.25, np.float32), 0, 1)
        assert np.all(ep.pull("g", timeout=10) == np.float32(3.25))
        assert counters.get("integrity.crc_reject") == r0 + 1
        assert counters.get("integrity.retransmit") == t0 + 1
    finally:
        ep.close()
        srv.close()
        eng.shutdown()


@pytest.mark.chaos
def test_nack_budget_exhaustion_raises():
    eng = ServerEngine(num_threads=1)
    srv = tp.TransportServer(rank=0, engine=eng)
    inj.arm("bitflip:site=server_push:p=1", seed=3, rank=0)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        with pytest.raises(integrity.IntegrityError, match="retransmis"):
            ep.push("g", np.ones(64, np.float32), 0, 1)
        assert counters.get("integrity.crc_reject") \
            == integrity.max_retransmits() + 1
    finally:
        inj.disarm()
        ep.close()
        srv.close()
        eng.shutdown()


# --- deadlines, partitions, resets ------------------------------------------


def test_send_deadline_surfaces_acklost_never_hangs():
    # nothing listens here: the connection never leaves CONNECTING and
    # the request must surface AckLost at its deadline
    from .conftest import free_port
    ep = tp.TcpEndpoint(("127.0.0.1", free_port()), peer=9,
                        send_deadline_s=0.5, keepalive_s=0.0)
    try:
        before = counters.get("transport.send_deadline_trips")
        t0 = time.monotonic()
        with pytest.raises(integrity.AckLost):
            ep.push_delta("k", np.ones(4, np.float32), seq=1)
        assert time.monotonic() - t0 < 3.0
        assert counters.get("transport.send_deadline_trips") > before
        assert ep.state == tp.CONNECTING
    finally:
        ep.close(drain=False)


@pytest.mark.chaos
def test_partition_blackholes_then_heals():
    kv, srv = _kv_server()
    ep = tp.TcpEndpoint(srv.addr, peer=0, send_deadline_s=0.6,
                        keepalive_s=0.0)
    try:
        ep.push_delta("k", np.ones(8, np.float32), seq=1)
        inj.arm("partition", seed=0, rank=0)
        with pytest.raises(integrity.AckLost):
            ep.push_delta("k", np.ones(8, np.float32), seq=2)
        assert counters.get("fault.partition") > 0
        inj.disarm()
        # the same token retries cleanly after the partition heals
        assert ep.push_delta("k", np.ones(8, np.float32), seq=2) == 2
        assert float(kv.pull("k")[0]) == 2.0
    finally:
        inj.disarm()
        ep.close()
        srv.close()


@pytest.mark.chaos
def test_partition_budget_heals_by_itself():
    kv, srv = _kv_server()
    ep = tp.TcpEndpoint(srv.addr, peer=0, send_deadline_s=0.6,
                        keepalive_s=0.0)
    try:
        ep.push_delta("k", np.ones(8, np.float32), seq=1)
        inj.arm("partition:n=2", seed=0, rank=0)  # heals after 2 ops
        while True:
            try:
                ep.push_delta("k", np.ones(8, np.float32), seq=2)
                break
            except integrity.AckLost:
                continue
        assert float(kv.pull("k")[0]) == 2.0
        assert counters.get("fault.partition") == 2
    finally:
        inj.disarm()
        ep.close()
        srv.close()


@pytest.mark.chaos
def test_conn_reset_reconnect_exact_sum():
    """The headline idempotence property in-process: resets mid
    send/recv, reconnect + same-token retransmit, the store sum is
    EXACT — zero double-sums, proven by the dedup counter."""
    kv, srv = _kv_server()
    inj.arm("conn_reset:p=0.2", seed=11, rank=0)
    ep = tp.TcpEndpoint(srv.addr, peer=0, send_deadline_s=3.0)
    n = 12
    try:
        for i in range(n):
            while True:
                try:
                    ep.push_delta("k", np.ones(8, np.float32),
                                  worker_id=0, seq=i + 1)
                    break
                except integrity.AckLost:
                    continue
        inj.disarm()
        assert float(kv.pull("k")[0]) == float(n)
        assert counters.get("transport.conn_resets") > 0
        assert counters.get("transport.reconnects") > 0
    finally:
        inj.disarm()
        ep.close()
        srv.close()


@pytest.mark.chaos
def test_partial_write_absorbed():
    kv, srv = _kv_server()
    inj.arm("partial_write:p=1:n=1", seed=2, rank=0)
    ep = tp.TcpEndpoint(srv.addr, peer=0, send_deadline_s=3.0)
    try:
        while True:
            try:
                ep.push_delta("k", np.ones(8, np.float32), seq=1)
                break
            except integrity.AckLost:
                continue
        assert float(kv.pull("k")[0]) == 1.0
        assert counters.get("fault.partial_write") == 1
    finally:
        inj.disarm()
        ep.close()
        srv.close()


@pytest.mark.chaos
def test_slow_socket_throttles_and_feeds_slowness():
    kv, srv = _kv_server()
    inj.arm("slow_socket:ms=40", seed=0, rank=0)
    ep = tp.TcpEndpoint(srv.addr, peer=3, keepalive_s=0.0)
    try:
        t0 = time.monotonic()
        ep.push_delta("k", np.ones(8, np.float32), seq=1)
        assert time.monotonic() - t0 >= 0.04
        assert counters.get("fault.slow_socket") >= 1
        from byteps_tpu.utils import slowness
        snap = slowness.tracker().snapshot()
        assert 3 in snap.get("transport", {})   # per-peer RTT observed
    finally:
        inj.disarm()
        ep.close()
        srv.close()


# --- backpressure / keepalive / state machine -------------------------------


def test_backpressure_bounds_inflight_bytes(monkeypatch):
    kv, srv = _kv_server()
    real = kv.apply_delta

    def slow_apply(*a, **kw):
        time.sleep(0.3)
        return real(*a, **kw)

    monkeypatch.setattr(kv, "apply_delta", slow_apply)
    # in-flight bound below one payload: a second concurrent push must
    # STALL until the first is acknowledged (inflight == 0 admits one
    # oversized request, so singles still flow)
    ep = tp.TcpEndpoint(srv.addr, peer=0, max_inflight=16)
    try:
        before = counters.get("transport.backpressure_stalls")
        t = threading.Thread(
            target=lambda: ep.push_delta("k", np.ones(8, np.float32),
                                         seq=1))
        t.start()
        time.sleep(0.05)   # t holds the in-flight budget
        ep.push_delta("k", np.ones(8, np.float32), seq=2)
        t.join()
        assert counters.get("transport.backpressure_stalls") > before
        assert float(kv.pull("k")[0]) == 2.0
    finally:
        ep.close()
        srv.close()


@pytest.mark.chaos
def test_keepalive_detects_dead_established_connection():
    kv, srv = _kv_server()
    ep = tp.TcpEndpoint(srv.addr, peer=0, keepalive_s=0.2,
                        send_deadline_s=1.0)
    try:
        ep.push_delta("k", np.ones(8, np.float32), seq=1)
        assert ep.state == tp.READY
        inj.arm("partition", seed=0, rank=0)   # silence, socket stays up
        deadline = time.monotonic() + 8
        while ep.state == tp.READY and time.monotonic() < deadline:
            time.sleep(0.05)
        # the keepalive deadline killed the dead-but-ESTABLISHED socket
        assert ep.state != tp.READY
    finally:
        inj.disarm()
        ep.close(drain=False)
        srv.close()


def test_keepalive_survives_parked_pull():
    """A pull parked on an incomplete merge round is a LEGITIMATE long
    wait: short keepalives must not read the parked silence as a dead
    socket and kill the connection mid-pull (the server answers parked
    pulls from a side thread, and the client skips probes while a
    request is pending — that request's own deadline already bounds a
    genuinely dead wire)."""
    eng = ServerEngine(num_threads=1)
    srv = tp.TransportServer(rank=0, engine=eng)
    ep = tp.TcpEndpoint(srv.addr, peer=0, keepalive_s=0.2,
                        send_deadline_s=15.0)
    try:
        ep.push("g", np.full(8, 1.0, np.float32), 0, 2)

        def late_second_contribution():
            time.sleep(1.2)   # ≫ the 0.2 s keepalive interval
            ep.push("g", np.full(8, 2.0, np.float32), 1, 2)

        t = threading.Thread(target=late_second_contribution)
        t.start()
        try:
            out = ep.pull("g", timeout=10)
        finally:
            t.join()
        assert np.all(out == np.float32(3.0))
        assert ep.connection.reconnects == 0   # never torn down
        assert ep.state == tp.READY
    finally:
        ep.close()
        srv.close()
        eng.shutdown()


def test_recreated_endpoint_tokens_advance_past_the_old_floor():
    """Seq tokens draw from ONE process-wide counter: a recreated
    endpoint must not restart at 1 below the server's process-lifetime
    dedup floor — its real contributions would be silently dup-ACKed
    and never land."""
    kv, srv = _kv_server()
    ep1 = tp.TcpEndpoint(srv.addr, peer=0, rank=1)
    ep2 = None
    try:
        ep1.push_delta("k", np.ones(8, np.float32), worker_id=3)
        ep1.close()
        ep2 = tp.TcpEndpoint(srv.addr, peer=0, rank=1)
        d0 = counters.get("integrity.dup_dropped")
        ep2.push_delta("k", np.ones(8, np.float32), worker_id=3)
        assert counters.get("integrity.dup_dropped") == d0
        assert float(kv.pull("k")[0]) == 2.0
    finally:
        if ep2 is not None:
            ep2.close()
        srv.close()


def test_endpoint_to_caches_per_peer(monkeypatch):
    """endpoint_to() returns the SAME supervised endpoint per peer (a
    fresh one per call would leak a supervisor thread pair each time);
    close() evicts the cache entry."""
    kv, srv = _kv_server()
    monkeypatch.setattr(tp, "transport_addr", lambda rank: srv.addr)
    a = tp.endpoint_to(5)
    try:
        assert isinstance(a, tp.TcpEndpoint)
        assert tp.endpoint_to(5) is a
        a.close()
        c = tp.endpoint_to(5)
        assert c is not a
        c.close()
    finally:
        srv.close()


def test_concurrent_same_token_push_merges_once(monkeypatch):
    """The dedup floor is claimed AT CHECK TIME: a same-token
    retransmit arriving while the original dispatch is still inside the
    merge (reconnect races make this real) must not be summed a second
    time — and must not be dup-ACKed either, because the in-flight
    merge could still fail: it gets SILENCE (deadline → retry), and the
    retry after the original resolved gets the honest dup-ACK."""
    eng = ServerEngine(num_threads=1)
    real = eng.receive_push

    def slow_receive(*a, **kw):
        time.sleep(0.5)
        return real(*a, **kw)

    monkeypatch.setattr(eng, "receive_push", slow_receive)
    srv = tp.TransportServer(rank=0, engine=eng)
    ep1 = tp.TcpEndpoint(srv.addr, peer=0)
    ep2 = tp.TcpEndpoint(srv.addr, peer=0, send_deadline_s=1.0)
    frame = integrity.seal_array(np.full(4, 2.0, np.float32), key="g",
                                 seq=5, worker=0)
    meta = {"hop": "server_push", "num_workers": 1, "mepoch": None}
    try:
        t = threading.Thread(target=ep1._transmit,
                             args=(dict(meta), frame, "server_push",
                                   "g", 0, 5))
        t.start()
        time.sleep(0.15)   # the original is mid-merge
        with pytest.raises(integrity.AckLost):
            ep2._transmit(dict(meta), frame, "server_push", "g", 0, 5)
        t.join()
        rmeta, _ = ep2._transmit(dict(meta), frame, "server_push",
                                 "g", 0, 5)
        assert rmeta.get("dup") is True
        assert np.all(ep1.pull("g", timeout=10) == np.float32(2.0))
    finally:
        ep1.close()
        ep2.close()
        srv.close()
        eng.shutdown()


def test_failed_merge_releases_the_dedup_claim():
    """A push whose merge RAISES (the error travels back as OP_ERR)
    must not leave its token claimed: a corrected retry with the SAME
    seq lands instead of being silently dup-ACKed."""
    eng = ServerEngine(num_threads=1)
    srv = tp.TransportServer(rank=0, engine=eng)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    meta = {"hop": "server_push_wire", "num_workers": 1, "mepoch": None}
    try:
        bad = integrity.seal_bytes(b"\x00" * 8, key="uc", seq=9, worker=0)
        with pytest.raises(Exception):
            # no codec registered for "uc": the merge raises AFTER the
            # claim — the claim must roll back
            ep._transmit(dict(meta), bad, "server_push", "uc", 0, 9)
        good = integrity.seal_array(np.full(4, 4.0, np.float32),
                                    key="uc", seq=9, worker=0)
        d0 = counters.get("integrity.dup_dropped")
        ep._transmit({"hop": "server_push", "num_workers": 1,
                      "mepoch": None}, good, "server_push", "uc", 0, 9)
        assert counters.get("integrity.dup_dropped") == d0
        assert np.all(ep.pull("uc", timeout=10) == np.float32(4.0))
    finally:
        ep.close()
        srv.close()
        eng.shutdown()


def test_state_machine_full_cycle():
    from .conftest import free_port
    port = free_port()
    ep = tp.TcpEndpoint(("127.0.0.1", port), peer=0, keepalive_s=0.0)
    try:
        assert ep.state == tp.CONNECTING   # nothing listening yet
        kv = KVStore()
        kv.init_key("k", np.zeros(8, np.float32))
        srv = tp.TransportServer(port=port, rank=0, kv=kv)
        deadline = time.monotonic() + 10
        while ep.state != tp.READY and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ep.state == tp.READY   # the supervisor dialed in
        ep.push_delta("k", np.ones(8, np.float32), seq=1)
        ep.close()
        assert ep.state == tp.DEAD
        with pytest.raises(tp.TransportClosed):
            ep.connection.request(tp.OP_KEEPALIVE, {})
        srv.close()
    finally:
        ep.close(drain=False)


def test_debug_state_and_gauges():
    kv, srv = _kv_server()
    ep = tp.TcpEndpoint(srv.addr, peer=4)
    try:
        ep.push_delta("k", np.ones(8, np.float32), seq=1)
        ds = ep.connection.debug_state()
        assert ds["state"] == tp.READY and ds["peer"] == 4
        assert ds["connects"] == 1 and ds["last_rtt_ms"] is not None
        ss = srv.debug_state()
        assert ss["attached"]["kv"] and ss["connections"] == 1
        assert gauges.get("transport.connections") >= 1
        assert gauges.get("transport.connections_ready") >= 1
        from byteps_tpu.common import obs_server
        doc = obs_server.debug_state()
        assert any(c["peer"] == 4 for c in doc["transport"]["connections"])
        assert any(s["rank"] == 0 for s in doc["transport"]["servers"])
        # bps_top CONN cell reads the gauges
        from tools.bps_top import _conn_cell
        cell = _conn_cell({"transport.connections": 2,
                           "transport.connections_ready": 1})
        assert cell == "1/2"
        assert _conn_cell({}) == "-"
    finally:
        ep.close()
        srv.close()


# --- serving over the wire --------------------------------------------------


def test_serve_pull_remote_with_pull_client():
    from byteps_tpu.server.serve_client import PullClient
    from byteps_tpu.server.serving import ServingPlane
    kv = KVStore()
    for k in ("a", "b"):
        kv.init_key(k, np.zeros(32, np.float32))
    plane = ServingPlane(kv, replicas=1)
    plane.cut()
    srv = tp.TransportServer(rank=0, serving=plane)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        client = PullClient(tp.RemoteServing(ep), max_staleness_s=0.0)
        vals = client.pull()
        assert np.all(vals["a"] == 0.0)
        kv.push_delta("a", np.ones(32, np.float32))
        plane.cut()
        vals = client.pull()
        assert np.all(vals["a"] == 1.0) and np.all(vals["b"] == 0.0)
        # the refresh was a DELTA: only the changed key traveled
        assert counters.get("serve.delta_pulls") >= 1
    finally:
        ep.close()
        srv.close()
        plane.close()


def test_serve_pull_remote_unavailable_maps_to_serve_unavailable():
    from byteps_tpu.server.serving import ServeUnavailable, ServingPlane
    kv = KVStore()
    kv.init_key("a", np.zeros(4, np.float32))
    plane = ServingPlane(kv, replicas=1)   # no snapshot cut yet
    srv = tp.TransportServer(rank=0, serving=plane)
    ep = tp.TcpEndpoint(srv.addr, peer=0)
    try:
        with pytest.raises(ServeUnavailable):
            ep.serve_pull()
    finally:
        ep.close()
        srv.close()
        plane.close()


# --- sharded routing --------------------------------------------------------


def test_sharded_client_routes_by_assigner():
    kvs, srvs, eps = [], [], []
    for i in range(2):
        kv = KVStore()
        srv = tp.TransportServer(rank=i, kv=kv)
        kvs.append(kv)
        srvs.append(srv)
        eps.append(tp.TcpEndpoint(srv.addr, peer=i))
    client = tp.ShardedClient(eps)
    try:
        keys = [f"param.{i}" for i in range(8)]
        for k in keys:
            shard = client.assigner.write_target(k)
            kvs[shard].init_key(k, np.zeros(4, np.float32))
            client.push_delta(k, np.ones(4, np.float32), seq=1)
        for k in keys:
            shard = client.assigner.write_target(k)
            assert k in kvs[shard].keys()
            assert k not in kvs[1 - shard].keys()
            val, ver = client.kv_pull(k)
            assert np.all(val == 1.0) and ver == 1
    finally:
        client.close()
        for srv in srvs:
            srv.close()


def test_sharding_cross_process_determinism():
    """The transport routes by ServerAssigner; two PROCESSES (different
    hash seeds) must route an identical key set — ints AND string
    serving keys — to identical shards under every BYTEPS_KEY_HASH_FN
    mode, or a sharded world silently double-sums (ISSUE satellite)."""
    prog = r"""
import json, sys
from byteps_tpu.server.sharding import ServerAssigner, key_to_int
keys = [0, 1, 17, 2**31, 2**63 - 1] + [f"layer.{i}.weight" for i in range(8)]
out = {}
for fn in ("naive", "built_in", "djb2", "sdbm"):
    a = ServerAssigner(num_servers=5, fn=fn, mixed_mode=False, bound=101,
                       replicas=1, hot_keys=0)
    out[fn] = {str(k): a.assign(key_to_int(k)) for k in keys}
m = ServerAssigner(num_servers=5, fn="djb2", mixed_mode=True,
                   num_workers=3, bound=101, replicas=1, hot_keys=0)
out["mixed"] = {str(k): m.assign(key_to_int(k)) for k in keys}
out["key_to_int"] = {str(k): key_to_int(k) for k in keys}
print(json.dumps(out, sort_keys=True))
"""
    results = []
    for seed in ("0", "31337"):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["PYTHONHASHSEED"] = seed   # salt-dependence would diverge here
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run([sys.executable, "-c", prog], env=env,
                             cwd=REPO, capture_output=True, text=True,
                             timeout=240)
        assert out.returncode == 0, out.stdout + out.stderr
        results.append(json.loads(out.stdout.strip().splitlines()[-1]))
    assert results[0] == results[1]
    # and this process agrees too (three independent interpreters)
    from byteps_tpu.server.sharding import ServerAssigner, key_to_int
    a = ServerAssigner(num_servers=5, fn="djb2", mixed_mode=False,
                       bound=101, replicas=1, hot_keys=0)
    for k in (0, 17, "layer.3.weight"):
        assert a.assign(key_to_int(k)) == results[0]["djb2"][str(k)]


# --- the 32-endpoint supervisor soak ----------------------------------------


@pytest.mark.chaos
def test_soak_32_endpoints_connect_storm_resets_no_thread_leak():
    """JAX-free supervisor scale proof (ISSUE acceptance): 32 servers +
    32 supervised connections brought up as one connect storm, a burst
    of injected resets absorbed mid-traffic, every connection back to
    READY, every store value EXACT, and thread count back to baseline
    after close — the supervisor scales past what CPU-host worlds can
    run."""
    base_threads = threading.active_count()
    n = 32
    kvs, srvs, eps = [], [], []
    try:
        for i in range(n):
            kv = KVStore()
            kv.init_key("k", np.zeros(4, np.float32))
            kvs.append(kv)
            srvs.append(tp.TransportServer(rank=i, kv=kv))
        # connect storm: every supervisor dials at once
        for i in range(n):
            eps.append(tp.TcpEndpoint(srvs[i].addr, peer=i,
                                      keepalive_s=0.0,
                                      send_deadline_s=5.0))
        deadline = time.monotonic() + 20
        while (any(ep.state != tp.READY for ep in eps)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert all(ep.state == tp.READY for ep in eps)
        # injected reset burst mid-traffic (bounded budget, then heals)
        inj.arm("conn_reset:p=0.3:n=40", seed=7, rank=0)
        rounds = 3
        for r in range(rounds):
            for i, ep in enumerate(eps):
                while True:
                    try:
                        ep.push_delta("k", np.ones(4, np.float32),
                                      worker_id=i, seq=r + 1)
                        break
                    except integrity.AckLost:
                        continue
        inj.disarm()
        deadline = time.monotonic() + 20
        while (any(ep.state != tp.READY for ep in eps)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert all(ep.state == tp.READY for ep in eps)   # all recovered
        for kv in kvs:
            assert float(kv.pull("k")[0]) == float(rounds)  # exact
        assert gauges.get("transport.connections_ready") == n
    finally:
        inj.disarm()
        for ep in eps:
            ep.close()
        for srv in srvs:
            srv.close()
    deadline = time.monotonic() + 10
    while (threading.active_count() > base_threads + 2
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert threading.active_count() <= base_threads + 2, \
        [t.name for t in threading.enumerate()]
    assert gauges.get("transport.connections") == 0
