"""HalfPrecisionDistributedOptimizer (reference
misc/imagenet18/__init__.py:39- — SURVEY.md §2.4 Misc): fp16 model params,
fp16 gradients on the wire, fp32 master weights, static loss scaling."""

import numpy as np
import pytest
import torch

import byteps_tpu.torch as bps


@pytest.fixture
def session():
    bps.init()
    yield
    bps.shutdown()


def _setup(loss_scale=1024.0):
    torch.manual_seed(0)
    model = torch.nn.Linear(8, 4).half()
    fp16_params = [p for p in model.parameters() if p.requires_grad]
    fp32_params = [p.detach().clone().float().requires_grad_()
                   for p in fp16_params]
    inner = torch.optim.SGD(fp32_params, lr=0.1)
    opt = bps.HalfPrecisionDistributedOptimizer(
        inner, fp16_params=fp16_params, fp32_params=fp32_params,
        loss_scale=loss_scale,
        named_parameters=[(n, p) for n, p in model.named_parameters()])
    return model, fp16_params, fp32_params, opt


def test_step_updates_masters_and_copies_back(session):
    model, fp16s, fp32s, opt = _setup()
    before32 = [p.detach().clone() for p in fp32s]
    x = torch.randn(16, 8).half()
    loss = model(x).float().pow(2).mean()
    opt.scale_loss(loss).backward()
    opt.step()
    for b, p32, p16 in zip(before32, fp32s, fp16s):
        assert not torch.equal(b, p32)          # master moved
        assert p16.dtype == torch.float16
        np.testing.assert_allclose(p16.detach().float().numpy(),
                                   p32.detach().numpy(),
                                   rtol=1e-2, atol=1e-3)  # copied back


def test_loss_scale_cancels(session):
    """The applied update must be invariant to the loss scale (grads are
    scaled up for the fp16 wire and unscaled before the master step)."""
    results = []
    for scale in (1.0, 4096.0):
        model, fp16s, fp32s, opt = _setup(loss_scale=scale)
        x = torch.ones(4, 8).half()
        loss = model(x).float().sum()
        opt.scale_loss(loss).backward()
        opt.step()
        results.append([p.detach().clone().numpy() for p in fp32s])
        opt.zero_grad()
        bps.shutdown(); bps.init()
    for a, b in zip(*results):
        np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-3)


def test_training_reduces_loss(session):
    model, fp16s, fp32s, opt = _setup(loss_scale=128.0)
    x = torch.randn(64, 8).half()
    # realizable target so the loss can actually go to ~0
    w_true = torch.randn(8, 4).half()
    y = (x @ w_true).half()
    losses = []
    for _ in range(25):
        opt.zero_grad()
        loss = (model(x) - y).float().pow(2).mean()
        losses.append(float(loss.detach()))
        opt.scale_loss(loss).backward()
        opt.step()
    assert losses[-1] < 0.5 * losses[0]


def test_mismatched_param_lists_raise(session):
    model = torch.nn.Linear(2, 2).half()
    fp16_params = list(model.parameters())
    with pytest.raises(ValueError):
        bps.HalfPrecisionDistributedOptimizer(
            torch.optim.SGD([torch.nn.Parameter(torch.zeros(2))], lr=0.1),
            fp16_params=fp16_params, fp32_params=[])
