"""Wire-byte assertions for the compressed DCN hop (VERDICT r1 item 5).

The claim in comm/compressed.py and ops/collective_ops.py — "only
compressed bytes cross the inter-slice network" — is verified here at the
XLA level: compile the hierarchical reduction on a (dcn=2, ici=4) mesh and
account the bytes each collective moves, classified by which mesh axis its
replica groups span.  This does not need two real slices: the compiled
HLO's collective shapes ARE the wire contract (what a 2-slice pod would
move over DCN), so the 32x saving is asserted, not just claimed.

Reference anchor: compression wraps exactly the PUSH/PULL stages
(reference operations.cc:199-204); the DCN hop is this design's analog.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from byteps_tpu.ops.collective_ops import (hierarchical_push_pull,
                                           make_onebit_pair)
from byteps_tpu.utils.hlo_wire import dcn_ici_bytes as _dcn_ici_bytes


def _compile_hierarchical(mesh, n, compressed: bool, min_bytes: int = 0):
    compress, decompress = (make_onebit_pair() if compressed
                            else (None, None))

    def body(x):
        return hierarchical_push_pull(x[0], op="sum", compress=compress,
                                      decompress=decompress,
                                      compress_min_bytes=min_bytes)

    # body returns the full reduced array (it all-gathers internally), so
    # the output is replicated
    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(("dcn", "ici")),
                              out_specs=P(), check_vma=False))
    x = jnp.zeros((mesh.size, n), jnp.float32)
    return f, f.lower(x).compile().as_text()


@pytest.fixture
def mesh():
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("dcn", "ici"))


def test_onebit_dcn_hop_is_32x_smaller(mesh):
    n = 1 << 20  # 4 MB of f32 per rank
    _, hlo_u = _compile_hierarchical(mesh, n, compressed=False)
    _, hlo_c = _compile_hierarchical(mesh, n, compressed=True)
    dcn_u, ici_u = _dcn_ici_bytes(hlo_u, n_ici=4)
    dcn_c, ici_c = _dcn_ici_bytes(hlo_c, n_ici=4)
    # uncompressed DCN hop: the full f32 1/n_ici shard (1 MB here)
    assert dcn_u >= (n // 4) * 4
    # compressed: sign bits (1/32 of f32) + the scale scalar; assert the
    # end-to-end ratio with headroom for the scale/padding overhead
    assert dcn_c * 25 < dcn_u, (dcn_c, dcn_u)
    # compression must not touch intra-slice traffic (full-precision ICI)
    assert ici_c == ici_u, (ici_c, ici_u)


def test_compressed_hop_executes_and_is_signwise_correct(mesh):
    n = 4096
    f, _ = _compile_hierarchical(mesh, n, compressed=True)
    rng = np.random.RandomState(3)
    base = rng.randn(n).astype(np.float32)
    x = jnp.asarray(np.broadcast_to(base, (8, n)).copy())
    out = np.asarray(f(x))
    assert out.shape == (n,)
    assert np.isfinite(out).all()
    # all ranks contribute identical tensors: the onebit hop preserves
    # the sign structure of the sum exactly
    np.testing.assert_array_equal(np.sign(out), np.sign(base * 8).astype(out.dtype))


def test_compress_threshold_gates_small_shards(mesh, monkeypatch):
    """Below the min-bytes cutoff the compressed hop must NOT engage: the
    DCN wire bytes match the plain path (reference
    BYTEPS_MIN_COMPRESS_BYTES semantics, global.cc:137-139)."""
    n = 1 << 16  # 256 KB/rank -> 64 KB shard, below the 2 MB default
    _, hlo_plain = _compile_hierarchical(mesh, n, compressed=False)
    _, hlo_gated = _compile_hierarchical(mesh, n, compressed=True,
                                         min_bytes=None)  # default gate
    dcn_p, _ = _dcn_ici_bytes(hlo_plain, n_ici=4)
    dcn_g, _ = _dcn_ici_bytes(hlo_gated, n_ici=4)
    assert dcn_g == dcn_p, (dcn_g, dcn_p)
    # env override drops the cutoff and the compression engages again
    monkeypatch.setenv("BYTEPS_DCN_COMPRESS_MIN_BYTES", "1024")
    _, hlo_env = _compile_hierarchical(mesh, n, compressed=True,
                                       min_bytes=None)
    dcn_e, _ = _dcn_ici_bytes(hlo_env, n_ici=4)
    assert dcn_e * 25 < dcn_p, (dcn_e, dcn_p)


def test_compress_threshold_admits_large_shards(mesh):
    """Above the cutoff the default gate lets compression through."""
    n = 1 << 22  # 16 MB/rank -> 4 MB shard, above the 2 MB default
    _, hlo_c = _compile_hierarchical(mesh, n, compressed=True,
                                     min_bytes=None)
    _, hlo_u = _compile_hierarchical(mesh, n, compressed=False)
    dcn_c, _ = _dcn_ici_bytes(hlo_c, n_ici=4)
    dcn_u, _ = _dcn_ici_bytes(hlo_u, n_ici=4)
    assert dcn_c * 25 < dcn_u, (dcn_c, dcn_u)
