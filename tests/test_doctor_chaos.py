"""bps_doctor (ISSUE 16): live/postmortem diagnosis pins + the doctor
chaos lane's 3-process acceptance run.

The pure half: sparkline rendering, firing-rule extraction from
snapshot gauges, the live verdict over a synthetic ``cluster_metrics``
reply, and the postmortem correlation over synthetic flight dumps /
saved time-series windows / a merged trace.

The acceptance run: three real workers on a fast sampling cadence, one
under a sustained straggler fault (``slow:rank=1:site=sync``) with a
``slow_socket`` rule armed alongside — the victim's health rules fire
within a few sampling windows (its ``/healthz`` flips to 503 and back
to 200 after the fault budget exhausts), ``cluster_metrics()`` carries
the piggybacked history view, and ``bps_doctor --postmortem`` over the
run's flight dumps + saved ``/timeseries`` window names the culprit
rank and the injection site.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request

import pytest

import byteps_tpu.core.api as api
from byteps_tpu.fault import membership as mm
from tools.bps_doctor import (diagnose_live, diagnose_postmortem,
                              dominant_attrib, firing_rules)
from tools.bps_doctor import main as doctor_main
from tools.bps_doctor import render_markdown, sparkline

from .conftest import free_port as _free_port
from .test_observability import _Reader, _spawn_obs_worker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_epoch():
    mm._reset_epoch_for_tests()
    yield
    if api.initialized():
        api.shutdown()
    api._declared_order = []
    mm._reset_epoch_for_tests()


# -- pure rendering / diagnosis ---------------------------------------------


def test_doctor_sparkline_shapes():
    assert sparkline([]) == "-"
    assert sparkline([1.0, 1.0, 1.0]) == "▁▁▁"      # flat: all-min
    s = sparkline([0, 2, 4, 6, 8])
    assert s[0] == "▁" and s[-1] == "█" and len(s) == 5


def test_doctor_firing_rules_reads_alert_gauges():
    cluster = {"ranks": {
        "1": {"metrics": {"gauges": {
            'health.alerts_active{rule="overlap_floor"}': 1.0,
            'health.alerts_active{rule="slow_peer"}': 0.0,
            "step.overlap_fraction": 0.1}}},
        "0": {"metrics": {"gauges": {
            'health.alerts_active{rule="overlap_floor"}': 0.0}}},
    }}
    assert firing_rules(cluster) == {1: ["overlap_floor"]}


def test_doctor_dominant_attrib_picks_largest_mean():
    summ = {"series": {"attrib_sync": {"mean": 40.0},
                       "attrib_compute": {"mean": 9.0},
                       "overlap": {"mean": 0.5}}}
    assert dominant_attrib(summ) == {"component": "sync", "mean_ms": 40.0}
    assert dominant_attrib({"series": {}}) is None


def _synthetic_cluster():
    hist_series = lambda mean, spark: {  # noqa: E731
        "series": {"attrib_sync": {"mean": mean},
                   "overlap": {"last": 0.2, "mean": 0.4, "min": 0.1,
                               "max": 0.9, "spark": spark}}}
    return {
        "epoch": 2, "world": [0, 1, 2], "coordinator": 0,
        "slow": {"1": 9.3, "0": 0.1},
        "probation": [1],
        "ranks": {"1": {"metrics": {"gauges": {
            'health.alerts_active{rule="overlap_floor"}': 1.0}}}},
        "history": {
            "0": {"summary": hist_series(4.0, [0.9, 0.9, 0.9])},
            "1": {"summary": hist_series(120.0, [0.9, 0.4, 0.1])},
            "2": {"summary": hist_series(5.0, [0.9, 0.9, 0.9])},
        },
    }


def test_doctor_diagnose_live_names_the_culprit():
    report = diagnose_live(_synthetic_cluster(), skew_ratio=4.0)
    assert report["healthy"] is False
    assert report["alerts"] == {1: ["overlap_floor"]}
    c = report["culprit"]
    assert c["rank"] == 1 and len(c["evidence"]) >= 3
    assert any("alert overlap_floor" in e for e in c["evidence"])
    assert any("skew" in e for e in c["evidence"])
    # trends render from the piggybacked spark tails
    assert report["trends"][1]["overlap"]["spark"] != "-"
    assert report["dominant_attrib"][1]["component"] == "sync"
    md = render_markdown(report)
    assert "Culprit: rank 1" in md and "DEGRADED" in md
    json.dumps(report)  # the --json path must serialize


def _write_dump(dir_, rank, events, reason="exit"):
    path = os.path.join(str(dir_), "bps_flight_1_rank%d_%d_%s_%d.json"
                        % (rank, 1000 + rank, reason, len(events)))
    with open(path, "w") as f:
        json.dump({"reason": reason, "wall_time": 10.0, "pid": 1000 + rank,
                   "rank": rank, "capacity": 64, "events": events}, f)


def test_doctor_diagnose_postmortem_synthetic(tmp_path, capsys):
    _write_dump(tmp_path, 0, [
        {"t": 2.0, "mono": 2.0, "kind": "membership.world_change"}])
    _write_dump(tmp_path, 1, [
        {"t": 1.0, "mono": 1.0, "kind": "alert", "rule": "overlap_floor",
         "state": "firing", "overlap": 0.1, "floor": 0.5},
        {"t": 3.0, "mono": 3.0, "kind": "fault.slow_cleared",
         "site": "sync", "rank": 1, "n": 12},
        {"t": 5.0, "mono": 5.0, "kind": "alert", "rule": "overlap_floor",
         "state": "cleared"}])
    with open(tmp_path / "bps_timeseries_rank1.json", "w") as f:
        json.dump({"points": [{"t": 0.5, "overlap": 0.9},
                              {"t": 1.0, "overlap": 0.1},
                              {"t": 1.5, "overlap": 0.8}]}, f)
    with open(tmp_path / "bps_trace_merged.json", "w") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "name": "process_name"},
            {"ph": "X", "ts": 1500.0, "name": "push"}],
            "mergedFrom": ["a.json", "b.json"]}, f)

    report = diagnose_postmortem(str(tmp_path))
    first = report["first_degradation"]
    assert first["rule"] == "overlap_floor" and first["rank"] == 1
    c = report["culprit"]
    assert c["rank"] == 1 and c["site"] == "sync"
    assert any("fault slow_cleared" in e for e in c["evidence"])
    ts = report["timeseries"]["bps_timeseries_rank1.json"]
    assert ts["len"] == 3 and ts["overlap_min"] == 0.1
    assert report["trace"]["events"] == 1
    assert report["trace"]["files"] == 2
    md = render_markdown(report)
    assert "Culprit: rank 1, site sync" in md
    assert "Degraded first: rule `overlap_floor` on rank 1" in md

    # the CLI: --json emits the same document, exit 0 on a named culprit
    rc = doctor_main(["--postmortem", str(tmp_path), "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert doc["culprit"]["rank"] == 1


def test_doctor_postmortem_without_evidence_exits_nonzero(tmp_path,
                                                          capsys):
    rc = doctor_main(["--postmortem", str(tmp_path)])
    assert rc == 1
    assert "postmortem" in capsys.readouterr().out


# -- the 3-process acceptance run -------------------------------------------


def _healthz(port, timeout=5.0):
    """(status, doc) — unlike urlopen's default, a 503 is an answer
    here, not an exception."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


@pytest.mark.chaos
def test_doctor_3proc_straggler_healthz_cycle_and_postmortem(tmp_path):
    """ISSUE 16 acceptance: under ``slow:rank=1:site=sync`` (with a
    ``slow_socket`` rule armed alongside) the victim's health rules
    fire within a few sampling windows and its /healthz flips to 503
    while the survivors stay 200; cluster_metrics() carries the
    piggybacked history view; after the fault budget exhausts and K
    clean windows pass the victim recovers to 200; and the postmortem
    over the run's flight dumps + the saved /timeseries window names
    culprit rank 1 at site "sync"."""
    steps = 90
    bus_port, hb_port = _free_port(), _free_port()
    fast = {
        "BYTEPS_ELASTIC_STEP_SLEEP": "0.05",
        "BYTEPS_TS_INTERVAL_S": "1.0",       # window > one slow step
        "BYTEPS_TS_WINDOW": "64",
        "BYTEPS_HEALTH_WINDOWS": "2",
        "BYTEPS_HEALTH_OVERLAP_FLOOR": "0.5",
        "BYTEPS_FLIGHT_DUMP_ON_EXIT": "1",
    }
    spec = ("slow:rank=1:site=sync:ms=300:n=16,"
            "slow_socket:rank=1:site=transport:ms=40")
    procs = {
        r: _spawn_obs_worker(r, bus_port, hb_port, steps, tmp_path, extra=(
            {**fast, "BYTEPS_FAULT_SPEC": spec} if r == 1 else dict(fast)))
        for r in (0, 1, 2)}
    readers = {r: _Reader(p) for r, p in procs.items()}
    try:
        ports = {}
        for r in (0, 1, 2):
            line = readers[r].wait_for("OBS ", timeout=120)
            ports[r] = int(line.split()[2])

        # clause 1: the victim degrades to 503 within a few windows of
        # the fault biting, and names the firing rule
        deadline = time.monotonic() + 60
        degraded = None
        while time.monotonic() < deadline and degraded is None:
            try:
                status, doc = _healthz(ports[1])
            except OSError:
                status, doc = 0, None
            if status == 503:
                degraded = doc
                break
            time.sleep(0.15)
        assert degraded is not None, \
            "rank 1 never answered 503 under the straggler fault"
        assert degraded["degraded"] is True
        assert "overlap_floor" in degraded["alerts"], degraded["alerts"]

        # rank 2 stays healthy through the victim's degradation; rank 0
        # hosts the bus, so the one rule it may legitimately fire is the
        # cluster-scoped attrib_skew — and it must name rank 1, not
        # accuse itself
        status2, doc2 = _healthz(ports[2])
        assert status2 == 200 and doc2["ok"] is True, doc2
        status0, doc0 = _healthz(ports[0])
        if status0 != 200:
            assert doc0["alerts"] == ["attrib_skew"], doc0["alerts"]
            worst = doc0["alert_details"]["attrib_skew"]["worst"]
            assert worst["rank"] == 1, worst

        # clause 2: cluster_metrics() grew the history view — windowed
        # summaries piggybacked over the bus, multiple ranks deep
        deadline = time.monotonic() + 45
        history = None
        while time.monotonic() < deadline:
            try:
                out = api.cluster_metrics(bus=f"127.0.0.1:{bus_port}",
                                          timeout=5)
            except (ConnectionError, TimeoutError, OSError):
                out = {}
            h = out.get("history") or {}
            with_overlap = {r for r, v in h.items()
                           if "overlap" in ((v.get("summary") or {})
                                            .get("series") or {})}
            if len(with_overlap) >= 2:
                history = h
                break
            time.sleep(0.3)
        assert history is not None, "history never showed 2 ranks' windows"
        summ = history[1]["summary"]
        assert summ["series"]["overlap"]["min"] < 0.5   # the collapse shows
        assert len(summ["series"]["overlap"]["spark"]) >= 1

        # save the victim's raw ring for the postmortem, while it lives
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ports[1]}/timeseries", timeout=5) as r:
            ring = json.loads(r.read().decode())
        assert ring["len"] >= 2
        (tmp_path / "bps_timeseries_rank1.json").write_text(
            json.dumps(ring))

        # clause 3: the fault budget exhausts -> K clean windows -> the
        # victim un-pages all the way back to 200
        deadline = time.monotonic() + 90
        recovered = None
        while time.monotonic() < deadline:
            try:
                status, doc = _healthz(ports[1])
            except OSError:
                break                       # the worker may have finished
            if status == 200 and doc["ok"]:
                recovered = doc
                break
            time.sleep(0.2)
        assert recovered is not None, \
            "rank 1 never recovered to 200 after the fault budget cleared"
        assert recovered["alerts"] == []

        outs = {}
        for r, p in procs.items():
            p.communicate(timeout=180)
            outs[r] = "\n".join(readers[r].lines)
        for r in (0, 1, 2):
            assert procs[r].returncode == 0, outs[r][-2000:]
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()

    # clause 4: the postmortem correlates the exit dumps + the saved
    # window into a verdict naming the culprit rank and injection site
    dumps = list(tmp_path.glob("bps_flight_*_exit_*.json"))
    assert len(dumps) == 3, list(tmp_path.iterdir())
    report = diagnose_postmortem(str(tmp_path))
    first = report["first_degradation"]
    assert first is not None
    # whichever rule paged first, it points at the victim: either it
    # fired ON rank 1, or it is the bus host's cluster-scoped skew rule
    # whose worst-offender detail names rank 1
    assert (first["rank"] == 1
            or first["detail"].get("worst", {}).get("rank") == 1), first
    c = report["culprit"]
    assert c["rank"] == 1 and c["site"] == "sync", c
    assert any("fault slow_cleared" in e for e in c["evidence"]), c
    ts = report["timeseries"]["bps_timeseries_rank1.json"]
    assert ts["overlap_min"] is not None and ts["overlap_min"] < 0.5
    # both transitions made it into the black box
    states = {(a["rank"], a["rule"], a["state"]) for a in report["alerts"]}
    assert (1, "overlap_floor", "firing") in states
    assert (1, "overlap_floor", "cleared") in states
