"""The data-path sync deadline (ISSUE 8 tentpole part 3,
``BYTEPS_SYNC_DEADLINE_S``): a unit the engine's syncer stays blocked on
past the deadline — the wedged-collective TPU failure mode — becomes
failure evidence routed to the INSTALLED failure action
(``failure_detector.data_path_stalled``), with ``os._exit`` demoted to
the escalation of last resort.  Under ``ElasticMembership`` the evidence
(an empty stale set) becomes a *reconcile* rendezvous.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

import byteps_tpu.core.api as api
from byteps_tpu.common.config import Config, reset_config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import membership as mm
from byteps_tpu.utils import failure_detector as fd

from .conftest import free_port as _free_port


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    """Fresh epoch + no leaked installed action + exit trapped (a real
    os._exit would take pytest with it — and the whole point here is
    proving it is NOT called)."""
    mm._reset_epoch_for_tests()
    exits = []
    monkeypatch.setattr(fd, "_exit", lambda code: exits.append(code))
    # the membership escalation path exits through its OWN alias — trap
    # it too so a failed transition shows up as a failed assert on
    # `exits`, not a dead pytest process
    monkeypatch.setattr(mm, "_exit", lambda code: exits.append(code))
    yield exits
    fd.install_failure_action(None)
    if api.initialized():
        api.shutdown()
    api._declared_order = []
    mm._reset_epoch_for_tests()


def _wedge_next_unit(eng, seconds):
    """Make the NEXT unit the syncer retires block ``seconds`` (one-shot;
    restores the real block hook before sleeping so only one unit is
    wedged)."""
    orig = eng._block

    def _wedge_once(x):
        eng._block = orig
        time.sleep(seconds)
        return orig(x)
    eng._block = _wedge_once


def _wait_for(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"{what} not reached within {timeout}s")


def test_sync_deadline_off_by_default():
    assert Config().sync_deadline_s == 0.0
    api.init(Config())
    assert api._require()._deadline_thread is None


def test_sync_deadline_config_validation(monkeypatch):
    with pytest.raises(ValueError, match="sync_deadline_s"):
        Config(sync_deadline_s=-1.0)
    monkeypatch.setenv("BYTEPS_SYNC_DEADLINE_S", "2.5")
    reset_config()
    from byteps_tpu.common.config import get_config
    assert get_config().sync_deadline_s == 2.5


@pytest.mark.chaos
def test_sync_deadline_fires_installed_action_not_exit(_clean_slate):
    """A wedged unit trips the deadline: the installed action receives
    the empty-stale-set evidence, counters/flight record it, and the
    process does NOT exit.  The unit itself still completes once the
    wedge resolves (no world change here — the action only observes)."""
    exits = _clean_slate
    calls = []
    fd.install_failure_action(lambda stale: calls.append(set(stale)))
    api.init(Config(sync_deadline_s=0.3))
    eng = api._require()
    assert eng._deadline_thread is not None
    _wedge_next_unit(eng, 1.2)
    h = eng.push_pull_local_async(np.ones(8, np.float32), "g", op="sum")
    _wait_for(lambda: calls, what="installed failure action call")
    assert calls[0] == set()          # wedge evidence names no suspect
    assert counters.get("engine.sync_deadline_trips") >= 1
    assert exits == []                # os._exit stayed the last resort
    out = np.asarray(h.wait(timeout=30))
    np.testing.assert_allclose(out, 1.0)


@pytest.mark.chaos
def test_sync_deadline_routes_through_reconcile_not_exit(_clean_slate):
    """End-to-end single-rank loop: deadline trip → installed
    ElasticMembership action → reconcile rendezvous (epoch +1, same
    world) → engine suspended/resumed — and the wedged unit's late
    result is dropped as stale, never delivered."""
    exits = _clean_slate
    port = _free_port()
    api.init(Config(sync_deadline_s=0.3,
                    membership_rendezvous_timeout_s=3.0,
                    membership_sync_timeout_s=10.0))
    m = mm.ElasticMembership(0, [0], f"127.0.0.1:{port}").start()
    try:
        fd.install_failure_action(m.on_failure)
        eng = api._require()
        _wedge_next_unit(eng, 1.5)
        h = eng.push_pull_local_async(np.ones(8, np.float32), "g", op="sum")
        _wait_for(lambda: mm.current_epoch() >= 1, what="reconcile epoch")
        assert counters.get("membership.reconcile_started") >= 1
        # the wedged unit was issued under epoch 0 and must be dropped
        with pytest.raises(RuntimeError, match="stale membership epoch"):
            h.wait(timeout=30)
        # the world re-agreed unchanged and the engine is back up
        _wait_for(lambda: api.initialized() and api._require()._running,
                  what="resumed engine")
        # the engine can resume a beat before THIS instance applies the
        # agreed view — wait on the view itself, don't assert the race
        _wait_for(lambda: m.view() == mm.MembershipView(1, (0,)),
                  what="reconciled view applied")
        out = api._require().push_pull_local(np.ones(8, np.float32), "g2",
                                             op="sum")
        np.testing.assert_allclose(np.asarray(out), 1.0)
        assert exits == []
    finally:
        m.stop()


@pytest.mark.chaos
def test_concurrent_stall_reports_fire_the_action_once(_clean_slate):
    """ISSUE 10 satellite: the sync-deadline watchdog and the step
    watchdog are separate threads observing the same wedge — a second
    ``data_path_stalled`` arriving while the first is still being acted
    on must be suppressed, not double-run the failure action (or,
    uninstalled, double-fire ``os._exit``)."""
    import threading
    exits = _clean_slate
    calls = []
    entered = threading.Event()

    def slow_action(stale):
        calls.append(set(stale))
        entered.set()
        time.sleep(0.5)         # the first report is still in flight...

    fd.install_failure_action(slow_action)
    t = threading.Thread(target=fd.data_path_stalled, args=(1.0, "first"))
    t.start()
    assert entered.wait(5.0)
    fd.data_path_stalled(1.0, "second")     # ...when the second lands
    t.join(timeout=5)
    assert calls == [set()]                 # the action ran ONCE
    assert counters.get("failure_detector.stall_suppressed") == 1
    assert exits == []
    # sequential reports (a later, distinct stall) still escalate
    fd.data_path_stalled(2.0, "third")
    assert len(calls) == 2


@pytest.mark.chaos
def test_stall_during_inflight_shrink_does_not_double_exit(_clean_slate):
    """Regression guard: a watchdog stall landing DURING an in-flight
    elastic transition (epoch already advanced by the shrink) resolves
    through the membership's already-moving-world path — never a second
    ``os._exit`` racing the transition."""
    import threading
    exits = _clean_slate
    port = _free_port()
    m = mm.ElasticMembership(0, [0], f"127.0.0.1:{port}",
                             rendezvous_timeout_s=2.0,
                             sync_timeout_s=5.0).start()
    try:
        fd.install_failure_action(m.on_failure)
        # an in-flight transition: another thread is applying epoch 1
        applier = threading.Thread(
            target=lambda: m._maybe_apply(mm.MembershipView(1, (0,))))
        mm.set_epoch(1)          # the shrink's guard is already up
        applier.start()
        # the stall report arrives mid-transition: reconcile sees the
        # epoch already moving and FOLLOWS it (wait_ready), no exit
        fd.data_path_stalled(3.0, "watchdog during shrink")
        applier.join(timeout=30)
        assert m.view().epoch == 1
        assert exits == [], exits
    finally:
        fd.install_failure_action(None)
        m.stop()


@pytest.mark.chaos
def test_step_watchdog_default_prefers_installed_action(_clean_slate):
    """StepWatchdog's default stall action is demoted: with an installed
    failure action the evidence goes there (empty stale set); os._exit
    only when nothing is installed."""
    exits = _clean_slate
    calls = []
    fd.install_failure_action(lambda stale: calls.append(set(stale)))
    wd = fd.StepWatchdog(timeout=0.2).start()
    try:
        _wait_for(lambda: calls, timeout=5.0, what="watchdog stall action")
        assert calls[0] == set()
        assert exits == []
    finally:
        wd.stop()
    # without an installed action the last resort still exits restartable
    fd.install_failure_action(None)
    wd2 = fd.StepWatchdog(timeout=0.2).start()
    try:
        _wait_for(lambda: exits, timeout=5.0, what="last-resort exit")
        assert exits[0] == 17
    finally:
        wd2.stop()
