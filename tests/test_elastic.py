"""Elastic membership end-to-end (fault/membership.py + elastic_worker.py).

The acceptance pins for shrink-to-survivors and in-place rejoin:

- ``test_shrink_to_survivors_matches_clean_run`` — 3 real processes,
  one killed mid-train by the fault injector; the two survivors shrink
  in place (no process exit), finish training, and their final state
  matches a clean 2-process run started from the state at the shrink.
- ``test_rejoin_in_place_at_step_boundary`` — the killed rank restarts
  and rejoins the running world at a step boundary, receiving
  epoch/declared keys/parameters from a survivor; stale-epoch chunks
  and server pushes manufactured after the transitions are dropped,
  not delivered/summed.
- ``test_double_failure_during_shrink`` — a second member dies inside
  the shrink window (before its rendezvous hello); the rendezvous
  times it out and the last survivor completes alone.

All are ``chaos``-marked; `tools/run_chaos.sh` runs them under a hard
per-test timeout so a wedged rendezvous fails fast.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


def _spawn(rank, world, bus_port, hb_port, steps, extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["DMLC_NUM_WORKER"] = "1"        # single-host engines; the world
    env["DMLC_WORKER_ID"] = str(rank)   # lives in the membership layer
    env["BYTEPS_ELASTIC_RANK"] = str(rank)
    env["BYTEPS_ELASTIC_WORLD"] = world
    env["BYTEPS_ELASTIC_BUS"] = f"127.0.0.1:{bus_port}"
    env["BYTEPS_ELASTIC_HB_PORT"] = hb_port
    env["BYTEPS_ELASTIC_STEPS"] = str(steps)
    env["BYTEPS_MEMBERSHIP_RENDEZVOUS_TIMEOUT"] = "3"
    env["BYTEPS_MEMBERSHIP_SYNC_TIMEOUT"] = "15"
    env["BYTEPS_LOG_LEVEL"] = "ERROR"
    env.pop("BYTEPS_FAULT_SPEC", None)
    env.pop("BYTEPS_ELASTIC_REJOIN", None)
    env.update(extra or {})
    return subprocess.Popen([sys.executable, WORKER], env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _communicate(procs, timeout=180):
    outs = {}
    try:
        for name, p in procs.items():
            outs[name], _ = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        for p in procs.values():
            p.kill()
        pytest.fail("elastic workers hung; partial output: "
                    + "".join(o[-1500:] for o in outs.values()))
    return outs


def _final(out):
    """Parse the worker's 'FINAL <epoch> <world> <w0>' line."""
    for line in out.splitlines():
        if line.startswith("FINAL "):
            _, epoch, world, w0 = line.split()
            return int(epoch), world, float(w0)
    raise AssertionError("no FINAL line in:\n" + out[-3000:])


def _simulate(w0, ranks, n_steps):
    """The worker's update rule, bit-for-bit (float32 ops, same order)."""
    w = np.float32(w0)
    for _ in range(n_steps):
        g = (np.sum([np.float32((r + 1) ** 2) for r in ranks],
                    dtype=np.float32) / np.float32(len(ranks)))
        w = np.float32(w - np.float32(0.1) * g)
    return float(w)


@pytest.mark.chaos
def test_shrink_to_survivors_matches_clean_run():
    """Kill rank 1 at push step 4 of 9: ranks 0 and 2 shrink in place
    (epoch 1, world {0,2}, no exit), finish training, and their final
    state equals a clean 2-process {0,2} run started from the state at
    the shrink boundary."""
    n, kill_at = 9, 4
    bus, hb = str(_free_port()), str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, hb, n, extra=(
            {"BYTEPS_FAULT_SPEC": f"kill:rank=1:step={kill_at}",
             "BYTEPS_FAULT_SEED": "7"} if r == 1 else None))
        for r in (0, 1, 2)}
    outs = _communicate(procs)

    # the victim really was killed mid-train (crash exit, no FINAL)
    assert procs[1].returncode == 1, outs[1][-3000:]
    assert "START 1" in outs[1]
    assert "FINAL" not in outs[1]
    # both survivors shrank in place: process exit code 0, shrink event
    # observed, final world/epoch agreed
    finals = {}
    for r in (0, 2):
        assert procs[r].returncode == 0, outs[r][-3000:]
        assert "WORLD 1 0,2" in outs[r], outs[r][-3000:]
        finals[r] = _final(outs[r])
        assert finals[r][0] == 1 and finals[r][1] == "0,2", finals[r]
    assert finals[0][2] == pytest.approx(finals[2][2], abs=1e-6)

    # clean 2-process run from the same state: world {0,2} from the
    # shrink-boundary state, steps kill_at..n
    w_shrink = _simulate(0.0, (0, 1, 2), kill_at - 1)
    bus2 = str(_free_port())
    procs2 = {
        r: _spawn(r, "0,2", bus2, "", n, extra={
            "BYTEPS_ELASTIC_START_STEP": str(kill_at),
            "BYTEPS_ELASTIC_INIT_W": repr(w_shrink)})
        for r in (0, 2)}
    outs2 = _communicate(procs2)
    for r in (0, 2):
        assert procs2[r].returncode == 0, outs2[r][-3000:]
    clean = _final(outs2[0])
    assert clean[0] == 0 and clean[1] == "0,2"
    assert clean[2] == pytest.approx(_final(outs2[2])[2], abs=1e-6)
    # the acceptance equivalence: elastic shrink == clean run from the
    # same state
    assert finals[0][2] == pytest.approx(clean[2], abs=1e-5), (
        finals, clean, w_shrink)


@pytest.mark.chaos
def test_rejoin_in_place_at_step_boundary():
    """Restart the killed rank: it rejoins at a step boundary (epoch 2)
    with epoch/declared keys/params broadcast from a survivor, every
    member finishes at the same state, and stale-epoch chunks/pushes
    after the transitions are dropped, not summed."""
    n, kill_at = 40, 4
    bus, hb = str(_free_port()), str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, hb, n, extra={
            "BYTEPS_ELASTIC_STEP_SLEEP": "0.3",
            **({"BYTEPS_FAULT_SPEC": f"kill:rank=1:step={kill_at}",
                "BYTEPS_FAULT_SEED": "7"} if r == 1 else
               {"BYTEPS_ELASTIC_STALE_PROBE": "1"} if r == 0 else {})})
        for r in (0, 1, 2)}
    # the victim dies early; restart it as a rejoiner against the same
    # bus (what bpslaunch-dist --elastic does with BYTEPS_ELASTIC_REJOIN)
    out_victim, _ = procs[1].communicate(timeout=120)
    assert procs[1].returncode == 1, out_victim[-3000:]
    # wait for the SURVIVORS' SHRINK TO LAND (epoch 1, world {0,2}) on
    # the bus before restarting the victim — not a sleep: under
    # full-suite load the detector + shrink rendezvous can trail the
    # victim's exit by seconds, and a rejoiner arriving mid-shrink
    # would be admitted into a different epoch than the one this test
    # pins.  The bus ping is the ground truth the rejoiner itself would
    # consult.
    import time as _time
    from byteps_tpu.fault.membership import bus_request
    deadline = _time.monotonic() + 90.0
    while True:
        try:
            pong = bus_request(("127.0.0.1", int(bus)), {"op": "ping"},
                               timeout=3.0)
        except (ConnectionError, TimeoutError):
            pong = {}
        if (pong.get("ok") and int(pong.get("epoch", 0)) >= 1
                and sorted(pong.get("world") or ()) == [0, 2]):
            break
        if _time.monotonic() > deadline:
            pytest.fail(f"survivors never shrank to world {{0,2}}: "
                        f"last ping {pong!r}")
        _time.sleep(0.1)
    rejoiner = _spawn(1, "0,1,2", bus, "", n, extra={
        "BYTEPS_ELASTIC_REJOIN": "1",
        "BYTEPS_ELASTIC_STEP_SLEEP": "0.3"})
    outs = _communicate({0: procs[0], 2: procs[2], "rj": rejoiner})

    # the rejoiner was admitted at a step boundary with state in hand
    assert rejoiner.returncode == 0, outs["rj"][-3000:]
    rejoin_line = next(l for l in outs["rj"].splitlines()
                       if l.startswith("REJOINED "))
    _, epoch, world, step0 = rejoin_line.split()
    assert int(epoch) == 2 and world == "0,1,2", rejoin_line
    assert kill_at - 1 <= int(step0) < n, rejoin_line
    # survivors observed both transitions: shrink then grow, each at a
    # step boundary
    finals = {}
    for r in (0, 2):
        assert procs[r].returncode == 0, outs[r][-3000:]
        assert "WORLD 1 0,2" in outs[r], outs[r][-3000:]
        assert "WORLD 2 0,1,2" in outs[r], outs[r][-3000:]
        finals[r] = _final(outs[r])
        assert finals[r][0] == 2 and finals[r][1] == "0,1,2", finals[r]
    fin_rj = _final(outs["rj"])
    assert fin_rj[0] == 2 and fin_rj[1] == "0,1,2", fin_rj
    # identical final state on every member — the rejoiner continued
    # from the survivor-broadcast parameters, not from scratch
    assert finals[0][2] == pytest.approx(finals[2][2], abs=1e-6)
    assert finals[0][2] == pytest.approx(fin_rj[2], abs=1e-6)
    # the deterministic stale-epoch probes (rank 0, post-training)
    assert "STALE-CHUNK-DROPPED" in outs[0], outs[0][-3000:]
    assert "STALE-PUSH-DROPPED" in outs[0], outs[0][-3000:]


def _simulate_sharded(worlds):
    """elastic_worker's sharded-update leg, bit-for-bit: eager optax
    sgd(momentum=0.9) on the mean-gradient basis vector, float32
    throughout — the same eager op sequence the slot's exact mode runs
    on its padded shards (the pad is zeros under elementwise
    transforms, so the logical region is identical)."""
    import jax.numpy as jnp
    import optax

    from .elastic_worker import LR, SU_DIM

    tx = optax.sgd(learning_rate=LR, momentum=0.9)
    basis = np.arange(1, SU_DIM + 1, dtype=np.float32)
    w = jnp.zeros(SU_DIM, jnp.float32)
    st = tx.init(w)
    for ranks in worlds:
        g0 = (np.sum([np.float32((r + 1) ** 2) for r in ranks],
                     dtype=np.float32) / np.float32(len(ranks)))
        u, st = tx.update(jnp.asarray(np.float32(g0) * basis), st, w)
        w = optax.apply_updates(w, u)
    return np.asarray(w)


@pytest.mark.chaos
def test_shrink_resharding_sharded_update():
    """ISSUE 20 chaos acceptance (tools/run_chaos.sh `sharded` lane):
    kill rank 1 mid-step while every worker ALSO trains a second model
    through the engine's sharded weight-update path
    (BYTEPS_SHARDED_UPDATE=1, optimizer state owner-resident on the
    local mesh).  The survivors' shrink tears each engine down —
    possibly mid-dispatch — and the suspend() stash carries master +
    momentum at logical length; declare_update re-pads them onto the
    rebuilt mesh (the ``RESHARDED <applied> <owners>`` line, applied>0,
    proves restore-not-reinit and the owner reassignment).

    Exactly-once: the slot's ``applied`` counter arbitrates a torn
    dispatch (committed before the drain → skip; dropped as stale →
    redispatch), so each survivor commits exactly one update per step
    and the final master is bit-for-bit the eager-optax replay of the
    mean-gradient sequence ({0,1,2} before the shrink, {0,2} after).
    The geometry-CHANGING re-shard (8→4 devices) is pinned in-process
    in tests/test_sharded_update.py; this lane pins the kill-driven
    export/restore path under real process chaos."""
    n, kill_step = 9, 4
    # the sharded leg doubles the per-step push count (grad + wsh), and
    # the injector counts pushes: land the kill on step 4's GRAD push,
    # before its step-4 sync — survivors sync steps 1-3 at full world
    kill_push = 2 * kill_step - 1
    bus, hb = str(_free_port()), str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, hb, n, extra={
            "BYTEPS_ELASTIC_SHARDED": "1",
            "BYTEPS_SHARDED_UPDATE": "1",
            **({"BYTEPS_FAULT_SPEC": f"kill:rank=1:step={kill_push}",
                "BYTEPS_FAULT_SEED": "7"} if r == 1 else {})})
        for r in (0, 1, 2)}
    outs = _communicate(procs)

    assert procs[1].returncode == 1, outs[1][-3000:]
    assert "FINAL" not in outs[1]

    expected = _simulate_sharded(
        [(0, 1, 2)] * (kill_step - 1) + [(0, 2)] * (n - kill_step + 1))
    for r in (0, 2):
        assert procs[r].returncode == 0, outs[r][-3000:]
        assert "WORLD 1 0,2" in outs[r], outs[r][-3000:]
        # the rebuilt engine restored (not re-initialized) the slot:
        # applied > 0 at re-declare time, and the owner map covers the
        # whole re-padded vector on the 2-device local mesh
        resh = [l for l in outs[r].splitlines()
                if l.startswith("RESHARDED ")]
        assert resh, outs[r][-3000:]
        assert all(int(l.split()[1]) >= 1 for l in resh), resh
        assert resh[-1].split()[2] == "0,1", resh
        fin = next(l for l in outs[r].splitlines()
                   if l.startswith("FINAL-SHARDED "))
        _, applied, vals = fin.split(" ", 2)
        assert int(applied) == n, fin   # exactly one commit per step
        got = np.array([float(v) for v in vals.split(",")], np.float32)
        assert np.array_equal(got, expected), (r, got, expected)


@pytest.mark.chaos
def test_double_failure_during_shrink():
    """Rank 1 is killed mid-train; rank 2 dies the moment its detector
    fires (inside the shrink window).  Rank 0 completes training alone
    at world {0} with the exact expected state.  Epoch count is a race,
    not a contract: usually the rendezvous times rank 2 out and one
    bump suffices, but rank 2's parked sync can be released into the
    rendezvous (reconcile join) just before it dies — it then makes the
    epoch-1 agreement and costs rank 0 one more (equally correct)
    shrink round to drop it."""
    n, kill_at = 9, 4
    bus, hb = str(_free_port()), str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, hb, n, extra=(
            {"BYTEPS_FAULT_SPEC": f"kill:rank=1:step={kill_at}",
             "BYTEPS_FAULT_SEED": "7"} if r == 1 else
            {"BYTEPS_ELASTIC_DIE_ON_DETECT": "1"} if r == 2 else None))
        for r in (0, 1, 2)}
    outs = _communicate(procs)

    assert procs[1].returncode == 1, outs[1][-3000:]
    assert procs[2].returncode == 1, outs[2][-3000:]
    assert "DIED-ON-DETECT" in outs[2], outs[2][-3000:]
    assert procs[0].returncode == 0, outs[0][-3000:]
    epoch, world, w0 = _final(outs[0])
    assert epoch >= 1 and world == "0", (epoch, world)
    expected = _simulate(_simulate(0.0, (0, 1, 2), kill_at - 1),
                         (0,), n - kill_at + 1)
    assert w0 == pytest.approx(expected, abs=1e-5), (w0, expected)
