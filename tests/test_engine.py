"""End-to-end engine tests: the full partition -> schedule -> collective ->
callback path (reference call stack §3.2, collapsed to TPU stages)."""

import jax.numpy as jnp
import numpy as np
import pytest

import byteps_tpu as bps
from byteps_tpu.common import Config
from byteps_tpu.common.config import set_config


@pytest.fixture
def bps_session():
    bps.init()
    yield bps
    bps.shutdown()


@pytest.fixture
def bps_chunked():
    # Tiny partition bound -> every tensor over 4096 B gets multiple chunks,
    # exercising partitioning + reassembly (reference BYTEPS_PARTITION_BYTES).
    set_config(Config(partition_bytes=4096))
    bps.init()
    yield bps
    bps.shutdown()


def test_basics(bps_session):
    assert bps.size() == 8
    assert bps.rank() == 0
    assert bps.local_size() == 8
    assert bps.local_rank() == 0


def test_push_pull_sum_and_average(bps_session):
    x = jnp.asarray(np.random.RandomState(0).randn(8, 13, 3).astype(np.float32))
    s = bps.push_pull(x, "grad/w", op="sum")
    np.testing.assert_allclose(np.asarray(s), np.asarray(x).sum(0), rtol=1e-5)
    a = bps.push_pull(x, "grad/w", op="average")
    np.testing.assert_allclose(np.asarray(a), np.asarray(x).mean(0), rtol=1e-5)


def test_push_pull_async_many(bps_session):
    rng = np.random.RandomState(1)
    tensors = {f"g{i}": rng.randn(8, 50 + i).astype(np.float32)
               for i in range(20)}
    handles = {n: bps.push_pull_async(jnp.asarray(v), n, op="sum")
               for n, v in tensors.items()}
    for n, h in handles.items():
        out = bps.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), tensors[n].sum(0),
                                   rtol=1e-5)


def test_partitioned_tensor_roundtrip(bps_chunked):
    # 40_000 f32 = 160 KB -> ~40 chunks at 4 KB bound
    x = np.random.RandomState(2).randn(8, 40_000).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "big", op="sum")
    eng = bps.core.api._require()
    ctx = eng.registry.get("big")
    assert len(ctx.chunk_bounds) > 1  # partitioning actually happened
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)


def test_partitioned_2d_average(bps_chunked):
    x = np.random.RandomState(3).randn(8, 200, 30).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "big2", op="average")
    np.testing.assert_allclose(np.asarray(out), x.mean(0), rtol=1e-5)


def test_declaration_order_sets_priority(bps_session):
    eng = bps.core.api._require()
    bps.declare("p/first")
    bps.declare("p/second")
    c1 = eng.registry.get("p/first")
    c2 = eng.registry.get("p/second")
    assert c1.declared_key < c2.declared_key


def test_declare_before_init():
    bps.declare("early/a")
    bps.declare("early/b")
    bps.init()
    try:
        eng = bps.core.api._require()
        assert eng.registry.get("early/a").declared_key == 0
        assert eng.registry.get("early/b").declared_key == 1
    finally:
        bps.shutdown()


def test_suspend_resume_preserves_keys(bps_session):
    x = jnp.ones((8, 4), jnp.float32)
    bps.push_pull(x, "el/a", op="sum")
    bps.push_pull(x, "el/b", op="sum")
    eng = bps.core.api._require()
    key_a = eng.registry.get("el/a").declared_key
    bps.suspend()
    bps.resume()
    eng2 = bps.core.api._require()
    assert eng2.registry.get("el/a").declared_key == key_a
    out = bps.push_pull(x, "el/a", op="sum")
    np.testing.assert_allclose(np.asarray(out), 8.0)
    bps.init()  # idempotent re-init is a no-op


def test_int_average_uses_floor_div(bps_session):
    x = jnp.ones((8, 4), jnp.int32) * 3
    out = bps.push_pull(x, "ints", op="average")
    np.testing.assert_array_equal(np.asarray(out), 3)


def test_pushpull_speed_moves(bps_session):
    x = jnp.ones((8, 1024), jnp.float32)
    for i in range(5):
        bps.push_pull(x, "spd", op="sum")
    ts, mbps = bps.get_pushpull_speed()
    assert mbps > 0


def test_f16_average_scales_before_downcast(bps_session):
    """The fused-scale path must divide inside the f32 accumulation: an
    8-rank sum of 10000.0 (80000 > f16 max 65504) would overflow if the
    downcast happened before the division."""
    x = jnp.full((8, 16), 10000.0, jnp.float16)
    out = bps.push_pull(x, "f16avg", op="average")
    assert out.dtype == jnp.float16
    np.testing.assert_allclose(np.asarray(out).astype(np.float32),
                               10000.0, rtol=1e-3)


def test_scaled_path_matches_unscaled_math(bps_session):
    rng = np.random.RandomState(17)
    x = rng.randn(8, 3000).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "sc1", op="average")
    np.testing.assert_allclose(np.asarray(out), x.mean(0),
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------------- buffer-mode edges
# (the scatter-accumulator hot path: slice -> psum_scatter -> block-sharded
# buffer, donated between chunk dispatches, one-pass assembly)


def test_buffer_mode_unaligned_length(bps_chunked):
    """n not divisible by n_ici: the staged flat is padded, the assemble
    program drops the pad."""
    n = 40_000 + 5  # 40005 % 8 != 0
    x = np.random.RandomState(5).randn(8, n).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "unal", op="sum")
    eng = bps.core.api._require()
    assert len(eng.registry.get("unal").chunk_bounds) > 1
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)


def test_buffer_mode_bf16_average(bps_chunked):
    """Multi-chunk bf16 average: f32 accumulation in the scatter buffer,
    scale before the downcast (8 x 10000 would overflow a bf16-free sum
    only in f16; for bf16 the check is value fidelity)."""
    x = np.random.RandomState(6).randn(8, 24_576).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x, jnp.bfloat16), "bfavg", op="average")
    assert out.dtype == jnp.bfloat16
    want = np.asarray(jnp.asarray(x, jnp.bfloat16).astype(np.float32)).mean(0)
    np.testing.assert_allclose(np.asarray(out).astype(np.float32), want,
                               rtol=3e-2, atol=3e-2)


def test_buffer_mode_int_sum_and_average(bps_chunked):
    x = np.arange(8 * 16_384, dtype=np.int32).reshape(8, 16_384) % 7
    s = bps.push_pull(jnp.asarray(x), "isum", op="sum")
    np.testing.assert_array_equal(np.asarray(s), x.sum(0))
    a = bps.push_pull(jnp.asarray(x), "iavg", op="average")
    np.testing.assert_array_equal(np.asarray(a), x.sum(0) // 8)


def test_buffer_mode_group_size_one_matches(bps_session):
    """group_size=1 (no chunk merging, the multi-host configuration) gives
    the same result as the default grouped dispatch."""
    from byteps_tpu.common.config import set_config
    x = np.random.RandomState(7).randn(8, 30_000).astype(np.float32)
    want = bps.push_pull(jnp.asarray(x), "grp/a", op="sum")
    bps.shutdown()
    set_config(Config(partition_bytes=4096, group_size=1))
    bps.init()
    out = bps.push_pull(jnp.asarray(x), "grp/b", op="sum")
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-6)


def test_local_contribution_matches_stacked(bps_chunked):
    """The single-process local fast path (one staged copy + on-device
    replication, collectives.stage_local_replicated) must agree with the
    rank-stacked path bit-for-bit in both buffer mode (multi-chunk) and
    single-chunk mode — same collective, different staging."""
    from byteps_tpu.core import api

    eng = api._require()
    rng = np.random.RandomState(3)
    for n in (33, 5000):            # single-chunk and multi-chunk (4 KB)
        x = rng.randn(n).astype(np.float32)
        got = np.asarray(eng.push_pull_local(x, f"local.match.{n}"))
        stacked = np.broadcast_to(x[None], (bps.size(), n))
        want = np.asarray(
            eng.push_pull_async(stacked, f"stacked.match.{n}",
                                op="average", denom=bps.size(),
                                out_shape=x.shape).wait())
        np.testing.assert_array_equal(got, want)
        np.testing.assert_allclose(got, x, rtol=1e-6, atol=1e-7)


def test_local_contribution_int_and_sum(bps_chunked):
    from byteps_tpu.core import api

    eng = api._require()
    xi = np.arange(2000, dtype=np.int32)
    got = np.asarray(eng.push_pull_local(xi, "local.int", op="sum"))
    np.testing.assert_array_equal(got, xi)  # sum over 1 process
    got = np.asarray(eng.push_pull_local(xi, "local.int.avg"))
    np.testing.assert_array_equal(got, xi)


def test_local_push_after_compressed_declaration_falls_back(bps_session):
    """A name declared WITH compression must keep materialized per-rank
    rows even when a later push uses the local fast path — the engine
    falls back to the stacked layout for that tensor (round-4 review:
    the caller's gate can't see registry state)."""
    from byteps_tpu.core import api

    eng = api._require()
    x = np.linspace(-1, 1, 4096).astype(np.float32)
    stacked = np.broadcast_to(x[None], (bps.size(), x.size))
    first = np.asarray(eng.push_pull_async(
        stacked, "mixed.comp", op="average", denom=bps.size(),
        out_shape=x.shape,
        compression={"compressor": "topk", "k": "1.0"}).wait())
    got = np.asarray(eng.push_pull_local(x, "mixed.comp"))
    assert got.shape == x.shape and got.dtype == x.dtype
    np.testing.assert_allclose(got, first, rtol=1e-6, atol=1e-7)


def test_concurrent_pushes_from_many_threads(bps_chunked):
    """Torch autograd hooks push gradients from framework threads while
    the dispatcher pops concurrently — the registry/scheduler/handle
    table must survive racing producers (reference: per-tensor mutexes in
    BytePSGlobal, global.cc).  Every tensor must come back equal to its
    own input (no cross-tensor mixing), across chunked and single-chunk
    sizes and repeated versions."""
    import threading

    from byteps_tpu.core import api

    eng = api._require()
    rng = np.random.RandomState(11)
    # sizes straddle the 4096 B partition bound: t0 (500 floats = 2000 B)
    # rides the single-chunk path, the rest are chunked
    tensors = {f"race.t{i}": rng.randn(500 + 1500 * i).astype(np.float32)
               for i in range(6)}
    results = {}
    errors = []

    def worker(name, x):
        try:
            for _ in range(3):          # repeated versions of each tensor
                out = eng.push_pull_local(x, name)
            results[name] = np.asarray(out)
        except Exception as e:  # noqa: BLE001 - surface in main thread
            errors.append((name, repr(e)))

    # daemon: a deadlocked producer must FAIL the test, not hang pytest
    # shutdown on a live non-daemon thread
    threads = [threading.Thread(target=worker, args=(n, x), daemon=True)
               for n, x in tensors.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "worker deadlocked"
    assert not errors, errors
    assert len(results) == len(tensors)
    for name, x in tensors.items():
        np.testing.assert_allclose(results[name], x, rtol=1e-6, atol=1e-7,
                                   err_msg=name)


def test_local_contribution_on_dcn2_mesh():
    """The local fast path on a two-level (dcn=2, ici=4) mesh: the
    hierarchical local reduce (psum_scatter over ICI + psum over DCN)
    and the buffer-mode chunk programs' DCN hop must agree with the
    plain result for both single-chunk and partitioned tensors."""
    import jax

    from byteps_tpu.comm.mesh import CommContext, _build_mesh
    from byteps_tpu.common.config import Config
    from byteps_tpu.core.engine import PushPullEngine

    comm = CommContext(mesh=_build_mesh(jax.devices()[:8], 2),
                       n_dcn=2, n_ici=4)
    eng = PushPullEngine(comm, Config(telemetry_on=False, trace_on=False,
                                      partition_bytes=4096))
    try:
        rng = np.random.RandomState(5)
        for n in (33, 5000):        # single-chunk and multi-chunk
            x = rng.randn(n).astype(np.float32)
            got = np.asarray(eng.push_pull_local(x, f"dcn2.local.{n}"))
            np.testing.assert_allclose(got, x, rtol=1e-6, atol=1e-7)
            got_sum = np.asarray(
                eng.push_pull_local(x, f"dcn2.sum.{n}", op="sum"))
            np.testing.assert_allclose(got_sum, x, rtol=1e-6, atol=1e-7)
    finally:
        eng.shutdown(wait=False)


def test_engine_single_device_mesh():
    """n_ici=1 — the shape every single-chip TPU bench run uses.  The
    collectives degenerate (psum over one device) but the engine
    machinery (partitioner, scatter layout, local staging, assembly)
    must still be exact; a regression here would turn a rare green
    hardware window into an error line.  Subprocess: the device count is
    fixed at backend init, so the 8-device conftest mesh can't host it."""
    import subprocess
    import sys

    code = r"""
import os
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=1'
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import jax.numpy as jnp
from byteps_tpu.comm.mesh import CommContext, _build_mesh
from byteps_tpu.common.config import Config
from byteps_tpu.core.engine import PushPullEngine
comm = CommContext(mesh=_build_mesh(jax.devices(), 1), n_dcn=1, n_ici=1)
eng = PushPullEngine(comm, Config(telemetry_on=False, trace_on=False,
                                  partition_bytes=4096))
x = np.random.RandomState(0).randn(5000).astype(np.float32)
np.testing.assert_allclose(
    np.asarray(eng.push_pull_local(x, 'one.local')), x,
    rtol=1e-6, atol=1e-7)
np.testing.assert_allclose(
    np.asarray(eng.push_pull_local(x[:33], 'one.small')), x[:33],
    rtol=1e-6, atol=1e-7)
np.testing.assert_allclose(
    np.asarray(eng.push_pull_async(jnp.asarray(x[None]), 'one.stacked',
                                   op='sum', denom=1,
                                   out_shape=x.shape).wait()), x, rtol=1e-6)
eng.shutdown(wait=False)
print('SINGLE_DEVICE_OK')
"""
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300)
    assert p.returncode == 0 and "SINGLE_DEVICE_OK" in p.stdout, (
        (p.stderr or p.stdout)[-600:])
