"""End-to-end engine tests: the full partition -> schedule -> collective ->
callback path (reference call stack §3.2, collapsed to TPU stages)."""

import jax.numpy as jnp
import numpy as np
import pytest

import byteps_tpu as bps
from byteps_tpu.common import Config
from byteps_tpu.common.config import set_config


@pytest.fixture
def bps_session():
    bps.init()
    yield bps
    bps.shutdown()


@pytest.fixture
def bps_chunked():
    # Tiny partition bound -> every tensor over 4096 B gets multiple chunks,
    # exercising partitioning + reassembly (reference BYTEPS_PARTITION_BYTES).
    set_config(Config(partition_bytes=4096))
    bps.init()
    yield bps
    bps.shutdown()


def test_basics(bps_session):
    assert bps.size() == 8
    assert bps.rank() == 0
    assert bps.local_size() == 8
    assert bps.local_rank() == 0


def test_push_pull_sum_and_average(bps_session):
    x = jnp.asarray(np.random.RandomState(0).randn(8, 13, 3).astype(np.float32))
    s = bps.push_pull(x, "grad/w", op="sum")
    np.testing.assert_allclose(np.asarray(s), np.asarray(x).sum(0), rtol=1e-5)
    a = bps.push_pull(x, "grad/w", op="average")
    np.testing.assert_allclose(np.asarray(a), np.asarray(x).mean(0), rtol=1e-5)


def test_push_pull_async_many(bps_session):
    rng = np.random.RandomState(1)
    tensors = {f"g{i}": rng.randn(8, 50 + i).astype(np.float32)
               for i in range(20)}
    handles = {n: bps.push_pull_async(jnp.asarray(v), n, op="sum")
               for n, v in tensors.items()}
    for n, h in handles.items():
        out = bps.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), tensors[n].sum(0),
                                   rtol=1e-5)


def test_partitioned_tensor_roundtrip(bps_chunked):
    # 40_000 f32 = 160 KB -> ~40 chunks at 4 KB bound
    x = np.random.RandomState(2).randn(8, 40_000).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "big", op="sum")
    eng = bps.core.api._require()
    ctx = eng.registry.get("big")
    assert len(ctx.chunk_bounds) > 1  # partitioning actually happened
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)


def test_partitioned_2d_average(bps_chunked):
    x = np.random.RandomState(3).randn(8, 200, 30).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "big2", op="average")
    np.testing.assert_allclose(np.asarray(out), x.mean(0), rtol=1e-5)


def test_declaration_order_sets_priority(bps_session):
    eng = bps.core.api._require()
    bps.declare("p/first")
    bps.declare("p/second")
    c1 = eng.registry.get("p/first")
    c2 = eng.registry.get("p/second")
    assert c1.declared_key < c2.declared_key


def test_declare_before_init():
    bps.declare("early/a")
    bps.declare("early/b")
    bps.init()
    try:
        eng = bps.core.api._require()
        assert eng.registry.get("early/a").declared_key == 0
        assert eng.registry.get("early/b").declared_key == 1
    finally:
        bps.shutdown()


def test_suspend_resume_preserves_keys(bps_session):
    x = jnp.ones((8, 4), jnp.float32)
    bps.push_pull(x, "el/a", op="sum")
    bps.push_pull(x, "el/b", op="sum")
    eng = bps.core.api._require()
    key_a = eng.registry.get("el/a").declared_key
    bps.suspend()
    bps.resume()
    eng2 = bps.core.api._require()
    assert eng2.registry.get("el/a").declared_key == key_a
    out = bps.push_pull(x, "el/a", op="sum")
    np.testing.assert_allclose(np.asarray(out), 8.0)
    bps.init()  # idempotent re-init is a no-op


def test_int_average_uses_floor_div(bps_session):
    x = jnp.ones((8, 4), jnp.int32) * 3
    out = bps.push_pull(x, "ints", op="average")
    np.testing.assert_array_equal(np.asarray(out), 3)


def test_pushpull_speed_moves(bps_session):
    x = jnp.ones((8, 1024), jnp.float32)
    for i in range(5):
        bps.push_pull(x, "spd", op="sum")
    ts, mbps = bps.get_pushpull_speed()
    assert mbps > 0


def test_f16_average_scales_before_downcast(bps_session):
    """The fused-scale path must divide inside the f32 accumulation: an
    8-rank sum of 10000.0 (80000 > f16 max 65504) would overflow if the
    downcast happened before the division."""
    x = jnp.full((8, 16), 10000.0, jnp.float16)
    out = bps.push_pull(x, "f16avg", op="average")
    assert out.dtype == jnp.float16
    np.testing.assert_allclose(np.asarray(out).astype(np.float32),
                               10000.0, rtol=1e-3)


def test_scaled_path_matches_unscaled_math(bps_session):
    rng = np.random.RandomState(17)
    x = rng.randn(8, 3000).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "sc1", op="average")
    np.testing.assert_allclose(np.asarray(out), x.mean(0),
                               rtol=1e-5, atol=1e-6)
