"""Vision model family tests (reference benchmark models: ResNet-50 /
VGG-16, docs/performance.md:3-23) on the 8-device CPU mesh.

The reference proves compressor/optimizer correctness by training
resnet18 on fake data (reference tests/test_onebit.py); same shape here:
the tiny ResNet must train end-to-end through the fused DP step with
cross-replica BatchNorm threading its running stats.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.comm.mesh import CommContext, _build_mesh
from byteps_tpu.models.resnet import (resnet_tiny, resnet50, vgg16,
                                      softmax_cross_entropy,
                                      synthetic_images)
from byteps_tpu.parallel import (make_dp_train_step_with_state, replicate,
                                 shard_batch)


@pytest.fixture
def comm():
    return CommContext(mesh=_build_mesh(jax.devices()[:8], 1),
                       n_dcn=1, n_ici=8)


def test_resnet50_init_shapes():
    model = resnet50(num_classes=1000, compute_dtype=jnp.float32)
    x = jnp.zeros((1, 64, 64, 3))  # smaller than 224 to keep CI fast
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(variables["params"]))
    # ResNet-50 is ~25.6M params; conv params are resolution-independent
    assert 25_000_000 < n_params < 26_000_000, n_params
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 1000)
    assert logits.dtype == jnp.float32


def test_vgg16_param_count():
    model = vgg16(num_classes=1000, compute_dtype=jnp.float32)
    x = jnp.zeros((1, 224, 224, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(variables["params"]))
    # the canonical 138M (the reference's bandwidth-bound best case)
    assert 138_000_000 < n_params < 139_000_000, n_params


def test_tiny_resnet_trains_with_sync_bn(comm):
    model = resnet_tiny(num_classes=10, axis_name=comm.dp_axes)
    rng = jax.random.PRNGKey(1)
    batch = synthetic_images(rng, batch=16, size=16, num_classes=10)
    variables = model.init(rng, batch["images"][:2], train=True)
    params, bn_state = variables["params"], variables["batch_stats"]

    def loss_fn(p, state, b):
        logits, mutated = model.apply(
            {"params": p, "batch_stats": state}, b["images"], train=True,
            mutable=["batch_stats"])
        return (softmax_cross_entropy(logits, b["labels"]),
                mutated["batch_stats"])

    tx = optax.sgd(0.05, momentum=0.9)
    step = make_dp_train_step_with_state(comm, loss_fn, tx)
    params = replicate(comm, params)
    bn_state = replicate(comm, bn_state)
    opt_state = replicate(comm, tx.init(params))
    batch = shard_batch(comm, batch)

    losses = []
    for _ in range(8):
        params, bn_state, opt_state, loss = step(params, bn_state,
                                                 opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # BN running stats moved away from init (mean 0 / var 1)
    mean_leaf = jax.tree.leaves(bn_state)[0]
    assert float(jnp.abs(np.asarray(mean_leaf)).sum()) > 0


def test_sync_bn_stats_are_global_batch(comm):
    """Cross-replica BN must normalize with *global* batch statistics:
    give each shard a different constant input; with axis_name the
    per-replica batch means agree (= global mean), without it they
    differ."""
    model = resnet_tiny(num_classes=4, axis_name=comm.dp_axes)
    # one example per device, value = device index
    x = np.zeros((8, 8, 8, 3), np.float32)
    for i in range(8):
        x[i] = float(i)
    y = np.zeros(8, np.int64)
    rng = jax.random.PRNGKey(2)
    variables = model.init(rng, jnp.asarray(x[:1]), train=True)

    from jax.sharding import PartitionSpec as P

    def fwd(v, images):
        _, mutated = model.apply(v, images, train=True,
                                 mutable=["batch_stats"])
        return mutated["batch_stats"]

    mapped = jax.jit(jax.shard_map(
        fwd, mesh=comm.mesh, in_specs=(P(), P(comm.dp_axes)),
        out_specs=P(), check_vma=False))
    stats = mapped(replicate(comm, variables),
                   shard_batch(comm, jnp.asarray(x)))
    # out_specs=P() asserts replica-identity: if per-shard stats
    # diverged, shard_map would produce inconsistent replicated output.
    # The first BN's running mean moved toward the global input mean
    # (3.5 scaled by momentum), identically on every device.
    leaf = np.asarray(jax.tree.leaves(stats)[0])
    assert np.isfinite(leaf).all()
    _ = y  # labels unused in forward-only check
