"""Vision model family tests (reference benchmark models: ResNet-50 /
VGG-16, docs/performance.md:3-23) on the 8-device CPU mesh.

The reference proves compressor/optimizer correctness by training
resnet18 on fake data (reference tests/test_onebit.py); same shape here:
the tiny ResNet must train end-to-end through the fused DP step with
cross-replica BatchNorm threading its running stats.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.comm.mesh import CommContext, _build_mesh
from byteps_tpu.models.resnet import (resnet_tiny, resnet50, vgg16,
                                      softmax_cross_entropy,
                                      synthetic_images)
from byteps_tpu.parallel import (make_dp_train_step_with_state, replicate,
                                 shard_batch)


@pytest.fixture
def comm():
    return CommContext(mesh=_build_mesh(jax.devices()[:8], 1),
                       n_dcn=1, n_ici=8)


def test_resnet50_init_shapes():
    model = resnet50(num_classes=1000, compute_dtype=jnp.float32)
    x = jnp.zeros((1, 64, 64, 3))  # smaller than 224 to keep CI fast
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(variables["params"]))
    # ResNet-50 is ~25.6M params; conv params are resolution-independent
    assert 25_000_000 < n_params < 26_000_000, n_params
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 1000)
    assert logits.dtype == jnp.float32


def test_vgg16_param_count():
    model = vgg16(num_classes=1000, compute_dtype=jnp.float32)
    x = jnp.zeros((1, 224, 224, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(variables["params"]))
    # the canonical 138M (the reference's bandwidth-bound best case)
    assert 138_000_000 < n_params < 139_000_000, n_params


def test_tiny_resnet_trains_with_sync_bn(comm):
    model = resnet_tiny(num_classes=10, axis_name=comm.dp_axes)
    rng = jax.random.PRNGKey(1)
    batch = synthetic_images(rng, batch=16, size=16, num_classes=10)
    variables = model.init(rng, batch["images"][:2], train=True)
    params, bn_state = variables["params"], variables["batch_stats"]

    def loss_fn(p, state, b):
        logits, mutated = model.apply(
            {"params": p, "batch_stats": state}, b["images"], train=True,
            mutable=["batch_stats"])
        return (softmax_cross_entropy(logits, b["labels"]),
                mutated["batch_stats"])

    tx = optax.sgd(0.05, momentum=0.9)
    step = make_dp_train_step_with_state(comm, loss_fn, tx)
    params = replicate(comm, params)
    bn_state = replicate(comm, bn_state)
    opt_state = replicate(comm, tx.init(params))
    batch = shard_batch(comm, batch)

    losses = []
    for _ in range(8):
        params, bn_state, opt_state, loss = step(params, bn_state,
                                                 opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # BN running stats moved away from init (mean 0 / var 1)
    mean_leaf = jax.tree.leaves(bn_state)[0]
    assert float(jnp.abs(np.asarray(mean_leaf)).sum()) > 0


@pytest.mark.parametrize("sync", [True, False])
def test_sync_bn_stats_are_global_batch(comm, sync):
    """Cross-replica BN must compute *global* batch statistics: give each
    shard a different constant input and collect every device's updated
    running stats.  With axis_name all 8 replicas' stats are identical
    (computed over the global batch); with axis_name=None they diverge
    (each shard normalized by its own constant) — pinning that the sync
    actually does something."""
    model = resnet_tiny(num_classes=4,
                        axis_name=comm.dp_axes if sync else None)
    # one example per device, value = device index
    x = np.zeros((8, 8, 8, 3), np.float32)
    for i in range(8):
        x[i] = float(i)
    rng = jax.random.PRNGKey(2)
    variables = model.init(rng, jnp.asarray(x[:1]), train=True)

    from jax.sharding import PartitionSpec as P

    def fwd(v, images):
        _, mutated = model.apply(v, images, train=True,
                                 mutable=["batch_stats"])
        # stack per-device stats on a leading axis so divergence is
        # observable (out_specs=P() would silently pick one shard under
        # check_vma=False)
        return jax.tree.map(lambda a: a[None], mutated["batch_stats"])

    mapped = jax.jit(jax.shard_map(
        fwd, mesh=comm.mesh, in_specs=(P(), P(comm.dp_axes)),
        out_specs=P(comm.dp_axes), check_vma=False))
    stats = mapped(replicate(comm, variables),
                   shard_batch(comm, jnp.asarray(x)))
    # the first BN's running mean, per device: [8, channels]
    leaves = [np.asarray(l) for l in jax.tree.leaves(stats)]
    assert all(l.shape[0] == 8 and np.isfinite(l).all() for l in leaves)
    spread = max(float(np.abs(l - l[0]).max()) for l in leaves)
    if sync:
        assert spread < 1e-6, f"synced BN stats diverged: {spread}"
    else:
        assert spread > 1e-3, "unsynced BN unexpectedly agreed — the " \
            "sync test has lost its sensitivity"
