"""Supervised recovery (fault/recovery.py): the detector-to-resumed-engine
path, elastic key-order preservation, escalation, and the real 2→1
kill-and-recover chaos run (chaos_worker.py)."""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

import byteps_tpu.core.api as api
from byteps_tpu.common.config import Config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import injector as inj_mod
from byteps_tpu.fault import recovery as rec_mod
from byteps_tpu.fault.recovery import RecoveryCoordinator

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_api():
    inj_mod.disarm()
    yield
    if api.initialized():
        api.shutdown()
    # suspend() snapshots declared-tensor order into module state so
    # resume can re-declare; between tests it is pollution
    api._declared_order = []
    inj_mod.disarm()


def _template():
    return {"w": np.zeros(8, np.float32), "step": np.array(0)}


@pytest.mark.chaos
def test_recovery_coordinator_full_flow(tmp_path):
    """Detection action → drain/suspend → resume → restore, in-process:
    the engine is replaced, tensor keys survive in declaration order, and
    the restored state is the last checkpoint."""
    from byteps_tpu.utils.checkpoint import CheckpointManager

    counters.reset()
    api.init(Config())
    eng = api._require()
    for name in ("a", "b", "c"):
        eng.push_pull(np.ones((eng.comm.num_ranks, 16), np.float32), name)
    keys_before = [(n, eng.registry.get(n).declared_key)
                   for n in eng.registry.names_in_declaration_order()]
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    w = np.arange(8, dtype=np.float32)
    mgr.save(5, {"w": w, "step": np.array(5)})

    rc = RecoveryCoordinator(checkpoint_manager=mgr, template=_template())
    res = rc.recover({1})

    assert res.failed_ranks == {1} and res.num_workers >= 1
    assert res.step == 5
    np.testing.assert_allclose(res.state["w"], w)
    assert rc.done() and rc.wait(0) is res
    eng2 = api._require()
    assert eng2 is not eng
    keys_after = [(n, eng2.registry.get(n).declared_key)
                  for n in eng2.registry.names_in_declaration_order()]
    assert keys_after == keys_before
    # the resumed engine is live
    out = eng2.push_pull(np.ones((eng2.comm.num_ranks, 16), np.float32),
                         "a")
    np.testing.assert_allclose(np.asarray(out), 1.0)
    assert counters.get("recovery.attempt") == 1
    assert counters.get("recovery.completed") == 1


@pytest.mark.chaos
def test_recovery_is_idempotent_across_concurrent_detections(tmp_path):
    """Two detections (e.g. heartbeat + watchdog) run ONE recovery; the
    second caller gets the first result."""
    api.init(Config())
    rc = RecoveryCoordinator(template=_template())
    r1 = rc.recover({1})
    r2 = rc.recover({2})     # late duplicate detection
    assert r2 is r1
    assert counters.get("recovery.attempt") >= 1


def test_suspend_resume_shrink_preserves_key_order():
    """Satellite: suspend() → resume(num_workers=k-1) re-declares tensors
    in original declaration order with identical keys (previously pinned
    only by a docstring)."""
    api.init(Config())
    eng = api._require()
    names = ["t.out", "t.mid", "t.in", "t.embed"]
    for n in names:
        eng.push_pull(np.ones((eng.comm.num_ranks, 8), np.float32), n)
    before = [(n, eng.registry.get(n).declared_key)
              for n in eng.registry.names_in_declaration_order()]
    assert [n for n, _ in before] == names  # declaration order, not sorted

    api.suspend()
    assert not api.initialized()
    api.resume(num_workers=1)

    eng2 = api._require()
    after = [(n, eng2.registry.get(n).declared_key)
             for n in eng2.registry.names_in_declaration_order()]
    assert after == before
    # a fresh tensor keys AFTER the re-declared block, like the reference
    eng2.push_pull(np.ones((eng2.comm.num_ranks, 8), np.float32), "t.new")
    assert eng2.registry.get("t.new").declared_key == len(names)


@pytest.mark.chaos
def test_failed_recovery_escalates_to_restartable_exit(monkeypatch):
    """When in-process recovery itself dies, on_failure falls back to the
    configurable restartable exit so the launcher supervision takes
    over."""
    monkeypatch.setenv("BYTEPS_FAILURE_EXIT_CODE", "23")
    exits = []
    monkeypatch.setattr(rec_mod, "_exit", exits.append)

    class BrokenManager:
        def restore_latest(self, template):
            raise IOError("checkpoint store unreachable")

    rc = RecoveryCoordinator(checkpoint_manager=BrokenManager(),
                             template=_template())
    rc.on_failure({1})
    assert exits == [23]
    assert counters.get("recovery.failed") >= 1


@pytest.mark.chaos
def test_failed_recovery_releases_waiters_and_escalates(monkeypatch):
    """A recovery that dies must not wedge later detections: the first
    caller sees the original error, later callers raise promptly (and
    their on_failure escalation path still runs)."""
    class BrokenManager:
        def restore_latest(self, template):
            raise IOError("checkpoint store unreachable")

    rc = RecoveryCoordinator(checkpoint_manager=BrokenManager(),
                             template=_template())
    with pytest.raises(IOError):
        rc.recover({1})
    with pytest.raises(RuntimeError, match="failed"):
        rc.recover({2})         # must raise, not block forever
    assert rc.done() and rc.wait(0) is None


@pytest.mark.chaos
def test_on_recovered_callback_error_does_not_kill_survivor(monkeypatch):
    """A broken user callback after a SUCCESSFUL recovery logs; it must
    not reach on_failure's escalation exit."""
    exits = []
    monkeypatch.setattr(rec_mod, "_exit", exits.append)

    def bad_callback(result):
        raise ValueError("user callback bug")

    rc = RecoveryCoordinator(template=_template(),
                             on_recovered=bad_callback)
    rc.on_failure({1})
    assert exits == []          # healthy survivor stays up
    assert rc.wait(0) is not None


@pytest.mark.chaos
def test_kill_and_recover_two_process(tmp_path):
    """The acceptance pin: two real processes; BYTEPS_FAULT_SPEC kills
    rank 1 at push step 3; rank 0's detector fires within its sub-second
    staleness timeout and the RecoveryCoordinator completes suspend →
    resume(1 worker) → checkpoint restore with the training step value
    preserved — no hang, no restartable exit."""
    port = str(_free_port())
    ckdir = str(tmp_path / "ckpts")
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["DMLC_NUM_WORKER"] = "1"       # single-host engines; the 2-ness
        env["DMLC_WORKER_ID"] = str(rank)  # lives in the heartbeat layer
        env["BYTEPS_CHAOS_RANK"] = str(rank)
        env["BYTEPS_CHAOS_HB_PORT"] = port
        env["BYTEPS_CHAOS_CKPT"] = ckdir
        env["BYTEPS_LOG_LEVEL"] = "ERROR"
        if rank == 1:
            env["BYTEPS_FAULT_SPEC"] = "kill:rank=1:step=3"
            env["BYTEPS_FAULT_SEED"] = "7"
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "chaos_worker.py")],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = ["", ""]
    try:
        # victim first (it dies early); survivor needs detection+recovery
        outs[1], _ = procs[1].communicate(timeout=120)
        outs[0], _ = procs[0].communicate(timeout=120)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("chaos workers hung (recovery did not complete); "
                    "partial output: " + "".join(o[-1500:] for o in outs))
    # the victim really was killed by the injector (exit code 1, no
    # restartable 17: a kill is a crash)
    assert procs[1].returncode == 1, outs[1][-3000:]
    assert "START 1" in outs[1]
    assert "RECOVERED" not in outs[1]
    # the survivor detected, recovered, verified the restored step, and
    # kept training on the resumed engine
    assert procs[0].returncode == 0, outs[0][-3000:]
    assert "RECOVERED" in outs[0], outs[0][-3000:]
