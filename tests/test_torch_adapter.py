"""Torch adapter tests — the reference's op-correctness shape
(tests/test_mxnet.py sums tensors against numpy; torch plugin semantics
from torch/__init__.py).  Single process == the reference's single-worker
forced-distributed mode: push_pull over one process is identity for
average, identity for sum."""

import numpy as np
import pytest
import torch

import byteps_tpu.torch as bps_torch


@pytest.fixture
def session():
    bps_torch.init()
    yield
    bps_torch.shutdown()


def test_push_pull_identity_single_process(session):
    t = torch.randn(17, 3)
    out = bps_torch.push_pull(t, average=True, name="t1")
    np.testing.assert_allclose(out.numpy(), t.numpy(), rtol=1e-5, atol=1e-6)
    out2 = bps_torch.push_pull(t, average=False, name="t1")
    np.testing.assert_allclose(out2.numpy(), t.numpy(), rtol=1e-5, atol=1e-6)


def test_push_pull_differentiable(session):
    """push_pull is an autograd Function (reference torch/ops.py:109-125):
    backward push_pulls the incoming gradient.  Single process: y = x, so
    d(sum(y * w))/dx == w."""
    x = torch.randn(6, 4, requires_grad=True)
    w = torch.randn(6, 4)
    y = bps_torch.push_pull(x, average=True, name="diff1")
    assert y.requires_grad
    (y * w).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), w.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_push_pull_through_model(session):
    """Gradients propagate through a push_pull in the middle of a graph."""
    torch.manual_seed(7)
    lin = torch.nn.Linear(5, 3)
    x = torch.randn(8, 5)
    out = bps_torch.push_pull(lin(x), average=True, name="diff2")
    out.sum().backward()
    assert lin.weight.grad is not None
    expected = x.sum(dim=0)  # d(sum(Wx+b))/dW rows are sum_b x
    for row in lin.weight.grad:
        np.testing.assert_allclose(row.numpy(), expected.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_push_pull_async_poll_synchronize(session):
    t = torch.ones(64)
    h = bps_torch.push_pull_async(t, average=False, name="t2")
    assert bps_torch.poll(h) in (False, True)  # may complete at any time
    out = bps_torch.synchronize(h, like=t)
    assert bps_torch.poll(h)  # after wait it must report done
    np.testing.assert_allclose(out.numpy(), np.ones(64), rtol=1e-6)


def test_broadcast_parameters_inplace(session):
    model = torch.nn.Linear(4, 2)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    bps_torch.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        np.testing.assert_allclose(v.numpy(), before[k].numpy(), rtol=1e-6)


def test_distributed_optimizer_trains(session):
    torch.manual_seed(0)
    model = torch.nn.Sequential(torch.nn.Linear(8, 16), torch.nn.ReLU(),
                                torch.nn.Linear(16, 1))
    opt = bps_torch.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    x = torch.randn(32, 8)
    y = x.sum(dim=1, keepdim=True)
    losses = []
    for _ in range(30):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.2, losses[::10]


def test_distributed_optimizer_matches_plain_sgd(session):
    """Single process: DistributedOptimizer == plain SGD exactly."""
    torch.manual_seed(1)
    m1 = torch.nn.Linear(5, 3)
    m2 = torch.nn.Linear(5, 3)
    m2.load_state_dict(m1.state_dict())
    o1 = torch.optim.SGD(m1.parameters(), lr=0.05)
    o2 = bps_torch.DistributedOptimizer(
        torch.optim.SGD(m2.parameters(), lr=0.05),
        named_parameters=m2.named_parameters())
    x = torch.randn(16, 5)
    y = torch.randn(16, 3)
    for _ in range(5):
        for o, m in ((o1, m1), (o2, m2)):
            o.zero_grad()
            torch.nn.functional.mse_loss(m(x), y).backward()
            o.step()
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.detach().numpy(), p2.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_gradient_accumulation_bpps(session):
    torch.manual_seed(2)
    m = torch.nn.Linear(4, 1)
    ref = torch.nn.Linear(4, 1)
    ref.load_state_dict(m.state_dict())
    opt = bps_torch.DistributedOptimizer(
        torch.optim.SGD(m.parameters(), lr=0.1),
        named_parameters=m.named_parameters(),
        backward_passes_per_step=2)
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    x = torch.randn(8, 4)
    y = torch.randn(8, 1)
    # two micro-batches through the distributed optimizer
    for i in range(2):
        xb, yb = x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4]
        loss = torch.nn.functional.mse_loss(m(xb), yb)
        loss.backward()
        opt.step()
    opt.zero_grad()
    # reference: average of the two micro-grads in one step
    ref_opt.zero_grad()
    l1 = torch.nn.functional.mse_loss(ref(x[:4]), y[:4])
    l2 = torch.nn.functional.mse_loss(ref(x[4:]), y[4:])
    ((l1 + l2) / 2).backward()
    ref_opt.step()
    for p1, p2 in zip(m.parameters(), ref.parameters()):
        np.testing.assert_allclose(p1.detach().numpy(), p2.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_gradient_accumulation_horovod_pattern(session):
    """Reference/Horovod style: N backwards, then ONE step()."""
    torch.manual_seed(3)
    m = torch.nn.Linear(4, 1)
    ref = torch.nn.Linear(4, 1)
    ref.load_state_dict(m.state_dict())
    opt = bps_torch.DistributedOptimizer(
        torch.optim.SGD(m.parameters(), lr=0.1),
        named_parameters=m.named_parameters(),
        backward_passes_per_step=2)
    ref_opt = torch.optim.SGD(ref.parameters(), lr=0.1)
    x = torch.randn(8, 4)
    y = torch.randn(8, 1)
    torch.nn.functional.mse_loss(m(x[:4]), y[:4]).backward()
    torch.nn.functional.mse_loss(m(x[4:]), y[4:]).backward()
    opt.step()  # must sync and update (not silently no-op)
    ref_opt.zero_grad()
    l1 = torch.nn.functional.mse_loss(ref(x[:4]), y[:4])
    l2 = torch.nn.functional.mse_loss(ref(x[4:]), y[4:])
    ((l1 + l2) / 2).backward()
    ref_opt.step()
    for p1, p2 in zip(m.parameters(), ref.parameters()):
        np.testing.assert_allclose(p1.detach().numpy(), p2.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_broadcast_optimizer_state(session):
    m = torch.nn.Linear(3, 2)
    opt = torch.optim.Adam(m.parameters(), lr=1e-3)
    m(torch.randn(4, 3)).sum().backward()
    opt.step()
    bps_torch.broadcast_optimizer_state(opt, root_rank=0)  # no crash, values kept
    assert len(opt.state_dict()["state"]) > 0


def test_fp16_compression_shim():
    from byteps_tpu.torch.compression import Compression
    t = torch.randn(10)
    c, ctx = Compression.fp16.compress(t)
    assert c.dtype == torch.float16
    d = Compression.fp16.decompress(c, ctx)
    assert d.dtype == t.dtype
    np.testing.assert_allclose(d.numpy(), t.numpy(), atol=1e-2)
