"""ZeRO-1 / FSDP sharded-optimizer data parallelism (parallel/zero.py).

The contract: ZeRO's reduce_scatter + shard-update + all_gather must
produce the SAME training trajectory as the fused replicated-DP step
(make_dp_train_step) — the sharding is a memory layout, not an algorithm
change.  Pinned step-for-step against the fused path on the (dcn=2,
ici=4) CPU mesh, plus persistent-memory and sharding-layout assertions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.comm.mesh import CommContext, _build_mesh
from byteps_tpu.models.mlp import MLP, softmax_cross_entropy
from byteps_tpu.parallel import (make_dp_train_step, replicate, shard_batch)
from byteps_tpu.parallel.zero import (ZeroState, init_zero_state,
                                      make_fsdp_train_step,
                                      make_zero_train_step, zero_params)


N_DEV = 8


@pytest.fixture(scope="module")
def comm():
    devs = jax.devices()[:N_DEV]
    return CommContext(mesh=_build_mesh(devs, 2), n_dcn=2, n_ici=4)


def _setup(comm, seed=0):
    model = MLP(features=(32, 16, 10))
    rng = jax.random.PRNGKey(seed)
    x = jax.random.normal(rng, (N_DEV * 4, 12))
    y = jax.random.randint(jax.random.PRNGKey(seed + 1), (N_DEV * 4,), 0, 10)
    params = model.init(rng, x)

    def loss_fn(params, batch):
        return softmax_cross_entropy(model.apply(params, batch["x"]),
                                     batch["y"])

    batch = shard_batch(comm, {"x": x, "y": y})
    return model, params, loss_fn, batch


def _run_dp_reference(comm, params, loss_fn, batch, tx, steps):
    step = make_dp_train_step(comm, loss_fn, tx, donate=False)
    p = replicate(comm, params)
    o = replicate(comm, tx.init(params))
    losses = []
    for _ in range(steps):
        p, o, loss = step(p, o, batch)
        losses.append(float(loss))
    return p, losses


def test_zero1_matches_fused_dp(comm):
    model, params, loss_fn, batch = _setup(comm)
    tx = optax.adam(1e-2)

    ref_params, ref_losses = _run_dp_reference(comm, params, loss_fn,
                                               batch, tx, steps=5)

    zstep = make_zero_train_step(comm, loss_fn, tx, donate=False)
    zstate = init_zero_state(comm, tx, params)
    p = replicate(comm, params)
    losses = []
    for _ in range(5):
        p, zstate, loss = zstep(p, zstate, batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_fsdp_matches_fused_dp(comm):
    model, params, loss_fn, batch = _setup(comm)
    tx = optax.adam(1e-2)

    ref_params, ref_losses = _run_dp_reference(comm, params, loss_fn,
                                               batch, tx, steps=5)

    fstep = make_fsdp_train_step(comm, loss_fn, tx, params_template=params,
                                 donate=False)
    zstate = init_zero_state(comm, tx, params)
    losses = []
    for _ in range(5):
        zstate, loss = fstep(zstate, batch)
        losses.append(float(loss))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)
    out = zero_params(comm, zstate, params)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_shard_layout_and_memory(comm):
    """Master vector and adam moments live 1/R per device; counters are
    replicated."""
    _, params, loss_fn, batch = _setup(comm)
    tx = optax.adam(1e-2)
    zstate = init_zero_state(comm, tx, params)

    padded = zstate.master.shape[0]
    assert padded % (N_DEV * 128) == 0
    shards = zstate.master.addressable_shards
    assert len(shards) == N_DEV
    assert all(s.data.shape == (padded // N_DEV,) for s in shards)

    sharded_leaves = [x for x in jax.tree.leaves(zstate.opt_state)
                      if getattr(x, "ndim", 0) == 1
                      and x.shape[0] == padded]
    assert len(sharded_leaves) == 2  # adam mu + nu
    for leaf in sharded_leaves:
        assert leaf.addressable_shards[0].data.shape == (padded // N_DEV,)


def test_fsdp_mixed_precision(comm):
    """bf16 compute against the f32 sharded master: loss finite, master
    stays f32, gathered params come back in the template dtype."""
    model, params, loss_fn, batch = _setup(comm)
    tx = optax.sgd(1e-2)
    fstep = make_fsdp_train_step(comm, loss_fn, tx, params_template=params,
                                 compute_dtype=jnp.bfloat16, donate=False)
    zstate = init_zero_state(comm, tx, params)
    prev = None
    for _ in range(3):
        zstate, loss = fstep(zstate, batch)
        assert np.isfinite(float(loss))
        if prev is not None:  # master actually moves
            assert not np.array_equal(prev, np.asarray(zstate.master))
        prev = np.asarray(zstate.master)
    assert zstate.master.dtype == jnp.float32
    out = zero_params(comm, zstate, params)
    assert all(a.dtype == b.dtype for a, b in
               zip(jax.tree.leaves(out), jax.tree.leaves(params)))


def test_zero1_bf16_params(comm):
    """ZeRO-1 with bf16 replicated params = sharded master-weight training
    (the reference's _HalfPrecisionDistributedOptimizer, with the f32
    master sharded instead of replicated)."""
    model, params, loss_fn, batch = _setup(comm)
    bf16_params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
    tx = optax.sgd(1e-2)
    zstep = make_zero_train_step(comm, loss_fn, tx, donate=False)
    zstate = init_zero_state(comm, tx, bf16_params)
    p = replicate(comm, bf16_params)
    for _ in range(3):
        p, zstate, loss = zstep(p, zstate, batch)
        assert np.isfinite(float(loss))
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(p))
    assert zstate.master.dtype == jnp.float32


def test_sharded_clip_by_global_norm(comm):
    """zero.clip_by_global_norm psums the norm over the shards, matching
    the replicated-DP trajectory with optax.clip_by_global_norm; the
    plain optax transform inside ZeRO would clip each shard by its own
    norm (documented restriction)."""
    from byteps_tpu.parallel.zero import clip_by_global_norm

    model, params, loss_fn, batch = _setup(comm)
    max_norm = 0.05  # far below the initial grad norm so the clip bites

    ref_tx = optax.chain(optax.clip_by_global_norm(max_norm),
                         optax.adam(1e-2))
    _, ref_losses = _run_dp_reference(comm, params, loss_fn, batch,
                                      ref_tx, steps=4)

    ztx = optax.chain(clip_by_global_norm(max_norm, comm),
                      optax.adam(1e-2))
    zstep = make_zero_train_step(comm, loss_fn, ztx, donate=False)
    zstate = init_zero_state(comm, ztx, params)
    p = replicate(comm, params)
    losses = []
    for _ in range(4):
        p, zstate, loss = zstep(p, zstate, batch)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)


def test_hsdp_ici_sharding_matches_fused_dp(comm):
    """shard_axes='ici' (HSDP): master sharded within a slice, replicated
    across dcn — trajectory still matches replicated DP; layout shows
    n_ici-way shards replicated across the dcn axis."""
    model, params, loss_fn, batch = _setup(comm)
    tx = optax.adam(1e-2)
    _, ref_losses = _run_dp_reference(comm, params, loss_fn, batch, tx,
                                      steps=4)

    zstep = make_zero_train_step(comm, loss_fn, tx, donate=False,
                                 shard_axes="ici")
    zstate = init_zero_state(comm, tx, params, shard_axes="ici")
    padded = zstate.master.shape[0]
    assert padded % (4 * 128) == 0              # n_ici = 4
    # 8 addressable shards, but only 4 DISTINCT ones (dcn replicas)
    assert len(zstate.master.addressable_shards) == 8
    assert zstate.master.addressable_shards[0].data.shape == (padded // 4,)
    p = replicate(comm, params)
    losses = []
    for _ in range(4):
        p, zstate, loss = zstep(p, zstate, batch)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)

    fstep = make_fsdp_train_step(comm, loss_fn, tx, params_template=params,
                                 donate=False, shard_axes="ici")
    fstate = init_zero_state(comm, tx, params, shard_axes="ici")
    flosses = []
    for _ in range(4):
        fstate, loss = fstep(fstate, batch)
        flosses.append(float(loss))
    np.testing.assert_allclose(flosses, ref_losses, rtol=1e-5)
    out = zero_params(comm, fstate, params, shard_axes="ici")
    assert np.isfinite(np.asarray(jax.tree.leaves(out)[0])).all()


def test_hsdp_clip_by_global_norm_sgd(comm):
    """HSDP + sharded clip with SGD (adam is scale-invariant and would
    mask a wrong norm): shard_axes='ici' clip must psum over ici only —
    counting the dcn replicas would inflate the norm by sqrt(n_dcn) and
    silently over-clip."""
    from byteps_tpu.parallel.zero import clip_by_global_norm

    model, params, loss_fn, batch = _setup(comm)
    max_norm = 0.05

    ref_tx = optax.chain(optax.clip_by_global_norm(max_norm),
                         optax.sgd(5e-2))
    _, ref_losses = _run_dp_reference(comm, params, loss_fn, batch,
                                      ref_tx, steps=6)

    ztx = optax.chain(clip_by_global_norm(max_norm, comm,
                                          shard_axes="ici"),
                      optax.sgd(5e-2))
    zstep = make_zero_train_step(comm, loss_fn, ztx, donate=False,
                                 shard_axes="ici")
    zstate = init_zero_state(comm, ztx, params, shard_axes="ici")
    p = replicate(comm, params)
    losses = []
    for _ in range(6):
        p, zstate, loss = zstep(p, zstate, batch)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)


# -- shard-geometry edge cases (ISSUE 20 satellite) ---------------------------
# `_padded_size` / `_spec_of_opt` are the unification surface shared with
# core/sharded_update.py (comm/shard_math.py): pin the boundary behavior
# the replay proofs never exercise.


def test_padded_size_edge_cases():
    from byteps_tpu.parallel.zero import _padded_size
    # the pad quantum is ranks*128 (lane alignment), so a numel not
    # divisible by ranks still lands on a full tile grid
    assert _padded_size(0, 8) == 0
    assert _padded_size(1, 8) == 1024
    assert _padded_size(33, 8) == 1024
    assert _padded_size(1024, 8) == 1024
    assert _padded_size(1025, 8) == 2048
    assert _padded_size(7, 1) == 128
    # every result divides evenly among the ranks
    for n in (1, 33, 1000, 4097):
        for r in (1, 2, 4, 8):
            p = _padded_size(n, r)
            assert p >= n and p % r == 0 and p % 128 == 0


def test_spec_of_opt_edge_cases(comm):
    from jax.sharding import PartitionSpec as P
    from byteps_tpu.parallel.zero import _spec_of_opt
    padded = 1024
    axes = ("dcn", "ici")
    tree = {
        "sharded": jnp.zeros(padded, jnp.float32),
        "sharded_i8": jnp.zeros(padded, jnp.int8),     # mixed dtype: the
        # spec rule is SHAPE-based, dtype does not exempt a leaf
        "short": jnp.zeros(padded - 1, jnp.float32),   # wrong length
        "matrix": jnp.zeros((padded, 1), jnp.float32),  # wrong rank
        "scalar": jnp.zeros((), jnp.float32),          # 0-d (step count)
        "count": jnp.array(0, jnp.int32),
        "empty": jnp.zeros(0, jnp.float32),            # empty leaf
        "none": None,                                  # optax EmptyState
    }
    spec = _spec_of_opt(tree, padded, axes)
    assert spec["sharded"] == P(axes)
    assert spec["sharded_i8"] == P(axes)
    for k in ("short", "matrix", "scalar", "count", "empty"):
        assert spec[k] == P(), k
    assert "none" not in jax.tree.leaves(spec) or spec["none"] == P()
    # empty optimizer state (optax.sgd has no state vectors) maps cleanly
    assert _spec_of_opt({}, padded, axes) == {}


def test_init_sharded_opt_state_pads_and_places(comm):
    from byteps_tpu.comm.shard_math import (init_sharded_opt_state,
                                            padded_size)
    tx = optax.adam(1e-2)
    n = 1000                                 # NOT divisible by 8
    nsh = comm.num_ranks
    padded = padded_size(n, nsh)
    master = jax.device_put(
        jnp.zeros(padded, jnp.float32),
        jax.sharding.NamedSharding(comm.mesh,
                                   jax.sharding.PartitionSpec(
                                       ("dcn", "ici"))))
    state = init_sharded_opt_state(comm, tx, master, padded,
                                   ("dcn", "ici"))
    for leaf in jax.tree.leaves(state):
        if leaf.ndim == 1 and leaf.shape[0] == padded:
            # padded-length vectors are committed to the shard layout
            assert len(leaf.sharding.device_set) == nsh
            shard = next(iter(leaf.addressable_shards))
            assert shard.data.shape[0] == padded // nsh
        else:
            # counters stay replicated
            assert leaf.sharding.is_fully_replicated
