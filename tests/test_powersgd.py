"""PowerSGD-style low-rank compressor (compression/powersgd.py): shape
algebra, warm-started subspace capture, fused server sum, EF-chain
convergence through the real engine, and wire accounting.  Beyond the
reference's compressor set; follows its per-worker-compress /
server-sum protocol (reference server.cc:87-113)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import byteps_tpu as bps
from byteps_tpu.common import Config
from byteps_tpu.common.config import set_config
from byteps_tpu.compression import create
from byteps_tpu.compression.powersgd import (PowerSGDCompressor,
                                             _matrix_shape)


def test_matrix_shape_near_square_and_lane_aligned():
    n, m = _matrix_shape(1 << 20)            # 1M elems
    assert n * m >= 1 << 20
    assert m % 128 == 0                      # MXU lane alignment
    assert n >= m
    # tiny chunks: exact-square fallback, no degenerate dims
    n, m = _matrix_shape(10)
    assert n * m >= 10 and m >= 1


def test_rank_clamped_to_matrix_dims():
    c = PowerSGDCompressor(numel=12, rank=64)   # 4x3-ish matrix
    assert c.rank <= min(c.n, c.m)


def test_payload_shapes_and_wire_savings():
    numel = 256 * 256
    c = PowerSGDCompressor(numel, rank=4)
    x = jnp.asarray(np.random.RandomState(0).randn(numel), jnp.float32)
    payload, state = c.compress(x, c.init_state())
    assert payload["p"].shape == (c.n, c.rank)
    assert payload["q"].shape == (c.m, c.rank)
    assert state["q"].shape == (c.m, c.rank)
    dense = numel * 4
    assert c.payload_nbytes() < dense / 25    # >25x for 256x256 at r=4
    # accounting matches the actual payload
    actual = sum(int(np.prod(v.shape)) * 4 for v in payload.values())
    assert actual == c.payload_nbytes()


def test_exactly_low_rank_input_recovered_after_warm_start():
    # A rank-2 matrix must be captured ~exactly by rank>=2 power
    # iteration once the warm-started subspace converges.
    rng = np.random.RandomState(1)
    n = m = 64
    M = (rng.randn(n, 2) @ rng.randn(2, m)).astype(np.float32)
    x = jnp.asarray(M.reshape(-1))
    c = PowerSGDCompressor(n * m, rank=2)
    state = c.init_state()
    err = []
    for _ in range(4):
        payload, state = c.compress(x, state)
        rec = np.asarray(c.decompress(payload)).reshape(n, m)
        err.append(np.linalg.norm(rec - M) / np.linalg.norm(M))
    assert err[-1] < 1e-3, err                # converged onto the subspace
    assert err[-1] <= err[0] + 1e-6           # warm start never hurts


def test_zero_and_rank_deficient_inputs_stay_finite():
    c = PowerSGDCompressor(1024, rank=4)
    for x in (jnp.zeros(1024, jnp.float32),
              jnp.ones(1024, jnp.float32)):   # rank-1: deficient at r=4
        payload, state = c.compress(x, c.init_state())
        rec = c.decompress(payload)
        assert np.isfinite(np.asarray(rec)).all()
        assert np.isfinite(np.asarray(state["q"])).all()


def test_decompress_sum_matches_per_rank_decompression():
    numel = 48 * 48
    c = PowerSGDCompressor(numel, rank=3)
    rng = np.random.RandomState(2)
    payloads = []
    for i in range(4):
        x = jnp.asarray(rng.randn(numel), jnp.float32)
        p, _ = c.compress(x, c.init_state())
        payloads.append(p)
    gathered = {k: jnp.stack([p[k] for p in payloads])
                for k in payloads[0]}
    fused = np.asarray(c.decompress_sum(gathered))
    ref = sum(np.asarray(c.decompress(p)).astype(np.float64)
              for p in payloads)
    np.testing.assert_allclose(fused, ref, rtol=2e-5, atol=1e-4)


def test_registry_string_kwargs():
    c = create({"compressor": "powersgd", "rank": "2", "seed": "7"},
               4096, jnp.float32)
    assert c.name == "powersgd" and c.rank == 2 and c.seed == 7
    assert c.cache_key() != create({"compressor": "powersgd", "rank": "3"},
                                   4096, jnp.float32).cache_key()
    # EF chain wraps it like any other compressor
    ef = create({"compressor": "powersgd", "ef": "vanilla"}, 4096,
                jnp.float32)
    assert "error" in str(type(ef).__name__).lower() or hasattr(ef, "inner")


def test_engine_push_pull_powersgd_end_to_end():
    # Through the real engine on the 8-rank mesh: compressed push_pull of
    # a LOW-RANK stacked gradient reproduces the plain average closely
    # after the warm-start settles (same tensor name -> same slot/state).
    set_config(Config(telemetry_on=False, trace_on=False,
                      min_compress_bytes=0))
    bps.init()
    try:
        rng = np.random.RandomState(3)
        base = (rng.randn(64, 2) @ rng.randn(2, 64)).astype(np.float32)
        stacked = np.stack([base * (i + 1) for i in range(8)])  # rank 2
        want = stacked.mean(0).reshape(-1)
        out = None
        for _ in range(4):   # warm-start iterations on the same key
            out = bps.push_pull(
                jnp.asarray(stacked.reshape(8, -1)), "psgd/g",
                op="average",
                compression={"compressor": "powersgd", "rank": "2"})
        got = np.asarray(out).reshape(-1)
        rel = (np.linalg.norm(got - want) / np.linalg.norm(want))
        assert rel < 1e-3, rel
    finally:
        bps.shutdown()


def test_engine_powersgd_with_error_feedback_converges():
    # EF accumulates what the rank-1 approximation drops; a full-rank
    # gradient pushed repeatedly must see its EF-compensated average
    # approach the true average over steps (the EF contract, same as the
    # onebit/topk chains).
    set_config(Config(telemetry_on=False, trace_on=False,
                      min_compress_bytes=0))
    bps.init()
    try:
        rng = np.random.RandomState(4)
        stacked = rng.randn(8, 32 * 32).astype(np.float32)  # full rank
        want = stacked.mean(0)
        errs = []
        acc = np.zeros_like(want)
        for step in range(6):
            out = bps.push_pull(
                jnp.asarray(stacked), "psgd/ef", op="average",
                compression={"compressor": "powersgd", "rank": "2",
                             "ef": "vanilla"})
            acc += np.asarray(out)
            # EF guarantee: the RUNNING SUM of outputs tracks step*want
            errs.append(np.linalg.norm(acc - (step + 1) * want)
                        / np.linalg.norm((step + 1) * want))
        assert errs[-1] < errs[0], errs       # compensation is working
    finally:
        bps.shutdown()


def test_decorators_delegate_fused_server_sum():
    # code-review r5: EF/momentum wrap the compressor, and the engine
    # calls decompress_sum on the WRAPPER — without delegation the
    # inner's fused kernel (powersgd einsum, onebit Pallas merge) is
    # silently replaced by the base vmap fallback.
    calls = []

    class Spy(PowerSGDCompressor):
        def decompress_sum(self, gathered):
            calls.append("fused")
            return super().decompress_sum(gathered)

    from byteps_tpu.compression.error_feedback import ErrorFeedback
    from byteps_tpu.compression.momentum import NesterovMomentum

    inner = Spy(1024, rank=2)
    for wrapper in (ErrorFeedback(inner),
                    NesterovMomentum(ErrorFeedback(inner), mu=0.9)):
        calls.clear()
        p, _ = inner.compress(jnp.ones(1024, jnp.float32),
                              inner.init_state())
        gathered = {k: jnp.stack([v, v]) for k, v in p.items()}
        wrapper.decompress_sum(gathered)
        assert calls == ["fused"], type(wrapper).__name__


def test_iters_sharpen_cold_start_toward_svd_optimum():
    # Stateless call sites (the DCN pair) cold-start; extra in-compress
    # power iterations must close the gap to the SVD rank-r optimum on a
    # decaying-spectrum matrix.
    rng = np.random.RandomState(5)
    n = m = 64
    U, _ = np.linalg.qr(rng.randn(n, n))
    V, _ = np.linalg.qr(rng.randn(m, m))
    s = 0.5 ** np.arange(m)
    M = (U * s) @ V.T
    x = jnp.asarray(M.reshape(-1), jnp.float32)
    r = 4
    svd_err = np.linalg.norm((U[:, r:] * s[r:]) @ V[:, r:].T)

    errs = {}
    for iters in (1, 3):
        c = PowerSGDCompressor(n * m, rank=r, iters=iters)
        payload, _ = c.compress(x, c.init_state())
        rec = np.asarray(c.decompress(payload)).reshape(n, m)
        errs[iters] = np.linalg.norm(rec - M)
    assert errs[3] < errs[1]
    # within a small constant of the SVD optimum (power iteration from a
    # random start converges geometrically; 2x after 3 iterations on this
    # spectrum)
    assert errs[3] < 2.0 * svd_err + 1e-6


def test_dcn_pair_wire_bytes_and_exactness_on_low_rank_shards():
    # The fused-path DCN hook: only (n+m)*r floats cross the inter-slice
    # axis (HLO-accounted), and a shard that IS low rank in the
    # compressor's matrix view survives the hop exactly.
    from jax.sharding import Mesh, PartitionSpec as P

    from byteps_tpu.ops.collective_ops import (hierarchical_push_pull,
                                               make_powersgd_pair)
    from byteps_tpu.utils.hlo_wire import dcn_ici_bytes

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dcn", "ici"))
    n = 1 << 16

    def body(x):
        c, d = make_powersgd_pair(rank=4, iters=2)
        return hierarchical_push_pull(x[0], op="sum", compress=c,
                                      decompress=d, compress_min_bytes=0)

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P(("dcn", "ici")),
                              out_specs=P(), check_vma=False))
    # constant-per-rank rows: every DCN shard reshapes to a (near-)
    # constant matrix — rank <= 2 with the pad row — so rank-4 is exact
    x = jnp.asarray(np.arange(1.0, 9.0, dtype=np.float32)[:, None]
                    * np.ones((8, n), np.float32))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.full(n, 36.0), rtol=1e-4)

    hlo = f.lower(x).compile().as_text()
    dcn_b, _ = dcn_ici_bytes(hlo, n_ici=4)
    from byteps_tpu.compression.powersgd import _matrix_shape
    nn, mm = _matrix_shape(n // 4)
    assert dcn_b == (nn + mm) * 4 * 4          # (n+m)*rank*itemsize
    # 16x at this deliberately small test shard (128x128, r=4); the
    # ratio grows as sqrt(numel) — the bench's 1 MiB shard shows 64x
    assert dcn_b <= (n // 4) * 4 / 16


def test_matches_numpy_reference():
    # House convention (tests/compression_refs.py): every compressor has
    # a portable numpy mirror.  The comparison must use a SEPARATED
    # spectrum: on a flat (random gaussian) spectrum the top-r subspace
    # is ill-conditioned and f32 rounding legitimately rotates it between
    # backends — with decaying singular values the captured subspace, and
    # therefore the reconstruction, is numerically pinned.
    from tests import compression_refs as refs

    rng = np.random.RandomState(6)
    nm = 80
    numel = nm * nm
    U, _ = np.linalg.qr(rng.randn(nm, nm).astype(np.float64))
    V, _ = np.linalg.qr(rng.randn(nm, nm).astype(np.float64))
    x = ((U * 0.5 ** np.arange(nm)) @ V.T).astype(np.float32).reshape(-1)
    c = PowerSGDCompressor(numel, rank=3, iters=2)
    payload, _ = c.compress(jnp.asarray(x), c.init_state())
    rec = np.asarray(c.decompress(payload))

    p_ref, q_ref = refs.powersgd_compress(x, rank=3, iters=2)
    rec_ref = refs.powersgd_decompress(p_ref, q_ref, numel)
    np.testing.assert_allclose(rec, rec_ref, rtol=1e-4, atol=1e-5)
    # warm-start parity: second step with each side's own state — looser,
    # because the states themselves have accumulated one step of f32
    # rounding differences between LAPACK and XLA
    payload2, _ = c.compress(jnp.asarray(x), {"q": payload["q"]})
    p2, q2 = refs.powersgd_compress(x, rank=3, q=q_ref)
    np.testing.assert_allclose(
        np.asarray(c.decompress(payload2)),
        refs.powersgd_decompress(p2, q2, numel), rtol=1e-2, atol=2e-3)
