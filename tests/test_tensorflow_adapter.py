"""TF/Keras adapter tests — the reference's op-correctness + keras
integration shape (tests/test_mxnet.py sums against numpy;
tests/test_tensorflow_keras.py trains a model and checks weight
consistency).  Single process == the reference's single-worker
forced-distributed mode: push_pull over one process is identity."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
import keras  # noqa: E402

import byteps_tpu.tensorflow as bps_tf  # noqa: E402
import byteps_tpu.keras as bps_keras  # noqa: E402


@pytest.fixture
def session():
    bps_tf.init()
    yield
    bps_tf.shutdown()


def test_push_pull_identity_and_sum(session):
    x = tf.constant(np.random.randn(13, 5).astype(np.float32))
    avg = bps_tf.push_pull(x, name="tfa")
    np.testing.assert_allclose(avg.numpy(), x.numpy(), rtol=1e-5, atol=1e-6)
    tot = bps_tf.push_pull(x, op="Sum", name="tfa")
    np.testing.assert_allclose(tot.numpy(), x.numpy(), rtol=1e-5, atol=1e-6)


def test_push_pull_fp16_compression(session):
    x = tf.constant(np.random.randn(64).astype(np.float32))
    out = bps_tf.push_pull(x, name="tfc", compression=bps_tf.Compression.fp16)
    assert out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-2, atol=1e-2)


def test_push_pull_inside_tf_function(session):
    @tf.function
    def reduced(v):
        return bps_tf.push_pull(v, name="tfg", op="Sum")

    x = tf.constant(np.arange(8, dtype=np.float32))
    np.testing.assert_allclose(reduced(x).numpy(), x.numpy(), rtol=1e-6)


def test_push_pull_gradient_is_push_pull(session):
    x = tf.Variable(np.ones(4, dtype=np.float32))
    with tf.GradientTape() as tape:
        y = tf.reduce_sum(bps_tf.push_pull(x, name="tfgrad", op="Sum") * 3.0)
    g = tape.gradient(y, x)
    np.testing.assert_allclose(g.numpy(), 3.0 * np.ones(4), rtol=1e-6)


def test_broadcast_variables(session):
    v = tf.Variable(np.full(6, 7.0, dtype=np.float32))
    bps_tf.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), np.full(6, 7.0), rtol=1e-6)


def test_broadcast_variables_graph_mode(session):
    # TF1-compat path: values read via session.run, assigned through
    # placeholder assign ops (reference BroadcastGlobalVariablesHook shape)
    g = tf.Graph()
    with g.as_default():
        v = tf.compat.v1.get_variable(
            "bv", initializer=np.full(5, 3.0, dtype=np.float32))
        init_op = tf.compat.v1.global_variables_initializer()
        with tf.compat.v1.Session(graph=g) as sess:
            sess.run(init_op)
            bps_tf.broadcast_global_variables(0, session=sess)
            np.testing.assert_allclose(sess.run(v), np.full(5, 3.0),
                                       rtol=1e-6)


def test_distributed_gradient_tape(session):
    w = tf.Variable(2.0)
    with bps_tf.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = w * w
    g = tape.gradient(loss, [w])
    assert abs(float(g[0]) - 4.0) < 1e-5


def test_distributed_optimizer_applies_reduced_grads(session):
    opt = bps_tf.DistributedOptimizer(keras.optimizers.SGD(learning_rate=0.5))
    v = tf.Variable(np.array([1.0, 2.0], dtype=np.float32))
    g = tf.constant(np.array([1.0, 1.0], dtype=np.float32))
    opt.apply_gradients([(g, v)])
    np.testing.assert_allclose(v.numpy(), [0.5, 1.5], rtol=1e-5)


def test_keras_fit_with_callbacks(session):
    # a tiny end-to-end fit: DistributedOptimizer + broadcast + metric
    # averaging + warmup schedule, run eagerly (py_function transport)
    xs = np.random.randn(32, 4).astype(np.float32)
    ys = (xs.sum(axis=1, keepdims=True) > 0).astype(np.float32)
    model = keras.Sequential([
        keras.layers.Dense(8, activation="relu"),
        keras.layers.Dense(1, activation="sigmoid"),
    ])
    opt = bps_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.1))
    model.compile(optimizer=opt, loss="binary_crossentropy",
                  metrics=["accuracy"], run_eagerly=True)
    cbs = [
        bps_keras.callbacks.BroadcastGlobalVariablesCallback(0),
        bps_keras.callbacks.MetricAverageCallback(),
        bps_keras.callbacks.LearningRateWarmupCallback(
            warmup_epochs=2, steps_per_epoch=4, verbose=0),
    ]
    hist = model.fit(xs, ys, batch_size=8, epochs=2, callbacks=cbs,
                     verbose=0)
    assert len(hist.history["loss"]) == 2
    assert all(np.isfinite(v) for v in hist.history["loss"])


def test_lr_schedule_callback_staircase(session):
    model = keras.Sequential([keras.layers.Dense(1)])
    model.compile(optimizer=keras.optimizers.SGD(learning_rate=1.0),
                  loss="mse", run_eagerly=True)
    cb = bps_keras.callbacks.LearningRateScheduleCallback(
        multiplier=lambda epoch: 0.1 ** epoch, staircase=True,
        momentum_correction=False)
    xs = np.random.randn(8, 3).astype(np.float32)
    ys = np.random.randn(8, 1).astype(np.float32)
    hist = model.fit(xs, ys, batch_size=4, epochs=3, callbacks=[cb],
                     verbose=0)
    lrs = hist.history["lr"]
    np.testing.assert_allclose(lrs, [1.0, 0.1, 0.01], rtol=1e-5)


def test_make_compiled_train_step_matches_plain(session):
    """Compiled-boundary step == plain jit training on one process (the
    push_pull averages over 1 process = identity), so the parameters must
    evolve identically."""
    tf.random.set_seed(4)
    loss_fn = tf.keras.losses.MeanSquaredError()

    def build():
        m = tf.keras.Sequential([
            tf.keras.layers.Dense(16, activation="relu",
                                  input_shape=(8,)),
            tf.keras.layers.Dense(1)])
        return m

    m1 = build()
    m2 = build()
    m2.set_weights(m1.get_weights())
    o1 = tf.keras.optimizers.SGD(0.05)
    o2 = tf.keras.optimizers.SGD(0.05)

    rng = np.random.RandomState(4)
    x = tf.constant(rng.randn(32, 8).astype(np.float32))
    y = tf.constant(rng.randn(32, 1).astype(np.float32))

    # jit_compile exercises the documented XLA composition; CPU supports it
    step = bps_tf.make_compiled_train_step(
        m2, lambda logits, yb: loss_fn(yb, logits), o2, jit_compile=True)

    @tf.function(jit_compile=True)
    def plain_step(xb, yb):
        with tf.GradientTape() as tape:
            loss = loss_fn(yb, m1(xb, training=True))
        o1.apply_gradients(zip(tape.gradient(loss, m1.trainable_variables),
                               m1.trainable_variables))
        return loss

    for _ in range(4):
        l_plain = float(plain_step(x, y))
        l_bps = float(step(x, y))
    np.testing.assert_allclose(l_bps, l_plain, rtol=1e-5)
    for w1, w2 in zip(m1.get_weights(), m2.get_weights()):
        np.testing.assert_allclose(w2, w1, rtol=1e-4, atol=1e-6)


def test_reduce_gradients_eager_priority_burst(session):
    grads = [tf.constant(np.full((4,), float(i + 1), np.float32))
             for i in range(3)] + [None]
    out = bps_tf.reduce_gradients_eager(grads, scope="t", op="average")
    assert out[3] is None
    for i in range(3):
        np.testing.assert_allclose(out[i].numpy(), np.full((4,), i + 1.0),
                                   rtol=1e-6)
