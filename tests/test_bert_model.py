"""BERT flagship model: the gathered MLM head must be mathematically
identical to the full-sequence head at the masked positions (it exists
purely to shrink the vocab projection/softmax from [B,T,V] to [B,P,V])."""

import jax
import jax.numpy as jnp
import numpy as np

from byteps_tpu.models.bert import (BertForMLM, bert_tiny, mlm_loss,
                                    synthetic_batch)


def test_gathered_head_matches_full_head():
    cfg = bert_tiny()
    m = BertForMLM(cfg)
    rng = jax.random.PRNGKey(0)
    b = synthetic_batch(rng, cfg, batch=4, seq_len=32)
    p = m.init(rng, b["input_ids"], b["attention_mask"])
    full = m.apply(p, b["input_ids"], b["attention_mask"])
    gath = m.apply(p, b["input_ids"], b["attention_mask"],
                   masked_positions=b["masked_positions"])
    sel = jnp.take_along_axis(full, b["masked_positions"][..., None], axis=1)
    np.testing.assert_allclose(np.asarray(gath), np.asarray(sel),
                               rtol=2e-4, atol=2e-4)
    l_full = mlm_loss(full, b["labels"])
    l_gath = mlm_loss(gath, b["masked_labels"])
    np.testing.assert_allclose(float(l_full), float(l_gath), rtol=1e-4)


def test_synthetic_batch_masks_exactly_p_positions():
    cfg = bert_tiny()
    b = synthetic_batch(jax.random.PRNGKey(1), cfg, batch=8, seq_len=64,
                        mask_frac=0.15)
    n_pred = int(64 * 0.15)
    assert b["masked_positions"].shape == (8, n_pred)
    # full-length labels carry the same P masked slots per row
    assert int((np.asarray(b["labels"]) >= 0).sum(axis=1).max()) == n_pred
    assert int((np.asarray(b["labels"]) >= 0).sum(axis=1).min()) == n_pred
    # masked inputs are zeroed
    ids = np.asarray(b["input_ids"])
    pos = np.asarray(b["masked_positions"])
    for r in range(8):
        assert (ids[r, pos[r]] == 0).all()
    # positions are unique per row (permutation-based selection)
    for r in range(8):
        assert len(set(pos[r].tolist())) == n_pred
