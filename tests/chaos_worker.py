"""Worker body for the 2→1 kill-and-recover chaos test.

Launched twice by tests/test_recovery.py (pattern of
tests/test_failure_detector.py's mp kill test): two real processes, each
with its own engine on the virtual CPU mesh, share one heartbeat
endpoint and one checkpoint directory.  The victim (rank 1) is killed
mid-run by the fault injector (``BYTEPS_FAULT_SPEC=kill:rank=1:step=N``
— the injector counts push_pull enqueues); the survivor's
HeartbeatMonitor detects the silence and its RecoveryCoordinator runs
the full automated path: drain → suspend → resume(num_workers=1) →
restore from the last CheckpointManager step — then the training loop
verifies the restored step/state and keeps stepping on the recovered
engine.

Deliberately NOT a jax.distributed run: the JAX runtime cannot drop a
dead peer's devices from an initialized backend in-process (the cached
backend keeps advertising them), so cross-host wedges end in the
detector's process exit + launcher restart (tested by
test_failure_detector / the launchers' --restart path).  What this test
pins is the *supervised recovery machinery itself* — detection wiring,
drain/suspend, elastic resume on the shrunk worker count, checkpoint
restore, and post-recovery engine health.

Env (set by the test): BYTEPS_CHAOS_RANK, BYTEPS_CHAOS_HB_PORT,
BYTEPS_CHAOS_CKPT, plus BYTEPS_FAULT_SPEC for the victim.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    rank = int(os.environ["BYTEPS_CHAOS_RANK"])
    hb_port = os.environ["BYTEPS_CHAOS_HB_PORT"]
    ckdir = os.environ["BYTEPS_CHAOS_CKPT"]

    import jax

    jax.config.update("jax_platforms", "cpu")

    import byteps_tpu.core.api as api
    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.fault.recovery import RecoveryCoordinator
    from byteps_tpu.utils.checkpoint import CheckpointManager
    from byteps_tpu.utils.failure_detector import HeartbeatMonitor

    template = {"w": np.zeros(8, np.float32), "step": np.array(0)}
    api.init()  # arms the injector from BYTEPS_FAULT_SPEC (victim only)
    eng = api._require()

    # Two managers over ONE directory: the training loop saves through
    # its own; the coordinator restores through its own on the detector
    # thread (orbax finalizes step dirs atomically, so directory-level
    # concurrency is safe where object-level sharing would not be).
    mgr = CheckpointManager(ckdir, max_to_keep=3) if rank == 0 else None
    coordinator = RecoveryCoordinator(
        checkpoint_manager=(CheckpointManager(ckdir, max_to_keep=3)
                            if rank == 0 else None),
        template=template)
    # Manual monitors, one per process (the auto-armed path needs
    # jax.process_count() > 1).  Sub-second staleness timeout; generous
    # grace covers the peer's interpreter/jax startup skew.
    mon = HeartbeatMonitor(
        rank, 2, "127.0.0.1:" + hb_port, interval=0.08, timeout=0.7,
        grace=60.0,
        on_failure=(coordinator.on_failure if rank == 0 else
                    lambda stale: None)).start()
    # Liveness bootstrap barrier: do not start (killable) training until
    # the server has seen this rank beat.  The survivor's startup is
    # seconds slower than the victim's (orbax CheckpointManager
    # construction); without the barrier the victim can beat and die
    # entirely BEFORE the server exists, landing in the never-seen
    # startup-grace shadow where its death is invisible.
    if not mon.wait_server(60.0):
        print("NO-HEARTBEAT-SERVER", flush=True)
        return 7
    print("START", rank, flush=True)

    # Each step's push_pull adds exactly 1.0 to every element (single
    # process: sum over processes == the local ones-contribution), so
    # the invariant "w == full(step)" makes restored state checkable
    # against the restored step number.
    w = np.zeros(8, np.float32)
    for step in range(1, 400):
        if coordinator.triggered:
            break
        try:
            # bounded wait, not push_pull's bare wait(): a push racing the
            # recovery teardown can miss the drain snapshot and would
            # otherwise park this thread forever on a dead engine
            h = eng.push_pull_local_async(np.ones(8, np.float32), "grad",
                                          op="sum")
            w = w + np.asarray(h.wait(timeout=10))
        except Exception:  # noqa: BLE001 — engine torn down mid-step
            if coordinator.triggered:
                break
            raise
        if rank == 0 and not coordinator.triggered:
            mgr.save(step, {"w": w, "step": np.array(step)})
        time.sleep(0.1)
    else:
        print("NO-FAILURE-DETECTED", flush=True)
        return 3

    # survivor side: the coordinator (running on the detector thread)
    # completes suspend -> resume(1) -> restore
    res = coordinator.wait(timeout=60)
    if res is None:
        print("RECOVERY-TIMEOUT", flush=True)
        return 4
    assert res.failed_ranks == {1}, res.failed_ranks
    assert res.num_workers == 1, res.num_workers
    # training step value preserved: the restored tensors are exactly the
    # ones saved at the restored step (the w == full(step) invariant)
    assert res.step is not None and res.step >= 1, res.step
    assert int(res.state["step"]) == res.step, (res.state["step"], res.step)
    np.testing.assert_allclose(res.state["w"],
                               np.full(8, float(res.step)), rtol=1e-6)
    assert counters.get("recovery.completed") == 1

    # the recovered engine is live: keep training where the ckpt left off
    eng2 = api._require()
    assert eng2 is not eng
    w = np.asarray(res.state["w"])
    for _ in range(2):
        out = eng2.push_pull_local(np.ones(8, np.float32), "grad", op="sum")
        w = w + np.asarray(out)
    np.testing.assert_allclose(w, np.full(8, float(res.step + 2)),
                               rtol=1e-6)
    mon.stop()
    api.shutdown()
    print("RECOVERED", res.step, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
