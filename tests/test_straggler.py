"""Gray-failure tolerance (ISSUE 10): step-barrier slowness scoring on
the membership bus, probation-based demotion under
``BYTEPS_STRAGGLER_POLICY=demote``, readmission through the ordinary
rejoin path, and the 3-process acceptance pin — one rank under a
sustained ``slow`` fault is demoted (throughput recovers), then
readmitted once the fault window ends, with zero lost or double-counted
gradients.

The in-process tests drive the raw bus protocol and
:class:`ElasticMembership` clients; the heavyweight end-to-end lives in
``test_straggler_demote_and_readmit_3proc`` (chaos lane
``tools/run_chaos.sh straggler``)."""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import byteps_tpu.core.api as api
from byteps_tpu.common.config import Config, set_config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import membership as mm
from byteps_tpu.fault.membership import (Demoted, ElasticMembership,
                                         MembershipView, WorldChanged,
                                         _BusServer, _recv_obj, _send_obj)

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "straggler_worker.py")


@pytest.fixture(autouse=True)
def _fresh_epoch():
    mm._reset_epoch_for_tests()
    yield
    if api.initialized():
        api.shutdown()
    api._declared_order = []
    mm._reset_epoch_for_tests()


def _req(port, msg, timeout=20.0):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(timeout)
    _send_obj(s, msg)
    reply = _recv_obj(s)
    s.close()
    return reply


def _demote_config(**kw):
    """A config tuned for fast in-process demotion tests."""
    base = dict(straggler_policy="demote", straggler_demote_after=2,
                straggler_min_lag_s=0.1, slowness_phi=3.0,
                membership_rendezvous_timeout_s=3.0,
                membership_sync_timeout_s=10.0)
    base.update(kw)
    cfg = Config(**base)
    set_config(cfg)
    return cfg


# -- config ------------------------------------------------------------------


def test_straggler_policy_validation(monkeypatch):
    assert Config().straggler_policy == "wait"
    for ok in ("wait", "hedge", "demote"):
        assert Config(straggler_policy=ok).straggler_policy == ok
    with pytest.raises(ValueError, match="STRAGGLER_POLICY"):
        Config(straggler_policy="panic")
    with pytest.raises(ValueError, match="slowness_phi"):
        Config(slowness_phi=0)
    with pytest.raises(ValueError, match="slowness_window"):
        Config(slowness_window=2)
    with pytest.raises(ValueError, match="demote_after"):
        Config(straggler_demote_after=0)
    with pytest.raises(ValueError, match="min_lag"):
        Config(straggler_min_lag_s=-1)
    with pytest.raises(ValueError, match="hedge_ms"):
        Config(serve_hedge_ms=-1)
    monkeypatch.setenv("BYTEPS_STRAGGLER_POLICY", "Demote")
    monkeypatch.setenv("BYTEPS_SLOWNESS_PHI", "5.5")
    monkeypatch.setenv("BYTEPS_STRAGGLER_DEMOTE_AFTER", "4")
    monkeypatch.setenv("BYTEPS_STRAGGLER_MIN_LAG", "0.5")
    monkeypatch.setenv("BYTEPS_SERVE_HEDGE_MS", "2.5")
    from byteps_tpu.common.config import reset_config
    reset_config()
    from byteps_tpu.common.config import get_config
    cfg = get_config()
    assert cfg.straggler_policy == "demote"          # case-normalized
    assert cfg.slowness_phi == 5.5
    assert cfg.straggler_demote_after == 4
    assert cfg.straggler_min_lag_s == 0.5
    assert cfg.serve_hedge_ms == 2.5


# -- the bus: arrival-lag scoring and the demote decision --------------------


def _run_rounds(port, epoch, steps, ranks, slow_rank=None, slow_s=0.25,
                metrics=None):
    """Drive sync rounds against a raw bus: one thread per rank per
    round, ``slow_rank`` arriving ``slow_s`` late.  Returns
    ``{step: {rank: reply}}``."""
    out = {}
    for step in steps:
        replies = {}
        lock = threading.Lock()

        def sync(rank, step=step):
            if rank == slow_rank:
                time.sleep(slow_s)
            msg = {"op": "sync", "rank": rank, "epoch": epoch,
                   "step": step, "payload": rank}
            if metrics is not None:
                msg["metrics"] = metrics(rank, step)
            r = _req(port, msg)
            with lock:
                replies[rank] = r

        ts = [threading.Thread(target=sync, args=(r,)) for r in ranks]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        out[step] = replies
    return out


def test_bus_scores_step_barrier_lags_and_demotes():
    """Three ranks, rank 1 consistently 0.25s late to every barrier:
    round 1 completes ok (hysteresis), the demote_after-th consecutive
    slow round answers EVERY member with the demote signal, and the bus
    parks rank 1 on the probation list."""
    _demote_config()
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1, 2)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=10.0)
    try:
        rounds = _run_rounds(port, 0, (1, 2, 3), (0, 1, 2), slow_rank=1)
        # round 1: slow but not yet demote_after consecutive — round ok
        assert all(r["ok"] for r in rounds[1].values()), rounds[1]
        # by round 2 the decision lands; whichever round carries it,
        # every member of that round sees the same signal
        demote_round = next(s for s in (2, 3)
                            if not rounds[s][0].get("ok"))
        for rank in (0, 1, 2):
            r = rounds[demote_round][rank]
            assert r["ok"] is False and r["demote"] == 1, (demote_round, r)
            assert r["probation"] == [1]
        assert counters.get("membership.straggler_demote_decided") == 1
        # the observability verbs expose the accusation and the state
        ping = _req(port, {"op": "ping"})
        assert ping["probation"] == [1]
        met = _req(port, {"op": "metrics"})
        assert met["probation"] == [1]
        assert met["slow"][1] >= 3.0, met["slow"]
        assert met["slow"].get(0, 0.0) < 3.0
        # the replica snapshot carries probation (failover-safe)
        rep = _req(port, {"op": "replicate", "rank": 1})
        assert sorted(rep["replica"]["probation"]) == [1]
    finally:
        bus.close()


def test_bus_policy_wait_scores_but_never_demotes():
    """Default policy: the same sustained straggler is SCORED (the
    operator sees it) but nothing acts — every round completes."""
    _demote_config(straggler_policy="wait")
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1, 2)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=10.0)
    try:
        rounds = _run_rounds(port, 0, (1, 2, 3, 4), (0, 1, 2),
                             slow_rank=1)
        for step, replies in rounds.items():
            assert all(r["ok"] for r in replies.values()), (step, replies)
        met = _req(port, {"op": "metrics"})
        assert met["slow"][1] >= 3.0
        assert met["probation"] == []
        assert counters.get("membership.straggler_demote_decided") == 0
    finally:
        bus.close()


def test_bus_coordinator_is_exempt_from_demotion():
    """The coordinator hosts the bus: demoting it would race its own
    failover.  A slow rank 0 is scored but never demoted — its
    slowness escalates through the crash-failover path instead."""
    _demote_config()
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=10.0)
    try:
        rounds = _run_rounds(port, 0, (1, 2, 3, 4), (0, 1), slow_rank=0)
        for step, replies in rounds.items():
            assert all(r["ok"] for r in replies.values()), (step, replies)
        assert _req(port, {"op": "ping"})["probation"] == []
    finally:
        bus.close()


def test_bus_deadline_trips_piggyback_drives_demotion():
    """The self-reported trigger: a rank whose metrics piggyback shows
    fresh ``engine.sync_deadline_trips`` each round is slow even with
    zero arrival lag — demoted after demote_after consecutive rounds."""
    _demote_config()
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=10.0)

    def metrics(rank, step):
        if rank != 1:
            return {"counters": {}}
        # trips grow every round; round 1 establishes the baseline
        return {"counters": {"engine.sync_deadline_trips": step}}

    try:
        rounds = _run_rounds(port, 0, (1, 2, 3, 4), (0, 1),
                             metrics=metrics)
        assert all(r["ok"] for r in rounds[1].values())   # baseline round
        demote_round = next(s for s in (2, 3, 4)
                            if not rounds[s][0].get("ok"))
        # rounds 2 and 3 carry fresh trips -> demote on the 2nd of them
        assert demote_round == 3, rounds
        for rank in (0, 1):
            assert rounds[demote_round][rank]["demote"] == 1
        assert _req(port, {"op": "ping"})["probation"] == [1]
    finally:
        bus.close()


def test_bus_readmission_clears_probation():
    """After a demotion, survivors agree the shrunk world (hello), the
    demoted rank parks a rejoin, and admission at a state-carrying
    quorum clears its probation entry — the full bus-side lifecycle."""
    _demote_config()
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1, 2)),
                     rendezvous_timeout_s=3.0, sync_timeout_s=10.0)
    try:
        rounds = _run_rounds(port, 0, (1, 2, 3), (0, 1, 2), slow_rank=1)
        assert any(not rounds[s][0].get("ok") for s in (2, 3))
        assert _req(port, {"op": "ping"})["probation"] == [1]
        # survivors run the shrink rendezvous for epoch 1, world {0, 2}
        hellos = {}

        def hello(rank):
            hellos[rank] = _req(port, {"op": "hello", "rank": rank,
                                       "epoch": 1, "world": [0, 2]})

        ts = [threading.Thread(target=hello, args=(r,)) for r in (0, 2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert hellos[0]["ok"] and hellos[0]["epoch"] == 1
        assert hellos[0]["world"] == [0, 2]
        # probation SURVIVES the shrink — demoted, not forgotten
        assert _req(port, {"op": "ping"})["probation"] == [1]
        # the straggler recovered: it parks a rejoin; survivors sync
        # with state and the admission lands on the second quorum
        join_out = {}

        def rejoin():
            join_out["r"] = _req(port, {"op": "rejoin", "rank": 1},
                                 timeout=30.0)

        tj = threading.Thread(target=rejoin)
        tj.start()
        time.sleep(0.2)
        for step in (10, 11, 12):
            _run_rounds(port, 1, (step,), (0, 2),
                        metrics=None)
            # attach state explicitly on a quorum (raw protocol: the
            # state-carrying sync is what admission consumes)
            replies = {}
            lock = threading.Lock()

            def sync(rank, step=step):
                r = _req(port, {"op": "sync", "rank": rank, "epoch": 1,
                                "step": step + 100, "payload": rank,
                                "state": b"blob", "declared": ["g"]})
                with lock:
                    replies[rank] = r

            ts = [threading.Thread(target=sync, args=(r,))
                  for r in (0, 2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            if "r" in join_out:
                break
        tj.join(timeout=30)
        r = join_out["r"]
        assert r["ok"] and r["world"] == [0, 1, 2], r
        assert r["state"] == b"blob" and r["declared"] == ["g"]
        assert _req(port, {"op": "ping"})["probation"] == []
        assert counters.get("membership.probation_readmitted") == 1
    finally:
        bus.close()


def test_bus_seed_restores_probation():
    """A coordinator failover must not forget who is demoted: the
    replica seed carries probation into the successor bus."""
    _demote_config()
    port = _free_port()
    seed = {"epoch": 2, "world": [0, 2],
            "probation": {1: {"since": 123.0, "score": 9.5}}}
    bus = _BusServer(("127.0.0.1", port), MembershipView(2, (0, 2)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=5.0,
                     seed=seed)
    try:
        assert _req(port, {"op": "ping"})["probation"] == [1]
    finally:
        bus.close()


# -- the client: Demoted vs Evicted, demote() --------------------------------


def test_stale_reply_with_probation_raises_demoted_not_evicted():
    """A demoted rank that syncs late (it raced the demote signal)
    learns its status from the stale reply: probation ⇒ Demoted (stay
    alive, recover, rejoin) — never Evicted (restartable exit)."""
    _demote_config()
    port = _free_port()
    seed = {"epoch": 3, "world": [0, 2],
            "probation": {1: {"since": 1.0, "score": 9.0}}}
    bus = _BusServer(("127.0.0.1", port), MembershipView(3, (0, 2)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=5.0,
                     seed=seed)
    try:
        m = ElasticMembership(1, [0, 1, 2], f"127.0.0.1:{port}")
        with pytest.raises(Demoted) as ei:
            m.step_sync(7, payload=0)
        assert ei.value.probation == [1]
    finally:
        bus.close()


@pytest.mark.chaos
def test_in_process_demote_lifecycle():
    """Two in-process members, rank 1 sleeping before every barrier:
    the bus demotes it — rank 1 raises Demoted (and does NOT exit),
    rank 0 applies the demotion through the ordinary shrink machinery
    and continues alone at epoch 1."""
    _demote_config()
    port = _free_port()
    addr = f"127.0.0.1:{port}"
    m0 = ElasticMembership(0, [0, 1], addr).start()
    m1 = ElasticMembership(1, [0, 1], addr).start()
    results = {}

    def run(m, rank):
        step = 1
        try:
            while step <= 8:
                if rank == 1:
                    time.sleep(0.25)
                try:
                    m.step_sync(step, payload=rank)
                except WorldChanged as e:
                    results[rank] = ("world", e.view)
                    return
                step += 1
            results[rank] = ("done", None)
        except Demoted as e:
            results[rank] = ("demoted", e.probation)
        except Exception as e:  # noqa: BLE001
            results[rank] = ("error", e)

    try:
        ts = [threading.Thread(target=run, args=(m, r))
              for r, m in ((0, m0), (1, m1))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert results[1][0] == "demoted", results
        assert results[1][1] == [1]
        assert results[0][0] == "world", results
        assert results[0][1] == MembershipView(1, (0,))
        assert m0.view() == MembershipView(1, (0,))
        assert counters.get("membership.straggler_demote") >= 1
        assert counters.get("membership.demoted") == 1
        assert _req(port, {"op": "ping"})["probation"] == [1]
    finally:
        m1.stop()
        m0.stop()


# -- surfaces ----------------------------------------------------------------


def test_cluster_metrics_carries_slow_and_probation():
    _demote_config()
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1, 2)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=10.0)
    try:
        _run_rounds(port, 0, (1, 2, 3), (0, 1, 2), slow_rank=1)
        out = api.cluster_metrics(bus=f"127.0.0.1:{port}")
        assert out["probation"] == [1]
        assert out["slow"][1] >= 3.0
    finally:
        bus.close()


def test_bps_top_renders_slow_and_probation_columns():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import importlib
    bps_top = importlib.import_module("bps_top")
    cluster = {
        "epoch": 3, "world": [0, 2], "coordinator": 0, "standby": 2,
        "slow": {1: 12.4, 0: 0.1, 2: 0.3},
        "probation": [1],
        "ranks": {0: {"age_s": 0.5, "metrics": {"epoch": 3}},
                  2: {"age_s": 0.7, "metrics": {"epoch": 3}}},
    }
    txt = bps_top.render(cluster)
    assert "SLOW" in txt and "STATE" in txt
    assert "PROBATION" in txt          # rank 1's state
    assert "12.4" in txt               # rank 1's score, shown although
    #                                    it is outside the world
    assert "probation=[1]" in txt      # header flag
    lines = txt.splitlines()
    # one row per world member PLUS the probation rank
    assert sum(1 for l in lines if l.strip().startswith(("0 ", "1 ", "2 "))
               or l.strip().split()[:1] in (["0"], ["1"], ["2"])) >= 3


# -- the acceptance pin ------------------------------------------------------


def _spawn(rank, world, bus_port, steps, extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["DMLC_NUM_WORKER"] = "1"
    env["DMLC_WORKER_ID"] = str(rank)
    env["BYTEPS_ELASTIC_RANK"] = str(rank)
    env["BYTEPS_ELASTIC_WORLD"] = world
    env["BYTEPS_ELASTIC_BUS"] = f"127.0.0.1:{bus_port}"
    env["BYTEPS_ELASTIC_STEPS"] = str(steps)
    env["BYTEPS_ELASTIC_STEP_SLEEP"] = "0.1"
    env["BYTEPS_MEMBERSHIP_RENDEZVOUS_TIMEOUT"] = "3"
    env["BYTEPS_MEMBERSHIP_SYNC_TIMEOUT"] = "20"
    env["BYTEPS_STRAGGLER_POLICY"] = "demote"
    env["BYTEPS_STRAGGLER_DEMOTE_AFTER"] = "3"
    env["BYTEPS_STRAGGLER_MIN_LAG"] = "0.15"
    env["BYTEPS_LOG_LEVEL"] = "ERROR"
    env.pop("BYTEPS_FAULT_SPEC", None)
    env.update(extra or {})
    return subprocess.Popen([sys.executable, WORKER], env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _final(out):
    for line in out.splitlines():
        if line.startswith("FINAL "):
            _, epoch, world, w0 = line.split()
            return int(epoch), world, float(w0)
    raise AssertionError("no FINAL line in:\n" + out[-3000:])


def _step_windows(out):
    """Parse one worker's output into ``[(step, world, dt), ...]`` by
    tracking the WORLD transitions around its STEP lines."""
    world = (0, 1, 2)
    rows = []
    for line in out.splitlines():
        if line.startswith("WORLD "):
            parts = line.split()
            world = tuple(int(r) for r in parts[2].split(","))
        elif line.startswith("STEP "):
            _, step, dt = line.split()
            rows.append((int(step), world, float(dt)))
    return rows


@pytest.mark.chaos
def test_straggler_demote_and_readmit_3proc():
    """THE acceptance pin: 3 real processes, rank 1 under a sustained
    ``slow`` fault (350ms per engine sync visit, 12-visit window).

    - the bus demotes rank 1 after 3 consecutive slow barriers
      (survivors print the shrink WORLD line; rank 1 prints DEMOTED);
    - survivor step throughput recovers: the demoted-window median step
      wall is a fraction of the faulted-window's, and within the 70%
      bound of the post-readmission fault-free window;
    - rank 1 probes its own data path, observes the fault clear
      (RECOVERED), rejoins at a step boundary (REJOINED), and the bus
      lifts probation — world (0,1,2) again at epoch 2;
    - zero lost / double-counted gradients: every member's FINAL state
      equals a float32 replay of the exact world sequence each step ran
      under.
    """
    n = 50
    bus = str(_free_port())
    procs = {
        r: _spawn(r, "0,1,2", bus, n, extra=(
            {"BYTEPS_FAULT_SPEC": "slow:rank=1:site=sync:ms=350:n=12",
             "BYTEPS_FAULT_SEED": "7"} if r == 1 else None))
        for r in (0, 1, 2)}
    outs = {}
    try:
        for r, p in procs.items():
            outs[r], _ = p.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        for p in procs.values():
            p.kill()
        pytest.fail("straggler workers hung; partial: "
                    + "".join(o[-2000:] for o in outs.values()))

    for r in (0, 1, 2):
        assert procs[r].returncode == 0, (r, outs[r][-4000:])

    # the straggler went through the full lifecycle
    assert "DEMOTED at" in outs[1], outs[1][-3000:]
    assert "RECOVERED after" in outs[1], outs[1][-3000:]
    assert "REJOINED 2 0,1,2" in outs[1], outs[1][-3000:]
    # the injected fault really fired AND really cleared
    slow_line = next(l for l in outs[1].splitlines()
                     if l.startswith("SLOW-FIRED"))
    assert int(slow_line.split()[1]) == 12 and slow_line.split()[3] == "1", \
        slow_line
    # survivors observed demote (shrink) then readmission (grow)
    for r in (0, 2):
        assert "WORLD 1 0,2" in outs[r], outs[r][-3000:]
        assert "WORLD 2 0,1,2" in outs[r], outs[r][-3000:]

    # throughput: faulted window vs demoted window vs readmitted window
    rows = _step_windows(outs[0])
    fault_w = [dt for s, w, dt in rows if w == (0, 1, 2) and s <= 5]
    demoted_w = [dt for s, w, dt in rows if w == (0, 2)]
    healthy_w = [dt for s, w, dt in rows if w == (0, 1, 2) and s > 5]
    assert fault_w and demoted_w and healthy_w, rows

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    assert med(fault_w) >= 0.25, (med(fault_w), fault_w)   # fault bit
    # demotion restored throughput: >= 70% of the fault-free rate
    # (post-readmission window IS fault-free operation of the full
    # world), with a small absolute allowance for host noise — and an
    # order of magnitude better than the faulted window either way
    assert med(demoted_w) <= max(med(healthy_w) / 0.7,
                                 med(healthy_w) + 0.05), (
        med(demoted_w), med(healthy_w))
    assert med(demoted_w) <= 0.4 * med(fault_w), (
        med(demoted_w), med(fault_w))

    # zero lost / double-counted gradients: FINALs agree and equal the
    # float32 replay of the observed world sequence (PR-3/PR-4 style
    # integrity equivalence)
    finals = {r: _final(outs[r]) for r in (0, 1, 2)}
    for r in (0, 1, 2):
        assert finals[r][0] == 2 and finals[r][1] == "0,1,2", finals
    assert finals[0][2] == pytest.approx(finals[2][2], abs=1e-6)
    assert finals[0][2] == pytest.approx(finals[1][2], abs=1e-6)
    w = np.float32(0.0)
    for _, world, _ in _step_windows(outs[0]):
        g = (np.sum([np.float32((r + 1) ** 2) for r in world],
                    dtype=np.float32) / np.float32(len(world)))
        w = np.float32(w - np.float32(0.1) * g)
    assert finals[0][2] == pytest.approx(float(w), abs=1e-5), (
        finals[0][2], float(w))
