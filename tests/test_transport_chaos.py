"""Chaos over real sockets (ISSUE acceptance): the established lanes'
headline scenarios rerun with the TCP transport armed, at 4 real
processes each —

- ``bitflip:site=server_push`` corrupts sealed frames ON THE WIRE;
  every corruption is NACKed by the server and retransmitted from the
  sealed source copy, and the finals are bit-identical to the
  fault-free replay;
- a mid-step ``conn_reset`` on one peer is absorbed by
  reconnect + same-token retransmit with ZERO double-sums (the store
  lands on the exact expected value; the dedup counter proves the
  retries were absorbed, not re-summed);
- a ``partition`` of one rank escalates through the send-deadline /
  membership path to a shrink-and-continue instead of a hang.

Worker body: tests/transport_worker.py.
"""

from __future__ import annotations

import hashlib
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "transport_worker.py")


def _spawn(mode, rank, port, steps, extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["BYTEPS_TW_MODE"] = mode
    env["BYTEPS_TW_RANK"] = str(rank)
    env["BYTEPS_TW_PORT"] = str(port)
    env["BYTEPS_TW_STEPS"] = str(steps)
    env["BYTEPS_LOG_LEVEL"] = "ERROR"
    env.pop("BYTEPS_FAULT_SPEC", None)
    env.update(extra or {})
    return subprocess.Popen([sys.executable, WORKER], env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _communicate(procs, timeout=240):
    outs = {}
    try:
        for name, p in procs.items():
            outs[name], _ = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        for p in procs.values():
            p.kill()
        pytest.fail("transport workers hung; partial output: "
                    + "".join(o[-1500:] for o in outs.values()))
    return outs


def _line_value(out, tag, idx=-1):
    for line in out.splitlines():
        if line.startswith(tag + " "):
            return line.split()[idx]
    raise AssertionError(f"no {tag!r} line in:\n" + out[-3000:])


def _expected_bitflip_digest(steps, nworkers) -> str:
    """The fault-free replay: integer-valued grads sum EXACTLY in f32,
    so the merged round is order-independent and the worker update is
    bit-reproducible from the seeds alone."""
    from tests.transport_worker import LR, N, _grad
    params = np.zeros(N, np.float32)
    for step in range(steps):
        merged = np.sum([_grad(step, w) for w in range(nworkers)],
                        axis=0, dtype=np.float32)
        params -= LR * merged
    return hashlib.sha256(params.tobytes()).hexdigest()


@pytest.mark.chaos
@pytest.mark.integrity
def test_transport_bitflip_4proc_converges_bit_identical():
    """bitflip:site=server_push over REAL sockets: 1 server + 3 pushing
    workers; corrupted wire frames are NACKed + retransmitted and every
    worker's final parameters equal the fault-free replay bit for
    bit."""
    port = _free_port()
    steps, nworkers = 15, 3
    procs = {0: _spawn("bitflip", 0, port, steps)}
    for rank in (1, 2, 3):
        procs[rank] = _spawn(
            "bitflip", rank, port, steps,
            extra={"BYTEPS_FAULT_SPEC": "bitflip:site=server_push:p=0.08",
                   "BYTEPS_FAULT_SEED": str(100 + rank)})
    outs = _communicate(procs)
    for rank, p in procs.items():
        assert p.returncode == 0, f"rank {rank}:\n{outs[rank][-4000:]}"
    digests = {r: _line_value(outs[r], "DIGEST") for r in (1, 2, 3)}
    assert len(set(digests.values())) == 1, digests
    assert digests[1] == _expected_bitflip_digest(steps, nworkers)
    # the chaos actually ran AND was absorbed: server NACKed, workers
    # retransmitted from the sealed source copies
    rejects = int(_line_value(outs[0], "REJECTS"))
    retrans = sum(int(_line_value(outs[r], "RETRANS", idx=2))
                  for r in (1, 2, 3))
    assert rejects >= 1 and retrans >= 1, (rejects, retrans)


@pytest.mark.chaos
@pytest.mark.integrity
def test_transport_conn_reset_4proc_zero_double_sums():
    """A mid-step conn_reset storm on ONE peer: its connection is RST
    repeatedly, the supervisor reconnects, and the same-token
    retransmits are dedup-absorbed — the server's accumulator lands on
    EXACTLY 3*STEPS (one over = double-sum, one under = lost push)."""
    port = _free_port()
    steps = 20
    procs = {0: _spawn("kvreset", 0, port, steps)}
    for rank in (1, 2, 3):
        extra = {}
        if rank == 2:
            extra = {"BYTEPS_FAULT_SPEC":
                     "conn_reset:rank=2:site=transport:p=0.2",
                     "BYTEPS_FAULT_SEED": "9"}
        procs[rank] = _spawn("kvreset", rank, port, steps, extra=extra)
    outs = _communicate(procs)
    for rank, p in procs.items():
        assert p.returncode == 0, f"rank {rank}:\n{outs[rank][-4000:]}"
    assert float(_line_value(outs[0], "SUM")) == float(3 * steps)
    resets = int(_line_value(outs[2], "RESETS", idx=2))
    reconnects = int(_line_value(outs[2], "RECONNECTS", idx=2))
    assert resets >= 1 and reconnects >= 1, (resets, reconnects)
    # seq-token dedup counters prove retries were absorbed, not summed
    assert int(_line_value(outs[0], "DUP")) >= 1


@pytest.mark.chaos
def test_transport_partition_4proc_shrinks_instead_of_hanging():
    """partition:rank=2 blackholes one rank's transport: its pushes
    surface as AckLost at the send deadline (never a hang), the rank
    converts the evidence into a detected restartable failure, and the
    remaining 3-rank elastic world shrinks and finishes every step —
    finals match an exact replay of the shrunk world, and the store
    proves zero lost/double-counted survivor pushes."""
    port = _free_port()
    bus_port = _free_port()
    hb_port = _free_port()
    steps = 10
    extra_common = {
        "BYTEPS_TW_WORLD": "0,1,2,3",
        "BYTEPS_TW_BUS": f"127.0.0.1:{bus_port}",
        "BYTEPS_TW_HB_PORT": str(hb_port),
        "BYTEPS_MEMBERSHIP_RENDEZVOUS_TIMEOUT": "3",
        "BYTEPS_MEMBERSHIP_SYNC_TIMEOUT": "15",
        "BYTEPS_FAULT_SPEC": "partition:rank=2:site=transport",
        "BYTEPS_FAULT_SEED": "0",
        "BYTEPS_FAILURE_EXIT_CODE": "17",
    }
    procs = {r: _spawn("partition", r, port, steps, extra=extra_common)
             for r in range(4)}
    outs = _communicate(procs)
    # the partitioned rank DETECTED its dead data path and left
    assert procs[2].returncode == 17, outs[2][-4000:]
    assert "PARTITIONED" in outs[2], outs[2][-2000:]
    trips = int(_line_value(outs[2], "PARTITIONED"))
    assert trips >= 1   # the send deadline, not a hang, surfaced it
    # survivors shrank and finished every step
    from tests.transport_worker import _elastic_grad
    for rank in (0, 1, 3):
        assert procs[rank].returncode == 0, \
            f"rank {rank}:\n{outs[rank][-4000:]}"
        m = re.search(r"FINAL (\d+) (\S+) (\S+)", outs[rank])
        assert m, outs[rank][-2000:]
        epoch, world = int(m.group(1)), m.group(2)
        assert epoch >= 1 and world == "0,1,3", (epoch, world)
    # every step's mean was over the shrunk world {0,1,3}: replay it
    w = np.zeros(4, np.float32)
    ranks = (0, 1, 3)
    for _ in range(steps):
        g = np.sum([_elastic_grad(r) for r in ranks], axis=0,
                   dtype=np.float32) / np.float32(len(ranks))
        w = w - np.float32(0.05) * g
    finals = {r: float(re.search(r"FINAL \d+ \S+ (\S+)",
                                 outs[r]).group(1)) for r in (0, 1, 3)}
    assert all(f == float(w[0]) for f in finals.values()), \
        (finals, float(w[0]))
    # survivor pushes: one per (rank, step), retries across the world
    # change dedup-absorbed, the partitioned rank landed NOTHING
    assert float(_line_value(outs[0], "SUM")) == float(3 * steps)
