"""Dispatch-amortization tests (round-4 VERDICT task 3): drain mode
(`group_size=-1`) executes the whole eligible credit window as the fewest
XLA programs — one chunk-scatter program per contiguous buffer run, one
batched collective per run of equal-shape small tensors — with results
bit-identical to ungrouped dispatch and provably fewer dispatches.

The reference amortizes per-chunk launch overhead the same way with NCCL
group batching (nccl_manager.cc:130-134, BYTEPS_NCCL_GROUP_SIZE); here a
"group" is one jitted program instead of one ncclGroupStart/End bracket.
"""

import numpy as np
import pytest

import byteps_tpu as bps
from byteps_tpu.common import Config
from byteps_tpu.common.config import set_config
from byteps_tpu.core.engine import _plan_batch, _pow2_split
from byteps_tpu.common.types import ChunkTask
from .conftest import legacy_skip


# ---------------------------------------------------------------- planning


class _FakePending:
    def __init__(self, use_buffer):
        self.use_buffer = use_buffer


class _Arr:
    def __init__(self, shape, dtype="float32"):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.ndim = len(shape)


def _task(name, key, off=0, ln=64, pending=None, data=None, scale=None):
    t = ChunkTask(name=name, key=key, priority=0, version=0,
                  offset_elems=off, num_elems=ln, nbytes=ln * 4,
                  total_parts=1, data=data, scale=scale, pending=pending)
    return t


def test_pow2_split_widths():
    assert [len(s) for s in _pow2_split(list(range(64)))] == [64]
    assert [len(s) for s in _pow2_split(list(range(63)))] == [32, 16, 8, 4,
                                                             2, 1]
    assert _pow2_split([]) == []


def test_plan_merges_contiguous_buffer_run():
    p = _FakePending(use_buffer=True)
    batch = [_task("w", k, off=k * 64, pending=p) for k in range(8)]
    units = _plan_batch(batch)
    assert [(k, len(u)) for k, u in units] == [("run", 8)]


def test_plan_splits_noncontiguous_and_foreign_runs():
    p1, p2 = _FakePending(True), _FakePending(True)
    batch = [_task("a", 0, off=0, pending=p1),
             _task("a", 1, off=64, pending=p1),
             _task("b", 2, off=0, pending=p2),      # different tensor
             _task("a", 3, off=192, pending=p1)]    # gap: not contiguous
    units = _plan_batch(batch)
    assert [(k, len(u)) for k, u in units] == [
        ("run", 2), ("run", 1), ("run", 1)]


def test_plan_groups_equal_shape_parts_tasks():
    d = _Arr((8, 64))
    batch = [_task(f"g{i}", i, data=d, scale=0.125) for i in range(5)]
    units = _plan_batch(batch)
    assert [(k, len(u)) for k, u in units] == [("group", 5)]
    # pow2 bucketing caps the compile-cache key space in drain mode; a
    # width-1 remainder rides the single-task path (its program is
    # already cached) instead of compiling a k=1 batched program
    units = _plan_batch(batch, pow2_runs=True)
    assert [(k, len(u)) for k, u in units] == [("group", 4), ("single", 1)]


def test_plan_never_groups_incompatible_neighbors():
    batch = [_task("a", 0, data=_Arr((8, 64)), scale=0.125),
             _task("b", 1, data=_Arr((8, 32)), scale=0.125),   # shape
             _task("c", 2, data=_Arr((8, 32)), scale=None),    # scale
             _task("d", 3, data=_Arr((8, 32), "int32"))]       # dtype
    units = _plan_batch(batch)
    assert [k for k, _ in units] == ["single"] * 4


def test_plan_order_preserved_across_units():
    # priority order must survive planning: units come out in batch order
    p = _FakePending(True)
    d = _Arr((8, 16))
    batch = [_task("hi", 0, data=d, scale=None),
             _task("bulk", 1, off=0, pending=p),
             _task("bulk", 2, off=64, pending=p),
             _task("lo", 3, data=d, scale=None)]
    kinds = [(k, [t.name for t in u]) for k, u in _plan_batch(batch)]
    assert kinds == [("single", ["hi"]), ("run", ["bulk", "bulk"]),
                     ("single", ["lo"])]


# ------------------------------------------------------------- end-to-end


class _Gate:
    """Adapter from the old Event-style gate to the engine's first-class
    pause/resume hook (the one copy of the settle-the-in-flight-pop
    invariant lives in PushPullEngine.pause_dispatch)."""

    def __init__(self, eng):
        self._eng = eng

    def set(self):
        self._eng.resume_dispatch()


def _gated_engine(cfg):
    """bps session whose dispatcher is held until every push is enqueued:
    makes the drain width deterministic (everything is in the queue when
    the gate opens)."""
    set_config(cfg)
    bps.init()
    from byteps_tpu.core import api
    eng = api._engine
    eng.pause_dispatch()
    return eng, _Gate(eng)


@pytest.fixture
def no_session():
    yield
    bps.shutdown()


def test_drain_buffer_tensor_one_dispatch_bitexact(no_session):
    # 1 MiB f32 per rank / 4 KiB chunks = 256 column slabs; drain mode
    # must execute them as ONE program (256 is a power of two) and match
    # the ungrouped result bit for bit.
    rng = np.random.RandomState(7)
    x = rng.randn(8, 1 << 18).astype(np.float32)

    eng, gate = _gated_engine(Config(partition_bytes=4096, group_size=1,
                                     telemetry_on=False))
    h = eng.push_pull_async(x, "bulk", op="average")
    gate.set()
    ref = np.asarray(h.wait())
    base_stats = dict(eng.stats)
    bps.shutdown()

    eng, gate = _gated_engine(Config(partition_bytes=4096, group_size=-1,
                                     telemetry_on=False))
    h = eng.push_pull_async(x, "bulk", op="average")
    gate.set()
    out = np.asarray(h.wait())
    drain_stats = dict(eng.stats)

    np.testing.assert_array_equal(out, ref)
    assert base_stats["chunks"] == drain_stats["chunks"] == 256
    assert base_stats["dispatches"] == 256         # group_size=1: one each
    assert drain_stats["dispatches"] == 1          # one program for all 256


def test_drain_groups_small_tensors_fewer_dispatches(no_session):
    # 8 equal-shape gradients: drain mode batches them into one program
    # (pow2: exactly one for 8); results identical to sequential sync
    # pushes through an ungrouped engine.
    rng = np.random.RandomState(8)
    xs = [rng.randn(8, 300).astype(np.float32) for _ in range(8)]

    set_config(Config(group_size=1, telemetry_on=False))
    bps.init()
    ref = [np.asarray(bps.push_pull(x, f"g{i}", op="average"))
           for i, x in enumerate(xs)]
    bps.shutdown()

    eng, gate = _gated_engine(Config(group_size=-1, telemetry_on=False))
    handles = [eng.push_pull_async(x, f"g{i}", op="average")
               for i, x in enumerate(xs)]
    gate.set()
    outs = [np.asarray(h.wait()) for h in handles]
    stats = dict(eng.stats)

    for o, r in zip(outs, ref):
        np.testing.assert_array_equal(o, r)
    assert stats["chunks"] == 8
    assert stats["dispatches"] == 1


def test_drain_groups_bitexact_on_dcn_mesh(no_session, monkeypatch):
    # code-review r5: on a (dcn=2, ici=4) mesh a single dispatch reduces
    # hierarchically (RS over ICI + psum over DCN); the batched group
    # program must use the SAME body, or grouping — a timing-dependent
    # decision — would change summation order and break bitwise
    # reproducibility between steps.
    monkeypatch.setenv("BYTEPS_DCN_SIZE", "2")
    rng = np.random.RandomState(9)
    xs = [rng.randn(8, 300).astype(np.float32) for _ in range(4)]

    set_config(Config(group_size=1, telemetry_on=False))
    bps.init()
    ref = [np.asarray(bps.push_pull(x, f"g{i}", op="average"))
           for i, x in enumerate(xs)]
    bps.shutdown()

    eng, gate = _gated_engine(Config(group_size=-1, telemetry_on=False))
    assert eng.comm.n_dcn == 2
    handles = [eng.push_pull_async(x, f"g{i}", op="average")
               for i, x in enumerate(xs)]
    gate.set()
    outs = [np.asarray(h.wait()) for h in handles]
    assert eng.stats["dispatches"] == 1 and eng.stats["chunks"] == 4
    for o, r in zip(outs, ref):
        np.testing.assert_array_equal(o, r)


def test_drain_mixed_dtypes_and_ints_still_exact(no_session):
    # int chunks keep the assembly // semantics through the batched path
    xs = {"f": np.random.RandomState(0).randn(8, 100).astype(np.float32),
          "i": np.arange(8 * 40, dtype=np.int32).reshape(8, 40),
          "h": np.random.RandomState(1).randn(8, 100).astype(np.float16)}
    set_config(Config(group_size=1, telemetry_on=False))
    bps.init()
    ref = {n: np.asarray(bps.push_pull(x, n, op="average"))
           for n, x in xs.items()}
    bps.shutdown()

    eng, gate = _gated_engine(Config(group_size=-1, telemetry_on=False))
    hs = {n: eng.push_pull_async(x, n, op="average") for n, x in xs.items()}
    gate.set()
    for n, h in hs.items():
        np.testing.assert_array_equal(np.asarray(h.wait()), ref[n])
        assert np.asarray(h.wait()).dtype == xs[n].dtype


@legacy_skip  # old XLA does not combine the k all-reduces into one
def test_batched_program_is_one_module_with_combined_collective():
    # Wire-level proof of "one dispatch executes k chunks": the batched
    # program compiles to ONE XLA module, and XLA's all-reduce combiner
    # merges the k psums into a single variadic all-reduce over a
    # k-tuple — strictly fewer wire operations than k single dispatches,
    # exactly the effect the reference buys with ncclGroupStart/End.
    import jax
    import jax.numpy as jnp

    from byteps_tpu.comm.collectives import _batched_all_reduce_fn
    from byteps_tpu.comm.mesh import CommContext, _build_mesh

    k, n = 4, 256
    comm = CommContext(mesh=_build_mesh(jax.devices()[:8], 1),
                       n_dcn=1, n_ici=8)
    fn = _batched_all_reduce_fn(comm, k, (8, n), jnp.float32,
                                scaled=True, local=False)
    xs = [jax.device_put(jnp.zeros((8, n), jnp.float32),
                         comm.stacked_sharding(extra_dims=1))
          for _ in range(k)]
    hlo = fn.lower(*xs, jnp.float32(0.125)).compile().as_text()
    ars = [ln for ln in hlo.splitlines()
           if "all-reduce(" in ln and "=" in ln
           and "get-tuple-element" not in ln]
    # Exactly ONE variadic all-reduce whose tuple result carries all k
    # chunks — the wire property docs/performance.md cites.  If an XLA
    # upgrade stops combining here, this fails as a canary: the batched
    # path would still be one dispatch but k wire ops, and the doc's
    # claim must be re-measured, not assumed.
    assert len(ars) == 1, ars
    assert ars[0].count(f"f32[{n}]") >= k, ars
