"""3D (dp, pp, tp) composite parallelism (parallel/three_d.py).

The oracle is the same as the pp and tp tests use individually: training
from restacked + sharded parameters must match plain single-device GPT
training step for step.  Layout assertions confirm tp actually shards
the block weights (this is a composition test, not just a numerics
test).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.models.gpt import GPT, GPTConfig, lm_loss
from byteps_tpu.parallel.long_context import synthetic_lm_batch
from byteps_tpu.parallel.pipeline import (init_pipeline_params,
                                          pipeline_params_to_gpt)
from byteps_tpu.parallel.three_d import (init_3d_opt_state, make_3d_mesh,
                                         make_dp_pp_tp_train_step,
                                         shard_3d_batch, shard_3d_params)
from .conftest import legacy_skip


def _cfg(num_layers=4):
    return GPTConfig(vocab_size=128, hidden_size=32, num_layers=num_layers,
                     num_heads=4, intermediate_size=64, max_position=64,
                     dtype=jnp.float32)


@pytest.mark.parametrize("n_pp,n_tp,microbatches", [(2, 2, 2), (2, 4, 4),
                                                    (4, 2, 2)])
@legacy_skip  # exact-match numerics diverge on pre-VMA shard_map
def test_3d_training_matches_single_device(n_pp, n_tp, microbatches):
    cfg = _cfg(num_layers=4)
    rng = jax.random.PRNGKey(1)
    batch = synthetic_lm_batch(rng, cfg, batch=16, seq_len=16)
    pp_params = init_pipeline_params(cfg, rng, batch["input_ids"][:1])
    gpt_vars = pipeline_params_to_gpt(cfg, pp_params)
    tx = optax.sgd(0.1)
    model = GPT(cfg)

    @jax.jit
    def ref_step(p, o, b):
        loss, g = jax.value_and_grad(
            lambda q: lm_loss(model.apply(q, b["input_ids"]),
                              b["labels"]))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    p_ref, o_ref = gpt_vars, tx.init(gpt_vars)
    for _ in range(3):
        p_ref, o_ref, loss_ref = ref_step(p_ref, o_ref, batch)

    mesh = make_3d_mesh(jax.devices()[:8], n_pp=n_pp, n_tp=n_tp)
    p3 = shard_3d_params(mesh, pp_params)
    o3 = init_3d_opt_state(tx, p3)
    step = make_dp_pp_tp_train_step(mesh, cfg, tx,
                                    num_microbatches=microbatches)
    b3 = shard_3d_batch(mesh, batch)
    for _ in range(3):
        p3, o3, loss_3d = step(p3, o3, b3)

    np.testing.assert_allclose(float(loss_3d), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    got = pipeline_params_to_gpt(cfg, jax.device_get(p3))
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(got),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(p_ref),
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=str(ka))


def test_3d_layout():
    """Blocks are sharded over BOTH pp (layer axis) and tp (inner dims);
    opt-state moments inherit the layout instead of replicating."""
    cfg = _cfg(num_layers=4)
    rng = jax.random.PRNGKey(0)
    ids = jnp.zeros((1, 8), jnp.int32)
    pp_params = init_pipeline_params(cfg, rng, ids)
    mesh = make_3d_mesh(jax.devices()[:8], n_pp=2, n_tp=2)
    p3 = shard_3d_params(mesh, pp_params)

    qkv = p3["blocks"]["attn"]["qkv"]["kernel"]  # [L, h, 3, heads, hd]
    local = qkv.addressable_shards[0].data.shape
    assert local[0] == cfg.num_layers // 2          # pp shards layers
    assert local[3] == cfg.num_heads // 2           # tp shards heads
    wte = p3["embed"]["wte"]["embedding"]
    assert wte.addressable_shards[0].data.shape[0] == cfg.vocab_size // 2

    tx = optax.adam(1e-3)
    o3 = init_3d_opt_state(tx, p3)
    mu_qkv = o3[0].mu["blocks"]["attn"]["qkv"]["kernel"]
    assert mu_qkv.addressable_shards[0].data.shape == local


def test_pp_step_body_reuse_unchanged():
    """The (dp, pp) path still trains after the body extraction."""
    import byteps_tpu.parallel as par
    cfg = _cfg(num_layers=2)
    rng = jax.random.PRNGKey(3)
    batch = synthetic_lm_batch(rng, cfg, batch=8, seq_len=16)
    pp_params = init_pipeline_params(cfg, rng, batch["input_ids"][:1])
    mesh = par.make_pp_mesh(jax.devices()[:8], n_pp=2)
    p = par.shard_pipeline_params(mesh, pp_params)
    o = jax.jit(optax.sgd(0.1).init)(p)
    step = par.make_dp_pp_train_step(mesh, cfg, optax.sgd(0.1),
                                     num_microbatches=2)
    p, o, loss = step(p, o, par.shard_pp_batch(mesh, batch))
    assert np.isfinite(float(loss))


@legacy_skip  # repro subprocess uses bare jax.shard_map
def test_bf16_partial_manual_psum_canary():
    """Canary for the XLA CPU bug that forces f32 on the 3D path.

    Minimal repro (isolated in a subprocess — the failure mode is a
    process-killing compiler CHECK, "Invalid binary instruction opcode
    copy"): a bf16 psum inside a partial-manual shard_map.  While the
    bug exists, the subprocess dies and three_d.py's f32-on-CPU gating
    stays justified.  When an XLA upgrade fixes it, this test FAILS —
    that is the signal to drop the f32 gating and this canary together.
    """
    import subprocess
    import sys

    code = r"""
import os
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
import jax
jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
mesh = Mesh(devs, ("dp", "pp", "tp"))
w = jax.device_put(jnp.zeros((16, 16), jnp.bfloat16),
                   NamedSharding(mesh, P(None, None)))
x = jax.device_put(jnp.zeros((4, 16), jnp.bfloat16),
                   NamedSharding(mesh, P("dp", None)))
def body(x, w):
    g = jax.grad(lambda w: jnp.sum((x @ w).astype(jnp.float32)))(w)
    return lax.psum(g, ("dp", "pp"))
f = jax.jit(jax.shard_map(body, mesh=mesh,
                          in_specs=(P("dp", None), P(None, None)),
                          out_specs=P(None, None),
                          axis_names={"dp", "pp"}, check_vma=False))
f(x, w).block_until_ready()
print("BF16_PARTIAL_MANUAL_OK")
"""
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300)
    if "BF16_PARTIAL_MANUAL_OK" in p.stdout:
        raise AssertionError(
            "XLA now compiles bf16 psum under partial-manual shard_map — "
            "remove the f32-on-CPU gating in parallel/three_d.py and this "
            "canary")
    # It must die with THE documented CHECK — any other failure (renamed
    # jax API, import error) means the canary no longer tests the bug.
    assert p.returncode != 0
    assert "Invalid binary instruction opcode copy" in (p.stderr or ""), (
        "repro subprocess failed for a different reason:\n"
        + (p.stderr or "")[-800:])
