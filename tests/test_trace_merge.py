"""ISSUE 12 — cluster-wide causal tracing: flow arcs, merged timelines,
clock alignment, and critical-path step attribution.

Covers: tools/bps_trace.py merge/validate semantics on synthetic and
real trace files; the engine's per-push flow arcs under the sampled
stream; the server engine's push→merge arc; the membership bus closing
each member's step-barrier arc; bus-driven clock-offset estimation; the
step.attrib_* breakdown (components sum to the step wall — the
acceptance bound); and the 3-process acceptance run where one merged
timeline carries cross-process flows with clock-aligned timestamps.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import byteps_tpu as bps  # noqa: E402
from byteps_tpu.common import tracing  # noqa: E402
from byteps_tpu.common.config import Config, set_config  # noqa: E402
from byteps_tpu.common.tracing import Tracer  # noqa: E402
from tools import bps_trace  # noqa: E402

from .conftest import free_port  # noqa: E402

WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


def _doc(rank, pid, events, wall=1000.0, mono=50.0, offset=None, err=0.001):
    return {"traceEvents": events, "rank": rank, "pid": pid,
            "monoAnchor": {"wall": wall, "mono": mono},
            "clockSync": {"offset_s": offset, "err_s": err,
                          "source": "test"},
            "droppedEvents": 0, "_path": f"mem://{rank}"}


def _span(name, ts_s, dur_s, pid, tid="t"):
    return {"name": name, "cat": "comm", "ph": "X", "ts": ts_s * 1e6,
            "dur": dur_s * 1e6, "pid": pid, "tid": tid, "args": {}}


def _flow(ph, fid, ts_s, pid, tid="t"):
    ev = {"name": tracing.FLOW_NAME, "cat": tracing.FLOW_CAT, "ph": ph,
          "id": fid, "ts": ts_s * 1e6, "pid": pid, "tid": tid}
    if ph == "f":
        ev["bp"] = "e"
    return ev


# -- merge + validate on synthetic files -------------------------------------


def test_merge_aligns_offset_clocks():
    # rank 1's wall clock runs 2.0s AHEAD of the coordinator's; its
    # event at mono 50.5 is wall 3000.5 locally = 2998.5 coordinator
    # time.  rank 0 (offset 0) has an event at coordinator 1000.25.
    d0 = _doc(0, 100, [_span("a", 50.25, 0.1, 100)],
              wall=1000.0, mono=50.0, offset=0.0)
    d1 = _doc(1, 200, [_span("b", 50.5, 0.1, 200)],
              wall=3000.0, mono=50.0, offset=2.0)
    merged = bps_trace.merge([d0, d1])
    spans = {e["name"]: e for e in merged["traceEvents"]
             if e.get("ph") == "X"}
    # aligned: a at 1000.25, b at 2998.5 -> origin at a, b 1998.25s later
    assert spans["a"]["ts"] == pytest.approx(0.0, abs=1.0)
    assert spans["b"]["ts"] - spans["a"]["ts"] == pytest.approx(
        1998.25 * 1e6, rel=1e-9)
    names = [e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    assert "rank 0 (pid 100)" in names and "rank 1 (pid 200)" in names


def test_validate_passes_clean_cross_process_flow():
    fid = tracing._new_flow_id(3)
    d0 = _doc(0, 100, [_span("push", 50.0, 0.5, 100),
                       _flow("s", fid, 50.1, 100)], offset=0.0)
    d1 = _doc(1, 200, [_span("merge", 50.3, 0.5, 200),
                       _flow("f", fid, 50.6, 200)], offset=0.0)
    merged = bps_trace.merge([d0, d1])
    assert bps_trace.validate(merged) == []
    summary = bps_trace.summarize(merged)
    assert summary["cross_process_arcs"] == 1


def test_validate_flags_orphan_s_and_backwards_flow():
    fid = 7
    d0 = _doc(0, 100, [_flow("s", fid, 50.5, 100)], offset=0.0)
    merged = bps_trace.merge([d0])
    errs = bps_trace.validate(merged)
    assert any("no matching f" in e for e in errs)
    # a flow whose f lands BEFORE its s beyond the clock-error budget
    d1 = _doc(0, 100, [_flow("s", 9, 55.0, 100),
                       _flow("f", 9, 50.0, 100)], offset=0.0)
    errs = bps_trace.validate(bps_trace.merge([d1]))
    assert any("runs backwards" in e for e in errs)


def test_validate_warns_not_fails_orphan_f(capsys):
    d0 = _doc(0, 100, [_flow("f", 11, 50.0, 100)], offset=0.0)
    assert bps_trace.validate(bps_trace.merge([d0])) == []
    assert "has no s" in capsys.readouterr().err


# -- engine: sampled per-push arcs -------------------------------------------


def test_engine_sampled_push_flows_merge_and_validate(tmp_path):
    set_config(Config(trace_sample="1/1", trace_dir=str(tmp_path)))
    bps.init()
    try:
        eng = bps.core.api._require()
        assert eng.tracer is tracing.tracer()
        for i in range(4):
            eng.push_pull_local(
                np.full(2048, float(i + 1), np.float32), "g", op="sum")
        path = eng.tracer.flush()
    finally:
        bps.shutdown()
    assert path is not None
    docs = bps_trace.load_trace_files(str(tmp_path))
    assert len(docs) == 1
    merged = bps_trace.merge(docs)
    assert bps_trace.validate(merged) == []
    evs = merged["traceEvents"]
    spans = [e for e in evs if e.get("ph") == "X"]
    assert {"queued", "push_pull"} <= {e["name"] for e in spans}
    # every captured push opened AND closed its arc
    s_ids = {e["id"] for e in evs if e.get("ph") == "s"}
    f_ids = {e["id"] for e in evs if e.get("ph") == "f"}
    assert len(s_ids) == 4 and s_ids == f_ids
    # spans carry the trace id for searchability
    assert all(e["args"].get("trace_id") for e in spans
               if e["name"] in ("queued", "push_pull"))


def test_engine_sample_1_in_n_thins_the_stream(tmp_path):
    set_config(Config(trace_sample="1/4", trace_dir=str(tmp_path)))
    bps.init()
    try:
        eng = bps.core.api._require()
        for i in range(8):
            eng.push_pull_local(np.ones(512, np.float32), "g", op="sum")
        eng.tracer.flush()
    finally:
        bps.shutdown()
    doc = json.load(open(os.path.join(
        str(tmp_path), f"bps_trace_rank0_{os.getpid()}.json")))
    s_ids = {e["id"] for e in doc["traceEvents"] if e.get("ph") == "s"}
    assert len(s_ids) == 2               # 8 pushes at 1/4


# -- server engine: push -> merge arc ----------------------------------------


def test_server_engine_push_closes_flow_on_merge_thread(tmp_path):
    from byteps_tpu.server.engine import ServerEngine
    tr = tracing.set_tracer(Tracer(enabled=False, sample_n=1,
                                   out_dir=str(tmp_path)))
    srv = ServerEngine(num_threads=1)
    try:
        srv.push("k", np.ones(64, np.float32), 0, 1)
        out = srv.pull("k", timeout=10)
        assert float(out[0]) == 1.0
    finally:
        srv.shutdown()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        evs = tr._events
        if any(e.get("ph") == "f" for e in evs):
            break
        time.sleep(0.02)
    names = {e["name"] for e in tr._events if e.get("ph") == "X"}
    assert {"server.push", "server.merge"} <= names
    s = [e for e in tr._events if e.get("ph") == "s"]
    f = [e for e in tr._events if e.get("ph") == "f"]
    assert len(s) == 1 and len(f) == 1 and s[0]["id"] == f[0]["id"]


# -- membership bus: barrier arcs + clock sync -------------------------------


def test_bus_barrier_closes_member_flows(tmp_path):
    from byteps_tpu.fault.membership import MembershipView, _BusServer
    from byteps_tpu.fault.membership import bus_request
    tr = tracing.set_tracer(Tracer(enabled=False, sample_n=1,
                                   out_dir=str(tmp_path)))
    port = free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=10.0)
    try:
        fids = {0: tracing._new_flow_id(0), 1: tracing._new_flow_id(1)}
        out = {}

        def member(r):
            out[r] = bus_request(
                ("127.0.0.1", port),
                {"op": "sync", "rank": r, "epoch": 0, "step": 1,
                 "payload": r, "trace": fids[r]}, timeout=15.0)

        ts = [threading.Thread(target=member, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=20)
        assert out[0]["ok"] and out[1]["ok"]
    finally:
        bus.close()
    evs = tr._events
    barrier = [e for e in evs if e.get("name") == "bus.step_barrier"]
    assert len(barrier) == 1
    assert barrier[0]["args"]["ranks"] == [0, 1]
    closes = {e["id"] for e in evs if e.get("ph") == "f"}
    assert closes == set(fids.values())


def test_elastic_step_sync_emits_member_side_flow(tmp_path):
    from byteps_tpu.fault.membership import ElasticMembership
    set_config(Config(trace_sample="1/1", trace_dir=str(tmp_path)))
    tracing._reset_for_tests()
    port = free_port()
    m = ElasticMembership(0, [0], f"127.0.0.1:{port}").start()
    try:
        m.step_sync(1)
    finally:
        m.stop()
    tr = tracing.tracer()
    evs = tr._events
    sync_spans = [e for e in evs
                  if e.get("name") == "membership.step_sync"]
    assert len(sync_spans) == 1
    s = [e for e in evs if e.get("ph") == "s"
         and e.get("tid") == "membership"]
    f = [e for e in evs if e.get("ph") == "f"]
    assert len(s) == 1
    assert s[0]["id"] in {e["id"] for e in f}   # bus closed the arc
    # single-host bus: the clock offset estimate ran and is near zero
    clock = tracing.clock_offset()
    assert clock["offset_s"] is not None
    assert abs(clock["offset_s"]) < 0.5


def test_estimate_clock_offset_against_live_bus():
    from byteps_tpu.fault.membership import (MembershipView, _BusServer,
                                             estimate_clock_offset)
    port = free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0,)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=5.0)
    try:
        est = estimate_clock_offset(("127.0.0.1", port), samples=4)
    finally:
        bus.close()
    assert est is not None
    offset, err = est
    assert abs(offset) < 0.5 and 0 <= err < 0.5   # same host, same clock
    assert tracing.clock_offset()["offset_s"] == pytest.approx(offset)


# -- step attribution --------------------------------------------------------


@pytest.mark.chaos
def test_attrib_components_sum_to_step_wall():
    """The ISSUE 12 acceptance bound: on a comm-bound synchronous loop
    the per-step attribution components (queue + dispatch + sync +
    assemble + ...) account for the measured step wall time to within
    15% — 'other' (compute/host residual) tops the breakdown up to at
    least the wall by construction.

    The partition is PINNED to one chunk per push: components are
    wall-time integrals of each activity, so pipelined multi-chunk
    units (or a planner exploring mid-test) legitimately overlap and
    the sum can exceed the wall — the serialized profile is where the
    sum-to-wall reading is exact."""
    set_config(Config(telemetry_on=True, partition_bytes=32 << 20))
    bps.init()
    try:
        eng = bps.core.api._require()
        # 16 MiB single chunk: per-step wall ~40ms, so fixed per-push
        # host overheads and cross-thread wake latencies (the 'other'
        # residual — they balloon on a loaded CI host mid-suite)
        # amortize well below the 15% budget
        x = np.random.RandomState(0).randn(1 << 22).astype(np.float32)
        eng.declare_tensor("att.g", x.shape, np.float32)
        for _ in range(3):               # warm: compile out of the way
            eng.push_pull_local(x, "att.g")
        for _ in range(8):
            eng.push_pull_local(x, "att.g")
        eng.step_stats.flush()
        hist = eng.step_stats.history()
    finally:
        bps.shutdown()
    steady = [s for s in hist if s.step > 4 and s.attrib
              and "compile" not in s.attrib]   # a late stray compile
    assert steady, hist
    # construction invariant: components + other >= wall (other only
    # clamps at zero when overlapping activities exceed the wall)
    for s in steady:
        total = sum(s.attrib.values())
        assert total >= s.wall_ms * 0.98 - 0.5, s
    # acceptance: measured components cover >= 85% of the wall on the
    # comm-bound loop (median over steady steps — single-step scheduler
    # hiccups land in 'other' and must not fail the bound; coverage is
    # capped at 100%, overlap cannot overstate it)
    shares = sorted(
        min(sum(v for k, v in s.attrib.items() if k != "other"),
            s.wall_ms) / s.wall_ms
        for s in steady)
    med = shares[len(shares) // 2]
    assert med >= 0.85, (med, [s.attrib for s in steady])


def test_step_attrib_gauges_lagging_tensor_and_flight_stamp():
    from byteps_tpu.common import flight_recorder as _flight
    from byteps_tpu.common.telemetry import gauges
    set_config(Config(telemetry_on=True, trace_sample="1/1"))
    tracing._reset_for_tests()
    bps.init()
    try:
        eng = bps.core.api._require()
        for _ in range(3):
            eng.push_pull_local(np.ones(4096, np.float32), "lag.g")
        done = eng.step_stats.flush()
    finally:
        bps.shutdown()
    assert done is not None and done.lagging_tensor == "lag.g"
    snap = gauges.snapshot()
    assert snap.get("step.attrib_sync_ms") is not None
    assert snap.get("step.attrib_other_ms") is not None
    # flight events: step_stats carries the breakdown + lagging tensor
    # + rank, and ordinary events are stamped with (step, trace_id)
    evs = _flight.recorder.snapshot()
    ss = [e for e in evs if e["kind"] == "step_stats"]
    assert ss and ss[-1]["lagging_tensor"] == "lag.g"
    assert ss[-1]["rank"] == 0 and ss[-1]["attrib"]
    stamped = [e for e in evs if e.get("trace_id")]
    assert stamped, "no flight event carried a trace_id stamp"
    assert any(e.get("step") for e in evs)


def test_metrics_snapshot_and_debug_state_carry_attrib_and_trace():
    from byteps_tpu.common.obs_server import debug_state
    set_config(Config(telemetry_on=True))
    bps.init()
    try:
        eng = bps.core.api._require()
        for _ in range(2):
            eng.push_pull_local(np.ones(1024, np.float32), "d.g")
        eng.step_stats.flush()
        snap = bps.metrics_snapshot()
        doc = debug_state()
    finally:
        bps.shutdown()
    assert snap["step"]["attrib"]
    assert "sync" in snap["step"]["attrib"]
    trace = doc["trace"]
    assert {"enabled", "sample_n", "active", "events_dropped",
            "clock"} <= set(trace)


def test_bps_top_attrib_cell_and_column():
    from tools import bps_top
    step = {"step": 4, "wall_ms": 100.0, "sync_stall_ms": 60.0,
            "attrib": {"sync": 60.0, "queue": 10.0, "other": 30.0}}
    assert bps_top._attrib_cell(step) == "sync:60%"
    assert bps_top._attrib_cell({}) == "-"
    assert bps_top._attrib_cell({"wall_ms": 10.0,
                                 "attrib": {"other": 10.0}}) == "other:100%"
    cluster = {"epoch": 0, "world": [0], "ranks": {
        0: {"age_s": 0.1, "metrics": {"epoch": 0, "step": step}}}}
    text = bps_top.render(cluster)
    assert "ATTRIB" in text and "sync:60%" in text


def test_bench_smoke_trace_gate_arithmetic():
    from tools import bench_smoke as bs
    floor = json.load(open(bs.FLOOR_PATH))
    assert 0 < floor["trace_sample_overhead_floor"] <= 1
    good = {"sample_n": 4, "overhead_ratio": 0.95, "events_buffered": 12,
            "events_dropped": 0}
    assert bs._trace_ok(good, floor, 0.3)
    slow = dict(good, overhead_ratio=0.2)
    assert not bs._trace_ok(slow, floor, 0.3)
    dead = dict(good, events_buffered=0)   # 1.0 ratio but traced nothing
    assert not bs._trace_ok(dead, floor, 0.3)


# -- the 3-process acceptance run --------------------------------------------


def _spawn_trace_worker(rank, bus_port, steps, trace_dir):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["DMLC_NUM_WORKER"] = "1"
    env["DMLC_WORKER_ID"] = str(rank)
    env["BYTEPS_ELASTIC_RANK"] = str(rank)
    env["BYTEPS_ELASTIC_WORLD"] = "0,1,2"
    env["BYTEPS_ELASTIC_BUS"] = f"127.0.0.1:{bus_port}"
    env["BYTEPS_ELASTIC_STEPS"] = str(steps)
    env["BYTEPS_ELASTIC_STEP_SLEEP"] = "0.05"
    env["BYTEPS_MEMBERSHIP_RENDEZVOUS_TIMEOUT"] = "3"
    env["BYTEPS_MEMBERSHIP_SYNC_TIMEOUT"] = "20"
    env["BYTEPS_LOG_LEVEL"] = "ERROR"
    env["BYTEPS_TRACE_SAMPLE"] = "1/1"     # capture every push/barrier
    env["BYTEPS_TRACE_DIR"] = str(trace_dir)
    env["BYTEPS_FLIGHT_DIR"] = str(trace_dir)
    env.pop("BYTEPS_FAULT_SPEC", None)
    env.pop("BYTEPS_ELASTIC_REJOIN", None)
    env.pop("BYTEPS_ELASTIC_HB_PORT", None)
    env.pop("BYTEPS_TRACE_ON", None)
    return subprocess.Popen([sys.executable, WORKER], env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


@pytest.mark.chaos
def test_trace_3proc_merged_timeline_cross_rank_flows(tmp_path):
    """The ISSUE 12 acceptance pin: a REAL 3-process run with
    BYTEPS_TRACE_SAMPLE armed yields per-rank trace files that
    bps_trace.py merges into ONE clock-aligned timeline that validates
    clean — every flow ``s`` paired with its ``f`` — and the
    step-barrier arcs genuinely CROSS process boundaries (each member's
    ``s`` binds to the coordinator bus's ``f``)."""
    steps = 6
    bus_port = free_port()
    procs = {r: _spawn_trace_worker(r, bus_port, steps, tmp_path)
             for r in (0, 1, 2)}
    outs = {}
    try:
        for r, p in procs.items():
            out, _ = p.communicate(timeout=180)
            outs[r] = out
            assert p.returncode == 0, (r, out[-2000:])
            assert "FINAL 0 0,1,2" in out, (r, out[-2000:])
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
    docs = bps_trace.load_trace_files(str(tmp_path))
    assert len(docs) == 3, [d["_path"] for d in docs]
    assert sorted(d["rank"] for d in docs) == [0, 1, 2]
    # every rank estimated its clock offset against the coordinator bus
    for d in docs:
        assert d["clockSync"]["offset_s"] is not None, d["_path"]
    merged = bps_trace.merge(docs)
    errors = bps_trace.validate(merged)
    assert errors == [], errors[:10]
    summary = bps_trace.summarize(merged)
    # cross-PROCESS arcs: members' step_sync `s` flows close at the
    # coordinator's bus.step_barrier `f` — ranks 1 and 2 each ran
    # `steps` barriers against rank 0's bus
    assert summary["cross_process_arcs"] >= steps, summary
    # the barrier spans live on the coordinator, the member spans on
    # every rank's own timeline
    names = {(e.get("pid"), e.get("name"))
             for e in merged["traceEvents"] if e.get("ph") == "X"}
    barrier_pids = {p for p, n in names if n == "bus.step_barrier"}
    sync_pids = {p for p, n in names if n == "membership.step_sync"}
    assert len(barrier_pids) == 1
    assert len(sync_pids) == 3
    # engine pushes were captured per rank too (sampled stream)
    push_pids = {p for p, n in names if n == "push_pull"}
    assert len(push_pids) == 3
