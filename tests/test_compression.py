"""Compression engine tests.

Strategy copied from the reference (SURVEY.md §4): every compressor's full
worker->server->worker pipeline is replicated in pure numpy
(tests/compression_refs.py) and the two implementations must agree — on the
PRNG bit-for-bit, on indices/codes exactly, on floats to tolerance — over
multiple state-evolving steps (the reference bit-matches parameter evolution
over real training iterations, test_onebit.py:32-113)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import byteps_tpu as bps
from byteps_tpu.compression import create as create_compressor
from byteps_tpu.compression.prng import uniform, uniform_np

from . import compression_refs as refs


# --- PRNG parity -----------------------------------------------------------

@pytest.mark.parametrize("seed,counter,n", [(0, 0, 97), (7, 1000, 256),
                                            (123456, 2**31, 64)])
def test_prng_jax_matches_numpy(seed, counter, n):
    a = np.asarray(uniform(seed, counter, n))
    b = uniform_np(seed, counter, n)
    np.testing.assert_array_equal(a, b)
    assert (b >= 0).all() and (b < 1).all()
    # counter advance produces a different draw
    c = uniform_np(seed, counter + n, n)
    assert not np.array_equal(b, c)


# --- single-compressor parity ---------------------------------------------

def _x(n=1000, seed=0):
    return np.random.RandomState(seed).randn(n).astype(np.float32)


@pytest.mark.parametrize("scaling", [True, False])
def test_onebit_matches_ref(scaling):
    x = _x()
    comp = create_compressor({"compressor": "onebit",
                              "scaling": str(scaling)}, len(x))
    payload, _ = comp.compress(jnp.asarray(x), comp.init_state())
    ref_words, ref_scale = refs.onebit_compress(x, scaling)
    np.testing.assert_array_equal(np.asarray(payload["words"]), ref_words)
    np.testing.assert_allclose(float(payload["scale"]), ref_scale, rtol=1e-6)
    out = np.asarray(comp.decompress(payload))
    ref_out = refs.onebit_decompress(ref_words, ref_scale, len(x))
    np.testing.assert_allclose(out, ref_out, rtol=1e-6)
    # every output is +-scale, sign matching input sign
    np.testing.assert_array_equal(np.sign(out),
                                  np.where(x >= 0, 1.0, -1.0))


def test_topk_matches_ref():
    x = _x()
    comp = create_compressor({"compressor": "topk", "k": "50"}, len(x))
    payload, _ = comp.compress(jnp.asarray(x), comp.init_state())
    ref_idx, ref_vals = refs.topk_compress(x, 50)
    np.testing.assert_array_equal(np.sort(np.asarray(payload["indices"])),
                                  np.sort(ref_idx))
    out = np.asarray(comp.decompress(payload))
    np.testing.assert_allclose(out, refs.sparse_decompress(ref_idx, ref_vals,
                                                           len(x)),
                               rtol=1e-6)


def test_topk_fractional_k():
    comp = create_compressor({"compressor": "topk", "k": "0.05"}, 1000)
    assert comp.k == 50


def test_randomk_matches_ref_and_advances():
    x = _x()
    comp = create_compressor({"compressor": "randomk", "k": "80",
                              "seed": "42"}, len(x))
    state = comp.init_state()
    p1, state = comp.compress(jnp.asarray(x), state)
    ref_idx1, ref_vals1, counter = refs.randomk_compress(x, 80, 42, 0)
    np.testing.assert_array_equal(np.asarray(p1["indices"]), ref_idx1)
    np.testing.assert_allclose(np.asarray(p1["values"]), ref_vals1, rtol=1e-6)
    # second step uses fresh indices, still matching the numpy stream
    p2, state = comp.compress(jnp.asarray(x), state)
    ref_idx2, _, _ = refs.randomk_compress(x, 80, 42, counter)
    np.testing.assert_array_equal(np.asarray(p2["indices"]), ref_idx2)
    assert not np.array_equal(ref_idx1, ref_idx2)


@pytest.mark.parametrize("partition", ["linear", "natural"])
@pytest.mark.parametrize("normalize", ["max", "l2"])
def test_dithering_matches_ref(partition, normalize):
    x = _x()
    kw = {"compressor": "dithering", "partition_num": "16",
          "partition": partition, "normalize": normalize, "seed": "3"}
    comp = create_compressor(kw, len(x))
    state = comp.init_state()
    payload, state = comp.compress(jnp.asarray(x), state)
    ref_codes, ref_norm, _ = refs.dithering_compress(
        x, 16, partition, normalize, 3, 0)
    np.testing.assert_array_equal(np.asarray(payload["codes"]), ref_codes)
    np.testing.assert_allclose(float(payload["norm"]), ref_norm, rtol=1e-6)
    out = np.asarray(comp.decompress(payload))
    ref_out = refs.dithering_decompress(ref_codes, ref_norm, 16, partition)
    np.testing.assert_allclose(out, ref_out, rtol=1e-5, atol=1e-7)


def test_dithering_sparse_matches_dense_when_capacity_covers():
    # sparse posterior: most elements quantize to code 0, so the sparse
    # (index, level) layout must reproduce the dense decode exactly
    rng = np.random.RandomState(12)
    x = np.zeros(2000, np.float32)
    hot = rng.choice(2000, 60, replace=False)
    x[hot] = rng.randn(60).astype(np.float32) * 5
    base_kw = {"compressor": "dithering", "partition_num": "16", "seed": "3"}
    dense = create_compressor(base_kw, len(x))
    sparse = create_compressor({**base_kw, "sparse_ratio": "0.05"}, len(x))
    pd, _ = dense.compress(jnp.asarray(x), dense.init_state())
    ps, _ = sparse.compress(jnp.asarray(x), sparse.init_state())
    np.testing.assert_allclose(np.asarray(sparse.decompress(ps)),
                               np.asarray(dense.decompress(pd)),
                               rtol=1e-6, atol=0)
    # wire accounting (VERDICT r1 item 8): k=100 pairs of (uint16, int8)
    # + norm = 304 B vs 2004 B dense — a measured 6.6x ratio
    assert sparse.payload_nbytes() == 100 * 3 + 4
    assert dense.payload_nbytes() == 2000 + 4
    assert sparse.payload_nbytes() * 6 < dense.payload_nbytes()


def test_dithering_sparse_overflow_keeps_largest():
    # more nonzeros than capacity: the k largest-|code| entries survive
    x = np.linspace(1.0, 2.0, 64).astype(np.float32)
    comp = create_compressor({"compressor": "dithering",
                              "partition_num": "16", "seed": "0",
                              "sparse_ratio": str(16 / 64)}, len(x))
    payload, _ = comp.compress(jnp.asarray(x), comp.init_state())
    out = np.asarray(comp.decompress(payload))
    assert np.count_nonzero(out) <= 16
    # the largest input (u = 1.0 -> top level) is always kept
    assert out[-1] > 0


def test_dithering_sparse_engine_pipeline(session):
    # full worker->merge->server cycle through the engine with the sparse
    # wire format (exercises decompress_sum over stacked sparse payloads)
    rng = np.random.RandomState(13)
    x = np.zeros((8, 4096), np.float32)
    for r in range(8):
        hot = rng.choice(4096, 40, replace=False)
        x[r, hot] = rng.randn(40).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "comp/dsparse", op="sum",
                        compression={"compressor": "dithering",
                                     "partition_num": "16", "seed": "5",
                                     "sparse_ratio": "0.05"})
    assert np.isfinite(np.asarray(out)).all()
    # energy sanity: the reduced tensor lives where contributions were
    assert np.count_nonzero(np.asarray(out)) <= 8 * 205 + 205


def test_dithering_unbiased_linear():
    # stochastic rounding must be unbiased: E[decompress] ~= x
    x = np.full(200_000, 0.37, np.float32)
    comp = create_compressor({"compressor": "dithering",
                              "partition_num": "4"}, len(x))
    payload, _ = comp.compress(jnp.asarray(x), comp.init_state())
    out = np.asarray(comp.decompress(payload))
    assert abs(out.mean() - 0.37) < 1e-3


# --- decorators ------------------------------------------------------------

def test_error_feedback_reduces_bias():
    x = _x(512, seed=5)
    kw = {"compressor": "onebit", "ef": "vanilla"}
    comp = create_compressor(kw, len(x))
    state = comp.init_state()
    # feed the same gradient repeatedly; with EF the *accumulated*
    # decompressed sum must track the accumulated true gradient
    acc = np.zeros_like(x)
    for step in range(20):
        payload, state = comp.compress(jnp.asarray(x), state)
        acc += np.asarray(comp.decompress(payload))
    avg_err = np.abs(acc / 20 - x).mean()
    # without EF the error would be ~mean(|x - sign(x)*L1mean|), much larger
    payload_nef, _ = create_compressor({"compressor": "onebit"},
                                       len(x)).compress(
        jnp.asarray(x), {})
    nef_err = np.abs(
        np.asarray(create_compressor({"compressor": "onebit"},
                                     len(x)).decompress(payload_nef)) - x
    ).mean()
    assert avg_err < 0.35 * nef_err


def test_error_feedback_state_matches_ref():
    x = _x(256, seed=6)
    comp = create_compressor({"compressor": "onebit", "ef": "vanilla"},
                             len(x))
    state = comp.init_state()
    err_ref = np.zeros(len(x), np.float32)
    for _ in range(3):
        payload, state = comp.compress(jnp.asarray(x), state)
        (ref_payload, err_ref) = refs.ef_compress(
            x, err_ref,
            lambda v: refs.onebit_compress(v, True),
            lambda p: refs.onebit_decompress(p[0], p[1], len(x)))
        np.testing.assert_array_equal(np.asarray(payload["words"]),
                                      ref_payload[0])
        np.testing.assert_allclose(np.asarray(state["error"]), err_ref,
                                   rtol=1e-5, atol=1e-6)


def test_nesterov_momentum_matches_ref():
    x = _x(128, seed=7)
    comp = create_compressor({"compressor": "onebit", "momentum": "nesterov",
                              "momentum_mu": "0.9"}, len(x))
    state = comp.init_state()
    m_ref = np.zeros(len(x), np.float32)
    for _ in range(3):
        payload, state = comp.compress(jnp.asarray(x), state)
        boosted, m_ref = refs.nesterov_compress(x, m_ref, 0.9)
        ref_words, ref_scale = refs.onebit_compress(boosted, True)
        np.testing.assert_array_equal(np.asarray(payload["words"]), ref_words)
        np.testing.assert_allclose(np.asarray(state["momentum"]), m_ref,
                                   rtol=1e-5)


def test_momentum_skipped_on_server():
    kw = {"compressor": "onebit", "momentum": "nesterov"}
    worker = create_compressor(kw, 64)
    server = create_compressor(kw, 64, for_server=True)
    assert worker.name == "nesterov_momentum"
    assert server.name == "onebit"


def test_registry_unknown_compressor():
    with pytest.raises(ValueError, match="unknown compressor"):
        create_compressor({"compressor": "gzip"}, 64)


def test_identity_below_none():
    comp = create_compressor(None, 64)
    assert comp.name == "identity"


# --- full engine pipeline parity ------------------------------------------

@pytest.fixture
def session():
    bps.init()
    yield
    bps.shutdown()


def _pipeline_ref(grads, compress_w, decompress_w, compress_s, decompress_s):
    """Numpy simulation of the full BytePS compressed cycle:
    out = D_s(C_s(sum_i D_w(C_w(g_i))))."""
    summed = np.zeros_like(grads[0])
    for g in grads:
        summed += decompress_w(compress_w(g))
    return decompress_s(compress_s(summed))


def test_engine_onebit_pipeline_matches_numpy(session):
    rng = np.random.RandomState(8)
    x = rng.randn(8, 512).astype(np.float32)
    out = bps.push_pull(jnp.asarray(x), "comp/onebit", op="sum",
                        compression={"compressor": "onebit"})
    ref = _pipeline_ref(
        [x[i] for i in range(8)],
        lambda g: refs.onebit_compress(g, True),
        lambda p: refs.onebit_decompress(p[0], p[1], 512),
        lambda g: refs.onebit_compress(g, True),
        lambda p: refs.onebit_decompress(p[0], p[1], 512))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)


def test_engine_randomk_pipeline_stateful(session):
    rng = np.random.RandomState(9)
    x = rng.randn(8, 300).astype(np.float32)
    counter_w = 0
    counter_s = 0
    for step in range(2):  # state must advance identically across steps
        out = bps.push_pull(jnp.asarray(x), "comp/rk", op="sum",
                            compression={"compressor": "randomk", "k": "30",
                                         "seed": "11"})
        idx, _, counter_w2 = refs.randomk_compress(x[0], 30, 11, counter_w)
        summed = np.zeros(300, np.float32)
        # same seed/counter on every rank -> same indices (reference
        # shared-seed behavior); server sums the scattered values
        for i in range(8):
            idx_i, vals_i, _ = refs.randomk_compress(x[i], 30, 11, counter_w)
            summed += refs.sparse_decompress(idx_i, vals_i, 300)
        counter_w = counter_w2
        sidx, svals, counter_s = refs.randomk_compress(summed, 30, 11,
                                                       counter_s)
        ref = refs.sparse_decompress(sidx, svals, 300)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                                   atol=1e-6)


def test_engine_compression_respects_min_bytes(session):
    # below the cutoff the tensor goes uncompressed
    # (BYTEPS_MIN_COMPRESS_BYTES semantics, operations.cc:362-364)
    from byteps_tpu.common.config import get_config
    cfg = get_config()
    cfg.min_compress_bytes = 10**9
    x = jnp.asarray(np.random.RandomState(10).randn(8, 128).astype(np.float32))
    out = bps.push_pull(x, "comp/small", op="sum",
                        compression={"compressor": "onebit"})
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-5)
    cfg.min_compress_bytes = 0


def test_training_with_onebit_ef_converges(session):
    """Sanity: compressed DP training still optimizes (the reference proves
    this by training resnet18 on fake data, test_onebit.py)."""
    import optax
    import byteps_tpu.jax as bps_jax
    from byteps_tpu.models.mlp import mnist_mlp, softmax_cross_entropy
    model = mnist_mlp()
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(64, 16).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, 64))
    params = model.init(jax.random.PRNGKey(0), x[:1])
    loss = lambda p, xb, yb: softmax_cross_entropy(model.apply(p, xb), yb)
    grad_fn = jax.jit(jax.vmap(jax.grad(loss), in_axes=(None, 0, 0)))
    tx = optax.sgd(0.05)
    state = tx.init(params)
    xs, ys = x.reshape(8, 8, -1), y.reshape(8, 8)
    first = float(loss(params, x, y))
    for _ in range(50):
        grads = grad_fn(params, xs, ys)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        names = [f"c/{i}" for i in range(len(leaves))]
        reduced = [bps.push_pull(g, n, op="average",
                                 compression={"compressor": "onebit",
                                              "ef": "vanilla"})
                   for g, n in zip(names and leaves, names)]
        grads = jax.tree_util.tree_unflatten(treedef, reduced)
        upd, state = tx.update(grads, state)
        params = optax.apply_updates(params, upd)
    last = float(loss(params, x, y))
    # onebit is effectively sign-SGD — slow but steady descent is the bar
    assert last < first * 0.8, (first, last)
