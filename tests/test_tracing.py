"""Direct unit tests for common/tracing.py (ISSUE 12 satellite).

The tracer was previously only incidentally covered through engine
tests; these pin its own contracts: step-window gating, flush's
idempotent-rewrite semantics, record_span's window independence,
numeric-tid metadata emission, the jax-profiler state machine (driven
without a real profiler), the new sampled capture stream, the bounded
event buffer (spill + dropped counter), and the clock/anchor metadata
the merge tool depends on.
"""

import json
import os
import sys
import threading
import types

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from byteps_tpu.common import tracing
from byteps_tpu.common.config import Config, set_config
from byteps_tpu.common.tracing import TraceContext, Tracer


def _read(path):
    with open(path) as f:
        return json.load(f)


# -- step-window gating ------------------------------------------------------


def test_record_gated_on_step_window(tmp_path):
    tr = Tracer(enabled=True, start_step=2, end_step=3, out_dir=str(tmp_path))
    for step in (1, 2, 3, 4):
        tr.record("g", 7, "push_pull", 1.0, 2.0, step, nbytes=64)
    # the step-4 record auto-flushed (window closed); an explicit path
    # forces a rewrite so the assertion reads the full file
    doc = _read(tr.flush(path=str(tmp_path / "win.json")))
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert sorted(e["args"]["step"] for e in spans) == [2, 3]


def test_on_push_counts_per_tensor_and_flushes_past_window(tmp_path):
    tr = Tracer(enabled=True, start_step=1, end_step=2, out_dir=str(tmp_path))
    assert tr.on_push("a") == 1
    assert tr.on_push("b") == 1
    assert tr.on_push("a") == 2
    tr.record("a", 0, "push_pull", 0.0, 1.0, 2)
    # stepping past the window triggers the idempotent flush
    assert tr.on_push("a") == 3
    out = os.path.join(str(tmp_path),
                       f"bps_trace_rank0_{os.getpid()}.json")
    assert os.path.exists(out)


def test_disabled_tracer_records_nothing(tmp_path):
    tr = Tracer(enabled=False, out_dir=str(tmp_path))
    assert not tr.active
    tr.record("g", 0, "push_pull", 0.0, 1.0, 15)
    tr.record_span("fault", 0.0, 1.0)
    assert tr.flush() is None


# -- flush semantics ---------------------------------------------------------


def test_flush_idempotent_rewrite(tmp_path):
    tr = Tracer(enabled=True, start_step=1, end_step=99,
                out_dir=str(tmp_path))
    tr.record("g", 0, "queued", 0.0, 1.0, 1)
    p1 = tr.flush()
    assert p1 is not None
    assert tr.flush() is None            # nothing new -> no rewrite
    tr.record("g", 0, "queued", 1.0, 2.0, 2)
    p2 = tr.flush()                      # new event -> full rewrite
    assert p2 == p1
    spans = [e for e in _read(p2)["traceEvents"] if e.get("ph") == "X"]
    assert len(spans) == 2


def test_record_span_outside_window(tmp_path):
    tr = Tracer(enabled=True, start_step=10, end_step=20,
                out_dir=str(tmp_path))
    # no windowed event ever recorded; the fault span must still land
    tr.record_span("recovery", 5.0, 6.0, epoch=3)
    doc = _read(tr.flush())
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert [e["name"] for e in spans] == ["recovery"]
    assert spans[0]["cat"] == "fault"
    assert spans[0]["args"]["epoch"] == 3


def test_numeric_tid_metadata_emission(tmp_path):
    tr = Tracer(enabled=True, start_step=1, end_step=9,
                out_dir=str(tmp_path))
    tr.record("tensor.a", 0, "queued", 0.0, 1.0, 1)
    tr.record("tensor.b", 1, "queued", 0.0, 1.0, 1)
    doc = _read(tr.flush())
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    metas = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    # chrome requires numeric tids; names ride thread_name metadata
    assert all(isinstance(e["tid"], int) for e in spans)
    names = {m["args"]["name"]: m["tid"] for m in metas}
    assert set(names) == {"tensor.a", "tensor.b"}
    by_name = {e["args"]["key"]: e["tid"] for e in spans}
    assert by_name[0] == names["tensor.a"]
    assert by_name[1] == names["tensor.b"]


def test_flush_carries_merge_metadata(tmp_path):
    tr = Tracer(enabled=True, start_step=1, end_step=9,
                out_dir=str(tmp_path))
    tracing.set_clock_offset(0.012, 0.001, source="bus test")
    tr.record("g", 0, "queued", 0.0, 1.0, 1)
    doc = _read(tr.flush())
    assert doc["rank"] == 0 and doc["pid"] == os.getpid()
    anchor = doc["monoAnchor"]
    assert anchor["mono"] <= 1e9 < anchor["wall"]  # mono vs wall clocks
    assert doc["clockSync"]["offset_s"] == pytest.approx(0.012)
    assert doc["clockSync"]["err_s"] == pytest.approx(0.001)


# -- jax-profiler state machine (no real profiler) ---------------------------


class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, path):
        self.calls.append(("start", path))

    def stop_trace(self):
        self.calls.append(("stop",))


def test_jax_profiler_state_machine(tmp_path, monkeypatch):
    import jax
    fake = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    set_config(Config(trace_on=True, trace_jax=True, trace_start_step=2,
                      trace_end_step=3, trace_dir=str(tmp_path)))
    tr = Tracer()
    assert tr._jax_state == "idle"
    tr.on_push("g")                      # step 1: before the window
    assert fake.calls == [] and tr._jax_state == "idle"
    tr.on_push("g")                      # step 2: window opens
    assert tr._jax_state == "running"
    tr.on_push("g")                      # step 3: still inside
    assert [c[0] for c in fake.calls] == ["start"]
    tr.on_push("g")                      # step 4: window closed
    assert tr._jax_state == "done"
    assert [c[0] for c in fake.calls] == ["start", "stop"]
    tr._jax_start()                      # done is terminal
    assert tr._jax_state == "done"
    assert [c[0] for c in fake.calls] == ["start", "stop"]


def test_jax_profiler_start_failure_is_terminal(tmp_path, monkeypatch):
    import jax

    class _Broken:
        def start_trace(self, path):
            raise RuntimeError("no profiler here")

    monkeypatch.setattr(jax, "profiler", _Broken())
    set_config(Config(trace_on=True, trace_jax=True, trace_start_step=1,
                      trace_end_step=9, trace_dir=str(tmp_path)))
    tr = Tracer()
    tr.on_push("g")
    assert tr._jax_state == "done"       # failed start never retries


# -- sampling (BYTEPS_TRACE_SAMPLE) ------------------------------------------


def test_trace_sample_parsing_and_validation():
    assert Config(trace_sample="1/8").trace_sample_n == 8
    assert Config(trace_sample="8").trace_sample_n == 8
    assert Config(trace_sample="0").trace_sample_n == 0
    assert Config(trace_sample="").trace_sample_n == 0
    with pytest.raises(ValueError, match="BYTEPS_TRACE_SAMPLE"):
        Config(trace_sample="every-other")


def test_sampled_capture_every_nth_push(tmp_path):
    tr = Tracer(enabled=False, sample_n=3, out_dir=str(tmp_path))
    assert tr.active and not tr.enabled
    caught = [tr.start_push("g")[1] for _ in range(9)]
    assert sum(c is not None for c in caught) == 3
    ids = {c.trace_id for c in caught if c is not None}
    assert len(ids) == 3                 # distinct per captured push
    # window-gated record() still records nothing in sampled-only mode
    tr.record("g", 0, "push_pull", 0.0, 1.0, 1)
    tr.record_traced(caught[2].trace_id, "push_pull", "g", 0.0, 1.0)
    doc = _read(tr.flush())
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len(spans) == 1
    assert spans[0]["args"]["trace_id"] == caught[2].trace_id


def test_maybe_sample_per_site_counters(tmp_path):
    tr = Tracer(enabled=False, sample_n=2, out_dir=str(tmp_path))
    a = [tr.maybe_sample("serve") for _ in range(4)]
    b = [tr.maybe_sample("kv") for _ in range(4)]
    assert sum(c is not None for c in a) == 2
    assert sum(c is not None for c in b) == 2
    # windowed-only tracing captures non-push site calls ONLY while the
    # step window is open (a closed window must stop the stream — the
    # capture bound the window exists for)
    tw = Tracer(enabled=True, start_step=2, end_step=3, sample_n=0,
                out_dir=str(tmp_path))
    assert tw.maybe_sample("serve") is None       # step 0: before window
    tw.start_push("g")                            # step 1
    assert tw.maybe_sample("serve") is None
    tw.start_push("g")                            # step 2: window open
    assert tw.maybe_sample("serve") is not None
    tw.start_push("g")                            # step 3
    tw.start_push("g")                            # step 4: window closed
    assert tw.maybe_sample("serve") is None


def test_flow_event_shape_and_pairing(tmp_path):
    tr = Tracer(enabled=False, sample_n=1, out_dir=str(tmp_path))
    _, ctx = tr.start_push("g")
    tr.record_traced(ctx.trace_id, "queued", "g", 1.0, 2.0)
    tr.flow(ctx.trace_id, "s", "g", 1.0)
    tr.flow(ctx.trace_id, "t", "wire/server_push", 2.5)
    tr.flow(ctx.trace_id, "f", "g", 3.0)
    doc = _read(tr.flush())
    flows = [e for e in doc["traceEvents"] if e.get("ph") in "stf"]
    assert [e["ph"] for e in flows] == ["s", "t", "f"]
    assert all(e["id"] == ctx.trace_id for e in flows)
    assert all(e["name"] == tracing.FLOW_NAME
               and e["cat"] == tracing.FLOW_CAT for e in flows)
    assert flows[2]["bp"] == "e"         # finish binds enclosing slice


def test_flow_ids_unique_across_ranks():
    a = tracing._new_flow_id(0)
    b = tracing._new_flow_id(1)
    c = tracing._new_flow_id(0)
    assert len({a, b, c}) == 3
    assert (b >> 48) & 0xFFFF == 1


# -- bounded buffer (capacity, spill, dropped) -------------------------------


def test_capacity_spills_to_disk_and_flush_folds_back(tmp_path):
    tr = Tracer(enabled=True, start_step=1, end_step=10 ** 9,
                out_dir=str(tmp_path), capacity=256)
    for i in range(1000):
        tr.record("g", 0, "queued", float(i), float(i) + 0.5, 1)
    assert len(tr._events) < 256         # memory stayed bounded
    assert tr._spill_count >= 1000 - 256
    assert tr.dropped == 0
    doc = _read(tr.flush())
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len(spans) == 1000            # spill folded back in order
    assert spans[0]["ts"] == 0.0


def test_spill_failure_drops_and_counts(tmp_path, monkeypatch):
    from byteps_tpu.common.telemetry import counters
    tr = Tracer(enabled=True, start_step=1, end_step=10 ** 9,
                out_dir=os.path.join(str(tmp_path), "nope"), capacity=256)
    monkeypatch.setattr(os, "makedirs",
                        lambda *a, **k: (_ for _ in ()).throw(OSError("ro")))
    before = counters.get("trace.events_dropped")
    for i in range(600):
        tr.record("g", 0, "queued", float(i), float(i) + 0.5, 1)
    assert tr.dropped >= 256
    assert counters.get("trace.events_dropped") - before == tr.dropped
    assert len(tr._events) < 256


def test_step_map_bounded(tmp_path):
    tr = Tracer(enabled=False, sample_n=1, out_dir=str(tmp_path))
    tr._MAX_TENSORS = 4                  # class default is 8192
    for i in range(8):
        tr.start_push(f"t{i}")
    assert len(tr._step) == 4
    step, ctx = tr.start_push("t7")      # overflow name: uncaptured
    assert step == 0 and ctx is None
    assert tr.dropped >= 4


# -- process singleton / context propagation ---------------------------------


def test_process_tracer_singleton_and_reset(tmp_path):
    set_config(Config(trace_on=False, trace_sample="1/4",
                      trace_dir=str(tmp_path)))
    tracing._reset_for_tests()
    t1 = tracing.tracer()
    assert t1 is tracing.tracer()
    assert t1.sample_n == 4
    tracing._reset_for_tests()
    assert tracing.tracer() is not t1


def test_use_and_current_propagate_within_thread():
    ctx = TraceContext(trace_id=42)
    assert tracing.current() is None
    with tracing.use(ctx):
        assert tracing.current() is ctx
        seen = []
        t = threading.Thread(target=lambda: seen.append(tracing.current()))
        t.start()
        t.join()
        assert seen == [None]            # contextvars don't cross spawn
    assert tracing.current() is None


def test_begin_sample_joins_existing_context(tmp_path):
    tracing.set_tracer(Tracer(enabled=False, sample_n=1,
                              out_dir=str(tmp_path)))
    outer = TraceContext(trace_id=7)
    with tracing.use(outer):
        ctx, t0 = tracing.begin_sample("kv.push")
        assert ctx is outer and t0 > 0
    ctx, _ = tracing.begin_sample("kv.push")
    assert ctx is not None and ctx.trace_id != 7


def test_last_stamp_tracks_captured_pushes(tmp_path):
    tracing._reset_for_tests()
    tr = Tracer(enabled=False, sample_n=2, out_dir=str(tmp_path))
    tr.start_push("g")                   # 1st: not sampled
    step, ctx = tr.start_push("g")       # 2nd: sampled
    assert ctx is not None
    assert tracing.last_stamp() == (2, ctx.trace_id)
    tracing.note_step(9)
    assert tracing.last_stamp() == (9, ctx.trace_id)
