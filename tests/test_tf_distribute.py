"""BytePS-backed tf.distribute strategy (reference
distribute/mirrored_strategy.py + cross_device_ops.py — SURVEY.md §2.4).
Single process == the reference's single-worker forced-distributed mode:
cross-worker push_pull is identity, so strategy semantics (replica-local
reduction, MEAN/SUM, broadcast-on-create) are what is under test."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import byteps_tpu.tensorflow as bps_tf  # noqa: E402
from byteps_tpu.tensorflow.distribute import (BytePSCrossDeviceOps,  # noqa: E402
                                              MirroredStrategy)


@pytest.fixture
def session():
    bps_tf.init()
    yield
    bps_tf.shutdown()


def test_cross_device_ops_reduce_sum_and_mean(session):
    ops = BytePSCrossDeviceOps()
    x = tf.constant(np.random.randn(8, 3).astype(np.float32))
    out = ops.reduce(tf.distribute.ReduceOp.SUM, x, destinations=x)
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-5)
    out = ops.reduce(tf.distribute.ReduceOp.MEAN, x, destinations=x)
    np.testing.assert_allclose(out.numpy(), x.numpy(), rtol=1e-5)


def test_mirrored_strategy_reduce(session):
    strat = MirroredStrategy(["/cpu:0"])
    assert isinstance(strat.extended._inferred_cross_device_ops
                      if hasattr(strat.extended,
                                 "_inferred_cross_device_ops")
                      else strat.extended._cross_device_ops,
                      BytePSCrossDeviceOps)

    def step():
        ctx = tf.distribute.get_replica_context()
        return tf.constant(3.0)

    per_replica = strat.run(step)
    tot = strat.reduce(tf.distribute.ReduceOp.SUM, per_replica, axis=None)
    assert float(tot) == pytest.approx(3.0)


def test_mirrored_strategy_training_step(session):
    strat = MirroredStrategy(["/cpu:0"])
    with strat.scope():
        v = tf.Variable(2.0)
    opt = tf.keras.optimizers.SGD(0.5)

    @tf.function
    def step():
        def replica_fn():
            with tf.GradientTape() as tape:
                loss = v * v
            g = tape.gradient(loss, v)
            opt.apply_gradients([(g, v)])
            return loss

        return strat.run(replica_fn)

    losses = [float(strat.reduce(tf.distribute.ReduceOp.MEAN, step(),
                                 axis=None)) for _ in range(3)]
    assert losses[0] > losses[-1]  # v: 2.0 -> 0.0 under lr .5 on v^2


def test_broadcast_mirrors_root_value(session):
    ops = BytePSCrossDeviceOps()
    x = tf.constant(np.arange(6, dtype=np.float32))
    out = ops.broadcast(x, destinations=x)
    np.testing.assert_allclose(tf.convert_to_tensor(out).numpy(), x.numpy())
