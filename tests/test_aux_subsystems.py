"""Aux-subsystem tests: tracing timeline, DDP, cross-barrier, async-PS mode
(SURVEY.md §5 and §2.6 items 6-7)."""

import json
import os

import numpy as np
import pytest
import torch

import byteps_tpu as bps
import byteps_tpu.torch as bps_torch
from byteps_tpu.common import Config
from byteps_tpu.common.config import set_config


@pytest.fixture
def session():
    bps.init()
    yield
    bps.shutdown()


# --- tracing ---------------------------------------------------------------

def test_trace_timeline_written(tmp_path):
    set_config(Config(trace_on=True, trace_start_step=1, trace_end_step=3,
                      trace_dir=str(tmp_path)))
    bps.init()
    try:
        import jax.numpy as jnp
        x = jnp.ones((8, 256))
        for _ in range(4):
            bps.push_pull(x, "traced", op="sum")
    finally:
        bps.shutdown()
    files = [f for f in os.listdir(tmp_path) if f.startswith("bps_trace")]
    assert files, "no trace file written"
    with open(tmp_path / files[0]) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    phases = {e["name"] for e in events if e["ph"] == "X"}
    assert {"queued", "push_pull"} <= phases
    steps = {e["args"]["step"] for e in events if e["ph"] == "X"}
    assert steps <= {1, 2, 3}  # window respected
    # tensor name is recoverable from thread metadata
    names = [e["args"]["name"] for e in events if e["ph"] == "M"]
    assert "traced" in names


def test_trace_off_writes_nothing(tmp_path):
    set_config(Config(trace_on=False, trace_dir=str(tmp_path)))
    bps.init()
    try:
        import jax.numpy as jnp
        bps.push_pull(jnp.ones((8, 16)), "t", op="sum")
    finally:
        bps.shutdown()
    assert not [f for f in os.listdir(tmp_path) if f.startswith("bps_trace")]


# --- DDP -------------------------------------------------------------------

def test_trace_dir_empty_env_falls_back_to_tmp_default(monkeypatch):
    # review regression: BYTEPS_TRACE_DIR exported EMPTY (a launch
    # script's unset $VAR) must behave like unset — os.path.join("", f)
    # would resurrect the repo-root trace litter the tmp default fixed
    from byteps_tpu.common.config import Config, _default_trace_dir
    monkeypatch.setenv("BYTEPS_TRACE_DIR", "")
    assert Config().trace_dir == _default_trace_dir()
    assert Config.from_env().trace_dir == _default_trace_dir()
    assert "byteps_traces_" in _default_trace_dir()
    monkeypatch.setenv("BYTEPS_TRACE_DIR", "/explicit/dir")
    assert Config().trace_dir == "/explicit/dir"


def test_ddp_matches_plain_training(session):
    from byteps_tpu.torch.parallel import DistributedDataParallel
    torch.manual_seed(4)
    plain = torch.nn.Sequential(torch.nn.Linear(6, 8), torch.nn.Tanh(),
                                torch.nn.Linear(8, 2))
    wrapped_inner = torch.nn.Sequential(torch.nn.Linear(6, 8),
                                        torch.nn.Tanh(),
                                        torch.nn.Linear(8, 2))
    wrapped_inner.load_state_dict(plain.state_dict())
    ddp = DistributedDataParallel(wrapped_inner)
    o1 = torch.optim.SGD(plain.parameters(), lr=0.1)
    o2 = torch.optim.SGD(ddp.parameters(), lr=0.1)
    x = torch.randn(20, 6)
    y = torch.randn(20, 2)
    for _ in range(5):
        for o, m in ((o1, plain), (o2, ddp)):
            o.zero_grad()
            torch.nn.functional.mse_loss(m(x), y).backward()
            o.step()
    for p1, p2 in zip(plain.parameters(), ddp.parameters()):
        np.testing.assert_allclose(p1.detach().numpy(), p2.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_ddp_no_sync_accumulates(session):
    from byteps_tpu.torch.parallel import DistributedDataParallel
    torch.manual_seed(5)
    m = torch.nn.Linear(4, 1)
    ddp = DistributedDataParallel(m)
    x = torch.randn(8, 4)
    y = torch.randn(8, 1)
    with ddp.no_sync():
        torch.nn.functional.mse_loss(ddp(x[:4]), y[:4]).backward()
    g_first = m.weight.grad.clone()
    torch.nn.functional.mse_loss(ddp(x[4:]), y[4:]).backward()
    # grads accumulated over both micro-batches and synced on the second
    assert not torch.allclose(m.weight.grad, g_first)


# --- CrossBarrier ----------------------------------------------------------

def test_cross_barrier_converges_and_overlaps(session):
    from byteps_tpu.torch.parallel import CrossBarrier
    torch.manual_seed(6)
    model = torch.nn.Sequential(torch.nn.Linear(8, 16), torch.nn.ReLU(),
                                torch.nn.Linear(16, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    xb = CrossBarrier(model, opt)
    x = torch.randn(32, 8)
    y = x.sum(dim=1, keepdim=True)
    losses = []
    for _ in range(25):
        out = model(x)             # forward pre-hooks apply pending updates
        loss = torch.nn.functional.mse_loss(out, y)
        losses.append(float(loss))
        model.zero_grad()
        loss.backward()            # hooks enqueue async push_pulls
        xb.step()                  # returns immediately
    xb.synchronize()
    assert losses[-1] < losses[0] * 0.5, losses[::8]


def test_cross_barrier_standard_loop_with_set_to_none(session):
    """The standard pattern — opt.zero_grad() (set_to_none) BEFORE forward —
    must work: the gate re-creates p.grad when it was None."""
    from byteps_tpu.torch.parallel import CrossBarrier
    torch.manual_seed(7)
    model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.ReLU(),
                                torch.nn.Linear(8, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    xb = CrossBarrier(model, opt)
    x = torch.randn(16, 4)
    y = x.mean(dim=1, keepdim=True)
    losses = []
    for _ in range(10):
        opt.zero_grad()            # set_to_none=True default
        loss = torch.nn.functional.mse_loss(model(x), y)
        losses.append(float(loss.detach()))
        loss.backward()
        xb.step()
    xb.synchronize()
    assert losses[-1] < losses[0]


# --- async-PS mode ---------------------------------------------------------

def test_async_optimizer_single_worker_matches_sync(session):
    import jax
    import jax.numpy as jnp
    import optax
    from byteps_tpu.jax.async_opt import AsyncDistributedOptimizer
    from byteps_tpu.models.mlp import mnist_mlp, softmax_cross_entropy
    rng = np.random.RandomState(12)
    x = jnp.asarray(rng.randn(32, 16).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, 32))
    model = mnist_mlp()
    params = model.init(jax.random.PRNGKey(0), x[:1])
    loss = lambda p, xb, yb: softmax_cross_entropy(model.apply(p, xb), yb)

    aopt = AsyncDistributedOptimizer(optax.sgd(0.1))
    astate = aopt.init(params)
    ref_tx = optax.sgd(0.1)
    ref_state = ref_tx.init(params)
    ref_params = params
    aparams = params
    for _ in range(5):
        g = jax.grad(loss)(aparams, x, y)
        aparams, astate = aopt.update_and_sync(g, astate, aparams)
        rg = jax.grad(loss)(ref_params, x, y)
        upd, ref_state = ref_tx.update(rg, ref_state)
        import optax as _o
        ref_params = _o.apply_updates(ref_params, upd)
    # one worker: async == sync exactly
    for a, b in zip(jax.tree.leaves(aparams), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_async_two_workers_interleave(session):
    """Two workers sharing a store: deltas sum without a barrier."""
    import jax
    import jax.numpy as jnp
    import optax
    from byteps_tpu.jax.async_opt import AsyncDistributedOptimizer
    from byteps_tpu.server import KVStore
    params = {"w": jnp.zeros(3)}
    store = KVStore()
    w1 = AsyncDistributedOptimizer(optax.sgd(1.0), store=store)
    w2 = AsyncDistributedOptimizer(optax.sgd(1.0), store=store)
    s1, s2 = w1.init(params), w2.init(params)
    # worker1 pushes delta -1*g1, worker2 then sees it in its pull
    p1, s1 = w1.update_and_sync({"w": jnp.ones(3)}, s1, params)
    p2, s2 = w2.update_and_sync({"w": jnp.ones(3) * 2}, s2, params)
    np.testing.assert_allclose(np.asarray(p1["w"]), -1.0)
    np.testing.assert_allclose(np.asarray(p2["w"]), -3.0)  # both deltas
    assert store.version(list(store.keys())[0]) == 2


def test_kv_store_requires_init():
    from byteps_tpu.server import KVStore
    s = KVStore()
    with pytest.raises(KeyError):
        s.push_delta("nope", np.ones(2))


def test_async_compressed_wire_converges_and_saves_bytes(session):
    """Async mode with compressed wire pushes (reference async +
    compressed, server.cc:87-113 + 310-314): training still converges
    (onebit + EF) and the store's accounted wire bytes are ~32x smaller
    than the dense deltas it replaced."""
    import jax
    import jax.numpy as jnp
    import optax
    from byteps_tpu.jax.async_opt import AsyncDistributedOptimizer
    from byteps_tpu.models.mlp import mnist_mlp, softmax_cross_entropy
    rng = np.random.RandomState(13)
    x = jnp.asarray(rng.randn(64, 16).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 10, 64))
    model = mnist_mlp()
    params = model.init(jax.random.PRNGKey(1), x[:1])
    loss = lambda p, xb, yb: softmax_cross_entropy(model.apply(p, xb), yb)

    aopt = AsyncDistributedOptimizer(
        optax.sgd(0.05),
        compression={"compressor": "onebit", "ef": "vanilla"})
    astate = aopt.init(params)
    first = float(loss(params, x, y))
    steps = 40
    for _ in range(steps):
        g = jax.grad(loss)(params, x, y)
        params, astate = aopt.update_and_sync(g, astate, params)
    assert float(loss(params, x, y)) < first * 0.8
    # wire accounting: onebit packs 32x (+ scale/frame overhead)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    dense_bytes = steps * n_params * 4
    assert 0 < aopt.store.wire_bytes < dense_bytes / 8


def test_kv_store_codec_registration_conflicts_and_accounting():
    from byteps_tpu.server import KVStore
    s = KVStore()
    s.init_key("k", np.zeros(256, np.float32))
    s.register_compression("k", {"compressor": "onebit"}, 256)
    s.register_compression("k", {"compressor": "onebit"}, 256)  # idempotent
    with pytest.raises(ValueError, match="different"):
        s.register_compression("k", {"compressor": "onebit",
                                     "scaling": "false"}, 256)
    with pytest.raises(KeyError, match="no registered"):
        s.push_delta_wire("unreg", b"\0" * 16)
    # a rejected push must not inflate the accounting
    s.init_key("k2", np.zeros(256, np.float32))
    before = s.wire_bytes
    with pytest.raises(ValueError):
        s.push_delta_wire("k", b"\0" * 4)  # malformed frame
    assert s.wire_bytes == before
    s.clear()
    assert s.wire_bytes == 0


def test_jax_profiler_window(tmp_path, monkeypatch):
    """BYTEPS_TRACE_JAX=1: the device profiler runs over the trace step
    window and its artifacts land under trace_dir/jax_profile."""
    import glob
    import os as _os
    import jax.numpy as jnp
    import numpy as np

    monkeypatch.setenv("BYTEPS_TRACE_ON", "1")
    monkeypatch.setenv("BYTEPS_TRACE_JAX", "1")
    monkeypatch.setenv("BYTEPS_TRACE_START_STEP", "1")
    monkeypatch.setenv("BYTEPS_TRACE_END_STEP", "2")
    monkeypatch.setenv("BYTEPS_TRACE_DIR", str(tmp_path))
    from byteps_tpu.common.config import reset_config
    reset_config()

    import byteps_tpu as bps
    bps.init()
    try:
        x = jnp.asarray(np.ones((bps.size(), 256), np.float32))
        for _ in range(4):  # steps 1..4: window opens at 1, closes past 2
            bps.push_pull(x, "prof.t")
    finally:
        bps.shutdown()
    host_traces = glob.glob(str(tmp_path / "bps_trace_rank*.json"))
    assert host_traces, "host comm trace missing"
    prof_files = [p for p in glob.glob(str(tmp_path / "jax_profile" / "**"),
                                       recursive=True)
                  if _os.path.isfile(p)]
    assert prof_files, "jax profiler artifacts missing"
