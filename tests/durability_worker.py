"""Worker body for the full-world kill-and-cold-restart durability test.

Launched by tests/test_durability.py (pattern of tests/chaos_worker.py):
ONE process is the entire world — there is no survivor holding state in
memory, which is exactly the correlated-failure case the durable state
plane (byteps_tpu/server/wal.py) exists for.  The worker opens the
process-lifetime durable KV store, pushes a deterministic delta sequence
with (worker_id, seq) idempotence tokens, and checkpoints every
CKPT_EVERY steps.  The parent SIGKILLs it mid-step, then relaunches it
against the SAME durable dir; the restarted worker cold-recovers
(snapshot + journal replay), reads the restored dedup floor, and resumes
pushing from floor+1 — journal-before-merge guarantees the floor names
EXACTLY the deltas folded into the restored arrays, so the final state
is bit-identical to a fault-free run, whatever instant the kill landed.

Prints (parent asserts on these):
  FLOOR <n>          the restored dedup floor at startup (0 = cold dir)
  RECOVERED <json>   the DurableKV.recover_stats of this incarnation
  STEP <n>           progress marker (the parent kills after seeing one)
  FINAL <hex>        sha256 of the final array bytes + generation

Env: BYTEPS_DURABLE_DIR (the shared dir), BYTEPS_DUR_STEPS,
BYTEPS_DUR_CKPT_EVERY, plus optional BYTEPS_WAL_* knobs under test.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    steps = int(os.environ.get("BYTEPS_DUR_STEPS", "300"))
    ckpt_every = int(os.environ.get("BYTEPS_DUR_CKPT_EVERY", "20"))

    import jax

    jax.config.update("jax_platforms", "cpu")

    from byteps_tpu.server import wal

    store, dur = wal.ensure_process_store()
    print("RECOVERED", json.dumps(dur.recover_stats), flush=True)

    # idempotent on a warm restart: init_key is a no-op once the key
    # exists (restored from the snapshot or replayed from its journal
    # record)
    store.init_key("w", np.zeros(64, np.float32))

    floor = store._seen.get(("w", 0), 0)
    print("FLOOR", floor, flush=True)

    # Deterministic per-seq delta: the fault-free final is a pure
    # function of `steps`, so bit-exactness is checkable across runs.
    for seq in range(floor + 1, steps + 1):
        delta = np.full(64, float(seq % 7) + 0.125, np.float32)
        store.push_delta("w", delta, worker_id=0, seq=seq)
        if seq % ckpt_every == 0:
            dur.checkpoint()
        if seq % 10 == 0:
            print("STEP", seq, flush=True)
        # keep the run long enough for the parent's kill to land mid-way
        time.sleep(0.002)

    final = store.pull("w")
    digest = hashlib.sha256(
        np.ascontiguousarray(final).tobytes()
        + str(store._generation).encode()).hexdigest()
    print("FINAL", digest, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
