"""The split-brain proof (ISSUE 17 headline): a real network partition
across three worker processes, asserted from both sides' flight records.

``partition:ranks=0|1.2:ms=10000`` severs the coordinator (rank 0) from
ranks 1 and 2 at a deterministic step boundary — every rank arms the
same edge-cut spec locally, so the "network" splits without any global
trigger.  What must happen, and what this test pins:

- the MAJORITY side (ranks 1, 2) detects the unreachable coordinator,
  takes the quorum-gated failover shrink (epoch 1, world {1,2} — 2 of 3
  IS a strict majority), and keeps training;
- the MINORITY side (rank 0) proposes world {0}, fails the strict-
  majority gate, and PARKS — ``membership.partition_minority`` in its
  flight ring, and crucially NO ``membership.shrink_started`` and no
  epoch ever advanced on that side: the two sides never agree two
  different worlds at any epoch (the split-brain proof);
- when the ``ms=`` heal opens the edges again, rank 0 returns through
  the ordinary rejoin path (host-map bus discovery — its OWN old bus
  socket is gone), epoch 2 re-agrees world {0,1,2}, and every rank's
  final weights are bit-identical to a fault-free float32 replay of the
  same piecewise world schedule;
- ``bps_doctor --postmortem`` over the run's flight dumps folds the
  whole incident into sides / parked ranks / heal time (satellite 3).
"""

import json

import pytest

from .conftest import free_port as _free_port
from .test_elastic import _communicate, _final, _simulate, _spawn


def _world_step(out, epoch, world):
    """Parse 'WORLD <epoch> <world> at <step>' (first occurrence)."""
    for line in out.splitlines():
        if line.startswith(f"WORLD {epoch} {world} at "):
            return int(line.rsplit(" ", 1)[1])
    raise AssertionError(
        f"no 'WORLD {epoch} {world}' line in:\n" + out[-3000:])


def _flight_paths(out):
    return [line.split(" ", 1)[1].strip() for line in out.splitlines()
            if line.startswith("FLIGHT ")]


def _events(path):
    with open(path) as f:
        return json.load(f)["events"]


def _applied_worlds(events):
    """{epoch: world} committed by this rank, per its flight ring."""
    out = {}
    for ev in events:
        if ev.get("kind") == "membership.applied":
            out[int(ev["epoch"])] = tuple(ev["world"])
    return out


@pytest.mark.chaos
def test_partition_minority_parks_majority_trains_heal_rejoins(tmp_path):
    n, cut_at, heal_ms = 40, 4, 10000
    ports = [_free_port() for _ in range(3)]
    hosts = ",".join(f"127.0.0.1:{p}" for p in ports)
    extra = {
        # EMPTY bus: per-view host-map resolution, so the failover
        # successor binds its OWN entry — rank 0's process is alive
        # across the cut, still holding hosts[0]
        "BYTEPS_ELASTIC_BUS": "",
        "BYTEPS_MEMBERSHIP_HOSTS": hosts,
        "BYTEPS_GOSSIP_ON": "1",
        "BYTEPS_GOSSIP_INTERVAL_S": "0.1",
        # tight budgets so each severed round surfaces in seconds
        "BYTEPS_BUS_RETRIES": "8",
        "BYTEPS_RETRY_DEADLINE": "3",
        "BYTEPS_MEMBERSHIP_SYNC_TIMEOUT": "4",
        "BYTEPS_MEMBERSHIP_RENDEZVOUS_TIMEOUT": "5",
        "BYTEPS_ELASTIC_STEP_SLEEP": "0.4",
        "BYTEPS_ELASTIC_PARTITION_SPEC":
            f"partition:ranks=0|1.2:ms={heal_ms}",
        "BYTEPS_ELASTIC_PARTITION_STEP": str(cut_at),
        "BYTEPS_FLIGHT_DIR": str(tmp_path),
    }
    procs = {r: _spawn(r, "0,1,2", ports[0], "", n, extra=extra)
             for r in (0, 1, 2)}
    outs = _communicate(procs, timeout=240)
    for r in (0, 1, 2):
        assert procs[r].returncode == 0, outs[r][-4000:]
        assert f"PARTITION-ARMED {r} at {cut_at}" in outs[r]

    # -- the minority parked; nobody exited ---------------------------
    assert "PARKED 0 0" in outs[0], outs[0][-4000:]
    assert "REJOINED 2 0,1,2" in outs[0], outs[0][-4000:]

    # -- the majority shrank to {1,2} (epoch 1), then re-admitted rank
    #    0 after the heal (epoch 2) — both survivors agree both steps
    s1 = _world_step(outs[2], 1, "1,2")
    s2 = _world_step(outs[2], 2, "0,1,2")
    assert _world_step(outs[1], 1, "1,2") == s1
    assert _world_step(outs[1], 2, "0,1,2") == s2
    assert cut_at <= s1 < s2 <= n

    # -- finals: all three ranks, same epoch/world/weights, and the
    #    weights are a bit-exact float32 replay of the world schedule
    finals = {r: _final(outs[r]) for r in (0, 1, 2)}
    for r in (0, 1, 2):
        assert finals[r][0] == 2 and finals[r][1] == "0,1,2", finals[r]
    expected = _simulate(
        _simulate(_simulate(0.0, (0, 1, 2), s1 - 1), (1, 2), s2 - s1),
        (0, 1, 2), n - s2 + 1)
    for r in (0, 1, 2):
        assert finals[r][2] == expected, (finals, expected, s1, s2)

    # -- the split-brain proof, from the flight records ---------------
    # rank 0's FIRST dump is the park-time ring: the minority side
    # recorded the refusal and NEVER started a shrink or committed an
    # epoch past the last agreed one
    park_events = _events(_flight_paths(outs[0])[0])
    park_kinds = [e["kind"] for e in park_events]
    assert "membership.partition_minority" in park_kinds
    minority = [e for e in park_events
                if e["kind"] == "membership.partition_minority"][0]
    assert minority["epoch"] == 0 and minority["world"] == [0, 1, 2]
    assert "membership.shrink_started" not in park_kinds
    assert all(ep == 0 for ep in _applied_worlds(park_events)), \
        park_kinds
    # no epoch is ever agreed with two different worlds across ALL
    # ranks' records — concurrent epochs would show up exactly here
    agreed = {}
    for r in (0, 1, 2):
        for ep, world in _applied_worlds(
                _events(_flight_paths(outs[r])[-1])).items():
            assert agreed.setdefault(ep, world) == world, \
                (r, ep, world, agreed)
    assert agreed[1] == (1, 2) and agreed[2] == (0, 1, 2)

    # the majority side observed the cut and (later) the heal
    maj_events = _events(_flight_paths(outs[1])[-1])
    maj_kinds = [e["kind"] for e in maj_events]
    assert "fault.partition" in maj_kinds
    assert "fault.partition_healed" in maj_kinds
    healed = [e for e in maj_events
              if e["kind"] == "fault.partition_healed"][0]
    assert healed["after_ms"] >= heal_ms

    # -- satellite 3: bps_doctor folds the dumps into one incident ----
    from tools.bps_doctor import diagnose_postmortem, render_markdown
    report = diagnose_postmortem(str(tmp_path))
    p = report["partition"]
    assert p["side_a"] == [0] and p["side_b"] == [1, 2]
    assert p["parked_ranks"] == [0]
    assert p["healed"] is True
    assert p["split_ms"] >= heal_ms
    assert "Network partition" in render_markdown(report)
