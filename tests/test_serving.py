"""Parameter-serving plane tests (server/serving.py + serve_client.py).

What is pinned here:

- snapshot cutting: monotonic ids, bounded retention, per-snapshot
  version vectors, ATOMIC publication (a reader sees a complete cut or
  the previous complete cut — never a torn multi-key view), and the
  copy-on-write contract (cutting copies nothing; pushes after a cut
  leave the snapshot frozen);
- delta pulls: only keys whose version advanced travel, wire-byte
  accounting is exact (O(churn), not O(model)), codec-encoded where the
  training plane registered a codec, full-snapshot fallback when the
  client's snapshot id aged out of retention;
- the ``serve_pull`` reply hop: chaos bitflips are NACKed and
  retransmitted to exact values (the PR-4 envelope machine);
- hot-key replication: pull-count histogram → replica sets, reads fan
  across replicas, writes stay primary-routed, a killed replica
  degrades to primary-served pulls with ZERO failed reads, and
  ``reshard()`` rebuilds the sets for a changed world;
- staleness-bounded client pulls: fresh cache serves locally, stale
  blocks or async-prefetches by the caller's choice;
- ISSUE 9 satellites: a slow pull copies OUTSIDE the store lock (pushes
  are not serialized behind it), ``clear()`` re-syncs the membership
  epoch, ``debug_state()`` clamps the dedup-floor listing.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from byteps_tpu.common.config import Config, reset_config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import injector as inj
from byteps_tpu.fault import membership as mm
from byteps_tpu.server import kv_store as kv_mod
from byteps_tpu.server.kv_store import DEBUG_FLOORS_MAX, KVStore
from byteps_tpu.server.serve_client import PullClient
from byteps_tpu.server.serving import ServingPlane, SnapshotStore


@pytest.fixture(autouse=True)
def _fresh():
    yield
    inj.disarm()


def _store(keys, numel=8, dtype=np.float32):
    s = KVStore()
    for k in keys:
        s.init_key(k, np.zeros(numel, dtype))
    return s


# -- snapshots --------------------------------------------------------------

def test_snapshot_ids_monotonic_and_retention_bounded():
    s = _store(["a"])
    ss = SnapshotStore(s, retention=3)
    ids = []
    for _ in range(6):
        s.push_delta("a", np.ones(8, np.float32))
        ids.append(ss.cut().id)
    assert ids == sorted(ids) == list(range(1, 7))
    assert len(ss.ring) == 3
    assert ss.ring.get(ids[0]) is None          # aged out
    assert ss.ring.get(ids[-1]).versions == {"a": 6}


def test_snapshot_version_vector_and_cow_freeze():
    s = _store(["a", "b"])
    ss = SnapshotStore(s, retention=4)
    s.push_delta("a", np.ones(8, np.float32))
    snap = ss.cut()
    assert snap.versions == {"a": 1, "b": 0}
    # pushes AFTER the cut must not leak into the frozen snapshot
    s.push_delta("a", np.ones(8, np.float32))
    s.push_delta("b", np.ones(8, np.float32))
    assert snap.refs["a"][0] == 1.0 and snap.refs["b"][0] == 0.0
    assert s.pull("a")[0] == 2.0 and s.pull("b")[0] == 1.0
    with pytest.raises(ValueError):
        snap.refs["a"][0] = 9.0                 # read-only view


def test_snapshot_publish_is_atomic_under_concurrent_cuts():
    """A reader polling latest() while cuts race must only ever observe
    complete, internally-consistent version vectors."""
    s = _store(["x", "y"])
    ss = SnapshotStore(s, retention=4)
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            snap = ss.ring.latest()
            if snap is None:
                continue
            # the invariant the writer maintains: x and y move together
            if snap.versions["x"] != snap.versions["y"]:
                bad.append(dict(snap.versions))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for _ in range(60):
        with s.write_batch():
            s.push_delta("x", np.ones(8, np.float32))
            s.push_delta("y", np.ones(8, np.float32))
        ss.cut()
    stop.set()
    t.join(timeout=10)
    assert bad == []


def test_write_subscription_cuts_only_at_consistent_points():
    """The auto-cut hook fires at write_batch exit, never mid-batch —
    no snapshot can split a multi-key update from one writer."""
    s = _store(["x", "y"])
    ss = SnapshotStore(s, retention=8, cut_interval_s=0.0)
    for _ in range(5):
        with s.write_batch():
            s.push_delta("x", np.ones(8, np.float32))
            s.push_delta("y", np.ones(8, np.float32))
    seen = [ss.ring.get(i) for i in range(1, 100)]
    for snap in filter(None, seen):
        assert snap.versions["x"] == snap.versions["y"], snap.versions
    assert ss.ring.latest().versions == {"x": 5, "y": 5}


# -- delta pulls ------------------------------------------------------------

def test_delta_pull_ships_only_changed_keys_exact_bytes():
    """The acceptance pin: wire-byte accounting proves a delta pull
    transfers ONLY the changed keys' encoded bytes."""
    numel = 256
    s = _store(["a", "b", "c"], numel=numel)
    plane = ServingPlane(s, replicas=1, retention=8)
    for k in ("a", "b", "c"):
        s.push_delta(k, np.ones(numel, np.float32))
    plane.cut()
    client = PullClient(plane, max_staleness_s=0.0)
    client.pull()
    key_bytes = numel * 4
    assert client.bytes_received == 3 * key_bytes       # full hydration
    s.push_delta("b", np.ones(numel, np.float32))
    plane.cut()
    vals = client.pull()
    assert client.bytes_received == 4 * key_bytes       # +ONE key only
    assert vals["b"][0] == 2.0 and vals["a"][0] == 1.0
    assert counters.get("serve.delta_pulls") >= 1
    # nothing changed -> zero-byte delta
    plane.cut()
    client.pull()
    assert client.bytes_received == 4 * key_bytes


def test_full_snapshot_fallback_when_since_id_aged_out():
    s = _store(["a"], numel=16)
    plane = ServingPlane(s, replicas=1, retention=2)
    s.push_delta("a", np.ones(16, np.float32))
    plane.cut()
    client = PullClient(plane, max_staleness_s=0.0)
    client.pull()
    old_sid = client.snapshot_id
    for _ in range(4):                  # push retention past old_sid
        s.push_delta("a", np.ones(16, np.float32))
        plane.cut()
    assert plane.snapstore.ring.get(old_sid) is None
    client.pull()
    assert counters.get("serve.retention_miss") == 1
    assert counters.get("serve.full_pulls") >= 2        # hydrate + fallback
    assert client.pull()["a"][0] == 5.0


def test_codec_encoded_delta_pull_reuses_training_codec():
    import jax.numpy as jnp

    from byteps_tpu.compression import registry as creg
    numel = 8192
    s = _store(["g"], numel=numel)
    s.register_compression("g", {"compressor": "onebit"}, numel)
    comp = creg.create({"compressor": "onebit"}, numel, np.float32)
    payload, _ = comp.compress(jnp.ones(numel), comp.init_state())
    s.push_delta_wire("g", comp.wire_encode(payload), worker_id=0, seq=1)
    plane = ServingPlane(s, replicas=1)
    plane.cut()
    client = PullClient(plane, max_staleness_s=0.0)
    vals = client.pull()
    # the client decodes the same wire bytes the server encoded: exact
    # agreement with a server-side round-trip of the stored value
    expect = np.asarray(comp.decompress(
        comp.compress(s.pull("g"), comp.init_state())[0]))
    np.testing.assert_allclose(vals["g"], expect)
    # onebit wire encoding beats raw float32 at this size
    assert 0 < client.bytes_received < numel * 4


def test_torn_snapshot_never_observed_by_concurrent_pullers():
    """Acceptance pin: a writer advances two keys in lockstep (one
    write_batch per step, auto-cut subscription); concurrent delta-pull
    clients must NEVER see the keys diverge."""
    numel = 64
    s = _store(["w.a", "w.b"], numel=numel)
    plane = ServingPlane(s, replicas=2, retention=8,
                         cut_interval_s=0.0)
    with s.write_batch():
        s.push_delta("w.a", np.ones(numel, np.float32))
        s.push_delta("w.b", np.ones(numel, np.float32))
    stop = threading.Event()
    torn = []

    def puller():
        client = PullClient(plane, max_staleness_s=0.0)
        while not stop.is_set():
            vals = client.pull()
            if vals and vals["w.a"][0] != vals["w.b"][0]:
                torn.append((float(vals["w.a"][0]),
                             float(vals["w.b"][0])))

    threads = [threading.Thread(target=puller, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    for _ in range(50):
        with s.write_batch():
            s.push_delta("w.a", np.ones(numel, np.float32))
            s.push_delta("w.b", np.ones(numel, np.float32))
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert torn == []
    assert s.pull("w.a")[0] == 51.0


# -- the serve_pull chaos hop (integrity lane) ------------------------------

@pytest.mark.integrity
def test_serve_pull_bitflip_nacked_and_retransmitted(monkeypatch):
    """A corrupted pull reply is NACKed and retransmitted from the
    sealed source — the client converges to exact values."""
    monkeypatch.setenv("BYTEPS_INTEGRITY_MAX_RETRANSMITS", "8")
    reset_config()
    inj.arm("bitflip:site=serve_pull:p=0.3", seed=7, rank=0)
    numel = 128
    s = _store(["k"], numel=numel)
    plane = ServingPlane(s, replicas=1)
    plane.cut()
    client = PullClient(plane, max_staleness_s=0.0)
    for i in range(12):
        s.push_delta("k", np.ones(numel, np.float32))
        plane.cut()
        vals = client.pull()
    assert vals["k"][0] == 12.0 and (vals["k"] == vals["k"][0]).all()
    assert counters.get("integrity.crc_reject") > 0
    assert counters.get("integrity.retransmit") > 0
    assert counters.get("serve.pull_bytes_wasted") > 0


@pytest.mark.integrity
def test_serve_pull_corruption_reaches_client_with_integrity_off(
        monkeypatch):
    """The unprotected baseline the envelope exists to fix: integrity
    off + a serve_pull bitflip lands silently in the reply."""
    monkeypatch.setenv("BYTEPS_INTEGRITY", "0")
    reset_config()
    inj.arm("bitflip:site=serve_pull:p=1", seed=0, rank=0)
    s = _store(["k"], numel=64)
    s.push_delta("k", np.ones(64, np.float32))
    plane = ServingPlane(s, replicas=1)
    plane.cut()
    vals = PullClient(plane, max_staleness_s=0.0).pull()
    assert not np.array_equal(vals["k"], s.pull("k"))
    assert counters.get("integrity.crc_reject") == 0


# -- hot-key replication (chaos lane) ---------------------------------------

def _warm_plane(keys, numel=64, replicas=3):
    s = _store(keys, numel=numel)
    plane = ServingPlane(s, replicas=replicas, retention=8, hot_keys=8)
    for k in keys:
        s.push_delta(k, np.ones(numel, np.float32))
    plane.cut()
    warm = PullClient(plane, max_staleness_s=0.0)
    warm.pull()                 # populate the pull-count histogram
    plane.cut()                 # mirror the now-hot keys
    return s, plane


def test_hot_key_histogram_drives_replica_sets():
    from byteps_tpu.server.sharding import ServerAssigner
    a = ServerAssigner(num_servers=4, fn="djb2", replicas=2, hot_keys=2)
    for _ in range(5):
        a.record_pull("hot.a")
    for _ in range(3):
        a.record_pull("hot.b")
    a.record_pull("cold.c")
    assert a.hot_keys() == ["hot.a", "hot.b"]
    sets = a.rebuild_replicas()
    assert set(sets) == {"hot.a", "hot.b"}
    for key, shard_set in sets.items():
        assert len(shard_set) == 2 == len(set(shard_set))
        # writes stay primary-routed: the set's head IS the primary
        assert shard_set[0] == a.write_target(key)
    assert a.replica_set("cold.c") == [a.write_target("cold.c")]


def test_reads_fan_across_replicas_writes_stay_primary():
    s, plane = _warm_plane(["r.a", "r.b"])
    client = PullClient(plane, max_staleness_s=0.0)
    for _ in range(6):
        client.pull()
    assert counters.get("serve.replica_reads") > 0
    assert plane.debug_state()["hot_keys_mirrored"] == 2
    # a write lands in the ONE store; the next cut propagates it to
    # every replica mirror (no forked value history)
    s.push_delta("r.a", np.ones(64, np.float32))
    plane.cut()
    assert client.pull()["r.a"][0] == 2.0


@pytest.mark.chaos
def test_serve_killed_replica_degrades_to_primary_zero_failed_reads():
    """Acceptance pin: kill replicas under concurrent training pushes —
    every pull keeps answering (primary degradation), zero failed
    reads."""
    numel = 256
    s, plane = _warm_plane(["h.a", "h.b"], numel=numel)
    stop = threading.Event()
    pushing = threading.Event()
    pushing.set()
    paused = threading.Event()
    pushes = [0]

    def pusher():
        while not stop.is_set():
            if not pushing.is_set():
                paused.set()        # handshake: no further cuts until
                time.sleep(0.001)   # pushing is re-set
                continue
            paused.clear()
            with s.write_batch():
                s.push_delta("h.a", np.ones(numel, np.float32))
                s.push_delta("h.b", np.ones(numel, np.float32))
            pushes[0] += 1
            plane.cut()

    failed = []
    results = [0]

    def puller():
        client = PullClient(plane, max_staleness_s=0.0)
        while not stop.is_set():
            try:
                vals = client.pull()
            except Exception as e:  # noqa: BLE001 — exactly what must
                failed.append(repr(e))          # never happen
                return
            assert vals["h.a"][0] == vals["h.b"][0]
            results[0] += 1

    pt = threading.Thread(target=pusher, daemon=True)
    ts = [threading.Thread(target=puller, daemon=True) for _ in range(2)]
    pt.start()
    for t in ts:
        t.start()
    time.sleep(0.2)
    # kill EVERY replica mid-traffic.  Cutting is paused so the mirror
    # sets still point at the corpses: the next pulls MUST pay the
    # discovery hop (ServeUnavailable -> serve.replica_fallback) and
    # still answer from the primary
    pushing.clear()
    assert paused.wait(timeout=30)  # the in-flight cut (if any) is done
    for rep in plane.replicas:
        rep.kill()
    probe = PullClient(plane, max_staleness_s=0.0)
    for _ in range(4):
        assert probe.pull()["h.a"][0] >= 1.0
    assert counters.get("serve.replica_fallback") > 0   # dead hop paid
    assert counters.get("serve.primary_reads") > 0      # ...and degraded
    pushing.set()           # cuts resume: corpses leave the mirror sets
    time.sleep(0.3)
    stop.set()
    pt.join(timeout=10)
    for t in ts:
        t.join(timeout=10)
    assert failed == []
    assert results[0] > 0 and pushes[0] > 0
    assert plane.debug_state()["dead_replicas"] == [1, 2]


def test_reshard_rebuilds_replica_sets_and_revives():
    s, plane = _warm_plane(["e.a", "e.b"], replicas=3)
    client = PullClient(plane, max_staleness_s=0.0)
    plane.reshard(1)                    # world shrank to the primary
    assert all(not r.alive for r in plane.replicas)
    assert client.pull()["e.a"][0] == 1.0
    assert plane.debug_state()["hot_keys_mirrored"] == 0
    plane.reshard(3)                    # rejoin re-opens the endpoints
    assert all(r.alive for r in plane.replicas)
    client.pull()
    plane.cut()
    assert plane.debug_state()["hot_keys_mirrored"] == 2
    assert counters.get("serve.reshards") == 2


def test_replica_set_and_ring_stay_routable_during_inflight_reshard():
    """ISSUE 15 satellite: ``ServerAssigner.replica_set`` (and the
    plane routing built on it) was only ever tested AT REST around a
    reshard.  Here pulls stay in flight while the world reshapes
    repeatedly: every concurrently-derived replica set must stay
    routable (distinct shards, inside the live clamp, head ==
    write_target) and every plane pull must succeed — a torn
    cache/replica-set view mid-``reshard()`` would surface as an
    out-of-range shard or a failed read."""
    from byteps_tpu.server.sharding import ServerAssigner
    s, plane = _warm_plane(["r.a", "r.b", "r.c"], replicas=3)
    assigner = ServerAssigner(num_servers=3, fn="djb2", mixed_mode=False,
                              bound=101, replicas=3, hot_keys=8)
    for k in ("r.a", "r.b", "r.c"):
        for _ in range(4):
            assigner.record_pull(k)
    assigner.rebuild_replicas()
    stop = threading.Event()
    failures: list = []
    pulls = [0]

    def spin_replica_set():
        # structural invariants only while racing (a reshard landing
        # between two reads legitimately changes the answer): distinct
        # shards, never outside the LARGEST world the loop uses
        while not stop.is_set():
            for k in ("r.a", "r.b", "r.c"):
                rs = assigner.replica_set(k)
                if (not rs or len(set(rs)) != len(rs)
                        or any(not 0 <= sid < 3 for sid in rs)):
                    failures.append((k, rs))

    def spin_plane_pulls():
        client = PullClient(plane, max_staleness_s=0.0)
        while not stop.is_set():
            try:
                vals = client.pull()
            except Exception as e:  # noqa: BLE001 — the one promise
                failures.append(("pull", repr(e)))
                continue
            if vals["r.a"][0] != 1.0:
                failures.append(("value", vals["r.a"][0]))
            pulls[0] += 1

    threads = [threading.Thread(target=spin_replica_set, daemon=True),
               threading.Thread(target=spin_plane_pulls, daemon=True),
               threading.Thread(target=spin_plane_pulls, daemon=True)]
    for t in threads:
        t.start()
    for i in range(30):
        n = (i % 3) + 1
        assigner.reshard(n)
        plane.reshard(n)
        time.sleep(0.01)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert failures == []
    assert pulls[0] > 20
    # at rest, the full contract again: deterministic sets, distinct
    # shards inside the final world, head == write_target
    n = assigner.num_servers
    for k in ("r.a", "r.b", "r.c"):
        rs = assigner.replica_set(k)
        assert rs == assigner.replica_set(k)
        assert len(set(rs)) == len(rs)
        assert all(0 <= sid < n for sid in rs)
        assert rs[0] == assigner.write_target(k)


def test_membership_world_change_reshards_active_planes():
    from byteps_tpu.server import serving as serving_mod
    s, plane = _warm_plane(["m.a"], replicas=3)
    view = mm.MembershipView(epoch=1, world=(0,))
    serving_mod.notify_world_change(view)
    assert plane.debug_state()["alive_clamp"] == 1
    assert PullClient(plane, max_staleness_s=0.0).pull()["m.a"][0] == 1.0


# -- staleness-bounded client pulls -----------------------------------------

def test_fresh_cache_serves_locally_without_wire_traffic():
    s, plane = _warm_plane(["s.a"])
    client = PullClient(plane, max_staleness_s=60.0)
    client.pull()
    served = counters.get("serve.pulls")
    got = client.bytes_received
    for _ in range(5):
        assert client.pull()["s.a"][0] == 1.0
    assert counters.get("serve.pulls") == served        # no plane trips
    assert client.bytes_received == got
    assert counters.get("serve.cache_hits") == 5


def test_stale_cache_blocking_refresh_picks_up_new_values():
    s, plane = _warm_plane(["s.b"], numel=64)
    client = PullClient(plane, max_staleness_s=0.0)
    assert client.pull()["s.b"][0] == 1.0
    s.push_delta("s.b", np.ones(64, np.float32))
    plane.cut()
    assert client.pull()["s.b"][0] == 2.0               # bound 0: refetch


def test_async_prefetch_serves_stale_then_converges():
    s, plane = _warm_plane(["s.c"], numel=64)
    client = PullClient(plane, max_staleness_s=0.0, prefetch=True)
    client.pull()                                       # first: blocking
    s.push_delta("s.c", np.ones(64, np.float32))
    plane.cut()
    first = client.pull()                               # stale, instant
    assert first["s.c"][0] in (1.0, 2.0)
    assert counters.get("serve.stale_served") >= 1
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if client.pull()["s.c"][0] == 2.0:
            break
        time.sleep(0.01)
    assert client.pull()["s.c"][0] == 2.0
    assert counters.get("serve.async_refresh") >= 1


def test_per_pull_staleness_override_and_config_default(monkeypatch):
    monkeypatch.setenv("BYTEPS_SERVE_MAX_STALENESS", "123.0")
    reset_config()
    s, plane = _warm_plane(["s.d"], numel=64)
    client = PullClient(plane)
    assert client.max_staleness_s == 123.0
    client.pull()
    s.push_delta("s.d", np.ones(64, np.float32))
    plane.cut()
    assert client.pull()["s.d"][0] == 1.0               # fresh per config
    assert client.pull(max_staleness_s=0.0)["s.d"][0] == 2.0


def test_serve_config_validation():
    with pytest.raises(ValueError):
        Config(serve_replicas=0)
    with pytest.raises(ValueError):
        Config(serve_retention=0)
    with pytest.raises(ValueError):
        Config(serve_max_staleness_s=-1.0)
    with pytest.raises(ValueError):
        Config(serve_cut_interval_s=-0.1)


# -- ISSUE 9 satellites ------------------------------------------------------

def test_slow_pull_does_not_serialize_pushes(monkeypatch):
    """Satellite: the pull-path copy runs OUTSIDE the store lock — a
    slow pull of a large key must not stall concurrent pushes."""
    s = _store(["big", "other"], numel=64)
    s.push_delta("big", np.ones(64, np.float32))
    started = threading.Event()
    release = threading.Event()
    orig = kv_mod._copy_outside_lock

    def slow_copy(arr):
        started.set()
        assert release.wait(timeout=30)
        return orig(arr)

    monkeypatch.setattr(kv_mod, "_copy_outside_lock", slow_copy)
    out = {}

    def slow_puller():
        out["v"] = s.pull("big")

    t = threading.Thread(target=slow_puller, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    # the pull is parked inside its copy; pushes must sail through
    monkeypatch.setattr(kv_mod, "_copy_outside_lock", orig)
    for _ in range(10):
        s.push_delta("other", np.ones(64, np.float32))
        s.push_delta("big", np.ones(64, np.float32))
    assert s.version("other") == 10 and s.version("big") == 11
    release.set()
    t.join(timeout=30)
    # ...and the parked pull still copied a CONSISTENT value: the COW
    # mark made the concurrent pushes replace the array, not mutate it
    assert out["v"][0] == 1.0


def test_clear_resets_membership_epoch():
    """Satellite: a cleared-and-reused store must accept the CURRENT
    world's deltas instead of dropping them as stale forever."""
    s = _store(["k"], numel=4)
    s.set_membership_epoch(7)
    # stale-dropped: the version stays at 0, the delta never lands
    assert s.push_delta("k", np.ones(4, np.float32),
                        mepoch=mm.current_epoch()) == 0
    assert s.pull("k")[0] == 0.0
    s.clear()
    s.init_key("k", np.zeros(4, np.float32))
    assert s.push_delta("k", np.ones(4, np.float32),
                        mepoch=mm.current_epoch()) == 1   # accepted
    assert counters.get("membership.stale_pushes_dropped") == 1


def test_debug_state_clamps_dedup_floors():
    """Satellite: /debug/state lists at most DEBUG_FLOORS_MAX floors —
    the lowest (laggard) ones — plus the true total count."""
    s = _store(["k"], numel=4)
    n = DEBUG_FLOORS_MAX + 9
    for w in range(n):
        # worker w's floor ends at w+1: worker 0 is the laggard
        s.push_delta("k", np.ones(4, np.float32), worker_id=w, seq=w + 1)
    d = s.debug_state()
    assert d["dedup_floor_count"] == n
    assert len(d["dedup_floors"]) == DEBUG_FLOORS_MAX
    assert set(d["dedup_floors"].values()) == set(
        range(1, DEBUG_FLOORS_MAX + 1))


def test_clear_bumps_generation_so_stale_delta_bases_go_full():
    """A store clear restarts versions at 0; a client whose snapshot
    predates the clear must get a FULL reply, never a 'delta' that
    skips re-initialized keys and serves pre-clear values as fresh."""
    s = _store(["g.a"], numel=16)
    plane = ServingPlane(s, replicas=1, retention=8)
    for _ in range(5):
        s.push_delta("g.a", np.ones(16, np.float32))
    plane.cut()
    client = PullClient(plane, max_staleness_s=0.0)
    assert client.pull()["g.a"][0] == 5.0
    s.clear()                               # re-keyed store, version 0
    s.init_key("g.a", np.full(16, 42.0, np.float32))
    plane.cut()
    vals = client.pull()                    # base snapshot: old gen
    assert vals["g.a"][0] == 42.0           # NOT the stale 5.0
    assert client.version("g.a") == 0


def test_start_serving_defaults_write_driven_cutting(monkeypatch):
    """bps.start_serving honors BYTEPS_SERVE_CUT_INTERVAL — a plane
    started through the product entry point publishes on writes without
    anyone calling cut()."""
    import byteps_tpu as bps
    monkeypatch.setenv("BYTEPS_SERVE_CUT_INTERVAL", "0.0")
    reset_config()
    s = _store(["w"], numel=8)
    plane = bps.start_serving(s, replicas=1)
    try:
        s.push_delta("w", np.ones(8, np.float32))
        snap = plane.snapstore.ring.latest()
        assert snap is not None and snap.versions == {"w": 1}
        # explicit opt-out still means manual cuts only
        s2 = _store(["w"], numel=8)
        plane2 = bps.start_serving(s2, replicas=1, cut_interval_s=None)
        s2.push_delta("w", np.ones(8, np.float32))
        assert plane2.snapstore.ring.latest() is None
    finally:
        plane.close()


def test_plane_close_detaches_write_driven_cutting():
    """A dropped plane must detach: the store's subscriber list holds
    strong references, so without close() it would keep cutting (and
    stay alive) for the store's lifetime."""
    s = _store(["d.a"], numel=8)
    plane = ServingPlane(s, replicas=1, cut_interval_s=0.0)
    s.push_delta("d.a", np.ones(8, np.float32))
    sid = plane.snapstore.ring.latest().id
    plane.close()
    s.push_delta("d.a", np.ones(8, np.float32))
    assert plane.snapstore.ring.latest().id == sid    # no further cuts
    plane.close()                                     # idempotent


def test_snapshot_encode_memoized_across_clients():
    """N clients refreshing against one cut must not pay N identical
    compressions: the wire encoding is cached per (snapshot, key)."""
    import jax.numpy as jnp

    from byteps_tpu.compression import registry as creg
    numel = 4096
    s = _store(["g"], numel=numel)
    s.register_compression("g", {"compressor": "onebit"}, numel)
    comp = creg.create({"compressor": "onebit"}, numel, np.float32)
    payload, _ = comp.compress(jnp.ones(numel), comp.init_state())
    s.push_delta_wire("g", comp.wire_encode(payload), worker_id=0, seq=1)
    plane = ServingPlane(s, replicas=1)
    snap = plane.cut()
    first = PullClient(plane, max_staleness_s=0.0)
    first.pull()
    assert "g" in snap.enc_cache                      # encoded once...
    sentinel = comp.wire_encode(
        comp.compress(jnp.zeros(numel), comp.init_state())[0])
    snap.enc_cache["g"] = sentinel
    second = PullClient(plane, max_staleness_s=0.0)
    vals = second.pull()
    assert np.allclose(vals["g"], 0.0)                # ...served cached
    assert second.bytes_received == len(sentinel)


def test_empty_key_list_pull_answers_without_crashing():
    """plane.pull(keys=[]) with hot keys mirrored must not trip the
    replica-eligibility intersection (an empty loop once left it None
    and the alive filter crashed on `in None`)."""
    s, plane = _warm_plane(["z.a"])
    reply = plane.pull(keys=[])
    assert reply.items == {} and reply.wire_bytes == 0
    assert PullClient(plane, keys=[], max_staleness_s=0.0).pull() == {}


def test_unbounded_staleness_first_pull_still_hydrates():
    """max_staleness_s=inf must not defeat the first-pull-always-blocks
    contract (inf <= inf 'hit' an empty cache forever)."""
    s, plane = _warm_plane(["u.a"], numel=8)
    client = PullClient(plane, max_staleness_s=float("inf"))
    vals = client.pull()                    # first: blocking hydration
    assert vals["u.a"][0] == 1.0 and client.snapshot_id is not None
    s.push_delta("u.a", np.ones(8, np.float32))
    plane.cut()
    assert client.pull()["u.a"][0] == 1.0   # then: cache forever


def test_partial_replica_refuses_uncovered_keys_router_degrades():
    """A replica asked for a key outside its mirror snapshot must
    REFUSE (router falls to the primary) — silently skipping it would
    stamp the reply with a snapshot id whose version vector already
    covers the key, and the update would never be re-shipped."""
    from byteps_tpu.server.serving import (ServeUnavailable,
                                           SnapshotServer)
    s, plane = _warm_plane(["p.a", "p.b"])
    rep = plane.replicas[0]
    assert rep.partial
    with pytest.raises(ServeUnavailable):
        rep.pull(keys=["p.a", "not.mirrored"])
    # plane level: stale mirror map claiming coverage degrades cleanly
    with plane._lock:
        plane._mirrored["ghost"] = [rep.server_id]
        plane._mirrored["p.a"] = [rep.server_id]
    reply = plane.pull(keys=["p.a", "ghost"])
    assert reply.server_id == 0             # primary answered
    assert "p.a" in reply.items             # ...completely
    assert counters.get("serve.replica_fallback") >= 1


# -- hedged pulls (ISSUE 10, chaos straggler lane) ---------------------------


def test_hedge_off_by_default_and_policy_knobs():
    from byteps_tpu.common.config import set_config
    s = _store(["hk.a"])
    assert not ServingPlane(s)._hedge            # wait = sequential
    assert ServingPlane(s, hedge=True)._hedge    # explicit opt-in
    set_config(Config(straggler_policy="hedge"))
    try:
        assert ServingPlane(s)._hedge            # policy default
        assert not ServingPlane(s, hedge=False)._hedge   # override wins
    finally:
        reset_config()


def test_hedge_delay_fixed_and_adaptive():
    from byteps_tpu.common.config import set_config
    s = _store(["hd.a"])
    plane = ServingPlane(s, hedge=True)
    assert plane._hedge_delay_s() == 0.002       # cold: no history yet
    for _ in range(50):
        plane._hedge_lat.observe(0.004)
    plane._hedge_lat.observe(0.020)              # one slow winner
    # adaptive = p99 of recent WINNING latencies, clamped
    assert plane._hedge_delay_s() == pytest.approx(0.020)
    set_config(Config(serve_hedge_ms=5.0))
    try:
        assert ServingPlane(s, hedge=True)._hedge_delay_s() == 0.005
    finally:
        reset_config()


@pytest.mark.chaos
def test_hedged_pull_bounds_tail_under_one_slow_replica():
    """Acceptance direction: one serving endpoint slow-but-alive (the
    gray failure) — hedged pulls answer from a backup after the hedge
    delay, so the tail stops tracking the slow endpoint's 80ms, while
    every reply stays correct and late duplicates are discarded."""
    s, plane = _warm_plane(["hg.a", "hg.b"], replicas=3)
    plane._hedge = True
    slow = plane.replicas[0]
    slow.delay_s = 0.08
    client = PullClient(plane, max_staleness_s=0.0, hedge=True)
    lats = []
    for _ in range(30):
        t0 = time.perf_counter()
        vals = client.pull()
        lats.append(time.perf_counter() - t0)
        # correctness never hedged away
        assert vals["hg.a"][0] == 1.0 and vals["hg.b"][0] == 1.0
    lats.sort()
    # the slow endpoint sits in the rotation, so WITHOUT hedging a
    # large fraction of pulls would cost >= 80ms; hedged, the tail is
    # bounded by hedge-delay + a healthy pull (generous CI margin)
    assert lats[int(len(lats) * 0.9)] < 0.04, lats
    assert counters.get("serve.hedged_pulls") > 0
    assert counters.get("serve.hedge_wins") > 0
    # the slow endpoint's late replies were discarded, not double-used
    time.sleep(0.15)
    assert counters.get("serve.hedge_discarded") > 0
    assert counters.get("serve.unavailable") == 0
    # the slowness tracker saw per-endpoint latency: the slow endpoint
    # is VISIBLE even while hedging hides it from clients
    from byteps_tpu.utils import slowness as _slowness
    snap = _slowness.tracker().snapshot()
    assert "serve_pull" in snap
    assert snap["serve_pull"][slow.server_id]["median_ms"] >= 50.0


@pytest.mark.chaos
def test_hedged_pull_survives_dead_candidates_and_raises_when_all_dead():
    s, plane = _warm_plane(["hx.a"], replicas=3)
    plane._hedge = True
    for rep in plane.replicas:
        rep.kill()
    # dead replicas: the hedge race still lands on the primary
    reply = plane.pull()
    assert reply.server_id == 0
    # everything dead: the failure propagates like the sequential path
    plane.primary.kill()
    from byteps_tpu.server.serving import ServeUnavailable
    with pytest.raises(ServeUnavailable):
        plane.pull()


def test_pull_client_hedge_override_reaches_the_plane():
    s, plane = _warm_plane(["hc.a", "hc.b"], replicas=3)
    assert not plane._hedge                      # plane default: off
    before = counters.get("serve.hedged_pulls")
    slow = plane.replicas[0]
    slow.delay_s = 0.05
    client = PullClient(plane, max_staleness_s=0.0, hedge=True)
    for _ in range(6):
        client.pull()
    assert counters.get("serve.hedged_pulls") > before


# -- the bench tool ----------------------------------------------------------

def test_serve_bench_reports_throughput_latency_and_delta_accounting():
    from tools import serve_bench
    out = serve_bench.measure(seconds=0.3, clients=2, keys=3,
                              numel=1024, replicas=2)
    assert out["pulls"] > 0 and out["pulls_per_s"] > 0
    assert out["p99_ms"] >= out["p50_ms"] >= 0
    assert out["pushes"] > 0                # concurrent training pushes
    assert out["failed_reads"] == 0
    check = serve_bench.delta_check(numel=512, keys=3)
    assert check["ok"]
    assert check["full_pull_bytes"] == 3 * 512 * 4
    assert check["delta_pull_bytes"] == 512 * 4
