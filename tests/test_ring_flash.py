"""Ring-flash attention (parallel/ring_flash.py): flash kernels inside
ring sequence parallelism, pinned against single-device full attention
and plain ring attention on the 8-device CPU mesh.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from byteps_tpu.parallel import (full_attention, make_sp_attention,
                                 make_sp_mesh)
from byteps_tpu.parallel.ring_flash import ring_flash_attention
from byteps_tpu.parallel.sequence import DP_AXIS, SP_AXIS





pytestmark = pytest.mark.slow  # multi-device attention integration: minutes of XLA compile on small CPU hosts (tier-1 budget)
def _qkv(b, t, h, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32
                                   ).astype(dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_sp", [4, 8])
def test_matches_full_attention(causal, n_sp):
    b, t, h, d = 2, 128, 2, 32
    q, k, v = _qkv(b, t, h, d)
    mesh = make_sp_mesh(jax.devices()[:8], n_sp=n_sp)
    attn = make_sp_attention(mesh, kind="ring_flash", causal=causal)
    sh = NamedSharding(mesh, P(DP_AXIS, SP_AXIS))
    got = attn(*jax.device_put((q, k, v), sh))
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_ring_attention_trajectory(causal):
    """Gradients through the manual vjp == gradients through plain ring
    attention (autodiff through the ppermutes), both mask modes —
    causal=False exercises the unconditional accumulation path of the
    hand-written backward."""
    b, t, h, d = 2, 64, 2, 32
    q, k, v = _qkv(b, t, h, d, seed=3)
    w = jax.random.normal(jax.random.PRNGKey(9), (b, t, h, d))
    mesh = make_sp_mesh(jax.devices()[:8], n_sp=4)
    sh = NamedSharding(mesh, P(DP_AXIS, SP_AXIS))
    qs, ks_, vs, ws = jax.device_put((q, k, v, w), sh)

    grads = {}
    for kind in ("ring_flash", "ring"):
        attn = make_sp_attention(mesh, kind=kind, causal=causal)
        f = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(attn(q, k, v) * ws), argnums=(0, 1, 2)))
        grads[kind] = f(qs, ks_, vs)
    for a, b_ in zip(grads["ring_flash"], grads["ring"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


def test_ragged_t_and_d():
    """Shard length not a block multiple, head dim not a lane multiple."""
    b, t, h, d = 2, 104, 2, 48  # t/sp = 26 -> padded inside the kernels
    q, k, v = _qkv(b, t, h, d, seed=5)
    mesh = make_sp_mesh(jax.devices()[:8], n_sp=4)
    sh = NamedSharding(mesh, P(DP_AXIS, SP_AXIS))
    got = make_sp_attention(mesh, kind="ring_flash", causal=True)(
        *jax.device_put((q, k, v), sh))
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow  # long-context training loop: tier-1 budget
def test_long_context_ring_flash_training():
    """attention='ring_flash' trains the (dp, sp) GPT step and matches
    the plain-ring trajectory."""
    import optax
    from byteps_tpu.models.gpt import GPT, gpt_tiny
    from byteps_tpu.parallel import (make_dp_sp_train_step,
                                     shard_lm_batch, synthetic_lm_batch)
    from byteps_tpu.parallel.long_context import replicate

    cfg = gpt_tiny()
    mesh = make_sp_mesh(jax.devices()[:8], n_sp=4)
    batch = synthetic_lm_batch(jax.random.PRNGKey(0), cfg, batch=4,
                               seq_len=64)
    params = GPT(cfg).init(jax.random.PRNGKey(1), batch["input_ids"][:1])
    tx = optax.sgd(0.1)

    losses = {}
    for kind in ("ring_flash", "ring"):
        step = make_dp_sp_train_step(mesh, cfg, tx, attention=kind,
                                     donate=False)
        p = replicate(mesh, params)
        o = replicate(mesh, tx.init(params))
        ls = []
        for _ in range(3):
            p, o, loss = step(p, o, shard_lm_batch(mesh, batch))
            ls.append(float(loss))
        losses[kind] = ls
    # gpt_tiny computes in bf16; the softmax decompositions agree to bf16
    np.testing.assert_allclose(losses["ring_flash"], losses["ring"],
                               rtol=5e-3, atol=5e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_exact(causal):
    """kind='ulysses_flash': flash kernels as the local attention after
    the all-to-all head reshard; forward and grads match exact."""
    b, t, h, d = 2, 128, 4, 32  # heads divisible by sp
    q, k, v = _qkv(b, t, h, d, seed=7)
    w = jax.random.normal(jax.random.PRNGKey(8), (b, t, h, d))
    mesh = make_sp_mesh(jax.devices()[:8], n_sp=4)
    sh = NamedSharding(mesh, P(DP_AXIS, SP_AXIS))
    qs, ks_, vs, ws = jax.device_put((q, k, v, w), sh)

    attn = make_sp_attention(mesh, kind="ulysses_flash", causal=causal)
    got = attn(qs, ks_, vs)
    want = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    g = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(attn(q, k, v) * ws),
        argnums=(0, 1, 2)))(qs, ks_, vs)
    ref_attn = make_sp_attention(mesh, kind="ulysses", causal=causal)
    ge = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(ref_attn(q, k, v) * ws),
        argnums=(0, 1, 2)))(qs, ks_, vs)
    for a, b_ in zip(g, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)
