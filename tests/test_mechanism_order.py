"""Deterministic dispatch-order proofs for the scheduling mechanisms.

Round-3 VERDICT Weak #5 / task 3: the wall-clock mechanism benches
(`tools/mechanism_bench.py`) are load-sensitive on a shared host, but the
*mechanisms themselves* — priority reordering, chunk-granular preemption
under a credit window — are deterministic at the scheduler level.  These
tests pin exactly the dispatch-order claims docs/performance.md makes, with
zero timing dependence, against BOTH scheduler implementations (Python heap
and the native C++ twin, reference scheduled_queue.cc:82-161).

The scenario modeled is the one the latency benches measure:

- the credit window (reference BYTEPS_SCHEDULING_CREDIT) creates the
  decision point: dispatch waits for completions, so the queue holds depth;
- priority decides what dispatches next (backward produces gradients
  last-layer-first; the next forward needs layer 0 first);
- partitioning sets the preemption granularity (an urgent tensor waits out
  one *chunk* of a bulk transfer, not the whole tensor).
"""

from __future__ import annotations

import pytest

from byteps_tpu import native
from byteps_tpu.common.registry import make_key
from byteps_tpu.common.scheduler import ChunkScheduler
from byteps_tpu.common.types import ChunkTask


def _make_scheduler(impl: str, credit_bytes: int):
    if impl == "native":
        if not native.available():
            pytest.skip("native toolchain unavailable")
        return native.NativeChunkScheduler(credit_bytes=credit_bytes)
    return ChunkScheduler(credit_bytes=credit_bytes)


def _task(name, key, priority, nbytes=100):
    return ChunkTask(name=name, key=key, priority=priority, version=0,
                     offset_elems=0, num_elems=nbytes // 4, nbytes=nbytes,
                     total_parts=1)


def _drain_order(s):
    """Pop everything, returning credits after each pop (a dispatch loop
    whose every collective completes before the next pop)."""
    order = []
    while True:
        t = s.get_task()
        if t is None:
            break
        order.append(t.name)
        s.report_finish(t.nbytes)
    return order


IMPLS = ("python", "native")


@pytest.mark.parametrize("impl", IMPLS)
def test_backward_enqueue_order_dispatches_declaration_order(impl):
    """K gradients enqueued in REVERSE declaration order (backward-pass
    production order) while the window is full dispatch in DECLARATION
    order once the window opens — the priority mechanism's core claim
    (priority = -declared_key, engine.py push_pull_async)."""
    s = _make_scheduler(impl, credit_bytes=100)
    blocker = _task("blocker", key=make_key(99, 0), priority=-99)
    s.add_task(blocker)
    assert s.get_task().name == "blocker"   # fills the window
    for i in reversed(range(6)):            # layer5 arrives first
        s.add_task(_task(f"layer{i}", key=make_key(10 + i, 0), priority=-i))
    assert s.get_task() is None             # window full: queue holds depth
    s.report_finish(blocker.nbytes)
    assert _drain_order(s) == [f"layer{i}" for i in range(6)]


@pytest.mark.parametrize("impl", IMPLS)
def test_fifo_priorities_dispatch_in_arrival_order(impl):
    """The FIFO baseline (priority pinned to arrival order, what a plain
    allreduce queue executes) dispatches in arrival order — the contrast
    that makes the previous test a mechanism proof, not a tautology."""
    s = _make_scheduler(impl, credit_bytes=100)
    blocker = _task("blocker", key=make_key(99, 0), priority=0)
    s.add_task(blocker)
    s.get_task()
    for pos, i in enumerate(reversed(range(6))):
        s.add_task(_task(f"layer{i}", key=make_key(10 + i, 0),
                         priority=-pos))
    s.report_finish(blocker.nbytes)
    assert _drain_order(s) == [f"layer{i}" for i in reversed(range(6))]


@pytest.mark.parametrize("impl", IMPLS)
def test_urgent_preempts_partitioned_bulk_at_chunk_granularity(impl):
    """With a bulk tensor split into 16 chunks and a 1-chunk credit window,
    an urgent tensor arriving mid-transfer dispatches after exactly ONE
    more bulk chunk — partitioning bounds head-of-line blocking to a chunk
    (reference operations.cc:140-180 partitioning rationale)."""
    s = _make_scheduler(impl, credit_bytes=100)
    for i in range(16):
        s.add_task(_task(f"bulk{i}", key=make_key(1, i), priority=-10))
    first = s.get_task()
    assert first.name == "bulk0"            # one chunk in flight
    assert s.get_task() is None             # window full
    s.add_task(_task("urgent", key=make_key(2, 0), priority=10, nbytes=50))
    s.report_finish(first.nbytes)
    nxt = s.get_task()
    assert nxt.name == "urgent"             # preempts 15 remaining chunks
    s.report_finish(nxt.nbytes)
    # the bulk transfer then resumes in chunk order
    assert _drain_order(s) == [f"bulk{i}" for i in range(1, 16)]


@pytest.mark.parametrize("impl", IMPLS)
def test_unpartitioned_bulk_blocks_urgent_for_whole_tensor(impl):
    """The contrast case: the same bytes as ONE task (no partitioning)
    occupy the window whole, so the urgent tensor waits out the entire
    transfer — 16x the dispatched-bytes head-of-line cost of the
    partitioned case above."""
    s = _make_scheduler(impl, credit_bytes=100)
    s.add_task(_task("bulk", key=make_key(1, 0), priority=-10, nbytes=1600))
    first = s.get_task()                    # oversized-but-idle clamp
    assert first.name == "bulk"
    s.add_task(_task("urgent", key=make_key(2, 0), priority=10, nbytes=50))
    # all 1600 bulk bytes are in flight; urgent cannot dispatch until the
    # WHOLE tensor completes
    assert s.get_task() is None
    s.report_finish(first.nbytes)
    assert s.get_task().name == "urgent"


@pytest.mark.parametrize("impl", IMPLS)
def test_credit_window_admits_multiple_small_chunks(impl):
    """The window is a byte budget, not a task count: two 100 B chunks fit
    a 250 B window simultaneously, a third waits (reference
    scheduled_queue.cc:136-150)."""
    s = _make_scheduler(impl, credit_bytes=250)
    for i in range(3):
        s.add_task(_task(f"c{i}", key=make_key(1, i), priority=0))
    assert s.get_task().name == "c0"
    assert s.get_task().name == "c1"
    assert s.get_task() is None
    s.report_finish(100)
    assert s.get_task().name == "c2"
