"""Host->device prefetch pipeline (utils/prefetch.py)."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.comm.mesh import CommContext, _build_mesh
from byteps_tpu.utils.prefetch import ShardedBatchLoader, prefetch_to_device


def _batches(n, shape=(8, 4), start=0):
    for i in range(start, start + n):
        yield {"x": np.full(shape, float(i), np.float32),
               "y": np.full((shape[0],), i, np.int32)}


def test_prefetch_yields_all_batches_in_order():
    got = list(prefetch_to_device(_batches(5), size=2))
    assert len(got) == 5
    for i, b in enumerate(got):
        assert isinstance(b["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(b["y"]), np.full((8,), i))


def test_prefetch_overlaps_source_latency():
    """With a slow source, the consumer sees batches the producer staged
    ahead — total wall time ~ max(source, consume), not the sum."""
    delay = 0.15  # large vs scheduler jitter so the bound isn't flaky

    def slow():
        for b in _batches(4):
            time.sleep(delay)
            yield b

    t0 = time.perf_counter()
    for b in prefetch_to_device(slow(), size=2):
        time.sleep(delay)          # consumer work of the same magnitude
        jax.block_until_ready(b["x"])
    wall = time.perf_counter() - t0
    # serial ~8*delay = 1.2s; overlapped ~5*delay = 0.75s.  The bound
    # sits between with ~0.27s of headroom for a loaded host.
    assert wall < 6.8 * delay, f"no overlap: wall={wall:.3f}s"


def test_prefetch_early_exit_releases_producer():
    """Breaking out of the consumer loop must unblock the producer
    thread (it would otherwise park in q.put forever, pinning staged
    device batches)."""
    produced = []

    def source():
        for b in _batches(100):
            produced.append(1)
            yield b

    it = prefetch_to_device(source(), size=2)
    next(it)
    it.close()  # what a `break` does via GeneratorExit
    time.sleep(0.5)
    n_after = len(produced)
    time.sleep(0.3)
    assert len(produced) == n_after, "producer still running after close"
    assert n_after < 100
    assert threading.active_count() < 20  # no thread pile-up


def test_loader_rejects_second_pass_over_exhausted_iterator():
    comm = CommContext(mesh=_build_mesh(jax.devices()[:8], 2),
                       n_dcn=2, n_ici=4)
    loader = ShardedBatchLoader(comm, _batches(2, shape=(16, 4)))
    assert len(list(loader)) == 2
    with pytest.raises(ValueError, match="one-shot iterator"):
        list(loader)
    # a re-iterable source supports epoch loops
    data = [{"x": np.zeros((16, 4), np.float32)} for _ in range(2)]
    loader2 = ShardedBatchLoader(comm, data)
    assert len(list(loader2)) == 2
    assert len(list(loader2)) == 2


def test_prefetch_propagates_source_error():
    def bad():
        yield from _batches(2)
        raise RuntimeError("source exploded")

    it = prefetch_to_device(bad(), size=2)
    assert next(it) is not None
    assert next(it) is not None
    with pytest.raises(RuntimeError, match="source exploded"):
        next(it)


def test_sharded_batch_loader():
    comm = CommContext(mesh=_build_mesh(jax.devices()[:8], 2),
                       n_dcn=2, n_ici=4)
    loader = ShardedBatchLoader(comm, _batches(3, shape=(16, 4)))
    seen = 0
    for b in loader:
        seen += 1
        assert b["x"].sharding.is_fully_replicated is False
        assert len(b["x"].addressable_shards) == 8
        assert b["x"].addressable_shards[0].data.shape == (2, 4)
    assert seen == 3


def test_sharded_batch_loader_rejects_bad_shapes():
    comm = CommContext(mesh=_build_mesh(jax.devices()[:8], 2),
                       n_dcn=2, n_ici=4)
    with pytest.raises(ValueError, match="not divisible"):
        for _ in ShardedBatchLoader(comm, _batches(1, shape=(6, 4))):
            pass

    def changing():
        yield {"x": np.zeros((16, 4), np.float32)}
        yield {"x": np.zeros((16, 8), np.float32)}

    with pytest.raises(ValueError, match="changed mid-stream"):
        for _ in ShardedBatchLoader(comm, changing()):
            pass


def test_loader_feeds_train_step():
    """End to end: loader batches drive the fused DP train step."""
    import optax
    from byteps_tpu.models.mlp import MLP, softmax_cross_entropy
    from byteps_tpu.parallel import make_dp_train_step, replicate

    comm = CommContext(mesh=_build_mesh(jax.devices()[:8], 1),
                       n_dcn=1, n_ici=8)
    model = MLP(features=(16, 10))
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 4)))
    tx = optax.sgd(0.1)
    step = make_dp_train_step(
        comm, lambda p, b: softmax_cross_entropy(
            model.apply(p, b["x"]), b["y"]), tx, donate=False)
    p = replicate(comm, params)
    o = replicate(comm, tx.init(params))
    n_steps = 0
    for b in ShardedBatchLoader(comm, _batches(4, shape=(16, 4))):
        p, o, loss = step(p, o, b)
        n_steps += 1
    assert n_steps == 4 and np.isfinite(float(loss))
