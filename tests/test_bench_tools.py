"""Unit tests for the evidence-tool helpers (tools/): the pure logic the
bench artifacts depend on — core-slice math, pin-spec parsing, quantile
stats — pinned without wall-clock dependence."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._bench_util import quantile_stats  # noqa: E402
from tools.weak_scaling import _core_slices  # noqa: E402


def test_quantile_stats_median_and_iqr():
    med, iqr = quantile_stats([0.1, 0.2, 0.3, 0.4])
    assert med == 250.0
    assert iqr == [175.0, 325.0]


def test_quantile_stats_single_sample():
    med, iqr = quantile_stats([0.05])
    assert med == 50.0 and iqr == [50.0, 50.0]


def test_core_slices_disjoint_and_capped(monkeypatch):
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: set(range(8)), raising=False)
    # same per-worker budget regardless of group size (cap = max group's
    # share): 1-proc group must NOT get all 8 cores when the cap is 2
    assert _core_slices(1, cores_per_proc=2) == [[0, 1]]
    s4 = _core_slices(4, cores_per_proc=2)
    assert s4 == [[0, 1], [2, 3], [4, 5], [6, 7]]
    flat = [c for s in s4 for c in s]
    assert len(flat) == len(set(flat))          # disjoint
    # infeasible: 4 workers x 3 cores > 8
    assert _core_slices(4, cores_per_proc=3) is None


def test_core_slices_single_core(monkeypatch):
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: {0}, raising=False)
    assert _core_slices(4) is None
    assert _core_slices(1) == [[0]]


def test_couple_overlap_to_projection():
    import json

    import bench

    line = json.dumps({
        "overlap": {"overlap_fraction": 0.5},
        "scaling": {"analytic_v5e256": {
            "measured_step_ms_per_chip": 60.0, "allreduce_ms": 20.0,
            "efficiency_no_overlap": 0.75}},
    })
    out = json.loads(bench._couple_overlap_to_projection(line))
    an = out["scaling"]["analytic_v5e256"]
    assert an["measured_overlap_fraction"] == 0.5
    assert an["efficiency_at_measured_overlap"] == round(60 / 70, 3)
    # negative measured fraction clamps to the no-overlap end
    line2 = json.dumps({
        "overlap": {"overlap_fraction": -0.2},
        "scaling": {"analytic_v5e256": {
            "measured_step_ms_per_chip": 60.0, "allreduce_ms": 20.0}},
    })
    an2 = json.loads(bench._couple_overlap_to_projection(line2))[
        "scaling"]["analytic_v5e256"]
    assert an2["efficiency_at_measured_overlap"] == 0.75
    # missing sections pass through untouched
    assert bench._couple_overlap_to_projection("{}") == "{}"


@pytest.mark.parametrize("spec,avail,want", [
    ("off", {0, 1, 2, 3}, None),
    ("none", {0, 1, 2, 3}, None),
    ("1", {0, 1, 2, 3}, [1]),             # bare "1" is core 1, not a flag
    ("0", {0, 1, 2, 3}, [0]),
    ("0-2", {0, 1, 2, 3}, [0, 1, 2]),
    ("0,2", {0, 1, 2, 3}, [0, 2]),
    ("bogus", {0, 1, 2, 3}, None),        # malformed: unpinned, not dead
    ("", {0}, None),                      # 1-core default: nothing to pin
    ("", {0, 1, 2, 3}, [1, 2, 3]),        # default: all but core 0
    ("", {0, 1}, None),                   # 2-3 cores: full-set pin is a
                                          # no-op, don't report one
])
def test_pin_cores_spec_parsing(monkeypatch, spec, avail, want):
    from tools import _bench_util

    monkeypatch.setenv("BYTEPS_BENCH_PIN", spec)
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: set(avail), raising=False)
    pinned = {}
    monkeypatch.setattr(os, "sched_setaffinity",
                        lambda pid, cores: pinned.update(c=sorted(cores)),
                        raising=False)
    got = _bench_util.pin_cores()
    assert got == want
    if want is not None:
        assert pinned["c"] == want          # affinity actually applied
