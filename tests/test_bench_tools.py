"""Unit tests for the evidence-tool helpers (tools/): the pure logic the
bench artifacts depend on — core-slice math, pin-spec parsing, quantile
stats — pinned without wall-clock dependence."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools._bench_util import quantile_stats  # noqa: E402
from tools.weak_scaling import _core_slices  # noqa: E402


def test_quantile_stats_median_and_iqr():
    med, iqr = quantile_stats([0.1, 0.2, 0.3, 0.4])
    assert med == 250.0
    assert iqr == [175.0, 325.0]


def test_quantile_stats_single_sample():
    med, iqr = quantile_stats([0.05])
    assert med == 50.0 and iqr == [50.0, 50.0]


def test_core_slices_disjoint_and_capped(monkeypatch):
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: set(range(8)), raising=False)
    # same per-worker budget regardless of group size (cap = max group's
    # share): 1-proc group must NOT get all 8 cores when the cap is 2
    assert _core_slices(1, cores_per_proc=2) == [[0, 1]]
    s4 = _core_slices(4, cores_per_proc=2)
    assert s4 == [[0, 1], [2, 3], [4, 5], [6, 7]]
    flat = [c for s in s4 for c in s]
    assert len(flat) == len(set(flat))          # disjoint
    # infeasible: 4 workers x 3 cores > 8
    assert _core_slices(4, cores_per_proc=3) is None


def test_core_slices_single_core(monkeypatch):
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: {0}, raising=False)
    assert _core_slices(4) is None
    assert _core_slices(1) == [[0]]


def test_couple_overlap_to_projection():
    import json

    import bench

    line = json.dumps({
        "overlap": {"overlap_fraction": 0.5},
        "scaling": {"analytic_v5e256": {
            "measured_step_ms_per_chip": 60.0, "allreduce_ms": 20.0,
            "efficiency_no_overlap": 0.75}},
    })
    out = json.loads(bench._couple_overlap_to_projection(line))
    an = out["scaling"]["analytic_v5e256"]
    assert an["measured_overlap_fraction"] == 0.5
    assert an["efficiency_at_measured_overlap"] == round(60 / 70, 3)
    # negative measured fraction clamps to the no-overlap end
    line2 = json.dumps({
        "overlap": {"overlap_fraction": -0.2},
        "scaling": {"analytic_v5e256": {
            "measured_step_ms_per_chip": 60.0, "allreduce_ms": 20.0}},
    })
    an2 = json.loads(bench._couple_overlap_to_projection(line2))[
        "scaling"]["analytic_v5e256"]
    assert an2["efficiency_at_measured_overlap"] == 0.75
    # the disjoint-pinned measurement, when present, wins over unpinned
    # (round-5: transport-on-own-cores is the TPU-host-like regime)
    line3 = json.dumps({
        "overlap": {"overlap_fraction": -0.1,
                    "pinned_disjoint": {"overlap_fraction": 0.5}},
        "scaling": {"analytic_v5e256": {
            "measured_step_ms_per_chip": 60.0, "allreduce_ms": 20.0}},
    })
    an3 = json.loads(bench._couple_overlap_to_projection(line3))[
        "scaling"]["analytic_v5e256"]
    assert an3["measured_overlap_fraction"] == 0.5
    # a SKIPPED pinned section must not mask the unpinned fraction
    line4 = json.dumps({
        "overlap": {"overlap_fraction": 0.3,
                    "pinned_disjoint": {"skipped": "1 core"}},
        "scaling": {"analytic_v5e256": {
            "measured_step_ms_per_chip": 60.0, "allreduce_ms": 20.0}},
    })
    an4 = json.loads(bench._couple_overlap_to_projection(line4))[
        "scaling"]["analytic_v5e256"]
    assert an4["measured_overlap_fraction"] == 0.3
    # missing sections pass through untouched
    assert bench._couple_overlap_to_projection("{}") == "{}"


@pytest.mark.parametrize("spec,avail,want", [
    ("off", {0, 1, 2, 3}, None),
    ("none", {0, 1, 2, 3}, None),
    ("1", {0, 1, 2, 3}, [1]),             # bare "1" is core 1, not a flag
    ("0", {0, 1, 2, 3}, [0]),
    ("0-2", {0, 1, 2, 3}, [0, 1, 2]),
    ("0,2", {0, 1, 2, 3}, [0, 2]),
    ("bogus", {0, 1, 2, 3}, None),        # malformed: unpinned, not dead
    ("0-3", {0, 1, 2, 3}, None),          # explicit full set: no-op, no
                                          # stabilization to report
    ("", {0}, None),                      # 1-core default: nothing to pin
    ("", {0, 1, 2, 3}, [1, 2, 3]),        # default: all but core 0
    ("", {0, 1}, None),                   # 2-3 cores: full-set pin is a
                                          # no-op, don't report one
])
def test_pin_cores_spec_parsing(monkeypatch, spec, avail, want):
    from tools import _bench_util

    monkeypatch.setenv("BYTEPS_BENCH_PIN", spec)
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: set(avail), raising=False)
    pinned = {}
    monkeypatch.setattr(os, "sched_setaffinity",
                        lambda pid, cores: pinned.update(c=sorted(cores)),
                        raising=False)
    got = _bench_util.pin_cores()
    assert got == want
    if want is not None:
        assert pinned["c"] == want          # affinity actually applied


# ---------------------------------------------------------------------------
# bench.py chip-drop salvage (round-4: the tunneled chip probed green, then
# hung 25 min into the first compile and the whole monolithic run was lost;
# the streamed-section protocol makes half a green window still count).
# ---------------------------------------------------------------------------

import json  # noqa: E402

import bench  # noqa: E402


def _section_line(key, value):
    return "BENCH_SECTION " + json.dumps({"key": key, "value": value})


def test_sections_salvage_and_hung_attribution():
    out = "\n".join([
        "BENCH_SECTION_START device",
        _section_line("device", {"device_kind": "TPU v5 lite",
                                 "n_devices": 1, "on_tpu": True}),
        "BENCH_SECTION_START push_pull_gbps",
        _section_line("push_pull_gbps", {"engine_256MB": 9.9}),
        "BENCH_SECTION_START train",  # started, never completed
        "garbage line the parser must skip",
    ])
    sections, hung = bench._sections_from_stdout(out)
    assert sections["push_pull_gbps"] == {"engine_256MB": 9.9}
    assert hung == "train"


def test_sections_salvage_empty_and_malformed():
    assert bench._sections_from_stdout("") == ({}, None)
    sections, hung = bench._sections_from_stdout(
        "BENCH_SECTION not json\nBENCH_SECTION_START flash_attention\n")
    assert sections == {} and hung == "flash_attention"


def test_assemble_partial_without_train_keeps_tpu_identity():
    sections = {
        "device": {"device_kind": "TPU v5 lite", "n_devices": 1,
                   "on_tpu": True},
        "push_pull_gbps": {"engine_256MB": 9.9, "fused_256MB": 34.0},
    }
    result = bench._assemble(sections, note="hung in train")
    assert result["metric"] == "bert_large_mlm_train_throughput_per_chip"
    assert result["value"] == 0.0
    assert result["device"] == "TPU v5 lite"
    assert result["push_pull_gbps"]["engine_256MB"] == 9.9
    assert result["flash_attention"] == {"skipped": "not reached"}
    assert "hung in train" in result["error"]


def test_assemble_train_error_dict_is_not_a_result():
    sections = {
        "device": {"device_kind": "TPU v5 lite", "n_devices": 1,
                   "on_tpu": True},
        "train": {"error": "RuntimeError: chip gone"},
    }
    result = bench._assemble(sections)
    assert result["value"] == 0.0
    assert "chip gone" in result["error"]


def test_prefer_line_complete_beats_partial():
    partial = json.dumps({"partial": True, "value": 0.0,
                          "push_pull_gbps": {"engine_1MB": 1.0},
                          "onebit_pallas": {"pack_gbps": 4.0},
                          "flash_attention": {"fwd_ms": 1.0},
                          "bf16_fsdp_tp": {"decreased": True}})
    complete = json.dumps({"value": 500.0,
                           "push_pull_gbps": {"engine_1MB": 1.0},
                           "onebit_pallas": {"skipped": "x"},
                           "flash_attention": {"error": "x"},
                           "bf16_fsdp_tp": {"skipped": "x"}})
    assert bench._prefer_line(partial, complete) == complete
    assert bench._prefer_line(complete, partial) == complete
    # two partials: more green sections wins
    smaller = json.dumps({"partial": True, "value": 0.0,
                          "push_pull_gbps": {"engine_1MB": 1.0}})
    assert bench._prefer_line(smaller, partial) == partial
    # unparseable loses to anything
    assert bench._prefer_line("not json", smaller) == smaller


def test_prefer_line_rich_partial_beats_value0_complete():
    # Review finding: a retry whose train step RAISED still prints a
    # non-partial line (value 0.0, error dicts everywhere); it must not
    # displace a salvaged partial that holds real TPU measurements.
    rich_partial = json.dumps({"partial": True, "value": 0.0,
                               "push_pull_gbps": {"engine_256MB": 9.0},
                               "onebit_pallas": {"pack_gbps": 4.0},
                               "flash_attention": {"fwd_ms": 1.0},
                               "bf16_fsdp_tp": {"decreased": True}})
    value0_complete = json.dumps({"value": 0.0,
                                  "error": "train: RuntimeError: chip gone",
                                  "push_pull_gbps": {"error": "x"},
                                  "onebit_pallas": {"error": "x"},
                                  "flash_attention": {"error": "x"},
                                  "bf16_fsdp_tp": {"error": "x"}})
    assert bench._prefer_line(rich_partial, value0_complete) == rich_partial
    assert bench._prefer_line(value0_complete, rich_partial) == rich_partial


def test_is_degraded():
    assert bench._is_degraded({"partial": True, "value": 500.0})
    assert bench._is_degraded({"value": 0.0})
    assert not bench._is_degraded({"value": 500.0})
    assert not bench._is_degraded(None)


def test_assemble_salvage_does_not_write_baseline(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "MEASURED_BASELINE_FILE",
                        str(tmp_path / "BASELINE_MEASURED.json"))
    train = {"on_tpu": True, "per_chip": 100.0, "mfu": 0.5,
             "tokens_per_sec_per_chip": 1e4, "device_kind": "TPU v5 lite",
             "n_devices": 1, "seq_len": 128, "per_dev_batch": 32}
    sections = {"device": {"device_kind": "TPU v5 lite", "n_devices": 1,
                           "on_tpu": True}, "train": train}
    bench._assemble(sections, write_baseline=False)
    assert not (tmp_path / "BASELINE_MEASURED.json").exists()
    bench._assemble(sections)  # the inner's full-run path does write
    assert (tmp_path / "BASELINE_MEASURED.json").exists()


def test_watch_record_degraded_never_displaces_complete(tmp_path):
    from tools import tpu_watch as w
    orig_m, orig_l = w.MEASURED, w.LATEST
    w.MEASURED = str(tmp_path / "M.json")
    w.LATEST = str(tmp_path / "L.json")
    try:
        complete = {"value": 500.0, "device": "TPU v5 lite"}
        partial = {"value": 0.0, "partial": True, "hung_section": "train",
                   "device": "TPU v5 lite"}
        value0 = {"value": 0.0, "device": "TPU v5 lite",
                  "error": "train: RuntimeError"}
        w.record(complete)
        w.record(partial)
        w.record(value0)
        doc = json.load(open(w.MEASURED))
        assert doc["line"]["value"] == 500.0
        assert len(doc["history"]) == 3
        assert doc["history"][1]["partial"] is True
        # the note describes doc["line"]: degraded records left it intact
        # (set when the complete line landed), and a new complete line
        # still displaces normally
        assert "Most recent green TPU run" in doc["note"]
        w.record({"value": 600.0, "device": "TPU v5 lite"})
        assert json.load(open(w.MEASURED))["line"]["value"] == 600.0
    finally:
        w.MEASURED, w.LATEST = orig_m, orig_l


def test_tpu_overlap_section_shape_on_cpu_mesh():
    # The section runs on-TPU in the bench; this pins its structure at CPU
    # scale so API drift can't break the TPU capture right when a green
    # window opens (the fraction itself is jitter on a shared host and is
    # deliberately not asserted).
    import jax
    out = bench._bench_tpu_overlap(jax.devices())
    assert "error" not in out, out
    for key in ("compute_ms", "comm_ms", "serial_ms", "pipelined_ms",
                "overlap_fraction", "grad_mb", "note"):
        assert key in out
    assert out["serial_ms"] > 0 and out["pipelined_ms"] > 0


def test_sections_salvage_progress_lines():
    # A section killed mid-stream: its last PROGRESS value is salvaged and
    # it is still attributed as the hung section; a later full SECTION
    # line for the same key wins over progress.
    out = "\n".join([
        "BENCH_SECTION_START push_pull_gbps",
        "BENCH_SECTION_PROGRESS " + json.dumps(
            {"key": "push_pull_gbps", "value": {"fused_256MB": 34.0}}),
        "BENCH_SECTION_PROGRESS " + json.dumps(
            {"key": "push_pull_gbps",
             "value": {"fused_256MB": 34.0, "engine_device_256MB": 12.0}}),
    ])
    sections, hung = bench._sections_from_stdout(out)
    assert sections["push_pull_gbps"]["engine_device_256MB"] == 12.0
    assert hung == "push_pull_gbps"
    # completed section: full line wins, no hang
    out2 = out + "\nBENCH_SECTION " + json.dumps(
        {"key": "push_pull_gbps", "value": {"fused_256MB": 35.0}})
    sections2, hung2 = bench._sections_from_stdout(out2)
    assert sections2["push_pull_gbps"] == {"fused_256MB": 35.0}
    assert hung2 is None


def test_push_pull_raising_measurement_keeps_partials():
    # Review finding: a chip drop that RAISES (vs hangs) mid-section must
    # keep the sizes already measured and skip the rest.
    import jax

    # _bench_push_pull imports PushPullEngine per call, so patching the
    # module attribute faults the Nth engine construction for real.
    import byteps_tpu.core.engine as eng_mod
    real_engine = eng_mod.PushPullEngine
    n_made = [0]

    class FlakyEngine(real_engine):
        def __init__(self, *a, **kw):
            n_made[0] += 1
            if n_made[0] >= 2:   # first engine (device path) OK, then die
                raise RuntimeError("chip gone")
            super().__init__(*a, **kw)

    snaps = []
    eng_mod.PushPullEngine = FlakyEngine
    try:
        out = bench._bench_push_pull(jax.devices(), on_tpu=False,
                                     emit=lambda v: snaps.append(v))
    finally:
        eng_mod.PushPullEngine = real_engine
    assert "fused_8MB" in out            # measured before the fault
    assert "engine_device_8MB" in out    # first engine construction OK
    assert "error" in out and "chip gone" in out["error"]
    assert "engine_8MB_credit16MB" not in out   # skipped after the fault
    assert snaps[-1] == out


def test_prefer_line_counts_entries_not_sections():
    # Review finding: an error-annotated section holding five salvaged
    # measurements must outweigh an error-free one holding a single entry.
    rich = json.dumps({"partial": True, "value": 0.0,
                       "push_pull_gbps": {"fused_256MB": 34.0,
                                          "engine_device_256MB": 12.0,
                                          "engine_1MB": 1.0,
                                          "engine_16MB": 2.0,
                                          "engine_256MB": 3.0,
                                          "error": "engine_256MB_x: gone"}})
    thin = json.dumps({"partial": True, "value": 0.0,
                       "push_pull_gbps": {"fused_256MB": 34.0}})
    assert bench._prefer_line(rich, thin) == rich
    assert bench._prefer_line(thin, rich) == rich


def test_merge_watch_summary_on_cpu_fallback(tmp_path, monkeypatch):
    # VERDICT r3 item 1: a chipless round's bench line must itself carry
    # the watch evidence.  Green complete lines stay untouched.
    watch = {"started": "2026-07-31T04:52:27Z", "last": "2026-07-31T06:00:00Z",
             "n_probes": 20, "n_green": 0, "probes": []}
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    (tmp_path / "TPU_WATCH_LOG.json").write_text(json.dumps(watch))
    cpu_line = json.dumps({"value": 20.0, "device": "cpu",
                           "error": "tpu unavailable"})
    out = json.loads(bench._merge_watch_summary(cpu_line))
    assert out["tpu_watch"]["n_probes"] == 20
    assert out["tpu_watch"]["n_green"] == 0
    green = json.dumps({"value": 500.0, "device": "TPU v5 lite"})
    assert bench._merge_watch_summary(green) == green
    partial = json.dumps({"value": 0.0, "device": "TPU v5 lite",
                          "partial": True})
    assert "tpu_watch" in json.loads(bench._merge_watch_summary(partial))
    # missing log file: documented as absent, not an exception
    monkeypatch.setattr(bench, "REPO", str(tmp_path / "nowhere"))
    out2 = json.loads(bench._merge_watch_summary(cpu_line))
    assert "absent" in out2["tpu_watch"]["log"]


def test_merge_watch_summary_non_dict_log(tmp_path, monkeypatch):
    # Review finding: a truncated/hand-edited log parsing to non-dict JSON
    # must degrade to "absent", never crash the final print.
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    (tmp_path / "TPU_WATCH_LOG.json").write_text("null")
    out = json.loads(bench._merge_watch_summary(
        json.dumps({"value": 0.0, "device": "cpu"})))
    assert "absent" in out["tpu_watch"]["log"]


def test_merge_watch_summary_degraded_tpu_line(tmp_path, monkeypatch):
    # Review finding: a value-0 "complete" TPU line (train raised) is
    # degraded and must carry the watch evidence too.
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    (tmp_path / "TPU_WATCH_LOG.json").write_text(json.dumps(
        {"started": "s", "last": "l", "n_probes": 5, "n_green": 1}))
    degraded = json.dumps({"value": 0.0, "device": "TPU v5 lite",
                           "error": "train: RuntimeError"})
    assert "tpu_watch" in json.loads(bench._merge_watch_summary(degraded))


def test_main_degraded_retry_prefers_better_line(monkeypatch, capsys,
                                                 tmp_path):
    # Pin the outer orchestration: a degraded first run triggers ONE
    # bounded retry only if the chip re-probes green, and the richer line
    # wins; tool merges are passed through untouched.
    monkeypatch.setattr(bench, "REPO", str(tmp_path))  # no real watch log
    partial = json.dumps({"value": 0.0, "partial": True,
                          "device": "TPU v5 lite",
                          "push_pull_gbps": {"fused_256MB": 34.0}})
    complete = json.dumps({"value": 500.0, "device": "TPU v5 lite",
                           "push_pull_gbps": {"fused_256MB": 34.0,
                                              "engine_256MB": 11.0}})
    calls = {"probe": 0, "inner": 0}

    def fake_probe(timeout):
        calls["probe"] += 1
        return {"platform": "tpu", "n": 1, "kind": "v5"}, None

    def fake_inner(extra_env=None, timeout=bench._INNER_TIMEOUT):
        calls["inner"] += 1
        if calls["inner"] == 1:
            return partial, None
        assert timeout == 2400.0     # retry covers the nominal full bench
        return complete, None

    monkeypatch.setattr(bench, "_probe", fake_probe)
    monkeypatch.setattr(bench, "_run_inner", fake_inner)
    for merge in ("_merge_dcn_compare", "_merge_scaling",
                  "_merge_mechanisms", "_merge_overlap",
                  "_merge_aot_memory", "_couple_overlap_to_projection"):
        monkeypatch.setattr(bench, merge, lambda line: line)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    assert bench.main() == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 500.0          # the complete retry won
    assert calls["inner"] == 2
    assert calls["probe"] == 2            # initial + pre-retry re-probe


def test_honor_jax_platforms_gates_on_cpu_first(monkeypatch):
    # The image exports JAX_PLATFORMS=axon globally; the helper must NOT
    # re-apply a non-cpu platform (it would override a test harness's
    # deliberate CPU mesh and hang on an unreachable chip), while a
    # cpu-first request passes through verbatim with its fallbacks.
    import types
    import example._common as c
    seen = []
    fake_jax = types.ModuleType("jax")
    fake_jax.config = types.SimpleNamespace(
        update=lambda k, v: seen.append((k, v)))
    monkeypatch.setitem(sys.modules, "jax", fake_jax)
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    c.honor_jax_platforms()
    assert seen == []
    monkeypatch.setenv("JAX_PLATFORMS", "cpu,tpu")
    c.honor_jax_platforms()
    assert seen == [("jax_platforms", "cpu,tpu")]


def test_inner_main_tpu_branch_order_and_assembly(monkeypatch, capsys,
                                                  tmp_path):
    # The TPU branch only executes on a green chip — exactly when a
    # regression would be found too late.  Stub every section and check
    # dispatch order (cheap evidence before the multi-minute compiles),
    # the emission protocol, and the assembled line.
    import jax

    class FakeDev:
        platform = "tpu"
        device_kind = "TPU v5 lite (fake)"

    order = []

    def stub(name, val=None):
        def f(*a, **kw):
            order.append(name)
            return val if val is not None else {"ok": name}
        return f

    monkeypatch.setattr(bench, "_bench_push_pull", stub("push_pull_gbps"))
    monkeypatch.setattr(bench, "_bench_tpu_overlap",
                        stub("tpu_overlap", {"overlap_fraction": 0.9}))
    monkeypatch.setattr(bench, "_bench_pallas", stub("onebit_pallas"))
    monkeypatch.setattr(bench, "_bench_flash", stub("flash_attention"))
    monkeypatch.setattr(bench, "_bench_train_step", stub("train", {
        "on_tpu": True, "per_chip": 500.0, "mfu": 0.75,
        "tokens_per_sec_per_chip": 64000.0,
        "device_kind": "TPU v5 lite (fake)", "n_devices": 1,
        "seq_len": 128, "per_dev_batch": 32}))
    monkeypatch.setattr(bench, "_bench_resnet", stub("resnet50"))
    monkeypatch.setattr(bench, "_bench_bf16_fsdp_tp", stub("bf16_fsdp_tp"))
    monkeypatch.setattr(bench, "_bench_bf16_three_d", stub("bf16_three_d"))
    monkeypatch.setattr(bench, "MEASURED_BASELINE_FILE",
                        str(tmp_path / "b.json"))
    monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
    for var in ("_BPS_BENCH_NOTE", "_BPS_BENCH_FORCE_CPU",
                "_BPS_BENCH_ONLY"):
        monkeypatch.delenv(var, raising=False)

    assert bench.inner_main() == 0
    out = capsys.readouterr().out
    assert order == ["push_pull_gbps", "tpu_overlap", "onebit_pallas",
                     "flash_attention", "train", "resnet50",
                     "bf16_fsdp_tp", "bf16_three_d"]
    starts = [ln.split()[1] for ln in out.splitlines()
              if ln.startswith("BENCH_SECTION_START")]
    assert starts[0] == "device" and starts[1] == "push_pull_gbps"
    final = json.loads(out.strip().splitlines()[-1])
    assert final["value"] == 500.0
    assert final["tpu_overlap"]["overlap_fraction"] == 0.9
    assert final["device"] == "TPU v5 lite (fake)"
    assert (tmp_path / "b.json").exists()   # first-green baseline written


def test_push_pull_ablations_skip_when_projected_slow(monkeypatch):
    # Window economy: a catastrophically slow hardware engine must not
    # spend the green window on secondary ablations — but the headline
    # engine figure itself always runs.  A stepping clock makes every
    # per-rep median enormous (and the headline round to 0.0 GB/s, the
    # slowest case, which must hit the skip rather than dodge it).
    import jax
    ticks = [0.0]

    def fake_clock():
        # two calls per rep (t0 and the delta read) -> 62 s per rep,
        # projecting 8 x 62 = 496 s per ablation, past the 240 s budget
        ticks[0] += 31.0
        return ticks[0]

    monkeypatch.setattr(bench.time, "perf_counter", fake_clock)
    out = bench._bench_push_pull(jax.devices(), on_tpu=False)
    assert "ablations_skipped" in out
    assert "engine_8MB" in out                 # headline still measured
    assert "engine_8MB_no_priority" not in out


# --- round-5 finalize pipeline: compact final line + committed full ---
# record (VERDICT r4 task 1: rounds 3-4 had parsed:null because the
# ~10 kB final line outgrew the driver's 2000-char tail capture).


def _rich_line():
    return json.dumps({
        "metric": "bert_large_mlm_train_throughput_per_chip",
        "value": 526.4, "unit": "examples/s", "vs_baseline": 0.985,
        "mfu": 0.752, "device": "TPU v5 lite", "n_devices": 1,
        "push_pull_gbps": {"fused_256MB": 34.69, "fused_256MB_iqr": [34, 35],
                           "engine_256MB": 0.026, "engine_device_256MB": 11.0,
                           "engine_1MB": 0.013},
        "tpu_overlap": {"overlap_fraction": 0.4},
        "overlap": {"overlap_fraction": -0.061, "conditions": {"c": 1}},
        "flash_attention": {"error": "chip dropped", "fwd_ms": 11.5},
        "bf16_fsdp_tp": {"skipped": "cpu run"},
        "scaling": {"weak": [1, 2, 3]},
        "mechanisms": {"priority": {"m": 1.6}},
    })


def test_finalize_writes_full_record_and_compact_line(tmp_path, monkeypatch,
                                                      capsys):
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    (tmp_path / "BENCH_r03.json").write_text("{}")
    (tmp_path / "BENCH_r04.json").write_text("{}")
    compact = bench._finalize(_rich_line())
    # final line parses, is small, and points at the committed record
    assert len(compact) <= bench._COMPACT_BUDGET
    doc = json.loads(compact)
    assert doc["value"] == 526.4 and doc["mfu"] == 0.752
    assert doc["full_record"] == "BENCH_FULL.json"
    assert doc["round"] == 5                     # one past newest BENCH_r
    # per-section status flags: ok / skip / error+data
    assert doc["sections"]["push_pull_gbps"] == "ok"
    assert doc["sections"]["bf16_fsdp_tp"] == "skip"
    assert doc["sections"]["flash_attention"] == "error+data"
    # headline figures survive compaction: largest-size engine/fused +
    # both overlap fractions
    assert doc["headline"]["fused_256MB_gbps"] == 34.69
    assert doc["headline"]["engine_256MB_gbps"] == 0.026
    assert doc["headline"]["engine_device_256MB_gbps"] == 11.0
    assert doc["headline"]["tpu_overlap_fraction"] == 0.4
    assert doc["headline"]["host_overlap_fraction"] == -0.061
    # the full record is on disk AND echoed as a BENCH_FULL stdout line
    full = json.loads((tmp_path / "BENCH_FULL.json").read_text())
    assert full["push_pull_gbps"]["engine_1MB"] == 0.013
    assert full["scaling"] == {"weak": [1, 2, 3]}
    assert full["recorded"] and full["round"] == 5
    streamed = [ln for ln in capsys.readouterr().out.splitlines()
                if ln.startswith("BENCH_FULL ")]
    assert len(streamed) == 1
    assert json.loads(streamed[0][len("BENCH_FULL "):]) == full


def test_finalize_terminal_failure_line_stays_compact(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    line = json.dumps({"metric": "m", "value": 0.0, "unit": "examples/s",
                       "vs_baseline": 0.0, "error": "x" * 5000})
    compact = bench._finalize(line)
    assert len(compact) <= bench._COMPACT_BUDGET
    assert len(json.loads(compact)["error"]) <= 200


def test_finalize_unparseable_line_passes_through(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    assert bench._finalize("not json") == "not json"
    assert not (tmp_path / "BENCH_FULL.json").exists()


def test_watch_parses_bench_full_line_over_compact_tail():
    from tools import tpu_watch as w
    full = {"value": 526.4, "device": "TPU v5 lite",
            "push_pull_gbps": {"engine_256MB": 0.026}}
    compact = {"value": 526.4, "device": "TPU v5 lite",
               "full_record": "BENCH_FULL.json"}
    out = "\n".join(["BENCH_SECTION whatever",
                     "BENCH_FULL " + json.dumps(full),
                     json.dumps(compact)])
    # the watch must record the FULL line (its history extracts section
    # figures the compact line no longer carries)
    assert w._parse_bench_stdout(out) == full
    # pre-round-5 output (no BENCH_FULL line): last JSON line still works
    assert w._parse_bench_stdout(json.dumps(full)) == full
    assert w._parse_bench_stdout("") is None
    assert w._parse_bench_stdout("BENCH_FULL not-json\n") is None


def test_quantile_raw_feeds_rates_without_rounding_collapse():
    # advisor r4: a sub-50 ns median rounds to 0.0 ms at 4 digits; rates
    # must come from the unrounded seconds
    from tools._bench_util import quantile_stats_raw
    med, q25, q75 = quantile_stats_raw([4e-8, 4e-8, 4e-8])
    assert med == 4e-8 and q25 == 4e-8 and q75 == 4e-8
    gbps = 1024 / med / 1e9          # finite, no ZeroDivisionError
    assert gbps > 0


def test_full_record_displacement_guard(tmp_path, monkeypatch):
    # code-review r5: a red round's terminal-failure line must not clobber
    # the numbers-of-record file; it lands in BENCH_FULL_LATEST.json only.
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    tpu = json.dumps({"metric": "m", "value": 526.0, "unit": "u",
                      "vs_baseline": 1.0, "device": "TPU v5 lite"})
    bench._finalize(tpu)
    fail = json.dumps({"metric": "m", "value": 0.0, "unit": "u",
                       "vs_baseline": 0.0, "error": "tpu unavailable"})
    bench._finalize(fail)
    record = json.loads((tmp_path / "BENCH_FULL.json").read_text())
    latest = json.loads((tmp_path / "BENCH_FULL_LATEST.json").read_text())
    assert record["value"] == 526.0          # record survived
    assert latest["value"] == 0.0            # latest shows the red run
    # a complete CPU evidence record does not displace a TPU record...
    cpu = json.dumps({"metric": "m", "value": 34.0, "unit": "u",
                      "vs_baseline": 0.0, "device": "cpu",
                      "mechanisms": {"m": 1}})
    bench._finalize(cpu)
    assert json.loads(
        (tmp_path / "BENCH_FULL.json").read_text())["value"] == 526.0
    # ...but does displace an equal-or-lower class (another CPU record)
    (tmp_path / "BENCH_FULL.json").write_text(cpu)
    cpu2 = json.dumps({"metric": "m", "value": 35.0, "unit": "u",
                       "vs_baseline": 0.0, "device": "cpu"})
    bench._finalize(cpu2)
    assert json.loads(
        (tmp_path / "BENCH_FULL.json").read_text())["value"] == 35.0


def test_watch_reassembles_sections_when_no_final_line():
    # code-review r5: the outer echoes the inner's BENCH_SECTION stream,
    # so a watch-level kill mid-merge still yields a partial record.
    from tools import tpu_watch as w
    out = "\n".join([
        "BENCH_SECTION " + json.dumps(
            {"key": "device", "value": {"device_kind": "TPU v5 lite",
                                        "n_devices": 1, "on_tpu": True}}),
        "BENCH_SECTION " + json.dumps(
            {"key": "push_pull_gbps", "value": {"fused_256MB": 34.0}}),
        "BENCH_SECTION_START train",
    ])
    doc = w._parse_bench_stdout(out)
    assert doc["partial"] is True
    assert doc["hung_section"] == "train"
    assert doc["push_pull_gbps"] == {"fused_256MB": 34.0}
    assert doc["device"].startswith("TPU")


def test_run_inner_echoes_section_stream(monkeypatch, capsys):
    # The echo is what makes the watch salvage above possible at all.
    sec = "BENCH_SECTION " + json.dumps({"key": "device", "value": {}})

    class P:
        stdout = sec + "\n{\"value\": 1.0}\n"
        stderr = ""
        returncode = 0

    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: P())
    line, err = bench._run_inner()
    assert err is None and json.loads(line) == {"value": 1.0}
    assert sec in capsys.readouterr().out


def test_async_bench_tool_emits_convergence_datum(capsys, monkeypatch):
    # round-5: the async-PS convergence datum (VERDICT r4 task 7) — the
    # tool runs both modes and reports the final-loss gap with conditions
    from tools import async_bench as ab
    monkeypatch.setenv("BYTEPS_BENCH_PIN", "off")  # in-process run must
    monkeypatch.setattr(ab, "STEPS", 12)           # not shrink pytest's
    assert ab.main() == 0                          # CPU affinity
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["workers"] == 2 and out["steps_per_worker"] == 12
    assert {"loss_init", "loss_sync", "loss_async", "final_loss_gap",
            "async_converged", "conditions"} <= set(out)
    assert out["loss_sync"] < out["loss_init"]       # sync made progress
    assert out["delta_pushes_per_key"] == 2 * 12     # no pushes lost


def test_bf16_three_d_section_single_device():
    # round-5 (VERDICT r4 task 8): the bf16 3D section adapts its mesh to
    # the device count; at one device it degenerates to (1,1,1), which is
    # safe even on the CPU emitter (the CHECK needs real multi-device
    # partial-manual psum) — exactly what a 1-chip green window runs.
    import jax
    out = bench._bench_bf16_three_d(jax.devices()[:1])
    assert out["dtype"] == "bfloat16"
    assert out["mesh"] == "dp=1 x pp=1 x tp=1"
    assert len(out["losses"]) == 8 and out["decreased"]
    assert "trivial at (1,1,1)" in out["note"]


def test_bench_smoke_floor_and_gate_arithmetic(tmp_path, monkeypatch):
    # round-6 (ISSUE 5): the bench-smoke lane gates the engine-vs-fused
    # ratio against the checked-in floor; pin the floor file's shape and
    # the gate arithmetic without running the (minutes-long) measurement
    from tools import bench_smoke as bs
    with open(bs.FLOOR_PATH) as f:
        floor = json.load(f)
    assert 0 < floor["engine_vs_fused_ratio"] <= 4
    assert floor["engine_8MB_gbps"] > 0
    measured = {"fused_8MB_gbps": 1.0, "engine_8MB_gbps": 0.5,
                "engine_vs_fused_ratio": 0.5, "ratio_per_rep": [0.5],
                "autotune": {}}
    monkeypatch.setattr(bs, "_measure", lambda: dict(measured))
    # synthetic passing lanes: the compressed measurement is seconds of
    # real pushes — its gate arithmetic is pinned separately below
    monkeypatch.setattr(bs, "_measure_compressed", lambda: {
        "onebit": {"wire_ratio": 0.031, "gbps": 0.02,
                   "throughput_ratio": 0.1, "golden_error": 0.27,
                   "zero_compile": True},
        "randomk": {"wire_ratio": 0.5, "gbps": 0.001,
                    "throughput_ratio": 0.01, "golden_error": 0.47,
                    "zero_compile": True}})
    # the trace lane is likewise seconds of real pushes; its gate
    # arithmetic is pinned in tests/test_trace_merge.py
    monkeypatch.setattr(bs, "_measure_trace", lambda: {
        "sample_n": 4, "overhead_ratio": 0.95, "events_buffered": 8,
        "events_dropped": 0})
    # the fleet measurement spawns real host processes and churns them
    # for seconds; its gate arithmetic is pinned separately below
    monkeypatch.setattr(bs, "_measure_fleet", lambda: {
        "base_hosts": 2, "peak_hosts": 4, "pulls_per_s": 1e9,
        "p50_ms": 0.1, "p99_ms": 1.0, "pushes_per_s": 10.0,
        "failed_reads": 0, "spawned": 4, "drain_started": 2,
        "drained": 2, "drain_escalated": 0, "banned": 0,
        "final_hosts": 2, "still_draining": []})
    # the durability measurement is seconds of real journaled pushes
    # plus a cold replay; its gate arithmetic is pinned separately below
    monkeypatch.setattr(bs, "_measure_durability", lambda: {
        "push_ratio": 0.6, "ratio_per_rep": [0.6], "replay_records": 401,
        "replay_mb": 25.0, "replay_mbps": 250.0, "truncated_tails": 0,
        "corrupt_records": 0})
    monkeypatch.setattr(bs, "setup_cpu8_mesh", lambda: None)
    monkeypatch.setenv("BENCH_SMOKE_TOLERANCE", "0.30")
    monkeypatch.setattr(sys, "argv", ["bench_smoke.py"])
    gate_r = floor["engine_vs_fused_ratio"] * 0.7
    gate_a = floor["engine_8MB_gbps"] * 0.7
    assert bs.main() == (0 if (0.5 >= gate_r or 0.5 >= gate_a) else 1)
    # a fast-regime run: ratio structurally low, absolute honest — passes
    measured.update(engine_vs_fused_ratio=0.35,
                    engine_8MB_gbps=floor["engine_8MB_gbps"] * 2)
    assert bs.main() == 0
    # a round-5-style machinery collapse tanks BOTH floors — fails
    measured.update(engine_vs_fused_ratio=0.2,
                    engine_8MB_gbps=floor["engine_8MB_gbps"] * 0.3)
    assert bs.main() == 1


def test_bench_smoke_serve_dist_floor_and_gate_arithmetic():
    """ISSUE 15: the serve_dist lane gates on zero failed reads
    (absolute), every spawned host actually serving, and aggregate
    pulls/s over the floor with the lane tolerance.  Pin the floor
    file's entry and the pure gate function."""
    from tools import bench_smoke as bs
    with open(bs.FLOOR_PATH) as f:
        floor = json.load(f)
    assert floor["serve_dist_pulls_per_s_floor"] > 0

    def sd():
        return {"failed_reads": 0, "pulls_per_s": 1e9,
                "per_host": {0: {"pulls": 5}, 1: {"pulls": 7},
                             2: {"pulls": 3}}}

    good = sd()
    assert bs._serve_dist_ok(good, floor, 0.3)
    assert good["gate_pulls_per_s"] == round(
        floor["serve_dist_pulls_per_s_floor"] * 0.7, 1)
    # one failed read fails the lane outright — no tolerance
    bad = sd()
    bad["failed_reads"] = 1
    assert not bs._serve_dist_ok(bad, floor, 0.3)
    # a host that never served is a silent death, not a pass
    dead = sd()
    dead["per_host"][2]["pulls"] = 0
    assert not bs._serve_dist_ok(dead, floor, 0.3)
    # a tier-machinery collapse fails the throughput floor
    slow = sd()
    slow["pulls_per_s"] = 0.1
    assert not bs._serve_dist_ok(slow, floor, 0.3)


def test_bench_smoke_fleet_floor_and_gate_arithmetic():
    """ISSUE 18: the fleet lane gates on zero failed reads through
    autoscaler-driven churn (absolute), the churn actually happening
    (spawns to the peak AND at least one graceful drain), drains
    landing clean (none escalated, none stuck), and pulls/s under churn
    over the floor with the lane tolerance.  Pin the floor file's entry
    and the pure gate function."""
    from tools import bench_smoke as bs
    with open(bs.FLOOR_PATH) as f:
        floor = json.load(f)
    assert floor["fleet_pulls_per_s_floor"] > 0

    def fl():
        return {"failed_reads": 0, "pulls_per_s": 1e9, "peak_hosts": 4,
                "spawned": 4, "drained": 2, "drain_escalated": 0,
                "still_draining": []}

    good = fl()
    assert bs._fleet_ok(good, floor, 0.3)
    assert good["gate_pulls_per_s"] == round(
        floor["fleet_pulls_per_s_floor"] * 0.7, 1)
    # one failed read mid-churn fails the lane outright — no tolerance
    bad = fl()
    bad["failed_reads"] = 1
    assert not bs._fleet_ok(bad, floor, 0.3)
    # a bench whose fleet never grew gates nothing — fail loudly
    still = fl()
    still["spawned"] = 2
    assert not bs._fleet_ok(still, floor, 0.3)
    # ...same when no drain ever completed
    nodrain = fl()
    nodrain["drained"] = 0
    assert not bs._fleet_ok(nodrain, floor, 0.3)
    # an escalated (killed) drain is not a graceful scale-down
    esc = fl()
    esc["drain_escalated"] = 1
    assert not bs._fleet_ok(esc, floor, 0.3)
    # a drain still stuck at the end means the deadline machinery broke
    stuck = fl()
    stuck["still_draining"] = [3]
    assert not bs._fleet_ok(stuck, floor, 0.3)
    # a churn-machinery collapse fails the throughput floor
    slow = fl()
    slow["pulls_per_s"] = 0.1
    assert not bs._fleet_ok(slow, floor, 0.3)


def test_bench_smoke_durability_floor_and_gate_arithmetic():
    """ISSUE 19: the durability lane gates on the journal's push-path
    cost ratio and the cold-start replay MB/s (both host measurements,
    lane tolerance), the replay actually reading records back, and a
    clean journal replaying with ZERO damage detected (absolute — torn
    tails or corrupt records on a fault-free bench mean the write path
    itself produces garbage).  Pin the floor file's entries and the
    pure gate function."""
    from tools import bench_smoke as bs
    with open(bs.FLOOR_PATH) as f:
        floor = json.load(f)
    assert 0 < floor["durability_push_ratio_floor"] <= 1
    assert floor["durability_replay_mbps_floor"] > 0

    def du():
        return {"push_ratio": 0.6, "replay_mbps": 250.0,
                "replay_records": 401, "truncated_tails": 0,
                "corrupt_records": 0}

    good = du()
    assert bs._durability_ok(good, floor, 0.3)
    assert good["gate_push_ratio"] == round(
        floor["durability_push_ratio_floor"] * 0.7, 3)
    assert good["gate_replay_mbps"] == round(
        floor["durability_replay_mbps_floor"] * 0.7, 1)
    # the journal taxing the push path fails the ratio floor
    taxed = du()
    taxed["push_ratio"] = 0.01
    assert not bs._durability_ok(taxed, floor, 0.3)
    # a slow cold start fails the replay floor
    slow = du()
    slow["replay_mbps"] = 0.5
    assert not bs._durability_ok(slow, floor, 0.3)
    # a replay that read nothing back gates nothing — fail loudly
    empty = du()
    empty["replay_records"] = 0
    assert not bs._durability_ok(empty, floor, 0.3)
    # damage on a FAULT-FREE run is absolute — no tolerance
    torn = du()
    torn["truncated_tails"] = 1
    assert not bs._durability_ok(torn, floor, 0.3)
    corrupt = du()
    corrupt["corrupt_records"] = 2
    assert not bs._durability_ok(corrupt, floor, 0.3)


def test_bench_smoke_compressed_floor_and_gate_arithmetic():
    """ISSUE 11: the compressed lanes gate on wire ratio (onebit — the
    quantized-reduce-leg contract, <= 0.35x at >= 1 MiB), the
    codec-golden quality ceiling (deterministic, no tolerance), and the
    throughput floor (host measurement, lane tolerance).  Pin the floor
    file's shape and the pure gate function."""
    from tools import bench_smoke as bs
    with open(bs.FLOOR_PATH) as f:
        floor = json.load(f)
    assert 0 < floor["compressed_wire_ratio_max"] <= 0.35
    assert 0 < floor["compressed_quality_ceiling"] <= 1
    assert floor["compressed_throughput_floor"] >= 0

    def lanes():
        return {"onebit": {"wire_ratio": 0.031, "golden_error": 0.27,
                           "throughput_ratio": 0.1},
                "randomk": {"wire_ratio": 0.5, "golden_error": 0.47,
                            "throughput_ratio": 0.01}}

    good = lanes()
    assert bs._compressed_ok(good, floor, 0.3)
    assert good["onebit"]["ok"] and good["randomk"]["ok"]
    # onebit shipping full-precision bytes on the reduce leg — fails
    fat = lanes()
    fat["onebit"]["wire_ratio"] = 0.9
    assert not bs._compressed_ok(fat, floor, 0.3)
    assert not fat["onebit"]["ok"] and fat["randomk"]["ok"]
    # a codec whose golden error broke the quality ceiling — fails
    lossy = lanes()
    lossy["randomk"]["golden_error"] = 0.9
    assert not bs._compressed_ok(lossy, floor, 0.3)
    # a machinery collapse on the compressed path — fails the tput floor
    slow = lanes()
    slow["onebit"]["throughput_ratio"] = 0.0
    assert not bs._compressed_ok(slow, floor, 0.3)
    # randomk's dense wire ratio (0.5 > 0.35) is NOT gated: the wire
    # contract is onebit's — randomk's lane reports it for the trend
    assert lanes()["randomk"]["wire_ratio"] > floor[
        "compressed_wire_ratio_max"]


def test_bench_smoke_sharded_update_floor_and_gate_arithmetic():
    """ISSUE 20: the sharded_update lane gates on the wire-ratio
    contract (push N + pull N/R — deterministic, no tolerance), the
    bitwise replay exactness (absolute), and the interleaved step-time
    ratio over the floor with the lane tolerance.  Pin the floor file's
    entries and the pure gate function."""
    from tools import bench_smoke as bs
    with open(bs.FLOOR_PATH) as f:
        floor = json.load(f)
    assert floor["sharded_wire_ratio_max"] <= 0.62
    assert floor["sharded_step_ratio_floor"] > 0

    def su():
        return {"exact": True, "wire_ratio": 0.577,
                "step_time_ratio": 1e9}

    good = su()
    assert bs._sharded_update_ok(good, floor, 0.3)
    assert good["gate_step_ratio"] == round(
        floor["sharded_step_ratio_floor"] * 0.7, 3)
    # trajectory drift fails outright — the replay proof is absolute
    drift = su()
    drift["exact"] = False
    assert not bs._sharded_update_ok(drift, floor, 0.3)
    # the wire ratio is the feature's contract — no tolerance applied
    fat = su()
    fat["wire_ratio"] = floor["sharded_wire_ratio_max"] + 0.01
    assert not bs._sharded_update_ok(fat, floor, 0.3)
    # an update-machinery collapse fails the step-time floor
    slow = su()
    slow["step_time_ratio"] = 0.0
    assert not bs._sharded_update_ok(slow, floor, 0.3)
