"""Golden-vector pins for the compression wire formats (VERDICT r1 item 9).

The expected values below were generated once from the numpy reference
implementations (tests/compression_refs.py) — which round 1 bit-pinned
against the reference's semantics (reference compressor/impl/onebit.cc,
dithering.cc; test pattern tests/test_onebit.py:32-113) — and are now
frozen as literals.  Any kernel or layout change that silently drifts the
wire format fails here, independently of the refs (which could drift with
the implementation if both were edited together).

The input vector hits the edge cases: exact zeros and signed zeros, exact
level boundaries for s=4 (0.25/0.5/0.75/1.0 of max), values straddling
boundaries by <1e-3, tiny magnitudes near the stochastic-rounding floor,
and the fp16 round-trip of all of it.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from byteps_tpu.compression import create as create_compressor

# Edge-case input (32 elements; see module docstring)
X = np.array([0.0, -0.0, 2.0, -2.0, 0.5, -0.5, 1.0, -1.0,
              1.5, -1.5, 0.25, -0.25, 1e-7, -1e-7, 0.125, -0.125,
              1.999, -1.999, 0.749, 0.751, 1.001, -1.001, 0.374, 0.376,
              0.06251, -0.06249, 1.75, -1.75, 0.875, -0.875, 1.125, -1.125],
             dtype=np.float32)

# onebit, scaling=True: word j < 32 carries the sign of element j in bit 0
# (sublane-major layout, lane-padded to 128); padding packs as 1-bits.
# 0xFFFFFFFF = positive, 0xFFFFFFFE = negative.
_P, _N = 0xFFFFFFFF, 0xFFFFFFFE
ONEBIT_WORDS_HEAD = np.array(
    [_P, _P, _P, _N, _P, _N, _P, _N, _P, _N, _P, _N, _P, _N, _P, _N,
     _P, _N, _P, _P, _P, _N, _P, _P, _P, _N, _P, _N, _P, _N, _P, _N],
    dtype=np.uint32)
ONEBIT_SCALE = 0.8320313096046448        # mean |x| over 32 elements
ONEBIT_SCALE_FP16 = 0.83203125           # same, after fp16 round-trip

# dithering, s=4, seed=3, first step (counter=0)
DITHERING_GOLDEN = {
    ("linear", "max"): (
        [0, 0, 4, -4, 1, -1, 2, -2, 3, -3, 1, -1, 0, 0, 0, 0,
         4, -4, 1, 1, 2, -2, 1, 0, 0, 0, 3, -4, 2, -1, 3, -2], 2.0),
    ("linear", "l2"): (
        [0, 0, 1, -1, 0, 0, 0, -1, 1, -1, 0, 0, 0, 0, 0, 0,
         2, -1, 0, 0, 1, 0, 0, 0, 0, 0, 1, -1, 0, 0, 1, -1],
        6.062492847442627),
    ("natural", "max"): (
        [0, 0, 4, -4, 2, -2, 3, -3, 3, -3, 1, -1, 0, 0, 0, 0,
         4, -4, 2, 2, 3, -3, 2, 1, 0, 0, 4, -4, 3, -2, 3, -3], 2.0),
    ("natural", "l2"): (
        [0, 0, 2, -2, 0, 0, 1, -1, 2, -2, 1, -1, 0, 0, 0, 0,
         3, -2, 1, 1, 2, -1, 1, 0, 0, 0, 2, -2, 1, -1, 2, -1],
        6.062492847442627),
}


@pytest.mark.parametrize("fp16", [False, True])
def test_onebit_golden(fp16):
    x = X.astype(np.float16).astype(np.float32) if fp16 else X
    comp = create_compressor({"compressor": "onebit", "scaling": "true"},
                             len(x))
    payload, _ = comp.compress(jnp.asarray(x), comp.init_state())
    words = np.asarray(payload["words"])
    np.testing.assert_array_equal(words[:32], ONEBIT_WORDS_HEAD)
    assert (words[32:] == _P).all()  # padding is all-ones
    expect_scale = ONEBIT_SCALE_FP16 if fp16 else ONEBIT_SCALE
    np.testing.assert_allclose(float(payload["scale"]), expect_scale,
                               rtol=1e-6)


def test_onebit_golden_pallas_interpret():
    """The Pallas kernel must produce the identical wire words (interpret
    mode executes the exact kernel program on CPU)."""
    from byteps_tpu.ops import pallas_kernels as pk
    L = pk.padded_lanes(len(X))
    x2d = jnp.pad(jnp.asarray(X), (0, 32 * L - len(X))).reshape(32, L)
    words, abs_sum = pk.onebit_pack(x2d, interpret=True)
    words = np.asarray(words)
    np.testing.assert_array_equal(words[:32], ONEBIT_WORDS_HEAD)
    assert (words[32:] == _P).all()
    np.testing.assert_allclose(float(abs_sum) / len(X), ONEBIT_SCALE,
                               rtol=1e-6)


@pytest.mark.parametrize("partition,normalize", list(DITHERING_GOLDEN))
def test_dithering_golden(partition, normalize):
    codes_exp, norm_exp = DITHERING_GOLDEN[(partition, normalize)]
    comp = create_compressor(
        {"compressor": "dithering", "partition_num": "4",
         "partition": partition, "normalize": normalize, "seed": "3"},
        len(X))
    payload, _ = comp.compress(jnp.asarray(X), comp.init_state())
    np.testing.assert_array_equal(np.asarray(payload["codes"]),
                                  np.asarray(codes_exp, np.int8))
    np.testing.assert_allclose(float(payload["norm"]), norm_exp, rtol=1e-6)


def test_dithering_golden_sparse_layout():
    """The sparse layout must decode to the identical dense tensor when the
    capacity covers every nonzero code."""
    codes_exp, norm_exp = DITHERING_GOLDEN[("linear", "max")]
    nnz = int(np.count_nonzero(codes_exp))
    comp = create_compressor(
        {"compressor": "dithering", "partition_num": "4", "seed": "3",
         "sparse_ratio": str((nnz + 2) / len(X))}, len(X))
    dense = create_compressor(
        {"compressor": "dithering", "partition_num": "4", "seed": "3"},
        len(X))
    ps, _ = comp.compress(jnp.asarray(X), comp.init_state())
    pd, _ = dense.compress(jnp.asarray(X), dense.init_state())
    np.testing.assert_allclose(np.asarray(comp.decompress(ps)),
                               np.asarray(dense.decompress(pd)),
                               rtol=1e-6, atol=0)
