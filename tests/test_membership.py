"""Elastic membership units (fault/membership.py): epoch semantics, the
bus protocol (sync quorum / shrink rendezvous / rejoin admission), and
the stale-epoch guards in the engine, server engine, KV store, and
server assigner.  The multiprocess end-to-end pins live in
tests/test_elastic.py."""

from __future__ import annotations

import socket
import threading

import numpy as np
import pytest

import byteps_tpu.core.api as api
from byteps_tpu.common.config import Config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import membership as mm
from byteps_tpu.fault.membership import (MembershipView, _BusServer,
                                         _recv_obj, _send_obj)
from byteps_tpu.server.engine import ServerEngine
from byteps_tpu.server.kv_store import KVStore
from byteps_tpu.server.sharding import ServerAssigner
from byteps_tpu.utils.checkpoint import pack_state, unpack_state

from .conftest import free_port as _free_port


@pytest.fixture(autouse=True)
def _fresh_epoch():
    mm._reset_epoch_for_tests()
    yield
    if api.initialized():
        api.shutdown()
    api._declared_order = []
    mm._reset_epoch_for_tests()


def _req(port, msg, timeout=20.0):
    s = socket.create_connection(("127.0.0.1", port), timeout=5)
    s.settimeout(timeout)
    _send_obj(s, msg)
    reply = _recv_obj(s)
    s.close()
    return reply


# -- epoch ------------------------------------------------------------------


def test_epoch_is_monotonic():
    assert mm.current_epoch() == 0
    assert mm.advance_epoch() == 1
    assert mm.set_epoch(5) == 5
    assert mm.set_epoch(3) == 5          # never regresses
    assert mm.current_epoch() == 5


def test_view_basics():
    v = MembershipView(2, (0, 2, 5))
    assert v.num_workers == 3
    assert v.coordinator == 0


# -- bus: sync --------------------------------------------------------------


def test_bus_sync_quorum_delivers_all_payloads():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=5.0)
    try:
        out = {}

        def member(r):
            out[r] = _req(port, {"op": "sync", "rank": r, "epoch": 0,
                                 "step": 1, "payload": r * 10})

        ts = [threading.Thread(target=member, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=20)
        for r in (0, 1):
            assert out[r]["ok"], out
            assert out[r]["payloads"] == {0: 0, 1: 10}
    finally:
        bus.close()


def test_bus_sync_wrong_epoch_is_stale():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(3, (0,)),
                     rendezvous_timeout_s=1.0, sync_timeout_s=2.0)
    try:
        r = _req(port, {"op": "sync", "rank": 0, "epoch": 1, "step": 7})
        assert r == {"ok": False, "stale": True, "epoch": 3, "world": [0],
                     "probation": []}
    finally:
        bus.close()


def test_bus_sync_timeout_names_the_missing():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1, 2)),
                     rendezvous_timeout_s=1.0, sync_timeout_s=0.5)
    try:
        r = _req(port, {"op": "sync", "rank": 0, "epoch": 0, "step": 1})
        assert r["timeout"] and r["missing"] == [1, 2], r
    finally:
        bus.close()


# -- bus: shrink rendezvous -------------------------------------------------


def test_bus_hello_agreement_and_stale_sync_release():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1, 2)),
                     rendezvous_timeout_s=5.0, sync_timeout_s=30.0)
    try:
        # a survivor parked on a sync when the failure hits...
        parked = {}

        def sync_waiter():
            parked["r"] = _req(port, {"op": "sync", "rank": 0, "epoch": 0,
                                      "step": 4}, timeout=40.0)

        t = threading.Thread(target=sync_waiter)
        t.start()
        # ...both survivors rendezvous for epoch 1 without rank 1
        out = {}

        def hello(r):
            out[r] = _req(port, {"op": "hello", "rank": r, "epoch": 1,
                                 "world": [0, 2]})

        hs = [threading.Thread(target=hello, args=(r,)) for r in (0, 2)]
        for h in hs:
            h.start()
        for h in hs:
            h.join(timeout=20)
        for r in (0, 2):
            assert out[r]["ok"], out
            assert out[r]["epoch"] == 1 and out[r]["world"] == [0, 2], out
        # rank 2 is the standby of the agreed world {0, 2}: its reply
        # carries the piggybacked replica snapshot (ISSUE 8)
        assert "replica" not in out[0]
        assert out[2]["replica"]["epoch"] == 1
        assert out[2]["replica"]["world"] == [0, 2]
        # the parked sync was released for the new world: as stale (the
        # agreement already landed) or told to JOIN the rendezvous
        # (reconcile=True while the hellos were still pending) — either
        # way the member retries at the agreed view
        t.join(timeout=20)
        assert parked["r"].get("stale") or parked["r"].get("reconcile"), \
            parked
        assert bus.view() == MembershipView(1, (0, 2))
        # a straggler's hello for the already-agreed epoch just gets the
        # current view (idempotent)
        late = _req(port, {"op": "hello", "rank": 0, "epoch": 1,
                           "world": [0, 2]})
        assert late == {"ok": True, "epoch": 1, "world": [0, 2]}
        assert counters.get("membership.shrink_agreed") >= 1
    finally:
        bus.close()


def test_bus_hello_timeout_drops_nonresponders():
    """Double failure during the shrink: the second dead member never
    hellos; the rendezvous window expires and the agreement proceeds
    with the responders only."""
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0, 1, 2)),
                     rendezvous_timeout_s=0.5, sync_timeout_s=5.0)
    try:
        r = _req(port, {"op": "hello", "rank": 0, "epoch": 1,
                        "world": [0, 2]})
        assert r == {"ok": True, "epoch": 1, "world": [0]}
        assert bus.view() == MembershipView(1, (0,))
    finally:
        bus.close()


# -- bus: rejoin admission --------------------------------------------------


def test_bus_rejoin_admitted_at_step_boundary_with_state():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(1, (0, 2)),
                     rendezvous_timeout_s=2.0, sync_timeout_s=10.0)
    try:
        state = pack_state({"w": np.arange(4, dtype=np.float32),
                            "step": np.array(6)})
        out = {}

        def rejoiner():
            out["join"] = _req(port, {"op": "rejoin", "rank": 1},
                               timeout=30.0)

        def member(r):
            out[r] = _req(port, {"op": "sync", "rank": r, "epoch": 1,
                                 "step": 7, "payload": None,
                                 "state": state,
                                 "declared": ["a", "b"]}, timeout=30.0)

        tj = threading.Thread(target=rejoiner)
        tj.start()
        # wait until the bus has PARKED the joiner before any member
        # syncs: if the step-7 quorum completes first, the members'
        # round legitimately finishes without a world change (the
        # joiner would be admitted at the NEXT boundary — which this
        # test never produces) and the ok-without-stale replies here
        # were a thread-scheduling flake, not a bus bug
        import time as _time
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            with bus._cv:
                if 1 in bus._join_wait:
                    break
            _time.sleep(0.005)
        ts = [threading.Thread(target=member, args=(r,)) for r in (0, 2)]
        for t in ts:
            t.start()
        for t in ts + [tj]:
            t.join(timeout=30)
        # members see the admission as a world change (retry the step)
        for r in (0, 2):
            assert out[r]["stale"], out[r]
            assert out[r]["epoch"] == 2 and out[r]["world"] == [0, 1, 2]
        # the joiner received epoch, world, declared order, and the
        # survivor's packed state for the boundary step
        join = out["join"]
        assert join["ok"] and join["epoch"] == 2
        assert join["world"] == [0, 1, 2]
        assert join["declared"] == ["a", "b"]
        assert join["step"] == 6     # state is the post-step-6 snapshot
        got = unpack_state(join["state"])
        np.testing.assert_allclose(got["w"],
                                   np.arange(4, dtype=np.float32))
        assert counters.get("membership.rejoin_admitted") >= 1
    finally:
        bus.close()


def test_bus_rejoin_times_out_without_a_quorum():
    port = _free_port()
    bus = _BusServer(("127.0.0.1", port), MembershipView(0, (0,)),
                     rendezvous_timeout_s=0.5, sync_timeout_s=0.5)
    try:
        r = _req(port, {"op": "rejoin", "rank": 9})
        assert r == {"ok": False, "timeout": True}
    finally:
        bus.close()


# -- engine epoch guard -----------------------------------------------------


@pytest.mark.chaos
def test_stale_epoch_chunk_dropped_not_delivered():
    """A chunk enqueued before a world change is dropped at dispatch
    with an ABORTED status naming the stale epoch — and fresh pushes
    under the new epoch flow normally."""
    counters.reset()
    api.init(Config())
    eng = api._require()
    eng.pause_dispatch()
    h = eng.push_pull_local_async(np.ones(8, np.float32), "g", op="sum")
    mm.advance_epoch()
    eng.resume_dispatch()
    with pytest.raises(RuntimeError, match="stale membership epoch"):
        h.wait(timeout=20)
    assert counters.get("membership.stale_chunks_dropped") >= 1
    out = eng.push_pull_local(np.ones(8, np.float32), "g", op="sum")
    np.testing.assert_allclose(np.asarray(out), 1.0)


@pytest.mark.chaos
def test_stale_epoch_chunk_dropped_at_completion():
    """The syncer-side guard: a chunk that was already ISSUED when the
    epoch moved is dropped at completion (the result was computed over
    a dead mesh)."""
    api.init(Config())
    eng = api._require()
    h = eng.push_pull_local_async(np.ones(8, np.float32), "g", op="sum")
    # freeze the syncer behind the runtime lock is racy; instead bump
    # after enqueue and rely on whichever guard (dispatch or finish)
    # catches it — both must produce the same recognizable ABORT
    mm.advance_epoch()
    with pytest.raises(RuntimeError, match="stale membership epoch"):
        h.wait(timeout=20)


# -- server engine / kv store epoch gates ----------------------------------


def test_server_engine_drops_stale_membership_push():
    counters.reset()
    srv = ServerEngine(num_threads=1)
    srv.push("k", np.ones(4, np.float32), 0, 1, mepoch=0)
    assert float(srv.pull("k", timeout=10)[0]) == 1.0
    srv.set_membership_epoch(2)
    assert srv.membership_epoch == 2
    srv.set_membership_epoch(1)          # monotonic: no regress
    assert srv.membership_epoch == 2
    # residue from the dead world: dropped, not summed
    srv.push("k", np.full(4, 100.0, np.float32), 0, 1, mepoch=0)
    srv.push("k", np.full(4, 2.0, np.float32), 0, 1, mepoch=2)
    assert float(srv.pull("k", timeout=10)[0]) == 2.0
    assert counters.get("membership.stale_pushes_dropped") == 1
    # un-stamped pushes (non-elastic callers) are never gated
    srv.push("k", np.full(4, 3.0, np.float32), 0, 1)
    assert float(srv.pull("k", timeout=10)[0]) == 3.0
    srv.shutdown()


def test_kv_store_drops_stale_membership_delta():
    counters.reset()
    kv = KVStore()
    kv.init_key("w", np.zeros(4, np.float32))
    assert kv.push_delta("w", np.ones(4), mepoch=0) == 1
    kv.set_membership_epoch(3)
    v = kv.push_delta("w", np.full(4, 50.0), mepoch=0)   # stale: dropped
    assert v == 1                                        # version unchanged
    np.testing.assert_allclose(kv.pull("w"), 1.0)
    assert kv.push_delta("w", np.ones(4), mepoch=3) == 2
    np.testing.assert_allclose(kv.pull("w"), 2.0)
    assert counters.get("membership.stale_pushes_dropped") == 1


# -- assigner resharding / mixed-mode config wiring -------------------------


def test_assigner_reshard_rehashes_and_resets_load():
    a = ServerAssigner(num_servers=4, fn="djb2")
    keys = list(range(64))
    before = {k: a.assign(k, 100) for k in keys}
    assert any(s >= 2 for s in before.values())
    a.reshard(2)
    assert a.load_bytes == [0, 0]        # accounting restarts
    after = {k: a.assign(k, 1) for k in keys}
    assert all(0 <= s < 2 for s in after.values())
    # deterministic: re-assignment equals a fresh 2-server assigner
    fresh = ServerAssigner(num_servers=2, fn="djb2")
    assert after == {k: fresh.assign(k) for k in keys}
    with pytest.raises(ValueError):
        a.reshard(0)


def test_assigner_mixed_mode_from_env(monkeypatch):
    """Satellite: BYTEPS_ENABLE_MIXED_MODE / BYTEPS_MIXED_MODE_BOUND
    reach ServerAssigner through Config env parsing (previously
    programmatic-only)."""
    from byteps_tpu.common.config import reset_config
    monkeypatch.setenv("BYTEPS_ENABLE_MIXED_MODE", "1")
    monkeypatch.setenv("BYTEPS_MIXED_MODE_BOUND", "120")
    monkeypatch.setenv("DMLC_NUM_WORKER", "3")
    reset_config()
    a = ServerAssigner(num_servers=5)
    assert a._mixed and a._bound == 120 and a._num_workers == 3
    # and the mixed constraint still validates through the env path
    monkeypatch.setenv("BYTEPS_MIXED_MODE_BOUND", "2")   # < num_servers
    reset_config()
    with pytest.raises(ValueError, match="MIXED_MODE_BOUND"):
        ServerAssigner(num_servers=5)
    reset_config()


def test_assigner_mixed_reshard_violation_restores_shape():
    a = ServerAssigner(num_servers=5, fn="djb2", mixed_mode=True,
                       num_workers=3, bound=101)
    with pytest.raises(ValueError):
        a.reshard(1, num_workers=0)      # nonsense shape
    assert a.num_servers == 5 and a._num_workers == 3
    # the split is deployment-specific: guessing it would silently
    # misroute, so a mixed reshard without num_workers refuses
    with pytest.raises(ValueError, match="explicit num_workers"):
        a.reshard(4)
    assert a.num_servers == 5 and a._num_workers == 3


# -- state wire form --------------------------------------------------------


def test_pack_unpack_state_roundtrip():
    import jax.numpy as jnp
    state = {"w": jnp.arange(6.0).reshape(2, 3), "opt": {"m": np.ones(3)},
             "step": 17}
    got = unpack_state(pack_state(state))
    np.testing.assert_allclose(got["w"], np.arange(6.0).reshape(2, 3))
    np.testing.assert_allclose(got["opt"]["m"], 1.0)
    assert int(got["step"]) == 17
    assert isinstance(got["w"], np.ndarray)   # host-materialized
