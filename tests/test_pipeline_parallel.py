"""Pipeline parallelism tests on the 8-device CPU mesh.

The make-or-break property: the GPipe schedule is a *schedule*, not a
model — pipelined training from restacked parameters must match plain
single-device GPT training step for step (same loss, same updated
parameters), bubbles and all.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.models.gpt import GPT, GPTConfig, lm_loss
from byteps_tpu.parallel.long_context import synthetic_lm_batch
from byteps_tpu.parallel.pipeline import (
    init_pipeline_params, make_dp_pp_train_step, make_pp_mesh,
    pipeline_params_to_gpt, shard_pipeline_params, shard_pp_batch)
from .conftest import legacy_skip


def _cfg(num_layers=4):
    return GPTConfig(vocab_size=128, hidden_size=32, num_layers=num_layers,
                     num_heads=4, intermediate_size=64, max_position=64,
                     dtype=jnp.float32)


def test_restack_roundtrip():
    cfg = _cfg()
    rng = jax.random.PRNGKey(0)
    ids = jnp.zeros((1, 8), jnp.int32)
    pp = init_pipeline_params(cfg, rng, ids)
    assert jax.tree.leaves(pp["blocks"])[0].shape[0] == cfg.num_layers
    variables = pipeline_params_to_gpt(cfg, pp)
    ref = GPT(cfg).init(rng, ids)
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(ref),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(variables),
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(ka))


@pytest.mark.parametrize("n_pp,microbatches", [(4, 4), (2, 2), (4, 8)])
@legacy_skip  # exact-match numerics diverge on pre-VMA shard_map
def test_pp_training_matches_single_device(n_pp, microbatches):
    cfg = _cfg(num_layers=4)
    rng = jax.random.PRNGKey(1)
    # 16: per-dp-shard batch stays divisible by every microbatch count
    batch = synthetic_lm_batch(rng, cfg, batch=16, seq_len=16)
    pp_params = init_pipeline_params(cfg, rng, batch["input_ids"][:1])
    gpt_vars = pipeline_params_to_gpt(cfg, pp_params)
    tx = optax.sgd(0.1)
    model = GPT(cfg)

    @jax.jit
    def ref_step(p, o, b):
        loss, g = jax.value_and_grad(
            lambda q: lm_loss(model.apply(q, b["input_ids"]),
                              b["labels"]))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    p_ref, o_ref = gpt_vars, tx.init(gpt_vars)
    for _ in range(3):
        p_ref, o_ref, loss_ref = ref_step(p_ref, o_ref, batch)

    mesh = make_pp_mesh(jax.devices()[:8], n_pp=n_pp)  # dp = 8/n_pp
    p_pp = shard_pipeline_params(mesh, pp_params)
    o_pp = jax.jit(tx.init)(p_pp)
    step = make_dp_pp_train_step(mesh, cfg, tx,
                                 num_microbatches=microbatches)
    b_pp = shard_pp_batch(mesh, batch)
    for _ in range(3):
        p_pp, o_pp, loss_pp = step(p_pp, o_pp, b_pp)

    np.testing.assert_allclose(float(loss_pp), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    got = pipeline_params_to_gpt(cfg, jax.device_get(p_pp))
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(p_ref),
                   key=lambda kv: str(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(got),
                   key=lambda kv: str(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=str(ka))


def test_pp_blocks_are_stage_sharded():
    cfg = _cfg(num_layers=4)
    mesh = make_pp_mesh(jax.devices()[:8], n_pp=4)
    rng = jax.random.PRNGKey(2)
    pp_params = init_pipeline_params(cfg, rng, jnp.zeros((1, 8), jnp.int32))
    sharded = shard_pipeline_params(mesh, pp_params)
    leaf = jax.tree.leaves(sharded["blocks"])[0]
    assert leaf.addressable_shards[0].data.shape[0] * 4 == leaf.shape[0]
    emb = jax.tree.leaves(sharded["embed"])[0]
    assert emb.addressable_shards[0].data.shape == emb.shape


def test_pp_trains_loss_decreases():
    cfg = _cfg(num_layers=4)
    rng = jax.random.PRNGKey(3)
    batch = synthetic_lm_batch(rng, cfg, batch=16, seq_len=16)
    mesh = make_pp_mesh(jax.devices()[:8], n_pp=4)
    pp_params = shard_pipeline_params(
        mesh, init_pipeline_params(cfg, rng, batch["input_ids"][:1]))
    tx = optax.adam(1e-2)
    opt_state = jax.jit(tx.init)(pp_params)
    step = make_dp_pp_train_step(mesh, cfg, tx, num_microbatches=4)
    b = shard_pp_batch(mesh, batch)
    losses = []
    for _ in range(10):
        pp_params, opt_state, loss = step(pp_params, opt_state, b)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
