"""Worker body for the 3-process data-integrity chaos test.

Launched three times by tests/test_integrity.py (the subprocess pattern
of tests/test_multiprocess.py / tests/chaos_worker.py): rank 0 hosts a
``ServerEngine`` and a plain TCP accept loop; ranks 1 and 2 connect and
ship their per-step gradients over the membership-bus wire helpers
(``_send_obj``/``_recv_obj`` — length-prefixed, CRC32C-enveloped, frame
clamped), so the cross-PROCESS hop exercises the bus envelope while the
server's push path exercises the loopback-wire envelope.

Per step, every rank derives a deterministic float32 gradient from
(seed, step, rank); rank 0 pushes all three contributions into the
engine in a fixed order (num_threads=1, so the merge order — COPY_FIRST
then SUM_RECV in arrival order — is reproducible bit-for-bit), pulls the
merged sum, broadcasts it back, and every rank applies the same SGD
update.  The chaos variant arms ``bitflip:site=server_push:p=0.05`` in
rank 0: each corrupted frame must be NACKed (``integrity.crc_reject``)
and retransmitted from the sealed source copy, so the final parameters
are BIT-IDENTICAL to the fault-free run from the same seed — that
equality is the test's headline assertion.

Env (set by the test): BYTEPS_INTEG_RANK, BYTEPS_INTEG_PORT,
BYTEPS_INTEG_OUT (rank 0 writes final params there), plus
BYTEPS_FAULT_SPEC / BYTEPS_FAULT_SEED for the chaos variant.

BYTEPS_INTEG_COMPRESS=<codec> (ISSUE 11): the QUANTIZED variant — every
worker compresses its gradient with the named codec (+ error feedback)
and ships WIRE-ENCODED payload bytes; rank 0 pushes them through
``ServerEngine.push_compressed`` (the envelope then wraps the quantized
frame — exactly what a real network hop would carry, and what the chaos
bitflip corrupts), pulls the merged result re-compressed
(``pull_compressed``) and broadcasts the merged wire bytes, which every
rank decodes identically.  The bit-identical-final assertion therefore
covers the compressed wire path end to end: a corrupt quantized frame
must be NACKed and retransmitted BEFORE the decode runs.
"""

from __future__ import annotations

import hashlib
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

STEPS = 30
N = 257  # odd, > one cache line: bitflips land all over the frame
LR = np.float32(0.05)


def _grad(step: int, rank: int) -> np.ndarray:
    return np.random.RandomState(7919 * step + rank).randn(N) \
        .astype(np.float32)


def main() -> int:
    rank = int(os.environ["BYTEPS_INTEG_RANK"])
    port = int(os.environ["BYTEPS_INTEG_PORT"])

    from byteps_tpu.common.telemetry import counters
    from byteps_tpu.fault import injector as inj
    from byteps_tpu.fault.membership import _recv_obj, _send_obj

    spec = os.environ.get("BYTEPS_FAULT_SPEC", "")
    if spec and rank == 0:
        inj.arm(spec, seed=int(os.environ.get("BYTEPS_FAULT_SEED", "0")),
                rank=rank)

    codec = os.environ.get("BYTEPS_INTEG_COMPRESS", "")
    comp_kw = {"compressor": codec, "ef": "vanilla"} if codec else None
    wcomp = wstate = None
    if comp_kw:
        import jax.numpy as jnp  # noqa: F401 — compress runs on jax
        from byteps_tpu.compression import create as create_compressor
        wcomp = create_compressor(comp_kw, N)
        wstate = wcomp.init_state()

    def _my_wire(step: int, r: int) -> bytes:
        """This rank's wire-encoded compressed gradient for ``step``
        (error-feedback state advances across steps, deterministically
        per rank)."""
        nonlocal wstate
        import jax.numpy as jnp
        payload, wstate = wcomp.compress(jnp.asarray(_grad(step, r)),
                                         wstate)
        return wcomp.wire_encode(payload)

    def _decode(wire: bytes) -> np.ndarray:
        """Merged wire bytes -> values; stateless, so every rank's
        decode of the same bytes is bit-identical."""
        return np.asarray(wcomp.decompress(wcomp.wire_decode(wire)),
                          np.float32)

    params = np.zeros(N, np.float32)

    if rank == 0:
        from byteps_tpu.server.engine import ServerEngine
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(2)
        srv.settimeout(60)
        conns = {}
        for _ in range(2):
            c, _addr = srv.accept()
            hello = _recv_obj(c)
            conns[hello["rank"]] = c
        eng = ServerEngine(num_threads=1)
        if comp_kw:
            eng.register_compression("grad", comp_kw, N)
        try:
            for step in range(STEPS):
                grads = {0: (_my_wire(step, 0) if comp_kw
                             else _grad(step, 0))}
                # fixed receive AND push order: the merge is
                # COPY_FIRST(0) + SUM_RECV(1) + SUM_RECV(2) every run,
                # so the float32 sum is bit-reproducible
                for r in (1, 2):
                    msg = _recv_obj(conns[r])
                    assert msg["step"] == step, (msg["step"], step)
                    grads[r] = msg["grad"]
                if comp_kw:
                    for r in (0, 1, 2):
                        eng.push_compressed("grad", grads[r], worker_id=r,
                                            num_workers=3)
                    wire = eng.pull_compressed("grad", timeout=30)
                    merged = _decode(wire)
                    for r in (1, 2):
                        _send_obj(conns[r], {"step": step, "merged": wire})
                else:
                    for r in (0, 1, 2):
                        eng.push("grad", grads[r], worker_id=r,
                                 num_workers=3)
                    merged = np.asarray(eng.pull("grad", timeout=30))
                    for r in (1, 2):
                        _send_obj(conns[r], {"step": step,
                                             "merged": merged})
                params -= LR * merged
        finally:
            eng.shutdown()
            for c in conns.values():
                c.close()
            srv.close()
        with open(os.environ["BYTEPS_INTEG_OUT"], "wb") as f:
            f.write(params.tobytes())
        print("REJECTS", counters.get("integrity.crc_reject"), flush=True)
        print("RETRANS", counters.get("integrity.retransmit"), flush=True)
    else:
        import time
        deadline = time.monotonic() + 60
        while True:  # rank 0 may not be listening yet
            try:
                sock = socket.create_connection(("127.0.0.1", port),
                                                timeout=60)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
        _send_obj(sock, {"rank": rank})
        try:
            for step in range(STEPS):
                g = _my_wire(step, rank) if comp_kw else _grad(step, rank)
                _send_obj(sock, {"step": step, "grad": g})
                reply = _recv_obj(sock)
                assert reply["step"] == step, (reply["step"], step)
                merged = (_decode(reply["merged"]) if comp_kw
                          else np.asarray(reply["merged"]))
                params -= LR * merged
        finally:
            sock.close()

    print("DIGEST", rank, hashlib.sha256(params.tobytes()).hexdigest(),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
