"""Data-integrity layer tests (common/integrity.py + the four wire paths).

What is pinned here:

- the envelope itself: CRC32C backends agree on the Castagnoli check
  value, every single-bit corruption of a frame is detected, shape/dtype
  mangling is as detectable as payload corruption;
- codec goldens: a corrupt *compressed* payload (onebit sign-packs,
  elias-coded dithering, PRNG-seeded sparsification) is rejected by the
  envelope before the codec ever decodes it — one flipped bit in an
  entropy-coded stream would otherwise decode into a many-element error;
- KVStore idempotence: per-(key, worker) sequence dedup makes a retry
  after a lost ack (chaos ``drop:site=kv_push``) a no-op, and the
  wasted-bytes accounting keeps ``wire_bytes`` meaningful under chaos;
- the non-finite quarantine on both the sync engine and the async store
  under all three ``BYTEPS_NONFINITE_POLICY`` values;
- the membership bus frame clamp (``BYTEPS_BUS_MAX_FRAME``) and envelope
  verification, and ``pack_state``/``unpack_state`` rejoin-blob sealing.

The multi-process headline proof (3-process bitflip chaos run converging
bit-identical to a fault-free run) lives at the bottom, ``chaos``-marked.
"""

from __future__ import annotations

import hashlib
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common import integrity
from byteps_tpu.common.config import reset_config
from byteps_tpu.common.telemetry import counters
from byteps_tpu.fault import injector as inj

from .conftest import free_port as _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.integrity


@pytest.fixture(autouse=True)
def _fresh_counters():
    counters.reset()
    yield
    inj.disarm()


# -- CRC32C backends --------------------------------------------------------

def test_crc32c_castagnoli_check_value():
    assert integrity.crc32c(b"123456789") == 0xE3069283


def test_crc32c_incremental_continuation():
    whole = integrity.crc32c(b"123456789")
    assert integrity.crc32c(b"6789", integrity.crc32c(b"12345")) == whole


def test_crc32c_backends_agree():
    """Whichever backend _pick_impl chose must match the pure-Python
    table (and the native core, when the toolchain built it)."""
    table = integrity._py_table()

    def pure(data, crc=0):
        c = ~crc & 0xFFFFFFFF
        for b in data:
            c = table[(c ^ b) & 0xFF] ^ (c >> 8)
        return ~c & 0xFFFFFFFF

    rng = np.random.RandomState(7)
    for n in (0, 1, 7, 8, 9, 63, 64, 65, 1024):
        buf = rng.bytes(n)
        assert integrity.crc32c(buf) == pure(buf), n
    from byteps_tpu.native import crc32c as native_crc
    got = native_crc(b"123456789")
    if got is not None:  # native core built on this host
        assert got == 0xE3069283
        buf = rng.bytes(4097)
        assert native_crc(buf, 123) == pure(buf, 123)


# -- envelope round-trips and corruption detection --------------------------

@pytest.mark.parametrize("dtype,shape", [
    (np.float32, (16,)), (np.float16, (3, 5)), (np.int64, (4,)),
    (np.float64, ()), (np.uint8, (0,)),
])
def test_seal_open_array_roundtrip(dtype, shape):
    arr = np.zeros(shape, dtype) if 0 in shape or shape == () \
        else np.arange(np.prod(shape), dtype=dtype).reshape(shape)
    frame = integrity.seal_array(arr, key="k/0", seq=42, worker=3)
    out, meta = integrity.open_array(frame)
    np.testing.assert_array_equal(out, arr)
    assert out.dtype == arr.dtype and out.shape == arr.shape
    assert (meta.key, meta.seq, meta.worker) == ("k/0", 42, 3)


def test_seal_open_bytes_roundtrip():
    blob = b"\x00\x01BPSE not a header\xff" * 9
    frame = integrity.seal_bytes(blob, key="blob", seq=7, worker=-1)
    out, meta = integrity.open_bytes(frame)
    assert bytes(out) == blob
    assert meta.kind == integrity.KIND_BYTES and meta.seq == 7


def test_every_single_bitflip_is_detected():
    """CRC32C catches all single-bit errors: flip EVERY bit of a frame
    (header, shape dims, payload, and the CRC trailer itself) and the
    open must reject each one."""
    frame = bytearray(integrity.seal_array(
        np.arange(6, dtype=np.float32).reshape(2, 3), key="g", seq=1,
        worker=0))
    for bit in range(len(frame) * 8):
        frame[bit // 8] ^= 1 << (bit % 8)
        with pytest.raises(integrity.IntegrityError):
            integrity.open_frame(bytes(frame))
        frame[bit // 8] ^= 1 << (bit % 8)
    integrity.open_frame(bytes(frame))  # restored: intact again


def test_truncation_and_bad_magic_rejected():
    frame = integrity.seal_bytes(b"payload", key="k")
    with pytest.raises(integrity.IntegrityError, match="truncated"):
        integrity.open_frame(frame[:8])
    with pytest.raises(integrity.IntegrityError, match="magic"):
        integrity.open_frame(b"XXXX" + frame[4:])
    with pytest.raises(integrity.IntegrityError):
        integrity.open_frame(frame[:-3])  # lost trailer bytes


def test_shape_and_dtype_mangling_rejected():
    """A frame whose header is internally inconsistent (even with a
    VALID CRC over the mangled bytes) must be rejected — re-sealing a
    tampered header cannot smuggle a wrong-shaped array through."""
    payload = np.ones(8, np.float32).tobytes()
    bad_shape = integrity._seal(integrity.KIND_NDARRAY, "k", 0, 1, "<f4",
                                (9,), payload)
    with pytest.raises(integrity.IntegrityError, match="shape-mangled"):
        integrity.open_frame(bad_shape)
    bad_dtype = integrity._seal(integrity.KIND_NDARRAY, "k", 0, 1,
                                "not-a-dtype", (8,), payload)
    with pytest.raises(integrity.IntegrityError, match="dtype"):
        integrity.open_frame(bad_dtype)
    bad_kind = integrity._seal(9, "k", 0, 1, "", (), b"x")
    with pytest.raises(integrity.IntegrityError, match="kind"):
        integrity.open_frame(bad_kind)


def test_kind_mismatch_between_open_array_and_open_bytes():
    af = integrity.seal_array(np.ones(2, np.float32), key="a")
    bf = integrity.seal_bytes(b"b", key="b")
    with pytest.raises(integrity.IntegrityError, match="ndarray"):
        integrity.open_array(bf)
    with pytest.raises(integrity.IntegrityError, match="bytes"):
        integrity.open_bytes(af)


def test_is_frame_sniff():
    assert integrity.is_frame(integrity.seal_bytes(b"x", key="k"))
    assert not integrity.is_frame(b"BPSE")          # too short
    assert not integrity.is_frame(b"\x80\x04pickle" + b"\0" * 40)


def test_integrity_off_is_passthrough(monkeypatch):
    """BYTEPS_INTEGRITY=0: nothing is sealed — pack_state returns the
    raw pickle and the engine never touches the envelope."""
    from byteps_tpu.utils.checkpoint import pack_state, unpack_state
    monkeypatch.setenv("BYTEPS_INTEGRITY", "0")
    reset_config()
    assert not integrity.enabled()
    blob = pack_state({"w": np.ones(3, np.float32)})
    assert not integrity.is_frame(blob)
    np.testing.assert_array_equal(unpack_state(blob)["w"], 1.0)


# -- codec goldens: the envelope rejects corrupt compressed payloads --------

CODECS = {
    "onebit": {"compressor": "onebit"},
    # dithering's wire format IS the elias-delta entropy coder
    # (compression/elias.py): the worst case for undetected corruption
    "elias": {"compressor": "dithering", "k": 16, "partition": "linear"},
    "dithering": {"compressor": "dithering", "k": 8,
                  "partition": "natural", "normalize": "l2"},
    # PRNG-index sparsification: decode re-derives indices from the seed
    "prng": {"compressor": "randomk", "k": 0.25, "seed": 11},
}


@pytest.mark.parametrize("name", sorted(CODECS))
def test_envelope_golden_roundtrip_per_codec(name):
    """Seal the codec's wire bytes, open, decode: bit-identical to
    decoding the original payload directly."""
    import jax.numpy as jnp
    from byteps_tpu.compression import registry as reg
    rng = np.random.RandomState(3)
    x = rng.randn(512).astype(np.float32)
    comp = reg.create(CODECS[name], x.size, np.float32)
    payload, _ = comp.compress(jnp.asarray(x), comp.init_state())
    wire = comp.wire_encode(payload)
    frame = integrity.seal_bytes(wire, key=name, seq=1, worker=0)
    opened, _ = integrity.open_bytes(frame)
    assert bytes(opened) == bytes(wire)
    direct = np.asarray(comp.decompress(comp.wire_decode(bytes(wire))))
    via = np.asarray(comp.decompress(comp.wire_decode(bytes(opened))))
    np.testing.assert_array_equal(via, direct)


@pytest.mark.parametrize("name", sorted(CODECS))
def test_envelope_rejects_corrupt_compressed_payload(name):
    """Flip bits in the sealed compressed payload: the envelope must
    NACK every corruption — the codec never sees unverified bytes."""
    import jax.numpy as jnp
    from byteps_tpu.compression import registry as reg
    rng = np.random.RandomState(4)
    x = rng.randn(512).astype(np.float32)
    comp = reg.create(CODECS[name], x.size, np.float32)
    payload, _ = comp.compress(jnp.asarray(x), comp.init_state())
    wire = comp.wire_encode(payload)
    frame = bytearray(integrity.seal_bytes(wire, key=name, seq=1,
                                           worker=0))
    body = len(frame) - len(wire) - 4  # payload starts here
    for byte in (body, body + len(wire) // 2, len(frame) - 5):
        frame[byte] ^= 0x10
        with pytest.raises(integrity.IntegrityError):
            integrity.open_bytes(bytes(frame))
        frame[byte] ^= 0x10


# -- KVStore: idempotent pushes, wasted-byte accounting ---------------------

def _store():
    from byteps_tpu.server import KVStore
    return KVStore()


def test_kv_seq_dedup_never_double_sums():
    s = _store()
    s.init_key("w", np.zeros(4, np.float32))
    v1 = s.push_delta("w", np.ones(4, np.float32), worker_id=0, seq=1)
    # the retry of the same push (same token): dropped, version unchanged
    v2 = s.push_delta("w", np.ones(4, np.float32), worker_id=0, seq=1)
    assert (v1, v2) == (1, 1)
    np.testing.assert_array_equal(s.pull("w"), 1.0)
    assert counters.get("integrity.dup_dropped") == 1
    # a later token from the same worker, and the same token from a
    # DIFFERENT worker, both land
    s.push_delta("w", np.ones(4, np.float32), worker_id=0, seq=2)
    s.push_delta("w", np.ones(4, np.float32), worker_id=1, seq=1)
    np.testing.assert_array_equal(s.pull("w"), 3.0)
    # legacy callers without a token stay unprotected but functional
    s.push_delta("w", np.ones(4, np.float32))
    np.testing.assert_array_equal(s.pull("w"), 4.0)


def test_kv_rejoined_worker_seq_restart_not_starved():
    """A membership-epoch adoption resets the dedup floors: a rejoined
    incarnation of a dead rank restarts its seq counter at 1 and must
    not be dup-dropped forever against the dead incarnation's floor."""
    s = _store()
    s.init_key("w", np.zeros(2, np.float32))
    s.push_delta("w", np.ones(2, np.float32), worker_id=1, seq=50)
    s.set_membership_epoch(s._membership_epoch + 1)
    s.push_delta("w", np.ones(2, np.float32), worker_id=1, seq=1)
    np.testing.assert_array_equal(s.pull("w"), 2.0)
    assert counters.get("integrity.dup_dropped") == 0


def test_kv_retry_across_membership_change_cannot_double_sum():
    """The dedup-floor reset on epoch adoption cannot reopen a
    double-sum: a retry of a pre-change push carries the OLD mepoch
    (async_opt stamps the epoch once per logical push, outside the
    retry loop) and is dropped by the stale gate, not the floor."""
    s = _store()
    s.init_key("w", np.zeros(2, np.float32))
    e = s._membership_epoch
    s.push_delta("w", np.ones(2, np.float32), worker_id=0, seq=1, mepoch=e)
    s.set_membership_epoch(e + 1)   # elastic world change; floors reset
    # the lost-ack retry of the SAME logical push, stamped pre-change
    s.push_delta("w", np.ones(2, np.float32), worker_id=0, seq=1, mepoch=e)
    np.testing.assert_array_equal(s.pull("w"), 1.0)  # summed ONCE


def test_kv_push_bitflip_fires_with_integrity_off(monkeypatch):
    """bitflip:site=kv_push must corrupt the delta even when the
    envelope is disabled — the unprotected baseline must never be a
    silent no-op that reports a clean run (mirrors ServerEngine.push)."""
    monkeypatch.setenv("BYTEPS_INTEGRITY", "0")
    reset_config()
    s = _store()
    s.init_key("w", np.zeros(4, np.float32))
    inj.arm("bitflip:site=kv_push:p=1", seed=2, rank=0)
    try:
        s.push_delta("w", np.ones(4, np.float32), worker_id=0, seq=1)
    finally:
        inj.disarm()
    assert counters.get("fault.bitflip") > 0
    assert not np.array_equal(s.pull("w"), np.ones(4, np.float32))


def test_async_push_stamps_membership_epoch(monkeypatch):
    """update_and_sync stamps each logical push with the membership
    epoch captured OUTSIDE the ack-retry loop — the stale gate (not the
    cleared dedup floor) is what blocks a retry that crosses an elastic
    world change."""
    import jax.numpy as jnp
    import optax
    from byteps_tpu.fault import membership as mem
    from byteps_tpu.jax.async_opt import AsyncDistributedOptimizer
    aopt = AsyncDistributedOptimizer(optax.sgd(1.0))
    params = {"w": jnp.zeros(4)}
    state = aopt.init(params)
    seen = []
    orig = aopt._store.push_delta

    def spy(key, delta, mepoch=None, worker_id=0, seq=None):
        seen.append(mepoch)
        return orig(key, delta, mepoch=mepoch, worker_id=worker_id,
                    seq=seq)

    monkeypatch.setattr(aopt._store, "push_delta", spy)
    aopt.update_and_sync({"w": jnp.ones(4)}, state, params)
    assert seen == [mem.current_epoch()]


def test_kv_ack_lost_retry_is_exactly_once():
    """drop:site=kv_push loses the ACK *after* the sum applied; the
    retry with the same seq token is absorbed by the dedup."""
    s = _store()
    s.init_key("w", np.zeros(2, np.float32))
    inj.arm("drop:site=kv_push:p=1", seed=1, rank=0)
    with pytest.raises(integrity.AckLost):
        s.push_delta("w", np.ones(2, np.float32), worker_id=0, seq=1)
    with pytest.raises(integrity.AckLost):  # the retry: dedup'd, ack lost
        s.push_delta("w", np.ones(2, np.float32), worker_id=0, seq=1)
    inj.disarm()
    np.testing.assert_array_equal(s.pull("w"), 1.0)  # summed ONCE
    assert counters.get("integrity.dup_dropped") == 1


def test_kv_wire_retransmit_budget_and_wasted_accounting():
    """bitflip:p=1 corrupts every transmission: the push exhausts the
    bounded retransmit budget and fails loudly; wire_bytes counts
    nothing, wire_bytes_wasted counts every rejected attempt."""
    s = _store()
    s.init_key("w", np.zeros(8, np.float32))
    s.register_compression("w", {"compressor": "onebit"}, 8)
    import jax.numpy as jnp
    from byteps_tpu.compression import registry as reg
    comp = reg.create({"compressor": "onebit"}, 8, np.float32)
    payload, _ = comp.compress(jnp.ones(8), comp.init_state())
    wire = comp.wire_encode(payload)
    inj.arm("bitflip:site=kv_push:p=1", seed=2, rank=0)
    with pytest.raises(integrity.IntegrityError):
        s.push_delta_wire("w", wire, worker_id=0, seq=1)
    inj.disarm()
    budget = integrity.max_retransmits()
    assert counters.get("integrity.crc_reject") == budget + 1
    assert counters.get("integrity.retransmit") == budget
    assert s.wire_bytes == 0
    assert s.wire_bytes_wasted == (budget + 1) * len(wire)
    # the failed push did not burn its token: the caller's retry with
    # the SAME seq lands (only a push that reached its final fate
    # advances the dedup floor), and only now wire_bytes moves
    s.push_delta_wire("w", wire, worker_id=0, seq=1)
    assert s.wire_bytes == len(wire)
    assert counters.get("integrity.dup_dropped") == 0
    np.testing.assert_array_equal(s.pull("w"), 1.0)


def test_kv_duplicate_wire_push_counts_wasted():
    s = _store()
    s.init_key("w", np.zeros(8, np.float32))
    s.register_compression("w", {"compressor": "onebit"}, 8)
    import jax.numpy as jnp
    from byteps_tpu.compression import registry as reg
    comp = reg.create({"compressor": "onebit"}, 8, np.float32)
    payload, _ = comp.compress(jnp.ones(8), comp.init_state())
    wire = comp.wire_encode(payload)
    s.push_delta_wire("w", wire, worker_id=0, seq=1)
    s.push_delta_wire("w", wire, worker_id=0, seq=1)  # retry: dropped
    assert s.wire_bytes == len(wire)
    assert s.wire_bytes_wasted == len(wire)
    assert counters.get("integrity.dup_dropped") == 1
    np.testing.assert_array_equal(s.pull("w"), 1.0)


# -- non-finite quarantine --------------------------------------------------

def _nan_delta():
    d = np.ones(4, np.float32)
    d[2] = np.nan
    return d


def test_kv_nonfinite_raise_blames_worker():
    s = _store()
    s.init_key("w", np.zeros(4, np.float32))
    with pytest.raises(ValueError, match="worker 3"):
        s.push_delta("w", _nan_delta(), worker_id=3, seq=1)
    np.testing.assert_array_equal(s.pull("w"), 0.0)
    assert counters.get("integrity.nonfinite_rejected") == 1


def test_kv_nonfinite_skip_and_zero(monkeypatch):
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "skip")
    reset_config()
    s = _store()
    s.init_key("w", np.zeros(4, np.float32))
    v = s.push_delta("w", _nan_delta(), worker_id=0, seq=1)
    assert v == 0  # dropped: version did not advance
    np.testing.assert_array_equal(s.pull("w"), 0.0)
    assert counters.get("integrity.nonfinite_skipped") == 1
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "zero")
    reset_config()
    s.push_delta("w", _nan_delta(), worker_id=0, seq=2)
    np.testing.assert_array_equal(s.pull("w"),
                                  np.array([1, 1, 0, 1], np.float32))
    assert counters.get("integrity.nonfinite_zeroed") == 1


def test_kv_merge_overflow_skip_restores_previous_value(monkeypatch):
    """Contributions can be finite while the MERGE is not (float32
    overflow): skip must undo the sum, leaving the stored value at its
    previous version."""
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "skip")
    reset_config()
    s = _store()
    big = np.full(2, np.finfo(np.float32).max, np.float32)
    s.init_key("w", big)
    v = s.push_delta("w", big, worker_id=0, seq=1)  # max + max -> inf
    assert v == 0
    np.testing.assert_array_equal(s.pull("w"), big)
    assert counters.get("integrity.nonfinite_skipped") == 1


def test_kv_merge_overflow_raise_restores_previous_value():
    """raise (the default policy) must ALSO leave the store untouched:
    the error goes to the pushing worker only, so a mutated value would
    be silently pullable by every other worker — the exact poisoning
    this layer exists to stop."""
    s = _store()
    big = np.full(2, np.finfo(np.float32).max, np.float32)
    s.init_key("w", big)
    with pytest.raises(RuntimeError, match="non-finite"):
        s.push_delta("w", big, worker_id=0, seq=1)  # max + max -> inf
    np.testing.assert_array_equal(s.pull("w"), big)
    assert counters.get("integrity.nonfinite_rejected") == 1


def _engine(**kw):
    from byteps_tpu.server.engine import ServerEngine
    return ServerEngine(num_threads=1, **kw)


def test_engine_nonfinite_push_raise_names_worker():
    eng = _engine()
    try:
        with pytest.raises(ValueError, match="worker 1"):
            eng.push("g", _nan_delta(), worker_id=1, num_workers=2)
    finally:
        eng.shutdown()


def test_engine_nonfinite_skip_republishes_previous_merge(monkeypatch):
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "skip")
    reset_config()
    eng = _engine()
    try:
        # round 1: clean — version 1 published
        for r in range(2):
            eng.push("g", np.ones(4, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
        # round 2: worker 1's contribution is NaN — the round is
        # quarantined and the previous merge is republished
        eng.push("g", np.ones(4, np.float32), worker_id=0, num_workers=2)
        eng.push("g", _nan_delta(), worker_id=1, num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
        assert counters.get("integrity.nonfinite_skipped") == 1
        # round 3: clean again — the engine was not wedged
        for r in range(2):
            eng.push("g", np.full(4, 3.0, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 6.0)
    finally:
        eng.shutdown()


def test_engine_nonfinite_zero_patches_contribution(monkeypatch):
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "zero")
    reset_config()
    eng = _engine()
    try:
        eng.push("g", _nan_delta(), worker_id=0, num_workers=2)
        eng.push("g", np.ones(4, np.float32), worker_id=1, num_workers=2)
        np.testing.assert_array_equal(
            eng.pull("g", timeout=5), np.array([2, 2, 1, 2], np.float32))
        assert counters.get("integrity.nonfinite_zeroed") == 1
    finally:
        eng.shutdown()


def test_engine_quarantine_drops_late_same_round_pushes(monkeypatch):
    """A worker whose round-k push arrives AFTER the round was
    quarantined must be dropped (one-shot), not counted into the
    restarted round — otherwise every later merge is phase-shifted by
    one contribution and publishes sums mixing two steps."""
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "skip")
    reset_config()
    eng = _engine()
    try:
        # round 1: clean — a previous merge exists to republish
        for r in range(3):
            eng.push("g", np.ones(4, np.float32), worker_id=r,
                     num_workers=3)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 3.0)
        # round 2: w0 lands, w1 is NaN (quarantine fires while w2's
        # contribution is still inbound), w2 arrives late
        eng.push("g", np.ones(4, np.float32), worker_id=0, num_workers=3)
        eng.push("g", _nan_delta(), worker_id=1, num_workers=3)
        eng.push("g", np.ones(4, np.float32), worker_id=2, num_workers=3)
        assert counters.get("integrity.quarantine_dropped") == 1
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 3.0)
        # round 3: clean and NOT phase-shifted — exactly these three
        # contributions publish
        for r in range(3):
            eng.push("g", np.full(4, 2.0, np.float32), worker_id=r,
                     num_workers=3)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 6.0)
    finally:
        eng.shutdown()


def test_engine_quarantine_drops_late_push_from_noncontiguous_rank(
        monkeypatch):
    """Post-shrink worlds keep ORIGINAL ranks (the elastic shrink's
    coordinator is the lowest LIVE rank): survivors {0, 2} with
    num_workers=2 must have rank 2's still-inbound push dropped by a
    quarantine — the drop set is derived from the ids actually seen,
    not from range(num_workers)."""
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "skip")
    reset_config()
    eng = _engine()
    try:
        # round 1: clean — survivors are ranks 0 and 2
        for r in (0, 2):
            eng.push("g", np.ones(4, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
        # round 2: rank 0's NaN quarantines while rank 2's contribution
        # is still inbound — rank 2 must be one-shot-dropped even though
        # it lies outside range(num_workers)
        eng.push("g", _nan_delta(), worker_id=0, num_workers=2)
        eng.push("g", np.ones(4, np.float32), worker_id=2, num_workers=2)
        assert counters.get("integrity.quarantine_dropped") == 1
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
        # round 3: clean and NOT phase-shifted
        for r in (0, 2):
            eng.push("g", np.full(4, 2.0, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 4.0)
    finally:
        eng.shutdown()


def test_engine_quarantine_spares_queued_earlier_round(monkeypatch):
    """A quarantine is scoped to the blamed push's OWN round: a fully
    pushed earlier round still sitting in the queue (backlogged engine)
    must merge and publish normally — the round restart must not discard
    a valid round's three contributions wholesale."""
    from byteps_tpu.server import engine as engine_mod
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "skip")
    reset_config()
    gate = threading.Event()
    orig = engine_mod.PriorityQueue.wait_and_pop

    def gated(self):
        gate.wait()
        return orig(self)

    monkeypatch.setattr(engine_mod.PriorityQueue, "wait_and_pop", gated)
    eng = _engine()
    try:
        # round 1 fully pushed while the engine is backlogged (gate shut)
        for r in range(3):
            eng.push("g", np.full(4, float(r + 1), np.float32),
                     worker_id=r, num_workers=3)
        # round 2: worker 0's contribution is NaN — the quarantine fires
        # with round 1 still queued, and must spare it
        eng.push("g", _nan_delta(), worker_id=0, num_workers=3)
        gate.set()
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 6.0)
        # workers 1 and 2's round-2 contributions are one-shot-dropped
        eng.push("g", np.ones(4, np.float32), worker_id=1, num_workers=3)
        eng.push("g", np.ones(4, np.float32), worker_id=2, num_workers=3)
        assert counters.get("integrity.quarantine_dropped") == 2
        # round 3: clean and not phase-shifted
        for r in range(3):
            eng.push("g", np.full(4, 3.0, np.float32), worker_id=r,
                     num_workers=3)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 9.0)
    finally:
        gate.set()
        eng.shutdown()


def test_engine_quarantine_discards_partial_merge_of_blamed_round(
        monkeypatch):
    """When part of the blamed round is already summed into the merge
    buffer, the quarantine discards that partial sum — the next
    surviving round's COPY_FIRST starts from scratch, not on top of two
    stale contributions."""
    from byteps_tpu.server import engine as engine_mod
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "skip")
    reset_config()
    sem = threading.Semaphore(0)
    orig = engine_mod.PriorityQueue.wait_and_pop

    def gated(self):
        sem.acquire()
        return orig(self)

    monkeypatch.setattr(engine_mod.PriorityQueue, "wait_and_pop", gated)
    eng = _engine()
    try:
        st = eng._state("g")
        eng.push("g", np.ones(4, np.float32), worker_id=1, num_workers=3)
        eng.push("g", np.ones(4, np.float32), worker_id=2, num_workers=3)
        sem.release(2)
        deadline = time.monotonic() + 5
        while st.count < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert st.count == 2
        # worker 0 completes the round with a NaN: the quarantine takes
        # the two already-merged contributions down with the round
        eng.push("g", _nan_delta(), worker_id=0, num_workers=3)
        # a fresh clean round publishes exactly its own three pushes
        for r in range(3):
            eng.push("g", np.full(4, 2.0, np.float32), worker_id=r,
                     num_workers=3)
        sem.release(10)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 6.0)
    finally:
        sem.release(100)
        eng.shutdown()


def test_engine_merged_overflow_skip_republishes(monkeypatch):
    """Finite contributions, non-finite merge (overflow at ALL_RECV):
    the skip policy republishes the previous version instead of the inf."""
    monkeypatch.setenv("BYTEPS_NONFINITE_POLICY", "skip")
    reset_config()
    eng = _engine()
    big = np.full(2, np.finfo(np.float32).max, np.float32)
    try:
        for r in range(2):
            eng.push("g", np.ones(2, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
        for r in range(2):
            eng.push("g", big, worker_id=r, num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
        assert counters.get("integrity.nonfinite_skipped") == 1
    finally:
        eng.shutdown()


def test_engine_pull_after_reset_key_parks_not_none():
    """reset_key drops the merged buffer but keeps the completed-round
    version (pull caches keyed on it must never regress) — a pull in
    that window must PARK for the next round, not answer immediately
    with an object array wrapping None."""
    eng = _engine()
    try:
        for r in range(2):
            eng.push("g", np.ones(4, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
        eng.reset_key("g")
        with pytest.raises(TimeoutError):  # parked: nothing to answer with
            eng.pull("g", timeout=0.2)
        for r in range(2):
            eng.push("g", np.full(4, 3.0, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 6.0)
    finally:
        eng.shutdown()


# -- in-process loopback fast path (ISSUE 5 tentpole part 4) ----------------

def test_engine_loopback_fast_path_skips_envelope(monkeypatch):
    """No chaos armed: the in-process push snapshots with one plain copy
    — no seal, no CRC, no frame build — while every BYTEPS_INTEGRITY=1
    semantic downstream still runs."""
    calls = {"seal": 0}
    real_seal = integrity.seal_array

    def spy(*a, **kw):
        calls["seal"] += 1
        return real_seal(*a, **kw)

    monkeypatch.setattr(integrity, "seal_array", spy)
    eng = _engine()
    try:
        assert integrity.enabled() and integrity.loopback_fast()
        for r in range(2):
            eng.push("g", np.full(8, r + 1.0, np.float32), worker_id=r,
                     num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 3.0)
    finally:
        eng.shutdown()
    assert calls["seal"] == 0
    assert counters.get("integrity.loopback_fast") == 2
    assert counters.get("integrity.crc_reject") == 0


def test_engine_loopback_fast_path_snapshots_contribution():
    """push() is async: a caller that reuses its gradient buffer after
    push returns must not corrupt the merge — the fast path snapshots
    the contribution exactly as the envelope path's seal->open did."""
    eng = _engine()
    try:
        a = np.ones(64, np.float32)
        eng.push("g", a, worker_id=0, num_workers=2)
        a[:] = 999.0          # caller reuse, before the round completes
        eng.push("g", np.ones(64, np.float32), worker_id=1, num_workers=2)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
    finally:
        eng.shutdown()


def test_engine_loopback_fast_path_still_screens_nonfinite():
    """The fast path must not bypass the non-finite screen — the raise
    policy still names the blamed worker on a skipped envelope."""
    eng = _engine()
    try:
        with pytest.raises(ValueError, match="worker 1"):
            eng.push("g", _nan_delta(), worker_id=1, num_workers=2)
        assert counters.get("integrity.loopback_fast") == 1
    finally:
        eng.shutdown()


def test_engine_loopback_disabled_forces_envelope(monkeypatch):
    """BYTEPS_INTEGRITY_LOOPBACK=0 pins the full seal->CRC->open path on
    every hop, chaos or not."""
    monkeypatch.setenv("BYTEPS_INTEGRITY_LOOPBACK", "0")
    reset_config()
    calls = {"seal": 0}
    real_seal = integrity.seal_array

    def spy(*a, **kw):
        calls["seal"] += 1
        return real_seal(*a, **kw)

    monkeypatch.setattr(integrity, "seal_array", spy)
    eng = _engine()
    try:
        eng.push("g", np.ones(8, np.float32), worker_id=0, num_workers=1)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 1.0)
    finally:
        eng.shutdown()
        reset_config()
    assert calls["seal"] == 1
    assert counters.get("integrity.loopback_fast") == 0


def test_engine_loopback_chaos_reroutes_through_envelope():
    """Arming chaos mid-run flips the SAME engine from the fast path to
    the verifying envelope: the corruption is caught, retransmitted, and
    the merge converges exactly (the fast path can never mask a fault
    the chaos harness injects)."""
    eng = _engine()
    try:
        eng.push("g", np.ones(8, np.float32), worker_id=0, num_workers=2)
        assert counters.get("integrity.loopback_fast") == 1
        inj.arm("bitflip:site=server_push:p=0.5", seed=3, rank=0)
        eng.push("g", np.ones(8, np.float32), worker_id=1, num_workers=2)
        inj.disarm()
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 2.0)
        # the armed push went through the wire, not the fast path
        assert counters.get("integrity.loopback_fast") == 1
    finally:
        inj.disarm()
        eng.shutdown()


def test_seal_array_zero_copy_matches_tobytes():
    """The memoryview seal is byte-identical to the old tobytes seal,
    including 0-d, empty, non-contiguous, and read-only inputs."""
    rng = np.random.RandomState(5)
    cases = [
        np.float32(rng.randn()),                    # 0-d
        np.zeros((0,), np.float32),                 # empty
        rng.randn(7, 5).astype(np.float16),
        rng.randn(8, 8).astype(np.float64)[::2, 1::2],  # non-contiguous
    ]
    ro = rng.randn(16).astype(np.float32)
    ro.setflags(write=False)
    cases.append(ro)
    for arr in cases:
        a = np.ascontiguousarray(np.asarray(arr))
        frame = integrity.seal_array(arr, key="k", seq=7, worker=2)
        expect = integrity._seal(integrity.KIND_NDARRAY, "k", 2, 7,
                                 a.dtype.str, np.asarray(arr).shape,
                                 a.tobytes())
        assert frame == expect
        out, meta = integrity.open_array(frame)
        np.testing.assert_array_equal(out, np.asarray(arr))
        assert meta.seq == 7 and meta.worker == 2


def test_engine_compressed_wire_push_rejects_corrupt_frame():
    """push_compressed with every transmission corrupted: bounded
    retransmit, then a loud failure — the codec never decodes unverified
    bytes."""
    import jax.numpy as jnp
    from byteps_tpu.compression import registry as reg
    eng = _engine()
    try:
        eng.register_compression("g", {"compressor": "onebit"}, 16)
        comp = reg.create({"compressor": "onebit"}, 16, np.float32)
        payload, _ = comp.compress(jnp.ones(16), comp.init_state())
        wire = comp.wire_encode(payload)
        inj.arm("bitflip:site=server_push:p=1", seed=9, rank=0)
        with pytest.raises(integrity.IntegrityError):
            eng.push_compressed("g", wire, worker_id=0, num_workers=1)
        inj.disarm()
        assert counters.get("integrity.crc_reject") \
            == integrity.max_retransmits() + 1
        # clean retransmission from the caller's copy lands exactly
        eng.push_compressed("g", wire, worker_id=0, num_workers=1)
        np.testing.assert_array_equal(eng.pull("g", timeout=5), 1.0)
    finally:
        inj.disarm()
        eng.shutdown()


# -- membership bus: frame clamp + envelope ---------------------------------

def test_bus_frame_clamp_rejects_corrupt_length_prefix(monkeypatch):
    from byteps_tpu.fault.membership import _BusFrameError, _recv_obj
    monkeypatch.setenv("BYTEPS_BUS_MAX_FRAME", str(1 << 20))
    reset_config()
    a, b = socket.socketpair()
    try:
        # a corrupt prefix claiming a multi-petabyte frame must fail the
        # connection, not park the thread on an endless recv
        a.sendall(struct.pack("!Q", 1 << 50))
        with pytest.raises(_BusFrameError, match="BYTEPS_BUS_MAX_FRAME"):
            _recv_obj(b)
    finally:
        a.close()
        b.close()


def test_bus_sender_clamps_oversize_frame(monkeypatch):
    """_send_obj refuses a frame over BYTEPS_BUS_MAX_FRAME at the
    SENDER, with an error naming the knob — a legitimately large rejoin
    state fails fast and actionably instead of being shipped and then
    misattributed to corruption by the receiver's clamp.  The refusal is
    deterministic, so it must NOT ride the transient-retry hierarchy:
    each backoff attempt would re-pickle and re-CRC the whole blob just
    to fail identically."""
    from byteps_tpu.fault.membership import (_BusFrameTooLarge,
                                             _BusUnreachable, _send_obj)
    monkeypatch.setenv("BYTEPS_BUS_MAX_FRAME", "64")
    reset_config()
    assert not issubclass(_BusFrameTooLarge, (_BusUnreachable, OSError))
    a, b = socket.socketpair()
    try:
        with pytest.raises(_BusFrameTooLarge, match="BYTEPS_BUS_MAX_FRAME"):
            _send_obj(a, {"blob": b"x" * 256})
    finally:
        a.close()
        b.close()


def test_bus_sender_clamp_refuses_before_sealing(monkeypatch):
    """The oversize refusal is budgeted from the pickled length plus the
    fixed envelope overhead — NOT by sealing first: a multi-GB rejoin
    blob must not pay a full CRC pass and copy just to be thrown away by
    the very check that exists to make the refusal cheap."""
    from byteps_tpu.common import integrity as _integrity
    from byteps_tpu.fault.membership import _BusFrameTooLarge, _send_obj
    # the budget helper must match what seal_bytes actually adds
    payload = b"x" * 100
    sealed = integrity.seal_bytes(payload, key="membership-bus")
    assert (len(sealed) - len(payload)
            == integrity.envelope_overhead("membership-bus"))
    monkeypatch.setenv("BYTEPS_BUS_MAX_FRAME", "64")
    reset_config()

    def _no_seal(*a, **kw):  # noqa: ANN002
        raise AssertionError("seal_bytes ran for a frame the size clamp "
                             "should have refused first")

    monkeypatch.setattr(_integrity, "seal_bytes", _no_seal)
    a, b = socket.socketpair()
    try:
        with pytest.raises(_BusFrameTooLarge, match="BYTEPS_BUS_MAX_FRAME"):
            _send_obj(a, {"blob": b"x" * 256})
    finally:
        a.close()
        b.close()


def test_bus_roundtrip_and_corrupt_frame_rejected():
    from byteps_tpu.fault.membership import (_BusFrameError, _recv_obj,
                                             _send_obj)
    a, b = socket.socketpair()
    try:
        obj = {"epoch": 3, "world": [0, 1, 2],
               "blob": np.arange(5, dtype=np.float32).tobytes()}
        _send_obj(a, obj)
        assert _recv_obj(b) == obj
        # corrupt one payload byte in flight: the receiver NACKs the
        # frame instead of unpickling garbage
        data = integrity.seal_bytes(b"not what was sent", key="m")
        data = bytearray(data)
        data[-6] ^= 0x40
        a.sendall(struct.pack("!Q", len(data)) + bytes(data))
        with pytest.raises(_BusFrameError, match="integrity"):
            _recv_obj(b)
        assert counters.get("integrity.crc_reject") == 1
    finally:
        a.close()
        b.close()


def test_bus_oversize_reply_answers_small_error(monkeypatch):
    """A coordinator whose reply exceeds BYTEPS_BUS_MAX_FRAME (mixed
    per-member knob settings) must answer with a small error naming the
    knob — not close silently and leave the client retrying a
    deterministic failure under backoff."""
    from byteps_tpu.fault import membership as mem
    monkeypatch.setenv("BYTEPS_BUS_MAX_FRAME", "4096")
    reset_config()
    srv = mem._BusServer(("127.0.0.1", _free_port()),
                         mem.MembershipView(0, (0,)), 1.0, 1.0)
    try:
        monkeypatch.setattr(
            mem._BusServer, "_do_sync",
            lambda self, msg: {"ok": True, "blob": b"x" * 1_000_000})
        conn = socket.create_connection(srv.addr, timeout=5)
        try:
            mem._send_obj(conn, {"op": "sync"})
            reply = mem._recv_obj(conn)
        finally:
            conn.close()
        assert reply["ok"] is False
        assert "BYTEPS_BUS_MAX_FRAME" in reply["error"]
    finally:
        srv.close()


def test_bus_corrupt_magic_fails_as_frame_error():
    """A flip in the envelope's 4 magic bytes defeats the is_frame
    sniff, so the raw envelope reaches pickle — that is still wire
    corruption and must fail through the retriable _BusFrameError path,
    not an unclassified UnpicklingError."""
    from byteps_tpu.fault.membership import _BusFrameError, _recv_obj
    a, b = socket.socketpair()
    try:
        data = bytearray(integrity.seal_bytes(b"payload", key="m"))
        data[0] ^= 0xFF  # kill the magic
        a.sendall(struct.pack("!Q", len(data)) + bytes(data))
        with pytest.raises(_BusFrameError, match="unpickle"):
            _recv_obj(b)
        assert counters.get("integrity.crc_reject") == 1
    finally:
        a.close()
        b.close()


# -- rejoin state blobs -----------------------------------------------------

def test_pack_state_envelope_roundtrip_and_corruption():
    from byteps_tpu.utils.checkpoint import pack_state, unpack_state
    state = {"w": np.arange(6, dtype=np.float32), "step": np.array(9)}
    blob = pack_state(state)
    assert integrity.is_frame(blob)
    out = unpack_state(blob)
    np.testing.assert_array_equal(out["w"], state["w"])
    assert int(out["step"]) == 9
    corrupt = bytearray(blob)
    corrupt[len(blob) // 2] ^= 0x08
    with pytest.raises(integrity.IntegrityError, match="rejoin state"):
        unpack_state(bytes(corrupt))
    assert counters.get("integrity.crc_reject") == 1


def test_pack_state_seal_false_for_sealing_transports():
    """seal=False (the membership bus path — its frames already ride the
    envelope) skips the inner seal so a multi-GB rejoin state is not
    CRC'd and copied twice; unpack_state accepts either form."""
    from byteps_tpu.utils.checkpoint import pack_state, unpack_state
    state = {"w": np.arange(4, dtype=np.float32)}
    blob = pack_state(state, seal=False)
    assert not integrity.is_frame(blob)
    np.testing.assert_array_equal(unpack_state(blob)["w"], state["w"])


# -- config validation ------------------------------------------------------

@pytest.mark.parametrize("env,val,msg", [
    ("BYTEPS_NONFINITE_POLICY", "quarantine", "NONFINITE_POLICY"),
    ("BYTEPS_INTEGRITY_MAX_RETRANSMITS", "-1", "retransmits"),
    ("BYTEPS_BUS_MAX_FRAME", "0", "bus_max_frame"),
])
def test_config_rejects_bad_integrity_knobs(monkeypatch, env, val, msg):
    from byteps_tpu.common.config import get_config
    monkeypatch.setenv(env, val)
    reset_config()
    with pytest.raises(ValueError, match=msg):
        get_config()


# -- async drop+retry: at-most-once summation under chaos -------------------

@pytest.mark.chaos
def test_async_drop_retry_never_double_sums():
    """The acceptance loop for idempotence: an async run under
    ``drop:site=kv_push`` (lost acks -> retries) must show
    ``integrity.dup_dropped`` > 0 and a final value identical to the
    fault-free sum — no delta lands twice."""
    import jax.numpy as jnp
    import optax
    from byteps_tpu.jax.async_opt import AsyncDistributedOptimizer
    inj.arm("drop:site=kv_push:p=0.5", seed=6, rank=0)
    aopt = AsyncDistributedOptimizer(optax.sgd(1.0))
    params = {"w": jnp.zeros(8)}
    state = aopt.init(params)
    steps = 12
    for _ in range(steps):
        params, state = aopt.update_and_sync(
            {"w": jnp.ones(8)}, state, params)
    inj.disarm()
    # sgd(1.0) on grad=1: each step's delta is exactly -1
    np.testing.assert_array_equal(np.asarray(params["w"]), -float(steps))
    assert counters.get("integrity.dup_dropped") > 0
    assert counters.get("fault.drop") > 0


# -- the headline proof: 3-process bitflip chaos, bit-identical result ------

def _run_three_workers(tmp_path, spec: str, tag: str, compress: str = ""):
    port = _free_port()
    out = tmp_path / f"params-{tag}.bin"
    procs = []
    for rank in range(3):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "BYTEPS_LOG_LEVEL": "WARNING",
            "BYTEPS_INTEG_RANK": str(rank),
            "BYTEPS_INTEG_PORT": str(port),
            "BYTEPS_INTEG_OUT": str(out),
            "BYTEPS_INTEG_COMPRESS": compress,
            "BYTEPS_FAULT_SPEC": spec if rank == 0 else "",
            "BYTEPS_FAULT_SEED": "17",
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests",
                                          "integrity_worker.py")],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            o, _ = p.communicate(timeout=180)
            outs.append(o)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"{tag}: integrity workers timed out; partial: " +
                    "".join(o[-1500:] for o in outs if o))
    for rank, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{tag} rank {rank} failed:\n{o[-4000:]}"
    digests = set()
    for o in outs:
        for line in o.splitlines():
            if line.startswith("DIGEST "):
                digests.add(line.split()[2])
    assert len(digests) == 1, f"{tag}: ranks diverged: {digests}\n" + \
        "".join(o[-1000:] for o in outs)
    stats = {}
    for line in outs[0].splitlines():
        if line.startswith(("REJECTS ", "RETRANS ")):
            k, v = line.split()
            stats[k] = int(v)
    return out.read_bytes(), stats


@pytest.mark.chaos
def test_three_process_bitflip_chaos_converges_bit_identical(tmp_path):
    """ISSUE 4 acceptance: a 3-process run with
    ``bitflip:site=server_push:p=0.05`` detects every corruption
    (crc_reject > 0), retransmits from the sender's source copy, and the
    final parameters are BIT-IDENTICAL to a fault-free run from the same
    seed — the silent-poisoning demo of PR 2 inverted into resilience."""
    chaos_params, chaos_stats = _run_three_workers(
        tmp_path, "bitflip:site=server_push:p=0.05", "chaos")
    clean_params, clean_stats = _run_three_workers(tmp_path, "", "clean")
    assert chaos_stats["REJECTS"] > 0, chaos_stats
    assert chaos_stats["RETRANS"] > 0, chaos_stats
    assert clean_stats["REJECTS"] == 0, clean_stats
    assert chaos_params == clean_params, (
        "chaos-run parameters diverged from the fault-free run: "
        f"sha256 {hashlib.sha256(chaos_params).hexdigest()[:16]} != "
        f"{hashlib.sha256(clean_params).hexdigest()[:16]}")


@pytest.mark.chaos
def test_three_process_compressed_bitflip_converges_bit_identical(tmp_path):
    """ISSUE 11 satellite: the same 3-process bitflip chaos, but on the
    QUANTIZED wire — workers ship wire-encoded onebit+EF payloads, the
    envelope wraps the compressed frame, and every corrupt frame must be
    NACKed and retransmitted BEFORE the decode runs (one flipped bit in
    a packed-sign payload would otherwise decode into a silent
    many-element error that error feedback then bakes into every later
    step).  Finals must be BIT-IDENTICAL to the fault-free compressed
    run."""
    chaos_params, chaos_stats = _run_three_workers(
        tmp_path, "bitflip:site=server_push:p=0.05", "comp-chaos",
        compress="onebit")
    clean_params, clean_stats = _run_three_workers(
        tmp_path, "", "comp-clean", compress="onebit")
    assert chaos_stats["REJECTS"] > 0, chaos_stats
    assert chaos_stats["RETRANS"] > 0, chaos_stats
    assert clean_stats["REJECTS"] == 0, clean_stats
    assert chaos_params == clean_params, (
        "compressed chaos run diverged from the fault-free compressed "
        f"run: sha256 {hashlib.sha256(chaos_params).hexdigest()[:16]} != "
        f"{hashlib.sha256(clean_params).hexdigest()[:16]}")
