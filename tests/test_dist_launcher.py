"""dist_launcher: hostfile parsing, env construction, ssh fan-out,
restart supervision, and exit-code surfacing (reference
launcher/dist_launcher.py — SURVEY.md §2.5).  ssh is stubbed with a
local runner so the fan-out, env injection, retry/restart, and
exit-code paths are exercised without a network."""

import os
import subprocess
import sys

import pytest

from byteps_tpu.common.retry import RetryPolicy
from byteps_tpu.launcher import dist_launcher as dl
from byteps_tpu.launcher import launch as bl


def _fast_backoff():
    return RetryPolicy(max_attempts=2, base_delay_s=0.0, max_delay_s=0.0)


def test_parse_hostfile(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("# comment\nhost-a\nhost-b:2222\n\nhost-c : ignored\n")
    hosts = dl.parse_hostfile(str(hf))
    assert hosts[0] == ("host-a", "22")
    assert hosts[1] == ("host-b", "2222")
    with pytest.raises(ValueError):
        empty = tmp_path / "empty"
        empty.write_text("\n# nothing\n")
        dl.parse_hostfile(str(empty))


def test_build_env_dmlc_protocol():
    hosts = [("w0", "22"), ("w1", "22"), ("w2", "22")]
    env = dl.build_env(hosts, worker_id=1, coordinator_port=9100,
                       extra={"FOO": "bar"})
    assert env["DMLC_ROLE"] == "worker"
    assert env["DMLC_NUM_WORKER"] == "3"
    assert env["DMLC_WORKER_ID"] == "1"
    assert env["DMLC_PS_ROOT_URI"] == "w0"
    assert env["DMLC_PS_ROOT_PORT"] == "9100"
    assert env["FOO"] == "bar"


def test_ssh_argv_no_shell_injection():
    argv = dl.ssh_argv("host-a", "22", {"A": "1"},
                       ["python", "train.py", "--name", "a b; rm -rf /"])
    assert argv[0] == "ssh"
    remote = argv[-1]
    # the dangerous arg arrives as ONE quoted token
    assert "'a b; rm -rf /'" in remote
    assert remote.startswith("env A=1 python train.py")


def test_launch_fans_out_and_collects_exit_codes(tmp_path):
    hf_hosts = [("h0", "22"), ("h1", "22"), ("h2", "22")]
    seen = {}

    def fake_ssh(argv, stdout, stderr):
        host = argv[argv.index("-p") + 2]  # ssh ... -p 22 host 'cmd'
        seen[host] = argv[-1]
        stdout.write(f"hello from {host}\n".encode())
        return 0 if host != "h2" else 3

    codes = dl.launch(hf_hosts, ["python", "-c", "pass"],
                      extra_env={"X": "y"},
                      log_dir=str(tmp_path / "logs"), ssh_runner=fake_ssh)
    assert codes == [0, 0, 3]
    assert set(seen) == {"h0", "h1", "h2"}
    # per-worker env baked into the remote command
    assert "DMLC_WORKER_ID=0" in seen["h0"]
    assert "DMLC_WORKER_ID=2" in seen["h2"]
    assert "X=y" in seen["h1"]
    assert (tmp_path / "logs" / "worker0.stdout").read_bytes() \
        .startswith(b"hello from h0")


def test_launch_signal_death_not_masked(tmp_path, monkeypatch):
    """A worker killed by a signal (negative code) must fail the launch
    even when other workers exit 0."""
    hf = tmp_path / "hosts"
    hf.write_text("h0\nh1\n")

    def fake_ssh(argv, stdout, stderr):
        host = argv[argv.index("-p") + 2]
        return 0 if host == "h0" else -9

    orig = dl.launch
    monkeypatch.setattr(dl, "launch",
                        lambda hosts, cmd, **kw: orig(
                            hosts, cmd, **{**kw, "ssh_runner": fake_ssh}))
    rc = dl.main(["-H", str(hf), "--log-dir", str(tmp_path / "l"),
                  "--", "true"])
    assert rc == 9


def test_inner_double_dash_survives(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("h0\n")
    seen = {}

    def fake_ssh(argv, stdout, stderr):
        seen["remote"] = argv[-1]
        return 0

    import byteps_tpu.launcher.dist_launcher as mod
    orig = mod.launch

    def patched(hosts, cmd, **kw):
        kw["ssh_runner"] = fake_ssh
        return orig(hosts, cmd, **kw)

    mod.launch = patched
    try:
        rc = mod.main(["-H", str(hf), "--log-dir", str(tmp_path / "l"),
                       "--", "git", "log", "--", "path"])
    finally:
        mod.launch = orig
    assert rc == 0
    # leading separator stripped, inner "--" preserved
    assert seen["remote"].endswith("git log -- path")


def test_restart_on_restartable_code_only(tmp_path):
    """A worker exiting with the failure detector's restartable code is
    restarted with backoff; a crash (exit 1) is not."""
    hosts = [("h0", "22"), ("h1", "22")]
    attempts = {"h0": 0, "h1": 0}

    def fake_ssh(argv, stdout, stderr):
        host = argv[argv.index("-p") + 2]
        attempts[host] += 1
        if host == "h0":
            return 17 if attempts[host] == 1 else 0   # detector exit, once
        return 1                                       # crash: never retried

    report = dl.launch(hosts, ["x"], log_dir=str(tmp_path / "l"),
                       ssh_runner=fake_ssh, restart_limit=3,
                       backoff=_fast_backoff())
    assert report == [0, 1]
    assert report.restarts == [1, 0]
    assert attempts == {"h0": 2, "h1": 1}


def test_restart_limit_exhausted_keeps_last_code(tmp_path):
    hosts = [("h0", "22")]
    calls = []

    def fake_ssh(argv, stdout, stderr):
        calls.append(1)
        stderr.write(b"detector fired\n")
        return 17

    report = dl.launch(hosts, ["x"], log_dir=str(tmp_path / "l"),
                       ssh_runner=fake_ssh, restart_limit=2,
                       backoff=_fast_backoff())
    assert report == [17] and report.restarts == [2]
    assert len(calls) == 3
    # restart logs APPEND: all three incarnations' evidence survives
    log = (tmp_path / "l" / "worker0.stderr").read_bytes()
    assert log.count(b"detector fired") == 3


def test_custom_failure_exit_code_honored(tmp_path, monkeypatch):
    monkeypatch.setenv("BYTEPS_FAILURE_EXIT_CODE", "23")
    monkeypatch.setenv("BYTEPS_RESTART_LIMIT", "1")
    seen = []

    def fake_ssh(argv, stdout, stderr):
        seen.append(1)
        return 23 if len(seen) == 1 else 0

    report = dl.launch([("h0", "22")], ["x"], log_dir=str(tmp_path / "l"),
                       ssh_runner=fake_ssh, backoff=_fast_backoff())
    assert report == [0] and report.restarts == [1]


def test_elastic_restarts_only_the_dead_rank_as_rejoiner(tmp_path):
    """--elastic supervision: survivors never exit, so ANY nonzero exit
    is one dead rank restarted alone — and the restarted incarnation
    carries BYTEPS_ELASTIC_REJOIN=1 so it rejoins the running world
    instead of re-bootstrapping."""
    hosts = [("h0", "22"), ("h1", "22"), ("h2", "22")]
    attempts = {"h0": 0, "h1": 0, "h2": 0}
    remotes = {}

    def fake_ssh(argv, stdout, stderr):
        host = argv[argv.index("-p") + 2]
        attempts[host] += 1
        remotes.setdefault(host, []).append(argv[-1])
        if host == "h1":
            return 1 if attempts[host] == 1 else 0   # crash once, rejoin
        return 0

    report = dl.launch(hosts, ["x"], log_dir=str(tmp_path / "l"),
                       ssh_runner=fake_ssh, restart_limit=2,
                       backoff=_fast_backoff(), elastic=True)
    assert report == [0, 0, 0]
    assert report.restarts == [0, 1, 0]              # only the dead rank
    assert attempts == {"h0": 1, "h1": 2, "h2": 1}
    # every worker runs in elastic mode; only the RESTARTED incarnation
    # is a rejoiner
    for host in hosts:
        assert "BYTEPS_ELASTIC=1" in remotes[host[0]][0]
    assert "BYTEPS_ELASTIC_REJOIN=1" not in remotes["h1"][0]
    assert "BYTEPS_ELASTIC_REJOIN=1" in remotes["h1"][1]
    assert "BYTEPS_ELASTIC_REJOIN" not in remotes["h0"][0]


def test_elastic_defaults_one_restart_and_cli_flag(tmp_path):
    """--elastic with no explicit limit still restarts once (an elastic
    world that can never re-grow is pointless); the CLI flag reaches
    launch()."""
    calls = []

    def fake_ssh(argv, stdout, stderr):
        calls.append(argv[-1])
        return 3 if len(calls) == 1 else 0

    report = dl.launch([("h0", "22")], ["x"], log_dir=str(tmp_path / "l"),
                       ssh_runner=fake_ssh, backoff=_fast_backoff(),
                       elastic=True)
    assert report == [0] and report.restarts == [1]
    assert "BYTEPS_ELASTIC_REJOIN=1" in calls[1]


def test_ssh_dispatch_retry_on_raised_runner(tmp_path):
    """A raising ssh_runner (connection refused) is retried by the
    backoff policy before the launch counts it as a launcher error."""
    calls = []

    def flaky_ssh(argv, stdout, stderr):
        calls.append(1)
        if len(calls) == 1:
            raise OSError("connect to host h0: Connection refused")
        return 0

    report = dl.launch([("h0", "22")], ["x"], log_dir=str(tmp_path / "l"),
                       ssh_runner=flaky_ssh, backoff=_fast_backoff())
    assert report == [0] and report.errors == [None]
    assert len(calls) == 2


def test_worker_thread_exception_logged_and_surfaced(tmp_path):
    """Satellite: an exception raised before ssh_runner returns must not
    collapse into a silent exit-1 — it lands in the worker's .stderr log
    and in the exit summary."""
    hosts = [("h0", "22"), ("h1", "22")]

    def fake_ssh(argv, stdout, stderr):
        host = argv[argv.index("-p") + 2]
        if host == "h1":
            raise ValueError("hostfile entry resolved to garbage")
        return 0

    report = dl.launch(hosts, ["x"], log_dir=str(tmp_path / "l"),
                       ssh_runner=fake_ssh,
                       backoff=RetryPolicy(max_attempts=1,
                                           base_delay_s=0.0))
    assert report == [0, 1]           # failed thread still maps to exit 1
    assert report.errors[0] is None
    assert "hostfile entry resolved to garbage" in report.errors[1]
    log = (tmp_path / "l" / "worker1.stderr").read_text()
    assert "launcher-side error" in log and "ValueError" in log
    summary = dl.format_exit_summary(hosts, report, str(tmp_path / "l"))
    assert "worker1 [h1]: launcher error" in summary
    assert "ValueError" in summary and "worker1.stderr" in summary


def test_exit_summary_formats_all_outcomes(tmp_path):
    hosts = [("a", "22"), ("b", "22"), ("c", "22")]
    report = dl.LaunchReport([0, -9, 17], [0, 0, 2], [None, None, None])
    s = dl.format_exit_summary(hosts, report, "sshlog")
    assert "worker0 [a]: ok" in s
    assert "worker1 [b]: killed by signal 9" in s
    assert "worker2 [c]: exit 17 after 2 restart(s)" in s


def test_main_prints_exit_summary(tmp_path, monkeypatch, capsys):
    hf = tmp_path / "hosts"
    hf.write_text("h0\nh1\n")

    def fake_ssh(argv, stdout, stderr):
        host = argv[argv.index("-p") + 2]
        return 0 if host == "h0" else 5

    monkeypatch.setattr(subprocess, "call",
                        lambda argv, **kw: fake_ssh(argv, None, None))
    rc = dl.main(["-H", str(hf), "--log-dir", str(tmp_path / "l"),
                  "--restart", "0", "--", "true"])
    assert rc == 5
    err = capsys.readouterr().err
    assert "worker exit summary:" in err
    assert "worker0 [h0]: ok" in err
    assert "worker1 [h1]: exit 5" in err


# --- bpslaunch (single-host launcher) supervision ---------------------------


def test_bpslaunch_restarts_on_failure_code(tmp_path, monkeypatch):
    """bpslaunch --restart N re-runs the worker while it exits with the
    restartable code; the sentinel file makes the second run clean."""
    monkeypatch.setenv("BYTEPS_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("BYTEPS_RETRY_MAX_DELAY", "0.001")
    sentinel = tmp_path / "came_back"
    code = (f"import os, sys; p = {str(sentinel)!r}\n"
            "if not os.path.exists(p):\n"
            "    open(p, 'w').close(); sys.exit(17)\n"
            "sys.exit(0)\n")
    rc = bl.main(["--restart", "1", sys.executable, "-c", code])
    assert rc == 0 and sentinel.exists()


def test_bpslaunch_does_not_restart_crashes(tmp_path, monkeypatch):
    monkeypatch.setenv("BYTEPS_RETRY_BASE_DELAY", "0.001")
    runs = tmp_path / "runs"
    code = (f"import sys; f = open({str(runs)!r}, 'a'); f.write('x'); "
            "f.close(); sys.exit(3)")
    rc = bl.main(["--restart", "5", sys.executable, "-c", code])
    assert rc == 3
    assert runs.read_text() == "x"  # exactly one run: 3 is not restartable


def test_bpslaunch_restart_flag_parsing():
    assert bl.main(["--restart"]) == 2          # missing N
    assert bl.main(["--restart", "nope"]) == 2  # non-numeric N
    assert bl.main([]) == 2                     # no command at all


def test_main_end_to_end_with_local_sh(tmp_path, monkeypatch):
    """Full CLI path with ssh replaced by a local shim that executes the
    remote command on this machine."""
    hf = tmp_path / "hosts"
    hf.write_text("localhost\n")
    shim = tmp_path / "ssh"
    shim.write_text("#!/bin/sh\n# drop ssh options; run last arg locally\n"
                    'eval "${@: -1}"\n')
    shim.chmod(0o755)

    monkeypatch.chdir(tmp_path)
    real_call = subprocess.call

    def call_with_shim(argv, **kw):
        assert argv[0] == "ssh"
        return real_call(["bash", str(shim)] + argv[1:], **kw)

    monkeypatch.setattr(subprocess, "call", call_with_shim)
    rc = dl.main(["-H", str(hf), "--env", "PROBE:42", "--",
                  "python", "-c",
                  "import os; print(os.environ['DMLC_NUM_WORKER'], "
                  "os.environ['PROBE'])"])
    assert rc == 0
    out = (tmp_path / "sshlog" / "worker0.stdout").read_text()
    assert out.strip() == "1 42"
