"""dist_launcher: hostfile parsing, env construction, ssh fan-out
(reference launcher/dist_launcher.py — SURVEY.md §2.5).  ssh is stubbed
with a local runner so the fan-out, env injection, and exit-code paths are
exercised without a network."""

import os
import subprocess

import pytest

from byteps_tpu.launcher import dist_launcher as dl


def test_parse_hostfile(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("# comment\nhost-a\nhost-b:2222\n\nhost-c : ignored\n")
    hosts = dl.parse_hostfile(str(hf))
    assert hosts[0] == ("host-a", "22")
    assert hosts[1] == ("host-b", "2222")
    with pytest.raises(ValueError):
        empty = tmp_path / "empty"
        empty.write_text("\n# nothing\n")
        dl.parse_hostfile(str(empty))


def test_build_env_dmlc_protocol():
    hosts = [("w0", "22"), ("w1", "22"), ("w2", "22")]
    env = dl.build_env(hosts, worker_id=1, coordinator_port=9100,
                       extra={"FOO": "bar"})
    assert env["DMLC_ROLE"] == "worker"
    assert env["DMLC_NUM_WORKER"] == "3"
    assert env["DMLC_WORKER_ID"] == "1"
    assert env["DMLC_PS_ROOT_URI"] == "w0"
    assert env["DMLC_PS_ROOT_PORT"] == "9100"
    assert env["FOO"] == "bar"


def test_ssh_argv_no_shell_injection():
    argv = dl.ssh_argv("host-a", "22", {"A": "1"},
                       ["python", "train.py", "--name", "a b; rm -rf /"])
    assert argv[0] == "ssh"
    remote = argv[-1]
    # the dangerous arg arrives as ONE quoted token
    assert "'a b; rm -rf /'" in remote
    assert remote.startswith("env A=1 python train.py")


def test_launch_fans_out_and_collects_exit_codes(tmp_path):
    hf_hosts = [("h0", "22"), ("h1", "22"), ("h2", "22")]
    seen = {}

    def fake_ssh(argv, stdout, stderr):
        host = argv[argv.index("-p") + 2]  # ssh ... -p 22 host 'cmd'
        seen[host] = argv[-1]
        stdout.write(f"hello from {host}\n".encode())
        return 0 if host != "h2" else 3

    codes = dl.launch(hf_hosts, ["python", "-c", "pass"],
                      extra_env={"X": "y"},
                      log_dir=str(tmp_path / "logs"), ssh_runner=fake_ssh)
    assert codes == [0, 0, 3]
    assert set(seen) == {"h0", "h1", "h2"}
    # per-worker env baked into the remote command
    assert "DMLC_WORKER_ID=0" in seen["h0"]
    assert "DMLC_WORKER_ID=2" in seen["h2"]
    assert "X=y" in seen["h1"]
    assert (tmp_path / "logs" / "worker0.stdout").read_bytes() \
        .startswith(b"hello from h0")


def test_launch_signal_death_not_masked(tmp_path, monkeypatch):
    """A worker killed by a signal (negative code) must fail the launch
    even when other workers exit 0."""
    hf = tmp_path / "hosts"
    hf.write_text("h0\nh1\n")

    def fake_ssh(argv, stdout, stderr):
        host = argv[argv.index("-p") + 2]
        return 0 if host == "h0" else -9

    orig = dl.launch
    monkeypatch.setattr(dl, "launch",
                        lambda hosts, cmd, **kw: orig(
                            hosts, cmd, **{**kw, "ssh_runner": fake_ssh}))
    rc = dl.main(["-H", str(hf), "--log-dir", str(tmp_path / "l"),
                  "--", "true"])
    assert rc == 9


def test_inner_double_dash_survives(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("h0\n")
    seen = {}

    def fake_ssh(argv, stdout, stderr):
        seen["remote"] = argv[-1]
        return 0

    import byteps_tpu.launcher.dist_launcher as mod
    orig = mod.launch

    def patched(hosts, cmd, **kw):
        kw["ssh_runner"] = fake_ssh
        return orig(hosts, cmd, **kw)

    mod.launch = patched
    try:
        rc = mod.main(["-H", str(hf), "--log-dir", str(tmp_path / "l"),
                       "--", "git", "log", "--", "path"])
    finally:
        mod.launch = orig
    assert rc == 0
    # leading separator stripped, inner "--" preserved
    assert seen["remote"].endswith("git log -- path")


def test_main_end_to_end_with_local_sh(tmp_path, monkeypatch):
    """Full CLI path with ssh replaced by a local shim that executes the
    remote command on this machine."""
    hf = tmp_path / "hosts"
    hf.write_text("localhost\n")
    shim = tmp_path / "ssh"
    shim.write_text("#!/bin/sh\n# drop ssh options; run last arg locally\n"
                    'eval "${@: -1}"\n')
    shim.chmod(0o755)

    monkeypatch.chdir(tmp_path)
    real_call = subprocess.call

    def call_with_shim(argv, **kw):
        assert argv[0] == "ssh"
        return real_call(["bash", str(shim)] + argv[1:], **kw)

    monkeypatch.setattr(subprocess, "call", call_with_shim)
    rc = dl.main(["-H", str(hf), "--env", "PROBE:42", "--",
                  "python", "-c",
                  "import os; print(os.environ['DMLC_NUM_WORKER'], "
                  "os.environ['PROBE'])"])
    assert rc == 0
    out = (tmp_path / "sshlog" / "worker0.stdout").read_text()
    assert out.strip() == "1 42"
