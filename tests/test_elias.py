"""Elias-delta wire codec tests (VERDICT r1 item 8, reference parity for
the entropy-coded dithering payload — reference dithering.cc:51-110).

The C++ coder (native/core.cc) and the numpy twin
(compression/elias.py) must agree bit-for-bit; the framed wire format
must round-trip through the DitheringCompressor's device layouts; and the
measured wire bytes must beat both static layouts on sparse posteriors —
the ratio the reference's entropy coding exists for.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from byteps_tpu.compression import elias
from byteps_tpu.compression import create as create_compressor
from byteps_tpu import native


def _sparse_codes(n=4096, nnz=80, seed=0, maxlevel=16):
    rng = np.random.RandomState(seed)
    codes = np.zeros(n, np.int8)
    hot = rng.choice(n, nnz, replace=False)
    codes[hot] = rng.randint(1, maxlevel + 1, nnz) * \
        rng.choice([-1, 1], nnz).astype(np.int8)
    return codes


@pytest.mark.parametrize("seed,nnz,maxlevel", [(0, 80, 16), (1, 1, 1),
                                               (2, 4096, 127), (3, 0, 16)])
def test_numpy_roundtrip(seed, nnz, maxlevel):
    codes = _sparse_codes(nnz=nnz, seed=seed, maxlevel=maxlevel)
    words, nbits = elias.elias_encode_np(codes)
    out = elias.elias_decode_np(words, nbits, len(codes))
    np.testing.assert_array_equal(out, codes)


def test_native_matches_numpy_bit_for_bit():
    if not native.available():
        pytest.skip("native core unavailable")
    for seed in range(5):
        codes = _sparse_codes(seed=seed, nnz=200, maxlevel=127)
        w_np, b_np = elias.elias_encode_np(codes)
        res = native.elias_encode(codes)
        assert res is not None
        w_c, b_c = res
        assert b_c == b_np
        np.testing.assert_array_equal(w_c, w_np)
        # cross-decode: each implementation reads the other's stream
        np.testing.assert_array_equal(
            native.elias_decode(w_np, b_np, len(codes)), codes)
        np.testing.assert_array_equal(
            elias.elias_decode_np(w_c, b_c, len(codes)), codes)


def test_edge_positions_and_levels():
    # first/last element nonzero, max gap, level 1 and 127
    codes = np.zeros(1000, np.int8)
    codes[0] = 127
    codes[999] = -1
    words, nbits = elias.elias_encode(codes)
    np.testing.assert_array_equal(
        elias.elias_decode(words, nbits, 1000), codes)


def test_malformed_stream_raises():
    codes = _sparse_codes(nnz=50)
    words, nbits = elias.elias_encode(codes)
    with pytest.raises(ValueError):
        elias.elias_decode(words, nbits - 3, len(codes))  # truncated
    bad = words.copy()
    bad[0] = 0  # a leading run of zeros longer than any valid length field
    with pytest.raises(ValueError):
        elias.elias_decode(bad, nbits, len(codes))


def test_wire_frame_roundtrip_and_ratio():
    codes = _sparse_codes(n=8192, nnz=100)
    data = elias.encode_wire(codes, 2.5)
    out, norm = elias.decode_wire(data)
    np.testing.assert_array_equal(out, codes)
    assert norm == 2.5
    # entropy coding beats the dense int8 layout ~20x at 1.2% density and
    # the static sparse (uint16+int8)/element layout too
    dense_bytes = 8192 + 4
    sparse_bytes = 100 * 3 + 4
    assert len(data) < dense_bytes / 15
    assert len(data) < sparse_bytes * 1.6  # within ~1.6x of exact-k sparse
    assert elias.wire_nbytes(codes) == len(data)


@pytest.mark.parametrize("sparse_ratio", ["0.0", "0.05"])
def test_dithering_wire_encode_decode(sparse_ratio):
    rng = np.random.RandomState(9)
    x = np.zeros(4000, np.float32)
    hot = rng.choice(4000, 60, replace=False)
    x[hot] = rng.randn(60).astype(np.float32) * 3
    comp = create_compressor(
        {"compressor": "dithering", "partition_num": "16", "seed": "4",
         "sparse_ratio": sparse_ratio}, len(x))
    payload, _ = comp.compress(jnp.asarray(x), comp.init_state())
    data = comp.wire_encode(payload)
    payload2 = comp.wire_decode(data)
    np.testing.assert_allclose(np.asarray(comp.decompress(payload2)),
                               np.asarray(comp.decompress(payload)),
                               rtol=1e-6, atol=0)
    # measured wire accounting
    assert comp.wire_nbytes(payload) == len(data)
    assert len(data) < comp.payload_nbytes()


def _bits_to_words(bits):
    words = np.zeros((len(bits) + 31) // 32, np.uint32)
    for pos, b in enumerate(bits):
        if b:
            words[pos >> 5] |= np.uint32(1 << (pos & 31))
    return words


def _elias_bits(x):
    n = int(x).bit_length()
    ln = n.bit_length()
    return ([0] * (ln - 1)
            + [(n >> k) & 1 for k in range(ln - 1, -1, -1)]
            + [(x >> k) & 1 for k in range(n - 2, -1, -1)])


def test_forged_gap_overflow_rejected():
    """A 64-bit gap >= 2^63 must be rejected, not wrap negative and write
    before the output buffer (untrusted wire bytes reach this decoder
    through ServerEngine.push_compressed)."""
    if not native.available():
        pytest.skip("native core unavailable")
    bits = _elias_bits((1 << 63) + 5) + [0] + _elias_bits(3)
    words = _bits_to_words(bits)
    with pytest.raises(ValueError):
        native.elias_decode(words, len(bits), np.int8(0).itemsize * 100)


def test_forged_length_field_terminates():
    """63 leading zeros forge a ~2^63 length field; the decoder must fail
    fast instead of looping for years."""
    if not native.available():
        pytest.skip("native core unavailable")
    words = np.zeros(4, np.uint32)  # 128 zero bits
    with pytest.raises(ValueError):
        native.elias_decode(words, 128, 100)
    with pytest.raises(ValueError):
        elias.elias_decode_np(words, 128, 100)


def test_gap_past_end_rejected():
    bits = _elias_bits(50) + [0] + _elias_bits(3)  # gap 50 into n=10
    words = _bits_to_words(bits)
    for decode in ((lambda w, b, n: native.elias_decode(w, b, n))
                   if native.available() else None,
                   elias.elias_decode_np):
        if decode is None:
            continue
        with pytest.raises(ValueError):
            decode(words, len(bits), 10)


def test_truncated_wire_frame_rejected():
    codes = _sparse_codes(nnz=40)
    data = elias.encode_wire(codes, 1.0)
    with pytest.raises(ValueError):
        elias.decode_wire(data[:8])  # shorter than the header
    with pytest.raises(ValueError):
        elias.decode_wire(data[:-4])  # header claims more words


def test_decorated_compressor_wire_matches_server_codec():
    """Worker chain momentum(ef(dithering)) and the momentum-skipping
    server codec must speak the same wire format (decorators delegate
    wire_* to the inner compressor)."""
    rng = np.random.RandomState(5)
    x = np.zeros(2048, np.float32)
    x[rng.choice(2048, 30, replace=False)] = rng.randn(30)
    kw = {"compressor": "dithering", "partition_num": "16", "seed": "1",
          "ef": "vanilla", "momentum": "nesterov"}
    worker = create_compressor(kw, len(x))
    server = create_compressor(kw, len(x), for_server=True)
    payload, _ = worker.compress(jnp.asarray(x), worker.init_state())
    wire = worker.wire_encode(payload)
    decoded = server.wire_decode(wire)
    np.testing.assert_allclose(np.asarray(server.decompress(decoded)),
                               np.asarray(worker.decompress(payload)),
                               rtol=1e-6, atol=0)
    # and it IS the tight elias frame, not the generic npz fallback
    assert not wire.startswith(b"PK")  # zip magic
    assert len(wire) < 2048 / 4


def test_forged_numel_header_rejected_before_allocation():
    """A 16-byte frame claiming numel=2^32-1 must be rejected by the
    expected-numel check, not allocate 4 GiB."""
    header = np.array([0, 0xFFFFFFFF, 0], np.uint32).tobytes()
    with pytest.raises(ValueError, match="numel"):
        elias.decode_wire(header + b"\x00" * 4, expected_numel=1000)
    comp = create_compressor({"compressor": "dithering",
                              "partition_num": "16"}, 1000)
    with pytest.raises(ValueError, match="numel"):
        comp.wire_decode(header + b"\x00" * 4)
