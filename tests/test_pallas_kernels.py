"""Pallas TPU kernels, exercised in interpret mode on CPU.

The engine only dispatches to the kernels on a real TPU backend; these
tests run the exact kernel bodies through the Pallas interpreter and
assert bit-equality with the portable jnp fallback / numpy refs, so the
two code paths can never drift (mirrors the reference's numpy-replication
test strategy, SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.compression import create as create_compressor
from byteps_tpu.ops import pallas_kernels as pk

from . import compression_refs as refs


def _x(n, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(n).astype(np.float32)


@pytest.mark.parametrize("numel", [100, 4096, 32 * 128, 50000])
def test_onebit_pack_kernel_matches_ref(numel):
    x = _x(numel, seed=1)
    L = pk.padded_lanes(numel)
    x2d = jnp.pad(jnp.asarray(x), (0, 32 * L - numel)).reshape(32, L)
    words, abs_sum = pk.onebit_pack(x2d, interpret=True)
    ref_words, ref_scale = refs.onebit_compress(x, scaling=True)
    np.testing.assert_array_equal(np.asarray(words), ref_words)
    np.testing.assert_allclose(float(abs_sum) / numel, ref_scale, rtol=1e-6)


@pytest.mark.parametrize("numel", [100, 32 * 128])
def test_onebit_unpack_kernel_roundtrip(numel):
    x = _x(numel, seed=2)
    ref_words, ref_scale = refs.onebit_compress(x, scaling=True)
    out2d = pk.onebit_unpack(jnp.asarray(ref_words),
                             jnp.float32(ref_scale), interpret=True)
    got = np.asarray(out2d).reshape(-1)[:numel]
    ref = refs.onebit_decompress(ref_words, ref_scale, numel)
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    # signs of the reconstruction match signs of the input
    np.testing.assert_array_equal(got > 0, x >= 0)


@pytest.mark.parametrize("ranks", [2, 8])
def test_onebit_unpack_sum_kernel_matches_naive_merge(ranks):
    numel = 5000
    words, scales = [], []
    for r in range(ranks):
        w, s = refs.onebit_compress(_x(numel, seed=10 + r))
        words.append(w)
        scales.append(s)
    words = jnp.asarray(np.stack(words))
    scales = jnp.asarray(np.array(scales, np.float32))
    out = pk.onebit_unpack_sum(words, scales, interpret=True)
    got = np.asarray(out).reshape(-1)[:numel]
    ref = sum(refs.onebit_decompress(np.asarray(words[r]), float(scales[r]),
                                     numel) for r in range(ranks))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_kernel_path_matches_jnp_compressor_path():
    """Force the pallas path (interpret) and compare against the
    compressor's jnp fallback on identical input: payloads must be
    bit-identical so mixed fleets (some hosts on TPU, tests on CPU)
    interoperate."""
    numel = 10000
    x = jnp.asarray(_x(numel, seed=3))
    comp = create_compressor({"compressor": "onebit"}, numel)
    payload_jnp, _ = comp.compress(x, {})

    x2d = comp._as2d(x.astype(jnp.float32))
    words_k, abs_k = pk.onebit_pack(x2d, interpret=True)
    np.testing.assert_array_equal(np.asarray(words_k),
                                  np.asarray(payload_jnp["words"]))
    np.testing.assert_allclose(float(abs_k) / numel,
                               float(payload_jnp["scale"]), rtol=1e-6)

    out_k = pk.onebit_unpack(words_k, payload_jnp["scale"],
                             interpret=True).reshape(-1)[:numel]
    np.testing.assert_allclose(np.asarray(out_k),
                               np.asarray(comp.decompress(payload_jnp)),
                               rtol=1e-6)
